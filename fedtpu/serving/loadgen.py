"""`fedtpu loadgen` — replay an arrival trace against a running server.

Streams a JSONL trace (fedtpu.serving.traces) through the socket
protocol in batch frames, aggregates the per-verdict admission counts
the server acks back, and optionally issues a final ``drain`` +
``stats`` so the run ends with everything incorporated and a full SLO
snapshot in hand.

All traffic rides the retrying :class:`fedtpu.serving.client
.GatewayClient`: a refused connection or a dropped socket mid-replay is
retried with capped exponential backoff instead of crashing the run,
redirect frames are followed, and every batch is session-stamped so a
retry after a lost ack is deduplicated server-side rather than
double-counted. With ``num_gateways > 1`` the trace is partitioned by
owning gateway per flush and the final drain/stats fans out per member.

Replay is as-fast-as-possible by design: arrival TIMESTAMPS carry the
virtual clock, so the server's admission/staleness/latency behavior is
identical whether the trace is streamed in one burst or paced over an
hour — wall time only changes the throughput numbers. That is what lets
one process push millions of simulated users through a localhost socket
in seconds.

Backend-free: numpy + stdlib only (the loadgen never touches jax).
"""

from __future__ import annotations

import time
from typing import Optional

from fedtpu.serving.client import (DEFAULT_BACKOFF_S, DEFAULT_RETRIES,
                                   GatewayClient)
from fedtpu.serving.protocol import MAX_BATCH_EVENTS
from fedtpu.serving.traces import read_trace


def read_port_file(path: str, timeout: float = 30.0) -> int:
    """Poll ``path`` (written by the server once bound) for the port —
    ephemeral-port discovery when the server was started with port 0."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with open(path) as fh:
                txt = fh.read().strip()
            if txt:
                return int(txt)
        except (OSError, ValueError):
            pass
        time.sleep(0.05)
    raise TimeoutError(f"no port appeared in {path} within {timeout}s")


def run_loadgen(trace_path: str, host: str = "127.0.0.1",
                port: Optional[int] = None,
                port_file: Optional[str] = None,
                batch: int = 1024, max_events: int = 0,
                drain: bool = True, timeout: float = 120.0,
                num_gateways: int = 1,
                retries: int = DEFAULT_RETRIES,
                backoff_s: float = DEFAULT_BACKOFF_S,
                seed: int = 0) -> dict:
    """Replay ``trace_path`` against the server at ``host:port`` (or the
    port in ``port_file`` — with ``num_gateways > 1`` the BASE path each
    gateway derives its own file from). Returns a summary dict: events
    sent, frames, aggregated admission counts, retry/redirect counters,
    wall seconds, events/sec, and — when ``drain`` — the server's
    post-drain stats snapshot (per-gateway when fleet-sized).

    ``batch`` events ride per protocol frame (capped at the protocol's
    MAX_BATCH_EVENTS); ``max_events > 0`` truncates the replay (bounded
    smoke tests over big traces).
    """
    if port is None and not port_file:
        raise ValueError("need port or port_file")
    batch = max(1, min(int(batch), MAX_BATCH_EVENTS))
    header, events = read_trace(trace_path)

    counts: dict = {}
    sent = 0
    t0 = time.monotonic()
    with GatewayClient(host=host, port=port, port_file=port_file,
                       num_gateways=num_gateways, timeout=timeout,
                       retries=retries, backoff_s=backoff_s,
                       seed=seed) as client:
        welcome = client.hello()
        pending: list = []

        def _flush():
            nonlocal sent
            if not pending:
                return
            for verdict, n in client.send_events(pending).items():
                counts[verdict] = counts.get(verdict, 0) + int(n)
            sent += len(pending)
            pending.clear()

        for ev in events:
            # v2 adversarial traces: attacker events ride a 5-element row
            # (version slot None) so honest frames stay byte-identical to
            # the v1 wire format.
            if ev.poison > 0.0:
                pending.append([ev.user, ev.t, ev.lat, None, ev.poison])
            else:
                pending.append([ev.user, ev.t, ev.lat])
            if len(pending) >= batch:
                _flush()
            if max_events and sent + len(pending) >= max_events:
                break
        _flush()
        stats = None
        if drain:
            if client.num_gateways == 1:
                client.request({"op": "drain"})
                stats = client.request({"op": "stats"})
                stats.pop("op", None)
            else:
                # Per-member, no failover: a drain aimed at a dead
                # gateway must not drain a survivor twice.
                client.request_each({"op": "drain"})
                per = client.request_each({"op": "stats"})
                stats = {str(g): (s if s is None
                                  else {k: v for k, v in s.items()
                                        if k != "op"})
                         for g, s in per.items()}
        frames = client.stats["frames"]
        retry_stats = dict(client.stats)
    wall = time.monotonic() - t0
    return {
        "trace": trace_path,
        "trace_users": header.users,
        "trace_arrivals": header.arrivals,
        "events_sent": sent,
        "frames": frames,
        "batch": batch,
        "num_gateways": int(max(1, num_gateways)),
        "cohort": welcome.get("cohort"),
        "admission": counts,
        "attempted": retry_stats["attempted"],
        "retried": retry_stats["retried"],
        "redirected": retry_stats["redirected"],
        "reconnects": retry_stats["reconnects"],
        "wall_s": wall,
        "events_per_sec": (sent / wall) if wall > 0 else 0.0,
        "server_stats": stats,
    }
