"""Admission control for the serving front-end.

Every arriving client update passes through :class:`AdmissionController`
before it may touch the engine. The controller runs entirely on the
TRACE (virtual) clock — the arrival timestamps in the trace, not wall
time — so the same trace + config yields the same sequence of verdicts
bit for bit, regardless of host speed. Checks are ordered cheapest /
hardest first, and the order is part of the contract (tests pin it):

    1. rate       — global token bucket (``rate_limit`` updates/s of
                    virtual time, burst ``rate_burst``). Over budget =>
                    ``reject_rate``.
    2. backpressure — the engine's pending (admitted-but-not-yet-
                    incorporated) queue depth. At ``max_pending`` =>
                    ``reject_backpressure``; this is the K-buffer
                    overload signal, the inbound twin of the
                    ``async_starvation`` SLO event.
    3. staleness  — how many model versions behind the client's pulled
                    version is. Beyond ``stale_reject`` =>
                    ``reject_stale`` (the update would be discounted to
                    noise anyway); beyond ``stale_deprioritize`` =>
                    ``deprioritize`` (admitted, but queued behind fresh
                    work).
    4. otherwise  — ``accept``.

Per-verdict counters land in the shared MetricsRegistry under
``admission_<verdict>`` so they flow through the normal ``counters``
snapshot into ``fedtpu report``. The controller additionally keeps a
sliding window (``window_s`` of virtual time) over its own verdict
stream — :meth:`AdmissionController.window_rates` — so the autoscale
control plane reads per-verdict rates off the SAME bookkeeping path the
cumulative counters use, never a second tally that could drift.

No jax in this module — admission is pure host bookkeeping.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Optional

from fedtpu.telemetry.metrics import MetricsRegistry

ACCEPT = "accept"
DEPRIORITIZE = "deprioritize"
REJECT_RATE = "reject_rate"
REJECT_STALE = "reject_stale"
REJECT_BACKPRESSURE = "reject_backpressure"
# Defense verdict (fedtpu.robust; docs/robustness.md): the update was
# admitted by the checks above but refused by the poisoning screen (or
# its sender is quarantined). Counted through record(), never decide() —
# screening happens at/after the engine boundary, not in the token path.
SCREENED = "screened"

# Verdict order is display / schema order, not check order. SCREENED
# must stay LAST: checkpoints store counts as a list in this order, and
# restore_state zips — old 5-entry checkpoints restore as a prefix.
VERDICTS = (ACCEPT, DEPRIORITIZE, REJECT_RATE, REJECT_STALE,
            REJECT_BACKPRESSURE, SCREENED)

ADMITTED = frozenset({ACCEPT, DEPRIORITIZE})


class TokenBucket:
    """Token bucket on an external (virtual) clock.

    ``rate`` tokens/s refill up to ``burst`` capacity; each admitted
    request spends one token. The clock is whatever the caller passes
    to :meth:`take` — monotone non-decreasing virtual seconds. A
    ``rate`` of 0 disables limiting (always allows).
    """

    __slots__ = ("rate", "burst", "tokens", "_t")

    def __init__(self, rate: float, burst: float):
        if rate < 0 or burst <= 0:
            raise ValueError("rate must be >= 0 and burst > 0")
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._t = 0.0

    def take(self, now: float, n: float = 1.0) -> bool:
        """Try to spend ``n`` tokens at virtual time ``now``."""
        if self.rate == 0.0:
            return True
        if now > self._t:
            self.tokens = min(self.burst, self.tokens + (now - self._t) * self.rate)
            self._t = now
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def state(self) -> tuple:
        """``(tokens, last_refill_t)`` — the fill level and its virtual
        timestamp, everything :meth:`restore_state` needs to continue
        the verdict sequence bitwise across a checkpoint/restore."""
        return (self.tokens, self._t)

    def restore_state(self, tokens: float, t: float) -> None:
        self.tokens = float(tokens)
        self._t = float(t)


@dataclass(frozen=True)
class AdmissionPolicy:
    """The serve-side admission knobs (see docs/serving.md)."""

    rate_limit: float = 0.0        # updates/s of virtual time; 0 = off
    rate_burst: float = 64.0       # token-bucket capacity
    max_pending: int = 0           # queue-depth cutoff; 0 = off
    stale_deprioritize: int = 4    # versions behind => deprioritize
    stale_reject: int = 16         # versions behind => reject
    window_s: float = 10.0         # sliding stats window (virtual s)

    def __post_init__(self):
        if self.stale_reject < self.stale_deprioritize:
            raise ValueError("stale_reject must be >= stale_deprioritize")
        if self.max_pending < 0:
            raise ValueError("max_pending must be >= 0")
        if self.window_s <= 0:
            raise ValueError("window_s must be > 0")


class AdmissionController:
    """Applies :class:`AdmissionPolicy` to one arrival at a time."""

    def __init__(self, policy: AdmissionPolicy,
                 registry: Optional[MetricsRegistry] = None):
        self.policy = policy
        self.registry = registry
        self._bucket = TokenBucket(policy.rate_limit, policy.rate_burst)
        self.counts = {v: 0 for v in VERDICTS}
        # Sliding window over (virtual_t, verdict) — fed by the same
        # `_count` call the cumulative counters use. Not checkpointed:
        # a resumed controller's rates warm back up over one window_s.
        self._window: deque = deque()

    def decide(self, now: float, staleness: int, pending: int) -> str:
        """Verdict for an update arriving at virtual time ``now`` whose
        pulled version is ``staleness`` versions old, while ``pending``
        admitted updates are still waiting for incorporation."""
        p = self.policy
        if not self._bucket.take(now):
            return self._count(REJECT_RATE, now)
        if p.max_pending and pending >= p.max_pending:
            return self._count(REJECT_BACKPRESSURE, now)
        if staleness > p.stale_reject:
            return self._count(REJECT_STALE, now)
        if staleness > p.stale_deprioritize:
            return self._count(DEPRIORITIZE, now)
        return self._count(ACCEPT, now)

    def record(self, verdict: str, now: float = 0.0) -> str:
        """Count a verdict decided OUTSIDE the policy checks — the
        defense screen's rejections (quarantine refusals at offer time,
        in-tick screened updates). Pure bookkeeping: no token is spent,
        so a screened update still consumed its rate token at decide()
        time, exactly like any other admitted-then-dropped frame."""
        if verdict not in self.counts:
            raise ValueError(f"unknown verdict {verdict!r}")
        return self._count(verdict, now)

    def _count(self, verdict: str, now: float = 0.0) -> str:
        self.counts[verdict] += 1
        self._window.append((now, verdict))
        self._evict(now)
        if self.registry is not None:
            self.registry.counter("admission_" + verdict).inc()
        return verdict

    def _evict(self, now: float) -> None:
        cutoff = now - self.policy.window_s
        while self._window and self._window[0][0] < cutoff:
            self._window.popleft()

    def window_rates(self, now: Optional[float] = None) -> dict:
        """Per-verdict share of the decisions inside the sliding window
        ending at virtual time ``now`` (default: the newest decision's
        timestamp). Shares of an empty window are all 0.0."""
        if now is not None:
            self._evict(now)
        total = len(self._window)
        tally = {v: 0 for v in VERDICTS}
        for _, verdict in self._window:
            tally[verdict] += 1
        return {"window_s": self.policy.window_s,
                "decisions": total,
                "rates": {v: (tally[v] / total if total else 0.0)
                          for v in VERDICTS}}

    # ------------------------------------------------------------------
    # checkpoint support (fedtpu.serving.engine persists this so a
    # --resume continues the exact verdict sequence — a fresh token
    # bucket would refill to full burst and diverge from the
    # uninterrupted run whenever rate limiting is on)

    def state(self) -> dict:
        """Host state for checkpointing: bucket fill + per-verdict
        counts, in :data:`VERDICTS` order."""
        tokens, t = self._bucket.state()
        return {"bucket_tokens": tokens, "bucket_t": t,
                "counts": [self.counts[v] for v in VERDICTS]}

    def restore_state(self, bucket_tokens: float, bucket_t: float,
                      counts) -> None:
        """Inverse of :meth:`state`. Registry counters are bumped by the
        delta so report totals cover the whole run, not just the
        post-resume segment."""
        self._bucket.restore_state(bucket_tokens, bucket_t)
        for v, n in zip(VERDICTS, counts):
            delta = int(n) - self.counts[v]
            self.counts[v] = int(n)
            if self.registry is not None and delta > 0:
                self.registry.counter("admission_" + v).inc(delta)
