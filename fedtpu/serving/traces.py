"""Versioned JSONL arrival traces: schema, heavy-tailed synthesis, replay.

A trace file is newline-delimited JSON. Line 1 is a header::

    {"kind": "trace_header", "v": 1, "users": 1000000, "arrivals": 50000,
     "seed": 0, "horizon_s": 60.0, "generator": "zipf_lognormal",
     "params": {...}}

Every subsequent line is one client-update arrival, sorted by ``t``::

    {"kind": "arrival", "t": 0.0123, "user": 48713, "lat": 0.87}

``t`` is the arrival time (seconds since trace start, virtual clock) at
which the update *reaches the server*; ``lat`` is the client's local
train+upload latency, so the model version the client pulled is the one
the server had at ``t - lat``. Admission and the serving engine run on
this virtual clock, which is what makes replay deterministic: identical
trace + seed => bitwise-identical metric history, independent of wall
time.

The synthesizer is deliberately heavy-tailed in both dimensions that
matter for admission control: per-user activity is Zipf-distributed
(a few hot users dominate, exercising the rate limiter) and both
inter-arrival gaps and client latencies are lognormal (bursts and
stragglers, exercising backpressure and staleness cutoffs).

No jax anywhere in this module — numpy + stdlib only, same convention
as telemetry/report.py, so loadgen and offline tooling never touch a
device.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from typing import IO, Iterator

import numpy as np

TRACE_SCHEMA_VERSION = 1
# v2 adds the ADVERSARIAL-USER mode (fedtpu.robust; docs/robustness.md):
# a seeded, deterministic attacker id set whose arrival lines carry a
# "poison" field — the amplified sign-flip weight scale the serving
# engine injects as a negative arrival weight. A v2 reader accepts v1
# files unchanged; plain synthesis (poison_frac=0) still writes v1, so
# existing goldens and trace fixtures stay byte-identical.
TRACE_SCHEMA_VERSION_POISON = 2
_READABLE_VERSIONS = (1, 2)
# Seed decorrelation for the attacker draw: the attacker set must not
# correlate with the arrival process drawn from the same seed.
_POISON_SEED_SALT = 0x9E3779B9


@dataclass(frozen=True)
class TraceHeader:
    """Parsed header line of a trace file."""

    v: int
    users: int
    arrivals: int
    seed: int
    horizon_s: float
    generator: str = "unknown"
    params: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "kind": "trace_header",
            "v": self.v,
            "users": self.users,
            "arrivals": self.arrivals,
            "seed": self.seed,
            "horizon_s": self.horizon_s,
            "generator": self.generator,
            "params": self.params,
        }


@dataclass(frozen=True)
class Arrival:
    """One client-update arrival. ``t`` >= ``lat`` >= 0; ``t`` is ascending.

    ``poison`` is 0.0 for honest arrivals (every v1 arrival). For a v2
    adversarial trace it is the positive sign-flip scale the serving
    engine turns into a negative arrival weight (``-poison``) so the
    screen has something real to catch.
    """

    t: float
    user: int
    lat: float
    poison: float = 0.0


def poisoned_user_ids(users: int, seed: int, poison_frac: float) -> np.ndarray:
    """The deterministic attacker id set for a v2 adversarial trace.

    A seeded permutation of the user range, decorrelated from the
    arrival RNG by salting the seed, truncated to
    ``round(poison_frac * users)`` ids. Shared by the synthesizer (to
    mark arrival lines), the defense sim (to score quarantine
    precision), and the chaos campaign (to assert containment), so the
    three can never disagree about who the attackers were.
    """
    if not (0.0 <= poison_frac <= 1.0):
        raise ValueError("poison_frac must be in [0, 1]")
    k = int(round(poison_frac * users))
    if k == 0:
        return np.empty(0, dtype=np.int64)
    rng = np.random.default_rng(seed ^ _POISON_SEED_SALT)
    return np.sort(rng.permutation(users)[:k]).astype(np.int64)


def synthesize_trace(users: int,
                     arrivals: int,
                     horizon_s: float = 60.0,
                     seed: int = 0,
                     zipf_a: float = 1.2,
                     gap_sigma: float = 1.0,
                     lat_mean_s: float = 0.5,
                     lat_sigma: float = 0.75,
                     poison_frac: float = 0.0,
                     poison_scale: float = 10.0) -> tuple[TraceHeader, np.ndarray, np.ndarray, np.ndarray]:
    """Draw a heavy-tailed arrival trace; fully vectorized, one RNG.

    Returns ``(header, t, user, lat)`` as numpy arrays sorted by ``t``.

    - user ids ~ Zipf(zipf_a) folded into [0, users): heavy-tailed
      per-user activity (hot users hammer the token bucket).
    - inter-arrival gaps ~ lognormal(0, gap_sigma), normalized so the
      last arrival lands at ``horizon_s`` (bursty but bounded horizon).
    - client latency ~ lognormal around ``lat_mean_s`` (stragglers pull
      stale versions; the tail drives reject_stale).

    ``poison_frac > 0`` enables the adversarial mode: the header becomes
    v2 and records ``poison_frac``/``poison_scale`` in ``params``. The
    arrival *arrays are unchanged* — attackers are a deterministic
    function of the header (:func:`poisoned_user_ids`), and
    :func:`write_trace` marks their lines. With ``poison_frac == 0``
    the output is byte-identical to a v1 trace from the same seed.
    """
    if users < 1 or arrivals < 1:
        raise ValueError("users and arrivals must be >= 1")
    if not (0.0 <= poison_frac <= 1.0):
        raise ValueError("poison_frac must be in [0, 1]")
    if poison_frac > 0.0 and poison_scale <= 0.0:
        raise ValueError("poison_scale must be > 0 when poison_frac > 0")
    rng = np.random.default_rng(seed)
    # Zipf draws are unbounded above; fold into the user range. (z - 1)
    # keeps user 0 the hottest.
    user = (rng.zipf(zipf_a, size=arrivals) - 1) % users
    gaps = rng.lognormal(mean=0.0, sigma=gap_sigma, size=arrivals)
    t = np.cumsum(gaps)
    t = t * (horizon_s / float(t[-1]))
    mu = math.log(max(lat_mean_s, 1e-9)) - 0.5 * lat_sigma * lat_sigma
    lat = rng.lognormal(mean=mu, sigma=lat_sigma, size=arrivals)
    # A client cannot have pulled before the trace started.
    lat = np.minimum(lat, t)
    params = {
        "zipf_a": zipf_a,
        "gap_sigma": gap_sigma,
        "lat_mean_s": lat_mean_s,
        "lat_sigma": lat_sigma,
    }
    v = TRACE_SCHEMA_VERSION
    if poison_frac > 0.0:
        v = TRACE_SCHEMA_VERSION_POISON
        params["poison_frac"] = float(poison_frac)
        params["poison_scale"] = float(poison_scale)
    header = TraceHeader(
        v=v,
        users=int(users),
        arrivals=int(arrivals),
        seed=int(seed),
        horizon_s=float(horizon_s),
        generator="zipf_lognormal",
        params=params,
    )
    return header, t, user.astype(np.int64), lat


def write_trace(path: str, header: TraceHeader, t: np.ndarray,
                user: np.ndarray, lat: np.ndarray) -> None:
    """Write a trace file (header + one arrival line per event)."""
    if not (len(t) == len(user) == len(lat) == header.arrivals):
        raise ValueError("header.arrivals does not match array lengths")
    # v2 adversarial traces: the attacker set is a pure function of the
    # header, so marking happens here and the arrival arrays stay the
    # same shape for every caller.
    attackers: frozenset = frozenset()
    scale = 0.0
    if header.v == TRACE_SCHEMA_VERSION_POISON:
        frac = float(header.params.get("poison_frac", 0.0))
        scale = float(header.params.get("poison_scale", 0.0))
        if frac <= 0.0 or scale <= 0.0:
            raise ValueError("v2 trace header must carry positive "
                             "poison_frac and poison_scale params")
        attackers = frozenset(
            int(u) for u in poisoned_user_ids(header.users, header.seed, frac))
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(json.dumps(header.to_json(), sort_keys=True) + "\n")
        for i in range(len(t)):
            u = int(user[i])
            if u in attackers:
                fh.write('{"kind": "arrival", "t": %.9f, "user": %d, '
                         '"lat": %.9f, "poison": %.9f}\n'
                         % (float(t[i]), u, float(lat[i]), scale))
            else:
                fh.write('{"kind": "arrival", "t": %.9f, "user": %d, "lat": %.9f}\n'
                         % (float(t[i]), u, float(lat[i])))


def read_header(fh: IO[str]) -> TraceHeader:
    line = fh.readline()
    if not line:
        raise ValueError("empty trace file")
    obj = json.loads(line)
    if obj.get("kind") != "trace_header":
        raise ValueError("trace file does not start with a trace_header line")
    v = int(obj.get("v", -1))
    if v not in _READABLE_VERSIONS:
        raise ValueError(f"unsupported trace schema v={v} "
                         f"(this build reads v in {_READABLE_VERSIONS})")
    return TraceHeader(
        v=v,
        users=int(obj["users"]),
        arrivals=int(obj["arrivals"]),
        seed=int(obj.get("seed", 0)),
        horizon_s=float(obj.get("horizon_s", 0.0)),
        generator=str(obj.get("generator", "unknown")),
        params=dict(obj.get("params", {})),
    )


def read_trace(path: str) -> tuple[TraceHeader, Iterator[Arrival]]:
    """Open a trace for streaming replay.

    Returns the parsed header and a generator of :class:`Arrival` in
    file order (ascending ``t``). Streaming — a 1M-user trace is never
    fully materialized by the reader; the caller decides how much to
    buffer.
    """
    fh = open(path, "r", encoding="utf-8")
    header = read_header(fh)

    def _iter() -> Iterator[Arrival]:
        last_t = -math.inf
        try:
            for line in fh:
                if not line.strip():
                    continue
                obj = json.loads(line)
                if obj.get("kind") != "arrival":
                    continue
                t = float(obj["t"])
                if t < last_t:
                    raise ValueError("trace arrivals are not sorted by t")
                last_t = t
                yield Arrival(t=t, user=int(obj["user"]),
                              lat=float(obj.get("lat", 0.0)),
                              poison=float(obj.get("poison", 0.0)))
        finally:
            fh.close()

    return header, _iter()


def load_trace_arrays(path: str) -> tuple[TraceHeader, np.ndarray, np.ndarray, np.ndarray]:
    """Read a whole trace into ``(header, t, user, lat)`` numpy arrays.

    Convenience for benches and the in-process replay path; prefer
    :func:`read_trace` when the trace may be huge relative to memory.
    """
    header, events = read_trace(path)
    t = np.empty(header.arrivals, dtype=np.float64)
    user = np.empty(header.arrivals, dtype=np.int64)
    lat = np.empty(header.arrivals, dtype=np.float64)
    n = 0
    for ev in events:
        if n >= header.arrivals:
            raise ValueError("trace has more arrivals than its header claims")
        t[n], user[n], lat[n] = ev.t, ev.user, ev.lat
        n += 1
    if n != header.arrivals:
        raise ValueError(f"trace has {n} arrivals, header claims {header.arrivals}")
    return header, t, user, lat
