"""Versioned JSONL arrival traces: schema, heavy-tailed synthesis, replay.

A trace file is newline-delimited JSON. Line 1 is a header::

    {"kind": "trace_header", "v": 1, "users": 1000000, "arrivals": 50000,
     "seed": 0, "horizon_s": 60.0, "generator": "zipf_lognormal",
     "params": {...}}

Every subsequent line is one client-update arrival, sorted by ``t``::

    {"kind": "arrival", "t": 0.0123, "user": 48713, "lat": 0.87}

``t`` is the arrival time (seconds since trace start, virtual clock) at
which the update *reaches the server*; ``lat`` is the client's local
train+upload latency, so the model version the client pulled is the one
the server had at ``t - lat``. Admission and the serving engine run on
this virtual clock, which is what makes replay deterministic: identical
trace + seed => bitwise-identical metric history, independent of wall
time.

The synthesizer is deliberately heavy-tailed in both dimensions that
matter for admission control: per-user activity is Zipf-distributed
(a few hot users dominate, exercising the rate limiter) and both
inter-arrival gaps and client latencies are lognormal (bursts and
stragglers, exercising backpressure and staleness cutoffs).

No jax anywhere in this module — numpy + stdlib only, same convention
as telemetry/report.py, so loadgen and offline tooling never touch a
device.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from typing import IO, Iterator

import numpy as np

TRACE_SCHEMA_VERSION = 1


@dataclass(frozen=True)
class TraceHeader:
    """Parsed header line of a trace file."""

    v: int
    users: int
    arrivals: int
    seed: int
    horizon_s: float
    generator: str = "unknown"
    params: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "kind": "trace_header",
            "v": self.v,
            "users": self.users,
            "arrivals": self.arrivals,
            "seed": self.seed,
            "horizon_s": self.horizon_s,
            "generator": self.generator,
            "params": self.params,
        }


@dataclass(frozen=True)
class Arrival:
    """One client-update arrival. ``t`` >= ``lat`` >= 0; ``t`` is ascending."""

    t: float
    user: int
    lat: float


def synthesize_trace(users: int,
                     arrivals: int,
                     horizon_s: float = 60.0,
                     seed: int = 0,
                     zipf_a: float = 1.2,
                     gap_sigma: float = 1.0,
                     lat_mean_s: float = 0.5,
                     lat_sigma: float = 0.75) -> tuple[TraceHeader, np.ndarray, np.ndarray, np.ndarray]:
    """Draw a heavy-tailed arrival trace; fully vectorized, one RNG.

    Returns ``(header, t, user, lat)`` as numpy arrays sorted by ``t``.

    - user ids ~ Zipf(zipf_a) folded into [0, users): heavy-tailed
      per-user activity (hot users hammer the token bucket).
    - inter-arrival gaps ~ lognormal(0, gap_sigma), normalized so the
      last arrival lands at ``horizon_s`` (bursty but bounded horizon).
    - client latency ~ lognormal around ``lat_mean_s`` (stragglers pull
      stale versions; the tail drives reject_stale).
    """
    if users < 1 or arrivals < 1:
        raise ValueError("users and arrivals must be >= 1")
    rng = np.random.default_rng(seed)
    # Zipf draws are unbounded above; fold into the user range. (z - 1)
    # keeps user 0 the hottest.
    user = (rng.zipf(zipf_a, size=arrivals) - 1) % users
    gaps = rng.lognormal(mean=0.0, sigma=gap_sigma, size=arrivals)
    t = np.cumsum(gaps)
    t = t * (horizon_s / float(t[-1]))
    mu = math.log(max(lat_mean_s, 1e-9)) - 0.5 * lat_sigma * lat_sigma
    lat = rng.lognormal(mean=mu, sigma=lat_sigma, size=arrivals)
    # A client cannot have pulled before the trace started.
    lat = np.minimum(lat, t)
    header = TraceHeader(
        v=TRACE_SCHEMA_VERSION,
        users=int(users),
        arrivals=int(arrivals),
        seed=int(seed),
        horizon_s=float(horizon_s),
        generator="zipf_lognormal",
        params={
            "zipf_a": zipf_a,
            "gap_sigma": gap_sigma,
            "lat_mean_s": lat_mean_s,
            "lat_sigma": lat_sigma,
        },
    )
    return header, t, user.astype(np.int64), lat


def write_trace(path: str, header: TraceHeader, t: np.ndarray,
                user: np.ndarray, lat: np.ndarray) -> None:
    """Write a trace file (header + one arrival line per event)."""
    if not (len(t) == len(user) == len(lat) == header.arrivals):
        raise ValueError("header.arrivals does not match array lengths")
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(json.dumps(header.to_json(), sort_keys=True) + "\n")
        for i in range(len(t)):
            fh.write('{"kind": "arrival", "t": %.9f, "user": %d, "lat": %.9f}\n'
                     % (float(t[i]), int(user[i]), float(lat[i])))


def read_header(fh: IO[str]) -> TraceHeader:
    line = fh.readline()
    if not line:
        raise ValueError("empty trace file")
    obj = json.loads(line)
    if obj.get("kind") != "trace_header":
        raise ValueError("trace file does not start with a trace_header line")
    v = int(obj.get("v", -1))
    if v != TRACE_SCHEMA_VERSION:
        raise ValueError(f"unsupported trace schema v={v} "
                         f"(this build reads v={TRACE_SCHEMA_VERSION})")
    return TraceHeader(
        v=v,
        users=int(obj["users"]),
        arrivals=int(obj["arrivals"]),
        seed=int(obj.get("seed", 0)),
        horizon_s=float(obj.get("horizon_s", 0.0)),
        generator=str(obj.get("generator", "unknown")),
        params=dict(obj.get("params", {})),
    )


def read_trace(path: str) -> tuple[TraceHeader, Iterator[Arrival]]:
    """Open a trace for streaming replay.

    Returns the parsed header and a generator of :class:`Arrival` in
    file order (ascending ``t``). Streaming — a 1M-user trace is never
    fully materialized by the reader; the caller decides how much to
    buffer.
    """
    fh = open(path, "r", encoding="utf-8")
    header = read_header(fh)

    def _iter() -> Iterator[Arrival]:
        last_t = -math.inf
        try:
            for line in fh:
                if not line.strip():
                    continue
                obj = json.loads(line)
                if obj.get("kind") != "arrival":
                    continue
                t = float(obj["t"])
                if t < last_t:
                    raise ValueError("trace arrivals are not sorted by t")
                last_t = t
                yield Arrival(t=t, user=int(obj["user"]),
                              lat=float(obj.get("lat", 0.0)))
        finally:
            fh.close()

    return header, _iter()


def load_trace_arrays(path: str) -> tuple[TraceHeader, np.ndarray, np.ndarray, np.ndarray]:
    """Read a whole trace into ``(header, t, user, lat)`` numpy arrays.

    Convenience for benches and the in-process replay path; prefer
    :func:`read_trace` when the trace may be huge relative to memory.
    """
    header, events = read_trace(path)
    t = np.empty(header.arrivals, dtype=np.float64)
    user = np.empty(header.arrivals, dtype=np.int64)
    lat = np.empty(header.arrivals, dtype=np.float64)
    n = 0
    for ev in events:
        if n >= header.arrivals:
            raise ValueError("trace has more arrivals than its header claims")
        t[n], user[n], lat[n] = ev.t, ev.user, ev.lat
        n += 1
    if n != header.arrivals:
        raise ValueError(f"trace has {n} arrivals, header claims {header.arrivals}")
    return header, t, user, lat
