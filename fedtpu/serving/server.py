"""The long-running `fedtpu serve` process.

A single-threaded selectors loop over one localhost listening socket:
clients (the loadgen, a gateway sidecar) stream update notifications in
the newline-JSON protocol (fedtpu.serving.protocol), each one passes
admission, and admitted ones become driven engine ticks
(fedtpu.serving.engine). Single-threaded is a feature — the engine's
determinism contract (same trace => same history, bitwise) needs a total
order over arrivals, and one thread is the cheapest total order.

Lifecycle honors the supervisor contract from orchestration/loop.py:

    SIGTERM/SIGINT -> finish the in-flight frame -> drain (incorporate
    everything pending) -> checkpoint (engine + serving host state +
    tick history) -> emit 'preempted' -> raise Preempted -> the CLI
    exits EXIT_PREEMPTED (75)

so ``fedtpu supervise -- serve --checkpoint-dir D ...`` restarts it with
``--resume`` and the buffer state RECOVERABLE rather than dropped. The
heartbeat file (``--heartbeat``) is rewritten on every loop wakeup, so
the supervisor's hang detection covers the socket loop too.

jax is only touched through the engine; this module stays importable
backend-free.
"""

from __future__ import annotations

import os
import selectors
import signal
import socket
import threading
from typing import Optional

from fedtpu.serving import protocol
from fedtpu.serving.engine import ServingEngine
from fedtpu.telemetry.log import TelemetryLogger
from fedtpu.telemetry.metrics import default_registry

# Seconds between selector wakeups when idle — bounds signal/heartbeat
# latency, not throughput (a busy socket wakes the loop immediately).
_POLL_S = 0.2

# Per-socket timeout on client connections. send_msg blocks in sendall
# on the single-threaded loop, so a peer that stops reading while we
# hold a response would wedge ingestion for every connection; the
# timeout turns it into a dropped connection instead (socket.timeout is
# an OSError, handled by the per-connection except below).
_CONN_TIMEOUT_S = 30.0


class _Conn:
    """Per-connection recv buffer. A LineBuffer, not a plain bytearray:
    an oversized line is refused at the cap with an error frame and the
    connection survives (the error-frame contract), instead of the legacy
    drop — see protocol.recv_lines."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.buf = protocol.LineBuffer()


def _frame_trace(msg: dict):
    """The frame's causal trace id: the client-stamped ``trace`` field
    when present, else derived server-side from the idempotency stamp
    (same pure function — protocol.trace_id — so old clients' frames
    still chain, and a retry still maps to the SAME id)."""
    trace = msg.get("trace")
    if trace:
        return str(trace)
    nonce, seq = msg.get("nonce"), msg.get("seq")
    if nonce is not None and seq is not None:
        try:
            return protocol.trace_id(nonce, seq)
        except (TypeError, ValueError):
            return None
    return None


def _handle(engine: ServingEngine, msg: dict) -> dict:
    """One request -> one response. Unknown/malformed ops answer with an
    ``error`` frame instead of dropping the connection — a loadgen
    mid-replay must not lose its socket to one bad frame."""
    op = msg.get("op")
    if op == "hello":
        v = msg.get("v")
        if v != protocol.PROTOCOL_VERSION:
            return protocol.error_msg(
                f"protocol v={v} unsupported (server speaks "
                f"v={protocol.PROTOCOL_VERSION})")
        trace = msg.get("trace")
        if trace:
            engine._trace("client_stamp", trace, op="hello",
                          nonce=(str(msg["nonce"]) if msg.get("nonce")
                                 else None), seq=0)
        return {"op": "welcome", "v": protocol.PROTOCOL_VERSION,
                "cohort": engine.C, "version": engine.version}
    if op == "update":
        nonce, seq = msg.get("nonce"), msg.get("seq")
        trace = _frame_trace(msg)
        # Ingress record FIRST: even a frame the dedup gate drops shows
        # its arrival in the causal chain.
        engine._trace("client_stamp", trace, op=op,
                      nonce=(None if nonce is None else str(nonce)),
                      seq=(None if seq is None else int(seq)), events=1)
        cached = engine.session_check(nonce, seq, 1, trace=trace)
        if cached is not None:
            verdict = ("duplicate" if "duplicate" in cached
                       else next(iter(cached)))
            return {"op": "ack", "verdict": verdict,
                    "version": engine.version, "duplicate": True}
        try:
            row = [int(msg["user"]), float(msg["t"]),
                   float(msg.get("lat", 0.0))]
            if msg.get("version") is not None:
                row.append(int(msg["version"]))
            if float(msg.get("poison", 0.0)) > 0.0:
                # Poison rides index 4 (the WAL/replay layout); pad the
                # version slot so the row stays positional.
                if len(row) == 3:
                    row.append(None)
                row.append(float(msg["poison"]))
        except (KeyError, TypeError, ValueError) as e:
            return protocol.error_msg(f"bad update frame: {e}")
        engine.wal_append(nonce, seq, [row], trace=trace)
        verdict = engine.offer(row[1], row[0], row[2],
                               version=(row[3] if len(row) > 3 else None),
                               poison=(float(row[4]) if len(row) > 4 else 0.0),
                               trace=trace)
        engine.session_commit(nonce, seq, {verdict: 1})
        return {"op": "ack", "verdict": verdict, "version": engine.version}
    if op == "updates":
        events = msg.get("events")
        if not isinstance(events, list):
            return protocol.error_msg("updates frame needs an events list")
        if len(events) > protocol.MAX_BATCH_EVENTS:
            return protocol.error_msg(
                f"batch of {len(events)} exceeds "
                f"MAX_BATCH_EVENTS={protocol.MAX_BATCH_EVENTS}")
        nonce, seq = msg.get("nonce"), msg.get("seq")
        trace = _frame_trace(msg)
        engine._trace("client_stamp", trace, op=op,
                      nonce=(None if nonce is None else str(nonce)),
                      seq=(None if seq is None else int(seq)),
                      events=len(events))
        cached = engine.session_check(nonce, seq, len(events), trace=trace)
        if cached is not None:
            return {"op": "acks", "n": len(events), "counts": cached,
                    "version": engine.version, "tick": engine.tick_count,
                    "duplicate": True}
        engine.wal_append(nonce, seq, events, trace=trace)
        try:
            counts = engine.offer_many(events, trace=trace)
        except (TypeError, ValueError, IndexError) as e:
            return protocol.error_msg(f"bad events row: {e}")
        engine.session_commit(nonce, seq, counts)
        return {"op": "acks", "n": len(events), "counts": counts,
                "version": engine.version, "tick": engine.tick_count}
    if op == "stats":
        return {"op": "stats", **engine.summary()}
    if op == "configure":
        try:
            applied = engine.configure(
                tick_interval_s=msg.get("tick_interval_s"),
                flush_every=msg.get("flush_every"))
        except (TypeError, ValueError) as e:
            return protocol.error_msg(f"bad configure frame: {e}")
        return {"op": "configured", **applied}
    if op == "pre_drain":
        try:
            spooled, path = engine.pre_drain(msg.get("path"))
        except (TypeError, ValueError, OSError) as e:
            return protocol.error_msg(f"pre_drain failed: {e}")
        return {"op": "pre_drained", "spooled": spooled, "path": path}
    if op == "drain":
        n = engine.drain()
        return {"op": "drained", "tick": engine.tick_count,
                "incorporated": engine.incorporated, "drained": n}
    return protocol.error_msg(f"unknown op {op!r}")


def _safe_handle(engine: ServingEngine, msg: Optional[dict], tracer,
                 registry, handler=_handle) -> dict:
    """``handler`` behind a crash barrier: an unexpected exception
    becomes an ``error`` frame (counted as ``serve_handler_errors`` and
    traced) instead of escaping the single-threaded loop and killing the
    whole server for every connection. ``Preempted``/KeyboardInterrupt
    are BaseException and pass through untouched."""
    try:
        return (handler(engine, msg) if msg is not None
                else protocol.error_msg("malformed frame"))
    except Exception as e:
        op = msg.get("op") if isinstance(msg, dict) else None
        registry.counter("serve_handler_errors").inc()
        tracer.event("serve_handler_error", op=op,
                     error=f"{type(e).__name__}: {e}")
        # Crash barrier == flight-recorder flush point: the ring (which
        # now ends with the serve_handler_error above) lands in
        # events.crash.<role>.jsonl so the failure ships a post-mortem
        # timeline even though the server itself survives.
        tracer.flush_crash(reason=f"handler:{op!r}:{type(e).__name__}")
        return protocol.error_msg(
            f"internal error handling {op!r}: {type(e).__name__}: {e}")


def run_server(cfg, *, events: Optional[str] = None,
               checkpoint_dir: Optional[str] = None,
               checkpoint_every_ticks: int = 0,
               port_file: Optional[str] = None,
               history_path: Optional[str] = None,
               heartbeat: Optional[str] = None,
               once: bool = False, resume: bool = False,
               verbose: bool = True, handle=None, on_engine=None,
               start_extra: Optional[dict] = None,
               net_fault_plan=None, net_gateway_index: int = 0,
               net_num_gateways: int = 1,
               role: Optional[str] = None) -> dict:
    """Serve until SIGTERM (raises ``Preempted`` after the drain) or,
    with ``once=True``, until the first accepted connection closes
    (clean drain, returns the summary). ``cfg`` is a ServingConfig.

    ``port_file``: the bound port is written here once listening —
    ephemeral-port discovery for loadgen/tests. ``checkpoint_every_ticks``
    adds periodic checkpoints on top of the drain-time one.

    The gateway (fedtpu.serving.gateway) reuses this loop wholesale:
    ``handle`` replaces the per-request dispatcher (same ``(engine, msg)
    -> response`` shape as :func:`_handle`), ``on_engine`` runs once
    after engine construction but before resume (store attach, WAL
    wiring), and ``start_extra`` merges extra identity fields into the
    ``serve_start`` event (e.g. the gateway index fedtpu report groups
    the merged fleet view by).

    ``net_fault_plan`` (a NetFaultPlan spec: path / inline JSON / dict)
    puts a deterministic wire-fault proxy (fedtpu.serving.netproxy) in
    front of this server: the proxy's port file (``<port_file>.net``) is
    written BEFORE the real one, so any client that can discover the
    server's port file atomically routes through the proxy. Requires
    ``port_file``. ``net_gateway_index`` selects which gateway's entries
    of the fleet-wide plan this proxy enforces.
    """
    from fedtpu.resilience.supervisor import Preempted, write_heartbeat
    from fedtpu.telemetry import make_tracer

    registry = default_registry()
    registry.reset()
    # Role-scoped v2 identity stamp ('serve' default; the gateway fleet
    # passes 'gateway-<i>') — what lets `fedtpu timeline` / merged
    # reports key per-process sections even when run_ids collide.
    tracer = make_tracer(events, role=role or "serve")
    log = TelemetryLogger(verbose=verbose, tracer=tracer)
    engine = ServingEngine(cfg, registry=registry, tracer=tracer)
    if checkpoint_dir:
        engine.spool_dir = checkpoint_dir
    if on_engine is not None:
        on_engine(engine)
    if resume and checkpoint_dir:
        from fedtpu.orchestration.checkpoint import latest_step
        if latest_step(checkpoint_dir) is not None:
            step = engine.restore(checkpoint_dir)
            if verbose:
                log.info(f"resumed serving state at tick {step} "
                         f"(version {engine.version}, "
                         f"{len(engine.pending)} pending)")
        # WAL tail: acked frames the kill beat the checkpoint to. Runs
        # even with no checkpoint yet (a first-checkpoint-window kill).
        replayed = engine.replay_wal()
        if replayed and verbose:
            log.info(f"replayed {replayed} acked update(s) from the "
                     "write-ahead log")

    # SIGTERM -> drain flag, main thread only (signal.signal's rule);
    # elsewhere (tests driving run_server from a worker thread) external
    # stop is simply not intercepted, like the round loop.
    preempt = {"sig": None}
    restore_sig = []
    if threading.current_thread() is threading.main_thread():
        def _on_sig(signum, frame):
            preempt["sig"] = signum
        for s in (signal.SIGTERM, signal.SIGINT):
            restore_sig.append((s, signal.signal(s, _on_sig)))

    lsock = socket.socket(  # fedtpu: noqa[FTP009] nonblocking listener under the selectors loop below
        socket.AF_INET, socket.SOCK_STREAM)
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind((cfg.host, cfg.port))
    lsock.listen(16)
    lsock.setblocking(False)
    port = lsock.getsockname()[1]
    proxy = None
    if net_fault_plan is not None:
        if not port_file:
            raise ValueError("--net-fault-plan requires --port-file (the "
                             "proxy is discovered via <port_file>.net)")
        from fedtpu.serving.netproxy import start_proxy
        # Started BEFORE the real port file exists: a client that can
        # read our port file is guaranteed to also see the proxy's.
        proxy = start_proxy(net_fault_plan, net_gateway_index,
                            net_num_gateways, port, port_file,
                            host=cfg.host)
        if verbose:
            log.info(f"net fault proxy on {cfg.host}:{proxy.port} "
                     f"(gateway {net_gateway_index}, "
                     f"schedule {proxy.plan.digest}, "
                     f"{len(proxy.plan.for_gateway(net_gateway_index))} "
                     "fault(s))")
    if port_file:
        tmp = f"{port_file}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            fh.write(str(port))
        os.replace(tmp, port_file)
    if verbose:
        log.info(f"serving on {cfg.host}:{port} (cohort={cfg.cohort}, "
                 f"buffer_size={cfg.buffer_size}, once={once})")
    tracer.event("serve_start", port=port, cohort=cfg.cohort,
                 buffer_size=cfg.buffer_size, resume=bool(resume),
                 **(start_extra or {}))

    sel = selectors.DefaultSelector()
    sel.register(lsock, selectors.EVENT_READ, None)
    ever_connected = False
    last_ckpt_tick = engine.tick_count

    def _shutdown(reason: str) -> dict:
        engine.drain()
        summary = engine.emit_summary()
        if history_path:
            engine.write_history(history_path)
        if checkpoint_dir:
            engine.checkpoint(checkpoint_dir)
        if proxy is not None:
            # Main thread hands the proxy's buffered fault records to
            # the tracer (single-writer events file) and writes the
            # bitwise-compared decision log (*.netlog).
            proxy.finish(tracer)
        tracer.event("serve_stop", round=engine.tick_count, reason=reason)
        if reason == "preempted":
            tracer.event("preempted", round=engine.tick_count)
            registry.counter("preemptions").inc()
        tracer.counters(registry.snapshot())
        if heartbeat:
            write_heartbeat(heartbeat, status=reason,
                            tick=engine.tick_count)
        tracer.close()
        return summary

    try:
        while True:
            if preempt["sig"] is not None:
                if verbose:
                    log.warning(f"signal {preempt['sig']}: draining "
                                f"{len(engine.pending)} pending update(s) "
                                "to checkpoint; exiting for resume "
                                "(preempted).")
                _shutdown("preempted")
                raise Preempted(engine.tick_count)
            if heartbeat:
                write_heartbeat(heartbeat, status="serving",
                                tick=engine.tick_count)
            for key, _ in sel.select(timeout=_POLL_S):
                if key.data is None:
                    try:
                        csock, addr = lsock.accept()
                    except OSError:
                        continue
                    # Timeout mode, not plain blocking: see _CONN_TIMEOUT_S.
                    # recv never waits on it — the selector already said
                    # readable — so only a stalled send can trip it.
                    csock.settimeout(_CONN_TIMEOUT_S)
                    sel.register(csock, selectors.EVENT_READ, _Conn(csock))
                    ever_connected = True
                    tracer.event("serve_accept", peer=str(addr))
                    continue
                conn = key.data
                try:
                    for line in protocol.recv_lines(conn.sock, conn.buf):
                        if line is None:
                            # Oversized line refused at the cap; the
                            # rest of it streams into the void and the
                            # connection lives on.
                            registry.counter("serve_oversized_lines").inc()
                            protocol.send_msg(conn.sock, protocol.error_msg(
                                "line exceeds MAX_LINE_BYTES="
                                f"{protocol.MAX_LINE_BYTES}"))
                            continue
                        msg = protocol.parse_msg(line)
                        resp = _safe_handle(engine, msg, tracer, registry,
                                            handle or _handle)
                        protocol.send_msg(conn.sock, resp)
                except (ConnectionError, OSError):
                    sel.unregister(conn.sock)
                    conn.sock.close()
                    if once and ever_connected:
                        return _shutdown("once")
            if (checkpoint_dir and checkpoint_every_ticks
                    and engine.tick_count - last_ckpt_tick
                    >= checkpoint_every_ticks):
                engine.checkpoint(checkpoint_dir)
                last_ckpt_tick = engine.tick_count
    finally:
        if proxy is not None:
            proxy.stop()
        for s, h in restore_sig:
            signal.signal(s, h)
        sel.close()
        lsock.close()
