"""The `fedtpu serve` wire protocol: newline-delimited JSON over TCP.

One JSON object per line, ``PROTOCOL_VERSION = 1``. The server binds
localhost only — this is a same-host ingestion socket (the loadgen, a
sidecar, a gateway), not an internet-facing API.

Client -> server ops:

    {"op": "hello", "v": 1}
        -> {"op": "welcome", "v": 1, "cohort": C, "version": n}
    {"op": "update", "user": 123, "t": 1.5, "lat": 0.2[, "version": 7]}
        -> {"op": "ack", "verdict": "accept", "version": n}
    {"op": "updates", "events": [[user, t, lat], ...]}
        -> {"op": "acks", "n": len, "counts": {verdict: n}, "version": n,
            "tick": k}
    {"op": "stats"}
        -> {"op": "stats", ...engine/admission snapshot...}
           (includes the machine-readable "signals" block the autoscale
           control plane polls: backlog, window rates, SLO burn)
    {"op": "configure", "tick_interval_s": 0.1, "flush_every": 64}
        -> {"op": "configured", "tick_interval_s": ..., "flush_every": ...}
           (autoscale knob actuation; omitted/null fields are unchanged)
    {"op": "pre_drain"[, "path": "..."]}
        -> {"op": "pre_drained", "spooled": n, "path": "..."}
           (spool the pending updates to disk ahead of a capacity loss)
    {"op": "drain"}
        -> {"op": "drained", "tick": k, "incorporated": n}

``t`` is the arrival's virtual-clock timestamp and ``lat`` the client's
train+upload latency (see traces.py); ``version``, when present, is the
model version the client claims to have pulled — otherwise the server
infers it from ``t - lat`` against its own apply history. The batch
``updates`` frame exists purely for load: one syscall + one parse per
thousands of arrivals is what lets the loadgen replay millions of
simulated users through a single socket.

Idempotent sessions (v1, optional fields): ``update``/``updates`` frames
may carry ``"nonce"`` (a per-client-session identifier that SURVIVES
socket reconnects) and ``"seq"`` (monotonic per nonce, one per frame).
The engine remembers each session's high-water seq and its last ack, so
a frame retried after a lost ack is answered with the ORIGINAL counts
(flagged ``"duplicate": true``) instead of being incorporated twice —
the exactly-once contract the retrying gateway client leans on.

Gateway routing (fedtpu.serving.gateway): a frame for a user another
gateway owns is refused with an error frame carrying a ``"redirect"``
object naming the owner — ``{"gateway": g, "num_gateways": N,
"port_file": ...}`` — which the retrying client follows.

Causal tracing (v1, optional field): a stamped frame may carry
``"trace"`` — the deterministic ``trace_id(nonce, seq)`` digest. The
trace id is a PURE function of the idempotency stamp (never wall time),
so a retried frame carries the SAME id and the merged fleet timeline
(`fedtpu timeline`) shows client-stamp -> gateway-WAL -> dedup-drop ->
incorporation as one logical update. Servers derive the id themselves
when the field is absent, so old clients still get traced.

Anything unparseable or unknown gets ``{"op": "error", ...}`` and the
connection stays up — a load generator mid-replay should not lose its
socket to one malformed frame.

Framing helpers below are shared by server, gateway, and loadgen;
stdlib only.
"""

from __future__ import annotations

import hashlib
import json
import socket
from typing import Iterator, Optional

PROTOCOL_VERSION = 1

# Batch frames bigger than this are refused (protocol error, connection
# survives): bounds per-frame memory on the server regardless of client.
MAX_BATCH_EVENTS = 65536

# A line longer than this is a protocol violation — prevents one bad
# client growing the recv buffer without bound. With a plain bytearray
# buffer the connection is dropped (ConnectionError); with a LineBuffer
# the oversized line is refused AT the cap in a streaming way (yield
# None, discard until the next newline) and the connection survives per
# the error-frame contract — the server uses the latter so a loadgen
# mid-replay does not lose its socket to one runaway frame.
MAX_LINE_BYTES = 8 * 1024 * 1024


class LineBuffer(bytearray):
    """Recv buffer that survives oversized lines.

    ``discarding`` marks that the tail of a refused line is still in
    flight: recv_lines swallows bytes until the terminating newline
    without buffering them, so memory stays bounded by
    ``MAX_LINE_BYTES`` + one recv chunk no matter how the peer segments
    the line. ``dropped`` counts refused lines for telemetry.
    """

    def __init__(self, *a):
        super().__init__(*a)
        self.discarding = False
        self.dropped = 0


def send_msg(sock: socket.socket, obj: dict) -> None:
    # sort_keys: frame bytes feed the netlog's deterministic byte
    # counters (frame_bytes), so the encoding must be canonical — the
    # same payload dict must always serialize to the same bytes.
    sock.sendall(json.dumps(obj, sort_keys=True,
                            separators=(",", ":")).encode() + b"\n")


def recv_lines(sock: socket.socket, buf: bytearray) -> Iterator[Optional[bytes]]:
    """Yield complete lines accumulated in ``buf`` from one recv().

    Returns without yielding when no full line arrived yet; raises
    ``ConnectionError`` on EOF. ``buf`` carries the partial tail between
    calls. A line exceeding ``MAX_LINE_BYTES`` — whether it arrived in
    one chunk or in many small TCP segments — is refused the moment the
    cap is crossed: with a ``LineBuffer`` the refusal is yielded as
    ``None`` (caller answers an error frame, connection survives) and
    the line's remaining bytes are discarded as they stream in; with a
    plain ``bytearray`` the legacy contract holds and ``ConnectionError``
    is raised.
    """
    chunk = sock.recv(1 << 16)
    if not chunk:
        raise ConnectionError("peer closed")
    buf += chunk
    while True:
        if getattr(buf, "discarding", False):
            nl = buf.find(b"\n")
            if nl < 0:
                del buf[:]            # mid-refused-line: drop, stay bounded
                return
            del buf[:nl + 1]
            buf.discarding = False
            continue
        nl = buf.find(b"\n")
        if nl < 0:
            if len(buf) > MAX_LINE_BYTES:
                if not isinstance(buf, LineBuffer):
                    raise ConnectionError("line exceeds MAX_LINE_BYTES")
                buf.discarding = True
                buf.dropped += 1
                del buf[:]
                yield None            # the cap refusal, exactly once
                continue
            return
        if isinstance(buf, LineBuffer) and nl > MAX_LINE_BYTES:
            # Oversized but already complete in the buffer (cap crossed
            # and terminated inside one recv chunk's worth of tail).
            del buf[:nl + 1]
            buf.dropped += 1
            yield None
            continue
        line = bytes(buf[:nl])
        del buf[:nl + 1]
        if line:
            yield line


def parse_msg(line: bytes) -> Optional[dict]:
    """Parse one frame; None (not an exception) for malformed input so
    the server can answer with an ``error`` op instead of dropping."""
    try:
        obj = json.loads(line)
    except ValueError:
        return None
    return obj if isinstance(obj, dict) else None


def error_msg(reason: str) -> dict:
    return {"op": "error", "v": PROTOCOL_VERSION, "reason": reason}


def trace_id(nonce, seq) -> str:
    """Deterministic causal-trace id of one logical frame: a pure digest
    of the idempotency stamp (nonce, seq) — NEVER wall time — so a retry
    resending the same stamp carries the same id, and two same-seed
    passes of a pinned campaign produce bitwise-identical timelines.
    16 hex chars: collision-safe for a fleet's worth of frames while
    keeping event lines small."""
    return hashlib.sha256(f"{nonce}:{int(seq)}".encode()).hexdigest()[:16]


def gateway_port_file(base: str, index: int) -> str:
    """Per-gateway port-file path (``<base>.g<i>``) — the one derivation
    rule shared by the gateway fleet, its clients, and the health probe,
    so a redirect frame's owner is discoverable from the base path
    alone."""
    return f"{base}.g{int(index)}"


def net_proxy_port_file(path: str) -> str:
    """Port-file path of the wire-fault proxy fronting the server whose
    own port file is ``path`` (``<path>.net``). When a ``--net-fault-plan``
    is active the server writes this file BEFORE its real one, so any
    client that discovered the real port file can atomically prefer the
    proxy — that single derivation rule is how loadgen, GatewayClient,
    and the LiveController all route through the chaos wire without
    flags of their own (see fedtpu.serving.netproxy)."""
    return f"{path}.net"


class Connection:
    """Blocking request/response client used by loadgen and tests."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self._buf = bytearray()
        self._pending: list[bytes] = []

    def request(self, obj: dict) -> dict:
        send_msg(self.sock, obj)
        return self.recv()

    def recv(self) -> dict:
        while not self._pending:
            self._pending.extend(recv_lines(self.sock, self._buf))
        msg = parse_msg(self._pending.pop(0))
        if msg is None:
            raise ConnectionError("malformed frame from server")
        return msg

    def hello(self) -> dict:
        resp = self.request({"op": "hello", "v": PROTOCOL_VERSION})
        if resp.get("op") != "welcome":
            raise ConnectionError(f"handshake refused: {resp}")
        return resp

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def __enter__(self) -> "Connection":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
