"""Model registry: ModelConfig -> (init_fn, apply_fn)."""

from __future__ import annotations

import functools

import jax.numpy as jnp

from fedtpu.config import ModelConfig
from fedtpu.models.mlp import mlp_init, mlp_apply
from fedtpu.models.convnet import convnet_init, convnet_apply

_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
           "float16": jnp.float16}


def build_model(cfg: ModelConfig):
    """Return ``(init_fn(key) -> params, apply_fn(params, x) -> logits)``."""
    param_dtype = _DTYPES[cfg.param_dtype]
    compute_dtype = (None if cfg.compute_dtype == cfg.param_dtype
                     else _DTYPES[cfg.compute_dtype])
    if cfg.kind == "mlp":
        init = functools.partial(mlp_init, input_dim=cfg.input_dim,
                                 hidden_sizes=cfg.hidden_sizes,
                                 num_classes=cfg.num_classes,
                                 param_dtype=param_dtype)
        apply = functools.partial(mlp_apply, compute_dtype=compute_dtype)
        return init, apply
    if cfg.kind == "convnet":
        init = functools.partial(convnet_init, image_shape=cfg.image_shape,
                                 conv_channels=cfg.conv_channels,
                                 hidden=cfg.hidden_sizes[0],
                                 num_classes=cfg.num_classes,
                                 param_dtype=param_dtype)
        apply = functools.partial(convnet_apply, compute_dtype=compute_dtype)
        return init, apply
    raise ValueError(f"unknown model kind {cfg.kind!r}")
