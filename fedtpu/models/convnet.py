"""2-layer ConvNet for the CIFAR-10 FedAvg stress config (BASELINE.json #5).

No reference analogue — the reference is tabular-only (SURVEY.md §5,
"long-context" bullet). This model exists to stress the FedAvg aggregation
payload (~1M params vs the income MLP's ~11K) and the MXU conv path.

Architecture: [Conv3x3 -> ReLU -> MaxPool2x2] x len(conv_channels)
-> flatten -> Dense(hidden) -> ReLU -> Dense(classes). NHWC layout (TPU
native); convs via lax.conv_general_dilated so XLA tiles them onto the MXU.
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    bound = 1.0 / math.sqrt(fan_in)
    wk, bk = jax.random.split(key)
    return {
        "w": jax.random.uniform(wk, (kh, kw, cin, cout), dtype, -bound, bound),
        "b": jax.random.uniform(bk, (cout,), dtype, -bound, bound),
    }


def convnet_init(key: jax.Array, image_shape: Tuple[int, int, int],
                 conv_channels: Sequence[int], hidden: int, num_classes: int,
                 param_dtype=jnp.float32):
    h, w, cin = image_shape
    convs = []
    for cout in conv_channels:
        key, sub = jax.random.split(key)
        convs.append(_conv_init(sub, 3, 3, cin, cout, param_dtype))
        cin = cout
        h, w = h // 2, w // 2  # maxpool 2x2 per block
    flat = h * w * cin
    key, k1, k2, k3, k4 = jax.random.split(key, 5)
    b1 = 1.0 / math.sqrt(flat)
    b2 = 1.0 / math.sqrt(hidden)
    return {
        "convs": convs,
        "dense": {"w": jax.random.uniform(k1, (flat, hidden), param_dtype, -b1, b1),
                  "b": jax.random.uniform(k2, (hidden,), param_dtype, -b1, b1)},
        "head": {"w": jax.random.uniform(k3, (hidden, num_classes), param_dtype, -b2, b2),
                 "b": jax.random.uniform(k4, (num_classes,), param_dtype, -b2, b2)},
    }


def _maxpool2(x):
    return lax.reduce_window(x, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1),
                             "VALID")


def convnet_apply(params, x: jax.Array, compute_dtype=None) -> jax.Array:
    """x: (N, H, W, C) or (N, H*W*C) flattened -> logits (N, classes)."""
    out_dtype = params["head"]["w"].dtype
    cast = (lambda a: a.astype(compute_dtype)) if compute_dtype else (lambda a: a)
    if x.ndim == 2:  # packed flat by the tabular-style pipeline
        first = params["convs"][0]["w"]
        cin = first.shape[2]
        side = int(math.isqrt(x.shape[1] // cin))
        x = x.reshape(x.shape[0], side, side, cin)
    h = cast(x)
    for conv in params["convs"]:
        h = lax.conv_general_dilated(
            h, cast(conv["w"]), window_strides=(1, 1), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        h = jax.nn.relu(h + cast(conv["b"]))
        h = _maxpool2(h)
    h = h.reshape(h.shape[0], -1)
    h = jax.nn.relu(h @ cast(params["dense"]["w"]) + cast(params["dense"]["b"]))
    h = h @ cast(params["head"]["w"]) + cast(params["head"]["b"])
    return h.astype(out_dtype)
