"""Pure-pytree MLP: the fedtpu analogue of the reference's ``MLPModel``.

The reference model (FL_CustomMLPCLassifierImplementation_Multiple_Rounds.py:12-25)
is a ``Linear -> ReLU`` stack per hidden size with a final ``Linear`` logits
head, built as ``nn.Sequential``. Here the model is a plain params pytree plus
a pure ``apply`` function — jit/vmap/grad-transformable with nothing hidden in
object state, which is what lets a whole federated round compile into one XLA
program.

Init parity: torch ``nn.Linear`` draws both weight and bias from
U(-1/sqrt(fan_in), +1/sqrt(fan_in)) (kaiming_uniform with a=sqrt(5) reduces to
exactly that bound). We reproduce the distribution with JAX PRNG — same law,
reproducible keys, not bit-identical streams.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp


def mlp_init(key: jax.Array, input_dim: int, hidden_sizes: Sequence[int],
             num_classes: int, param_dtype=jnp.float32):
    """Build the params pytree: ``{'layers': [{'w': (in,out), 'b': (out,)}]}``."""
    dims = (input_dim, *hidden_sizes, num_classes)
    layers = []
    for fan_in, fan_out in zip(dims[:-1], dims[1:]):
        key, wk, bk = jax.random.split(key, 3)
        bound = 1.0 / math.sqrt(fan_in)
        layers.append({
            "w": jax.random.uniform(wk, (fan_in, fan_out), param_dtype,
                                    -bound, bound),
            "b": jax.random.uniform(bk, (fan_out,), param_dtype,
                                    -bound, bound),
        })
    return {"layers": layers}


def mlp_apply(params, x: jax.Array, compute_dtype=None) -> jax.Array:
    """Forward pass -> logits. ``compute_dtype=bfloat16`` runs the matmuls in
    bf16 on the MXU while keeping params (and the returned logits) in the
    param dtype — the standard TPU mixed-precision recipe."""
    layers = params["layers"]
    out_dtype = layers[0]["w"].dtype
    h = x if compute_dtype is None else x.astype(compute_dtype)
    for i, lyr in enumerate(layers):
        w, b = lyr["w"], lyr["b"]
        if compute_dtype is not None:
            w, b = w.astype(compute_dtype), b.astype(compute_dtype)
        h = h @ w + b
        if i < len(layers) - 1:
            h = jax.nn.relu(h)
    return h.astype(out_dtype)
