from fedtpu.models.mlp import mlp_init, mlp_apply  # noqa: F401
from fedtpu.models.convnet import convnet_init, convnet_apply  # noqa: F401
from fedtpu.models.registry import build_model  # noqa: F401
