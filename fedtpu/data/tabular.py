"""Host-side tabular data pipeline.

Re-implements (once, as a library) the preamble duplicated across all three
reference scripts: CSV load -> LabelEncoder over every object column ->
StandardScaler -> ``train_test_split(test_size=0.2, random_state=42)``
(FL_CustomMLPCLassifierImplementation_Multiple_Rounds.py:216-246,
FL_SkLearn_MLPClassifier_Limitation.py:163-197).

Differences from the reference, by design:
  * The reference makes EVERY MPI rank read and preprocess the whole CSV and
    then broadcasts rank 0's split over it anyway (SURVEY.md §3.1). fedtpu is
    single-controller: the host loads once and shards straight onto the device
    mesh — there is no broadcast step to replicate.
  * The reference fits its scaler on the full dataset before splitting
    (FL_CustomMLP...:235-236), leaking test statistics into train. That is the
    parity default here (``scaler_leakage_parity=True``) but the clean
    fit-on-train-only path is one flag away.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np
import pandas as pd

from fedtpu.config import DataConfig


@dataclasses.dataclass
class Dataset:
    """A preprocessed train/test split, still on host as float32/int32 numpy."""

    x_train: np.ndarray
    y_train: np.ndarray
    x_test: np.ndarray
    y_test: np.ndarray
    num_classes: int
    feature_names: tuple
    label_classes: np.ndarray  # original label values, sorted (LabelEncoder order)

    @property
    def input_dim(self) -> int:
        return self.x_train.shape[1]


def _label_encode(df: pd.DataFrame) -> Dict[str, np.ndarray]:
    """Encode every object column to sorted-unique integer codes.

    Equivalent to the reference's per-column ``LabelEncoder().fit_transform``
    (FL_CustomMLP...:222-230): sklearn's LabelEncoder maps values to indices
    into ``np.unique(values)``, which is exactly pandas factorize with sorting.
    """
    encoders = {}
    for col in df.columns:
        # The reference selects ``object`` dtype columns (:224); pandas 3
        # loads text as Arrow-backed string dtype, so check both.
        if df[col].dtype == object or pd.api.types.is_string_dtype(df[col]):
            classes, codes = np.unique(df[col].to_numpy(), return_inverse=True)
            df[col] = codes
            encoders[col] = classes
    return encoders


def _standard_scale(x: np.ndarray, with_mean: bool,
                    stats_from: Optional[np.ndarray] = None):
    """StandardScaler semantics: (x - mean) / std with ddof=0; std==0 -> 1.

    ``with_mean=False`` matches FL_SkLearn...:184 (divide by std only).
    """
    src = x if stats_from is None else stats_from
    mean = src.mean(axis=0) if with_mean else np.zeros(src.shape[1], src.dtype)
    std = src.std(axis=0)
    std = np.where(std == 0.0, 1.0, std)
    return (x - mean) / std, (mean, std)


def _train_test_split(x, y, test_size: float, seed: int):
    """Bit-parity with sklearn's ``train_test_split(random_state=seed)``:
    a seeded permutation with the last ``ceil(n*test_size)`` indices as test
    (sklearn draws ``permutation(n)``, takes the first n_test as test)."""
    from sklearn.model_selection import train_test_split  # parity source of truth

    return train_test_split(x, y, test_size=test_size, random_state=seed)


def _load_encoded(csv_path: str, use_native: bool):
    """Load + label-encode a CSV: ``(column_names, float64 matrix, classes)``
    where object columns in the matrix already hold sorted-unique codes.

    Primary path is the native C++ loader (fedtpu.native — one parse pass,
    the host-runtime replacement for the reference's per-rank pandas +
    LabelEncoder preamble, FL_CustomMLP...:216-230); pandas is the fallback
    when no toolchain is available. A parity test pins both to identical
    output on the shipped income CSV; see csv_loader.cpp for the known
    inference divergences on exotic inputs (pandas NA tokens)."""
    if use_native:
        from fedtpu import native
        if native.available():
            header, _, mat, classes = native.load_csv(csv_path)
            return list(header), mat, classes
    df = pd.read_csv(csv_path)
    encoders = _label_encode(df)
    return list(df.columns), df.to_numpy(dtype=np.float64), encoders


def synthetic_income_like(rows: int, features: int, classes: int,
                          seed: int = 7):
    """A balanced, linearly-separable-ish stand-in for
    balanced_income_data.csv, for tests and environments without the CSV."""
    rng = np.random.default_rng(seed)
    y = np.arange(rows) % classes
    rng.shuffle(y)
    centers = rng.normal(0.0, 2.0, size=(classes, features))
    x = centers[y] + rng.normal(0.0, 1.0, size=(rows, features))
    return x.astype(np.float32), y.astype(np.int32)


def load_tabular_dataset(cfg: DataConfig) -> Dataset:
    """Load + preprocess per the reference pipeline; see module docstring."""
    if cfg.csv_path is None:
        x, y = synthetic_income_like(cfg.synthetic_rows, cfg.synthetic_features,
                                     cfg.synthetic_classes)
        label_classes = np.arange(cfg.synthetic_classes)
        feature_names = tuple(f"f{i}" for i in range(x.shape[1]))
    else:
        loaded = _load_encoded(cfg.csv_path, cfg.native_loader)
        columns, mat, encoders = loaded
        if cfg.label_column not in columns:
            # Same guard as FL_CustomMLP...:219-220.
            raise KeyError(
                f"'{cfg.label_column}' not found in dataset columns. "
                f"Available columns: {list(columns)}")
        li = columns.index(cfg.label_column)
        y = mat[:, li]
        x = np.delete(mat, li, axis=1)
        # Re-encode labels to contiguous 0..K-1 class indices regardless of
        # source dtype: numeric label columns (e.g. the diabetes 'Outcome'
        # path, FL_CustomMLP...:217) bypass _label_encode, and raw values like
        # {1, 2} would otherwise be used as class indices directly —
        # silently clamping in the loss and falling off the confusion matrix.
        original_classes, y = np.unique(y, return_inverse=True)
        label_classes = encoders.get(cfg.label_column, original_classes)
        feature_names = tuple(c for c in columns if c != cfg.label_column)

    num_classes = int(len(np.unique(y)))

    if cfg.scaler_leakage_parity:
        # Reference behavior: scale on the full data, then split
        # (FL_CustomMLP...:235-239).
        x, _ = _standard_scale(x, cfg.scale_with_mean)
        x_train, x_test, y_train, y_test = _train_test_split(
            x, y, cfg.test_size, cfg.split_seed)
    else:
        x_train, x_test, y_train, y_test = _train_test_split(
            x, y, cfg.test_size, cfg.split_seed)
        x_train, (mean, std) = _standard_scale(x_train, cfg.scale_with_mean)
        x_test = (x_test - (mean if cfg.scale_with_mean else 0.0)) / std

    return Dataset(
        x_train=np.asarray(x_train, dtype=np.float32),
        y_train=np.asarray(y_train, dtype=np.int32),
        x_test=np.asarray(x_test, dtype=np.float32),
        y_test=np.asarray(y_test, dtype=np.int32),
        num_classes=num_classes,
        feature_names=feature_names,
        label_classes=np.asarray(label_classes),
    )
