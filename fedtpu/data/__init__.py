from fedtpu.data.tabular import load_tabular_dataset, Dataset  # noqa: F401
from fedtpu.data.sharding import (  # noqa: F401
    shard_indices,
    pack_clients,
    ClientBatch,
)


def load_dataset(cfg) -> Dataset:
    """Single dispatch point for ``DataConfig.dataset_name`` — every consumer
    (run/sweep/parity) resolves data through here so named datasets like
    'cifar10' are honored everywhere, not just in ``build_experiment``."""
    if cfg.dataset_name == "cifar10":
        from fedtpu.data.cifar10 import load_cifar10
        return load_cifar10(synthetic_rows=cfg.synthetic_rows)
    if cfg.dataset_name is not None:
        raise ValueError(f"unknown dataset_name: {cfg.dataset_name!r}")
    return load_tabular_dataset(cfg)
