from fedtpu.data.tabular import load_tabular_dataset, Dataset  # noqa: F401
from fedtpu.data.sharding import (  # noqa: F401
    shard_indices,
    pack_clients,
    ClientBatch,
)
