"""CIFAR-10 loader for the ConvNet stress config (BASELINE.json #5).

No reference analogue — the reference ships exactly one tabular CSV
(SURVEY.md §0). This loader reads the standard CIFAR-10 python pickle batches
(``cifar-10-batches-py``) from a local directory if present; in zero-egress
environments (no download possible) it falls back to a deterministic
synthetic image set with CIFAR shapes, so the full pipeline — packing,
sharding, ConvNet FedAvg — exercises identically either way.
"""

from __future__ import annotations

import os
import pickle
from typing import Optional, Tuple

import numpy as np

from fedtpu.data.tabular import Dataset

_CANDIDATES = ("cifar-10-batches-py", "data/cifar-10-batches-py",
               "/root/data/cifar-10-batches-py")


def find_cifar10_dir(root: Optional[str] = None) -> Optional[str]:
    for cand in ((root,) if root else _CANDIDATES):
        if cand and os.path.isdir(cand) and \
                os.path.exists(os.path.join(cand, "data_batch_1")):
            return cand
    return None


def _load_batch(path: str) -> Tuple[np.ndarray, np.ndarray]:
    with open(path, "rb") as f:
        blob = pickle.load(f, encoding="bytes")
    x = blob[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)  # NHWC
    y = np.asarray(blob[b"labels"], np.int32)
    return x, y


def synthetic_cifar_like(rows: int, seed: int = 11,
                         image_shape=(32, 32, 3), classes: int = 10,
                         center_scale: float = 0.12,
                         noise_std: float = 0.5,
                         label_noise: float = 0.15):
    """Class-conditioned Gaussian blobs + label noise — deterministic,
    CIFAR-shaped, and NON-separable by construction (VERDICT r3 #5: the
    round-3 generator's wide centers saturated the config-5 benchmark at
    accuracy 1.0 by round 38, a smoke test wearing benchmark clothes).

    ``center_scale`` sets the class overlap: pairwise center distance is
    ~``center_scale * sqrt(2 * dim)`` against per-direction noise std
    ``noise_std``. Defaults calibrated on the v5e (round 4): 0.04 left
    the 300-round config-5 trajectory at 0.20 (too hard), 0.08 at 0.58,
    0.12 plateaus at ~0.81 by round ~200 — learnable, sub-cap, and
    falsifiable (the label-noise ceiling is ~0.865).
    ``label_noise`` uniformly re-draws that fraction of labels
    (including possibly the true one), capping reachable accuracy well
    below 1.0 unless the model memorizes individual flipped points.
    ``center_scale=1.0, label_noise=0.0`` reproduces the old separable
    smoke-test distribution."""
    rng = np.random.default_rng(seed)
    y = np.arange(rows) % classes
    rng.shuffle(y)
    h, w, ch = image_shape
    centers = rng.normal(0.0, center_scale, size=(classes, h, w, ch))
    x = centers[y] + rng.normal(0.0, noise_std, size=(rows, h, w, ch))
    y_obs = y.copy()
    if label_noise > 0:
        flip = rng.random(rows) < label_noise
        y_obs[flip] = rng.integers(0, classes, int(flip.sum()))
    return x.astype(np.float32), y_obs.astype(np.int32)


def load_cifar10(root: Optional[str] = None, flatten: bool = True,
                 synthetic_rows: int = 4096) -> Dataset:
    """Return a Dataset with CIFAR-10 train/test (real if the pickle batches
    exist locally, synthetic otherwise). ``flatten=True`` packs images as
    (N, 3072) rows so the tabular sharding/packing path applies unchanged;
    the ConvNet apply reshapes back to NHWC (fedtpu.models.convnet)."""
    d = find_cifar10_dir(root)
    if d is not None:
        xs, ys = zip(*(_load_batch(os.path.join(d, f"data_batch_{i}"))
                       for i in range(1, 6)))
        x_train = np.concatenate(xs).astype(np.float32) / 255.0
        y_train = np.concatenate(ys)
        x_test, y_test = _load_batch(os.path.join(d, "test_batch"))
        x_test = x_test.astype(np.float32) / 255.0
        y_test = np.asarray(y_test, np.int32)
    else:
        x, y = synthetic_cifar_like(synthetic_rows)
        n_test = max(1, len(x) // 5)
        x_train, y_train = x[:-n_test], y[:-n_test]
        x_test, y_test = x[-n_test:], y[-n_test:]

    if flatten:
        x_train = x_train.reshape(len(x_train), -1)
        x_test = x_test.reshape(len(x_test), -1)

    return Dataset(
        x_train=x_train, y_train=y_train.astype(np.int32),
        x_test=x_test, y_test=y_test.astype(np.int32),
        num_classes=10,
        feature_names=tuple(f"px{i}" for i in range(x_train.shape[1])),
        label_classes=np.arange(10),
    )
