"""Client sharding: carve the train set into per-client shards and pack them
into dense ``(clients, samples, ...)`` arrays ready to lay out on the mesh.

Reference semantics being reproduced (and fixed):

* Contiguous chunking by rank, last rank takes the remainder
  (FL_CustomMLPCLassifierImplementation_Multiple_Rounds.py:48-61,
  FL_SkLearn_MLPClassifier_Limitation.py:17-22).
* The torch driver shuffles with an UNSEEDED ``np.random.permutation`` per
  rank (FL_CustomMLP...:53) — each rank permutes independently, so shards
  overlap and do not partition the data. fedtpu's default is a shared-seed
  permutation (a true partition); the bug is available behind
  ``unseeded_per_client_bug`` for parity experiments.
* Non-IID label-skew shards ('label_sort', 'dirichlet') are NEW — required by
  BASELINE.json config 4; the reference only shards IID-contiguously.

TPU-first design note: clients own different shard sizes (the remainder), but
XLA wants static shapes. We pad every shard to the max shard length and carry a
``(clients, samples)`` validity mask plus true per-client counts; masked loss /
metrics make padding invisible, and the true counts drive data-size-weighted
FedAvg exactly like ``len(X_local)`` does at FL_CustomMLP...:104-106.
"""

from __future__ import annotations

import dataclasses
from typing import List

import numpy as np

from fedtpu.config import ShardConfig


@dataclasses.dataclass
class ClientBatch:
    """Dense, padded per-client data. Leading axis = clients; shard it over the
    ('clients',) mesh axis with a NamedSharding."""

    x: np.ndarray       # (C, N_pad, ...) float32
    y: np.ndarray       # (C, N_pad) int32
    mask: np.ndarray    # (C, N_pad) float32, 1.0 for real samples
    counts: np.ndarray  # (C,) int32 true shard sizes

    @property
    def num_clients(self) -> int:
        return self.x.shape[0]


def _contiguous_bounds(num_samples: int, num_clients: int):
    """Chunk bounds per FL_CustomMLP...:58-60: ``chunk = max(1, n // size)``,
    client c takes [c*chunk, (c+1)*chunk) and the last client the remainder."""
    chunk = max(1, num_samples // num_clients)
    bounds = []
    for c in range(num_clients):
        start = c * chunk
        end = start + chunk if c != num_clients - 1 else num_samples
        bounds.append((min(start, num_samples), min(max(end, start), num_samples)))
    return bounds


def _partition_view(cfg: ShardConfig):
    """Resolve the elastic-verification partition window (config.py): shard
    as-if ``partition_clients`` clients exist, keep the ``num_clients``-wide
    window at ``partition_offset``. Returns (full_cfg, offset) — full_cfg is
    the as-if config with the window fields cleared, or None when off."""
    if cfg.partition_clients <= 0:
        return None, 0
    if not (0 <= cfg.partition_offset
            and cfg.partition_offset + cfg.num_clients <= cfg.partition_clients):
        raise ValueError(
            f"partition window [{cfg.partition_offset}, "
            f"{cfg.partition_offset + cfg.num_clients}) exceeds "
            f"partition_clients={cfg.partition_clients}")
    full = dataclasses.replace(cfg, num_clients=cfg.partition_clients,
                               partition_clients=0, partition_offset=0)
    return full, cfg.partition_offset


def shard_indices(y: np.ndarray, cfg: ShardConfig) -> List[np.ndarray]:
    """Return per-client index arrays into the train set."""
    full, offset = _partition_view(cfg)
    if full is not None:
        return shard_indices(y, full)[offset:offset + cfg.num_clients]
    n = len(y)
    c = cfg.num_clients
    rng = np.random.default_rng(cfg.shard_seed)

    if cfg.strategy == "contiguous":
        if cfg.shuffle and cfg.unseeded_per_client_bug:
            # Reference bug parity: every client draws its own unseeded
            # permutation of the FULL set, then takes its contiguous chunk —
            # shards overlap (FL_CustomMLP...:52-61).
            out = []
            for client, (start, end) in enumerate(_contiguous_bounds(n, c)):
                perm = np.random.permutation(n)  # deliberately unseeded
                out.append(perm[start:end])
            return out
        perm = rng.permutation(n) if cfg.shuffle else np.arange(n)
        return [perm[start:end] for start, end in _contiguous_bounds(n, c)]

    if cfg.strategy == "label_sort":
        # Pathological non-IID: sort by label, chunk contiguously — each
        # client sees only one or two labels.
        order = np.argsort(y, kind="stable")
        return [order[start:end] for start, end in _contiguous_bounds(n, c)]

    if cfg.strategy == "dirichlet":
        # Standard federated non-IID benchmark sharding (Hsu et al. style):
        # for each class, split its samples across clients with proportions
        # drawn from Dirichlet(alpha). Small alpha => heavy label skew.
        classes = np.unique(y)
        client_idx = [[] for _ in range(c)]
        for k in classes:
            idx_k = rng.permutation(np.flatnonzero(y == k))
            props = rng.dirichlet(np.full(c, cfg.dirichlet_alpha))
            cuts = (np.cumsum(props)[:-1] * len(idx_k)).astype(int)
            for client, part in enumerate(np.split(idx_k, cuts)):
                client_idx[client].append(part)
        return [rng.permutation(np.concatenate(parts)) if parts else
                np.empty((0,), dtype=np.int64) for parts in client_idx]

    raise ValueError(f"unknown shard strategy {cfg.strategy!r}")


def pack_clients(x: np.ndarray, y: np.ndarray, cfg: ShardConfig,
                 pad_multiple: int = 8) -> ClientBatch:
    """Shard then pack into padded dense arrays (see module docstring).

    ``pad_multiple`` rounds the per-client sample axis up so its size stays
    friendly to XLA tiling (the 8-sublane dimension on TPU).

    Under a partition window (``partition_clients``, see ShardConfig) the
    pad length is computed over ALL partition shards — not just the kept
    window — so every kept row is bitwise-identical (padding included) to
    the corresponding row of the full pack.
    """
    full, offset = _partition_view(cfg)
    if full is not None:
        idx_all = shard_indices(y, full)
        idx = idx_all[offset:offset + cfg.num_clients]
        max_n = max((len(i) for i in idx_all), default=0)
    else:
        idx = shard_indices(y, cfg)
        max_n = max((len(i) for i in idx), default=0)
    max_n = max(1, -(-max_n // pad_multiple) * pad_multiple)

    feat_shape = x.shape[1:]
    c = cfg.num_clients
    xp = np.zeros((c, max_n) + feat_shape, dtype=np.float32)
    yp = np.zeros((c, max_n), dtype=np.int32)
    mask = np.zeros((c, max_n), dtype=np.float32)
    counts = np.zeros((c,), dtype=np.int32)
    for client, ids in enumerate(idx):
        k = len(ids)
        xp[client, :k] = x[ids]
        yp[client, :k] = y[ids]
        mask[client, :k] = 1.0
        counts[client] = k
    return ClientBatch(x=xp, y=yp, mask=mask, counts=counts)
