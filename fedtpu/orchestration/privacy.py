"""PrivacyLedger: the resumable RDP bookkeeping of the DP aggregation path.

Extracted from ``run_experiment`` (VERDICT r3 #8) — the cumulative
per-order RDP curve is the resumable currency of the privacy spend (RDP
composes additively, so a resume that CHANGES noise multiplier or
sampling rate still accounts every round at the rate it was actually
noised with — review r3: charging all rounds at the current config's
rate would under-report epsilon, the unsafe direction). The curve is
maintained and persisted in every checkpoint's meta item UNCONDITIONALLY
(a zero curve while DP is off), so a DP-off resume segment carries the
earlier segments' spend forward instead of silently destroying it.

The reference has no DP at all; this ledger serves the fedtpu DP
extension's accountant (fedtpu.ops.dp_accountant). The loop asks the
ledger three questions — the cumulative curve at a round label, whether
the guarantee is void at that label, and what to persist with a
checkpoint — and reports the final spend through
``ExperimentResult.privacy_spent``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from fedtpu.ops.dp_accountant import DEFAULT_ORDERS, rdp_vector


class PrivacyLedger:
    """Cumulative per-order RDP curve for one run segment, composing the
    restored spend of earlier resumed segments.

    Parameters
    ----------
    fed:
        The run's ``FedConfig`` — supplies the CURRENT segment's
        (participation_rate, dp_noise_multiplier).
    start_round:
        The resume point (0 for a fresh run). Rounds before it belong to
        earlier segments and are charged from ``restored_meta``'s curve.
    restored_meta:
        The checkpoint meta dict the run resumed from (None for a fresh
        run). Recognized keys: ``dp_rdp`` (cumulative curve),
        ``dp_rdp_orders`` (its order grid), ``dp_rdp_assumed`` and
        ``dp_guarantee_void`` (sticky honesty flags).
    """

    def __init__(self, fed, start_round: int = 0,
                 restored_meta: Optional[dict] = None):
        # Charging the CONFIGURED dp_noise_multiplier is correct under
        # adaptive clipping too: the engine calibrates the delta noise at
        # the effective z_delta and the clipped-count at z_count such that
        # the per-round composition equals one Gaussian mechanism of the
        # configured z (fedtpu.parallel.round.
        # effective_delta_noise_multiplier, Andrew et al. 2021).
        self._noise_on = fed.dp_noise_multiplier > 0
        self.per_step = (np.asarray(rdp_vector(fed.participation_rate,
                                               fed.dp_noise_multiplier))
                         if self._noise_on
                         else np.zeros(len(DEFAULT_ORDERS)))
        self.start_round = start_round
        self.base = np.zeros(len(DEFAULT_ORDERS))
        # Both honesty flags persist WITH the curve and OR forward — once
        # a segment's accounting was assumed (pre-r3 checkpoint) or its
        # guarantee voided (unnoised rounds, see void_at), no later
        # resume may silently launder the epsilon back to "clean".
        self.base_assumed = False
        self.void_base = False
        if start_round > 0:
            self._restore(restored_meta or {})

    def _restore(self, meta: dict) -> None:
        self.base_assumed = bool(np.asarray(meta.get("dp_rdp_assumed",
                                                     False)))
        self.void_base = bool(np.asarray(meta.get("dp_guarantee_void",
                                                  False)))
        saved_rdp = meta.get("dp_rdp")
        saved_orders = meta.get("dp_rdp_orders")
        if saved_rdp is not None:
            saved_rdp = np.asarray(saved_rdp, dtype=np.float64)
            if not np.any(saved_rdp > 0):
                # An all-zero curve is exactly zero spend on ANY grid —
                # no projection or assumption needed.
                self.base = np.zeros(len(DEFAULT_ORDERS))
            elif saved_orders is None and len(saved_rdp) == len(self.per_step):
                # Same-era checkpoint without the orders array: the grid
                # length matching today's is the best available identity
                # evidence.
                self.base = saved_rdp
            elif saved_orders is not None:
                # Re-project the saved curve onto today's order grid by
                # MONOTONE UPPER BOUND: Renyi divergence is non-decreasing
                # in the order (van Erven & Harremoes 2014, Thm. 3), so
                # for each of today's orders o the smallest saved value at
                # any order o' >= o over-estimates the true RDP at o —
                # the safe direction (epsilon is over-, never
                # under-reported). Exact matches project exactly (the
                # saved curve is itself monotone, so min over o' >= o
                # lands on o' == o when present); orders above the saved
                # grid's maximum get +inf and drop out of the epsilon
                # minimization. This keeps a DISJOINT grid change finite
                # (advisor r3: all-inf read as a genuinely infinite
                # spend) without assuming any config's rate — and works
                # whether or not the current segment's noise is on, so a
                # noise-off resume can never zero out a positive restored
                # spend (review r4).
                o_arr = np.asarray(saved_orders, dtype=np.float64)
                if o_arr.shape != saved_rdp.shape:
                    # Mismatched curve/orders lengths (cross-version or
                    # partially-written meta): no per-order attribution
                    # is trustworthy — degrade to the unattributable
                    # path instead of crashing resume (review r4).
                    self._unattributable_spend()
                    return
                projected = np.asarray(
                    [np.min(saved_rdp[o_arr >= o])
                     if np.any(o_arr >= o) else np.inf
                     for o in DEFAULT_ORDERS])
                if np.any(np.isfinite(projected)):
                    self.base = projected
                else:
                    # Every saved order sits BELOW today's smallest —
                    # monotonicity bounds nothing. The spend exists but
                    # is unquantifiable on this grid.
                    self._unattributable_spend()
            else:
                # Unidentifiable grid (no orders array, length mismatch):
                # the spend exists but cannot be attributed per order.
                self._unattributable_spend()
        elif self._noise_on:
            # Pre-r3 checkpoint without the curve under a DP config: the
            # only available assumption is the current config's rate —
            # flagged in the report so the epsilon is never silently
            # wrong. (Without DP on, a missing curve stays zero: the
            # pre-r3 non-DP behavior, not a claim — a missing curve,
            # unlike a recorded one, is no evidence of spend.)
            self.base = self.per_step * self.start_round
            self.base_assumed = True

    def _unattributable_spend(self) -> None:
        """A restored curve with POSITIVE spend that cannot be projected
        onto today's order grid. With noise currently on, charge the
        pre-resume rounds at the current config's rate, flagged. With
        noise off there is no rate to assume — per_step is zero, and
        charging zero would silently erase the recorded spend (review
        r4: the laundering the module docstring forbids); carry it as
        +inf instead (epsilon over-reported, the safe direction), still
        flagged so the report distinguishes it from a genuinely infinite
        spend."""
        self.base = (self.per_step * self.start_round if self._noise_on
                     else np.full(len(DEFAULT_ORDERS), np.inf))
        self.base_assumed = True

    @property
    def composed(self) -> bool:
        """True when the epsilon composes noised rounds from EARLIER
        resumed segments — the current segment's (sigma, q) alone cannot
        re-derive it."""
        return bool(np.any(self.base > 0))

    def rdp_at(self, round_label: int) -> np.ndarray:
        """Cumulative RDP curve when the state is at ``round_label``."""
        return self.base + self.per_step * max(
            0, round_label - self.start_round)

    def void_at(self, round_label: int) -> bool:
        """True when the released model has NO (epsilon, delta) guarantee
        despite a nonzero spend: some rounds after the noised ones
        re-trained on the private data with the noise OFF (that is not
        post-processing — it voids the guarantee; review r3)."""
        trained_unnoised = (not self._noise_on
                            and round_label > self.start_round)
        return bool(self.void_base
                    or (trained_unnoised and np.any(self.base > 0)))

    def checkpoint_meta(self, round_label: int) -> dict:
        """The DP bookkeeping persisted with every checkpoint (periodic
        and quarantine) — one definition so the save sites can't
        drift."""
        return {"dp_rdp": self.rdp_at(round_label),
                "dp_rdp_orders": np.asarray(DEFAULT_ORDERS),
                "dp_rdp_assumed": self.base_assumed,
                "dp_guarantee_void": self.void_at(round_label)}


__all__ = ["PrivacyLedger"]
