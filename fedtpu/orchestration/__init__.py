from fedtpu.orchestration.loop import run_experiment, ExperimentResult  # noqa: F401
from fedtpu.orchestration.checkpoint import save_checkpoint, load_checkpoint  # noqa: F401
