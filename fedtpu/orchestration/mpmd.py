"""MPMD round pipelining — the monolithic round chunk decomposed into a
small static DAG of AOT sub-programs (ISSUE 18; ROADMAP item 2).

The synchronous early-stopping mode pays one dispatch RTT *and* one
metric-fetch RTT per round through a remote transport — 15x slower than
the pipelined headline at rps=100 (BENCH_r05: 1.04e-3 vs 7.1e-5
s/round). The round-4 roofline (benchmarks/RESULTS.md) pinned the
on-chip marginal at its byte-bandwidth ceiling, so the remaining lever
is host-side: split the round into concurrently resident programs in
the spirit of MPMD pipeline parallelism (PAPERS.md, arXiv 2412.14374)
and let round k+1's client step run in flight while round k's
aggregation output transfers to the server slice, its metrics program
runs there, and its host fetch drains. The per-round RTT then amortizes
to pipeline fill cost.

The DAG (per chunk of ``R = rounds_per_step`` rounds)::

    client slice (the full round mesh)          server slice (submesh)
    ------------------------------------------  ----------------------
    R == 1:  client_step ──> aggregate ──┐
    R  > 1:  chain (scanned c+a rounds) ─┤
                                         ├─ device_put raw stats ──> metrics
    state' stays resident ───────────────┘      (loss/conf/pooled_conf)

Every sub-program is compiled ahead-of-time (``fn.lower().compile()``),
through the PR 3 :class:`~fedtpu.compilation.cache.ProgramCache` when a
cache directory is configured — the fingerprint includes the
sub-program's device-assignment slice, so client-slice and server-slice
builds of the same avals never collide. Donation crosses program
boundaries: the chain donates the whole federated state (params /
opt-state update in place, exactly like the monolithic step), and the
metrics program donates the transferred raw-stat buffers.

**Parity contract.** The monolithic :func:`fedtpu.parallel.round
.build_round_fn` chunk stays the default engine and the bitwise oracle:
the sub-programs are built from the SAME primitives
(``make_local_train_step`` / ``make_local_eval_step`` /
``make_all_reduce`` / ``bcast_global``) in the same op order, so metric
history and final params match the monolithic path bit for bit
(tests/test_mpmd.py). Only the plain synchronous averaging path
decomposes this way — :func:`validate_mpmd_config` rejects every knob
whose math threads state *through* the aggregation boundary
(server_opt / DP / scaffold / compression / robust rules / sampling)
loudly at startup.

On a single-host mesh the "server slice" is a 1-device
:func:`~fedtpu.parallel.mesh.submesh` of the same device pool (it
overlaps the client slice at device 0); the scheduling win is the host
RTT hiding, which needs no disjoint hardware. On a pod with a spare
slice, heterogeneous placement falls out of the same code path.
"""

from __future__ import annotations

import itertools
import time
from functools import partial
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from fedtpu.ops.metrics import metrics_from_confusion
from fedtpu.parallel.mesh import (CLIENTS_AXIS, replicated_sharding,
                                  submesh)
from fedtpu.parallel.ring import make_all_reduce
from fedtpu.parallel.round import bcast_global
from fedtpu.training.client import make_local_eval_step, make_local_train_step

__all__ = [
    "AUDIT_SPEC", "AUDIT_SPECS", "MpmdStep", "build_mpmd_step",
    "build_mpmd_programs", "parity_check", "server_submesh",
    "validate_mpmd_config",
]

# Per-sub-program audit contracts (PR 8 auditor; fedtpu.analysis.program).
# Each sub-program's collective schedule is gated INDEPENDENTLY: the
# client step and the metrics program must stay collective-free (their
# whole point is to dispatch without waiting on a cross-device phase),
# while aggregate/chain own the clients-axis reductions. ``state`` is
# donated everywhere it threads through; the metrics program donates the
# transferred raw-stat buffers (``loss`` aliases straight back out).
AUDIT_SPECS: Dict[str, dict] = {
    "mpmd_client": {
        "engine": "mpmd_client",
        "builder": "build_mpmd_programs",
        "donate_argnums": (0,),
        "collective_axes": (),
    },
    "mpmd_aggregate": {
        "engine": "mpmd_aggregate",
        "builder": "build_mpmd_programs",
        "donate_argnums": (0,),
        "collective_axes": (CLIENTS_AXIS,),
    },
    "mpmd_chain": {
        "engine": "mpmd_chain",
        "builder": "build_mpmd_programs",
        "donate_argnums": (0,),
        "collective_axes": (CLIENTS_AXIS,),
    },
    "mpmd_metrics": {
        "engine": "mpmd_metrics",
        "builder": "build_mpmd_programs",
        "donate_argnums": (0,),
        # Donate-to-free: the raw-stat buffers are consumed, but only
        # ``loss`` threads back out (metrics["loss"] aliases it) — the
        # confusion matrices have no same-shape output to alias.
        "alias_expected": (),
        "collective_axes": (),
    },
}

# The engine-level spec (engine_audit_spec dispatch): the chain is the
# program that holds the round math and the donated state, so it is the
# manifest's headline sub-program.
AUDIT_SPEC = AUDIT_SPECS["mpmd_chain"]


def validate_mpmd_config(cfg) -> None:
    """Reject configs whose round math cannot decompose at the
    client/aggregate boundary. Loud and exhaustive, at startup — the
    same contract style as ``build_experiment``'s engine branches."""
    fed = cfg.fed
    bad = []
    if fed.async_mode:
        bad.append("async_mode (FedBuff owns its own arrival loop)")
    if fed.cohort_size > 0:
        bad.append("cohort_size > 0 (the cohort scheduler owns the loop)")
    if cfg.run.model_parallel > 1:
        bad.append("model_parallel > 1 (the GSPMD engine is one program "
                   "by construction)")
    if fed.participation_rate < 1.0:
        bad.append("participation_rate < 1 (the sampling coin flips "
                   "thread round state through aggregation)")
    if fed.server_opt != "none":
        bad.append("server_opt (server momentum threads through the "
                   "aggregate boundary)")
    if fed.dp_clip_norm > 0 or fed.dp_noise_multiplier > 0 \
            or fed.dp_adaptive_clip:
        bad.append("differential privacy (clip state and the noise "
                   "stream live on the delta path)")
    if fed.robust_aggregation != "none":
        bad.append("robust_aggregation (gather-based rules)")
    if fed.compress != "none":
        bad.append("compress (delta reconstruction needs shared_start "
                   "state)")
    if fed.scaffold:
        bad.append("scaffold (control variates update inside "
                   "aggregation)")
    if fed.byzantine_clients > 0:
        bad.append("byzantine_clients (corruption is injected between "
                   "training and aggregation)")
    if bad:
        raise ValueError(
            "run.mpmd decomposes the plain synchronous averaging round "
            "only; incompatible with: " + "; ".join(bad))


def server_submesh(mesh):
    """The server slice: a 1-device submesh of the round mesh (order
    preserved, PR 9 machinery), hosting the metrics program. Degenerates
    to the same device on a 1-device mesh — the dispatch overlap, not
    device disjointness, is what hides the RTT."""
    return submesh(mesh, num_devices=1)


def _spec_c():
    return P(CLIENTS_AXIS)


def build_mpmd_programs(mesh, apply_fn: Callable, tx, num_classes: int, *,
                        weighting: str = "data_size",
                        aggregation: str = "psum",
                        local_steps: int = 1,
                        prox_mu: float = 0.0,
                        rounds_per_step: int = 1) -> Dict[str, Callable]:
    """The DAG's jit wrappers, pre-AOT: ``{"client", "aggregate",
    "chain", "metrics"}``. Built from the same primitives as the
    monolithic ``build_round_fn`` plain path, in the same op order, so
    every value is bitwise-identical to the oracle.

    Signatures (all state-dict shaped like the loop's ``state``):

    * ``client(state, batch) -> (state', loss, conf)`` — vmap'd local
      train + eval, zero collectives, donates ``state``.
    * ``aggregate(state, conf, mask) -> (state'', pooled_conf)`` —
      weighted average + pooled-confusion psum, donates ``state``
      (``conf`` is NOT donated: the metrics program still reads it).
    * ``chain(state, batch) -> (state', raw)`` — ``rounds_per_step``
      scanned client+aggregate rounds in one program (one dispatch per
      chunk); ``raw = {"loss", "conf", "pooled_conf"}`` stacked over
      rounds exactly like the monolithic scan outputs.
    * ``metrics(raw, mask) -> metrics`` — ``assemble_metrics`` math,
      donates ``raw``. Takes the LIVE batch mask and derives
      ``masked_client_mean``'s nonempty row in-graph exactly like the
      oracle — fault injection (client dropout) mutates the mask in
      place between rounds, so a build-time snapshot would go stale.
    """
    local_train = make_local_train_step(apply_fn, tx,
                                        local_steps=local_steps,
                                        prox_mu=prox_mu)
    local_eval = make_local_eval_step(apply_fn, num_classes)
    n_devices = mesh.devices.size
    all_reduce = make_all_reduce(aggregation, CLIENTS_AXIS, n_devices)
    spec_c = _spec_c()
    spec_rc = P(None, CLIENTS_AXIS)

    def train_eval(params, opt_state, x, y, mask):
        trained, new_opt, loss = jax.vmap(local_train)(
            params, opt_state, x, y, mask)
        conf = jax.vmap(local_eval)(trained, x, y, mask)     # (Cb, K, K)
        return trained, new_opt, loss, conf

    def average(params, conf, mask):
        n = mask.sum(axis=1)
        w = n if weighting == "data_size" else jnp.ones_like(n)
        total_w = all_reduce(w.sum())             # clients-varying

        def avg(p):
            local = jnp.tensordot(w.astype(jnp.float32),
                                  p.astype(jnp.float32), axes=1)
            glob = all_reduce(local) / jnp.maximum(total_w, 1.0)
            return jnp.where(total_w > 0, bcast_global(glob, p), p)

        new_params = jax.tree.map(avg, params)
        pooled_conf = jax.lax.psum(conf.sum(axis=0), CLIENTS_AXIS)
        return new_params, pooled_conf

    client_body = jax.shard_map(
        train_eval, mesh=mesh,
        in_specs=(spec_c, spec_c, spec_c, spec_c, spec_c),
        out_specs=(spec_c, spec_c, spec_c, spec_c))

    aggregate_body = jax.shard_map(
        average, mesh=mesh,
        in_specs=(spec_c, spec_c, spec_c),
        out_specs=(spec_c, P()))

    def chain_body(params, opt_state, x, y, mask):
        def one_round(carry, _):
            params, opt_state = carry
            trained, new_opt, loss, conf = train_eval(
                params, opt_state, x, y, mask)
            new_params, pooled_conf = average(trained, conf, mask)
            return (new_params, new_opt), (loss, conf, pooled_conf)

        (params, opt_state), stacked = jax.lax.scan(
            one_round, (params, opt_state), length=rounds_per_step)
        loss, conf, pooled_conf = stacked        # leading axis = rounds R
        return params, opt_state, loss, conf, pooled_conf

    chain_sharded = jax.shard_map(
        chain_body, mesh=mesh,
        in_specs=(spec_c, spec_c, spec_c, spec_c, spec_c),
        out_specs=(spec_c, spec_c, spec_rc, spec_rc, P()))

    def _check_state(state):
        for key in ("server_opt_state", "client_cv", "dp_clip"):
            if key in state:
                raise ValueError(
                    f"state holds {key!r} — built for an engine "
                    "validate_mpmd_config rejects; the MPMD DAG would "
                    "silently drop it")

    @partial(jax.jit, donate_argnums=(0,))
    def client(state, batch):
        _check_state(state)
        trained, new_opt, loss, conf = client_body(
            state["params"], state["opt_state"], batch["x"], batch["y"],
            batch["mask"])
        return ({"params": trained, "opt_state": new_opt,
                 "round": state["round"]}, loss, conf)

    @partial(jax.jit, donate_argnums=(0,))
    def aggregate(state, conf, mask):
        new_params, pooled_conf = aggregate_body(state["params"], conf,
                                                 mask)
        return ({"params": new_params, "opt_state": state["opt_state"],
                 "round": state["round"] + 1}, pooled_conf)

    @partial(jax.jit, donate_argnums=(0,))
    def chain(state, batch):
        _check_state(state)
        params, opt_state, loss, conf, pooled_conf = chain_sharded(
            state["params"], state["opt_state"], batch["x"], batch["y"],
            batch["mask"])
        return ({"params": params, "opt_state": opt_state,
                 "round": state["round"] + rounds_per_step},
                {"loss": loss, "conf": conf, "pooled_conf": pooled_conf})

    stacked = rounds_per_step > 1

    @partial(jax.jit, donate_argnums=(0,))
    def metrics(raw, mask):
        loss, conf, pooled_conf = (raw["loss"], raw["conf"],
                                   raw["pooled_conf"])
        # The oracle's masked_client_mean occupancy row, derived from
        # the live mask inside the program (never snapshotted: dropout
        # faults edit the mask between rounds).
        nonempty = (mask.sum(axis=1) > 0).astype(jnp.float32)
        # Same per-element math as assemble_metrics: the R=1 DAG feeds
        # UNSTACKED raws (no leading rounds axis), so the monolithic
        # path's stack-then-squeeze becomes a no-op here instead of a
        # device round-trip.
        if stacked:
            per_client = jax.vmap(jax.vmap(metrics_from_confusion))(conf)
            pooled = jax.vmap(metrics_from_confusion)(pooled_conf)
        else:
            per_client = jax.vmap(metrics_from_confusion)(conf)
            pooled = metrics_from_confusion(pooled_conf)
        denom = jnp.maximum(nonempty.sum(), 1.0)
        client_mean = jax.tree.map(
            lambda v: (v * nonempty).sum(axis=-1) / denom, per_client)
        return {"loss": loss, "per_client": per_client,
                "client_mean": client_mean, "pooled": pooled}

    return {"client": client, "aggregate": aggregate, "chain": chain,
            "metrics": metrics}


def _avals(tree) -> Any:
    return jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype,
                                       sharding=a.sharding), tree)


def _aot(fn: Callable, args: Tuple[Any, ...], *, label: str,
         mesh=None, cache=None, config_slice=None, extra=None):
    """AOT-compile one sub-program, through the ProgramCache when one is
    wired (the fingerprint's mesh signature carries the device slice —
    see cache._mesh_signature)."""
    if cache is None:
        return fn.lower(*args).compile(), None
    from fedtpu.compilation.cache import program_fingerprint
    key = program_fingerprint(label, config=config_slice, mesh=mesh,
                              args=args, extra=extra)
    entry = cache.get_or_compile(key, fn, *args, label=label,
                                 extra_meta={"mpmd": label})
    return entry.compiled, entry


def audit_probes(cfg, chain_width: int = 4) -> Dict[str, tuple]:
    """Per-sub-program audit probe parts for the PR 8 auditor
    (fedtpu.analysis.program ``_PROBES``): ``{engine_name: (jit wrapper,
    example avals, AUDIT_SPEC, mesh)}``. The chain is probed at a
    representative multi-round width so its scanned collective schedule
    (one reduction set per round trip) is what the golden pins."""
    import dataclasses as dc

    from jax.sharding import NamedSharding

    from fedtpu.orchestration.loop import build_experiment

    cfg = dc.replace(cfg, run=dc.replace(
        cfg.run, mpmd=True, pipelined_stop=False, overlap_compile=False,
        model_parallel=1))
    validate_mpmd_config(cfg)
    exp = build_experiment(cfg)
    mesh = exp.mesh
    state_av, batch_av = _avals(exp.state), _avals(exp.batch)
    k = exp.num_classes
    c = exp.batch["mask"].shape[0]
    spec_c = P(CLIENTS_AXIS)

    def c_aval(shape, spec):
        return jax.ShapeDtypeStruct(shape, jnp.float32,
                                    sharding=NamedSharding(mesh, spec))

    kw = dict(weighting=cfg.fed.weighting, aggregation=cfg.fed.aggregation,
              local_steps=cfg.fed.local_steps, prox_mu=cfg.fed.prox_mu)
    p1 = build_mpmd_programs(mesh, exp.apply_fn, exp.tx, k,
                             rounds_per_step=1, **kw)
    pR = build_mpmd_programs(mesh, exp.apply_fn, exp.tx, k,
                             rounds_per_step=chain_width, **kw)
    raw1 = {"loss": c_aval((c,), spec_c),
            "conf": c_aval((c, k, k), spec_c),
            "pooled_conf": c_aval((k, k), P())}
    return {
        "mpmd_client": (p1["client"], (state_av, batch_av),
                        AUDIT_SPECS["mpmd_client"], mesh),
        "mpmd_aggregate": (p1["aggregate"],
                           (state_av, c_aval((c, k, k), spec_c),
                            batch_av["mask"]),
                           AUDIT_SPECS["mpmd_aggregate"], mesh),
        "mpmd_chain": (pR["chain"], (state_av, batch_av),
                       AUDIT_SPECS["mpmd_chain"], mesh),
        "mpmd_metrics": (p1["metrics"], (raw1, batch_av["mask"]),
                         AUDIT_SPECS["mpmd_metrics"], mesh),
    }


class MpmdStep:
    """One chunk of the DAG, presented as the loop's ``step(state,
    batch) -> (new_state, metrics)`` contract.

    Every call issues the whole DAG asynchronously — chain (or
    client->aggregate at width 1) on the client slice, the raw-stat
    transfer, and the metrics program on the server slice — and returns
    with everything still in flight. The loop's pipelined pending
    machinery then overlaps this chunk's fetch under the NEXT chunk's
    dispatch, which is where the RTT disappears.
    """

    def __init__(self, programs: Dict[str, Any], *, width: int,
                 server_mesh, tracer=None):
        self._p = programs
        self._width = width
        self._server_sharding = replicated_sharding(server_mesh)
        self._tracer = tracer
        self._chunk_ids = itertools.count()

    def _event(self, stage: str, rnd, trace_id: str, dur_s: float) -> None:
        tr = self._tracer
        if tr is not None and tr.enabled:
            tr.event("trace", phase=stage, round=rnd, dur_s=dur_s,
                     trace_id=trace_id, op="mpmd", rounds=self._width)

    def __call__(self, state, batch):
        tid = f"mpmd-{next(self._chunk_ids)}"
        rnd = None
        # Dispatch timing brackets ASYNC enqueues on purpose: the whole
        # point of the DAG is that these clocks close before the device
        # work does, so the spans measure host dispatch cost, not
        # compute. A sync here would re-serialize the pipeline.
        t0 = time.perf_counter()  # fedtpu: noqa[FTP010] dispatch-cost span: timing the async enqueue itself; a sync would defeat the MPMD overlap
        if self._width == 1:
            state, loss, conf = self._p["client"](state, batch)
            t1 = time.perf_counter()  # fedtpu: noqa[FTP010] dispatch-cost span (see above)
            self._event("client_step", rnd, tid, t1 - t0)
            state, pooled_conf = self._p["aggregate"](state, conf,
                                                      batch["mask"])
            raw = {"loss": loss, "conf": conf, "pooled_conf": pooled_conf}
        else:
            state, raw = self._p["chain"](state, batch)
            t1 = time.perf_counter()  # fedtpu: noqa[FTP010] dispatch-cost span (see above)
            self._event("client_step", rnd, tid, t1 - t0)
        t2 = time.perf_counter()  # fedtpu: noqa[FTP010] dispatch-cost span (see above)
        self._event("aggregate", rnd, tid, t2 - t1)
        # Metrics sub-program: compiled against the client mesh's
        # shardings (its cross-client reductions must partition exactly
        # like the monolithic oracle's for bitwise parity), then the
        # finished metric dict — a few KB — crosses to the server slice
        # asynchronously. The host fetch drains single-device buffers
        # there while the next chunk's client step is already in flight;
        # client-slice params/opt-state never move.
        metrics = self._p["metrics"](raw, batch["mask"])
        metrics = jax.device_put(metrics, self._server_sharding)
        t3 = time.perf_counter()  # fedtpu: noqa[FTP010] dispatch-cost span (see above)
        self._event("metrics", rnd, tid, t3 - t2)
        return state, metrics


def build_mpmd_step(cfg, *, mesh, apply_fn, tx, num_classes: int,
                    state, batch, width: int, cache=None,
                    tracer=None) -> MpmdStep:
    """Wire the whole DAG for one chunk width: build the jit wrappers,
    AOT-compile each on its slice (through ``cache`` when given), and
    return the loop-ready :class:`MpmdStep`."""
    validate_mpmd_config(cfg)
    programs = build_mpmd_programs(
        mesh, apply_fn, tx, num_classes,
        weighting=cfg.fed.weighting, aggregation=cfg.fed.aggregation,
        local_steps=cfg.fed.local_steps, prox_mu=cfg.fed.prox_mu,
        rounds_per_step=width)
    srv = server_submesh(mesh)
    srv_sharding = replicated_sharding(srv)

    config_slice = None
    if cache is not None:
        from fedtpu.compilation.warmup import program_config_slice
        config_slice = dict(program_config_slice(cfg), mpmd=True)

    state_av, batch_av = _avals(state), _avals(batch)
    k = num_classes
    c = batch["mask"].shape[0]
    compiled: Dict[str, Any] = {}

    def aot(name, fn, args, prog_mesh, extra=None):
        span = tracer.span("mpmd_compile", program=name) if tracer \
            else None
        out, _ = _aot(fn, args, label=f"mpmd_{name}", mesh=prog_mesh,
                      cache=cache, config_slice=config_slice, extra=extra)
        if span is not None:
            span.end()
        compiled[name] = out

    from jax.sharding import NamedSharding

    def c_aval(shape, spec):
        return jax.ShapeDtypeStruct(shape, jnp.float32,
                                    sharding=NamedSharding(mesh, spec))

    spec_c = P(CLIENTS_AXIS)
    spec_rc = P(None, CLIENTS_AXIS)
    if width == 1:
        aot("client", programs["client"], (state_av, batch_av), mesh)
        aot("aggregate", programs["aggregate"],
            (state_av, c_aval((c, k, k), spec_c), batch_av["mask"]), mesh)
        raw_av = {"loss": c_aval((c,), spec_c),
                  "conf": c_aval((c, k, k), spec_c),
                  "pooled_conf": c_aval((k, k), P())}
    else:
        aot("chain", programs["chain"], (state_av, batch_av), mesh,
            extra={"rounds_per_step": width})
        raw_av = {"loss": c_aval((width, c), spec_rc),
                  "conf": c_aval((width, c, k, k), spec_rc),
                  "pooled_conf": c_aval((width, k, k), P())}
    # The metrics program compiles on the CLIENT mesh against the raw
    # stats' live shardings: masked_client_mean's cross-client sum must
    # partition exactly like the monolithic oracle's for bitwise parity.
    # Its (tiny, replicated) outputs are what cross to the server slice.
    aot("metrics", programs["metrics"], (raw_av, batch_av["mask"]),
        mesh, extra={"rounds_per_step": width})

    return MpmdStep(compiled, width=width, server_mesh=srv,
                    tracer=tracer)


def parity_check(preset: str = "income-8", *, rounds: int = 4,
                 synthetic_rows: int = 256) -> dict:
    """Bitwise MPMD-vs-monolithic parity probe (``fedtpu check --mpmd``).

    Runs the preset twice on small synthetic data — once through the
    monolithic oracle, once through the MPMD DAG — and compares the
    recorded metric history and the final parameters bitwise.  Any
    drift (a reassociated cross-client sum, a sharding change in a
    sub-program, a round dropped at a chunk boundary) fails the gate;
    there is no tolerance knob on purpose.
    """
    import dataclasses

    import numpy as np

    from fedtpu.config import get_preset
    from fedtpu.orchestration.loop import run_experiment

    base = get_preset(preset)
    # Chunk width > 1 so the scanned chain program — the production
    # configuration — is what's being compared, not just the 2-program
    # special case.
    width = max(1, rounds // 2)
    base = dataclasses.replace(
        base,
        data=dataclasses.replace(base.data, csv_path=None,
                                 dataset_name=None,
                                 synthetic_rows=synthetic_rows),
        fed=dataclasses.replace(base.fed, rounds=rounds),
        run=dataclasses.replace(base.run, rounds_per_step=width))

    mono = run_experiment(
        dataclasses.replace(base, run=dataclasses.replace(
            base.run, rounds_per_step=width, mpmd=False)),
        verbose=False)
    mp = run_experiment(
        dataclasses.replace(base, run=dataclasses.replace(
            base.run, rounds_per_step=width, mpmd=True)),
        verbose=False)

    metric_mismatches = []
    for key in sorted(set(mono.global_metrics) | set(mp.global_metrics)):
        a = np.asarray(mono.global_metrics.get(key))
        b = np.asarray(mp.global_metrics.get(key))
        if a.shape != b.shape or not np.array_equal(a, b):
            metric_mismatches.append(key)
    param_leaf_mismatches = sum(
        not np.array_equal(np.asarray(pa), np.asarray(pb))
        for pa, pb in zip(jax.tree_util.tree_leaves(mono.final_params),
                          jax.tree_util.tree_leaves(mp.final_params)))
    ok = (not metric_mismatches and param_leaf_mismatches == 0
          and mono.rounds_run == mp.rounds_run)
    return {
        "ok": bool(ok),
        "preset": preset,
        "rounds": rounds,
        "width": width,
        "rounds_run": [mono.rounds_run, mp.rounds_run],
        "metric_mismatches": metric_mismatches,
        "param_leaf_mismatches": int(param_leaf_mismatches),
    }
