"""Host round loop: the fedtpu analogue of ``train_and_evaluate``
(FL_CustomMLPCLassifierImplementation_Multiple_Rounds.py:122-207).

What the reference round loop does with ~5 collectives, 2N+3 barriers, and
pickled weight dicts per round, this loop does with ONE call into the compiled
round program (fedtpu.parallel.round) per chunk of rounds and a scalar
metrics read-back. The host's only jobs are: decide early stopping, accumulate
history, log, checkpoint, and time.

Early-stopping parity (:181-192): rank 0 compares the 4-metric vector
(accuracy, precision, recall, f1 — mean over clients) against the previous
round with ``np.allclose(atol=tolerance)``; `patience` consecutive unchanged
rounds stop training. The reference's stop SIGNAL is read one loop-top late
(:132 reads the signal set at :195), but that lag changes NOTHING trained:
detection at round r happens after round r's train/eval/averaging, and the
re-entered iteration r+1 breaks before its Barrier/train — so the reference
trains and averages exactly r rounds, the same count fedtpu stops at. Pinned
by executing the reference's own ``train_and_evaluate`` under a fake
single-rank comm (tests/test_stop_lag.py); the only observable residue is
the second message ("Training stopped early at round N.") printed from the
doomed iteration, which this loop reproduces for log-faithful A/B.

Throughput knob: ``RunConfig.rounds_per_step = R`` scans R rounds inside one
compiled program, syncing metrics to host once per R rounds. Early stopping is
still evaluated for every round (the compiled program returns per-round
metrics), but a stop that triggers mid-chunk is detected after the chunk
already ran — training may overshoot by up to R-1 rounds (history is
truncated at the stop round; final params include the overshoot). R=1
(default) reproduces the reference cadence exactly.

The metric accumulated for stopping is the reference's semantics #1 — the
MEAN of per-client train-shard metrics (:169). The pooled semantics
(FL_SkLearn...:132-134) and the held-out test metrics (NEW — the reference
broadcasts a test split it never touches, :243-246) are recorded alongside.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import signal
import threading
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedtpu.config import ExperimentConfig
from fedtpu.data import load_dataset
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import Dataset
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.ops.metrics import METRIC_NAMES
from fedtpu.orchestration.checkpoint import (complete_steps,
                                             retain_checkpoints,
                                             save_checkpoint)
from fedtpu.orchestration.privacy import PrivacyLedger
from fedtpu.resilience.distributed import (CollectiveWatchdog,
                                           heartbeat_path_for)
from fedtpu.resilience.supervisor import Preempted, write_heartbeat
from fedtpu.parallel.mesh import make_mesh, client_sharding
from fedtpu.telemetry import (TelemetryLogger, build_manifest,
                              default_registry, install_compile_probe,
                              make_tracer)
from fedtpu.telemetry.metrics import device_memory_gauges
from fedtpu.parallel.round import (build_round_fn, build_eval_fn,
                                   init_federated_state, global_params)
from fedtpu.utils.timing import Timer, force_fetch
from fedtpu.utils.trees import to_numpy


@dataclasses.dataclass
class ExperimentResult:
    """History + final model, the superset of the reference's
    ``global_metrics`` return dict (FL_CustomMLP...:124,207)."""

    # semantics #1: mean of per-client train-shard metrics, one list per
    # metric name — shape-compatible with the reference's global_metrics.
    global_metrics: Dict[str, List[float]]
    # semantics #2: pooled-over-all-clients metrics per round.
    pooled_metrics: Dict[str, List[float]]
    # per-client metric trajectories: (rounds, clients) per name.
    per_client_metrics: Dict[str, List[np.ndarray]]
    # held-out test metrics of the averaged global model (NEW).
    test_metrics: Dict[str, List[float]]
    loss: List[np.ndarray]
    sec_per_round: List[float]
    rounds_run: int
    stopped_early: bool
    final_params: dict
    config: ExperimentConfig
    # True when the non-finite guard (RunConfig.halt_on_nonfinite) fired.
    diverged: bool = False
    # per-client metrics after post-training local fine-tuning
    # (FedConfig.personalize_steps > 0): {"per_client": {name: (C,)},
    # "client_mean": {name: float}}. Empty dict when personalization is off.
    personalized_metrics: Dict[str, dict] = dataclasses.field(
        default_factory=dict)
    # Rounds the RELEASED final_params actually trained through — after a
    # pipelined early stop this exceeds rounds_run by the dropped
    # in-flight overshoot chunk. 0 means "same as rounds_run".
    rounds_trained: int = 0
    # Cumulative per-order RDP curve of the released state (None when DP
    # noise is off). Composes across resumes: rounds noised under an
    # earlier config are charged at THAT config's rate (restored from the
    # checkpoint meta), not the current one.
    dp_rdp_total: Optional[np.ndarray] = None
    # True when a resumed pre-r3 checkpoint carried no RDP curve and the
    # pre-resume rounds had to be charged at the current config's rate.
    dp_base_assumed: bool = False
    # True when rounds AFTER the noised ones re-trained on the private
    # data with noise off — the released model then has NO (eps, delta)
    # guarantee, whatever the curve says (reported as epsilon=inf).
    dp_guarantee_void: bool = False
    # True when the epsilon composes noised rounds from EARLIER resumed
    # segments: the reported (noise_multiplier, sampling_rate) describe
    # only the current segment and cannot re-derive the epsilon alone.
    dp_composed: bool = False
    # Final adaptive clip norm (FedConfig.dp_adaptive_clip); None when
    # adaptive clipping is off.
    final_dp_clip: Optional[float] = None
    # Async engine only (FedConfig.async_mode): per-tick (C,) staleness
    # vectors — arrivals report the staleness their shipped update had,
    # absentees their current age. Empty for the synchronous engines.
    staleness: List[np.ndarray] = dataclasses.field(default_factory=list)

    def summary(self) -> dict:
        last = {k: v[-1] for k, v in self.global_metrics.items() if v}
        extra = ({"personalized_client_mean":
                  self.personalized_metrics.get("client_mean")}
                 if self.personalized_metrics else {})
        # Exclude the first chunk's entries from the mean: its compile time is
        # smeared over rounds_per_step per-round entries, not just the first.
        warm = max(1, self.config.run.rounds_per_step)
        steady = (self.sec_per_round[warm:] if len(self.sec_per_round) > warm
                  else self.sec_per_round or [0.0])
        dp = self.privacy_spent()
        return {
            "rounds_run": self.rounds_run,
            "stopped_early": self.stopped_early,
            "diverged": self.diverged,
            "final_global_metrics": last,
            "mean_sec_per_round": float(np.mean(steady)),
            **extra,
            **({"dp": dp} if dp else {}),
            **({"final_dp_clip": self.final_dp_clip}
               if self.final_dp_clip is not None else {}),
            **({"mean_staleness":
                float(np.mean([s.mean() for s in self.staleness])),
                "max_staleness":
                float(max(s.max() for s in self.staleness))}
               if self.staleness else {}),
        }

    def privacy_spent(self) -> dict:
        """(epsilon, delta) actually spent by this run's DP aggregation —
        the number a DP feature exists to produce (VERDICT r2 weak #6).
        Empty dict when DP noise was off (clipping alone bounds influence
        but provides no epsilon). The mechanism is the client-level
        subsampled Gaussian: q = participation_rate, sigma =
        dp_noise_multiplier, one invocation per round the released state
        trained through — ``rounds_trained``, NOT ``rounds_run``: after a
        pipelined early stop the final params carry the overshoot chunk's
        extra noised rounds, and a privacy accountant must never
        under-count. See fedtpu.ops.dp_accountant for the RDP analysis."""
        fed = self.config.fed
        curve_spent = (self.dp_rdp_total is not None
                       and bool(np.any(np.asarray(self.dp_rdp_total) > 0)))
        if fed.dp_noise_multiplier <= 0 and not curve_spent:
            return {}
        from fedtpu.ops.dp_accountant import (epsilon_from_rdp,
                                              privacy_spent)
        steps = max(self.rounds_run, self.rounds_trained)
        if self.dp_rdp_total is not None:
            # The composed curve — exact across resumes with changed
            # (noise multiplier, sampling rate), and still reported when
            # the CURRENT segment ran with noise off but earlier noised
            # segments built the released model.
            spent = epsilon_from_rdp(list(self.dp_rdp_total), fed.dp_delta)
        else:
            spent = privacy_spent(q=fed.participation_rate,
                                  noise_multiplier=fed.dp_noise_multiplier,
                                  steps=steps, delta=fed.dp_delta)
        out = {"epsilon": spent["epsilon"], "delta": spent["delta"],
               "rdp_order": spent["order"],
               "noise_multiplier": fed.dp_noise_multiplier,
               "sampling_rate": fed.participation_rate,
               "rounds": steps}
        if self.dp_composed:
            # (sigma, q) above are the CURRENT segment's only; the
            # epsilon composes earlier resumed segments' spend from the
            # persisted RDP curve and cannot be re-derived from this
            # dict's triple alone.
            out["composed_over_resumed_segments"] = True
        if self.dp_guarantee_void:
            # Unnoised rounds re-trained on the private data after the
            # noised ones — NOT post-processing: no finite (eps, delta)
            # holds for the released model, whatever was spent before.
            out["epsilon"] = math.inf
            out["rdp_order"] = None
            out["guarantee_void"] = ("rounds trained with noise off "
                                     "after noised rounds")
        if self.dp_base_assumed:
            # Pre-r3 checkpoint: the pre-resume rounds' true (sigma, q)
            # are unrecorded — they were charged at the CURRENT config's
            # rate, so epsilon may be off for those rounds.
            out["resume_rdp"] = "assumed_current_config"
        return out


@dataclasses.dataclass
class Experiment:
    """Wired-up experiment: data on the mesh + compiled-step factory."""

    make_step: Callable[[int], Callable]   # rounds_per_step -> round_step fn
    state: dict
    batch: dict
    eval_step: Callable
    dataset: Dataset
    mesh: object
    # Post-training per-client fine-tune (FedConfig.personalize_steps > 0).
    personalize_fn: Optional[Callable] = None
    # Extract the global model from the engine's state: slot 0 for the
    # synchronous engines (every slot holds the post-average global), the
    # freshest anchor for the async engine (slots hold per-client models).
    global_fn: Callable = global_params
    # Model/optimizer handles for engines that compile their own programs
    # from the experiment's wiring (the MPMD DAG builds its sub-programs
    # from these).
    apply_fn: Optional[Callable] = None
    tx: Optional[object] = None
    num_classes: int = 0


def build_experiment(cfg: ExperimentConfig,
                     dataset: Optional[Dataset] = None,
                     mesh: Optional[object] = None) -> Experiment:
    """Wire data -> mesh -> model -> optimizer -> compiled round factory.

    ``mesh``: explicit ('clients',) mesh to build on instead of the
    process-local default from ``make_mesh``. A live reshard
    (fedtpu.resilience.reshard) passes the agreed post-shrink submesh here —
    under jax.distributed the default would re-enroll every process,
    including the departing one."""
    ds = dataset if dataset is not None else load_dataset(cfg.data)
    model_cfg = cfg.model
    if model_cfg.kind == "mlp" and model_cfg.input_dim != ds.input_dim:
        model_cfg = dataclasses.replace(model_cfg, input_dim=ds.input_dim)
    if model_cfg.num_classes != ds.num_classes:
        model_cfg = dataclasses.replace(model_cfg, num_classes=ds.num_classes)

    init_fn, apply_fn = build_model(model_cfg)
    tx = build_optimizer(cfg.optim)
    packed = pack_clients(ds.x_train, ds.y_train, cfg.shard)

    # Fail fast on a DP config the round builders would reject later —
    # after data loading and state init (both engines share this check).
    if cfg.fed.dp_noise_multiplier > 0 and cfg.fed.dp_clip_norm <= 0:
        raise ValueError("dp_noise_multiplier requires dp_clip_norm > 0 "
                         "(noise std is noise_multiplier * clip / weight)")
    if cfg.fed.dp_adaptive_clip and cfg.fed.dp_clip_norm <= 0:
        # Fail before state init (its adaptive_clip_init guard fires first
        # otherwise, with a less actionable message).
        raise ValueError("dp_adaptive_clip needs dp_clip_norm > 0 as the "
                         "initial clip")

    # Server optimizer / DP delta path: shared by both engines.
    server = None
    if cfg.fed.server_opt != "none":
        from fedtpu.ops.server_opt import make_server_optimizer
        server = make_server_optimizer(
            cfg.fed.server_opt, learning_rate=cfg.fed.server_lr,
            momentum=cfg.fed.server_momentum, b1=cfg.fed.server_b1,
            b2=cfg.fed.server_b2, tau=cfg.fed.server_tau)
    elif cfg.fed.dp_clip_norm > 0 or cfg.fed.scaffold:
        # DP with plain averaging — and SCAFFOLD, whose server update is
        # the paper's eta_g=1 — still run the delta path and need the
        # (empty-momentum) server state initialized.
        from fedtpu.ops.server_opt import identity_server_optimizer
        server = identity_server_optimizer()

    global_fn = global_params
    if cfg.fed.async_mode:
        # The async engine replaces the whole synchronous aggregation
        # stack with the tick/arrival process — every knob of that stack
        # is meaningless (or privacy-unsound) under it, so each is
        # rejected loudly rather than silently ignored.
        if cfg.run.model_parallel > 1:
            raise ValueError("async_mode requires the 1-D engine "
                             "(model_parallel=1)")
        if cfg.fed.weighting != "uniform":
            raise ValueError("async_mode requires weighting='uniform': the "
                             "FedBuff arrival mean is unweighted "
                             "(--weighting uniform)")
        if cfg.fed.participation_rate < 1.0:
            raise ValueError("async_mode replaces client sampling with its "
                             "own arrival process; use --arrival-rate, not "
                             "--participation-rate")
        if server is not None and cfg.fed.server_opt != "none":
            raise ValueError("async_mode has its own server update "
                             "(server_lr-scaled discounted delta mean); "
                             "FedOpt server optimizers are unsupported")
        if cfg.fed.dp_clip_norm > 0 or cfg.fed.dp_noise_multiplier > 0:
            raise ValueError("async_mode does not support DP aggregation: "
                             "per-arrival releases need an async-specific "
                             "accountant fedtpu does not claim to have")
        if cfg.fed.robust_aggregation != "none" or cfg.fed.byzantine_clients:
            raise ValueError("async_mode does not support robust "
                             "aggregation rules (they need the full cohort "
                             "each round; arrivals are a sparse subset)")
        if cfg.fed.compress != "none":
            raise ValueError("async_mode does not support compressed "
                             "exchange")
        if cfg.fed.scaffold:
            raise ValueError("async_mode does not support SCAFFOLD (its "
                             "variate refresh assumes lockstep rounds)")
        if cfg.fed.personalize_steps > 0:
            raise ValueError("async_mode does not support personalize_steps: "
                             "post-training fine-tune starts every client "
                             "from the final averaged global, but async "
                             "client slots hold distinct (possibly stale) "
                             "local models, not that global")
        if cfg.fed.aggregation != "psum":
            raise ValueError("async_mode uses the psum aggregation path "
                             "only")
        from fedtpu.parallel import async_fed
        if mesh is None:
            mesh = make_mesh(cfg.run.mesh_devices, cfg.shard.num_clients)
        shard = client_sharding(mesh)
        state_fn = lambda: async_fed.init_async_state(
            jax.random.key(cfg.fed.init_seed), mesh, cfg.shard.num_clients,
            init_fn, tx, same_init=cfg.fed.same_init,
            buffer_size=cfg.fed.async_buffer_size)
        step_fn = lambda r: async_fed.build_async_round_fn(
            mesh, apply_fn, tx, ds.num_classes,
            arrival_rate=cfg.fed.async_arrival_rate,
            arrival_seed=cfg.fed.async_arrival_seed,
            staleness_power=cfg.fed.async_staleness_power,
            server_lr=cfg.fed.server_lr,
            local_steps=cfg.fed.local_steps,
            prox_mu=cfg.fed.prox_mu,
            buffer_size=cfg.fed.async_buffer_size,
            ticks_per_step=r)
        global_fn = async_fed.async_global_params
    elif cfg.run.model_parallel > 1:
        # 2-D ('clients','model') GSPMD engine (fedtpu.parallel.tp).
        from fedtpu.parallel import tp
        if model_cfg.kind not in ("mlp", "convnet"):
            raise ValueError("model_parallel > 1 supports the MLP and "
                             "ConvNet families only")
        if cfg.fed.participation_rate < 1.0:
            raise ValueError("partial participation requires the 1-D engine "
                             "(model_parallel=1)")
        if cfg.fed.aggregation != "psum":
            raise ValueError("explicit ring aggregation requires the 1-D "
                             "engine (model_parallel=1); the 2-D engine's "
                             "collectives are GSPMD-chosen")
        if cfg.fed.compress != "none":
            raise ValueError("compressed aggregation requires the 1-D "
                             "engine (model_parallel=1)")
        if (cfg.fed.robust_aggregation != "none"
                or cfg.fed.byzantine_clients > 0):
            raise ValueError("robust aggregation / byzantine injection "
                             "requires the 1-D engine (model_parallel=1)")
        if cfg.fed.scaffold:
            raise ValueError("scaffold requires the 1-D engine "
                             "(model_parallel=1)")
        if cfg.fed.dp_adaptive_clip:
            raise ValueError("dp_adaptive_clip requires the 1-D engine "
                             "(model_parallel=1)")
        # Only dims the tp specs actually place on the 'model' axis need to
        # divide: the col-sharded out-dims (even indices — row layers shard
        # the PREVIOUS layer's out-dim, already covered) plus, for convnets,
        # the dense hidden dim (col out / head row in).
        sharded_dims = (model_cfg.hidden_sizes[0::2]
                        if model_cfg.kind == "mlp"
                        else (*model_cfg.conv_channels[0::2],
                              model_cfg.hidden_sizes[0]))
        bad = [h for h in sharded_dims if h % cfg.run.model_parallel]
        if bad:
            raise ValueError(
                f"sharded dims {bad} not divisible by "
                f"model_parallel={cfg.run.model_parallel}; uneven shards "
                "would silently pad and imbalance memory/compute")
        if mesh is not None:
            raise ValueError("build_experiment(mesh=...) supports the 1-D "
                             "engines only (elastic reshard does not cover "
                             "model_parallel > 1)")
        mesh = tp.make_mesh_2d(cfg.run.model_parallel, cfg.shard.num_clients,
                               cfg.run.mesh_devices)
        shard = tp.batch_sharding_2d(mesh)
        state_fn = lambda: tp.init_federated_state_2d(
            jax.random.key(cfg.fed.init_seed), mesh, cfg.shard.num_clients,
            init_fn, tx, same_init=cfg.fed.same_init, server_opt=server)
        step_fn = lambda r: tp.build_round_fn_2d(
            mesh, apply_fn, tx, ds.num_classes, weighting=cfg.fed.weighting,
            rounds_per_step=r, local_steps=cfg.fed.local_steps,
            prox_mu=cfg.fed.prox_mu,
            server_opt=server,
            dp_clip_norm=cfg.fed.dp_clip_norm,
            dp_noise_multiplier=cfg.fed.dp_noise_multiplier,
            dp_seed=cfg.fed.dp_seed)
    else:
        if mesh is None:
            mesh = make_mesh(cfg.run.mesh_devices, cfg.shard.num_clients)
        shard = client_sharding(mesh)
        state_fn = lambda: init_federated_state(
            jax.random.key(cfg.fed.init_seed), mesh, cfg.shard.num_clients,
            init_fn, tx, same_init=cfg.fed.same_init, server_opt=server,
            shared_start=cfg.fed.compress != "none",
            scaffold=cfg.fed.scaffold,
            adaptive_clip_init=(cfg.fed.dp_clip_norm
                                if cfg.fed.dp_adaptive_clip else None))
        step_fn = lambda r: build_round_fn(
            mesh, apply_fn, tx, ds.num_classes, weighting=cfg.fed.weighting,
            rounds_per_step=r,
            participation_rate=cfg.fed.participation_rate,
            participation_seed=cfg.fed.participation_seed,
            aggregation=cfg.fed.aggregation,
            local_steps=cfg.fed.local_steps,
            prox_mu=cfg.fed.prox_mu,
            server_opt=server,
            dp_clip_norm=cfg.fed.dp_clip_norm,
            dp_noise_multiplier=cfg.fed.dp_noise_multiplier,
            dp_seed=cfg.fed.dp_seed,
            dp_adaptive_clip=cfg.fed.dp_adaptive_clip,
            dp_target_quantile=cfg.fed.dp_target_quantile,
            dp_clip_lr=cfg.fed.dp_clip_lr,
            dp_count_noise_multiplier=cfg.fed.dp_count_noise_multiplier,
            compress=cfg.fed.compress,
            robust_aggregation=cfg.fed.robust_aggregation,
            trim_ratio=cfg.fed.trim_ratio,
            krum_f=cfg.fed.krum_f,
            byzantine_clients=cfg.fed.byzantine_clients,
            scaffold=cfg.fed.scaffold)

    # safe_put: plain device_put of a host value onto a cross-process
    # sharding runs an implicit per-array equality broadcast under
    # jax.distributed (fedtpu.parallel.multihost.safe_put).
    from fedtpu.parallel.multihost import safe_put
    batch = {
        "x": safe_put(packed.x, shard),
        "y": safe_put(packed.y, shard),
        "mask": safe_put(packed.mask, shard),
    }
    state = state_fn()

    if cfg.fed.init_weights_npz:
        # Warm start from a persisted weights artifact (sweep winner):
        # broadcast the loaded global model into every client slot,
        # preserving each leaf's live sharding (works for both engines and
        # under jax.distributed — same host data on every process).
        from fedtpu.sweep.grid import load_best_weights
        loaded = load_best_weights(cfg.fed.init_weights_npz)["weights"]
        live = state["params"]
        l_leaves = jax.tree.leaves(loaded)
        p_leaves = jax.tree.leaves(live)
        shapes_ok = (jax.tree.structure(loaded) == jax.tree.structure(live)
                     and all(tuple(a.shape) == tuple(b.shape[1:])
                             for a, b in zip(l_leaves, p_leaves)))
        if not shapes_ok:
            raise ValueError(
                f"init_weights_npz architecture mismatch: artifact leaves "
                f"{[tuple(a.shape) for a in l_leaves]} vs model (per-client) "
                f"{[tuple(b.shape[1:]) for b in p_leaves]} — the artifact "
                "was saved for a different hidden_sizes/input_dim")
        state["params"] = _bcast_into_slots(loaded, live)
        if "anchors" in state:
            # Async engine: clients "pulled" the warm-start global, so the
            # anchors (the deltas' reference points) must carry it too.
            state["anchors"] = _bcast_into_slots(loaded, state["anchors"])

    # Opt-in Pallas fused forward for the held-out eval (a plain jit, outside
    # shard_map; the in-round eval stays on the XLA path, which shard_map's
    # scan requires in interpret mode). Measured on the v5e the XLA path is
    # FASTER (4.5 vs 6.1 us — benchmarks/RESULTS.md 'Pallas kernel
    # timings'), so this stays opt-in for demonstration, not a perf default.
    eval_apply = apply_fn
    if (model_cfg.use_pallas and model_cfg.kind == "mlp"
            and model_cfg.param_dtype == "float32"
            and model_cfg.compute_dtype == "float32"):
        from fedtpu.ops.pallas_kernels import fused_mlp_forward
        eval_apply = fused_mlp_forward

    eval_step = build_eval_fn(eval_apply, ds.num_classes)
    personalize_fn = None
    if cfg.fed.personalize_steps > 0:
        from fedtpu.training.personalize import build_personalize_fn
        personalize_fn = build_personalize_fn(apply_fn, tx, ds.num_classes,
                                              cfg.fed.personalize_steps)
    return Experiment(make_step=step_fn, state=state, batch=batch,
                      eval_step=eval_step, dataset=ds, mesh=mesh,
                      personalize_fn=personalize_fn, global_fn=global_fn,
                      apply_fn=apply_fn, tx=tx, num_classes=ds.num_classes)


@jax.jit
def _tree_finite(tree) -> jax.Array:
    """Single-scalar device reduction: every floating leaf entirely finite
    (integer leaves — optimizer step counts — cannot be non-finite)."""
    checks = [jnp.all(jnp.isfinite(l)) for l in jax.tree.leaves(tree)
              if jnp.issubdtype(l.dtype, jnp.inexact)]
    return jnp.all(jnp.stack(checks)) if checks else jnp.array(True)


def _bcast_into_slots(global_np, live_params):
    """Host-side form of bcast_global (fedtpu.parallel.round): one global
    (clients-free) numpy pytree into every client slot of the live sharded
    params, preserving each leaf's per-leaf sharding and dtype. Shared by
    elastic resume and the init_weights warm start — keep them from
    drifting apart."""
    from fedtpu.parallel.multihost import safe_put
    return jax.tree.map(
        lambda g, p: safe_put(
            np.broadcast_to(np.asarray(g)[None], p.shape).astype(p.dtype),
            p.sharding),
        global_np, live_params)


def _unstack_metrics(metrics: dict, take: int) -> List[dict]:
    """Per-round metric dicts out of a (possibly R-stacked) metrics pytree."""
    if take == 1:
        return [metrics]
    return [jax.tree.map(lambda v: v[j], metrics) for j in range(take)]


def _drop_tail(lst: list, n: int) -> None:
    """Drop the last ``n`` entries in place (no-op for n <= 0; clamped) —
    the rollback truncation primitive for the in-memory-only histories,
    which may hold FEWER entries than rounds when the run resumed."""
    if n > 0:
        del lst[max(0, len(lst) - n):]


def run_experiment(cfg: ExperimentConfig, dataset: Optional[Dataset] = None,
                   verbose: bool = True,
                   resume: bool = False) -> ExperimentResult:
    """``resume=True``: restore the latest checkpoint under
    ``cfg.run.checkpoint_dir`` (full per-client state + the client-mean metric
    history) and continue the round loop from the saved round. Pooled /
    per-client / test histories restart at the resume point; the early-stop
    comparator re-seeds from the restored history's last entry."""
    # Multi-process (multi-host) awareness — the reference runs its WHOLE
    # driver under `mpirun --hostfile`, so the whole loop must run under
    # jax.distributed too (tests/test_multihost_e2e.py runs it across two
    # OS processes). Three rules:
    #   * anything fetched to host must be FULLY REPLICATED first —
    #     per-client leaves are client-sharded across processes and not
    #     host-addressable; `_rep` re-lays a pytree out replicated (GSPMD
    #     inserts the cross-host all-gathers), which also keeps the
    #     early-stop/divergence control flow consensual on every process;
    #   * print/console side effects happen on process 0 only — every
    #     process gets a real role-scoped tracer (peers write to the
    #     derived ``<events>.p<i>`` sink) but a silent logger — but
    #     NOT checkpoint writes: orbax save is a collective (every process
    #     must call it or the job deadlocks in orbax's internal barrier),
    #     with each process persisting the client shards it owns to the
    #     shared checkpoint filesystem;
    #   * control flow (early stop, divergence, round counters) stays
    #     identical on every process because it is derived from the
    #     replicated metrics.
    # Cohort-store engine mode (fedtpu.cohort; docs/scaling.md): the
    # population lives in a host-side ClientStateStore and only
    # cohort_size slots exist on device — the round loop, prefetch, and
    # store writeback all live in run_cohort_experiment. Same config
    # surface, same ExperimentResult, bitwise-equal to this loop when
    # cohort_size == num_clients (tests/test_cohort.py).
    if cfg.fed.cohort_size > 0:
        from fedtpu.cohort.scheduler import run_cohort_experiment
        return run_cohort_experiment(cfg, dataset=dataset, verbose=verbose,
                                     resume=resume)
    # Resilience knob validation FIRST — before any build/compile work,
    # so a bad combination fails in milliseconds, not after a compile.
    if cfg.run.on_divergence not in ("halt", "rollback"):
        raise ValueError("on_divergence must be 'halt' or 'rollback', got "
                         f"{cfg.run.on_divergence!r}")
    if cfg.run.on_divergence == "rollback":
        if not (cfg.run.checkpoint_dir and cfg.run.checkpoint_every > 0):
            raise ValueError("on_divergence='rollback' needs a restore "
                             "point: set checkpoint_dir and "
                             "checkpoint_every > 0")
        if cfg.run.pipelined_stop:
            raise ValueError(
                "on_divergence='rollback' is incompatible with "
                "pipelined_stop: the pipelined divergence guard fires one "
                "in-flight chunk late, after the restore point's successor "
                "chunk already dispatched")
    if cfg.run.rollback_exclude:
        if cfg.run.on_divergence != "rollback":
            raise ValueError("rollback_exclude requires "
                             "on_divergence='rollback'")
        if cfg.fed.async_mode:
            raise ValueError("rollback_exclude requires the synchronous "
                             "engines: exclusion zeroes the sample mask, "
                             "which the async arrival process ignores")
        if cfg.fed.weighting != "data_size":
            raise ValueError(
                "rollback_exclude requires weighting='data_size': a "
                "zero-mask client has aggregation weight mask.sum()=0 only "
                "under data-size weighting (under 'uniform' it would still "
                "average in at weight 1)")
    if cfg.run.mpmd:
        # MPMD DAG (fedtpu.orchestration.mpmd): same fail-fast contract —
        # every engine knob the decomposition cannot honor is rejected
        # before any build work.
        from fedtpu.orchestration.mpmd import validate_mpmd_config
        validate_mpmd_config(cfg)
        if cfg.run.pipelined_stop:
            raise ValueError(
                "run.mpmd subsumes pipelined_stop (the DAG already keeps "
                "one chunk in flight); set only one of the two")
        if cfg.run.on_divergence == "rollback":
            raise ValueError(
                "on_divergence='rollback' is incompatible with mpmd for "
                "the same reason as pipelined_stop: the divergence guard "
                "fires one in-flight chunk late, after the restore "
                "point's successor chunk already dispatched")
        if cfg.run.overlap_compile:
            raise ValueError(
                "run.mpmd compiles every sub-program ahead of time; "
                "overlap_compile has no monolithic chunk left to build "
                "in the background")

    multiproc = jax.process_count() > 1
    if cfg.run.mpmd and multiproc:
        raise ValueError(
            "run.mpmd is single-process: the DAG's cross-slice "
            "device_put edge has no multihost transfer path")
    io_proc = jax.process_index() == 0
    verbose = verbose and io_proc

    tel = cfg.run.telemetry
    # Schema-v2 identity: every process gets a REAL role-scoped tracer.
    # Process 0 keeps the configured sink; peers derive ``<events>.p<i>``
    # (the heartbeat derivation rule) so each file stays single-writer
    # and `fedtpu timeline` / merged `fedtpu report` can key per-process
    # sections explicitly instead of colliding on run_id.
    events_path = tel.events_path
    if events_path and not io_proc:
        events_path = f"{events_path}.p{jax.process_index()}"
    tracer = make_tracer(events_path, role="run",
                         process_index=jax.process_index())
    registry = default_registry()
    registry.reset()
    install_compile_probe()
    log = TelemetryLogger(verbose=verbose, tracer=tracer,
                          level=tel.log_level)

    if cfg.run.compilation_cache:
        # Before ANY compile (build_experiment may already trace programs):
        # the same entry point the CLI's --compilation-cache flag uses, so
        # library callers get identical warm-start behavior.
        from fedtpu.compilation import configure_persistent_cache
        configure_persistent_cache(cfg.run.compilation_cache)

    with tracer.span("build"):
        exp = build_experiment(cfg, dataset)
    state, batch, eval_step, ds = exp.state, exp.batch, exp.eval_step, exp.dataset

    # Supervisor restart generation (fedtpu.resilience.supervisor sets
    # FEDTPU_RESTARTS on every child): recorded in the manifest, and it
    # disarms the fault plan's once-per-run kill faults — a restarted run
    # resumes BELOW the fault round and would re-kill itself forever.
    restart_count = int(os.environ.get("FEDTPU_RESTARTS", "0") or 0)

    injector = None
    if cfg.run.fault_plan:
        from fedtpu.resilience.faults import FaultInjector, FaultPlan
        plan = FaultPlan.load(cfg.run.fault_plan,
                              num_clients=cfg.shard.num_clients,
                              rounds=cfg.fed.rounds)
        injector = FaultInjector(plan, restart_count=restart_count,
                                 tracer=tracer, registry=registry,
                                 process_index=jax.process_index())
        log.info(f"Fault plan {plan.digest}: {len(plan.faults)} fault(s), "
                 f"{injector.armed_count} armed"
                 + (f" (restart {restart_count})" if restart_count else "")
                 + ".")

    # Preemption drain: SIGTERM (the cloud's eviction notice, and the
    # supervisor's forwarded stop) sets a flag the loop-top check turns
    # into checkpoint + Preempted (exit code 75 via the CLI). Installed
    # only when there is somewhere to drain TO, and only on the main
    # thread (signal.signal's requirement). Multihost preemption assumes
    # the signal reaches every process (the TPU maintenance-event
    # convention) — the drain save is a collective.
    preempt = {"sig": None}
    _prev_term = None
    if (cfg.run.checkpoint_dir
            and threading.current_thread() is threading.main_thread()):
        def _on_term(signum, frame):
            preempt["sig"] = signum
        _prev_term = signal.signal(signal.SIGTERM, _on_term)

    # Liveness: EVERY process writes its own derived heartbeat path
    # (process 0 keeps the configured base, peers get .p<i>) so the gang
    # supervisor can tell a wedged worker from a healthy gang — a single
    # shared file would let any one live process mask a hung peer.
    heartbeat = (heartbeat_path_for(cfg.run.heartbeat_file,
                                    jax.process_index())
                 if cfg.run.heartbeat_file else None)

    def _beat(status: str, rnd: int) -> None:
        """Liveness heartbeat (atomic rewrite, one file per process): the
        supervisor's --hang-timeout reads its mtime."""
        if heartbeat:
            write_heartbeat(heartbeat, status=status, round=rnd,
                            restarts=restart_count)

    _beat("starting", 0)

    # Elastic live reshard (fedtpu.resilience.reshard; docs/resilience.md
    # "Elastic resharding"): a preemption NOTICE — SIGUSR1/SIGUSR2
    # forwarded by the gang supervisor, or a preempt_notice/preempt_cancel
    # fault-plan entry — resizes the gang at a round boundary instead of
    # tearing it down. 1-D engines only; the lockstep protocol needs
    # width-1 chunks and the synchronous stop path, so a SIGNAL under any
    # other config degrades to the ordinary SIGTERM drain in the loop (a
    # PLAN entry under such a config is a startup error instead — the plan
    # promised an exact-round reshard the config cannot deliver).
    reshard_ctl = None
    reshard_stack: List[dict] = []     # pre-shrink bindings, for grow-back
    ckpt_group = None                  # surviving processes after a shrink
    reshard_live = (max(1, cfg.run.rounds_per_step) == 1
                    and not cfg.run.pipelined_stop and not cfg.run.mpmd)
    if cfg.run.model_parallel == 1:
        from fedtpu.resilience.distributed import ENV_LAUNCH_ID
        from fedtpu.resilience.reshard import (ReshardController,
                                               ReshardFailed)
        reshard_ctl = ReshardController(
            plan=injector.plan if injector is not None else None,
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            launch_id=os.environ.get(ENV_LAUNCH_ID) or None,
            restart_count=restart_count,
            checkpoint_dir=cfg.run.checkpoint_dir or None,
            ack_timeout=cfg.run.collective_timeout or 60.0,
            tracer=tracer, registry=registry,
            heartbeat=cfg.run.heartbeat_file or None)
        reshard_ctl.install_signal_handlers()
    if injector is not None:
        from fedtpu.resilience.faults import RESHARD_KINDS
        if any(f.kind in RESHARD_KINDS for f in injector.plan.faults):
            if reshard_ctl is None:
                raise ValueError("preempt_notice/preempt_cancel faults "
                                 "require the 1-D engines "
                                 "(model_parallel=1)")
            if not reshard_live:
                raise ValueError("preempt_notice/preempt_cancel faults "
                                 "require rounds_per_step=1 and "
                                 "pipelined_stop off: the reshard fires at "
                                 "an exact round boundary")
            if multiproc and not cfg.run.checkpoint_dir:
                raise ValueError("multi-process elastic reshard needs "
                                 "checkpoint_dir: the commit barrier and "
                                 "grow spool live under "
                                 "<checkpoint_dir>/.reshard")

    # Collective watchdog: armed only around the loop's BLOCKING windows
    # (warm round dispatch, chunk metric fetch, held-out-eval fetch,
    # collective checkpoint save) — the FIRST dispatch at each chunk
    # width is excluded, so compile time never counts against the
    # timeout. Fires from any process (non-io processes append the
    # collective_hang event to the sink directly) and turns the hang
    # into exit 75, which the gang supervisor answers with a gang
    # restart. See fedtpu.resilience.distributed.
    watchdog = None
    if cfg.run.collective_timeout:
        watchdog = CollectiveWatchdog(
            cfg.run.collective_timeout, events_path=tel.events_path,
            process_index=jax.process_index(), heartbeat=heartbeat,
            restart_count=restart_count).start()
        _guard = watchdog.guard
    else:
        from contextlib import nullcontext
        _guard = lambda phase, rnd=None: nullcontext()

    # Overlap compile (fedtpu.compilation): the rounds_per_step-wide chunk
    # program builds on a background thread — from abstract avals, through
    # the serialized-executable ProgramCache when a cache dir is set — while
    # R=1 warmup rounds already train. Bitwise-identical results (R width-1
    # chunks compute exactly what one R-wide chunk computes); dispatch
    # blocks only if the executable isn't ready when it is finally needed.
    overlap_exec = None
    overlap_cache = None
    overlap_key = None
    overlap_chunk = max(1, cfg.run.rounds_per_step)
    if (cfg.run.overlap_compile and overlap_chunk > 1
            and cfg.fed.rounds > 1):
        from fedtpu.compilation import (CompileExecutor, ProgramCache,
                                        program_config_slice,
                                        program_fingerprint)
        from fedtpu.compilation.warmup import PROGRAMS_SUBDIR
        _wide_step = exp.make_step(overlap_chunk)
        _abstract = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=a.sharding),
            (state, batch))
        overlap_key = program_fingerprint(
            "round", config=program_config_slice(cfg), mesh=exp.mesh,
            args=_abstract, extra={"rounds_per_step": overlap_chunk})
        if cfg.run.compilation_cache:
            overlap_cache = ProgramCache(
                os.path.join(cfg.run.compilation_cache, PROGRAMS_SUBDIR),
                tracer=tracer, registry=registry)
        overlap_exec = CompileExecutor(tracer=tracer, registry=registry)

        def _build_wide(step=_wide_step, avals=_abstract):
            if overlap_cache is not None:
                return overlap_cache.get_or_compile(
                    overlap_key, step, *avals,
                    label=f"round[w={overlap_chunk}]").compiled
            return step.lower(*avals).compile()

        overlap_exec.submit(overlap_key, _build_wide,
                            label=f"round[w={overlap_chunk}]")

    if tel.manifest:
        manifest_extra = {"program": "run",
                          "engine": ("async" if cfg.fed.async_mode
                                     else "tp2d" if cfg.run.model_parallel > 1
                                     else "mpmd" if cfg.run.mpmd
                                     else "sync1d"),
                          # Resilience attribution: which restart of a
                          # supervised run wrote this sink, under which
                          # exact fault schedule (digest of the
                          # MATERIALIZED plan, probabilistic entries
                          # already expanded).
                          "restarts": restart_count}
        if injector is not None:
            manifest_extra["fault_plan"] = injector.plan.digest
        if overlap_key is not None:
            # Cache directory + hit/miss state for the run's main program
            # (peek: no deserialization at manifest time).
            manifest_extra["program_cache"] = {
                "key": overlap_key,
                "dir": overlap_cache.cache_dir if overlap_cache else None,
                "cached": bool(overlap_cache
                               and overlap_cache.peek(overlap_key)),
            }
        try:
            # Trace-only program audit of the canonical width-1 round:
            # schedule digest + per-round comm bytes pin WHAT this run
            # communicated (docs/analysis.md "Program audit"); no compile,
            # no donation proof here — 'fedtpu audit' carries the proofs.
            from fedtpu.analysis.program import (audit_step_summary,
                                                 engine_audit_spec)
            manifest_extra["audit"] = dict(
                audit_step_summary(exp.make_step(1), (state, batch)),
                engine=engine_audit_spec(cfg)["engine"])
            if cfg.run.mpmd:
                # Under mpmd the summary above still audits the
                # monolithic ORACLE program (the parity reference); the
                # per-sub-program contracts live in the committed
                # `fedtpu audit --engines mpmd_*` goldens.
                from fedtpu.orchestration.mpmd import AUDIT_SPECS
                manifest_extra["audit"]["audited_program"] = \
                    "monolithic_oracle"
                manifest_extra["mpmd"] = {
                    "sub_programs": sorted(AUDIT_SPECS),
                    "width": max(1, cfg.run.rounds_per_step),
                    "server_mesh_devices": 1,
                }
        except Exception as exc:
            # The audit is diagnostic metadata; a trace failure must not
            # take down the run it describes.
            manifest_extra["audit"] = {"error": str(exc)}
        try:
            # Device-time attribution (docs/observability.md): XLA's own
            # cost model for the canonical width-1 round. `fedtpu report`
            # joins these static counts with the measured chunk span
            # durations into per-round MFU / roofline rows. Trace+lower
            # only — no compile — so the manifest stays cheap and
            # deterministic.
            costs = exp.make_step(1).lower(state, batch).cost_analysis()
            if isinstance(costs, (list, tuple)):  # pre-0.5 jax: [dict]
                costs = costs[0] if costs else {}
            profile: dict = {
                "flops_per_round": float(costs.get("flops", 0.0)),
                "bytes_per_round": float(costs.get("bytes accessed", 0.0)),
                "profile_rounds": int(cfg.run.profile_rounds),
            }
            peak_env = os.environ.get("FEDTPU_PEAK_FLOPS")
            if peak_env:
                # Hardware peak for MFU denominators; benchmarks pin the
                # measured v5e figure in benchmarks/RESULTS.md.
                profile["peak_flops"] = float(peak_env)
            manifest_extra["profile"] = profile
        except Exception as exc:
            manifest_extra["profile"] = {"error": str(exc)}
        tracer.event("manifest", **build_manifest(
            cfg=cfg, mesh=exp.mesh, extra=manifest_extra))
    # Estimated exchange volume per round: every client ships one model's
    # worth of floats through the aggregation (and receives the average
    # back); int8 compression quarters the f32 payload. An estimate of the
    # logical exchange, not a wire measurement — psum's actual traffic is
    # XLA-scheduled.
    model_bytes = sum(int(np.prod(l.shape[1:]) or 1) * l.dtype.itemsize
                      for l in jax.tree.leaves(state["params"]))
    registry.gauge("exchange_bytes_per_round_est").set(
        model_bytes * cfg.shard.num_clients
        // (4 if cfg.fed.compress == "int8" else 1))

    if multiproc:
        from fedtpu.parallel.mesh import replicated_sharding
        from fedtpu.utils.trees import identity
        # Module-level `identity` (not a lambda) so repeated run_experiment
        # calls in one process hit the jit cache instead of retracing.
        _rep = jax.jit(identity,
                       out_shardings=replicated_sharding(exp.mesh))
    else:
        _rep = lambda t: t

    start_round = 0
    restored_history = None
    restored_meta = None
    if (not resume and cfg.run.checkpoint_dir and cfg.run.checkpoint_every
            and complete_steps(cfg.run.checkpoint_dir)):
        # A FRESH run into a directory already holding rounds is almost
        # always a mistake, and actively dangerous: a later --resume (or
        # crash-resume) would restore the STALE higher-numbered round
        # over this run's work, and retention would treat the stale
        # rounds as this run's newest and GC the fresh ones (review r4).
        # Deleting another run's checkpoints uninvited would be worse —
        # refuse with the two honest options instead.
        raise ValueError(
            f"checkpoint dir {cfg.run.checkpoint_dir!r} already holds "
            f"round checkpoints (latest: "
            f"{complete_steps(cfg.run.checkpoint_dir)[-1]}). Pass "
            "resume=True (--resume) to continue that run, or point "
            "checkpoint_dir at a clean directory.")
    if resume and cfg.run.checkpoint_dir:
        from fedtpu.orchestration.checkpoint import (
            latest_step, load_checkpoint_fallback, load_checkpoint_raw,
            load_meta, saved_num_clients)
        agreed_step = None
        local_latest = latest_step(cfg.run.checkpoint_dir)
        if multiproc:
            # Cross-host checkpoint agreement: a worker that died mid-save
            # (or a filesystem syncing unevenly) can leave processes seeing
            # DIFFERENT latest complete rounds — restoring each process's
            # own latest would silently desync the gang. Exchange the
            # locally-visible latest step and restore the minimum common
            # one; when any process sees none, ALL start fresh together.
            from fedtpu.resilience.distributed import (ENV_LAUNCH_ID,
                                                       NO_CHECKPOINT,
                                                       agree_resume_step)
            launch_id = os.environ.get(ENV_LAUNCH_ID) or None
            if launch_id is None:
                # Manual multi-host launch (no gang parent): the
                # generation tag must still be launch-unique, or a
                # leftover .agreement file from a previous launch —
                # which also had FEDTPU_RESTARTS == 0 — could hand one
                # process a stale step while a peer reads the fresh
                # one: the split-brain restore the agreement exists to
                # prevent. Process 0's nonce, broadcast once, is the
                # gang-wide launch identity.
                from jax.experimental import multihost_utils
                nonce = np.frombuffer(os.urandom(4), np.uint32)[0]
                with _guard("resume_agreement"):
                    shared = multihost_utils.broadcast_one_to_all(
                        np.asarray(nonce, np.uint32))
                launch_id = f"bcast:{int(shared):08x}"
            agreed_step = agree_resume_step(
                cfg.run.checkpoint_dir, jax.process_index(),
                jax.process_count(), local_latest,
                restart_count=restart_count, launch_id=launch_id)
            if agreed_step == NO_CHECKPOINT:
                log.info("Resume agreement: no complete checkpoint common "
                         "to the whole gang; starting fresh consensually.")
                agreed_step = None
                local_latest = None
            elif agreed_step != local_latest:
                log.info(f"Resume agreement: restoring round {agreed_step}"
                         f" (local latest: {local_latest}) — the newest "
                         "step every process can see.")
        if local_latest is not None:
            # ONE meta read serves elastic detection AND the DP RDP-curve
            # restore below; only a count MISMATCH (or a pre-num_clients
            # checkpoint) pays the raw state read.
            restored_meta = load_meta(cfg.run.checkpoint_dir,
                                      step=agreed_step)
            # Engine kind gate FIRST, from the meta item alone: a
            # cross-engine resume at the SAME client count used to sail
            # past the count comparison into the template restore, where
            # orbax killed it with an opaque tree-structure diff. The
            # saved flag (engine_async, written by save_checkpoint) names
            # the real problem before any state is read; checkpoints from
            # before the flag existed fall through to the structural
            # check in the elastic path below.
            saved_async = restored_meta.get("engine_async")
            if saved_async is not None:
                saved_async = bool(int(np.asarray(saved_async)))
                if saved_async != ("anchors" in state):
                    raise ValueError(
                        "resume engine mismatch: the checkpoint was "
                        f"written by the "
                        f"{'async' if saved_async else 'synchronous'} "
                        "engine but the current config selects the "
                        "other; resume with the matching engine, or "
                        "warm-start a fresh run from exported weights")
            nc = restored_meta.get("num_clients")
            saved_c = None if nc is None else int(np.asarray(nc))
            if saved_c is None:
                raw, raw_history, raw_round = load_checkpoint_raw(
                    cfg.run.checkpoint_dir, step=agreed_step)
                saved_c = saved_num_clients(raw)
            elif saved_c != cfg.shard.num_clients:
                raw, raw_history, raw_round = load_checkpoint_raw(
                    cfg.run.checkpoint_dir, step=agreed_step)
            if saved_c == cfg.shard.num_clients:
                # Per-leaf shardings come from the live state template, so
                # the 2-D engine's tensor-parallel layout survives resume.
                # Fallback restore: corrupt-on-disk rounds pass the commit
                # check but fail to load — walk back to the newest round
                # that actually restores instead of stranding the run.
                state, restored_history, start_round = \
                    load_checkpoint_fallback(cfg.run.checkpoint_dir,
                                             state_like=state,
                                             max_step=agreed_step)
                if start_round != int(np.asarray(restored_meta["step"])):
                    # The ledger (DP RDP curve) must come from the round
                    # actually restored, not the corrupt latest.
                    restored_meta = load_meta(cfg.run.checkpoint_dir,
                                              step=start_round)
                log.info(f"Resumed from checkpoint at round {start_round}.")
            else:
                from fedtpu.parallel.multihost import safe_put
                if ("anchors" in state) != ("anchors" in raw):
                    # Engine mismatch either way: async state is NOT
                    # post-averaging (slots hold distinct local models),
                    # so a sync resume of an async checkpoint would
                    # mean-collapse models nobody trained, and an async
                    # resume of a sync checkpoint has no pull/anchor
                    # history to restore.
                    raise ValueError(
                        "elastic resume engine mismatch: the checkpoint "
                        f"was written by the "
                        f"{'async' if 'anchors' in raw else 'synchronous'}"
                        " engine but the current config selects the other"
                        "; resume with the matching engine (and client "
                        f"count {saved_c}), or warm-start a fresh run "
                        "from exported weights")
                if "anchors" in state:
                    # ASYNC elastic resume: a restart IS every client
                    # re-pulling the current global — which lives in the
                    # FRESHEST anchor, not a mean over slots (slots hold
                    # distinct per-client local models). New cohort:
                    # params = anchors = that global, pull ticks at the
                    # resume tick (staleness restarts at 0), fresh Adam
                    # moments, and any PENDING K-buffer contributions are
                    # dropped (their deltas reference anchors of a cohort
                    # that no longer exists) — said out loud below.
                    from fedtpu.parallel.async_fed import \
                        async_global_params
                    # The engine's own freshest-anchor rule (ONE
                    # definition); works on the raw numpy tree at the
                    # saved client count.
                    g = jax.tree.map(np.asarray, async_global_params(raw))
                    state["params"] = _bcast_into_slots(g, state["params"])
                    state["anchors"] = _bcast_into_slots(g,
                                                         state["anchors"])
                    state["pull_tick"] = safe_put(
                        np.full(cfg.shard.num_clients, raw_round, np.int32),
                        state["pull_tick"].sharding)
                    state["round"] = jnp.asarray(raw_round, jnp.int32)
                    dropped = float(np.asarray(raw.get("buf_count", 0.0)))
                    restored_history, start_round = raw_history, raw_round
                    buf_note = (f", {int(dropped)} pending buffered "
                                "updates dropped" if dropped > 0 else "")
                    log.info(f"Async elastic resume at tick {raw_round}: "
                             f"{saved_c} -> {cfg.shard.num_clients} "
                             "clients (freshest-anchor global carried "
                             "over, every client re-pulled, fresh "
                             f"optimizer state{buf_note}).")
                else:
                    # SYNC ELASTIC resume — the cluster grew or shrank
                    # (the reference cannot do this at all: client count
                    # is baked into `mpirun -np N`). Periodic checkpoints
                    # hold a post-averaging state, so every client slot is
                    # the same global model: collapse to the global (mean
                    # over slots == slot 0), re-broadcast over the NEW
                    # client count, and restore the client-count-
                    # independent server-optimizer state as-is. Per-client
                    # Adam moments cannot be re-shaped meaningfully across
                    # counts — they restart fresh (the same state a client
                    # joining a federation starts with).
                    g = jax.tree.map(lambda a: np.asarray(a).mean(axis=0),
                                     raw["params"])
                    state["params"] = _bcast_into_slots(g, state["params"])
                    if ("server_opt_state" in raw
                            and "server_opt_state" in state):
                        state["server_opt_state"] = jax.tree.map(
                            lambda live, rawv: safe_put(
                                np.asarray(rawv), live.sharding),
                            state["server_opt_state"],
                            raw["server_opt_state"])
                    if "dp_clip" in raw and "dp_clip" in state:
                        # The adaptive clip is client-count-independent
                        # server state — carry it like the server
                        # optimizer state.
                        state["dp_clip"] = safe_put(
                            np.asarray(raw["dp_clip"]),
                            state["dp_clip"].sharding)
                    state["round"] = jnp.asarray(raw_round, jnp.int32)
                    restored_history, start_round = raw_history, raw_round
                    # Per-client SCAFFOLD variates are client-count-
                    # shaped like the Adam moments: an elastic resume
                    # restarts them at zero (invariant-consistent; the
                    # correction re-warms over the next rounds) — say
                    # so, or a drift study across a resume sees an
                    # unexplained regression.
                    cv_note = (", control variates reset to zero"
                               if "client_cv" in state else "")
                    log.info(f"Elastic resume at round {raw_round}: "
                             f"{saved_num_clients(raw)} -> "
                             f"{cfg.shard.num_clients} clients (global "
                             "model carried over, fresh client optimizer "
                             f"state{cv_note}).")
        if multiproc:
            # The agreement bounds the restore step, but the restore
            # itself is per-process: load_checkpoint_fallback walks back
            # past rounds that fail to LOAD locally, so an agreed step
            # that is unreadable (or not yet synced) on one host leaves
            # that host on an OLDER round than its peers — the desync
            # the agreement exists to rule out. Cross-check the round
            # each process ACTUALLY restored and fail loudly on
            # mismatch: the gang supervisor turns the crash into a
            # clean gang restart, whereas proceeding would silently
            # corrupt the federation.
            from jax.experimental import multihost_utils
            with _guard("resume_verify"):
                gang_rounds = np.asarray(multihost_utils.process_allgather(
                    np.asarray(start_round, np.int32)))
            if int(gang_rounds.min()) != int(gang_rounds.max()):
                raise RuntimeError(
                    "post-restore desync: the gang restored different "
                    f"rounds {gang_rounds.tolist()} (agreed step: "
                    f"{agreed_step}) — the agreed checkpoint loaded on "
                    "some hosts but not others; refusing to train "
                    "desynced")

    if restored_history is not None:
        tracer.event("resume", round=start_round)

    # DP RDP bookkeeping lives in its own module (fedtpu.orchestration.
    # privacy): the cumulative per-order RDP curve is the resumable
    # currency of the privacy spend, persisted in every checkpoint's meta
    # item UNCONDITIONALLY (a zero curve while DP is off) so a DP-off
    # resume segment carries the earlier segments' spend forward.
    ledger = PrivacyLedger(cfg.fed, start_round=start_round,
                           restored_meta=restored_meta)

    history = {k: [] for k in METRIC_NAMES}
    pooled_hist = {k: [] for k in METRIC_NAMES}
    per_client_hist = {k: [] for k in METRIC_NAMES}
    test_hist = {k: [] for k in METRIC_NAMES}
    staleness_hist: List[np.ndarray] = []
    losses: List[np.ndarray] = []
    sec_per_round: List[float] = []
    timer = Timer().start()

    prev_metric = None
    termination_count = cfg.fed.termination_patience
    stopped_early = False
    diverged = False
    rounds_run = 0

    def state_poisoned() -> bool:
        """The full poisoned-state predicate shared by the in-loop and
        loop-exit gates: any non-finite leaf in params, client optimizer
        moments, or server optimizer state. Reads the CURRENT ``state``
        binding (one definition — the two gates can't drift apart)."""
        return not bool(_tree_finite(
            {k: state[k] for k in
             ("params", "opt_state", "server_opt_state",
              "client_cv", "server_cv", "dp_clip", "anchors")
             if k in state}))

    def halt_diverged(reason: str, label_round: int):
        """Shared divergence halt: quarantine the poisoned state under
        diverged/ (so latest_step() — and therefore resume — still finds the
        last GOOD periodic checkpoint) and stop the loop. ``label_round`` is
        the round the CURRENT ``state`` corresponds to — under chunking the
        chunk-end state; in pipelined mode possibly one chunk past the
        divergent metrics (callers pass ``state_round``), so the quarantine
        label always matches the saved state even when the history ends at
        the earlier divergent round."""
        nonlocal stopped_early, diverged
        log.warning(f"Non-finite {reason}; halting (diverged run).")
        tracer.event("diverged", round=label_round, reason=reason)
        if cfg.run.checkpoint_dir:
            # All processes reach here together (the decision derives from
            # replicated metrics/state) and all must call the save — orbax
            # barriers internally (see save_checkpoint).
            save_checkpoint(
                os.path.join(cfg.run.checkpoint_dir, "diverged"),
                state, history, label_round,
                extra_meta=ledger.checkpoint_meta(label_round),
                process_group=ckpt_group)
        stopped_early = True
        diverged = True

    # --- Divergence rollback (cfg.run.on_divergence == 'rollback') ----
    # The retry budget is per RUN (not per incident): a run that keeps
    # diverging must eventually halt, and a single monotone counter is
    # the property the supervisor/report can reason about.
    rollback = {"attempts": 0, "resume_at": None}
    excluded: set = set()

    def _offending_clients(m, loss_row) -> tuple:
        """Clients with a non-finite loss or per-client metric this
        round — the rollback_exclude candidates."""
        bad = ~np.isfinite(np.asarray(loss_row))
        for k in METRIC_NAMES:
            bad = bad | ~np.isfinite(np.asarray(m["per_client"][k]))
        return tuple(int(c) for c in np.nonzero(bad)[0])

    def try_rollback(reason: str, label_round: int, offenders=()) -> bool:
        """Restore the newest loadable checkpoint, truncate every history
        to it, optionally exclude the offending clients, and tell the
        loop to re-enter at the restored round. Returns False — caller
        halts as before — when the policy is off, the retry budget is
        spent, or nothing restores. The first retry is a PURE replay
        (transient faults recover bitwise — round-keyed randomness makes
        the replayed rounds identical); from the second on, params are
        perturbed by rollback_perturb to move off a deterministic
        re-divergence."""
        nonlocal state, prev_metric, termination_count, rounds_run
        if cfg.run.on_divergence != "rollback":
            return False
        if rollback["attempts"] >= cfg.run.rollback_retries:
            log.warning("Rollback budget exhausted "
                        f"({cfg.run.rollback_retries}); halting.")
            return False
        from fedtpu.orchestration.checkpoint import load_checkpoint_fallback
        try:
            state2, hist2, j = load_checkpoint_fallback(
                cfg.run.checkpoint_dir, state_like=state)
        except FileNotFoundError:
            return False
        rollback["attempts"] += 1
        state = state2
        # The divergent rounds' entries were appended BEFORE the guard
        # fired: the client-mean history comes back from the checkpoint
        # (authoritative through round j); the in-memory-only histories
        # drop exactly the rounds past j they hold.
        drop = max(0, rounds_run - j)
        for k in METRIC_NAMES:
            history[k] = list(hist2.get(k, []))
            _drop_tail(pooled_hist[k], drop)
            _drop_tail(per_client_hist[k], drop)
        _drop_tail(losses, drop)
        _drop_tail(sec_per_round, drop)
        _drop_tail(staleness_hist, drop)
        if cfg.run.eval_test_every:
            edrop = sum(1 for rr in range(j + 1, rounds_run + 1)
                        if rr % cfg.run.eval_test_every == 0)
            for k in METRIC_NAMES:
                _drop_tail(test_hist[k], edrop)
        rounds_run = j
        prev_metric = ([history[k][-1] for k in METRIC_NAMES]
                       if history[METRIC_NAMES[0]] else None)
        termination_count = cfg.fed.termination_patience
        if cfg.run.rollback_exclude and offenders:
            fresh = sorted(set(offenders) - excluded)
            if fresh:
                excluded.update(fresh)
                from fedtpu.resilience.faults import drop_clients
                batch["mask"] = drop_clients(batch["mask"], fresh)
                if injector is not None:
                    # A departed client cannot re-inject: drop its
                    # still-armed faults, or a sticky NaN source would
                    # defeat the retry (NaN*0 still poisons a psum).
                    injector.exclude(fresh)
                tracer.event("exclusion", round=j, clients=list(fresh))
                registry.counter("clients_excluded").inc(len(fresh))
                log.warning(f"Excluding diverging client(s) {fresh} from "
                            "aggregation (mask weight 0) for the retry.")
        if rollback["attempts"] >= 2 and cfg.run.rollback_perturb > 0:
            from fedtpu.resilience.faults import perturb_params
            state["params"] = perturb_params(state["params"],
                                             rollback["attempts"],
                                             cfg.run.rollback_perturb)
        tracer.event("rollback", round=label_round, restored_round=j,
                     attempt=rollback["attempts"], reason=reason,
                     excluded=sorted(excluded))
        registry.counter("rollbacks").inc()
        log.warning(f"Non-finite {reason}; rolled back to round {j} "
                    f"(attempt {rollback['attempts']}/"
                    f"{cfg.run.rollback_retries}).")
        timer.lap()        # restore time must not pollute sec/round
        rollback["resume_at"] = j
        return True

    if restored_history is not None:
        for k in METRIC_NAMES:
            history[k] = list(restored_history.get(k, []))
        if history[METRIC_NAMES[0]]:
            prev_metric = [history[k][-1] for k in METRIC_NAMES]
        rounds_run = start_round

    # Checkpoint retention (RunConfig.keep_checkpoints > 0): after every
    # periodic save, keep only the k newest complete rounds plus the
    # best-client-mean-accuracy round. ``best_saved`` tracks (accuracy,
    # step) over the checkpoints THIS run wrote; on resume it re-seeds
    # from the rounds still on disk and the restored history, so a
    # resumed run never GCs a better pre-resume round. Derived from
    # replicated metrics, so it is identical on every process; only
    # io_proc deletes (orbax save has barriered by then, so every round
    # being deleted is fully committed).
    best_saved = None
    if (cfg.run.keep_checkpoints > 0 and cfg.run.checkpoint_dir
            and restored_history is not None):
        acc_hist = history["accuracy"]
        for s in complete_steps(cfg.run.checkpoint_dir):
            if 0 < s <= len(acc_hist) and (best_saved is None
                                           or acc_hist[s - 1] > best_saved[0]):
                best_saved = (acc_hist[s - 1], s)

    def retain_after_save(step: int) -> None:
        nonlocal best_saved
        if cfg.run.keep_checkpoints <= 0:
            return
        acc = history["accuracy"][-1] if history["accuracy"] else -math.inf
        if best_saved is None or acc > best_saved[0]:
            best_saved = (acc, step)
        if io_proc:
            retain_checkpoints(cfg.run.checkpoint_dir,
                               cfg.run.keep_checkpoints,
                               protect=(best_saved[1],))

    if (cfg.run.on_divergence == "rollback"
            and not complete_steps(cfg.run.checkpoint_dir)):
        # Rollback's worst case — divergence before the first periodic
        # save — still needs a restore point: persist the initial state
        # as round `start_round` (0 for a fresh run). Collective: every
        # process calls (the condition is deterministic).
        save_checkpoint(cfg.run.checkpoint_dir, state, history, start_round,
                        extra_meta=ledger.checkpoint_meta(start_round))

    ckpt_every = cfg.run.checkpoint_every
    chunk = max(1, cfg.run.rounds_per_step)
    step_fns: Dict[int, Callable] = {}

    # MPMD sub-program cache: same directory layout as overlap_compile's
    # (the <cache>/programs store), so a warmed cache serves both paths.
    mpmd_cache = None
    if cfg.run.mpmd and cfg.run.compilation_cache:
        from fedtpu.compilation import ProgramCache
        from fedtpu.compilation.warmup import PROGRAMS_SUBDIR
        mpmd_cache = ProgramCache(
            os.path.join(cfg.run.compilation_cache, PROGRAMS_SUBDIR),
            tracer=tracer, registry=registry)

    def get_step(r: int) -> Callable:
        if r not in step_fns:
            if cfg.run.mpmd:
                # The DAG of AOT sub-programs; compiles (or loads from
                # the cache) every sub-program at this width up front.
                from fedtpu.orchestration.mpmd import build_mpmd_step
                step_fns[r] = build_mpmd_step(
                    cfg, mesh=exp.mesh, apply_fn=exp.apply_fn, tx=exp.tx,
                    num_classes=exp.num_classes, state=state, batch=batch,
                    width=r, cache=mpmd_cache, tracer=tracer)
            else:
                step_fns[r] = exp.make_step(r)
        return step_fns[r]

    jsonl = (open(cfg.run.metrics_jsonl, "a")
             if cfg.run.metrics_jsonl and io_proc else None)
    # Windowed device profiling (--profile-rounds K, K > 0): the
    # jax.profiler capture is deferred until the FIRST chunk's metrics
    # land on host — compile and warmup never pollute the window — and
    # stops once K steady-state rounds are covered (chunk granularity:
    # the window closes at the first chunk boundary at or past K).
    # K == 0 keeps the historical whole-run trace.
    prof_win = {"on": False, "start_round": 0,
                "pending": bool(cfg.run.profile_dir
                                and cfg.run.profile_rounds > 0)}
    if cfg.run.profile_dir and cfg.run.profile_rounds <= 0:
        # Tracing subsystem the reference lacks entirely (SURVEY.md §5):
        # capture a device profile of the round loop for xprof/tensorboard.
        jax.profiler.start_trace(cfg.run.profile_dir)
        prof_win["on"] = True

    # try/finally so a mid-run failure (OOM, Ctrl-C, I/O error) still
    # finalizes the profiler trace and closes the jsonl handle — the trace
    # exists precisely to diagnose such runs.
    try:
        def process_chunk(rnd0, take, metrics, state_round=None):
            """Host-side consumption of one chunk's metrics: history, logs,
            JSONL, divergence guard, early stopping. Fetches the metrics —
            the completion proof AND (in pipelined mode) the point where
            the host finally waits on this chunk. ``state_round``: the round
            the loop's CURRENT ``state`` corresponds to (in pipelined mode
            one chunk past this chunk's metrics) — used to label a
            divergence quarantine honestly."""
            if state_round is None:
                state_round = rnd0 + take
            nonlocal prev_metric, termination_count, stopped_early
            nonlocal rounds_run
            # ONE batched device->host transfer for the whole chunk's
            # metrics: the per-round float()/np.asarray conversions below
            # would otherwise each pay a serialized transfer round-trip
            # (~13 per round; measured ~1.5 s/round through the tunneled
            # transport vs ~20 ms for the batched fetch). Issue every
            # leaf's transfer async first, then materialize — which is
            # also the completion proof that must close the lap time
            # (block_until_ready does not synchronize on this transport).
            # Multi-process: replicate first (collective, every process) so
            # the client-sharded leaves become host-addressable everywhere.
            with _guard("chunk_fetch", rnd0 + take):
                metrics = _rep(metrics)
                for leaf in jax.tree.leaves(metrics):
                    if hasattr(leaf, "copy_to_host_async"):
                        leaf.copy_to_host_async()
                metrics = jax.tree.map(np.asarray, metrics)
            per_round = _unstack_metrics(metrics, take)
            dt = timer.lap() / take
            # The chunk span closes HERE, on the np.asarray materialization
            # above — the fetch-forced-completion rule (block_until_ready
            # does not synchronize on the axon transport; the host fetch is
            # the proof the chunk's device work finished).
            tracer.event("span", phase="chunk", round=rnd0 + take,
                         dur_s=dt * take, rounds=take)
            # Windowed profiler control: arm after the first chunk's fetch
            # (the completion proof that compile is behind us), disarm at
            # the first chunk boundary covering >= profile_rounds rounds —
            # the fetch above already proved the window's device work
            # finished, so stop_trace here loses nothing.
            if prof_win["pending"]:
                prof_win["pending"] = False
                prof_win["on"] = True
                prof_win["start_round"] = rnd0 + take
                jax.profiler.start_trace(cfg.run.profile_dir)
                tracer.event("profile_window", phase="start",
                             round=rnd0 + take,
                             rounds=int(cfg.run.profile_rounds))
            elif (prof_win["on"] and cfg.run.profile_rounds > 0
                    and rnd0 + take - prof_win["start_round"]
                    >= cfg.run.profile_rounds):
                jax.profiler.stop_trace()
                prof_win["on"] = False
                tracer.event("profile_window", phase="stop",
                             round=rnd0 + take,
                             rounds=rnd0 + take - prof_win["start_round"])
            # Host-side decision window (history/log/early-stop); ended at
            # every exit of the loop below — Span.end is idempotent.
            sp_stop = tracer.span("stop_check", round=rnd0 + take)

            for j, m in enumerate(per_round):
                r = rnd0 + j
                client_mean = {k: float(v) for k, v in m["client_mean"].items()}
                per_client = {k: np.asarray(v) for k, v in m["per_client"].items()}
                losses.append(np.asarray(m["loss"]))
                sec_per_round.append(dt)
                rounds_run = r + 1
                loss_mean = float(np.mean(losses[-1]))

                for k in METRIC_NAMES:
                    history[k].append(client_mean[k])
                    pooled_hist[k].append(float(m["pooled"][k]))
                    per_client_hist[k].append(per_client[k])
                if "staleness" in m:        # async engine's extra metric
                    staleness_hist.append(np.asarray(m["staleness"]))

                registry.counter("rounds").inc()
                tracer.event("round", round=r + 1, dur_s=dt,
                             accuracy=client_mean["accuracy"],
                             loss_mean=loss_mean,
                             **({"staleness_mean":
                                 float(staleness_hist[-1].mean()),
                                 "staleness_max":
                                 float(staleness_hist[-1].max())}
                                if "staleness" in m else {}))
                if "staleness" in m:
                    from fedtpu.parallel.async_fed import \
                        record_tick_telemetry
                    record_tick_telemetry(registry, tracer, r + 1,
                                          staleness_hist[-1])

                if jsonl is not None:
                    jsonl.write(json.dumps({
                        "round": r + 1, "sec_per_round": dt,
                        "client_mean": client_mean,
                        "pooled": {k: pooled_hist[k][-1] for k in METRIC_NAMES},
                        "loss_mean": loss_mean,
                        **({"staleness_mean":
                            float(staleness_hist[-1].mean())}
                           if "staleness" in m else {}),
                    }) + "\n")
                    jsonl.flush()

                if verbose and (r % cfg.run.log_every == 0):
                    log.parity(f"\nRound {r + 1}:\n")
                    if cfg.run.log_per_client:
                        # Parity with the barrier-serialized rank-ordered prints
                        # (FL_CustomMLP...:151-162) — here just a loop, no barriers.
                        for c in range(cfg.shard.num_clients):
                            vals = ", ".join(f"{k}: {per_client[k][c]:.4f}"
                                             for k in METRIC_NAMES)
                            log.parity(f"  CLIENT {c} - Local Metrics "
                                       f"(Round {r + 1}): [{vals}]")
                    gvals = ", ".join(f"{k}: {client_mean[k]:.4f}"
                                      for k in METRIC_NAMES)
                    stale_note = (f"  (mean staleness "
                                  f"{staleness_hist[-1].mean():.2f})"
                                  if "staleness" in m else "")
                    # parity, not info: the line is reference-shaped and
                    # must never grow a prefix; its timing suffix is what
                    # keeps it out of the byte-identity tests.
                    log.parity(f"  Global Metrics (Round {r + 1}): [{gvals}]  "
                               f"({dt * 1e3:.1f} ms/round){stale_note}")

                # Failure detection: a diverged step (NaN/inf loss or
                # metrics) halts cleanly instead of burning the remaining
                # rounds — with an emergency checkpoint of the last state.
                cur = [client_mean[k] for k in METRIC_NAMES]
                if cfg.run.halt_on_nonfinite and not (
                        np.all(np.isfinite(cur))
                        and np.all(np.isfinite(losses[-1]))):
                    # Rollback policy first (restores + truncates + sets
                    # resume_at; the while loop re-enters at the restored
                    # round); only when it declines does the run halt.
                    if not try_rollback(
                            f"loss/metrics at round {r + 1}", r + 1,
                            offenders=_offending_clients(m, losses[-1])):
                        halt_diverged(f"loss/metrics at round {r + 1}",
                                      state_round)
                    sp_stop.end()
                    return

                # Early stopping — exact reference logic (FL_CustomMLP...:181-192).
                if prev_metric is not None and np.allclose(
                        cur, prev_metric, atol=cfg.fed.tolerance):
                    termination_count -= 1
                    if termination_count == 0:
                        log.parity("Early stopping triggered: No significant "
                                   "change in metrics for "
                                   f"{cfg.fed.termination_patience} rounds.")
                        if r + 1 < cfg.fed.rounds:
                            # The reference's break-iteration message
                            # (FL_CustomMLP...:135): its loop re-enters
                            # round r+1 (0-indexed == this r+1) and
                            # breaks before training; printed only when
                            # there IS a next round to break out of.
                            log.parity(f"Training stopped early at round "
                                       f"{r + 1}.")
                        tracer.event("early_stop", round=r + 1)
                        stopped_early = True
                        sp_stop.end()
                        return
                else:
                    prev_metric = cur
                    termination_count = cfg.fed.termination_patience
            sp_stop.end()

        # ---- Elastic live reshard (docs/resilience.md) ----------------
        def _reshard_join_fn(join_map, tick_round):
            """join_rows callback for reshard_state: global-model rows for
            params/anchors, the current round for pull_tick, zeros (fresh
            optimizer moments / control variates) for everything else —
            the same joiner semantics as elastic resume."""
            def jr(path, jidx, row_shape, dtype):
                if path in join_map:
                    v = np.asarray(join_map[path])
                    return np.broadcast_to(
                        v, (len(jidx),) + tuple(row_shape)).astype(dtype)
                if path == "['pull_tick']":
                    return np.full((len(jidx),) + tuple(row_shape),
                                   tick_round, dtype=dtype)
                return np.zeros((len(jidx),) + tuple(row_shape), dtype=dtype)
            return jr

        def _global_join_map():
            """Join values from the CURRENT global model: state paths under
            ['params'] and (async) ['anchors'] both join at the live
            global — a joining client starts from the freshest model, like
            an elastic-resume joiner."""
            g = to_numpy(_rep(exp.global_fn(state)))
            jm = {}
            for keys, leaf in jax.tree_util.tree_flatten_with_path(g)[0]:
                sub = jax.tree_util.keystr(keys)
                jm[f"['params']{sub}"] = np.asarray(leaf)
                if "anchors" in state:
                    jm[f"['anchors']{sub}"] = np.asarray(leaf)
            return jm

        def _victim_grow(rec):
            """The parked member's rejoin: rebuild its full-topology state
            from the survivors' spool (replicated values + join rows over
            its stale structure), re-sync the host-side control state
            (history, early-stop comparator, DP ledger), and continue at
            the grow round — its compiled executables and batch still
            target the original mesh, so nothing recompiles."""
            nonlocal state, prev_metric, termination_count, rounds_run
            nonlocal ledger
            from fedtpu.parallel.reshard import grow_row_map, reshard_state
            ctl = reshard_ctl
            seq = ctl.seq        # advanced past the shrink by committed()
            r_grow = int(rec["round"])
            src_C = int(rec["src_clients"])
            orig_C = cfg.shard.num_clients
            join_map, repl, control = ctl.read_spool(seq)
            ctl.event("reshard_begin", r_grow, mode="grow_rejoin",
                      victim=ctl.process_index, target=orig_C)
            _beat("resharding", r_grow)
            ctl.publish_ack(seq, "a", r_grow)
            participants = tuple(sorted(set(ctl.active)
                                        | {ctl.process_index}))
            ctl.await_acks(seq, "a", participants)
            new_state, steps = reshard_state(
                state, dst_mesh=exp.mesh, dst_clients=orig_C,
                row_map=grow_row_map(src_C, orig_C,
                                     int(rec["block_start"])),
                join_rows=_reshard_join_fn(join_map, r_grow),
                replicated_values=repl)
            ctl.publish_ack(seq, "b", r_grow)
            ctl.await_acks(seq, "b", participants)
            state = new_state
            ctl.committed("grow", ctl.process_index)
            for k in METRIC_NAMES:
                if control.get("history", {}).get(k) is not None:
                    history[k] = list(control["history"][k])
            prev_metric = control.get("prev_metric")
            termination_count = int(control.get(
                "termination_count", cfg.fed.termination_patience))
            if control.get("ledger"):
                ledger = PrivacyLedger(
                    cfg.fed, start_round=r_grow,
                    restored_meta={k: np.asarray(v) for k, v in
                                   control["ledger"].items()})
            rounds_run = r_grow
            ctl.event("reshard_done", r_grow, mode="grow_rejoin",
                      steps=[s.to_json() for s in steps])
            _beat("running", r_grow)
            return r_grow

        def _do_reshard(req, rnd):
            """Execute one agreed reshard at loop-top ``rnd``: move the
            live state onto the new mesh with the wire-free planner,
            rebuild (shrink) or restore (grow) the round executables, and
            rebind every loop-level reference — then continue at the SAME
            round, no process restart, no checkpoint restore. Returns the
            round to continue from (the parked victim returns at the grow
            round, or exits EXIT_RESHARDED at run end). A participant
            dying mid-protocol times out the commit barrier and raises
            ReshardFailed, which crashes this process into the gang
            supervisor's ordinary restart + checkpoint-resume contract."""
            nonlocal state, batch, exp, _rep, cfg, eval_step, step_fns
            nonlocal prev_metric, termination_count, ckpt_group
            from fedtpu.parallel.mesh import submesh
            from fedtpu.parallel.reshard import (grow_row_map,
                                                 host_replicated,
                                                 is_client_leaf,
                                                 reshard_state,
                                                 shrink_row_map)
            from fedtpu.resilience.reshard import ReshardFailed
            ctl = reshard_ctl
            seq = ctl.seq
            me = ctl.process_index
            try:
                if req.mode == "shrink":
                    src_C = cfg.shard.num_clients
                    src_devs = list(exp.mesh.devices.flat)
                    pd = src_C // len(src_devs)
                    target = req.target_clients
                    survivors = (me,)
                    if multiproc:
                        survivors = tuple(p for p in ctl.active
                                          if p != req.victim)
                        n_dst = sum(1 for d in src_devs
                                    if d.process_index != req.victim)
                        target = target or pd * n_dst
                        if target != pd * n_dst:
                            raise ReshardFailed(
                                f"shrink target {target} does not match "
                                f"the surviving devices ({n_dst} devices x "
                                f"{pd} clients/device)")
                    elif not target:
                        log.warning("Ignoring shrink notice: a "
                                    "single-process signal shrink needs a "
                                    "fault-plan target_clients.")
                        return rnd
                    ctl.event("reshard_begin", rnd, mode="shrink",
                              victim=req.victim, target=target)
                    _beat("resharding", rnd)
                    ctl.maybe_crash()
                    # Phase A: every PRE-reshard member is at this round's
                    # loop-top with no collective in flight. A victim that
                    # died without handing off fails this barrier -> gang
                    # restart, never a half-resharded continue.
                    ctl.publish_ack(seq, "a", rnd)
                    ctl.await_acks(seq, "a", ctl.active)
                    if multiproc and me == req.victim:
                        ctl.committed("shrink", req.victim)
                        log.info(f"Preempted member parking at round {rnd} "
                                 "(state handed off; will rejoin on grow).")
                        return _victim_grow(ctl.park(seq, rnd))
                    dst_mesh = (submesh(exp.mesh, process_indices=survivors,
                                        num_clients=target)
                                if multiproc
                                else submesh(exp.mesh, num_clients=target))
                    pos = {d.id: i for i, d in enumerate(src_devs)}
                    rows = []
                    for d in dst_mesh.devices.flat:
                        rows.extend(range(pos[d.id] * pd,
                                          (pos[d.id] + 1) * pd))
                    if rows != list(range(rows[0], rows[0] + target)):
                        raise ReshardFailed(
                            f"surviving client rows {rows} are not one "
                            "contiguous block; the wire-free plan cannot "
                            "renumber them")
                    block_start = rows[0]
                    with tracer.span("reshard_move", round=rnd):
                        new_state, steps = reshard_state(
                            state, dst_mesh=dst_mesh, dst_clients=target,
                            row_map=shrink_row_map(block_start, target))
                    # Data repack through the partition view
                    # (ShardConfig.partition_clients): shard as the
                    # ORIGINAL full population, keep the survivors'
                    # window — every kept client's packed batch (padding
                    # included) is bitwise its pre-shrink one.
                    P = cfg.shard.partition_clients or src_C
                    cfg2 = dataclasses.replace(
                        cfg, shard=dataclasses.replace(
                            cfg.shard, num_clients=target,
                            partition_clients=P,
                            partition_offset=(cfg.shard.partition_offset
                                              + block_start)))
                    reshard_stack.append({
                        "cfg": cfg, "exp": exp, "rep": _rep,
                        "eval_step": eval_step, "step_fns": step_fns,
                        "ckpt_group": ckpt_group,
                        "block_start": block_start})
                    with tracer.span("reshard_build", round=rnd):
                        exp2 = build_experiment(cfg2, ds, mesh=dst_mesh)
                    cfg, exp = cfg2, exp2
                    state, batch = new_state, exp2.batch
                    eval_step = exp2.eval_step
                    step_fns = {}
                    if multiproc:
                        from fedtpu.parallel.mesh import replicated_sharding
                        from fedtpu.utils.trees import identity
                        _rep = jax.jit(
                            identity,
                            out_shardings=replicated_sharding(dst_mesh))
                    # Phase B: every POST-reshard member holds the rebuilt
                    # state — only then does anyone dispatch on the shrunk
                    # mesh.
                    ctl.publish_ack(seq, "b", rnd)
                    ctl.await_acks(seq, "b", survivors)
                    ctl.committed("shrink", req.victim)
                    if multiproc:
                        ckpt_group = sorted(ctl.active)
                    if history[METRIC_NAMES[0]]:
                        prev_metric = [history[k][-1] for k in METRIC_NAMES]
                    termination_count = cfg.fed.termination_patience
                    ctl.event("reshard_done", rnd, mode="shrink",
                              target=target, block_start=block_start,
                              steps=[s.to_json() for s in steps])
                    log.info(f"Elastic shrink at round {rnd}: {src_C} -> "
                             f"{target} clients (block offset "
                             f"{block_start}), no restart.")
                    _beat("running", rnd)
                    return rnd

                # ---- grow ---------------------------------------------
                if not reshard_stack:
                    log.warning("Ignoring grow notice: nothing shrunk.")
                    return rnd
                st = reshard_stack[-1]
                orig_C = st["cfg"].shard.num_clients
                src_C = cfg.shard.num_clients
                ctl.event("reshard_begin", rnd, mode="grow",
                          victim=req.victim, target=orig_C)
                _beat("resharding", rnd)
                ctl.maybe_crash()
                jm = _global_join_map()
                if multiproc and me == min(ctl.active):
                    # Leader spools everything the rejoiner needs BEFORE
                    # publishing the grow record its park loop polls —
                    # record visibility implies spool completeness.
                    repl = {}
                    def _collect(keys, leaf):
                        if not is_client_leaf(leaf) and hasattr(leaf, "sharding"):
                            repl[jax.tree_util.keystr(keys)] = \
                                host_replicated(leaf)
                        return leaf
                    jax.tree_util.tree_map_with_path(_collect, state)
                    ctl.write_spool(ctl.seq, jm, repl, {
                        "round": rnd,
                        "history": {k: [float(v) for v in history[k]]
                                    for k in METRIC_NAMES},
                        "prev_metric": prev_metric,
                        "termination_count": termination_count,
                        "ledger": {k: np.asarray(v).tolist() for k, v in
                                   ledger.checkpoint_meta(rnd).items()},
                    })
                    ctl.publish_grow(ctl.seq, rnd, {
                        "src_clients": src_C,
                        "block_start": st["block_start"]})
                participants = (tuple(sorted(set(ctl.active)
                                             | {req.victim}))
                                if multiproc and req.victim >= 0
                                else ctl.active)
                ctl.publish_ack(seq, "a", rnd)
                ctl.await_acks(seq, "a", participants)
                with tracer.span("reshard_move", round=rnd):
                    new_state, steps = reshard_state(
                        state, dst_mesh=st["exp"].mesh,
                        dst_clients=orig_C,
                        row_map=grow_row_map(src_C, orig_C,
                                             st["block_start"]),
                        join_rows=_reshard_join_fn(jm, rnd))
                ctl.publish_ack(seq, "b", rnd)
                ctl.await_acks(seq, "b", participants)
                reshard_stack.pop()
                cfg, exp, _rep = st["cfg"], st["exp"], st["rep"]
                eval_step, step_fns = st["eval_step"], st["step_fns"]
                ckpt_group = st["ckpt_group"]
                state, batch = new_state, exp.batch
                ctl.committed("grow", req.victim)
                if history[METRIC_NAMES[0]]:
                    prev_metric = [history[k][-1] for k in METRIC_NAMES]
                termination_count = cfg.fed.termination_patience
                ctl.event("reshard_done", rnd, mode="grow", target=orig_C,
                          steps=[s.to_json() for s in steps])
                log.info(f"Elastic grow at round {rnd}: {src_C} -> "
                         f"{orig_C} clients, no restart, no recompile.")
                _beat("running", rnd)
                return rnd
            except ReshardFailed as e:
                ctl.event("reshard_failed", rnd, error=str(e))
                _beat("reshard_failed", rnd)
                log.warning(f"Elastic reshard failed ({e}); degrading to "
                            "the gang-restart contract.")
                raise

        # Pipelined-stop mode (cfg.run.pipelined_stop): dispatch chunk k+1
        # BEFORE processing chunk k's metrics, so the per-chunk host work
        # (metric fetch + early-stop decision — one dispatch+fetch RTT,
        # ~60-120 ms through the tunneled transport) overlaps the device
        # executing the next chunk. The trade, documented and deliberate:
        #   * stop decisions lag one chunk — when early stopping (or the
        #     metric divergence guard) fires, one already-in-flight chunk
        #     has trained past the stop; its metrics are DROPPED (history
        #     matches the synchronous run exactly) but the final state
        #     carries its training. (The reference's stop-signal bcast is
        #     also read one loop-top late — :132 vs :195 — but its doomed
        #     iteration breaks BEFORE training, so unlike this mode the
        #     reference never trains past the stop; see module docstring.)
        #   * the chunk-end STATE finiteness gate runs only at checkpoint /
        #     held-out-eval boundaries (which sync inherently) and at loop
        #     exit — fetching the in-flight state between ordinary chunks
        #     would serialize every chunk, the exact cost this mode removes;
        #     the per-round METRIC guard still runs every round, one chunk
        #     late.
        # Checkpoint / held-out-eval boundaries force their inherent sync
        # and are unchanged. Default OFF: the synchronous loop keeps exact
        # reference stop semantics.
        # run.mpmd rides the same pending machinery: the DAG dispatches
        # everything (chain, cross-slice transfer, metrics program)
        # asynchronously, and this one-chunk-in-flight schedule is what
        # overlaps chunk k's metric fetch under chunk k+1's client
        # compute — the RTT-hiding half of the MPMD win.
        pipelined = cfg.run.pipelined_stop or cfg.run.mpmd
        pending = None                      # (rnd0, take, metrics) in flight
        rnd = start_round
        while rnd < cfg.fed.rounds and not stopped_early:
            if preempt["sig"] is not None:
                # Graceful preemption drain: finish any in-flight chunk,
                # checkpoint (unless the state is poisoned — a NaN drain
                # checkpoint would resume straight back into divergence),
                # and exit through the Preempted contract (code 75, the
                # supervisor restarts with --resume).
                if pending is not None:
                    process_chunk(*pending, state_round=rnd)
                    pending = None
                if not stopped_early:
                    if not (cfg.run.halt_on_nonfinite and state_poisoned()):
                        with tracer.span("checkpoint", round=rnd), \
                                _guard("checkpoint", rnd):
                            save_checkpoint(
                                cfg.run.checkpoint_dir, state, history, rnd,
                                extra_meta=ledger.checkpoint_meta(rnd),
                                process_group=ckpt_group)
                            retain_after_save(rnd)
                    tracer.event("preempted", round=rnd)
                    registry.counter("preemptions").inc()
                    log.warning(f"SIGTERM: drained checkpoint at round "
                                f"{rnd}; exiting for resume (preempted).")
                    _beat("preempted", rnd)
                    raise Preempted(rnd)
                break
            if reshard_ctl is not None and reshard_ctl.pending:
                if not reshard_live or (multiproc
                                        and not cfg.run.checkpoint_dir):
                    # This config cannot live-reshard (validated at
                    # startup for PLAN entries, so only a SIGNAL notice
                    # reaches here): degrade it to the plain preemption
                    # drain — checkpoint + exit 75 + gang restart at the
                    # new size.
                    reshard_ctl.clear_signal()
                    if cfg.run.checkpoint_dir:
                        tracer.event("reshard_degraded", round=rnd)
                        registry.counter("reshard_degraded").inc()
                        log.warning("Preemption notice under a config that "
                                    "cannot live-reshard (rounds_per_step"
                                    ">1, pipelined_stop, or no checkpoint_"
                                    "dir); draining via the preempt path.")
                        preempt["sig"] = getattr(signal, "SIGUSR1", 10)
                        continue
                    log.warning("Ignoring preemption notice: no "
                                "checkpoint_dir to drain to and no "
                                "live-reshard support in this config.")
                else:
                    req = reshard_ctl.poll(rnd)
                    if req is not None:
                        rnd = _do_reshard(req, rnd)
                        continue
            take = min(chunk, cfg.fed.rounds - rnd)
            if injector is not None:
                # A fault round must run as its own width-1 dispatch so
                # pre/post_round bracket exactly that round.
                take = injector.chunk_limit(rnd, take)
            if (overlap_exec is not None and take == chunk
                    and chunk not in step_fns):
                if (overlap_exec.done(overlap_key)
                        or cfg.fed.rounds - rnd <= chunk):
                    # Adopt the background-built executable (an AOT
                    # ``Compiled`` is called exactly like the jit wrapper).
                    # When no warmup round can still fit, this get() is the
                    # one place dispatch blocks on compilation.
                    try:
                        step_fns[chunk] = overlap_exec.get(overlap_key)
                    except Exception:
                        # Background build failed; the eager compile path
                        # below takes over at this width.
                        registry.counter(
                            "background_compile_failures").inc()
                        overlap_exec = None
                else:
                    # Wide program still compiling: train a width-1 warmup
                    # round meanwhile (bitwise-identical math — R width-1
                    # chunks == one R-wide chunk).
                    take = 1
            if injector is not None:
                injector.pre_round(rnd, state, batch,
                                   checkpoint_dir=cfg.run.checkpoint_dir)
            if take not in step_fns:
                # First call at this chunk width: trace + lower + compile
                # happen synchronously inside the dispatch (only execution
                # is async), so the span brackets the compile cost. The
                # jax.monitoring probe (install_compile_probe) counts the
                # backend-reported compile seconds alongside.
                with tracer.span("compile", round=rnd + take, rounds=take):
                    state, metrics = get_step(take)(state, batch)
            else:
                # Guarded: on the CPU/gloo backend a dispatch whose
                # collectives wait on a dead peer blocks HERE, not at the
                # metric fetch (TPU dispatch is async, so this guard
                # window is microseconds there). The first-call branch
                # above stays unguarded — compile time must never count
                # against --collective-timeout; a hang during a first
                # dispatch is the supervisor --hang-timeout's job.
                with _guard("dispatch", rnd + take):
                    state, metrics = get_step(take)(state, batch)
            if injector is not None:
                # After dispatch (the launched chunk holds its own array
                # references): restore the pre-fault mask so every later
                # round is bitwise-identical to an unfaulted run.
                injector.post_round(rnd, batch)
            if pipelined:
                if pending is not None:
                    # The current `state` is the just-dispatched chunk's
                    # output, ending at rnd + take.
                    process_chunk(*pending, state_round=rnd + take)
                pending = (rnd, take, metrics)
            else:
                process_chunk(rnd, take, metrics)
            rnd += take

            if rollback["resume_at"] is not None:
                # A divergence rolled back mid-chunk-processing: re-enter
                # the loop at the restored round (state/history already
                # rewound by try_rollback).
                rnd = rollback["resume_at"]
                rollback["resume_at"] = None
                _beat("running", rnd)
                continue
            _beat("running", rnd)

            if stopped_early:
                # The chunk overshot the stop round; don't checkpoint or eval the
                # overshoot state (the unchunked loop's `break` skips these too).
                # In pipelined mode `pending` is the in-flight overshoot chunk:
                # dropped (see above).
                pending = None
                break

            # Held-out eval / checkpoint at chunk boundaries when due within the
            # chunk (with rounds_per_step=1 this is the exact per-round cadence).
            # Every due round appends an entry so test_hist round-alignment
            # matches the unchunked run; due rounds inside one chunk share the
            # chunk-end global params (documented approximation). In pipelined
            # mode these fetch the in-flight state — an inherent sync, paid
            # only on due boundaries; process the pending chunk first so
            # history stays ordered.
            eval_due = cfg.run.eval_test_every and sum(
                1 for j in range(take)
                if (rnd - j) % cfg.run.eval_test_every == 0)
            ckpt_due = bool(ckpt_every and cfg.run.checkpoint_dir and any(
                (rnd - j) % ckpt_every == 0 for j in range(take)))
            if pipelined and pending is not None and (eval_due or ckpt_due):
                process_chunk(*pending, state_round=rnd)
                pending = None
                if stopped_early:
                    break

            # Chunk-end state check: metrics can stay finite for one round
            # AFTER params go NaN (argmax over NaN logits yields index 0, and
            # the reported loss is computed at pre-update params), and Adam
            # moments can overflow while params are still finite — so the
            # per-round metric guard above would let a periodic checkpoint
            # capture a poisoned state as "good". Gate the checkpoint on the
            # actual full state (params + optimizer moments). In pipelined
            # mode the per-chunk check would force a sync every chunk — the
            # exact cost the mode removes — so it runs only at checkpoint /
            # held-out-eval boundaries (which already sync inherently; the
            # gate adds no extra serialization) and once at loop exit. A
            # periodic save therefore NEVER persists a poisoned state as the
            # latest good checkpoint, and held-out eval never runs on NaN
            # params, in either mode.
            if cfg.run.halt_on_nonfinite \
                    and (not pipelined or ckpt_due or eval_due) \
                    and state_poisoned():
                # Offenders unknown here (the poison shows in the full
                # state, not a per-client metric) — rollback without
                # exclusion; halt when the policy declines.
                if try_rollback(
                        f"params/optimizer state after round {rnd}", rnd):
                    rnd = rollback["resume_at"]
                    rollback["resume_at"] = None
                    continue
                halt_diverged(f"params/optimizer state after round {rnd}",
                              rnd)
                break

            if eval_due:
                # _rep: the global slice of a client-sharded array is not
                # host-addressable from every process; replicated params
                # also make the eval jit's output fetchable everywhere.
                sp = tracer.span("eval", round=rnd)
                with _guard("eval_fetch", rnd):
                    tm = eval_step(_rep(exp.global_fn(state)),
                                   ds.x_test, ds.y_test)
                    # Span closes on the host fetch of the eval metrics —
                    # the fetch-forced-completion rule again.
                    sp.end_after_fetch(tm)
                registry.counter("held_out_evals").inc()
                for _ in range(eval_due):
                    for k in METRIC_NAMES:
                        test_hist[k].append(float(tm[k]))

            # Checkpoint label semantics under chunking: a checkpoint due
            # mid-chunk is saved once at the chunk boundary, labeled with —
            # and containing — the CHUNK-END round `rnd` (states interior to
            # a scanned chunk never exist on the host). With rounds_per_step
            # R and checkpoint_every not a multiple of R, on-disk
            # `round_NNNN` labels therefore land on chunk ends rather than
            # on the exact due rounds; resume is consistent (label == state
            # == resume point), just coarser than the R=1 cadence.
            if ckpt_due:
                # EVERY process calls this: orbax save is itself a
                # collective (barriers internally — a process-0-only call
                # deadlocks), and it writes each client shard from the
                # process that owns it (true distributed checkpointing).
                with tracer.span("checkpoint", round=rnd), \
                        _guard("checkpoint", rnd):
                    save_checkpoint(cfg.run.checkpoint_dir, state, history,
                                    rnd,
                                    extra_meta=ledger.checkpoint_meta(rnd),
                                    process_group=ckpt_group)
                    retain_after_save(rnd)

        if pending is not None and not stopped_early:
            process_chunk(*pending, state_round=rnd)
        if (pipelined or stopped_early) and not diverged \
                and cfg.run.halt_on_nonfinite and state_poisoned():
            # The deferred state gate (see above) — in pipelined mode the
            # only between-boundary state check; in sync mode only after an
            # early-stop break, the one path the in-loop gate misses (its
            # final chunk may poison the state while pre-update metrics
            # stay finite). A healthy sync completion skips it: the in-loop
            # gate already checked the final chunk, and the re-check would
            # cost a redundant fetch RTT. Label with `rnd` — the
            # round the CURRENT state corresponds to — not rounds_run: after
            # an early stop the state carries the overshoot chunk's training
            # (up to one chunk past rounds_run), and halt_diverged's
            # contract is label == saved state.
            halt_diverged(f"params/optimizer state after round {rnd}", rnd)
        if reshard_ctl is not None:
            # Release any still-parked member: the run is over, and it
            # must exit EXIT_RESHARDED (76, a non-failure departure to the
            # gang supervisor) rather than wait for a grow that will
            # never come. Reached only on clean completion — on a crash
            # the supervisor's gang teardown collects the parked member.
            reshard_ctl.finish()

    finally:
        if watchdog is not None:
            # Post-loop fetches (final params, personalization) run
            # unguarded — a healthy completion reached them, and the
            # watchdog must never fire on epilogue work it can't see.
            watchdog.stop()
        if _prev_term is not None:
            signal.signal(signal.SIGTERM, _prev_term)
        if overlap_exec is not None:
            # Don't wait on a background compile the run never needed
            # (early stop before the first wide chunk).
            overlap_exec.shutdown()
        if prof_win["on"]:
            # Completion proof before finalizing the trace —
            # block_until_ready does not synchronize on the axon transport,
            # and a trace stopped early would miss the device activity it
            # exists to capture. Best-effort: on the mid-run-failure path
            # this finally exists for, the donated state buffers may
            # already be deleted, and a raise here would mask the original
            # error and skip stop_trace/close below.
            try:
                force_fetch(state["params"])
            except Exception:  # fedtpu: noqa[FTP102] raising here would mask the original error and skip stop_trace/close
                pass
            jax.profiler.stop_trace()
        if jsonl is not None:
            jsonl.close()
        # Final counter snapshot even on the failure path — the sink exists
        # to diagnose exactly such runs. Memory gauges are best-effort
        # (buffers may already be deleted mid-failure).
        device_memory_gauges(registry)
        tracer.counters(registry.snapshot())

    personalized: Dict[str, dict] = {}
    if exp.personalize_fn is not None and not diverged:
        # Post-training per-client fine-tune from the final global model;
        # the personalized models are reported, not kept (the returned
        # final_params stay the GLOBAL model, which is what checkpoints and
        # downstream eval use).
        _, pm = exp.personalize_fn(state["params"], batch)
        pm = _rep(pm)
        personalized = {
            "per_client": {k: np.asarray(v)
                           for k, v in pm["per_client"].items()},
            "client_mean": {k: float(v)
                            for k, v in pm["client_mean"].items()},
        }
        vals = ", ".join(f"{k}: {v:.4f}"
                         for k, v in personalized["client_mean"].items())
        log.info(f"Personalized ({cfg.fed.personalize_steps} local steps) "
                 f"client-mean: [{vals}]")

    result = ExperimentResult(
        global_metrics=history,
        pooled_metrics=pooled_hist,
        per_client_metrics=per_client_hist,
        test_metrics=test_hist,
        loss=losses,
        sec_per_round=sec_per_round,
        rounds_run=rounds_run,
        stopped_early=stopped_early,
        final_params=to_numpy(_rep(exp.global_fn(state))),
        config=cfg,
        diverged=diverged,
        personalized_metrics=personalized,
        staleness=staleness_hist,
        # The state's own round counter — the exact ledger of what the
        # released params trained through (> rounds_run after a pipelined
        # early stop's overshoot chunk; the DP accountant must count it).
        rounds_trained=int(np.asarray(jax.device_get(_rep(state["round"])))),
        dp_base_assumed=ledger.base_assumed,
        final_dp_clip=(float(np.asarray(jax.device_get(
            _rep(state["dp_clip"])))) if "dp_clip" in state else None),
    )
    result = dataclasses.replace(
        result, dp_rdp_total=ledger.rdp_at(result.rounds_trained),
        dp_guarantee_void=ledger.void_at(result.rounds_trained),
        dp_composed=ledger.composed)
    if verbose or tracer.enabled:
        dp = result.privacy_spent()
        if dp:
            notes = ""
            if dp.get("composed_over_resumed_segments"):
                notes += ("; composed over resumed segments — sigma/q "
                          "shown are the current segment's")
            if dp.get("guarantee_void"):
                notes += f"; GUARANTEE VOID: {dp['guarantee_void']}"
            log.info(f"DP budget spent: epsilon={dp['epsilon']:.3f} at "
                     f"delta={dp['delta']:.1e} (noise multiplier "
                     f"{dp['noise_multiplier']}, sampling rate "
                     f"{dp['sampling_rate']}, {dp['rounds']} rounds; RDP "
                     f"order {dp['rdp_order']}{notes})")
    if (cfg.fed.async_mode and cfg.fed.async_buffer_size >= 2
            and not diverged and "buf_count" in state):
        # K-buffer starvation guard (VERDICT item 7): with --buffer-size
        # large relative to arrivals the buffer may never fill, so the
        # global silently never moves. The run is still sound — metrics
        # recorded, checkpoints/resume carry the pending buffer — but
        # the user must hear that their contributions were never applied.
        pending = int(np.asarray(jax.device_get(_rep(state["buf_count"]))))
        if pending > 0:
            log.warning(
                f"ASYNC K-BUFFER STARVATION: {pending} buffered update(s) "
                f"never reached --buffer-size {cfg.fed.async_buffer_size} "
                f"by the final tick, so the global model did not advance "
                "on them. Lower --buffer-size or raise --arrival-rate/"
                "--rounds; a resumed run carries the pending buffer "
                "forward.")
            tracer.event("async_starvation", round=rounds_run,
                         pending=pending,
                         buffer_size=cfg.fed.async_buffer_size)
    _beat("diverged" if diverged else "done", rounds_run)
    tracer.event("run_end", round=rounds_run, stopped_early=stopped_early,
                 diverged=diverged, rounds_trained=result.rounds_trained,
                 restarts=restart_count, rollbacks=rollback["attempts"])
    tracer.close()
    return result
