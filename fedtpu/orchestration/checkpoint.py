"""Round-indexed checkpoint / resume.

The reference has NO persistence at all (SURVEY.md §5): best weights are only
printed to stdout (hyperparameters_tuning.py:130-132, FL_SkLearn...:146-150)
and a 300-round run that dies restarts from scratch. fedtpu checkpoints the
full federated state — per-client params, per-client optimizer state (Adam
moments are NOT averaged, so they are real per-client state), round counter,
and metric history — via orbax, and can resume mid-run.

Layout: ``<dir>/round_<step>/{state,meta}`` — two orbax PyTree items. The
``state`` item is restored against a live state template (``state_like``) so
optax namedtuple nodes come back as namedtuples, not dicts.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from fedtpu.utils.trees import to_numpy


def _ckpt_path(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), f"round_{step:06d}")


def save_checkpoint(directory: str, state, history: dict, step: int) -> str:
    """Write state + {history, step} under ``directory/round_<step>``."""
    path = _ckpt_path(directory, step)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(os.path.join(path, "state"), to_numpy(state), force=True)
    ckptr.save(os.path.join(path, "meta"),
               {"history": {k: np.asarray(v) for k, v in history.items()},
                "step": np.asarray(step)},
               force=True)
    return path


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        if name.startswith("round_"):
            try:
                steps.append(int(name.split("_")[1]))
            except (IndexError, ValueError):
                continue
    return max(steps) if steps else None


def load_checkpoint(directory: str, step: Optional[int] = None,
                    sharding=None, state_like=None) -> Tuple[dict, dict, int]:
    """Read back ``(state, history, step)``.

    ``state_like``: a live state pytree (e.g. a freshly-initialized one from
    ``init_federated_state``) used as the restore template so container types
    (optax namedtuples) survive the roundtrip; when its leaves are committed
    jax Arrays, each restored leaf is placed on the SAME per-leaf sharding —
    this is what preserves the tensor-parallel layout of the 2-D engine
    (fedtpu.parallel.tp), where params mix clients-only and
    clients+model-sharded leaves. ``sharding``: fallback single layout for
    all non-scalar leaves when ``state_like`` carries no shardings.
    """
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = _ckpt_path(directory, step)
    ckptr = ocp.PyTreeCheckpointer()
    template = to_numpy(state_like) if state_like is not None else None
    state = ckptr.restore(os.path.join(path, "state"), item=template)
    meta = ckptr.restore(os.path.join(path, "meta"))
    def _mesh_sharding(like):
        s = getattr(like, "sharding", None)
        return s if isinstance(s, jax.sharding.NamedSharding) else None

    if state_like is not None and any(
            _mesh_sharding(l) is not None for l in jax.tree.leaves(state_like)):
        # Mesh-laid-out leaves reuse their template sharding; scalars (the
        # round counter) stay uncommitted so jit can place them freely.
        state = jax.tree.map(
            lambda l, like: (jax.device_put(l, _mesh_sharding(like))
                             if _mesh_sharding(like) is not None
                             else jax.device_put(l)),
            state, state_like)
    elif sharding is not None:
        # Every non-scalar state leaf carries the leading clients axis
        # (params, Adam moments); scalars (the round counter, Adam counts of
        # shape (C,) stay client-sharded too since ndim >= 1).
        state = jax.tree.map(
            lambda l: (jax.device_put(l, sharding)
                       if getattr(l, "ndim", 0) >= 1 else jax.device_put(l)),
            state)
    history = {k: list(np.asarray(v))
               for k, v in meta["history"].items()}
    return state, history, int(np.asarray(meta["step"]))
