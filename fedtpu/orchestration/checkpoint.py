"""Round-indexed checkpoint / resume.

The reference has NO persistence at all (SURVEY.md §5): best weights are only
printed to stdout (hyperparameters_tuning.py:130-132, FL_SkLearn...:146-150)
and a 300-round run that dies restarts from scratch. fedtpu checkpoints the
full federated state — per-client params, per-client optimizer state (Adam
moments are NOT averaged, so they are real per-client state), round counter,
and metric history — via orbax, and can resume mid-run.

Layout: ``<dir>/round_<step>/{state,meta}`` — two orbax PyTree items. The
``state`` item is restored against a live state template (``state_like``) so
optax namedtuple nodes come back as namedtuples, not dicts.
"""

from __future__ import annotations

import itertools
import os
import shutil
import warnings
from collections import defaultdict
from typing import Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from fedtpu.telemetry import default_registry
from fedtpu.utils.trees import identity, to_numpy


def _ckpt_path(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), f"round_{step:06d}")




def _strip_marker(state):
    """Drop the leafless 'shared_start' marker (fedtpu.parallel.round) from
    a state dict. The marker records how the LIVE state was constructed —
    config, not data — so it is never persisted; keeping it out of the
    on-disk tree also keeps checkpoints written before the marker existed
    restorable (orbax rejects template/on-disk structure mismatches)."""
    if isinstance(state, dict) and "shared_start" in state:
        state = {k: v for k, v in state.items() if k != "shared_start"}
    return state


# Per-process attempt ordinal per checkpoint step — see
# _sync_orbax_barrier_counters.
_SAVE_ATTEMPTS: dict = defaultdict(itertools.count)


def _sync_orbax_barrier_counters(step: int) -> None:
    """Orbax derives collective barrier names from PROCESS-LOCAL monotonic
    counters (orbax.checkpoint.multihost.counters). After a live shrink
    (fedtpu.resilience.reshard) the survivors checkpoint alone while the
    parked member's counters stand still, so the first post-grow full-gang
    save would barrier under mismatched names — an AssertionError on the
    sync_global_devices path, a timeout on the KV-barrier path. Every
    member of a save group calls save_checkpoint together, so resetting
    the counters to a base derived from (step, per-step attempt) — both
    symmetric across the group — restores the equal-names invariant orbax
    assumes, while keeping names unique across rounds and across repeated
    same-round saves."""
    if jax.process_count() == 1:
        return
    from orbax.checkpoint.multihost import counters as _counters
    attempt = next(_SAVE_ATTEMPTS[step])
    base = (step + 1) * 10_000 + attempt * 100
    for name in ("_async_save_counter", "_composite_save_counter",
                 "_tmp_directory_counter"):
        setattr(_counters, name, itertools.count(base))


def _checkpointer(step: int, process_group=None) -> ocp.Checkpointer:
    """A PyTree checkpointer scoped to ``process_group`` (process indices)
    when given. After a live shrink (fedtpu.resilience.reshard) the
    departed member is parked outside every collective, so orbax's default
    all-process barrier would hang; the group-scoped checkpointer barriers
    only the survivors, with the lowest survivor as primary host. The
    barrier key prefix is derived from (group, step) so concurrent saves
    of different rounds never alias."""
    if process_group is None or jax.process_count() == 1:
        return ocp.PyTreeCheckpointer()
    group = sorted(int(p) for p in process_group)
    mp_opts = ocp.options.MultiprocessingOptions(
        primary_host=group[0],
        active_processes=set(group),
        barrier_sync_key_prefix=f"fedtpu_g{group[0]}x{len(group)}s{step}")
    # The handler holds its OWN barrier options (defaulting to every
    # process) — scoping only the Checkpointer leaves the handler's
    # internal save barrier waiting on the parked member forever.
    return ocp.Checkpointer(
        ocp.PyTreeCheckpointHandler(multiprocessing_options=mp_opts),
        multiprocessing_options=mp_opts)


def save_checkpoint(directory: str, state, history: dict, step: int,
                    extra_meta: Optional[dict] = None,
                    process_group=None) -> str:
    """Write state + {history, step, num_clients, **extra_meta} under
    ``directory/round_<step>``. ``num_clients`` lives in the tiny meta item
    so elastic-resume detection (fedtpu.orchestration.loop) never has to
    read the full state twice on the common same-count path.
    ``extra_meta``: additional small arrays/scalars for the meta item —
    the loop uses it to persist the cumulative DP RDP curve so a resumed
    run composes its privacy spend instead of re-deriving it from the
    possibly-changed current config.

    Multi-process (jax.distributed): EVERY process must call this — orbax
    save is a collective (it barriers internally; a process-0-only call
    deadlocks the job). The state is passed through as jax.Arrays so orbax
    writes each client shard from the process that owns it (distributed
    checkpointing over the shared checkpoint filesystem); single-process
    keeps the simple host-numpy path.

    ``process_group``: after a live shrink, the surviving process indices —
    every member of the group (and ONLY the group) must make this call;
    see ``_checkpointer``."""
    path = _ckpt_path(directory, step)
    _sync_orbax_barrier_counters(step)
    ckptr = _checkpointer(step, process_group)
    state_item = _strip_marker(state)
    if jax.process_count() == 1:
        state_item = to_numpy(state_item)
    else:
        # After a live shrink the surviving group may hold the WHOLE state
        # (every leaf fully addressable) while jax.process_count() still
        # reports the original gang — jax's array serialization refuses
        # fully-addressable arrays under multiprocess ("Cannot serialize
        # host local arrays"). Route such leaves through the host-numpy
        # path; the scoped checkpointer's primary is the only writer, so
        # the on-disk checkpoint is equivalent. Full-gang saves never
        # match (client-sharded and gang-replicated leaves are not fully
        # addressable from any one process), so their path is unchanged.
        state_item = jax.tree.map(
            lambda l: np.asarray(l)
            if isinstance(l, jax.Array) and l.is_fully_addressable else l,
            state_item)
    ckptr.save(os.path.join(path, "state"), state_item, force=True)
    num_clients = jax.tree.leaves(state["params"])[0].shape[0]
    # Engine kind as an int flag (orbax meta passes through np.asarray, so
    # strings are off the table): the async engine's state carries its
    # anchors pytree, the sync engines' never does. Read back by resume
    # BEFORE the client-count comparison — a cross-engine resume must fail
    # on engine kind, not on whichever structural mismatch orbax hits first.
    engine_async = 1 if (isinstance(state, dict) and "anchors" in state) else 0
    # Zero-length metric arrays are dropped: tensorstore cannot commit an
    # empty chunk (orbax rejects the save as "missing params"), and the
    # loop's restore paths already treat an absent key as an empty
    # history. This is what makes the round-0 restore point — saved
    # BEFORE any metrics exist, for ``on_divergence=rollback`` — storable.
    meta = {"history": {k: np.asarray(v) for k, v in history.items()
                        if np.asarray(v).size},
            "step": np.asarray(step),
            "num_clients": np.asarray(num_clients),
            "engine_async": np.asarray(engine_async)}
    if extra_meta:
        meta.update({k: np.asarray(v) for k, v in extra_meta.items()})
    ckptr.save(os.path.join(path, "meta"), meta, force=True)
    reg = default_registry()
    reg.counter("checkpoint_saves").inc()
    reg.counter("checkpoint_bytes_written").inc(
        sum(getattr(l, "nbytes", 0) for l in jax.tree.leaves(state_item)))
    return path


def _is_complete(path: str) -> bool:
    """A round checkpoint is COMMITTED only when both orbax items exist at
    their final names. Each item is individually atomic (orbax writes to a
    ``*.orbax-checkpoint-tmp`` dir and renames), but the round is two
    sequential items — a SIGKILL mid-save leaves ``round_N`` holding only
    a tmp dir, or ``state`` without ``meta`` (found by the chaos test,
    tests/test_chaos_resume.py). Such half-rounds must be invisible to
    resume: ``meta`` is written last, so state-present + meta-present is
    the commit condition."""
    return (os.path.isdir(os.path.join(path, "state"))
            and os.path.isdir(os.path.join(path, "meta")))


def _step_of(name: str) -> Optional[int]:
    """Step of a ``round_<N>`` directory name; None for anything else.
    The ONE definition of what counts as a round dir — complete_steps
    and the retention remnant sweep must agree on it."""
    if not name.startswith("round_"):
        return None
    try:
        return int(name.split("_")[1])
    except (IndexError, ValueError):
        return None


def _scan_rounds(directory: str) -> list:
    """All round dirs under ``directory`` as sorted (step, complete)
    pairs — one listdir serving both the resume view and retention."""
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        step = _step_of(name)
        if step is not None:
            out.append((step, _is_complete(os.path.join(directory, name))))
    return sorted(out)


def complete_steps(directory: str) -> list:
    """Sorted steps of every COMPLETE checkpoint under ``directory``
    (half-written rounds from a crash are skipped — see
    ``_is_complete``)."""
    return [s for s, ok in _scan_rounds(directory) if ok]


def latest_step(directory: str) -> Optional[int]:
    """Largest COMPLETE checkpoint step under ``directory``."""
    steps = complete_steps(directory)
    return steps[-1] if steps else None


def retain_checkpoints(directory: str, keep: int,
                       protect: Tuple[int, ...] = ()) -> list:
    """Delete all but the ``keep`` NEWEST complete round checkpoints
    (plus any ``protect``-ed steps — the loop protects the best-metric
    round), returning the deleted steps. ``keep <= 0`` keeps everything
    (the default; VERDICT r3 weak #4: unbounded accumulation is the
    wrong shape for a framework that advertises resume).

    Incomplete rounds OLDER than the newest complete one are reclaimed
    too: they are crash remnants (a SIGKILL between the state and meta
    items) that can hold a full-state-sized dir, are invisible to resume
    (``_is_complete``), and would otherwise accumulate across
    crash+resume cycles — the growth this flag exists to prevent. An
    incomplete round AT or ABOVE the newest complete step is left alone:
    called anywhere but right after a save, it could be a concurrent
    writer mid-commit. Multi-process: call from ONE process only (orbax
    save has already barriered, so every round being deleted is fully
    committed).

    GC is best-effort: a transient filesystem error deleting one round
    (NFS silly-rename, an external reader holding a handle) warns and
    skips that round rather than killing the training run — losing
    wall-clock progress over disk GC would invert the priorities."""
    if keep <= 0:
        return []
    rounds = _scan_rounds(directory)
    steps = [s for s, ok in rounds if ok]
    kept = set(steps[-keep:]) | {int(p) for p in protect}
    removed = []

    def _rm(step):
        try:
            shutil.rmtree(_ckpt_path(directory, step))
            removed.append(step)
        except OSError as e:
            warnings.warn(f"checkpoint retention: could not delete "
                          f"round {step} ({e}); will retry after the "
                          "next save", RuntimeWarning)

    for s in steps:
        if s not in kept:
            _rm(s)
    if steps:
        # Incomplete dirs below the newest complete round are dead crash
        # remnants (see docstring); at/above it they may be mid-commit.
        for s, ok in rounds:
            if not ok and s < steps[-1]:
                _rm(s)
    return sorted(removed)


def load_checkpoint_raw(directory: str, step: Optional[int] = None
                        ) -> Tuple[dict, dict, int]:
    """Read back ``(state, history, step)`` WITHOUT a restore template:
    plain nested dicts/lists of numpy arrays (optax namedtuples come back as
    dicts). Used by elastic resume (fedtpu.orchestration.loop), which needs
    the saved arrays under a DIFFERENT client count than the live state —
    a typed template restore would reject the shape mismatch."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = _ckpt_path(directory, step)
    ckptr = ocp.PyTreeCheckpointer()
    state = ckptr.restore(os.path.join(path, "state"))
    meta = ckptr.restore(os.path.join(path, "meta"))
    history = {k: list(np.asarray(v))
               for k, v in (meta.get("history") or {}).items()}
    default_registry().counter("checkpoint_restores").inc()
    return state, history, int(np.asarray(meta["step"]))


def load_meta(directory: str, step: Optional[int] = None) -> dict:
    """The raw meta item of a checkpoint (history, step, num_clients, and
    any ``extra_meta`` the save attached — e.g. the cumulative DP RDP
    curve)."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    return ocp.PyTreeCheckpointer().restore(
        os.path.join(_ckpt_path(directory, step), "meta"))


def saved_num_clients(raw_state: dict) -> int:
    """Client count of a raw checkpoint: the leading axis every params leaf
    carries."""
    return int(jax.tree.leaves(raw_state["params"])[0].shape[0])


def peek_num_clients(directory: str, step: Optional[int] = None
                     ) -> Optional[int]:
    """Client count of a checkpoint from the meta item alone (no state
    read). None for checkpoints written before num_clients was recorded —
    callers then fall back to :func:`load_checkpoint_raw`."""
    nc = load_meta(directory, step).get("num_clients")
    return None if nc is None else int(np.asarray(nc))


def load_checkpoint_fallback(directory: str, sharding=None, state_like=None,
                             max_step: Optional[int] = None
                             ) -> Tuple[dict, dict, int]:
    """``load_checkpoint`` of the NEWEST checkpoint that actually
    restores, walking complete steps newest-first past corrupt rounds.

    ``_is_complete`` only proves both items were committed — it cannot
    see in-place byte corruption (a dying disk, a partial overwrite; the
    ``ckpt_corrupt`` fault in fedtpu.resilience.faults manufactures
    exactly this). A restore failure on the latest round must not strand
    a resumable run when an older good round exists, so each failure is
    warned about, counted (``checkpoint_restore_corrupt``), and skipped.
    Raises FileNotFoundError when no checkpoint loads at all.

    ``max_step`` bounds the walk: on a multi-process resume the gang has
    AGREED on a common step (fedtpu.resilience.distributed), and a
    process restoring anything newer would desync the federation."""
    steps = complete_steps(directory)
    if max_step is not None:
        steps = [s for s in steps if s <= max_step]
    last_err: Optional[Exception] = None
    for step in reversed(steps):
        try:
            return load_checkpoint(directory, step=step, sharding=sharding,
                                   state_like=state_like)
        except Exception as e:
            last_err = e
            default_registry().counter("checkpoint_restore_corrupt").inc()
            warnings.warn(f"checkpoint round {step} failed to restore "
                          f"({type(e).__name__}: {e}); falling back to the "
                          "previous round", RuntimeWarning)
    raise FileNotFoundError(
        f"no restorable checkpoint under {directory} "
        f"({len(steps)} complete-looking round(s) all failed to load)"
    ) from last_err


def load_checkpoint(directory: str, step: Optional[int] = None,
                    sharding=None, state_like=None) -> Tuple[dict, dict, int]:
    """Read back ``(state, history, step)``.

    ``state_like``: a live state pytree (e.g. a freshly-initialized one from
    ``init_federated_state``) used as the restore template so container types
    (optax namedtuples) survive the roundtrip; when its leaves are committed
    jax Arrays, each restored leaf is placed on the SAME per-leaf sharding —
    this is what preserves the tensor-parallel layout of the 2-D engine
    (fedtpu.parallel.tp), where params mix clients-only and
    clients+model-sharded leaves. ``sharding``: fallback single layout for
    all non-scalar leaves when ``state_like`` carries no shardings.
    """
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = _ckpt_path(directory, step)
    ckptr = ocp.PyTreeCheckpointer()
    # The 'shared_start' marker is config, not data: never on disk (see
    # _strip_marker), re-attached below from the live template.
    had_marker = isinstance(state_like, dict) and "shared_start" in state_like
    state_like = _strip_marker(state_like)
    # Template from the live state's STRUCTURE only (shapes/dtypes/container
    # types) — never fetch its values: under jax.distributed the
    # client-sharded leaves are not host-addressable (to_numpy would raise),
    # and orbax only reads the template's structure anyway.
    template = (jax.tree.map(lambda l: np.zeros(np.shape(l), l.dtype),
                             state_like)
                if state_like is not None else None)
    state = ckptr.restore(os.path.join(path, "state"), item=template)
    meta = ckptr.restore(os.path.join(path, "meta"))
    def _mesh_sharding(like):
        s = getattr(like, "sharding", None)
        return s if isinstance(s, jax.sharding.NamedSharding) else None

    def _place(l, sh):
        """Put a restored leaf on sharding ``sh``. Under jax.distributed a
        multi-process-saved checkpoint restores as GLOBAL jax.Arrays, which
        ``jax.device_put`` refuses to reshard (not fully addressable) — an
        identity jit with out_shardings does the reshard as an SPMD program
        instead. Host/numpy and single-process leaves take the plain path."""
        if isinstance(l, jax.Array) and not l.is_fully_addressable:
            if sh is None:
                return l                      # already a fine global array
            return jax.jit(identity, out_shardings=sh)(l)  # fedtpu: noqa[FTP006] one-shot resume-time reshard, not a hot path
        if sh is None:
            return jax.device_put(l)
        # safe_put: a host leaf onto a cross-process sharding would run an
        # implicit per-leaf equality broadcast under jax.distributed
        # (fedtpu.parallel.multihost.safe_put) — resume replays one per
        # restored leaf, exactly when a freshly restarted gang is most
        # sensitive to collective misalignment.
        from fedtpu.parallel.multihost import safe_put
        return safe_put(l, sh)

    if state_like is not None and any(
            _mesh_sharding(l) is not None for l in jax.tree.leaves(state_like)):
        # Mesh-laid-out leaves reuse their template sharding; scalars (the
        # round counter) stay uncommitted so jit can place them freely.
        state = jax.tree.map(
            lambda l, like: _place(l, _mesh_sharding(like)),
            state, state_like)
    elif sharding is not None:
        # Every non-scalar state leaf carries the leading clients axis
        # (params, Adam moments); scalars (the round counter, Adam counts of
        # shape (C,) stay client-sharded too since ndim >= 1).
        state = jax.tree.map(
            lambda l: _place(l, sharding if getattr(l, "ndim", 0) >= 1
                             else None),
            state)
    if had_marker:
        state["shared_start"] = ()
    history = {k: list(np.asarray(v))
               for k, v in (meta.get("history") or {}).items()}
    default_registry().counter("checkpoint_restores").inc()
    return state, history, int(np.asarray(meta["step"]))
