from fedtpu.utils.trees import param_count, tree_bytes  # noqa: F401
from fedtpu.utils.timing import Timer  # noqa: F401
