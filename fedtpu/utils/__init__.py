from fedtpu.utils.trees import (max_device_bytes, param_count,  # noqa: F401
                                per_device_bytes, tree_bytes)
from fedtpu.utils.timing import Timer  # noqa: F401
