"""Wall-clock timing — the observability the reference lacks entirely
(SURVEY.md §5: no timers, no profiler; ``print(flush=True)`` only)."""

from __future__ import annotations

import time


class Timer:
    """Accumulates per-lap wall-clock times (seconds)."""

    def __init__(self):
        self.laps = []
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()
        return self

    def lap(self) -> float:
        now = time.perf_counter()
        dt = now - self._t0
        self._t0 = now
        self.laps.append(dt)
        return dt

    @property
    def total(self) -> float:
        return sum(self.laps)

    def mean(self, skip_first: int = 0) -> float:
        laps = self.laps[skip_first:] or self.laps
        return sum(laps) / max(len(laps), 1)
