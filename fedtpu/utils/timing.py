"""Wall-clock timing — the observability the reference lacks entirely
(SURVEY.md §5: no timers, no profiler; ``print(flush=True)`` only).

Fetch-forced timing (``force_fetch`` / ``measured_peak_flops`` /
``assert_above_flops_floor``): on this platform's remote ('axon') TPU
transport, ``jax.block_until_ready`` can return before the enqueued compute
has actually executed, so wall-clock timing closed by it measures DISPATCH
rate, not compute (round-1 postmortem: a 22,260x headline that was really
~44x). A host value fetch cannot lie — transferring a value that depends on
the full program forces real completion. Every benchmark in this repo must
close its timed window with ``force_fetch`` and guard the result with
``assert_above_flops_floor``."""

from __future__ import annotations

import time


class Timer:
    """Accumulates per-lap wall-clock times (seconds)."""

    def __init__(self):
        self.laps = []
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()
        return self

    def lap(self) -> float:
        now = time.perf_counter()
        dt = now - self._t0
        self._t0 = now
        self.laps.append(dt)
        return dt

    @property
    def total(self) -> float:
        return sum(self.laps)

    def mean(self, skip_first: int = 0) -> float:
        laps = self.laps[skip_first:] or self.laps
        return sum(laps) / max(len(laps), 1)


def force_fetch(tree) -> float:
    """Fetch one host value that depends on ``tree`` — the only completion
    proof this platform offers (see module docstring). The reduction to a
    scalar happens ON DEVICE so only ~4 bytes cross the (slow, tunneled)
    host link — fetching a whole array would add seconds of transfer to the
    timed window. Returns the fetched scalar so callers can sanity-check
    it."""
    import jax
    import numpy as np

    leaves = [l for l in jax.tree.leaves(tree) if isinstance(l, jax.Array)]
    if not leaves:
        # A host-only tree proves nothing about device completion — a timed
        # window "closed" here would silently measure dispatch rate again.
        # Refuse rather than look like success.
        raise TypeError(
            "force_fetch: no device-backed (jax.Array) leaf in the tree — "
            "fetching host values proves nothing about device completion")
    leaf = leaves[-1]
    if getattr(leaf, "ndim", 0):
        leaf = leaf.reshape(-1)[-1]        # device-side slice, scalar out
    return float(np.asarray(leaf))


def program_flops(compiled) -> float:
    """Flops from an executable's XLA cost analysis (0.0 when absent)."""
    cost = compiled.cost_analysis() or {}
    if isinstance(cost, (list, tuple)):   # pre-0.5 jax: list of per-module dicts
        cost = cost[0] if cost else {}
    return float(cost.get("flops", 0.0))


def program_bytes_accessed(compiled) -> float:
    """Bytes accessed from an executable's XLA cost analysis (0.0 when
    absent) — the roofline denominator's memory side: flops / bytes is
    the program's arithmetic intensity (docs/observability.md)."""
    cost = compiled.cost_analysis() or {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return float(cost.get("bytes accessed", 0.0))


def compile_with_flops(step, *args, cache=None, key=None):
    """AOT-compile a jitted program once; return ``(compiled, flops)``.

    The single shared path for benchmark scripts: the returned executable is
    what the timed loop must call (the AOT path does not populate jax.jit's
    dispatch cache, so lowering for cost analysis and then calling the
    jitted function would compile the same program twice — expensive through
    the tunneled backend). ``flops`` is the program's XLA cost analysis;
    note a ``lax.scan`` body is counted ONCE regardless of length, so for a
    scanned multi-round program this is the PER-ROUND cost. Raises when cost
    analysis is unavailable: a benchmark that cannot check its flops floor
    must not record a number at all.

    ``cache`` (a :class:`fedtpu.compilation.ProgramCache`) routes the build
    through the serialized-executable store: a warm entry under ``key``
    deserializes in milliseconds and carries its flops in the meta sidecar
    (cost analysis is computed at store time)."""
    if cache is not None:
        if key is None:
            raise ValueError("compile_with_flops: cache given without a key")
        entry = cache.get_or_compile(key, step, *args, label="bench")
        compiled = entry.compiled
        flops = float(entry.meta.get("flops") or program_flops(compiled))
    else:
        compiled = step.lower(*args).compile()
        flops = program_flops(compiled)
    if flops <= 0:
        raise RuntimeError(
            "XLA cost_analysis unavailable for this program; the flops "
            "floor cannot be checked — refusing to record an unguarded "
            "perf number")
    return compiled, flops


def timed_rounds(step, state, batch, n_calls: int, rounds_per_step: int,
                 peak_flops: float, flops_per_round: float,
                 label: str = "", warmup: int = 3, window_reps: int = 3):
    """THE benchmark harness — the only sanctioned way to time round
    programs in this repo: executable warmup, a fetch-forced pipelined
    window (back-to-back calls, one completion-proving host fetch at the
    end), per-round normalization, and the mandatory flops-floor check.
    Returns ``(sec_per_round, final_state, final_metrics)``; read accuracy
    etc. from the returned metrics outside the timed window.

    Exists so benchmark scripts cannot drift back to hand-rolled timing
    (the round-1 artifact): pair with ``compile_with_flops`` for the step
    and ``measured_peak_flops`` for the peak.

    ``window_reps`` windows are timed and the fastest kept — the tunneled
    transport's per-call dispatch cost jitters by tens of ms, and min is
    the standard least-noise latency estimator (every window still proves
    completion, so min cannot select an artifact)."""
    for _ in range(warmup):
        state, metrics = step(state, batch)
    force_fetch(metrics)
    best = float("inf")
    for _ in range(window_reps):
        t0 = time.perf_counter()
        for _ in range(n_calls):
            state, metrics = step(state, batch)
        force_fetch(metrics)
        best = min(best, time.perf_counter() - t0)
    sec = best / (n_calls * rounds_per_step)
    assert_above_flops_floor(sec, flops_per_round, peak_flops, label=label)
    return sec, state, metrics


def measured_peak_flops(dtype="float32", n: int | None = None,
                        chains=None, device=None) -> float:
    """Achieved FLOP/s on an n x n matmul chain, fetch-forced.

    Times two scanned programs of ``chains[0]`` and ``chains[1]`` dependent
    matmuls and uses the SLOPE (t2-t1)/(k2-k1): fixed per-call costs —
    dispatch RTT over the tunnel (~100 ms on this box) and the scalar fetch
    — cancel exactly, so the result is the marginal per-matmul rate. The
    chain lengths are far apart because the fixed cost dwarfs short chains
    (measured here: 191 TFLOP/s bf16 from a (80,256) slope ≈ the v5e spec
    peak, vs 571 "TFLOP/s" from a noise-dominated (16,80) slope). The chain
    returns an on-device scalar so the fetch moves ~4 bytes.

    This feeds the DENOMINATOR of the flops-floor check, so accuracy
    matters in one direction: an UNDERestimated peak inflates the floor and
    could fail an honest measurement. The slope method plus large-n MXU
    -friendly shapes keeps the estimate near true peak; the floor's 2x
    headroom absorbs the rest."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if n is None or chains is None:
        platform = (device.platform if device is not None
                    else jax.devices()[0].platform)
        if platform == "cpu":
            # The accelerator-scale default (~1.8e14 FLOPs) would run for
            # hours on the 1-core CPU verification box; a small probe keeps
            # the floor meaningful (CPU peak ~ GFLOP/s) and the script fast.
            n, chains = (n or 512), (chains or (4, 20))
        else:
            n, chains = (n or 4096), (chains or (32, 288))

    a = jnp.asarray(np.random.default_rng(0).standard_normal((n, n)),
                    dtype=dtype)
    if device is not None:
        a = jax.device_put(a, device)

    def make(k):
        @jax.jit
        def chained(x):
            def body(y, _):
                # Rescale so the chain neither overflows nor denormals out.
                y = y @ x
                return y / jnp.sqrt(jnp.float32(n)).astype(y.dtype), None
            y, _ = jax.lax.scan(body, x, length=k)
            return y.sum()                 # scalar out: 4-byte fetch
        return chained

    def slope_times(ks):
        out = []
        for k in ks:
            fn = make(k)
            force_fetch(fn(a))             # compile + warmup
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                force_fetch(fn(a))
                best = min(best, time.perf_counter() - t0)
            out.append(best)
        return out

    # A non-positive slope means noise swamped the marginal rate. Before
    # degrading, ESCALATE: double the chain lengths (the fixed cost the
    # slope cancels is amortized 2x harder each time) and re-measure, up
    # to two escalations. On the contended 1-core verification box this
    # recovers a usable slope nearly always (VERDICT r3 weak #7: the
    # first-try fallback fired often enough off-TPU that the FLOPs floor
    # was effectively unguarded there).
    attempt_log = []
    for attempt in range(3):
        ks = tuple(k * 2 ** attempt for k in chains)
        times = slope_times(ks)
        dt = times[1] - times[0]
        attempt_log.append((ks, times))
        if dt > 0:
            return 2.0 * n * n * n * (ks[1] - ks[0]) / dt
    # Escalation exhausted. The only available fallback — long chain FLOPs
    # over its FULL wall time — includes the fixed dispatch+fetch cost the
    # slope method exists to cancel, so it UNDERestimates peak; since peak
    # is the denominator of assert_above_flops_floor, that inflates the
    # floor and can spuriously fail an honest benchmark. Never degrade
    # silently (review r2): warn loudly so a floor violation downstream is
    # traceable to the measurement, not the timed program.
    import warnings
    ks, times = attempt_log[-1]
    fallback = 2.0 * n * n * n * ks[1] / times[1]
    detail = "; ".join(
        f"k={k0},{k1}: {t0:.3e}s,{t1:.3e}s"
        for (k0, k1), (t0, t1) in attempt_log)
    warnings.warn(
        f"measured_peak_flops: non-positive slope after "
        f"{len(attempt_log) - 1} chain-length escalations "
        f"({detail}) — dispatch noise swamped the "
        f"marginal rate. Falling back to the fixed-cost-contaminated "
        f"whole-chain estimate {fallback:.3e} FLOP/s, which UNDERestimates "
        f"peak and inflates any FLOPs floor computed from it. Re-run on a "
        f"quieter box.",
        RuntimeWarning, stacklevel=2)
    return fallback


def assert_above_flops_floor(sec_per_round: float, flops_per_round: float,
                             peak_flops: float, label: str = "") -> float:
    """Physics guard for benchmark numbers: no program can run its FLOPs
    faster than 2x the measured peak (the 2x absorbs peak-measurement noise
    and mixed-precision ambiguity). A violation means the timing methodology
    is broken (round 1: async dispatch measured instead of compute) and MUST
    fail loudly rather than record a fantasy number. Returns the floor."""
    floor = flops_per_round / (2.0 * peak_flops)
    if sec_per_round < floor:
        raise RuntimeError(
            f"timing methodology broken{' (' + label + ')' if label else ''}:"
            f" measured {sec_per_round:.3e} s/round but the program costs "
            f"{flops_per_round:.3e} FLOPs and the device measures "
            f"{peak_flops:.3e} FLOP/s peak — physical floor "
            f"{floor:.3e} s/round. The timed window is not capturing "
            "execution (dispatch-rate artifact); close it with force_fetch.")
    return floor


def marginal_slope(make_fn, lens=(1000, 4000), reps=4):
    """Marginal seconds-per-iteration via the scan-length SLOPE:
    ``(t(lens[1]) - t(lens[0])) / (lens[1] - lens[0])``, each window
    fetch-forced and min-of-``reps``. Fixed per-call costs — dispatch RTT
    through the tunnel and the completion fetch — cancel exactly, so the
    result is the pure on-device marginal (the same methodology as
    ``measured_peak_flops``; shared by the round-4 roofline and Pallas
    benchmarks so the scripts cannot drift apart). ``make_fn(R)`` must
    return a zero-arg callable running an R-iteration program whose
    result force_fetch can prove complete."""
    ts = []
    for R in lens:
        fn = make_fn(R)
        force_fetch(fn())                  # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            force_fetch(fn())
            best = min(best, time.perf_counter() - t0)
        ts.append(best)
    return (ts[1] - ts[0]) / (lens[1] - lens[0])
