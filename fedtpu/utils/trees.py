"""Small pytree utilities."""

from __future__ import annotations

import jax
import numpy as np


def param_count(tree) -> int:
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(tree))


def tree_bytes(tree) -> int:
    return sum(int(np.prod(l.shape)) * l.dtype.itemsize
               for l in jax.tree.leaves(tree))


def identity(tree):
    """Module-level identity for reshard/replicate jits
    (``jax.jit(identity, out_shardings=...)``): jit's cache is keyed on
    function identity, so a fresh lambda per call site would retrace and
    recompile every time. Shared by the orchestration loop's metric
    replication and the checkpoint restore's reshard."""
    return tree


def to_numpy(tree):
    """Device -> host copy of a whole pytree."""
    return jax.tree.map(lambda l: np.asarray(jax.device_get(l)), tree)


def clone(tree):
    """Fresh device buffers with the same values and shardings.

    The compiled round step DONATES its input state
    (fedtpu.parallel.round.build_round_fn): after ``new = round_step(state,
    batch)`` the old ``state``'s buffers are gone. Callers that need the
    pre-step state afterwards (A/B comparisons, snapshots) should step a
    ``clone(state)`` instead.
    """
    return jax.tree.map(
        lambda l: l.copy() if isinstance(l, jax.Array) else l, tree)


def per_device_bytes(tree) -> dict:
    """Measured live bytes per device id: sums each leaf's ACTUAL shard
    buffers (``addressable_shards``), so replicated leaves count fully on
    every device they occupy. The measurement behind the 2-D engine's
    memory proof (benchmarks/tp_memory.py and its pinning test)."""
    per: dict = {}
    for leaf in jax.tree.leaves(tree):
        for s in leaf.addressable_shards:
            per[s.device.id] = per.get(s.device.id, 0) + s.data.nbytes
    return per


def max_device_bytes(tree) -> int:
    """Max over devices of measured live bytes for ``tree``."""
    return max(per_device_bytes(tree).values())
