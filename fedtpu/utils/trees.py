"""Small pytree utilities."""

from __future__ import annotations

import jax
import numpy as np


def param_count(tree) -> int:
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(tree))


def tree_bytes(tree) -> int:
    return sum(int(np.prod(l.shape)) * l.dtype.itemsize
               for l in jax.tree.leaves(tree))


def to_numpy(tree):
    """Device -> host copy of a whole pytree."""
    return jax.tree.map(lambda l: np.asarray(jax.device_get(l)), tree)
