"""Content-addressed AOT executable store (ProgramCache).

fedtpu launches a *family* of XLA programs per job — one round program
per chunk width, one sweep program per depth bucket, an eval program —
and ROUND5 measured the cold compile of the 72-slot arch-vmap sweep
program at 90-207 s against a 29 s warm-run win. The persistent XLA
compilation cache (``--compilation-cache``) already amortizes the
*backend* compile, but the first dispatch still pays tracing, lowering
and executable construction synchronously. This module stores the
**compiled executable itself**: ``lower().compile()`` once (the same
AOT shape as ``fedtpu.utils.timing.compile_with_flops``), serialize via
``jax.experimental.serialize_executable``, and on the next run
deserialize in tens of milliseconds instead of recompiling.

Keying is content-addressed: a cache key fingerprints the config slice,
mesh shape, abstract argument shapes/dtypes/shardings, and the
jax/jaxlib/runtime versions, so a changed hidden width, client count or
dtype misses the cache instead of loading a stale program. Every entry
carries a sidecar meta JSON with the environment fingerprint and a
payload checksum; a mismatch (version skew, truncated blob, unpickle
failure) falls back to a fresh compile — the cache can make a run
faster, never wrong.

Like the telemetry package this module is import-light: jax is only
imported inside functions, so ``fedtpu.compilation`` can be imported
from lint/CI contexts without dragging in a backend.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import tempfile
import time
from typing import Any, Callable, Dict, Optional, Tuple

__all__ = [
    "CACHE_FORMAT_VERSION",
    "CacheEntry",
    "ProgramCache",
    "configure_persistent_cache",
    "environment_fingerprint",
    "program_fingerprint",
]

# Bump when the on-disk layout or pickled tuple shape changes; old
# entries are then treated as misses, never deserialized.
CACHE_FORMAT_VERSION = 1


def configure_persistent_cache(cache_dir: str) -> str:
    """Point jax's persistent (backend) compilation cache at ``cache_dir``.

    One shared entry point for the CLI, ``run_experiment``, the sweep and
    bench, so library callers get identical behavior to ``fedtpu run
    --compilation-cache``. Must run before the programs of interest are
    compiled; safe to call repeatedly. Respects an explicit
    ``JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS`` from the environment.
    """
    import jax

    path = os.path.abspath(os.path.expanduser(cache_dir))
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
        # Default floor skips caching sub-half-second programs; an env var
        # set by the caller (e.g. CPU tests caching tiny programs) wins.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return path


def environment_fingerprint() -> Dict[str, Any]:
    """Version facts that invalidate a serialized executable when changed."""
    import jax
    import jaxlib

    env: Dict[str, Any] = {
        "cache_format": CACHE_FORMAT_VERSION,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
    }
    try:
        # PJRT exposes the runtime build (XLA revision) here; best-effort —
        # jax/jaxlib versions alone already pin the wheel.
        env["platform_version"] = jax.devices()[0].client.platform_version
    except Exception:  # pragma: no cover - backend-specific attribute
        env["platform_version"] = "unknown"
    return env


def _canonical(obj: Any) -> Any:
    """JSON-stable view of configs/conditions for hashing."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return _canonical(dataclasses.asdict(obj))
    if isinstance(obj, dict):
        return {str(k): _canonical(v) for k, v in sorted(obj.items())}
    if isinstance(obj, (list, tuple)):
        return [_canonical(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return repr(obj)


def _abstract_signature(args: Tuple[Any, ...]) -> list:
    """Per-leaf (shape, dtype, sharding) of the call arguments plus the
    tree structure — the part of the key that makes a changed client
    count, hidden width or dtype a cache *miss*."""
    import jax

    sig = []
    for a in args:
        leaves, treedef = jax.tree.flatten(a)
        entry = []
        for leaf in leaves:
            shape = tuple(getattr(leaf, "shape", ()))
            dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
            sharding = getattr(leaf, "sharding", None)
            entry.append([list(shape), dtype,
                          repr(getattr(sharding, "spec", sharding))])
        sig.append({"tree": str(treedef), "leaves": entry})
    return sig


def _mesh_signature(mesh: Any) -> Any:
    if mesh is None:
        return None
    try:
        # device_ids makes the *assignment* part of the key, not just the
        # extent: two equal-sized slices of one parent mesh (MPMD client
        # slice vs server slice) compile against different device sets and
        # must never share an executable.
        return {"shape": [[str(k), int(v)] for k, v in mesh.shape.items()],
                "devices": int(mesh.devices.size),
                "device_ids": [[str(getattr(d, "platform", "?")), int(d.id)]
                               for d in mesh.devices.flat]}
    except Exception:
        return repr(mesh)


def program_fingerprint(label: str,
                        *,
                        config: Any = None,
                        mesh: Any = None,
                        args: Tuple[Any, ...] = (),
                        extra: Any = None) -> str:
    """Content-address for one program: sha256 over the program label,
    the config slice that shaped it, the mesh, the abstract argument
    signature and the environment fingerprint. 20 hex chars."""
    material = {
        "label": label,
        "config": _canonical(config),
        "mesh": _mesh_signature(mesh),
        "args": _abstract_signature(tuple(args)),
        "env": environment_fingerprint(),
        "extra": _canonical(extra),
    }
    blob = json.dumps(material, sort_keys=True).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()[:20]


@dataclasses.dataclass
class CacheEntry:
    """Result of a cache lookup-or-compile."""

    compiled: Any                 # the executable (jax ``Compiled``-like)
    key: str
    warm: bool                    # True = deserialized from disk
    seconds: float                # deserialize time (warm) or compile (cold)
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)


class ProgramCache:
    """Disk store of serialized XLA executables, keyed by fingerprint.

    Layout: ``<dir>/<key>.bin`` (pickled ``serialize_executable`` tuple)
    plus ``<dir>/<key>.json`` (environment fingerprint, payload sha256,
    label, optional flops). Any integrity or version mismatch is a miss;
    any store failure is a warning-level no-op — lookups degrade to the
    eager compile path, never to a wrong program.
    """

    def __init__(self, cache_dir: str, tracer=None, registry=None):
        self.cache_dir = os.path.abspath(os.path.expanduser(cache_dir))
        os.makedirs(self.cache_dir, exist_ok=True)
        if tracer is None:
            from fedtpu.telemetry import NullTracer
            tracer = NullTracer()
        self.tracer = tracer
        self.registry = registry
        self.hits = 0
        self.misses = 0
        self.store_errors = 0

    # ------------------------------------------------------------- paths
    def _paths(self, key: str) -> Tuple[str, str]:
        return (os.path.join(self.cache_dir, f"{key}.bin"),
                os.path.join(self.cache_dir, f"{key}.json"))

    def _count(self, name: str, dur_ms: Optional[float] = None) -> None:
        if self.registry is not None:
            self.registry.counter(f"program_cache_{name}").inc()
            if dur_ms is not None:
                self.registry.histogram(
                    f"program_cache_{name}_ms",
                    bins=(1.0, 10.0, 100.0, 1e3, 1e4, 1e5)).observe(dur_ms)

    # ----------------------------------------------------------- queries
    def peek(self, key: str) -> bool:
        """True iff ``key`` has a complete, version-compatible entry on
        disk (no deserialization — cheap enough for manifests)."""
        bin_path, meta_path = self._paths(key)
        meta = self._read_meta(meta_path)
        return (meta is not None and os.path.exists(bin_path)
                and meta.get("env") == _jsonish(environment_fingerprint()))

    def entries(self) -> list:
        """Keys with both payload and sidecar present."""
        out = []
        for fn in sorted(os.listdir(self.cache_dir)):
            if fn.endswith(".bin"):
                key = fn[:-4]
                if os.path.exists(self._paths(key)[1]):
                    out.append(key)
        return out

    def _read_meta(self, meta_path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(meta_path, encoding="utf-8") as fh:
                meta = json.load(fh)
            return meta if isinstance(meta, dict) else None
        except (OSError, ValueError):
            return None

    # ------------------------------------------------------------ load
    def load(self, key: str) -> Optional[CacheEntry]:
        """Deserialize ``key`` or return None (miss / guard failure)."""
        bin_path, meta_path = self._paths(key)
        meta = self._read_meta(meta_path)
        if meta is None or not os.path.exists(bin_path):
            return None
        if meta.get("env") != _jsonish(environment_fingerprint()):
            return None                       # version skew: recompile
        t0 = time.perf_counter()
        try:
            with open(bin_path, "rb") as fh:
                raw = fh.read()
            if hashlib.sha256(raw).hexdigest() != meta.get("payload_sha256"):
                return None                   # truncated / corrupted blob
            payload, in_tree, out_tree = pickle.loads(raw)
            from jax.experimental import serialize_executable as se
            compiled = se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception:
            # Graceful fallback: any unpickle/deserialize failure (stale
            # jaxlib internals, foreign blob) degrades to a recompile.
            return None
        dur = time.perf_counter() - t0
        return CacheEntry(compiled=compiled, key=key, warm=True,
                          seconds=dur, meta=meta)

    # ------------------------------------------------------------ store
    def store(self, key: str, compiled: Any,
              extra_meta: Optional[Dict[str, Any]] = None) -> bool:
        """Serialize ``compiled`` under ``key``; False (never raise) on
        any failure so a broken disk can't take down a run."""
        bin_path, meta_path = self._paths(key)
        t0 = time.perf_counter()
        try:
            from jax.experimental import serialize_executable as se
            payload, in_tree, out_tree = se.serialize(compiled)
            raw = pickle.dumps((payload, in_tree, out_tree))
            meta = {
                "key": key,
                "env": _jsonish(environment_fingerprint()),
                "payload_sha256": hashlib.sha256(raw).hexdigest(),
                "payload_bytes": len(raw),
            }
            if extra_meta:
                meta.update(_jsonish(extra_meta))
            # Atomic publish: payload first, sidecar last — a reader only
            # trusts entries whose sidecar exists and checksums match.
            for path, data, mode in ((bin_path, raw, "wb"),
                                     (meta_path,
                                      json.dumps(meta, sort_keys=True), "w")):
                fd, tmp = tempfile.mkstemp(dir=self.cache_dir)
                try:
                    with os.fdopen(fd, mode) as fh:
                        fh.write(data)
                    os.replace(tmp, path)
                except BaseException:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
                    raise
        except Exception:
            self.store_errors += 1
            self._count("store_errors")
            return False
        self.tracer.event("program_cache", phase="store", key=key,
                          serialize_ms=(time.perf_counter() - t0) * 1e3,
                          payload_bytes=meta["payload_bytes"])
        self._count("stores", (time.perf_counter() - t0) * 1e3)
        return True

    # --------------------------------------------------- lookup-or-build
    def get_or_compile(self, key: str, step: Any, *args: Any,
                       label: str = "program",
                       extra_meta: Optional[Dict[str, Any]] = None,
                       ) -> CacheEntry:
        """Warm path: deserialize ``key``. Cold path: ``step.lower(*args)
        .compile()`` (the AOT shape of ``compile_with_flops``), persist,
        return. Flops are computed at store time and carried in the meta
        sidecar because ``cost_analysis`` is cheapest on a fresh build."""
        entry = self.load(key)
        if entry is not None:
            self.hits += 1
            self.tracer.event("program_cache", phase="hit", key=key,
                              label=entry.meta.get("label", label),
                              deserialize_ms=entry.seconds * 1e3)
            self._count("hits", entry.seconds * 1e3)
            return entry

        self.misses += 1
        t0 = time.perf_counter()
        compiled = step.lower(*args).compile()
        compile_s = time.perf_counter() - t0
        meta: Dict[str, Any] = {"label": label, "compile_s": compile_s}
        try:
            from fedtpu.utils.timing import (program_bytes_accessed,
                                             program_flops)
            meta["flops"] = program_flops(compiled)
            # Memory side of the roofline: with flops this gives the
            # program's arithmetic intensity without re-lowering.
            meta["bytes_accessed"] = program_bytes_accessed(compiled)
        except Exception:  # fedtpu: noqa[FTP102] flops are advisory metadata; cost_analysis availability varies by backend
            pass
        if extra_meta:
            meta.update(extra_meta)
        self.tracer.event("program_cache", phase="miss", key=key,
                          label=label, compile_s=compile_s)
        self._count("misses", compile_s * 1e3)
        self.store(key, compiled, extra_meta=meta)
        return CacheEntry(compiled=compiled, key=key, warm=False,
                          seconds=compile_s, meta=meta)

    # -------------------------------------------------------- reporting
    def stats(self) -> Dict[str, Any]:
        return {"dir": self.cache_dir, "hits": self.hits,
                "misses": self.misses, "store_errors": self.store_errors,
                "entries": len(self.entries())}

    def manifest_info(self) -> Dict[str, Any]:
        """Shape recorded into the telemetry run manifest (cache
        directory + hit/miss state)."""
        return {"program_cache": self.stats()}


def _jsonish(obj: Any) -> Any:
    """Round-trip through JSON so stored and freshly-computed metadata
    compare equal (tuples vs lists, int keys vs str)."""
    return json.loads(json.dumps(_canonical(obj), sort_keys=True))
