"""AOT compilation subsystem: persist, key and overlap XLA compilation.

Three layers (ISSUE 3):

- :mod:`fedtpu.compilation.cache` — ``ProgramCache``, a content-addressed
  store of serialized executables with integrity/version guards, plus
  ``configure_persistent_cache`` for jax's backend compilation cache;
- :mod:`fedtpu.compilation.executor` — ``CompileExecutor``, a background
  compile thread pool that builds not-yet-needed programs while the
  current one runs;
- :mod:`fedtpu.compilation.warmup` — ``warmup_preset``, the ``fedtpu
  warmup`` driver pre-compiling a preset's program family into a cache
  directory.

Import-light: jax loads only when a compile/lookup actually happens.
"""

from fedtpu.compilation.cache import (CACHE_FORMAT_VERSION, CacheEntry,
                                      ProgramCache, configure_persistent_cache,
                                      environment_fingerprint,
                                      program_fingerprint)
from fedtpu.compilation.executor import CompileExecutor
from fedtpu.compilation.warmup import program_config_slice, warmup_preset

__all__ = [
    "CACHE_FORMAT_VERSION",
    "CacheEntry",
    "CompileExecutor",
    "ProgramCache",
    "configure_persistent_cache",
    "environment_fingerprint",
    "program_config_slice",
    "program_fingerprint",
    "warmup_preset",
]
