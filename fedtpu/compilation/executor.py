"""Background compile thread pool (CompileExecutor).

Hides compilation behind running compute: while sweep bucket k executes,
bucket k+1's program lowers and compiles on a worker thread; while R=1
warmup rounds already train, the R-wide chunk program builds in the
background. Dispatch then blocks only if the executable is not ready
yet — never to start a compile it could have overlapped.

jax tracing/lowering/compilation is thread-safe (compilation itself
releases the GIL inside XLA), so a single worker thread is enough to
overlap compile with the host-side dispatch/fetch of the running
program without oversubscribing the machine. Builds are deduplicated by
key: submitting the same key twice returns the same future, mirroring
the jit cache's per-shape semantics.

Failures are not raised on the worker: ``get`` re-raises the build
exception at the dispatch site so callers can fall back to the eager
path (see ``fedtpu/sweep/grid.py``) with the error attributed to the
launch that needed the program.
"""

from __future__ import annotations

import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional

__all__ = ["CompileExecutor"]


class CompileExecutor:
    """Keyed, deduplicating thread pool for AOT program builds."""

    def __init__(self, max_workers: int = 1, tracer=None, registry=None):
        if tracer is None:
            from fedtpu.telemetry import NullTracer
            tracer = NullTracer()
        self.tracer = tracer
        self.registry = registry
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="fedtpu-compile")
        self._futures: Dict[str, Future] = {}
        self._submitted_at: Dict[str, float] = {}

    # ---------------------------------------------------------- lifecycle
    def __enter__(self) -> "CompileExecutor":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    def shutdown(self, wait: bool = False) -> None:
        """Cancel queued builds; by default do not block on in-flight
        ones (an unused background compile must not delay run exit)."""
        self._pool.shutdown(wait=wait, cancel_futures=True)

    # ------------------------------------------------------------- submit
    def submit(self, key: str, build: Callable[[], Any],
               label: str = "program") -> Future:
        """Schedule ``build()`` under ``key``; duplicate keys return the
        already-scheduled future (one compile per distinct program)."""
        fut = self._futures.get(key)
        if fut is not None:
            return fut
        self._submitted_at[key] = time.perf_counter()
        # jax.default_device is thread-local: a caller running under a
        # device pin (e.g. a CPU-pinned dryrun on a box whose default
        # backend is an accelerator) must not have its build dispatch
        # trace-time constants to a different backend on the worker.
        import jax
        default_device = jax.config.jax_default_device

        def _run():
            t0 = time.perf_counter()
            with jax.default_device(default_device):
                out = build()
            self.tracer.event("background_compile", phase="built", key=key,
                              label=label,
                              compile_s=time.perf_counter() - t0)
            if self.registry is not None:
                self.registry.counter("background_compiles").inc()
            return out

        fut = self._pool.submit(_run)
        self._futures[key] = fut
        return fut

    def succeeded(self) -> list:
        """Keys whose build completed without error (compile accounting)."""
        return [key for key, fut in self._futures.items()
                if fut.done() and not fut.cancelled()
                and fut.exception() is None]

    # --------------------------------------------------------------- get
    def done(self, key: str) -> bool:
        fut = self._futures.get(key)
        return fut is not None and fut.done()

    def get(self, key: str, timeout: Optional[float] = None) -> Any:
        """Block until ``key``'s build finishes and return it. The time
        spent blocked (compile not hidden by compute) is traced so the
        overlap win stays measurable. Re-raises build errors."""
        fut = self._futures[key]
        waited0 = time.perf_counter()
        out = fut.result(timeout=timeout)
        blocked_s = time.perf_counter() - waited0
        self.tracer.event("background_compile", phase="acquired", key=key,
                          blocked_s=blocked_s)
        if self.registry is not None and blocked_s > 1e-3:
            self.registry.counter("background_compile_stall_s").inc(blocked_s)
        return out
