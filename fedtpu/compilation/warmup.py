"""Pre-compile a preset's program family into a cache dir (``fedtpu
warmup``).

Pod-launch / CI use: pay every cold compile once on a toolbox machine
(or in a CI warm stage), ship the cache directory, and the real job
deserializes its executables in milliseconds instead of stalling its
first rounds on XLA. The "program family" is what a job actually
launches: the round program at each requested chunk width plus the eval
program. The same directory also hosts jax's persistent backend cache,
so even a program missing from the AOT store skips the XLA backend
compile.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Dict, Optional, Sequence

from fedtpu.compilation.cache import (ProgramCache, configure_persistent_cache,
                                      program_fingerprint)

__all__ = ["program_config_slice", "warmup_preset"]

# Subdirectory of the user-facing cache dir holding serialized
# executables; the remainder is jax's persistent backend cache.
PROGRAMS_SUBDIR = "programs"


def program_config_slice(cfg) -> Dict[str, Any]:
    """The part of an ``ExperimentConfig`` that shapes the compiled round
    program. Telemetry paths, logging cadence and checkpoint locations
    are deliberately excluded — they vary per run without changing the
    program, and including them would turn every run into a cold miss."""
    return {
        "data": dataclasses.asdict(cfg.data),
        "shard": dataclasses.asdict(cfg.shard),
        "model": dataclasses.asdict(cfg.model),
        "optim": dataclasses.asdict(cfg.optim),
        "fed": dataclasses.asdict(cfg.fed),
        "run": {
            "model_parallel": cfg.run.model_parallel,
            "halt_on_nonfinite": cfg.run.halt_on_nonfinite,
            "pipelined_stop": cfg.run.pipelined_stop,
            "mesh_devices": cfg.run.mesh_devices,
        },
    }


def warmup_preset(
    preset: str = "income-8",
    cache_dir: str = "fedtpu-cache",
    widths: Optional[Sequence[int]] = None,
    synthetic_rows: Optional[int] = None,
    include_eval: bool = True,
    tracer=None,
    registry=None,
) -> dict:
    """Compile (or verify cached) the preset's program family.

    Returns a JSON-serializable report: one row per program with its
    cache key, cold/warm state and build/deserialize seconds, plus the
    cache's aggregate hit/miss stats. Re-running against a populated
    cache is the verification mode: every row comes back ``warm``.
    """
    from fedtpu.config import get_preset
    from fedtpu.orchestration.loop import build_experiment
    from fedtpu.telemetry import build_manifest

    t_begin = time.perf_counter()
    configure_persistent_cache(cache_dir)
    cache = ProgramCache(os.path.join(cache_dir, PROGRAMS_SUBDIR),
                         tracer=tracer, registry=registry)

    cfg = get_preset(preset)
    if synthetic_rows is not None:
        # CI mode: probe compilation, not accuracy — same forcing as
        # ``fedtpu check``.
        cfg = dataclasses.replace(
            cfg, data=dataclasses.replace(cfg.data, csv_path=None,
                                          dataset_name=None,
                                          synthetic_rows=synthetic_rows))
    if widths is None:
        widths = sorted({1, max(1, cfg.run.rounds_per_step)})

    exp = build_experiment(cfg)
    slice_ = program_config_slice(cfg)
    programs = []
    for width in widths:
        step = exp.make_step(int(width))
        key = program_fingerprint(
            "round", config=slice_, mesh=exp.mesh,
            args=(exp.state, exp.batch),
            extra={"rounds_per_step": int(width)})
        entry = cache.get_or_compile(key, step, exp.state, exp.batch,
                                     label=f"round[w={width}]")
        programs.append({"label": f"round[w={width}]", "key": entry.key,
                         "warm": entry.warm,
                         "seconds": round(entry.seconds, 4)})
    if include_eval:
        params = exp.global_fn(exp.state)
        ds = exp.dataset
        key = program_fingerprint(
            "eval", config=slice_, mesh=exp.mesh,
            args=(params, ds.x_test, ds.y_test))
        entry = cache.get_or_compile(key, exp.eval_step, params,
                                     ds.x_test, ds.y_test, label="eval")
        programs.append({"label": "eval", "key": entry.key,
                         "warm": entry.warm,
                         "seconds": round(entry.seconds, 4)})

    report = {
        "preset": preset,
        "cache_dir": os.path.abspath(cache_dir),
        "widths": [int(w) for w in widths],
        "programs": programs,
        "total_s": round(time.perf_counter() - t_begin, 4),
        **cache.stats(),
    }
    if tracer is not None:
        tracer.event("manifest", **build_manifest(
            cfg=cfg, mesh=exp.mesh,
            extra={"program": "warmup", **cache.manifest_info()}))
    return report
