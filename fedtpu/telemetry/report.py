"""Offline aggregation of a telemetry events JSONL (``fedtpu report``).

Reconstructs — from the event log ALONE, no run state needed — the
per-phase time breakdown, round-cadence percentiles, staleness
distribution, and counter/gauge totals, rendered as text, JSON, or a
Prometheus text-exposition snapshot for scraping.

numpy + stdlib only: ``fedtpu report`` must work on a machine with no JAX
backend (the log was produced on a TPU host; the analysis runs anywhere).
Unknown event kinds and newer schema versions degrade to a warning line,
never a crash — logs outlive the code that wrote them.
"""

from __future__ import annotations

import json
from typing import List, Optional, Tuple

import numpy as np

from fedtpu.telemetry.trace import EVENT_SCHEMA_VERSION


def load_events(path: str) -> Tuple[List[dict], int]:
    """Parse a JSONL sink; returns (events, malformed_line_count). A
    truncated final line (crash mid-write) is counted, not fatal."""
    events, bad = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            if isinstance(rec, dict) and "kind" in rec:
                events.append(rec)
            else:
                bad += 1
    return events, bad


def _percentiles(durs: List[float]) -> dict:
    a = np.asarray(durs, dtype=np.float64)
    return {"p50_s": float(np.percentile(a, 50)),
            "p90_s": float(np.percentile(a, 90)),
            "p99_s": float(np.percentile(a, 99)),
            "mean_s": float(a.mean()),
            "max_s": float(a.max())}


def _merge_counts(dicts) -> dict:
    """Sum a stream of {key: count} dicts into one sorted tally."""
    total: dict = {}
    for d in dicts:
        for k, v in d.items():
            total[k] = total.get(k, 0) + int(v)
    return dict(sorted(total.items()))


def aggregate(events: List[dict], malformed: int = 0) -> dict:
    """One pass over the events into the report dict (see module
    docstring). Counter/gauge/histogram totals come from the LAST
    ``counters`` event — each is a full registry snapshot, so the last one
    is the run's final tally."""
    phases: dict = {}
    round_durs: List[float] = []
    round_nums: List[int] = []
    round_max = 0
    stale_means: List[float] = []
    manifest = None
    last_counters = None
    run_ids = []
    identities = []
    newer_schema = 0
    faults: List[dict] = []
    rollbacks: List[dict] = []
    exclusions: List[dict] = []
    restarts: List[dict] = []
    gang_restarts: List[dict] = []
    collective_hangs: List[dict] = []
    child_exits: List[dict] = []
    reshards: List[dict] = []
    reshard_failures: List[dict] = []
    reshard_degraded: List[dict] = []
    preempted_rounds: List[int] = []
    resume_rounds: List[int] = []
    diverged_at: Optional[dict] = None
    supervisor_exit: Optional[dict] = None
    serve_ticks = 0
    serve_start: Optional[dict] = None
    serve_last: Optional[dict] = None
    serve_summary: Optional[dict] = None
    starvation: List[dict] = []
    cohort_rounds = 0
    cohort_last: Optional[dict] = None
    cohort_config: Optional[dict] = None
    cohort_summary: Optional[dict] = None
    cohort_stall_s = 0.0
    autoscale_ticks = 0
    autoscale_kinds: dict = {}
    autoscale_acts: dict = {}
    autoscale_pre_drains: List[dict] = []
    autoscale_summary: Optional[dict] = None
    serve_pre_drains: List[dict] = []
    serve_configures = 0
    screened_events = 0
    screened_updates = 0
    quarantines: List[dict] = []
    net_faults: List[dict] = []
    netproxy_summaries: List[dict] = []
    fuzz_campaigns: List[dict] = []
    fuzz_run: Optional[dict] = None
    for e in events:
        v = e.get("v")
        if isinstance(v, int) and v > EVENT_SCHEMA_VERSION:
            newer_schema += 1
        rid = e.get("run_id")
        if rid and rid not in run_ids:
            run_ids.append(rid)
        # v2 identity keying: a merged fleet report must distinguish
        # sources by (run_id, role, process_index) — gateway sinks
        # restored from one checkpoint lineage (or pinned test runs)
        # legitimately COLLIDE on run_id alone. v1 events key as the
        # (0, 'run') defaults.
        if rid:
            ident = (rid, e.get("role") or "run",
                     int(e.get("process_index") or 0))
            if ident not in identities:
                identities.append(ident)
        kind = e.get("kind")
        payload = e.get("payload") or {}
        if kind == "span" and e.get("phase"):
            p = phases.setdefault(e["phase"],
                                  {"count": 0, "total_s": 0.0, "max_s": 0.0})
            d = float(e.get("dur_s") or 0.0)
            p["count"] += 1
            p["total_s"] += d
            p["max_s"] = max(p["max_s"], d)
        elif kind == "round":
            round_durs.append(float(e.get("dur_s") or 0.0))
            round_nums.append(int(e.get("round") or 0))
            if e.get("round"):
                round_max = max(round_max, int(e["round"]))
            if payload.get("staleness_mean") is not None:
                stale_means.append(float(payload["staleness_mean"]))
        elif kind == "manifest":
            manifest = payload
        elif kind == "counters":
            last_counters = payload
        # Resilience timeline (fedtpu.resilience; docs/resilience.md).
        # Supervisor events and in-run fault/rollback events usually share
        # one sink, so the report sees the whole incident end to end.
        elif kind == "fault":
            faults.append({"round": e.get("round"), **payload})
        elif kind == "rollback":
            rollbacks.append({"round": e.get("round"), **payload})
        elif kind == "exclusion":
            exclusions.append({"round": e.get("round"), **payload})
        elif kind == "restart":
            restarts.append(payload)
        elif kind == "gang_restart":
            gang_restarts.append(payload)
        elif kind == "collective_hang":
            collective_hangs.append({"round": e.get("round"), **payload})
        elif kind == "child_exit":
            child_exits.append(payload)
        # Elastic reshard timeline (fedtpu.resilience.reshard): a
        # completed reshard is a topology change WITHOUT a restart, so it
        # gets its own rows instead of riding gang_restart. The done
        # event's per-leaf plan steps collapse to totals here — the
        # report answers "what moved, how much, when", not "which leaf".
        elif kind == "reshard_done":
            steps = payload.get("steps") or []
            reshards.append({
                "round": e.get("round"),
                "mode": payload.get("mode"),
                "target_clients": payload.get("target"),
                "moved_leaves": len(steps),
                "moved_bytes": sum(int(s.get("nbytes") or 0)
                                   for s in steps),
                "join_rows": sum(int(s.get("join_rows") or 0)
                                 for s in steps)})
        elif kind == "reshard_failed":
            reshard_failures.append({"round": e.get("round"), **payload})
        elif kind == "reshard_degraded":
            reshard_degraded.append({"round": e.get("round"), **payload})
        elif kind == "preempted":
            preempted_rounds.append(int(e.get("round") or 0))
        elif kind == "resume":
            resume_rounds.append(int(e.get("round") or 0))
        elif kind == "diverged":
            diverged_at = {"round": e.get("round"), **payload}
        elif kind == "supervisor_exit":
            supervisor_exit = payload
        # Serving timeline (fedtpu.serving; docs/serving.md). The drain
        # summary carries the authoritative SLO numbers (admission
        # counts, update-to-incorporation percentiles, rounds/sec);
        # per-tick events supply the cadence when a run died pre-drain.
        elif kind == "serve_start":
            # LAST start wins: a supervised restart re-emits it, and the
            # current launch's identity (gateway index, generation) is
            # the one the merged fleet view should group by.
            serve_start = dict(payload)
        elif kind == "serve_tick":
            serve_ticks += 1
            serve_last = {"tick": e.get("round"), **payload}
        elif kind == "serve_summary":
            serve_summary = {"tick": e.get("round"), **payload}
        elif kind == "async_starvation":
            starvation.append({"round": e.get("round"), **payload})
        # Defense timeline (fedtpu.robust; docs/robustness.md): one
        # serve_screened event per tick that screened anything, one
        # serve_quarantine event per quarantined user id.
        elif kind == "serve_screened":
            screened_events += 1
            screened_updates += int(payload.get("n_screened") or 0)
        elif kind == "serve_quarantine":
            quarantines.append({"tick": e.get("round"), **payload})
        # Cohort timeline (fedtpu.cohort; docs/scaling.md). The summary
        # carries the end-of-run store footprint; per-round events supply
        # the cadence and resident-bytes trajectory when a run died early.
        elif kind == "cohort_config":
            cohort_config = payload
        elif kind == "cohort_round":
            cohort_rounds += 1
            cohort_last = {"round": e.get("round"), **payload}
            cohort_stall_s += float(payload.get("prefetch_stall_s") or 0.0)
        elif kind == "cohort_summary":
            cohort_summary = payload
        # Autoscale timeline (fedtpu.autoscale; docs/autoscale.md). One
        # decision event per control tick; act events record what the
        # controller actually did to the deployment.
        elif kind == "autoscale_decision":
            autoscale_ticks += 1
            for d in payload.get("decisions") or []:
                dk = d.get("kind")
                autoscale_kinds[dk] = autoscale_kinds.get(dk, 0) + 1
        elif kind == "autoscale_act":
            ak = payload.get("decision")
            autoscale_acts[ak] = autoscale_acts.get(ak, 0) + 1
        elif kind == "autoscale_pre_drain":
            autoscale_pre_drains.append(payload)
        elif kind == "autoscale_summary":
            autoscale_summary = payload
        elif kind == "serve_pre_drain":
            serve_pre_drains.append({"tick": e.get("round"), **payload})
        elif kind == "serve_configure":
            serve_configures += 1
        # Network timeline (fedtpu.serving.netproxy; docs/resilience.md):
        # one net_fault event per fired wire fault, one netproxy_summary
        # per proxied gateway at drain.
        elif kind == "net_fault":
            net_faults.append(payload)
        elif kind == "netproxy_summary":
            netproxy_summaries.append(payload)
        # Fuzz timeline (fedtpu.resilience.fuzz; docs/resilience.md):
        # one fuzz_campaign event per replayed campaign, one fuzz_run
        # summary at the end of the sweep.
        elif kind == "fuzz_campaign":
            fuzz_campaigns.append(payload)
        elif kind == "fuzz_run":
            fuzz_run = payload

    out: dict = {
        "events_total": len(events),
        "malformed_lines": malformed,
        "newer_schema_events": newer_schema,
        "run_ids": run_ids,
        "identities": [{"run_id": r, "role": ro, "process_index": p}
                       for r, ro, p in sorted(identities,
                                              key=lambda i: (i[1], i[2],
                                                             i[0]))],
        "manifest": None,
        "phases": {k: {**v, "mean_s": v["total_s"] / v["count"]}
                   for k, v in sorted(phases.items())},
        "rounds": {"count": len(round_durs), "last_round": round_max},
        "staleness": None,
        "counters": {}, "gauges": {}, "histograms": {},
        "resilience": None,
        "network": None,
        "serving": None,
        "cohort": None,
        "autoscale": None,
        "static_analysis": None,
        "fuzz": None,
    }
    if fuzz_campaigns or fuzz_run:
        violations = [c for c in fuzz_campaigns if not c.get("ok")]
        # Which oracle tripped, how often — the violation histogram is
        # the fuzzer's headline (what KIND of bug the space holds).
        oracle_hits: dict = {}
        for c in violations:
            for o in c.get("failed") or []:
                oracle_hits[o] = oracle_hits.get(o, 0) + 1
        out["fuzz"] = {
            "campaigns": len(fuzz_campaigns),
            "passed": sum(1 for c in fuzz_campaigns if c.get("ok")),
            "violations": [
                {"name": c.get("name"), "digest": c.get("digest"),
                 "failed": c.get("failed"),
                 "shrunk_entries": c.get("shrunk_entries"),
                 "reproducer": c.get("reproducer")}
                for c in violations],
            "failed_oracles": dict(sorted(oracle_hits.items())),
            "fired": _merge_counts(c.get("fired") or {}
                                   for c in fuzz_campaigns),
            "summary": fuzz_run,
        }
    if (autoscale_ticks or autoscale_acts or autoscale_summary
            or autoscale_pre_drains or serve_pre_drains or serve_configures):
        out["autoscale"] = {
            "control_ticks": autoscale_ticks,
            "decisions": dict(sorted(autoscale_kinds.items())),
            "acted": dict(sorted(autoscale_acts.items())),
            "pre_drains": autoscale_pre_drains,
            "serve_pre_drains": serve_pre_drains,
            "serve_configures": serve_configures,
            "summary": autoscale_summary,
        }
    if serve_ticks or serve_summary or starvation or serve_start:
        out["serving"] = {
            "ticks": serve_ticks,
            "start": serve_start,
            "last_tick": serve_last,
            "summary": serve_summary,
            "starvation": starvation,
        }
        if screened_events or quarantines:
            out["serving"]["defense"] = {
                "screened_ticks": screened_events,
                "screened_updates": screened_updates,
                "quarantines": quarantines,
                "quarantined_users": sorted(
                    {int(q["user"]) for q in quarantines
                     if q.get("user") is not None}),
            }
    if cohort_rounds or cohort_config or cohort_summary:
        out["cohort"] = {
            "rounds": cohort_rounds,
            "config": cohort_config,
            "last_round": cohort_last,
            "summary": cohort_summary,
            "prefetch_stall_s_total": cohort_stall_s,
        }
    if manifest:
        out["manifest"] = {k: manifest.get(k) for k in
                           ("config_hash", "package_version", "jax_version",
                            "backend", "device_count", "device_kinds",
                            "mesh_shape", "git_rev", "process_count",
                            "program", "engine", "restarts", "fault_plan")
                           if manifest.get(k) is not None}
        # The run's program-audit stamp (orchestration/loop.py manifest
        # wiring): schedule digest + comm bytes of the width-1 round.
        if manifest.get("audit"):
            out["static_analysis"] = manifest["audit"]
        # MPMD DAG shape (run.mpmd): which sub-programs ran at what
        # chunk width — the report's key for reading the per-sub-program
        # trace spans against the right schedule.
        if manifest.get("mpmd"):
            out["manifest"]["mpmd"] = manifest["mpmd"]
    # Device-time attribution (docs/observability.md): join the
    # manifest's static XLA cost model (flops / bytes accessed of the
    # width-1 round, orchestration/loop.py manifest wiring) with the
    # measured per-round durations into per-round MFU / roofline rows.
    # Without a hardware peak (FEDTPU_PEAK_FLOPS at run time) the rows
    # still carry achieved FLOP/s and arithmetic intensity — just no
    # MFU ratio. Pinned reference numbers live in benchmarks/RESULTS.md.
    prof = (manifest or {}).get("profile")
    if prof and not prof.get("error"):
        flops = float(prof.get("flops_per_round") or 0.0)
        bytes_rw = float(prof.get("bytes_per_round") or 0.0)
        peak = prof.get("peak_flops")
        rows = []
        if flops > 0:
            for rnd, d in zip(round_nums, round_durs):
                if d <= 0:
                    continue
                row = {"round": rnd, "dur_s": d,
                       "achieved_flops_per_s": flops / d}
                if peak:
                    row["mfu"] = flops / d / float(peak)
                rows.append(row)
        out["profile"] = {
            "flops_per_round": flops,
            "bytes_per_round": bytes_rw,
            "arithmetic_intensity": (flops / bytes_rw if bytes_rw
                                     else None),
            "peak_flops": (float(peak) if peak else None),
            "profile_rounds": prof.get("profile_rounds"),
            "rounds": rows,
        }
        if rows:
            ach = np.asarray([r["achieved_flops_per_s"] for r in rows])
            out["profile"]["achieved_flops_per_s"] = {
                "mean": float(ach.mean()), "max": float(ach.max())}
            if peak:
                out["profile"]["mfu"] = {
                    "mean": float(ach.mean() / float(peak)),
                    "max": float(ach.max() / float(peak))}
    if (faults or rollbacks or exclusions or restarts or gang_restarts
            or collective_hangs or child_exits or preempted_rounds
            or resume_rounds or diverged_at or supervisor_exit
            or reshards or reshard_failures or reshard_degraded):
        out["resilience"] = {
            "faults": faults,
            "rollbacks": rollbacks,
            "exclusions": exclusions,
            "restarts": len(restarts),
            "gang_restarts": len(gang_restarts),
            "collective_hangs": collective_hangs,
            "child_exit_codes": [c.get("rc") for c in child_exits],
            "reshards": reshards,
            "reshard_failures": reshard_failures,
            "reshard_degraded": reshard_degraded,
            "preempted_rounds": preempted_rounds,
            "resume_rounds": resume_rounds,
            "diverged": diverged_at,
            "supervisor_exit": supervisor_exit,
        }
    if round_durs:
        out["rounds"]["total_s"] = float(np.sum(round_durs))
        out["rounds"]["cadence"] = _percentiles(round_durs)
    if last_counters:
        out["counters"] = dict(last_counters.get("counters") or {})
        out["gauges"] = dict(last_counters.get("gauges") or {})
        out["histograms"] = dict(last_counters.get("histograms") or {})
    # Built AFTER the counters fold so the wire-fault view can sit next
    # to the server-side counters the faults are supposed to move
    # (redirects followed, duplicate frames dropped, oversized lines).
    if net_faults or netproxy_summaries:
        per_gateway: dict = {}
        for f in net_faults:
            g = int(f.get("gateway") or 0)
            row = per_gateway.setdefault(g, {})
            k = f.get("fault") or "unknown"
            row[k] = row.get(k, 0) + 1
        out["network"] = {
            "faults": len(net_faults),
            "per_gateway": {g: dict(sorted(v.items()))
                            for g, v in sorted(per_gateway.items())},
            "proxies": [
                {k: s.get(k) for k in ("gateway", "digest", "connections",
                                       "frames", "relayed_frames",
                                       "frame_bytes", "fired")}
                for s in netproxy_summaries],
            "redirects": out["counters"].get("gateway_redirects"),
            "duplicate_drops": out["counters"].get("serve_duplicate_drop"),
            "oversized_lines": out["counters"].get("serve_oversized_lines"),
        }
    hist = out["histograms"].get("staleness")
    if hist or stale_means:
        out["staleness"] = {
            **({"count": hist["count"], "mean": hist["mean"],
                "min": hist["min"], "max": hist["max"],
                "bins": hist["bins"],
                "bucket_counts": hist["bucket_counts"]} if hist else {}),
            **({"round_mean_of_means": float(np.mean(stale_means))}
               if stale_means else {}),
        }
    return out


def render_text(agg: dict) -> str:
    lines = ["fedtpu telemetry report",
             f"  events: {agg['events_total']}"
             + (f" ({agg['malformed_lines']} malformed lines skipped)"
                if agg["malformed_lines"] else "")]
    if agg.get("newer_schema_events"):
        lines.append(f"  warning: {agg['newer_schema_events']} events carry "
                     f"a schema newer than v{EVENT_SCHEMA_VERSION} — "
                     "fields this reader doesn't know are ignored")
    if agg.get("run_ids"):
        lines.append(f"  run_id: {', '.join(agg['run_ids'])}")
    idents = agg.get("identities") or []
    if len(idents) > 1:
        # More sources than run_ids == the v2 identity did its job:
        # same-run_id sinks split by (role, process_index).
        lines.append("  sources: " + ", ".join(
            f"{i['role']}/p{i['process_index']}" for i in idents))
    man = agg.get("manifest")
    if man:
        lines.append("  manifest: " + ", ".join(
            f"{k}={man[k]}" for k in sorted(man)))
    sa = agg.get("static_analysis")
    if sa:
        if "error" in sa:
            lines.append(f"static analysis: audit failed ({sa['error']})")
        else:
            lines.append(
                f"static analysis: engine={sa.get('engine')} "
                f"schedule={sa.get('schedule_digest')} "
                f"collectives={sa.get('collectives')} "
                f"comm={sa.get('comm_bytes_per_round')}B/round "
                f"findings={sa.get('findings')}")
    ph = agg.get("phases") or {}
    if ph:
        lines.append("phase breakdown:")
        width = max(len(k) for k in ph)
        for k, v in sorted(ph.items(), key=lambda kv: -kv[1]["total_s"]):
            lines.append(f"  {k:<{width}}  total {v['total_s']:9.3f} s  "
                         f"x{v['count']:<5d} mean {v['mean_s']:.4f} s  "
                         f"max {v['max_s']:.4f} s")
    prof = agg.get("profile")
    if prof:
        lines.append("device-time attribution:")
        ai = prof.get("arithmetic_intensity")
        lines.append(f"  cost model: {prof['flops_per_round']:.3e} "
                     f"FLOPs/round, {prof['bytes_per_round']:.3e} B/round"
                     + (f", intensity {ai:.2f} FLOP/B" if ai else ""))
        if prof.get("peak_flops"):
            lines.append(f"  peak: {prof['peak_flops']:.3e} FLOP/s")
        mfu = prof.get("mfu")
        ach = prof.get("achieved_flops_per_s")
        if ach:
            lines.append(f"  achieved: mean {ach['mean']:.3e} FLOP/s, "
                         f"max {ach['max']:.3e} FLOP/s"
                         + (f"  (MFU mean {mfu['mean'] * 100:.2f}%, "
                            f"max {mfu['max'] * 100:.2f}%)" if mfu else ""))
        rows = prof.get("rounds") or []
        for r in rows[:8]:
            lines.append(f"    round {r['round']}: {r['dur_s']:.4f} s, "
                         f"{r['achieved_flops_per_s']:.3e} FLOP/s"
                         + (f", MFU {r['mfu'] * 100:.2f}%"
                            if r.get("mfu") is not None else ""))
        if len(rows) > 8:
            lines.append(f"    ... {len(rows) - 8} more round(s)")
    rounds = agg.get("rounds") or {}
    if rounds.get("count"):
        c = rounds.get("cadence") or {}
        lines.append(f"rounds: {rounds['count']} "
                     f"(last round {rounds.get('last_round')}, "
                     f"total {rounds.get('total_s', 0.0):.3f} s)")
        if c:
            lines.append(f"  cadence p50 {c['p50_s']:.4f} s  "
                         f"p90 {c['p90_s']:.4f} s  p99 {c['p99_s']:.4f} s  "
                         f"mean {c['mean_s']:.4f} s  max {c['max_s']:.4f} s")
    st = agg.get("staleness")
    if st:
        if st.get("count"):
            lines.append(f"staleness: {st['count']} observations, "
                         f"mean {st['mean']:.3f}, min {st['min']:.0f}, "
                         f"max {st['max']:.0f}")
            lines.append("  histogram (<= bound: count): " + ", ".join(
                f"{b:g}: {n}" for b, n in zip(st["bins"],
                                              st["bucket_counts"])))
        elif st.get("round_mean_of_means") is not None:
            lines.append(f"staleness: mean-of-round-means "
                         f"{st['round_mean_of_means']:.3f}")
    res = agg.get("resilience")
    if res:
        lines.append("resilience:")
        for f in res.get("faults") or []:
            detail = ", ".join(f"{k}={f[k]}" for k in sorted(f)
                               if k not in ("fault", "fault_round", "round"))
            lines.append(f"  fault {f.get('fault')} @ round {f.get('round')}"
                         + (f" ({detail})" if detail else ""))
        for rb in res.get("rollbacks") or []:
            lines.append(f"  rollback @ round {rb.get('round')} -> "
                         f"restored round {rb.get('restored_round')} "
                         f"(attempt {rb.get('attempt')}, "
                         f"reason: {rb.get('reason')})")
        for ex in res.get("exclusions") or []:
            lines.append(f"  excluded clients {ex.get('clients')} "
                         f"@ round {ex.get('round')}")
        for ch in res.get("collective_hangs") or []:
            lines.append(f"  COLLECTIVE HANG @ round {ch.get('round')}: "
                         f"process {ch.get('process')} stuck in "
                         f"{ch.get('phase')} for {ch.get('waited_s')} s "
                         f"(timeout {ch.get('timeout_s')} s) -> exit 75")
        for rs in res.get("reshards") or []:
            mb = (rs.get("moved_bytes") or 0) / 2**20
            lines.append(f"  reshard {rs.get('mode')} @ round "
                         f"{rs.get('round')} -> "
                         f"{rs.get('target_clients')} client(s): "
                         f"{rs.get('moved_leaves')} leaves, "
                         f"~{mb:.2f} MiB placed, "
                         f"{rs.get('join_rows')} join row(s), no restart")
        for rf in res.get("reshard_failures") or []:
            lines.append(f"  RESHARD FAILED @ round {rf.get('round')}: "
                         f"{rf.get('error')} -> gang-restart fallback")
        for rd in res.get("reshard_degraded") or []:
            lines.append(f"  reshard degraded to checkpoint drain @ round "
                         f"{rd.get('round')} (config cannot live-reshard)")
        if res.get("restarts"):
            lines.append(f"  supervisor restarts: {res['restarts']} "
                         f"(child exit codes: "
                         f"{res.get('child_exit_codes')})")
        if res.get("gang_restarts"):
            lines.append(f"  gang restarts: {res['gang_restarts']} "
                         f"(child exit codes: "
                         f"{res.get('child_exit_codes')})")
        if res.get("preempted_rounds"):
            lines.append("  preempted (graceful drain) at rounds: "
                         f"{res['preempted_rounds']}")
        if res.get("resume_rounds"):
            lines.append(f"  resumed at rounds: {res['resume_rounds']}")
        if res.get("diverged"):
            d = res["diverged"]
            lines.append(f"  DIVERGED @ round {d.get('round')}: "
                         f"{d.get('reason')}")
        if res.get("supervisor_exit"):
            se = res["supervisor_exit"]
            lines.append(f"  supervisor exit: rc={se.get('rc')} "
                         f"reason={se.get('reason')}")
    hbs = agg.get("heartbeats")
    if hbs:
        if not res:
            lines.append("resilience:")
        for hb in hbs:
            lines.append(f"  heartbeat p{hb.get('process')}: "
                         f"{hb.get('status')}")
    net = agg.get("network")
    if net:
        lines.append("network (wire faults):")
        for g, kinds in sorted((net.get("per_gateway") or {}).items()):
            detail = ", ".join(f"{k}={v}" for k, v in sorted(kinds.items()))
            lines.append(f"  gateway {g}: {detail}")
        for p in net.get("proxies") or []:
            # connections - 1 = reconnects forced onto this gateway's
            # clients; frames - relayed_frames = frames the wire ate.
            lines.append(
                f"  proxy g{p.get('gateway')} [{p.get('digest')}]: "
                f"{p.get('connections')} conn(s), "
                f"{p.get('frames')} frame(s) "
                f"({p.get('relayed_frames')} relayed, "
                f"{p.get('frame_bytes')} B)")
        for key in ("redirects", "duplicate_drops", "oversized_lines"):
            if net.get(key) is not None:
                lines.append(f"  {key}: {net[key]:g}")
    fz = agg.get("fuzz")
    if fz:
        lines.append("fuzz (compositional chaos campaigns):")
        lines.append(f"  campaigns: {fz.get('campaigns')} "
                     f"({fz.get('passed')} passed all oracles)")
        fired = ", ".join(f"{k}={v}" for k, v in
                          sorted((fz.get("fired") or {}).items()))
        if fired:
            lines.append(f"  faults fired: {fired}")
        oh = fz.get("failed_oracles") or {}
        if oh:
            lines.append("  failed oracles: " + ", ".join(
                f"{k}={v}" for k, v in sorted(oh.items())))
        for v in fz.get("violations") or []:
            tail = (f" -> {v['shrunk_entries']}-entry reproducer"
                    if v.get("shrunk_entries") is not None else "")
            lines.append(f"  VIOLATION {v.get('name')} "
                         f"[{v.get('digest')}]: "
                         f"{', '.join(v.get('failed') or [])}{tail}")
            if v.get("reproducer"):
                lines.append(f"    committed: {v['reproducer']}")
    srv = agg.get("serving")
    if srv:
        lines.append("serving:")
        summ = srv.get("summary") or srv.get("last_tick") or {}
        if srv.get("ticks") or summ.get("ticks"):
            lines.append(f"  ticks: {summ.get('ticks', srv['ticks'])} "
                         f"(incorporated {summ.get('incorporated', '?')} "
                         f"update(s), version {summ.get('version', '?')})")
        adm = summ.get("admission")
        if adm:
            lines.append("  admission: " + ", ".join(
                f"{k}={adm[k]:g}" for k in sorted(adm)))
        lat = summ.get("update_to_incorporation")
        if lat:
            lines.append(f"  update_to_incorporation p50 {lat['p50_s']:.4f} s"
                         f"  p90 {lat['p90_s']:.4f} s  "
                         f"p99 {lat['p99_s']:.4f} s  "
                         f"mean {lat['mean_s']:.4f} s  "
                         f"max {lat['max_s']:.4f} s")
        if summ.get("rounds_per_sec") is not None:
            lines.append(f"  rounds/sec under load: "
                         f"{summ['rounds_per_sec']:.2f} "
                         f"({summ.get('wall_s', 0.0):.2f} s wall)")
        for sv in srv.get("starvation") or []:
            lines.append(f"  K-BUFFER STARVATION @ tick {sv.get('round')}: "
                         f"{sv.get('pending')} buffered update(s) never "
                         f"reached buffer_size {sv.get('buffer_size')}")
        defense = srv.get("defense")
        if defense:
            lines.append(f"  defense: {defense['screened_updates']} "
                         f"screened update(s) over "
                         f"{defense['screened_ticks']} tick(s), "
                         f"{len(defense['quarantined_users'])} user(s) "
                         f"quarantined")
            for q in defense.get("quarantines") or []:
                lines.append(f"    QUARANTINED user {q.get('user')} @ tick "
                             f"{q.get('tick')} (t {q.get('t_virtual')}, "
                             f"{q.get('strikes')} strike(s))")
    coh = agg.get("cohort")
    if coh:
        lines.append("cohort:")
        conf = coh.get("config") or {}
        if conf:
            lines.append(f"  config: cohort_size {conf.get('cohort_size')} "
                         f"of {conf.get('total_clients')} clients, "
                         f"store {conf.get('store')}, "
                         f"sampling {conf.get('sampling')}, "
                         f"{conf.get('cohorts_per_step')} cohort(s)/step")
        summ = coh.get("summary") or coh.get("last_round") or {}
        if coh.get("rounds") or summ.get("rounds"):
            lines.append(f"  rounds: {summ.get('rounds', coh['rounds'])} "
                         f"(touched {summ.get('touched_records', '?')} "
                         f"client record(s))")
        if summ.get("store_resident_bytes") is not None:
            res_mb = summ["store_resident_bytes"] / 2**20
            app_mb = (summ.get("store_apparent_bytes")
                      or conf.get("store_apparent_bytes") or 0) / 2**20
            lines.append(f"  store: resident ~{res_mb:.1f} MiB "
                         f"(apparent {app_mb:.1f} MiB)")
        if coh.get("prefetch_stall_s_total") or summ.get("prefetch_stalls"):
            lines.append(f"  prefetch: {summ.get('prefetch_stalls', '?')} "
                         f"stall(s), "
                         f"{coh.get('prefetch_stall_s_total', 0.0):.3f} s "
                         "stalled total")
    asc = agg.get("autoscale")
    if asc:
        lines.append("autoscale:")
        dec = ", ".join(f"{k}={v}" for k, v in
                        sorted((asc.get("decisions") or {}).items()))
        lines.append(f"  control ticks: {asc.get('control_ticks')}"
                     + (f" ({dec})" if dec else ""))
        act = ", ".join(f"{k}={v}" for k, v in
                        sorted((asc.get("acted") or {}).items()))
        if act:
            lines.append(f"  acted: {act}")
        for pd in asc.get("pre_drains") or []:
            lines.append(f"  pre-drain victim p{pd.get('victim')}: "
                         f"{pd.get('spooled')} update(s) spooled "
                         f"-> {pd.get('path')}")
        for pd in asc.get("serve_pre_drains") or []:
            lines.append(f"  server spool @ tick {pd.get('tick')}: "
                         f"{pd.get('spooled')} update(s) -> "
                         f"{pd.get('path')}")
        if asc.get("serve_configures"):
            lines.append(f"  server reconfigures: "
                         f"{asc['serve_configures']}")
        summ = asc.get("summary")
        if summ:
            lines.append("  summary: " + ", ".join(
                f"{k}={summ[k]}" for k in sorted(summ)
                if not isinstance(summ[k], (dict, list))))
    fleet = agg.get("gateway_fleet")
    if fleet:
        lines.append("gateway fleet (merged):")
        lines.append("  gateways: " + ", ".join(
            str(g) for g in fleet["gateways"]))
        if fleet.get("admission"):
            lines.append("  admission: " + ", ".join(
                f"{k}={fleet['admission'][k]:g}"
                for k in sorted(fleet["admission"])))
        lines.append(f"  incorporated: {fleet['incorporated']}")
        lines.append(f"  duplicate_drops: {fleet['duplicate_drops']}")
        if fleet.get("slo_burn_max") is not None:
            lines.append(f"  slo_burn (worst member): "
                         f"{fleet['slo_burn_max']:.3f}")
    srcs = agg.get("sources")
    if srcs:
        lines.append("per-source view:")
        for s in srcs:
            tag = (f" [gateway {s['gateway']}]"
                   if s.get("gateway") is not None
                   else f" [{s['role']}]"
                   if s.get("role") and s["role"] != "run" else "")
            lines.append(f"  {s['path']}{tag}: {s['events']} event(s)")
            adm = s.get("admission")
            if adm:
                lines.append("    admission: " + ", ".join(
                    f"{k}={adm[k]:g}" for k in sorted(adm)))
            lat = s.get("update_to_incorporation")
            if lat:
                lines.append(f"    update_to_incorporation "
                             f"p50 {lat['p50_s']:.4f} s  "
                             f"p99 {lat['p99_s']:.4f} s")
            if s.get("slo_burn") is not None:
                lines.append(f"    slo_burn: {s['slo_burn']:.3f}")
    if agg.get("counters"):
        lines.append("counters:")
        for k, v in sorted(agg["counters"].items()):
            lines.append(f"  {k} = {v:g}")
    if agg.get("gauges"):
        lines.append("gauges:")
        for k, v in sorted(agg["gauges"].items()):
            lines.append(f"  {k} = {v:g}")
    return "\n".join(lines)


def _prom_name(name: str) -> str:
    return "fedtpu_" + "".join(c if c.isalnum() or c == "_" else "_"
                               for c in name)


def render_prometheus(agg: dict) -> str:
    """Prometheus text-exposition snapshot of the aggregated log — a file
    a textfile-collector / pushgateway setup can scrape as-is."""
    lines: List[str] = []

    def emit(name, value, typ, labels=""):
        n = _prom_name(name)
        lines.append(f"# TYPE {n} {typ}")
        lines.append(f"{n}{labels} {value:g}")

    for k, v in sorted((agg.get("counters") or {}).items()):
        emit(k + "_total", v, "counter")
    for k, v in sorted((agg.get("gauges") or {}).items()):
        emit(k, v, "gauge")
    for k, v in sorted((agg.get("phases") or {}).items()):
        emit(f"phase_{k}_seconds_total", v["total_s"], "counter")
        emit(f"phase_{k}_spans_total", v["count"], "counter")
    cadence = (agg.get("rounds") or {}).get("cadence")
    if cadence:
        for q, key in (("0.5", "p50_s"), ("0.9", "p90_s"),
                       ("0.99", "p99_s")):
            n = _prom_name("round_duration_seconds")
            lines.append(f'{n}{{quantile="{q}"}} {cadence[key]:g}')
    # Serving SLO quantiles from the drain summary (the exact-percentile
    # view; the cumulative-bucket histogram below is the scrapeable one).
    srv_lat = ((agg.get("serving") or {}).get("summary")
               or {}).get("update_to_incorporation")
    if srv_lat:
        n = _prom_name("update_to_incorporation_seconds")
        for q, key in (("0.5", "p50_s"), ("0.9", "p90_s"),
                       ("0.99", "p99_s")):
            lines.append(f'{n}{{quantile="{q}"}} {srv_lat[key]:g}')
    # Defense section (fedtpu.robust; docs/robustness.md): screening +
    # quarantine census. These lived only in the text report before —
    # a scrape-driven alert ("quarantines > 0") needs them here.
    defense = (agg.get("serving") or {}).get("defense")
    if defense:
        emit("screened_updates_total",
             defense.get("screened_updates") or 0, "counter")
        emit("quarantined_users",
             len(defense.get("quarantined_users") or []), "gauge")
    # Network section (fedtpu.serving.netproxy): per-gateway wire-fault
    # firing counts, labeled like the merged fleet view groups them.
    net = agg.get("network")
    if net and net.get("per_gateway"):
        n = _prom_name("net_faults_fired_total")
        lines.append(f"# TYPE {n} counter")
        for g, kinds in sorted(net["per_gateway"].items()):
            lines.append(f'{n}{{gateway="{g}"}} '
                         f'{sum(kinds.values()):g}')
    # Device-time attribution: the roofline numbers as gauges, so a
    # dashboard can trend MFU across runs.
    prof = agg.get("profile")
    if prof:
        emit("model_flops_per_round", prof.get("flops_per_round") or 0,
             "gauge")
        if prof.get("mfu"):
            emit("mfu_mean", prof["mfu"]["mean"], "gauge")
            emit("mfu_max", prof["mfu"]["max"], "gauge")
    for name, h in sorted((agg.get("histograms") or {}).items()):
        n = _prom_name(name)
        lines.append(f"# TYPE {n} histogram")
        for b, c in zip(h["bins"], h["bucket_counts"]):
            lines.append(f'{n}_bucket{{le="{b:g}"}} {c}')
        lines.append(f'{n}_bucket{{le="+Inf"}} {h["count"]}')
        lines.append(f"{n}_sum {h['sum']:g}")
        lines.append(f"{n}_count {h['count']}")
    return "\n".join(lines) + "\n"


def _source_view(path: str, events: List[dict], bad: int) -> dict:
    """The per-source admission/SLO slice of one log — what the merged
    report shows next to the combined numbers. Gateway sources (a
    ``serve_start`` carrying a fleet index) additionally expose the
    identity + dedup/incorporation totals the merged fleet view sums."""
    agg = aggregate(events, malformed=bad)
    srv = agg.get("serving") or {}
    summ = srv.get("summary") or srv.get("last_tick") or {}
    signals = summ.get("signals") or {}
    start = srv.get("start") or {}
    # Gateway identity: the serve_start payload when the run got that
    # far, else the v2 role stamp ('gateway-<i>') any event carries —
    # a member that crashed pre-start (or whose run_id collides with a
    # sibling's) still lands in the right fleet slot.
    gateway = start.get("gateway")
    role = None
    process_index = None
    for e in events:
        if role is None and e.get("role"):
            role = e["role"]
            process_index = int(e.get("process_index") or 0)
        if gateway is None and str(e.get("role") or "").startswith(
                "gateway-"):
            try:
                gateway = int(str(e["role"]).rsplit("-", 1)[1])
            except ValueError:
                pass
        if role is not None and gateway is not None:
            break
    return {"path": path, "events": len(events),
            "gateway": gateway,
            "role": role or "run",
            "process_index": process_index or 0,
            "admission": summ.get("admission"),
            "incorporated": summ.get("incorporated"),
            "duplicate_drops": summ.get("duplicate_drops"),
            "update_to_incorporation": summ.get("update_to_incorporation"),
            "slo_burn": signals.get("slo_burn")}


def _fleet_view(sources: List[dict]) -> dict:
    """The merged admission/SLO view over >= 2 gateway sources: summed
    admission counts, incorporation and dedup totals, and the WORST
    member's SLO burn (a fleet meets its objective only if every shard
    does)."""
    admission: dict = {}
    for s in sources:
        for k, v in (s.get("admission") or {}).items():
            admission[k] = admission.get(k, 0) + int(v)
    burns = [s["slo_burn"] for s in sources
             if s.get("slo_burn") is not None]
    return {
        "gateways": sorted(int(s["gateway"]) for s in sources),
        "admission": admission,
        "incorporated": sum(int(s.get("incorporated") or 0)
                            for s in sources),
        "duplicate_drops": sum(int(s.get("duplicate_drops") or 0)
                               for s in sources),
        "slo_burn_max": max(burns) if burns else None,
    }


def render_report(path, fmt: str = "text",
                  heartbeat: Optional[str] = None,
                  process_count: int = 0) -> Tuple[str, str]:
    """CLI entry: returns (rendered report in ``fmt``, Prometheus text).
    Both derive from one aggregation pass over the log.

    ``path`` may be one JSONL path or a list of them — multiple sinks
    (a serve log + a gang log + a controller log) merge into one
    combined aggregation plus a per-source admission/SLO view.
    ``heartbeat`` + ``process_count`` add live supervisor heartbeat
    status rows (serving/parked/stale/missing) to the resilience
    section.
    """
    paths = [path] if isinstance(path, str) else list(path)
    per_source = []
    events: List[dict] = []
    bad = 0
    for p in paths:
        ev, b = load_events(p)
        per_source.append((p, ev, b))
        events.extend(ev)
        bad += b
    agg = aggregate(events, malformed=bad)
    if len(paths) > 1:
        agg["sources"] = [_source_view(p, ev, b)
                          for p, ev, b in per_source]
        fleet = [s for s in agg["sources"]
                 if s.get("gateway") is not None]
        if len(fleet) >= 2:
            agg["gateway_fleet"] = _fleet_view(fleet)
    if heartbeat:
        from fedtpu.autoscale.signals import read_gang_members
        agg["heartbeats"] = [
            {"process": idx, "status": status}
            for idx, status in read_gang_members(
                heartbeat, max(1, process_count))]
    if fmt == "json":
        rendered = json.dumps(agg, indent=2, sort_keys=True)
    else:
        rendered = render_text(agg)
    return rendered, render_prometheus(agg)
