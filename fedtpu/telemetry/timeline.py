"""Causal fleet timeline (``fedtpu timeline``) — ISSUE 16.

Merges N heterogeneous observability artifacts into ONE ordered fleet
view:

    * events JSONL sinks (schema v1/v2; ``fedtpu.telemetry.trace``) —
      the run loop, the serving engines, the gateway fleet, the
      supervisor; v2 lines carry the ``(process_index, role)`` identity
      this merger keys on, v1 lines read with the (0, 'run') defaults;
    * netproxy decision logs (``*.netlog``;
      ``fedtpu.serving.netproxy``) — schedule header, one line per
      fired wire fault in firing order, summary;
    * autoscale decision logs (``fedtpu.autoscale.policy
      .decision_line`` canonical JSONL) — one line per control tick.

Two renderers:

    * **deterministic JSONL** (:func:`deterministic_lines`) — every
      wall-clock / process-identity accident (``t_start``, ``dur_s``,
      ``pid``, ``run_id``, ``launch_id``) stripped, payloads reduced to
      the :data:`PAYLOAD_WHITELIST` of virtual-time-deterministic
      fields, sources emitted in sorted-label order, and one ``chain``
      row per ``trace_id`` giving the update's causal stage sequence
      (client_stamp -> wal -> admit -> buffer_insert -> incorporate,
      with dedup_drop on the retry path). Canonical ``json.dumps``
      (sorted keys, no whitespace) so byte comparison IS the replay
      check — ``fedtpu check --timeline-sim`` gates a pinned
      two-gateway campaign against ``tests/goldens/timeline_sim.jsonl``
      this way (see :mod:`fedtpu.telemetry.timeline_sim`).

    * **Chrome trace JSON** (:func:`chrome_trace`) — load in Perfetto
      or ``chrome://tracing``. One trace pid per source, spans as
      complete ('X') events on the wall clock, instants for everything
      else, and flow arrows stitching each trace_id's stages across
      processes.

stdlib-only (not even numpy): like ``fedtpu report``, the timeline of a
TPU run must render on a laptop with no backend.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from fedtpu.telemetry.report import load_events

# Causal stage order of one update's trace chain. Within one engine
# tick the stages can only advance left to right; dedup_drop is the
# retry path's terminal stage (the original verdict was already acked).
# The MPMD trio (client_step → aggregate → metrics) is one chunk's pass
# through the DAG of sub-programs; appended at the END so existing
# goldens' ranks never move.
STAGES = ("client_stamp", "wal", "dedup_drop", "admit",
          "buffer_insert", "incorporate",
          "client_step", "aggregate", "metrics")
_STAGE_RANK = {s: i for i, s in enumerate(STAGES)}

# Payload fields that are pure functions of the virtual-time campaign —
# the ONLY payload fields the deterministic renderer keeps. Everything
# else (wall seconds, percentile dicts, counter snapshots, paths) is an
# accident of the host that ran the campaign.
PAYLOAD_WHITELIST = frozenset({
    "trace_id", "user", "seq", "nonce", "verdict", "tick", "events",
    "gateway", "fault", "reason", "elig_tick", "t_virtual", "op",
    "rounds", "rc", "version", "decisions", "t", "incorporated",
    "pending", "n_screened", "frame", "conn", "outcome", "delivered",
    "duplicate", "strikes", "notice", "backlog",
})


# ---------------------------------------------------------------------------
# loading / classification


def _parse_jsonl(path: str) -> Tuple[List[dict], int]:
    recs, bad = [], 0
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            if isinstance(obj, dict):
                recs.append(obj)
            else:
                bad += 1
    return recs, bad


def classify(path: str, records: List[dict]) -> str:
    """'events' | 'netlog' | 'decisions' — by filename convention first
    (``*.netlog`` is the proxy's contract), then by line shape."""
    if path.endswith(".netlog"):
        return "netlog"
    for rec in records:
        if "kind" in rec:
            return "events"
        if "decisions" in rec and "version" in rec:
            return "decisions"
        if "digest" in rec and "gateway" in rec:
            return "netlog"
    return "events"


def _source_label(kind: str, records: List[dict], path: str) -> str:
    """The deterministic display label: a role, never a path (temp-dir
    names must not leak into goldens)."""
    if kind == "netlog":
        g = next((r.get("gateway") for r in records
                  if r.get("gateway") is not None), None)
        return f"proxy-{g}" if g is not None else "proxy"
    if kind == "decisions":
        return "autoscale"
    for rec in records:
        role = rec.get("role")
        if role:
            p = rec.get("process_index")
            # Roles that already carry a fleet index ('gateway-1') stay
            # as-is; the generic 'run' role disambiguates by process.
            return (f"{role}.p{p}" if p and not role[-1:].isdigit()
                    else role)
    return "run"


def load_timeline(paths) -> List[dict]:
    """Load + classify each artifact. Returns one source dict per path:
    ``{"path", "type", "label", "records", "malformed"}``, sorted by
    label (ties broken by input order) so the merged view is stable no
    matter the argv order."""
    sources = []
    for order, path in enumerate(paths):
        if path.endswith(".netlog"):
            records, bad = _parse_jsonl(path)
            kind = "netlog"
        else:
            kind_guess, bad_guess = _parse_jsonl(path)
            kind = classify(path, kind_guess)
            if kind == "events":
                records, bad = load_events(path)
            else:
                records, bad = kind_guess, bad_guess
        sources.append({"path": path, "type": kind,
                        "label": _source_label(kind, records, path),
                        "records": records, "malformed": bad,
                        "order": order})
    sources.sort(key=lambda s: (s["label"], s["order"]))
    return sources


# ---------------------------------------------------------------------------
# causal chains


def trace_chains(sources: List[dict]) -> List[dict]:
    """Group every ``kind == 'trace'`` event by trace_id into causal
    chains. Stage order inside a chain: (engine tick, stage rank,
    source label, file position) — ticks are the virtual clock, the
    stage rank breaks same-tick ties causally."""
    by_id: Dict[str, List[tuple]] = {}
    for src in sources:
        if src["type"] != "events":
            continue
        for pos, rec in enumerate(src["records"]):
            if rec.get("kind") != "trace":
                continue
            payload = rec.get("payload") or {}
            tid = payload.get("trace_id")
            if not tid:
                continue
            stage = rec.get("phase")
            entry = {"stage": stage, "role": rec.get("role", "run"),
                     "round": rec.get("round")}
            for k in ("user", "seq", "nonce", "verdict", "events",
                      "t_virtual", "elig_tick", "op"):
                if payload.get(k) is not None:
                    entry[k] = payload[k]
            by_id.setdefault(str(tid), []).append(
                (rec.get("round") or 0,
                 _STAGE_RANK.get(stage, len(STAGES)),
                 src["label"], pos, entry))
    chains = []
    for tid in sorted(by_id):
        keyed = sorted(by_id[tid], key=lambda x: x[:4])
        chains.append({"chain": tid, "stages": [k[-1] for k in keyed]})
    return chains


# ---------------------------------------------------------------------------
# deterministic renderer (the goldenable one)


def _det_payload(payload: dict) -> dict:
    return {k: payload[k] for k in sorted(payload)
            if k in PAYLOAD_WHITELIST and payload[k] is not None}


def _det_row(src: dict, idx: int, rec: dict) -> Optional[dict]:
    if src["type"] == "events":
        row = {"src": src["label"], "i": idx, "kind": rec.get("kind"),
               "role": rec.get("role", "run")}
        if rec.get("phase") is not None:
            row["phase"] = rec["phase"]
        if rec.get("round") is not None:
            row["round"] = rec["round"]
        x = _det_payload(rec.get("payload") or {})
        if x:
            row["x"] = x
        return row
    if src["type"] == "netlog":
        # Proxy lines are deterministic by construction (ordinal
        # arithmetic, no wall clock) — pass them through whole.
        return {"src": src["label"], "i": idx, "kind": "netlog", "x": rec}
    if src["type"] == "decisions":
        return {"src": src["label"], "i": idx, "kind": "autoscale_decision",
                "x": {k: rec[k] for k in ("version", "t", "decisions")
                      if k in rec}}
    return None


def deterministic_lines(sources: List[dict]) -> List[str]:
    """The goldenable canonical-JSONL rendering (module docstring):
    one header line per source, every record as a wall-clock-free row
    in file order, then one ``chain`` row per trace_id."""
    rows: List[dict] = []
    for src in sources:
        rows.append({"source": src["label"], "type": src["type"],
                     "records": len(src["records"])})
        for idx, rec in enumerate(src["records"]):
            row = _det_row(src, idx, rec)
            if row is not None:
                rows.append(row)
    rows.extend(trace_chains(sources))
    return [json.dumps(r, sort_keys=True, separators=(",", ":"))
            for r in rows]


# ---------------------------------------------------------------------------
# Chrome trace renderer (the human one)


def _flow_id(tid: str) -> int:
    try:
        return int(tid, 16) & 0x7FFFFFFF
    except ValueError:
        return abs(hash(tid)) & 0x7FFFFFFF


def chrome_trace(sources: List[dict]) -> dict:
    """Chrome trace-event JSON ('traceEvents' array format): open in
    Perfetto / chrome://tracing. One pid per source; spans become
    complete ('X') slices on each source's own monotonic clock,
    everything else an instant; each trace_id's stages are stitched
    with flow ('s'/'t'/'f') arrows so one update reads as one arrowed
    path across the fleet's tracks."""
    events: List[dict] = []
    flow_seen: Dict[str, int] = {}
    flow_total: Dict[str, int] = {}
    for src in sources:
        if src["type"] == "events":
            for rec in src["records"]:
                tid = (rec.get("payload") or {}).get("trace_id")
                if rec.get("kind") == "trace" and tid:
                    flow_total[str(tid)] = flow_total.get(str(tid), 0) + 1
    for pid, src in enumerate(sources):
        events.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_name",
                       "args": {"name": src["label"]}})
        for idx, rec in enumerate(src["records"]):
            if src["type"] == "events":
                ts = float(rec.get("t_start") or 0.0) * 1e6
                dur = float(rec.get("dur_s") or 0.0) * 1e6
                payload = rec.get("payload") or {}
                name = rec.get("kind") or "event"
                if rec.get("phase"):
                    name = f"{name}:{rec['phase']}"
                base = {"pid": pid, "tid": 0, "name": name, "ts": ts,
                        "cat": rec.get("kind") or "event",
                        "args": {k: v for k, v in payload.items()
                                 if isinstance(v, (int, float, str, bool))}}
                if rec.get("round") is not None:
                    base["args"]["round"] = rec["round"]
                if dur > 0:
                    events.append({**base, "ph": "X", "dur": dur})
                else:
                    events.append({**base, "ph": "i", "s": "t"})
                tid = payload.get("trace_id")
                if rec.get("kind") == "trace" and tid:
                    tid = str(tid)
                    seen = flow_seen.get(tid, 0)
                    flow_seen[tid] = seen + 1
                    ph = ("s" if seen == 0
                          else "f" if seen + 1 == flow_total.get(tid, 0)
                          else "t")
                    flow = {"ph": ph, "pid": pid, "tid": 0,
                            "name": f"trace:{tid}", "cat": "trace",
                            "id": _flow_id(tid), "ts": ts}
                    if ph == "f":
                        flow["bp"] = "e"
                    events.append(flow)
            elif src["type"] == "netlog":
                # The proxy log has no wall clock — its ordinal (frame
                # number when present, else line index) IS its time
                # axis, rendered as microseconds.
                ts = float(rec.get("frame", idx))
                name = (rec.get("fault") or
                        ("summary" if "summary" in rec else "header"))
                events.append({"ph": "i", "s": "t", "pid": pid, "tid": 0,
                               "name": f"net:{name}", "cat": "netlog",
                               "ts": ts,
                               "args": {k: v for k, v in rec.items()
                                        if isinstance(v, (int, float,
                                                          str, bool))}})
            elif src["type"] == "decisions":
                ts = float(rec.get("t") or 0.0) * 1e6
                kinds = ",".join(d.get("kind", "?")
                                 for d in rec.get("decisions") or []) or "hold"
                events.append({"ph": "i", "s": "t", "pid": pid, "tid": 0,
                               "name": f"autoscale:{kinds}",
                               "cat": "autoscale", "ts": ts,
                               "args": {"version": rec.get("version"),
                                        "t": rec.get("t")}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# CLI entry


def render_timeline(paths, fmt: str = "jsonl") -> str:
    """``fedtpu timeline`` body: merge ``paths`` and render. ``fmt``
    'jsonl' gives the deterministic canonical lines, 'chrome' the
    Perfetto-loadable JSON."""
    sources = load_timeline(paths)
    if fmt == "chrome":
        return json.dumps(chrome_trace(sources), indent=1, sort_keys=True)
    return "\n".join(deterministic_lines(sources))


def default_artifacts(events_path: str) -> List[str]:
    """Expand one events path into every sibling artifact the fleet
    convention derives from it: per-gateway sinks (``*.g<i>``),
    per-process sinks (``*.p<i>``), netproxy logs (``*.g<i>.netlog``).
    Lets ``fedtpu timeline events.jsonl`` pick up a whole fleet."""
    out = [events_path]
    d = os.path.dirname(events_path) or "."
    base = os.path.basename(events_path)
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return out
    for name in names:
        if name == base:
            continue
        stem = name.rsplit(".netlog", 1)[0] if name.endswith(".netlog") \
            else name
        core = stem.rsplit(".g", 1)[0] if ".g" in stem else stem
        core = core.rsplit(".p", 1)[0] if ".p" in core else core
        if core == base:
            out.append(os.path.join(d, name))
    return out


__all__ = ["STAGES", "PAYLOAD_WHITELIST", "load_timeline", "classify",
           "trace_chains", "deterministic_lines", "chrome_trace",
           "render_timeline", "default_artifacts"]
