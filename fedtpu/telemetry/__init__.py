"""Structured observability for fedtpu (ISSUE 1).

The reference has zero observability — ``print(flush=True)`` only
(SURVEY.md §5) — and until this subsystem fedtpu had two disconnected
islands: fetch-forced wall-clock timing (fedtpu.utils.timing) and a
schemaless per-round metrics JSONL. This package unifies them:

    trace     — span/event tracer writing a versioned JSONL event sink
                (monotonic timestamps; device spans close on host
                materialization, never on dispatch — the repo's
                fetch-forced-completion rule)
    metrics   — process-local counters / gauges / histograms, plus the
                jax.monitoring compile-event probe
    manifest  — the startup run manifest (config dump + hash, mesh shape,
                device kinds, backend, package version, git rev) so every
                artifact is attributable
    log       — the leveled logger that byte-preserves the reference-parity
                output lines while mirroring everything else into the sink
    report    — offline aggregation of an events JSONL into per-phase time
                breakdowns, round-cadence percentiles, staleness
                distributions and counter totals (``fedtpu report``);
                numpy-only so it runs without a JAX backend
    timeline  — causal fleet timeline (``fedtpu timeline``): merges N
                events sinks + netproxy logs + autoscale decision logs
                into one ordered view, rendered as deterministic
                (goldenable) JSONL or Chrome/Perfetto trace JSON; the
                trace_id chains stitch one update's client-stamp ->
                WAL -> admission -> incorporation path across processes

Everything here is import-light: no module in this package imports jax at
import time (probes that need it import lazily), so ``fedtpu report`` and
the tests' synthetic round-trips run without touching a backend.
"""

from fedtpu.telemetry.trace import (EVENT_SCHEMA_VERSION,  # noqa: F401
                                    FlightRecorder, NullTracer, Tracer,
                                    crash_artifact_path, make_tracer,
                                    process_identity)
from fedtpu.telemetry.timeline import (chrome_trace,  # noqa: F401
                                       deterministic_lines, load_timeline,
                                       render_timeline, trace_chains)
from fedtpu.telemetry.metrics import (MetricsRegistry, default_registry,  # noqa: F401
                                      install_compile_probe)
from fedtpu.telemetry.log import TelemetryLogger  # noqa: F401
from fedtpu.telemetry.manifest import build_manifest  # noqa: F401
