"""Leveled logger that byte-preserves the reference-parity output lines.

Two output classes, one object:

  * ``parity(msg)`` — the reference-faithful lines (the doomed-iteration
    early-stop message, the barrier-ordered per-client prints, the sweep's
    winner report). Printed byte-for-byte via ``print(msg, flush=True)``
    and NEVER reformatted, prefixed, or redirected — an A/B diff against
    the reference's stdout must stay clean with telemetry on or off.
  * ``info(msg)`` / ``warning(msg)`` / ``debug(msg)`` — fedtpu's own
    operational lines. Printed when the level allows AND mirrored into the
    event sink (kind ``log``) so a quiet run still records what happened.

This module and ``fedtpu/cli.py`` are the ONLY places in ``fedtpu/``
allowed to call bare ``print`` — enforced by the tier-1 lint test
(tests/test_telemetry.py); everything else routes through here.

Verbosity composes the caller's ``verbose`` flag with the multi-process
rule (side effects on process 0 only): the round loop constructs the
logger after folding ``io_proc`` into ``verbose``, so non-zero processes
stay silent without call-site guards.
"""

from __future__ import annotations

from typing import Optional

_LEVELS = {"debug": 10, "info": 20, "warning": 30}


class TelemetryLogger:
    def __init__(self, verbose: bool = True, tracer=None,
                 level: str = "info"):
        self.verbose = verbose
        self._tracer = tracer
        self._threshold = _LEVELS.get(level, 20)

    def _emit(self, level: str, msg: str) -> None:
        if self.verbose and _LEVELS[level] >= self._threshold:
            print(msg, flush=True)
        if self._tracer is not None:
            self._tracer.event("log", level=level, msg=msg)

    def debug(self, msg: str) -> None:
        self._emit("debug", msg)

    def info(self, msg: str) -> None:
        self._emit("info", msg)

    def warning(self, msg: str) -> None:
        self._emit("warning", msg)

    def parity(self, msg: str) -> None:
        """Reference-parity line: byte-exact stdout, no sink mirror, no
        level filtering beyond the verbose gate (the reference prints these
        unconditionally; ``--quiet`` maps to ``verbose=False``)."""
        if self.verbose:
            print(msg, flush=True)
