"""Span/event tracer with a versioned JSONL sink.

Event schema (``EVENT_SCHEMA_VERSION = 1``) — one JSON object per line:

    v        int    schema version
    run_id   str    one uuid4 hex per tracer (joins every event of a run)
    kind     str    'manifest' | 'span' | 'round' | 'counters' | 'log' | ...
    phase    str?   span phase label ('build', 'compile', 'chunk', 'eval',
                    'checkpoint', 'stop_check', 'personalize', 'launch', ...)
    round    int?   1-based round (tick) the event belongs to, when any
    t_start  float  seconds since the tracer's epoch (time.monotonic-based,
                    so deltas are immune to wall-clock steps)
    dur_s    float  span duration; 0.0 for instantaneous events
    payload  dict   kind-specific data (metric values, counter snapshots...)

Timing rule, inherited from fedtpu.utils.timing's round-1 postmortem:
``jax.block_until_ready`` does NOT synchronize on this platform's remote
('axon') transport, so a device span must close on a HOST VALUE FETCH
(``force_fetch`` / ``np.asarray`` materialization), never on dispatch.
``Span.end_after_fetch`` packages that rule; the round loop closes its
chunk spans on the batched metrics materialization, which is the same
proof.

Writes flush per event: a crashed run's sink still holds everything
emitted before the crash (the tracer exists precisely to diagnose such
runs), so ``close()`` is a nicety, not a durability requirement.

No jax import at module scope — the reader side (fedtpu.telemetry.report)
and the tests' synthetic emitters must work backend-free.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Optional

EVENT_SCHEMA_VERSION = 1


class Span:
    """One open phase window; created by ``Tracer.span``. Usable as a
    context manager (closes on ``__exit__``) or manually via ``end`` /
    ``end_after_fetch``."""

    def __init__(self, tracer: "Tracer", phase: str,
                 round: Optional[int] = None, **payload):
        self._tracer = tracer
        self.phase = phase
        self.round = round
        self.payload = dict(payload)
        self._t0 = time.monotonic()
        self._closed = False

    def end(self, **extra) -> float:
        """Close the span (idempotent) and emit it; returns the duration."""
        dur = time.monotonic() - self._t0
        if not self._closed:
            self._closed = True
            self._tracer.event("span", phase=self.phase, round=self.round,
                               dur_s=dur, **{**self.payload, **extra})
        return dur

    def end_after_fetch(self, tree, **extra) -> float:
        """Close the span on a host value fetch of ``tree`` — the
        fetch-forced-completion rule (module docstring). The fetch is the
        proof the device work inside the span actually finished."""
        from fedtpu.utils.timing import force_fetch
        force_fetch(tree)
        return self.end(**extra)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.end(**({"error": repr(exc)} if exc is not None else {}))


class Tracer:
    """Appends schema-v1 events to a JSONL sink. One per run; all
    timestamps are seconds since this tracer's construction (monotonic)."""

    def __init__(self, path: str, run_id: Optional[str] = None):
        self.path = path
        self.run_id = run_id or uuid.uuid4().hex
        self._epoch = time.monotonic()
        self._f = open(path, "a")

    @property
    def enabled(self) -> bool:
        return True

    def _now(self) -> float:
        return time.monotonic() - self._epoch

    def event(self, kind: str, phase: Optional[str] = None,
              round: Optional[int] = None, dur_s: float = 0.0,
              t_start: Optional[float] = None, **payload) -> None:
        """Emit one event. ``t_start`` defaults to now minus ``dur_s`` so a
        caller that timed a window itself (the round loop's chunk lap) gets
        an honest window start without threading timestamps around."""
        if self._f.closed:
            return
        rec = {"v": EVENT_SCHEMA_VERSION, "run_id": self.run_id,
               "kind": kind, "phase": phase, "round": round,
               "t_start": (self._now() - dur_s if t_start is None
                           else t_start),
               "dur_s": dur_s, "payload": payload}
        self._f.write(json.dumps(rec, default=_json_default) + "\n")
        self._f.flush()

    def span(self, phase: str, round: Optional[int] = None,
             **payload) -> Span:
        return Span(self, phase, round=round, **payload)

    def counters(self, snapshot: dict) -> None:
        """Emit a full registry snapshot (kind 'counters'). The report's
        counter totals come from the LAST such event in the log."""
        self.event("counters", **snapshot)

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()


class _NullSpan:
    phase = None
    round = None
    payload: dict = {}

    def end(self, **extra) -> float:
        return 0.0

    def end_after_fetch(self, tree, **extra) -> float:
        return 0.0

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


class NullTracer:
    """Telemetry-off tracer: same surface as ``Tracer``, every call a
    no-op. The round loop is written against this API unconditionally, so
    the disabled path costs a method call per event, not a branch per
    call site."""

    path = None
    run_id = None

    @property
    def enabled(self) -> bool:
        return False

    def event(self, kind, phase=None, round=None, dur_s=0.0, t_start=None,
              **payload) -> None:
        pass

    def span(self, phase, round=None, **payload) -> _NullSpan:
        return _NullSpan()

    def counters(self, snapshot) -> None:
        pass

    def close(self) -> None:
        pass


def _json_default(obj):
    """Sink-side coercion for numpy scalars/arrays and other non-JSON
    payload leaves — the tracer must never crash the run it observes."""
    for attr in ("item",):
        if hasattr(obj, attr) and getattr(obj, "ndim", None) == 0:
            return obj.item()
    if hasattr(obj, "tolist"):
        return obj.tolist()
    return repr(obj)


def make_tracer(path: Optional[str], run_id: Optional[str] = None):
    """The one constructor call sites use: a real ``Tracer`` when ``path``
    is set (process 0 of a run), a ``NullTracer`` otherwise."""
    return Tracer(path, run_id=run_id) if path else NullTracer()
