"""Span/event tracer with a versioned JSONL sink.

Event schema (``EVENT_SCHEMA_VERSION = 2``) — one JSON object per line:

    v              int    schema version
    run_id         str    one uuid4 hex per tracer (joins every event of a run)
    kind           str    'manifest' | 'span' | 'round' | 'counters' | 'log' |
                          'trace' | ...
    phase          str?   span phase label ('build', 'compile', 'chunk',
                          'eval', 'checkpoint', 'stop_check', 'personalize',
                          'launch', ...); for kind 'trace' the causal stage
                          ('client_stamp', 'wal', 'admit', 'buffer_insert',
                          'dedup_drop', 'incorporate')
    round          int?   1-based round (tick) the event belongs to, when any
    t_start        float  seconds since the tracer's epoch (time.monotonic-
                          based, so deltas are immune to wall-clock steps)
    dur_s          float  span duration; 0.0 for instantaneous events
    process_index  int    fleet process identity (v2): FEDTPU_PROCESS_ID or 0
    pid            int    OS pid of the emitting process (v2)
    launch_id      str?   gang launch id (FEDTPU_LAUNCH_ID) when one (v2)
    role           str    emitting role (v2): 'run', 'serve', 'gateway-<i>',
                          'proxy-<i>', 'supervisor', ...
    payload        dict   kind-specific data (metric values, counters...)

v1 files (no identity fields) stay readable: every consumer reads the
identity with defaults (``process_index=0``, ``role='run'``), so old
sinks parse unchanged and merged multi-process reports key sections on
``(run_id, role, process_index)`` instead of the colliding ``run_id``
alone.

Timing rule, inherited from fedtpu.utils.timing's round-1 postmortem:
``jax.block_until_ready`` does NOT synchronize on this platform's remote
('axon') transport, so a device span must close on a HOST VALUE FETCH
(``force_fetch`` / ``np.asarray`` materialization), never on dispatch.
``Span.end_after_fetch`` packages that rule; the round loop closes its
chunk spans on the batched metrics materialization, which is the same
proof.

Crash flight recorder: every Tracer keeps a bounded in-memory ring of
its most recent event lines (``FlightRecorder``). The supervisor's
0/3/75 exit paths and the serving crash barrier (``_safe_handle``)
flush it to ``events.crash.<role>.jsonl`` next to the events sink, so a
chaos-row failure always ships a post-mortem timeline even when the
main sink is on a dead disk or got truncated mid-crash.

Writes flush per event: a crashed run's sink still holds everything
emitted before the crash (the tracer exists precisely to diagnose such
runs), so ``close()`` is a nicety, not a durability requirement.

No jax import at module scope — the reader side (fedtpu.telemetry.report)
and the tests' synthetic emitters must work backend-free.
"""

from __future__ import annotations

import collections
import json
import os
import time
import uuid
from typing import Optional

EVENT_SCHEMA_VERSION = 2

# Ring capacity of the per-process crash flight recorder: enough for the
# serving fleet's last few ticks of context without holding a long run's
# whole history in memory.
FLIGHT_RECORDER_CAPACITY = 256


def process_identity(role: Optional[str] = None,
                     process_index: Optional[int] = None) -> dict:
    """The v2 identity stamp for this process. ``process_index`` falls
    back to the gang supervisor's FEDTPU_PROCESS_ID contract
    (fedtpu.resilience.distributed), ``launch_id`` to FEDTPU_LAUNCH_ID —
    both absent on a plain single-process run, which stamps as the
    canonical (0, 'run')."""
    if process_index is None:
        try:
            process_index = int(os.environ.get("FEDTPU_PROCESS_ID", "0") or 0)
        except ValueError:
            process_index = 0
    return {"process_index": int(process_index), "pid": os.getpid(),
            "launch_id": os.environ.get("FEDTPU_LAUNCH_ID"),
            "role": role or "run"}


def crash_artifact_path(events_path: Optional[str], role: str) -> str:
    """Path of the flight-recorder flush target for ``role``:
    ``events.crash.<role>.jsonl`` in the events sink's directory (the
    cwd when the tracer has no sink)."""
    base = os.path.dirname(events_path) if events_path else "."
    return os.path.join(base or ".", f"events.crash.{role}.jsonl")


class FlightRecorder:
    """Bounded ring of the most recent serialized event lines.

    Append-only and O(1) per event (collections.deque with maxlen); the
    whole point is that recording must be cheap enough to run on EVERY
    event of a healthy process that will probably never crash."""

    def __init__(self, capacity: int = FLIGHT_RECORDER_CAPACITY):
        self.capacity = int(capacity)
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity)

    def record(self, line: str) -> None:
        self._ring.append(line)

    def __len__(self) -> int:
        return len(self._ring)

    def lines(self) -> list:
        return list(self._ring)

    def flush(self, path: str) -> int:
        """Write the ring to ``path`` (overwrite: the LAST crash of a
        process is the one worth keeping) and return the line count.
        Never raises — the flight recorder runs inside crash paths where
        a secondary I/O error must not mask the primary failure."""
        lines = self.lines()
        if not lines:
            return 0
        try:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                for line in lines:
                    fh.write(line + "\n")
            os.replace(tmp, path)
        except OSError:
            return 0
        return len(lines)


class Span:
    """One open phase window; created by ``Tracer.span``. Usable as a
    context manager (closes on ``__exit__``) or manually via ``end`` /
    ``end_after_fetch``."""

    def __init__(self, tracer: "Tracer", phase: str,
                 round: Optional[int] = None, **payload):
        self._tracer = tracer
        self.phase = phase
        self.round = round
        self.payload = dict(payload)
        self._t0 = time.monotonic()
        self._closed = False

    def end(self, **extra) -> float:
        """Close the span (idempotent) and emit it; returns the duration."""
        dur = time.monotonic() - self._t0
        if not self._closed:
            self._closed = True
            self._tracer.event("span", phase=self.phase, round=self.round,
                               dur_s=dur, **{**self.payload, **extra})
        return dur

    def end_after_fetch(self, tree, **extra) -> float:
        """Close the span on a host value fetch of ``tree`` — the
        fetch-forced-completion rule (module docstring). The fetch is the
        proof the device work inside the span actually finished."""
        from fedtpu.utils.timing import force_fetch
        force_fetch(tree)
        return self.end(**extra)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.end(**({"error": repr(exc)} if exc is not None else {}))


class Tracer:
    """Appends schema-v2 events to a JSONL sink. One per run; all
    timestamps are seconds since this tracer's construction (monotonic).
    Every emitted line also lands in the in-memory flight recorder."""

    def __init__(self, path: str, run_id: Optional[str] = None,
                 role: Optional[str] = None,
                 process_index: Optional[int] = None):
        self.path = path
        self.run_id = run_id or uuid.uuid4().hex
        self.identity = process_identity(role, process_index)
        self.role = self.identity["role"]
        self.flight = FlightRecorder()
        self._epoch = time.monotonic()
        self._f = open(path, "a")

    @property
    def enabled(self) -> bool:
        return True

    def _now(self) -> float:
        return time.monotonic() - self._epoch

    def event(self, kind: str, phase: Optional[str] = None,
              round: Optional[int] = None, dur_s: float = 0.0,
              t_start: Optional[float] = None, **payload) -> None:
        """Emit one event. ``t_start`` defaults to now minus ``dur_s`` so a
        caller that timed a window itself (the round loop's chunk lap) gets
        an honest window start without threading timestamps around."""
        if self._f.closed:
            return
        rec = {"v": EVENT_SCHEMA_VERSION, "run_id": self.run_id,
               "kind": kind, "phase": phase, "round": round,
               "t_start": (self._now() - dur_s if t_start is None
                           else t_start),
               "dur_s": dur_s, **self.identity, "payload": payload}
        line = json.dumps(rec, default=_json_default)
        self.flight.record(line)
        self._f.write(line + "\n")
        self._f.flush()

    def span(self, phase: str, round: Optional[int] = None,
             **payload) -> Span:
        return Span(self, phase, round=round, **payload)

    def counters(self, snapshot: dict) -> None:
        """Emit a full registry snapshot (kind 'counters'). The report's
        counter totals come from the LAST such event in the log."""
        self.event("counters", **snapshot)

    def flush_crash(self, reason: str = "") -> Optional[str]:
        """Flush the flight recorder to ``events.crash.<role>.jsonl``
        next to the sink; returns the artifact path (None when the ring
        was empty). Called from crash barriers — never raises."""
        path = crash_artifact_path(self.path, self.role)
        if reason:
            self.flight.record(json.dumps(
                {"v": EVENT_SCHEMA_VERSION, "run_id": self.run_id,
                 "kind": "crash_flush", "phase": None, "round": None,
                 "t_start": self._now(), "dur_s": 0.0, **self.identity,
                 "payload": {"reason": reason}}, default=_json_default))
        return path if self.flight.flush(path) else None

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()


class _NullSpan:
    phase = None
    round = None
    payload: dict = {}

    def end(self, **extra) -> float:
        return 0.0

    def end_after_fetch(self, tree, **extra) -> float:
        return 0.0

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


class NullTracer:
    """Telemetry-off tracer: same surface as ``Tracer``, every call a
    no-op. The round loop is written against this API unconditionally, so
    the disabled path costs a method call per event, not a branch per
    call site."""

    path = None
    run_id = None
    role = "run"

    def __init__(self):
        self.identity = process_identity()
        self.flight = FlightRecorder(capacity=1)

    @property
    def enabled(self) -> bool:
        return False

    def event(self, kind, phase=None, round=None, dur_s=0.0, t_start=None,
              **payload) -> None:
        pass

    def span(self, phase, round=None, **payload) -> _NullSpan:
        return _NullSpan()

    def counters(self, snapshot) -> None:
        pass

    def flush_crash(self, reason: str = "") -> Optional[str]:
        return None

    def close(self) -> None:
        pass


def _json_default(obj):
    """Sink-side coercion for numpy scalars/arrays and other non-JSON
    payload leaves — the tracer must never crash the run it observes."""
    for attr in ("item",):
        if hasattr(obj, attr) and getattr(obj, "ndim", None) == 0:
            return obj.item()
    if hasattr(obj, "tolist"):
        return obj.tolist()
    return repr(obj)


def make_tracer(path: Optional[str], run_id: Optional[str] = None,
                role: Optional[str] = None,
                process_index: Optional[int] = None):
    """The one constructor call sites use: a real ``Tracer`` when ``path``
    is set, a ``NullTracer`` otherwise. ``role`` scopes the v2 identity
    stamp ('run' default; the gateway fleet passes 'gateway-<i>', the
    supervisor 'supervisor') so merged fleet timelines can key sections
    on something better than a colliding run_id."""
    return (Tracer(path, run_id=run_id, role=role,
                   process_index=process_index)
            if path else NullTracer())
