"""Process-local counters / gauges / histograms + the compile-event probe.

A ``MetricsRegistry`` is plain host-side bookkeeping, no jax at import
time. The round loop resets the default registry at run start,
increments it as the run progresses (rounds trained, checkpoint
saves/restores, async ticks, staleness observations, estimated bytes
exchanged), and emits ``registry.snapshot()`` as a ``counters`` event so
``fedtpu report`` can total everything offline.

The round loop is single-threaded per process, but the registry is NOT:
``CompileExecutor``'s worker increments ``background_compiles`` from the
pool thread, and jax's monitoring dispatch may fire the compile probe
off the main thread. Every instrument therefore updates under one
registry-wide lock — ``x += n`` is a read-modify-write that loses
updates under concurrency, and ``snapshot()`` must not observe a
half-applied histogram.

``install_compile_probe`` hooks ``jax.monitoring``'s event-duration stream
(the channel jax itself reports backend compile times on) into the DEFAULT
registry: every ``*compil*`` event increments ``jax_compile_events`` and
adds its duration to ``jax_compile_secs``. Registered once per process —
jax keeps listeners forever, so re-registration would double-count.

Histogram buckets are cumulative-style upper bounds (Prometheus ``le``
semantics) so the report's Prometheus export is a direct rendering.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Optional, Sequence

# Upper bounds for the staleness histogram: async staleness is a small
# non-negative integer (ticks since last pull), heavy-tailed under low
# arrival rates — powers of two cover the studyable range.
DEFAULT_STALENESS_BINS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self, lock: Optional[threading.Lock] = None):
        self.value = 0.0
        self._lock = lock if lock is not None else threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Gauge:
    __slots__ = ("value", "_lock")

    def __init__(self, lock: Optional[threading.Lock] = None):
        self.value = 0.0
        self._lock = lock if lock is not None else threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Cumulative-bucket histogram (Prometheus ``le`` upper bounds) with
    running count/sum/min/max. ``bucket_counts[i]`` counts observations
    ``<= bins[i]``; one implicit +Inf bucket equals ``count``."""

    __slots__ = ("bins", "bucket_counts", "count", "sum", "min", "max",
                 "_lock")

    def __init__(self, bins: Sequence[float] = DEFAULT_STALENESS_BINS,
                 lock: Optional[threading.Lock] = None):
        self.bins = tuple(float(b) for b in bins)
        self.bucket_counts = [0] * len(self.bins)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._lock = lock if lock is not None else threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = min(self.min, v)
            self.max = max(self.max, v)
            for i, b in enumerate(self.bins):
                if v <= b:
                    self.bucket_counts[i] += 1

    def observe_many(self, values) -> None:
        for v in values:
            self.observe(v)

    def to_dict(self) -> dict:
        return {"bins": list(self.bins),
                "bucket_counts": list(self.bucket_counts),
                "count": self.count, "sum": self.sum,
                "min": self.min if self.count else None,
                "max": self.max if self.count else None,
                "mean": (self.sum / self.count) if self.count else None}


class MetricsRegistry:
    """One lock for the whole registry, shared into every instrument it
    creates: instrument updates, name->instrument map growth, snapshot
    and reset all serialize against each other, so a background-compile
    ``inc()`` can neither lose an update nor tear a snapshot."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(lock=self._lock)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(lock=self._lock)
            return g

    def histogram(self, name: str,
                  bins: Optional[Sequence[float]] = None) -> Histogram:
        with self._lock:
            if name not in self._histograms:
                self._histograms[name] = Histogram(
                    bins if bins is not None else DEFAULT_STALENESS_BINS,
                    lock=self._lock)
            return self._histograms[name]

    def snapshot(self) -> dict:
        """JSON-ready view — the payload of a ``counters`` event."""
        with self._lock:
            return {
                "counters": {k: c.value for k, c in self._counters.items()},
                "gauges": {k: g.value for k, g in self._gauges.items()},
                "histograms": {k: h.to_dict()
                               for k, h in self._histograms.items()},
            }

    def reset(self) -> None:
        """Clear all instruments IN PLACE — the registry object's identity
        survives (the compile probe holds a reference across runs)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The per-process registry the run loop / checkpoint layer share."""
    return _DEFAULT


_COMPILE_PROBE_INSTALLED = False


def install_compile_probe() -> bool:
    """Route jax's compile-event durations into the default registry
    (counters ``jax_compile_events`` / ``jax_compile_secs``). Idempotent:
    jax.monitoring listeners are registered for the process lifetime and
    cannot be removed, so only the first call installs. Returns whether a
    probe is installed (False when this jax build lacks the API)."""
    global _COMPILE_PROBE_INSTALLED
    if _COMPILE_PROBE_INSTALLED:
        return True

    def _on_duration(event: str, duration: float, **kw) -> None:
        # Event names are jax-internal paths ('/jax/core/compile',
        # backend_compile...); match loosely, never raise into jax.
        try:
            if "compil" in event:
                reg = default_registry()
                reg.counter("jax_compile_events").inc()
                reg.counter("jax_compile_secs").inc(float(duration))
        except Exception:  # fedtpu: noqa[FTP102] never raise into jax's monitoring dispatch
            pass

    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:
        return False
    _COMPILE_PROBE_INSTALLED = True
    return True


def device_memory_gauges(registry: Optional[MetricsRegistry] = None) -> None:
    """Best-effort device-memory gauges from the live backend:
    ``live_array_count`` / ``live_array_bytes`` (jax.live_arrays) and
    ``device_bytes_in_use`` (PJRT memory_stats, where the backend reports
    it — CPU does not). Never raises: telemetry must not kill the run it
    observes, and mid-failure some buffers may already be deleted."""
    reg = registry if registry is not None else default_registry()
    try:
        import jax
        arrays = [a for a in jax.live_arrays() if not a.is_deleted()]
        reg.gauge("live_array_count").set(len(arrays))
        reg.gauge("live_array_bytes").set(
            sum(getattr(a, "nbytes", 0) for a in arrays))
        stats = jax.local_devices()[0].memory_stats()
        if stats and "bytes_in_use" in stats:
            reg.gauge("device_bytes_in_use").set(stats["bytes_in_use"])
    except Exception:  # fedtpu: noqa[FTP102] telemetry must not kill the run it observes; buffers may be deleted mid-failure
        pass
