"""``fedtpu check --timeline-sim`` — deterministic causal-trace replay.

Replays a PINNED two-gateway campaign (the ``SIM_*`` constants below)
against two REAL (small) :class:`fedtpu.serving.engine.ServingEngine`
instances through the real request dispatcher
(``fedtpu.serving.server._handle``), each engine writing a real
schema-v2 events sink through a role-scoped
:class:`fedtpu.telemetry.trace.Tracer` — then merges the two sinks with
:mod:`fedtpu.telemetry.timeline` and compares the deterministic JSONL
rendering bitwise against the committed golden
(``tests/goldens/timeline_sim.jsonl``), reusing the autoscale control
plane's write/compare machinery like the net/defense/audit gates.

The campaign includes a DELIBERATE retry: one frame is re-sent with its
original idempotency stamp, so the golden pins the full exactly-once
causal story under one trace_id — client_stamp -> wal -> admit ->
buffer_insert on the first delivery, client_stamp -> dedup_drop on the
retry, incorporate at the drain tick — across two gateway processes.
Any silent change to the trace-id derivation, the stage emission
points, the dedup path, or the timeline canonicalization moves these
bytes and turns into a reviewed golden regeneration instead of an
accident.

Like the net sim this touches jax (engine ticks are real), so it only
runs when explicitly invoked — never at import.
"""

from __future__ import annotations

import os
import tempfile

# One write/compare implementation repo-wide (see net_sim.py): the
# golden gates must never drift in format or failure reporting.
from fedtpu.autoscale.controller import compare_decisions, write_decisions

# ---------------------------------------------------------------------------
# Simulation contract: these constants are part of the committed golden
# (tests/goldens/timeline_sim.jsonl). Changing ANY of them — or the
# trace-id derivation in serving/protocol.py, the stage emission in
# serving/engine.py / serving/server.py, the v2 event schema in
# telemetry/trace.py, or the canonicalization in telemetry/timeline.py
# — legitimately regenerates the golden via
# ``python -m fedtpu.telemetry.timeline_sim --write <path>``.

SIM_USERS = 16
SIM_ARRIVALS = 64
SIM_HORIZON_S = 8.0
SIM_SEED = 7
SIM_BATCH = 8                       # trace rows per global chunk
SIM_GATEWAYS = 2
SIM_COHORT = 8
SIM_BUFFER = 2
SIM_TICK_INTERVAL_S = 0.5
# The session nonce is pinned (a live client draws a uuid), and the
# retried frame is pinned by its seq: determinism.
SIM_NONCE = "tlsim0campaign42"
SIM_RETRY_SEQ = 3
SIM_RUN_IDS = ("tlsim0g0", "tlsim0g1")


def _sim_config():
    from fedtpu.config import ServingConfig
    return ServingConfig(
        cohort=SIM_COHORT, buffer_size=SIM_BUFFER,
        tick_interval_s=SIM_TICK_INTERVAL_S,
        data_rows=64, model_hidden=(8,), seed=0)


def simulate(events_dir=None) -> dict:
    """Replay the pinned campaign. Returns ``{"lines": [...],
    "summary": {...}}`` where ``lines`` is the merged deterministic
    timeline JSONL and ``summary`` scores the campaign: per-gateway
    incorporation/dedup totals, chain count, and the retried trace_id's
    stage sequence (the acceptance chain).

    ``events_dir``: where the two sinks are written; a temp dir (cleaned
    up) when None. The dir name never reaches the golden — the
    deterministic renderer labels sources by role, not path."""
    from fedtpu.serving import protocol
    from fedtpu.serving.engine import ServingEngine
    from fedtpu.serving.server import _handle
    from fedtpu.serving.traces import synthesize_trace
    from fedtpu.telemetry.metrics import MetricsRegistry
    from fedtpu.telemetry.timeline import (deterministic_lines,
                                           load_timeline, trace_chains)
    from fedtpu.telemetry.trace import Tracer

    tmp = None
    if events_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="fedtpu_tlsim_")
        events_dir = tmp.name
    try:
        paths = [os.path.join(events_dir, f"events.g{g}.jsonl")
                 for g in range(SIM_GATEWAYS)]
        tracers = [Tracer(paths[g], run_id=SIM_RUN_IDS[g],
                          role=f"gateway-{g}", process_index=g)
                   for g in range(SIM_GATEWAYS)]
        engines = [ServingEngine(_sim_config(), registry=MetricsRegistry(),
                                 tracer=tracers[g])
                   for g in range(SIM_GATEWAYS)]
        for g, eng in enumerate(engines):
            # Real WAL (the gateway wiring's per-member path) so every
            # chain carries its gateway-WAL leg — the acceptance chain
            # is client_stamp -> wal -> ... -> incorporate.
            eng.wal_path = os.path.join(events_dir, f"wal.g{g}.jsonl")

        _, t, user, lat = synthesize_trace(
            SIM_USERS, SIM_ARRIVALS, SIM_HORIZON_S, seed=SIM_SEED)
        rows = [[int(user[i]), float(t[i]), float(lat[i])]
                for i in range(len(t))]

        # GatewayClient semantics: ONE session nonce, a GLOBAL seq, each
        # chunk partitioned by the ownership rule (user % num_gateways)
        # into one stamped frame per owning gateway. Frames are stamped
        # ONCE — the deliberate retry below re-sends the frame verbatim.
        for g in range(SIM_GATEWAYS):
            _handle(engines[g],
                    {"op": "hello", "v": protocol.PROTOCOL_VERSION,
                     "nonce": SIM_NONCE,
                     "trace": protocol.trace_id(SIM_NONCE, 0)})
        seq = 0
        frames = []                 # (gateway, frame) in send order
        for i in range(0, len(rows), SIM_BATCH):
            chunk = rows[i:i + SIM_BATCH]
            for g in range(SIM_GATEWAYS):
                owned = [r for r in chunk if r[0] % SIM_GATEWAYS == g]
                if not owned:
                    continue
                seq += 1
                frames.append((g, {
                    "op": "updates", "events": owned,
                    "nonce": SIM_NONCE, "seq": seq,
                    "trace": protocol.trace_id(SIM_NONCE, seq)}))
        retry = next((f for f in frames if f[1]["seq"] == SIM_RETRY_SEQ),
                     frames[0])
        for g, frame in frames:
            _handle(engines[g], frame)
        # The retry: same stamp, same trace — the engine must answer
        # with the original verdict and the chain must gain ONLY a
        # client_stamp + dedup_drop leg under the SAME trace_id.
        dup = _handle(engines[retry[0]], retry[1])
        drains = [_handle(engines[g], {"op": "drain"})
                  for g in range(SIM_GATEWAYS)]
        for tr in tracers:
            tr.close()

        sources = load_timeline(paths)
        lines = deterministic_lines(sources)
        chains = trace_chains(sources)
        retry_tid = protocol.trace_id(SIM_NONCE, int(retry[1]["seq"]))
        retry_chain = next((c for c in chains if c["chain"] == retry_tid),
                           None)
        summary = {
            "arrivals": len(rows),
            "frames": len(frames),
            "chains": len(chains),
            "retry_duplicate": bool(dup.get("duplicate", False)),
            "retry_trace": retry_tid,
            "retry_stages": ([s["stage"] for s in retry_chain["stages"]]
                             if retry_chain else []),
            "incorporated": [int(d.get("incorporated", 0))
                             for d in drains],
            "duplicate_drops": [int(e.duplicate_drops) for e in engines],
        }
        return {"lines": lines, "summary": summary}
    finally:
        if tmp is not None:
            tmp.cleanup()


def main(argv=None) -> int:
    """Regenerate or check the golden:
    ``python -m fedtpu.telemetry.timeline_sim --write tests/goldens/...``
    """
    import argparse
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--write", metavar="PATH", default=None,
                    help="write the canonical timeline JSONL here")
    ap.add_argument("--golden", metavar="PATH", default=None,
                    help="compare against this golden; exit 1 on mismatch")
    args = ap.parse_args(argv)
    sim = simulate()
    if args.write:
        write_decisions(args.write, sim["lines"])
        print(f"wrote {len(sim['lines'])} timeline lines -> {args.write}")  # fedtpu: noqa[FTP005] golden-regen CLI entry point
    if args.golden:
        res = compare_decisions(sim["lines"], args.golden)
        print(("OK: " if res["ok"] else "MISMATCH: ") + res["reason"])  # fedtpu: noqa[FTP005] golden-regen CLI entry point
        return 0 if res["ok"] else 1
    if not args.write:
        for line in sim["lines"]:
            print(line)  # fedtpu: noqa[FTP005] golden-regen CLI entry point
    return 0


__all__ = ["simulate", "write_decisions", "compare_decisions",
           "SIM_NONCE", "SIM_SEED", "SIM_RETRY_SEQ", "SIM_GATEWAYS"]

if __name__ == "__main__":
    raise SystemExit(main())
