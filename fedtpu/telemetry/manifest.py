"""Run manifest: the attribution record every artifact needs.

Emitted once at run start as the sink's ``manifest`` event — config dump +
stable hash, mesh shape, device kinds, backend, package/jax/python
versions, process topology, and a best-effort git revision. A BENCH_*.json
or events log found on disk six months later answers "what exactly
produced this?" from the manifest alone.

The config hash is sha256 over the sorted-key JSON of the dataclass dump,
so two runs with identical configs hash identically regardless of field
order or how the config object was built.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import subprocess
import sys
from typing import Optional


def config_digest(cfg) -> str:
    """Stable 16-hex-char digest of an ExperimentConfig (or any
    dataclass/dict tree)."""
    if dataclasses.is_dataclass(cfg):
        cfg = dataclasses.asdict(cfg)
    canon = json.dumps(cfg, sort_keys=True, default=str)
    return hashlib.sha256(canon.encode()).hexdigest()[:16]


def _git_rev() -> Optional[str]:
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        out = subprocess.run(["git", "-C", here, "rev-parse", "HEAD"],
                             capture_output=True, text=True, timeout=5)
        return out.stdout.strip() or None if out.returncode == 0 else None
    except Exception:
        return None


def build_manifest(cfg=None, mesh=None, extra: Optional[dict] = None) -> dict:
    """Assemble the manifest payload. ``cfg`` is the ExperimentConfig (or
    None for programs without one, e.g. bench); ``mesh`` supplies the
    shape/axis names when the caller has one. Backend/device fields are
    best-effort — a backend-free caller still gets config + versions."""
    import fedtpu

    out: dict = {
        "package": "fedtpu",
        "package_version": fedtpu.__version__,
        "python_version": sys.version.split()[0],
        "git_rev": _git_rev(),
        "argv": list(sys.argv),
    }
    if cfg is not None:
        out["config"] = dataclasses.asdict(cfg) \
            if dataclasses.is_dataclass(cfg) else dict(cfg)
        out["config_hash"] = config_digest(cfg)
    try:
        import jax
        out["jax_version"] = jax.__version__
        devs = jax.devices()
        out["backend"] = devs[0].platform
        out["device_count"] = len(devs)
        out["device_kinds"] = sorted({d.device_kind for d in devs})
        out["process_index"] = jax.process_index()
        out["process_count"] = jax.process_count()
        # Where this run's XLA compiles were persisted (None when the
        # persistent compilation cache is off) — the half of "why was
        # startup fast/slow?" the config dump alone can't answer.
        out["compilation_cache"] = jax.config.jax_compilation_cache_dir
    except Exception:  # fedtpu: noqa[FTP102] manifest is best-effort; no backend must not kill the run
        pass
    if mesh is not None:
        try:
            out["mesh_shape"] = {axis: int(n) for axis, n
                                 in mesh.shape.items()}
        except Exception:  # fedtpu: noqa[FTP102] mesh introspection differs across jax versions; manifest stays best-effort
            pass
    if extra:
        out.update(extra)
    return out
