"""Compile-on-first-use for the native loader: g++ -O2 -shared -fPIC, cached
next to the source, rebuilt when the source is newer than the .so. No build
system required at install time; no toolchain required at run time (callers
check ``available()`` and fall back)."""

from __future__ import annotations

import os
import pathlib
import shutil
import subprocess
import threading
import warnings
from typing import Optional

_HERE = pathlib.Path(__file__).resolve().parent
_SRC = _HERE / "csv_loader.cpp"
_SO = _HERE / "_fastcsv.so"
_lock = threading.Lock()


def ensure_built(verbose: bool = False) -> Optional[pathlib.Path]:
    """Return the shared-object path, compiling if stale; None if impossible."""
    with _lock:
        if _SO.exists() and _SO.stat().st_mtime >= _SRC.stat().st_mtime:
            return _SO
        cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
        if cxx is None:
            return None
        # Compile to a process-unique temp name, then rename atomically:
        # a concurrent process must never dlopen a half-written .so.
        tmp = _SO.with_suffix(f".so.tmp{os.getpid()}")
        cmd = [cxx, "-O2", "-std=c++17", "-shared", "-fPIC",
               str(_SRC), "-o", str(tmp)]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=120)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            if verbose:
                warnings.warn(f"[fedtpu.native] build failed:\n{proc.stderr}",
                              RuntimeWarning, stacklevel=2)
            tmp.unlink(missing_ok=True)
            return None
        os.replace(tmp, _SO)
        return _SO
