// Native CSV loader + label encoder for the fedtpu data pipeline.
//
// The reference's L1 data layer makes every MPI rank run pandas.read_csv +
// sklearn LabelEncoder over the whole file (SURVEY.md §3.1,
// FL_CustomMLPCLassifierImplementation_Multiple_Rounds.py:216-230). fedtpu
// is single-controller, and its host-side loader is this C++ module: one
// pass to parse, type-sniff, and sorted-unique label-encode, exposed to
// Python over a C ABI (ctypes — no pybind11 in the image). Semantics parity:
//   * a column is numeric iff every non-empty cell fully parses as a double
//     (pandas' effective inference for these files);
//   * categorical columns get codes into the lexicographically sorted unique
//     values — exactly sklearn LabelEncoder / np.unique(return_inverse=True);
//   * empty cells: NaN in numeric columns, the empty string as a category
//     otherwise;
//   * RFC-4180 double-quote fields are honored; CRLF, blank lines, and a
//     missing final newline are tolerated (blank lines skipped, like
//     pandas); hex literals are NOT numeric (pandas treats them as strings).
//   Known divergence from pandas: its default na_values tokens ("NA",
//   "null", ...) read as NaN there but as category strings here; "inf"/"nan"
//   spellings parse as floats on both paths.
//
// Build: g++ -O2 -shared -fPIC (fedtpu/native/build.py, cached .so). The
// Python side falls back to pandas if the toolchain is absent; a parity test
// asserts both loaders agree byte-for-byte on the shipped income CSV.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <map>
#include <string>
#include <vector>

namespace {

struct Table {
  std::vector<std::string> header;
  // Cells stored column-major as raw strings during parse, then resolved.
  std::vector<std::vector<std::string>> cols;
  std::vector<uint8_t> numeric;                 // per-column flag
  std::vector<std::vector<std::string>> classes; // per-categorical column
  std::vector<double> values;                   // row-major resolved matrix
  int64_t rows = 0;
  std::string error;
};

// Split one CSV record (which may span buffer lines only via quoting; we
// parse the whole file in one scan so embedded newlines inside quotes work).
void parse_file(const std::string& text, Table* t) {
  std::vector<std::string> field_buf;
  std::string cur;
  bool in_quotes = false;
  bool first_record = true;
  size_t i = 0, n = text.size();

  auto end_field = [&]() {
    field_buf.push_back(cur);
    cur.clear();
  };
  auto end_record = [&]() {
    if (field_buf.empty() && cur.empty()) return;  // blank line: skip, like pandas
    end_field();
    if (first_record) {
      t->header = field_buf;
      t->cols.resize(field_buf.size());
      first_record = false;
    } else {
      if (field_buf.size() != t->header.size()) {
        t->error = "ragged row with " + std::to_string(field_buf.size()) +
                   " fields, expected " + std::to_string(t->header.size());
        return;
      }
      for (size_t c = 0; c < field_buf.size(); ++c)
        t->cols[c].push_back(std::move(field_buf[c]));
      ++t->rows;
    }
    field_buf.clear();
  };

  while (i < n && t->error.empty()) {
    char ch = text[i];
    if (in_quotes) {
      if (ch == '"') {
        if (i + 1 < n && text[i + 1] == '"') { cur += '"'; ++i; }
        else in_quotes = false;
      } else cur += ch;
    } else if (ch == '"' && cur.empty()) {
      in_quotes = true;
    } else if (ch == ',') {
      end_field();
    } else if (ch == '\n') {
      if (!cur.empty() && cur.back() == '\r') cur.pop_back();
      end_record();
    } else {
      cur += ch;
    }
    ++i;
  }
  if (t->error.empty() && (!cur.empty() || !field_buf.empty())) {
    if (!cur.empty() && cur.back() == '\r') cur.pop_back();
    end_record();  // file without trailing newline
  }
}

bool parse_double(const std::string& s, double* out) {
  const char* p = s.c_str();
  while (*p == ' ' || *p == '\t') ++p;
  // strtod accepts hex ("0x2A"); pandas inference treats those as strings.
  const char* q = (*p == '+' || *p == '-') ? p + 1 : p;
  if (q[0] == '0' && (q[1] == 'x' || q[1] == 'X')) return false;
  char* end = nullptr;
  errno = 0;
  double v = std::strtod(p, &end);
  if (end == p || errno == ERANGE) return false;
  while (*end == ' ' || *end == '\t') ++end;
  if (*end != '\0') return false;
  *out = v;
  return true;
}

void resolve(Table* t) {
  const size_t ncols = t->header.size();
  t->numeric.assign(ncols, 1);
  t->classes.resize(ncols);
  t->values.assign(static_cast<size_t>(t->rows) * ncols, 0.0);

  for (size_t c = 0; c < ncols; ++c) {
    auto& col = t->cols[c];
    double v;
    bool is_num = true;
    for (const auto& cell : col) {
      if (cell.empty()) continue;            // missing -> NaN, stays numeric
      if (!parse_double(cell, &v)) { is_num = false; break; }
    }
    t->numeric[c] = is_num ? 1 : 0;
    if (is_num) {
      for (int64_t r = 0; r < t->rows; ++r)
        t->values[r * ncols + c] =
            col[r].empty() ? std::nan("") : (parse_double(col[r], &v), v);
    } else {
      // Sorted-unique codes == sklearn LabelEncoder == np.unique ordering.
      std::vector<std::string> uniq(col.begin(), col.end());
      std::sort(uniq.begin(), uniq.end());
      uniq.erase(std::unique(uniq.begin(), uniq.end()), uniq.end());
      std::map<std::string, double> code;
      for (size_t k = 0; k < uniq.size(); ++k) code[uniq[k]] = double(k);
      for (int64_t r = 0; r < t->rows; ++r)
        t->values[r * ncols + c] = code[col[r]];
      t->classes[c] = std::move(uniq);
    }
    col.clear();
    col.shrink_to_fit();
  }
}

// NUL-delimited transport: cells may legally contain newlines (quoted
// fields), so '\n' cannot delimit. A NUL can't appear in a text CSV cell.
std::string join_nul(const std::vector<std::string>& parts) {
  std::string out;
  for (size_t k = 0; k < parts.size(); ++k) {
    if (k) out += '\0';
    out += parts[k];
  }
  return out;
}

}  // namespace

extern "C" {

void* csv_open(const char* path) {
  auto* t = new Table();
  std::ifstream f(path, std::ios::binary);
  if (!f) {
    t->error = "cannot open file";
    return t;
  }
  std::string text((std::istreambuf_iterator<char>(f)),
                   std::istreambuf_iterator<char>());
  parse_file(text, t);
  if (t->error.empty()) resolve(t);
  return t;
}

const char* csv_error(void* h) {
  auto* t = static_cast<Table*>(h);
  return t->error.empty() ? nullptr : t->error.c_str();
}

int64_t csv_rows(void* h) { return static_cast<Table*>(h)->rows; }

int64_t csv_cols(void* h) {
  return static_cast<int64_t>(static_cast<Table*>(h)->header.size());
}

int csv_col_is_numeric(void* h, int64_t col) {
  return static_cast<Table*>(h)->numeric[col];
}

// Row-major (rows x cols) float64 matrix; categorical cells hold their code.
void csv_fill(void* h, double* out) {
  auto* t = static_cast<Table*>(h);
  std::memcpy(out, t->values.data(), t->values.size() * sizeof(double));
}

// Header names, NUL-delimited; returns the exact byte count. Call with
// buf=null to size, then again with a buffer; the caller slices by the
// returned length (the payload itself contains the delimiting NULs).
int64_t csv_header(void* h, char* buf, int64_t buflen) {
  std::string s = join_nul(static_cast<Table*>(h)->header);
  if (buf && buflen > 0) {
    int64_t n = std::min<int64_t>(buflen, s.size());
    std::memcpy(buf, s.data(), n);
  }
  return static_cast<int64_t>(s.size());
}

// Sorted unique values of a categorical column, NUL-delimited.
int64_t csv_col_classes(void* h, int64_t col, char* buf, int64_t buflen) {
  std::string s = join_nul(static_cast<Table*>(h)->classes[col]);
  if (buf && buflen > 0) {
    int64_t n = std::min<int64_t>(buflen, s.size());
    std::memcpy(buf, s.data(), n);
  }
  return static_cast<int64_t>(s.size());
}

void csv_close(void* h) { delete static_cast<Table*>(h); }

}  // extern "C"
