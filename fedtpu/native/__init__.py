"""Native (C++) host runtime for fedtpu: the CSV loader / label encoder.

The compute path is JAX/XLA; the host runtime around it is native where the
work is host-bound. ``load_csv`` is the C++ replacement for the
pandas.read_csv + per-column LabelEncoder preamble every reference rank runs
(FL_CustomMLPCLassifierImplementation_Multiple_Rounds.py:216-230): one parse
pass, type inference, and sorted-unique label encoding behind a C ABI.

Bindings are ctypes (pybind11 is not in the image); the shared object is
compiled on first use by :mod:`fedtpu.native.build` and cached next to the
source. ``available()`` is False when no C++ toolchain exists — callers
(fedtpu.data.tabular) fall back to the pandas path, which a parity test
pins to identical output.
"""

from __future__ import annotations

import ctypes
from typing import Dict, Optional, Tuple

import numpy as np

from fedtpu.native.build import ensure_built

_lib = None
_lib_failed = False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.csv_open.argtypes = [ctypes.c_char_p]
    lib.csv_open.restype = ctypes.c_void_p
    lib.csv_error.argtypes = [ctypes.c_void_p]
    lib.csv_error.restype = ctypes.c_char_p
    lib.csv_rows.argtypes = [ctypes.c_void_p]
    lib.csv_rows.restype = ctypes.c_int64
    lib.csv_cols.argtypes = [ctypes.c_void_p]
    lib.csv_cols.restype = ctypes.c_int64
    lib.csv_col_is_numeric.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.csv_col_is_numeric.restype = ctypes.c_int
    lib.csv_fill.argtypes = [ctypes.c_void_p,
                             np.ctypeslib.ndpointer(np.float64, flags="C")]
    lib.csv_fill.restype = None
    lib.csv_header.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_int64]
    lib.csv_header.restype = ctypes.c_int64
    lib.csv_col_classes.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                    ctypes.c_char_p, ctypes.c_int64]
    lib.csv_col_classes.restype = ctypes.c_int64
    lib.csv_close.argtypes = [ctypes.c_void_p]
    lib.csv_close.restype = None
    return lib


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    if _lib is None and not _lib_failed:
        so = ensure_built()
        if so is None:
            _lib_failed = True
        else:
            _lib = _bind(ctypes.CDLL(str(so)))
    return _lib


def available() -> bool:
    return _get_lib() is not None


def _read_strings(fn, *args) -> list:
    """Read a NUL-delimited string list over the C ABI (cells may contain
    newlines via quoted fields, so '\\n' cannot delimit)."""
    n = int(fn(*args, None, 0))
    if n == 0:
        return [""]
    buf = ctypes.create_string_buffer(n)
    fn(*args, buf, n)
    return [part.decode("utf-8") for part in buf.raw[:n].split(b"\x00")]


def load_csv(path: str) -> Tuple[Tuple[str, ...], np.ndarray,
                                 np.ndarray, Dict[str, np.ndarray]]:
    """Parse ``path`` natively. Returns ``(header, numeric_mask, matrix,
    classes)``: matrix is float64 row-major with categorical columns already
    label-encoded; classes maps each categorical column name to its sorted
    unique original values (LabelEncoder ``classes_``)."""
    lib = _get_lib()
    if lib is None:
        raise RuntimeError("native CSV loader unavailable (no C++ toolchain)")
    h = lib.csv_open(path.encode("utf-8"))
    try:
        err = lib.csv_error(h)
        if err:
            raise ValueError(f"native CSV parse of {path!r}: "
                             f"{err.decode('utf-8')}")
        rows, cols = lib.csv_rows(h), lib.csv_cols(h)
        header = tuple(_read_strings(lib.csv_header, h))
        numeric = np.array([bool(lib.csv_col_is_numeric(h, c))
                            for c in range(cols)])
        mat = np.empty((rows, cols), np.float64)
        lib.csv_fill(h, mat)
        classes = {}
        for c in range(cols):
            if not numeric[c]:
                vals = _read_strings(lib.csv_col_classes, h, c)
                classes[header[c]] = np.array(vals, dtype=object)
        return header, numeric, mat, classes
    finally:
        lib.csv_close(h)
