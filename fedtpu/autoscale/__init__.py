"""SLO-driven autoscaling control plane (ROADMAP item 4).

Three layers, strictly stacked so every one is testable on its own:

- :mod:`fedtpu.autoscale.signals` — a :class:`SignalBus` folds live
  telemetry (serving ``stats`` payloads, heartbeat files, cohort
  prefetch gauges) into a versioned, immutable :class:`Snapshot` per
  control tick.
- :mod:`fedtpu.autoscale.policy` — pure virtual-clock policy functions
  map a snapshot to an ordered decision list (``grow`` / ``shrink`` /
  ``set_cohort_size`` / ``set_tick_cadence`` / ``pre_drain`` /
  ``hold``). Pure in (policy config, snapshot stream): the decision
  sequence is bitwise-replayable.
- :mod:`fedtpu.autoscale.controller` — the actuator: executes decisions
  through the reshard protocol (SIGUSR1/SIGUSR2 to the gang
  supervisor), the serving engine's ``configure`` / ``pre_drain``
  protocol ops, and a deterministic virtual-time simulator whose
  decision JSONL is golden-gated in tier-1.

Import the submodules directly (``from fedtpu.autoscale import policy``);
this package initializer deliberately imports nothing, so jax-free
callers (signals/policy, the simulator) never pull in the serving
protocol client transitively.
"""

__all__ = ["signals", "policy", "controller"]
