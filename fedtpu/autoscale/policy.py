"""Pure virtual-clock scaling policies: Snapshot in, decision list out.

A policy is a pure function of (policy config, snapshot stream): it
carries its between-tick memory in an explicit JSON-serializable state
dict that the caller threads through :meth:`Policy.decide`, and it
never reads a clock, a file, or a socket. That purity is the contract
the simulator's golden gate rests on — identical config + snapshot
stream must yield a bitwise-identical decision sequence.

Decisions are ordered (the actuator executes them left to right) and
drawn from a closed vocabulary::

    grow(n)              add n gang members (reshard grow notice)
    shrink(n)            remove n gang members (reshard shrink notice)
    set_cohort_size(v)   retarget the serving engine's per-tick cohort
                         (its count-driven flush threshold)
    set_tick_cadence(v)  retarget the serving tick interval (seconds)
    pre_drain(victim)    spool the pending updates ahead of losing
                         ``victim`` — always ordered BEFORE the shrink
                         that loses it
    hold                 no action this tick

The default :class:`ThresholdHysteresisPolicy` is a plain
threshold-with-hysteresis controller: a scale signal must persist for
``hysteresis_ticks`` consecutive snapshots before it acts, and every
action opens a ``cooldown_ticks`` refractory window so the control loop
cannot flap faster than the actuated system can respond. A preemption
NOTICE bypasses both — the deadline does not wait for hysteresis.

Third-party policies register through :func:`register_policy` and are
selected by name (``fedtpu autoscale --policy``).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Callable, Dict, List, Tuple

from fedtpu.autoscale.signals import Snapshot
from fedtpu.config import AutoscaleConfig

DECISION_SCHEMA_VERSION = 1

GROW = "grow"
SHRINK = "shrink"
SET_COHORT_SIZE = "set_cohort_size"
SET_TICK_CADENCE = "set_tick_cadence"
PRE_DRAIN = "pre_drain"
HOLD = "hold"

KINDS = (GROW, SHRINK, SET_COHORT_SIZE, SET_TICK_CADENCE, PRE_DRAIN, HOLD)


@dataclass(frozen=True)
class Decision:
    """One actuator instruction. Unused fields keep their defaults so
    every decision serializes with the same fixed shape (bitwise
    goldens tolerate no optional keys)."""

    kind: str
    n: int = 0           # grow/shrink member count
    value: float = 0.0   # set_cohort_size / set_tick_cadence target
    victim: int = -1     # pre_drain target process index

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown decision kind {self.kind!r}; "
                             f"pick from {list(KINDS)}")

    def to_json(self) -> dict:
        return {"kind": self.kind, "n": self.n, "value": self.value,
                "victim": self.victim}


def grow(n: int = 1) -> Decision:
    return Decision(GROW, n=int(n))


def shrink(n: int = 1) -> Decision:
    return Decision(SHRINK, n=int(n))


def set_cohort_size(v: int) -> Decision:
    return Decision(SET_COHORT_SIZE, value=float(v))


def set_tick_cadence(v: float) -> Decision:
    return Decision(SET_TICK_CADENCE, value=float(v))


def pre_drain(victim: int) -> Decision:
    return Decision(PRE_DRAIN, victim=int(victim))


def hold() -> Decision:
    return Decision(HOLD)


def decision_line(snapshot: Snapshot, decisions: List[Decision]) -> str:
    """One canonical-JSON line of the decision sequence: snapshot
    version + virtual time + the ordered decisions. Same canonical form
    as the serving history lines (sorted keys, no whitespace), so byte
    comparison IS the replay check."""
    return json.dumps({"v": DECISION_SCHEMA_VERSION,
                       "version": snapshot.version,
                       "t": snapshot.t,
                       "decisions": [d.to_json() for d in decisions]},
                      sort_keys=True, separators=(",", ":"))


class Policy:
    """Pluggable policy interface. Subclasses implement :meth:`decide`
    as a pure function of ``(snapshot, state)`` and return the ordered
    decision list plus the successor state dict."""

    name = "base"

    def initial_state(self) -> dict:
        return {}

    def decide(self, snapshot: Snapshot,
               state: dict) -> Tuple[List[Decision], dict]:
        raise NotImplementedError


class ThresholdHysteresisPolicy(Policy):
    """The default controller (see module docstring for the shape)."""

    name = "threshold"

    def __init__(self, cfg: AutoscaleConfig):
        self.cfg = cfg

    def initial_state(self) -> dict:
        return {"hot": 0, "cold": 0, "cooldown": 0}

    def _overload(self, snap: Snapshot) -> bool:
        c = self.cfg
        reject = (snap.rates.get("reject_backpressure", 0.0)
                  + snap.rates.get("reject_rate", 0.0))
        return (snap.backlog >= c.backlog_high
                or snap.slo_burn >= c.burn_high
                or reject >= c.reject_high)

    def _underload(self, snap: Snapshot) -> bool:
        c = self.cfg
        reject = (snap.rates.get("reject_backpressure", 0.0)
                  + snap.rates.get("reject_rate", 0.0))
        return (snap.backlog <= c.backlog_low
                and snap.slo_burn <= c.burn_high / 2.0
                and reject < c.reject_high / 2.0)

    def decide(self, snapshot: Snapshot,
               state: dict) -> Tuple[List[Decision], dict]:
        c = self.cfg
        st = dict(state) if state else self.initial_state()
        if snapshot.notice >= 0:
            # Preemption notice: spool ahead of the loss, then shrink.
            # No hysteresis — the deadline is the scheduler's, not ours.
            st = {"hot": 0, "cold": 0, "cooldown": c.cooldown_ticks}
            return [pre_drain(snapshot.notice), shrink(1)], st
        if st.get("cooldown", 0) > 0:
            st["cooldown"] = st["cooldown"] - 1
            return [hold()], st
        overload = self._overload(snapshot)
        underload = self._underload(snapshot)
        st["hot"] = st.get("hot", 0) + 1 if overload else 0
        st["cold"] = (st.get("cold", 0) + 1
                      if underload and not overload else 0)
        if st["hot"] >= c.hysteresis_ticks:
            st = {"hot": 0, "cold": 0, "cooldown": c.cooldown_ticks}
            return [grow(1), set_tick_cadence(c.tick_fast_s),
                    set_cohort_size(c.cohort_high)], st
        if st["cold"] >= c.hysteresis_ticks:
            st = {"hot": 0, "cold": 0, "cooldown": c.cooldown_ticks}
            return [shrink(1), set_tick_cadence(c.tick_slow_s),
                    set_cohort_size(c.cohort_low)], st
        return [hold()], st


POLICIES: Dict[str, Callable[[AutoscaleConfig], Policy]] = {
    "threshold": ThresholdHysteresisPolicy,
}


def register_policy(name: str,
                    factory: Callable[[AutoscaleConfig], Policy]) -> None:
    """Register a policy factory under ``name`` (the plugin hook).
    Re-registering a taken name is an error — silent replacement would
    make `--policy` mean different things in different processes."""
    if name in POLICIES:
        raise ValueError(f"policy {name!r} is already registered")
    POLICIES[name] = factory


def get_policy(name: str, cfg: AutoscaleConfig) -> Policy:
    try:
        factory = POLICIES[name]
    except KeyError:
        raise ValueError(f"unknown policy {name!r}; "
                         f"pick from {sorted(POLICIES)}") from None
    return factory(cfg)
