"""Autoscale actuation: the virtual-time simulator and the live controller.

Two consumers of the same (SignalBus -> Policy) stack:

- :func:`simulate` — a deterministic control-loop replay in PURE virtual
  time. A heavy-tailed arrival trace (fedtpu.serving.traces) runs
  through the REAL :class:`AdmissionController` and a closed-form
  service model (capacity x cohort / tick-interval updates per second);
  every ``control_interval_s`` the bus folds a snapshot, the policy
  decides, and the decisions feed back into the model (grow/shrink move
  capacity, cadence/cohort retarget the drain rate, a preemption notice
  triggers pre-drain + shrink). No wall clock anywhere, so the decision
  JSONL is bitwise-replayable and golden-gated in tier-1
  (``fedtpu check --autoscale-sim``).

- :class:`LiveController` — the same loop against a real deployment:
  polls the serving ``stats`` op for the machine-readable signals
  block, reads gang heartbeat files for membership, and executes
  decisions through the serving ``configure``/``pre_drain`` protocol
  ops and SIGUSR1/SIGUSR2 to the gang supervisor (the reshard notice
  path — fedtpu.resilience.reshard). Preemption notices arrive through
  a notice FILE (``{"victim": p}``) the scheduler drill writes, so the
  chaos harness and a real maintenance hook share one mechanism.

jax-free throughout: the simulator must run in the jax-free CLI path
(like loadgen/report), and the live controller is a sidecar that never
touches a device.
"""

from __future__ import annotations

import json
import os
import signal as _signal
import time as _time
from collections import deque
from typing import Dict, List, Optional

from fedtpu.autoscale.policy import (GROW, HOLD, PRE_DRAIN, SET_COHORT_SIZE,
                                     SET_TICK_CADENCE, SHRINK, Decision,
                                     Policy, decision_line, get_policy)
from fedtpu.autoscale.signals import SignalBus, read_gang_members
from fedtpu.config import AutoscaleConfig
from fedtpu.serving.admission import (ADMITTED, AdmissionController,
                                      AdmissionPolicy)
from fedtpu.serving.engine import LATENCY_BINS_S
from fedtpu.telemetry.metrics import Histogram

# ---------------------------------------------------------------------------
# Simulation contract: these constants are part of the committed golden
# (tests/goldens/autoscale_sim.jsonl). Changing ANY of them — or the
# default AutoscaleConfig, the default policy, the admission model, or
# the trace synthesizer — legitimately regenerates the golden; the gate
# exists so that regeneration is a reviewed decision, not an accident.

SIM_USERS = 2000
SIM_ARRIVALS = 6000
SIM_HORIZON_S = 30.0
SIM_SEED = 7
SIM_PROCESSES = 2
# A preemption notice for process 1 lands mid-burst (the backlog is a
# few hundred deep at 2.5 s), so the golden's pre_drain spools real
# pending work before the shrink, not an empty queue.
SIM_NOTICE_AT_S = 2.5
SIM_NOTICE_VICTIM = 1
# Admission knobs for the simulated front door: the rate limit bites on
# bursts, backpressure bites when the backlog outruns the drain rate.
SIM_ADMISSION = AdmissionPolicy(rate_limit=400.0, rate_burst=64.0,
                                max_pending=4096, stale_deprioritize=4,
                                stale_reject=16, window_s=5.0)
# Service-model starting point (the policy retargets both at runtime).
SIM_TICK_INTERVAL_S = 0.5
# Safety valve: a policy that never drains the queue still terminates.
_SIM_MAX_TICKS = 4096


def simulate(cfg: Optional[AutoscaleConfig] = None, *,
             policy: Optional[Policy] = None,
             trace_path: Optional[str] = None,
             users: int = SIM_USERS, arrivals: int = SIM_ARRIVALS,
             horizon_s: float = SIM_HORIZON_S, seed: int = SIM_SEED,
             processes: int = SIM_PROCESSES,
             notice_at_s: float = SIM_NOTICE_AT_S,
             notice_victim: int = SIM_NOTICE_VICTIM,
             tracer=None) -> dict:
    """Replay a bursty heavy-tailed trace against a policy in pure
    virtual time. Returns ``{"lines": [...], "summary": {...}}`` where
    ``lines`` is the canonical decision JSONL (one line per control
    tick) and ``summary`` aggregates what the control loop did."""
    cfg = cfg if cfg is not None else AutoscaleConfig()
    policy = policy if policy is not None else get_policy(cfg.policy, cfg)
    if trace_path:
        from fedtpu.serving.traces import load_trace_arrays
        _, t, user, lat = load_trace_arrays(trace_path)
    else:
        from fedtpu.serving.traces import synthesize_trace
        _, t, user, lat = synthesize_trace(users, arrivals, horizon_s,
                                           seed=seed)
    adm = AdmissionController(SIM_ADMISSION)
    hist = Histogram(bins=LATENCY_BINS_S)
    bus = SignalBus(cfg.objective_s, cfg.error_budget)
    pstate = policy.initial_state()

    capacity = int(processes)
    tick_interval = float(SIM_TICK_INTERVAL_S)
    cohort = int(cfg.cohort_low)
    members: Dict[int, str] = {p: "serving" for p in range(capacity)}
    queue: deque = deque()          # admitted arrival timestamps (virtual)
    notice_pending = notice_at_s >= 0
    admitted = incorporated = spooled = 0
    counts: Dict[str, int] = {}
    lines: List[str] = []
    i, n = 0, len(t)

    k = 0
    while (i < n or queue) and k < _SIM_MAX_TICKS:
        k += 1
        t_now = k * cfg.control_interval_s
        # Ingest every arrival up to this control tick through REAL
        # admission. Staleness model: versions advance once per engine
        # tick, so a client that trained for `lat` is ~lat/tick versions
        # behind — deterministic, no device needed.
        while i < n and t[i] <= t_now:
            staleness = (int(lat[i] / tick_interval)
                         if tick_interval > 0 else 0)
            verdict = adm.decide(float(t[i]), staleness, len(queue))
            if verdict in ADMITTED:
                queue.append(float(t[i]))
                admitted += 1
            i += 1
        # Serve: capacity members x cohort updates per engine tick.
        if tick_interval > 0:
            served = int(capacity * cohort * cfg.control_interval_s
                         / tick_interval)
        else:
            served = len(queue)
        served = min(served, len(queue))
        for _ in range(served):
            hist.observe(t_now - queue.popleft())
            incorporated += 1
        notice = (notice_victim
                  if notice_pending and t_now >= notice_at_s else -1)
        win = adm.window_rates(t_now)
        snap = bus.fold(
            t_now,
            stats={"backlog": len(queue), "incorporated": incorporated,
                   "admitted": admitted,
                   "window_decisions": win["decisions"],
                   "rates": win["rates"]},
            members=sorted(members.items()), notice=notice,
            latency_hist=hist.to_dict())
        decisions, pstate = policy.decide(snap, pstate)
        for d in decisions:
            counts[d.kind] = counts.get(d.kind, 0) + 1
            if d.kind == GROW:
                for _ in range(d.n):
                    if capacity >= cfg.max_capacity:
                        break
                    parked = [p for p, s in sorted(members.items())
                              if s != "serving"]
                    p = parked[0] if parked else len(members)
                    members[p] = "serving"
                    capacity += 1
            elif d.kind == SHRINK:
                for _ in range(d.n):
                    if capacity <= cfg.min_capacity:
                        break
                    victim = (notice if notice >= 0
                              else max(p for p, s in members.items()
                                       if s == "serving"))
                    members[victim] = "parked"
                    capacity -= 1
                if notice >= 0:
                    notice_pending = False
            elif d.kind == SET_TICK_CADENCE:
                tick_interval = float(d.value)
            elif d.kind == SET_COHORT_SIZE:
                cohort = int(d.value)
            elif d.kind == PRE_DRAIN:
                # Durability copy of the whole backlog ahead of the loss.
                spooled += len(queue)
        lines.append(decision_line(snap, decisions))
        if tracer is not None:
            tracer.event("autoscale_decision", round=snap.version,
                         t_virtual=snap.t, backlog=snap.backlog,
                         slo_burn=snap.slo_burn, notice=snap.notice,
                         decisions=[d.to_json() for d in decisions])
    summary = {
        "control_ticks": len(lines),
        "arrivals": n,
        "admitted": admitted,
        "incorporated": incorporated,
        "spooled": spooled,
        "backlog_end": len(queue),
        "capacity_end": capacity,
        "decisions": {kind: counts.get(kind, 0) for kind in sorted(counts)},
        "truncated": bool(queue) or i < n,
    }
    if tracer is not None:
        tracer.event("autoscale_summary", **summary)
    return {"lines": lines, "summary": summary}


def write_decisions(path: str, lines: List[str]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        for line in lines:
            fh.write(line + "\n")
    os.replace(tmp, path)


def compare_decisions(lines: List[str], golden_path: str) -> dict:
    """Bitwise golden comparison, audit-gate style: every line must
    match exactly. Returns ``{"ok": bool, "reason": str}``."""
    try:
        with open(golden_path, encoding="utf-8") as fh:
            golden = [ln.rstrip("\n") for ln in fh if ln.strip()]
    except OSError as e:
        return {"ok": False, "reason": f"golden unreadable: {e}"}
    if len(golden) != len(lines):
        return {"ok": False,
                "reason": (f"decision count {len(lines)} != golden "
                           f"{len(golden)}")}
    for idx, (got, want) in enumerate(zip(lines, golden)):
        if got != want:
            return {"ok": False,
                    "reason": (f"first divergence at line {idx + 1}: "
                               f"got {got[:120]} want {want[:120]}")}
    return {"ok": True, "reason": f"{len(lines)} decision lines match"}


# ---------------------------------------------------------------------------
# live mode


class LiveController:
    """Attach the control loop to a running deployment (see module
    docstring). Wall time only paces the polling; every decision input
    is the deployment's own virtual-clock telemetry."""

    def __init__(self, cfg: AutoscaleConfig, policy: Optional[Policy] = None,
                 *, host: str = "127.0.0.1", port: int = 0,
                 supervisor_pid: int = 0, heartbeat: Optional[str] = None,
                 process_count: int = 0, notice_file: Optional[str] = None,
                 spool_path: Optional[str] = None, tracer=None):
        self.cfg = cfg
        self.policy = (policy if policy is not None
                       else get_policy(cfg.policy, cfg))
        self.host, self.port = host, int(port)
        self.supervisor_pid = int(supervisor_pid)
        self.heartbeat = heartbeat
        self.process_count = int(process_count)
        self.notice_file = notice_file
        self.spool_path = spool_path
        self.tracer = tracer
        self.bus = SignalBus(cfg.objective_s, cfg.error_budget)
        self.state = self.policy.initial_state()
        self._conn = None
        self._noticed: set = set()
        self.acted: Dict[str, int] = {}

    def _connection(self):
        # The retrying client, not a raw Connection: a control tick that
        # lands while the serving process restarts (the exact moment a
        # controller exists for) reconnects with backoff instead of
        # killing the control loop.
        if self._conn is None:
            from fedtpu.serving.client import GatewayClient
            self._conn = GatewayClient(host=self.host, port=self.port,
                                       retries=4, backoff_s=0.1)
            self._conn.hello()
        return self._conn

    def _poll_stats(self) -> dict:
        if not self.port:
            return {}
        resp = self._connection().request({"op": "stats"})
        return dict(resp.get("signals") or {})

    def _poll_notice(self) -> int:
        """A pending preemption notice (victim index), -1 when none.
        Each notice file payload is acted on once."""
        if not self.notice_file or not os.path.exists(self.notice_file):
            return -1
        try:
            with open(self.notice_file, encoding="utf-8") as fh:
                rec = json.load(fh)
            victim = int(rec.get("victim", -1))
        except (OSError, ValueError):
            return -1
        if victim < 0 or victim in self._noticed:
            return -1
        return victim

    def step(self, now: Optional[float] = None):
        """One control tick: fold, decide, act. Returns the
        ``(snapshot, decisions)`` pair for callers that log or test."""
        stats = self._poll_stats()
        members = ()
        if self.heartbeat and self.process_count:
            members = read_gang_members(self.heartbeat, self.process_count)
        notice = self._poll_notice()
        snap = self.bus.fold(float(stats.get("t", now or _time.time())),
                             stats=stats, members=members, notice=notice)
        decisions, self.state = self.policy.decide(snap, self.state)
        if notice >= 0:
            self._noticed.add(notice)
        if self.tracer is not None:
            self.tracer.event("autoscale_decision", round=snap.version,
                              t_virtual=snap.t, backlog=snap.backlog,
                              slo_burn=snap.slo_burn, notice=snap.notice,
                              decisions=[d.to_json() for d in decisions])
        self._act(decisions)
        return snap, decisions

    def _act(self, decisions: List[Decision]) -> None:
        for d in decisions:
            if d.kind == HOLD:
                continue
            self.acted[d.kind] = self.acted.get(d.kind, 0) + 1
            if d.kind == PRE_DRAIN and self.port:
                msg = {"op": "pre_drain"}
                if self.spool_path:
                    msg["path"] = self.spool_path
                resp = self._connection().request(msg)
                if self.tracer is not None:
                    self.tracer.event("autoscale_pre_drain",
                                      victim=d.victim,
                                      spooled=resp.get("spooled"),
                                      path=resp.get("path"))
            elif d.kind == SET_TICK_CADENCE and self.port:
                self._connection().request(
                    {"op": "configure", "tick_interval_s": d.value})
            elif d.kind == SET_COHORT_SIZE and self.port:
                self._connection().request(
                    {"op": "configure", "flush_every": int(d.value)})
            elif d.kind in (GROW, SHRINK) and self.supervisor_pid:
                # The reshard notice path: the gang supervisor forwards
                # SIGUSR1 (shrink) / SIGUSR2 (grow) to every member.
                sig = (_signal.SIGUSR1 if d.kind == SHRINK
                       else _signal.SIGUSR2)
                try:
                    os.kill(self.supervisor_pid, sig)
                except OSError as e:
                    if self.tracer is not None:
                        self.tracer.event("autoscale_act_failed",
                                          decision=d.kind, error=str(e))
                    continue
            if self.tracer is not None:
                self.tracer.event("autoscale_act", decision=d.kind, n=d.n,
                                  value=d.value, victim=d.victim)

    def run(self, duration_s: float = 0.0,
            interval_s: Optional[float] = None,
            stop_after_notice: bool = False) -> dict:
        """Poll until ``duration_s`` elapses (0 = forever /
        KeyboardInterrupt) or, with ``stop_after_notice``, until a
        preemption notice has been acted on — the drill mode the chaos
        harness drives. Returns a run summary."""
        interval = (interval_s if interval_s is not None
                    else self.cfg.control_interval_s)
        start = _time.monotonic()
        ticks = 0
        try:
            while True:
                _, decisions = self.step()
                ticks += 1
                if stop_after_notice and any(d.kind == PRE_DRAIN
                                             for d in decisions):
                    break
                if duration_s and _time.monotonic() - start >= duration_s:
                    break
                _time.sleep(interval)
        except KeyboardInterrupt:
            pass
        finally:
            if self._conn is not None:
                self._conn.close()
                self._conn = None
        summary = {"control_ticks": ticks, "acted": dict(self.acted),
                   "wall_s": _time.monotonic() - start}
        if self.tracer is not None:
            self.tracer.event("autoscale_summary", **summary)
        return summary
