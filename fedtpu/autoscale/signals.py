"""SignalBus: fold live telemetry into versioned controller snapshots.

The control plane never reads raw event streams. Every control tick the
:class:`SignalBus` folds whatever sources are present — the serving
``stats`` protocol payload (its machine-readable ``signals`` block),
per-process supervisor heartbeat files, and the cohort prefetch gauges
riding inside the stats counters — into one immutable
:class:`Snapshot`, stamped with a monotonically increasing ``version``.
Policies see snapshots and nothing else, which is what makes the
decision sequence replayable: record the snapshot stream and the policy
is a pure function of it.

SLO burn follows the error-budget convention: with objective
``objective_s`` on update-to-incorporation latency and an allowed
violation share ``error_budget``, burn is

    (share of observed latencies > objective_s) / error_budget

so 1.0 means the budget is being consumed exactly as provisioned and
anything above it is an overload signal. The share comes from the
cumulative ``update_to_incorporation`` histogram (telemetry.metrics
``le`` buckets) — the objective is resolved against the closest bucket
bound at or above it, so burn is exact with respect to what the
histogram can represent, never an interpolation.

No jax and no sockets in this module — folding is pure bookkeeping, the
same testability bar as admission control.
"""

from __future__ import annotations

import os
import time as _time
from dataclasses import dataclass, field
from typing import Mapping, Optional, Sequence, Tuple

from fedtpu.resilience.supervisor import read_heartbeat

SNAPSHOT_SCHEMA_VERSION = 1

# A heartbeat older than this (wall seconds) marks its member ``stale``
# — the same liveness idea as the supervisor's --hang-timeout, scaled
# for a control loop that ticks every second or two.
DEFAULT_STALE_AFTER_S = 15.0


def slo_burn_from_hist(hist: Optional[Mapping], objective_s: float,
                       error_budget: float) -> float:
    """Error-budget burn rate from a cumulative-bucket histogram dict
    (the ``telemetry.metrics.Histogram.to_dict`` shape). 0.0 when the
    histogram is missing or empty."""
    if not hist or not hist.get("count"):
        return 0.0
    if error_budget <= 0:
        raise ValueError("error_budget must be > 0")
    count = int(hist["count"])
    bins = [float(b) for b in hist.get("bins", ())]
    bucket_counts = [int(c) for c in hist.get("bucket_counts", ())]
    within = 0
    for b, c in zip(bins, bucket_counts):
        if b >= objective_s:
            within = c
            break
    else:
        within = count        # objective beyond the last bound: all pass
    violating = count - within
    return (violating / count) / error_budget


@dataclass(frozen=True)
class Snapshot:
    """One versioned controller input. ``t`` is the virtual clock the
    producing system runs on (trace seconds for serving); ``members``
    is the gang view as ``(process_index, status)`` pairs; ``notice``
    is the process index of a pending preemption notice (-1: none)."""

    version: int
    t: float
    backlog: int = 0              # admitted-but-not-incorporated depth
    buffered: int = 0             # K-buffer fill
    incorporated: int = 0
    admitted: int = 0
    window_decisions: int = 0     # admission decisions inside the window
    rates: Mapping[str, float] = field(default_factory=dict)
    slo_burn: float = 0.0
    prefetch_stall_s: float = 0.0
    prefetch_stalls: int = 0
    members: Tuple[Tuple[int, str], ...] = ()
    notice: int = -1

    def to_json(self) -> dict:
        return {
            "v": SNAPSHOT_SCHEMA_VERSION,
            "version": self.version,
            "t": self.t,
            "backlog": self.backlog,
            "buffered": self.buffered,
            "incorporated": self.incorporated,
            "admitted": self.admitted,
            "window_decisions": self.window_decisions,
            "rates": dict(self.rates),
            "slo_burn": self.slo_burn,
            "prefetch_stall_s": self.prefetch_stall_s,
            "prefetch_stalls": self.prefetch_stalls,
            "members": [list(m) for m in self.members],
            "notice": self.notice,
        }


def read_gang_members(heartbeat_base: str, process_count: int,
                      now: Optional[float] = None,
                      stale_after_s: float = DEFAULT_STALE_AFTER_S,
                      ) -> Tuple[Tuple[int, str], ...]:
    """Gang membership view from per-process heartbeat files (the
    ``heartbeat_path_for`` derivation the supervisor writes). Statuses:
    the heartbeat's own ``status`` field (``parked`` / ``running`` /
    ``serving`` / ...), downgraded to ``stale`` when the beat is older
    than ``stale_after_s`` wall seconds and to ``missing`` when the
    file does not exist."""
    from fedtpu.resilience.distributed import heartbeat_path_for
    if now is None:
        now = _time.time()
    members = []
    for p in range(process_count):
        path = heartbeat_path_for(heartbeat_base, p)
        rec = read_heartbeat(path) if os.path.exists(path) else None
        if rec is None:
            members.append((p, "missing"))
            continue
        status = str(rec.get("status", "unknown"))
        age = now - float(rec.get("time", 0.0))
        if status != "parked" and age > stale_after_s:
            status = "stale"
        members.append((p, status))
    return tuple(members)


class SignalBus:
    """Folds telemetry sources into the next :class:`Snapshot`.

    ``objective_s`` / ``error_budget`` configure the SLO-burn fold; a
    serving stats payload that already carries a ``slo_burn`` (satellite
    export) wins over the histogram recomputation, so live mode and
    simulation read identical numbers.
    """

    def __init__(self, objective_s: float = 1.0,
                 error_budget: float = 0.1):
        if objective_s <= 0 or error_budget <= 0:
            raise ValueError("objective_s and error_budget must be > 0")
        self.objective_s = float(objective_s)
        self.error_budget = float(error_budget)
        self._version = 0

    @property
    def version(self) -> int:
        """Version the NEXT fold will stamp."""
        return self._version

    def fold(self, t: float, stats: Optional[Mapping] = None,
             members: Sequence[Tuple[int, str]] = (),
             notice: int = -1,
             latency_hist: Optional[Mapping] = None) -> Snapshot:
        """One control tick: fold a serving ``signals`` block (the
        ``stats`` op's machine-readable section — or any dict with the
        same keys), a gang membership view, and an optional raw latency
        histogram into a fresh snapshot."""
        s = dict(stats or {})
        rates = dict(s.get("rates") or {})
        burn = s.get("slo_burn")
        if burn is None:
            burn = slo_burn_from_hist(
                latency_hist or s.get("update_to_incorporation_hist"),
                self.objective_s, self.error_budget)
        snap = Snapshot(
            version=self._version,
            t=float(t),
            backlog=int(s.get("backlog", 0)),
            buffered=int(s.get("buffered", 0)),
            incorporated=int(s.get("incorporated", 0)),
            admitted=int(s.get("admitted", 0)),
            window_decisions=int(s.get("window_decisions", 0)),
            rates=rates,
            slo_burn=float(burn),
            prefetch_stall_s=float(s.get("prefetch_stall_s", 0.0)),
            prefetch_stalls=int(s.get("prefetch_stalls", 0)),
            members=tuple((int(i), str(st)) for i, st in members),
            notice=int(notice),
        )
        self._version += 1
        return snap
