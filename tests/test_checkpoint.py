"""Checkpoint / resume — persistence the reference entirely lacks
(SURVEY.md §5: best weights only ever printed to stdout)."""

import numpy as np
import jax

from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.orchestration.checkpoint import (save_checkpoint, load_checkpoint,
                                             latest_step)
from fedtpu.parallel import make_mesh, client_sharding
from fedtpu.parallel.round import build_round_fn, init_federated_state


def test_checkpoint_roundtrip_and_resume(tmp_path):
    x, y = synthetic_income_like(256, 6, 2)
    packed = pack_clients(x, y, ShardConfig(num_clients=8, shuffle=False))
    mesh = make_mesh(num_clients=8)
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6, hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig())
    shard = client_sharding(mesh)
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    state = init_federated_state(jax.random.key(0), mesh, 8, init_fn, tx)
    round_step = build_round_fn(mesh, apply_fn, tx, 2)

    for _ in range(3):
        state, _ = round_step(state, batch)
    history = {"accuracy": [0.5, 0.6, 0.7]}
    ckdir = str(tmp_path / "ck")
    save_checkpoint(ckdir, state, history, step=3)
    assert latest_step(ckdir) == 3

    template = init_federated_state(jax.random.key(7), mesh, 8, init_fn, tx)
    restored, hist, step = load_checkpoint(ckdir, sharding=shard,
                                           state_like=template)
    assert step == 3
    assert hist["accuracy"] == [0.5, 0.6, 0.7]
    np.testing.assert_allclose(
        np.asarray(restored["params"]["layers"][0]["w"]),
        np.asarray(state["params"]["layers"][0]["w"]), rtol=0, atol=0)

    # Resume: running one more round from the restored state must match
    # running one more round from the live state bit-for-bit.
    cont_live, _ = round_step(state, batch)
    cont_restored, _ = round_step(restored, batch)
    np.testing.assert_allclose(
        np.asarray(cont_restored["params"]["layers"][0]["w"]),
        np.asarray(cont_live["params"]["layers"][0]["w"]), rtol=0, atol=0)
    assert int(cont_restored["round"]) == 4
