"""Checkpoint / resume — persistence the reference entirely lacks
(SURVEY.md §5: best weights only ever printed to stdout)."""

import numpy as np
import jax

from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.orchestration.checkpoint import (save_checkpoint, load_checkpoint,
                                             latest_step)
from fedtpu.parallel import make_mesh, client_sharding
from fedtpu.parallel.round import build_round_fn, init_federated_state


def test_checkpoint_roundtrip_and_resume(tmp_path):
    x, y = synthetic_income_like(256, 6, 2)
    packed = pack_clients(x, y, ShardConfig(num_clients=8, shuffle=False))
    mesh = make_mesh(num_clients=8)
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6, hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig())
    shard = client_sharding(mesh)
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    state = init_federated_state(jax.random.key(0), mesh, 8, init_fn, tx)
    round_step = build_round_fn(mesh, apply_fn, tx, 2)

    for _ in range(3):
        state, _ = round_step(state, batch)
    history = {"accuracy": [0.5, 0.6, 0.7]}
    ckdir = str(tmp_path / "ck")
    save_checkpoint(ckdir, state, history, step=3)
    assert latest_step(ckdir) == 3

    template = init_federated_state(jax.random.key(7), mesh, 8, init_fn, tx)
    restored, hist, step = load_checkpoint(ckdir, sharding=shard,
                                           state_like=template)
    assert step == 3
    assert hist["accuracy"] == [0.5, 0.6, 0.7]
    np.testing.assert_allclose(
        np.asarray(restored["params"]["layers"][0]["w"]),
        np.asarray(state["params"]["layers"][0]["w"]), rtol=0, atol=0)

    # Resume: running one more round from the restored state must match
    # running one more round from the live state bit-for-bit.
    cont_live, _ = round_step(state, batch)
    cont_restored, _ = round_step(restored, batch)
    np.testing.assert_allclose(
        np.asarray(cont_restored["params"]["layers"][0]["w"]),
        np.asarray(cont_live["params"]["layers"][0]["w"]), rtol=0, atol=0)
    assert int(cont_restored["round"]) == 4


def test_elastic_resume_changes_client_count(tmp_path):
    """Resume an 8-client run as a 4-client run AND as a 16-client run —
    each leg from the same 8-client round-4 checkpoint, each actually
    training rounds 5-6 under the new count. The reference cannot do this:
    its client count is baked into the `mpirun -np N` launch."""
    import shutil
    from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                               RunConfig)
    from fedtpu.orchestration.loop import run_experiment

    ckdir = str(tmp_path / "elastic")
    base = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256,
                        synthetic_features=6),
        shard=ShardConfig(num_clients=8, shuffle=False),
        model=ModelConfig(input_dim=6, hidden_sizes=(8,)),
        optim=OptimConfig(),
        fed=FedConfig(rounds=4, server_opt="fedadam", server_lr=0.02),
        run=RunConfig(checkpoint_dir=ckdir, checkpoint_every=2),
    )
    first = run_experiment(base, verbose=False)
    assert first.rounds_run == 4

    for new_clients in (4, 16):
        # Fresh dir seeded with the 8-client checkpoint, so each leg
        # resumes 8 -> new_clients (not from the previous leg's output).
        leg_dir = str(tmp_path / f"leg{new_clients}")
        shutil.copytree(ckdir, leg_dir)
        cfg = base.replace(
            shard=ShardConfig(num_clients=new_clients, shuffle=False),
            fed=FedConfig(rounds=6, server_opt="fedadam", server_lr=0.02),
            run=RunConfig(checkpoint_dir=leg_dir, checkpoint_every=0),
        )
        result = run_experiment(cfg, verbose=False, resume=True)
        # Continued from round 4, trained rounds 5-6 under the new count.
        assert result.rounds_run == 6
        assert len(result.global_metrics["accuracy"]) == 6
        # Rounds 5-6 really ran: their metrics were appended (finite) and
        # timing entries exist for the post-resume chunks.
        assert all(np.isfinite(v) for v in result.global_metrics["accuracy"])
        assert len(result.sec_per_round) == 2


def test_elastic_resume_carries_global_model(tmp_path):
    """The resumed (different-count) run restores EXACTLY the checkpointed
    global model: resume with rounds == saved round trains nothing, so its
    final_params are purely the elastic collapse/broadcast output."""
    from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                               RunConfig)
    from fedtpu.orchestration.loop import run_experiment

    ckdir = str(tmp_path / "elastic2")
    base = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256,
                        synthetic_features=6),
        shard=ShardConfig(num_clients=8, shuffle=False),
        model=ModelConfig(input_dim=6, hidden_sizes=(8,)),
        fed=FedConfig(rounds=2),
        run=RunConfig(checkpoint_dir=ckdir, checkpoint_every=2),
    )
    first = run_experiment(base, verbose=False)

    cfg4 = base.replace(shard=ShardConfig(num_clients=4, shuffle=False))
    resumed = run_experiment(cfg4, verbose=False, resume=True)
    assert resumed.rounds_run == 2          # nothing new trained
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a),
                                                np.asarray(b), atol=1e-6),
        first.final_params, resumed.final_params)


def test_latest_step_skips_half_written_rounds(tmp_path):
    # A SIGKILL mid-save leaves round_N with only an orbax tmp dir, or
    # state without meta (meta is written last). Resume must see neither
    # (tests/test_chaos_resume.py found this live).
    from fedtpu.orchestration.checkpoint import latest_step

    def fake_round(step, items):
        d = tmp_path / f"round_{step:06d}"
        d.mkdir()
        for name in items:
            (d / name).mkdir()

    fake_round(2, ["state", "meta"])                 # committed
    fake_round(4, ["state"])                         # killed before meta
    fake_round(6, ["state.orbax-checkpoint-tmp"])    # killed mid-state
    assert latest_step(str(tmp_path)) == 2


def test_retention_keeps_k_newest_plus_protected(tmp_path):
    from fedtpu.orchestration.checkpoint import (complete_steps, latest_step,
                                                 retain_checkpoints)

    def fake_round(step, items):
        d = tmp_path / f"round_{step:06d}"
        d.mkdir()
        for name in items:
            (d / name).mkdir()

    for s in (2, 4, 6, 8, 10):
        fake_round(s, ["state", "meta"])
    fake_round(5, ["state"])                 # stale crash remnant: GC'd
    fake_round(12, ["state"])                # could be mid-commit: untouched
    removed = retain_checkpoints(str(tmp_path), keep=2, protect=(4,))
    assert removed == [2, 5, 6]
    assert complete_steps(str(tmp_path)) == [4, 8, 10]
    assert latest_step(str(tmp_path)) == 10          # half-round still invisible
    assert (tmp_path / "round_000012").is_dir()
    assert not (tmp_path / "round_000005").exists()
    # keep <= 0 keeps everything (the default).
    assert retain_checkpoints(str(tmp_path), keep=0) == []
    assert complete_steps(str(tmp_path)) == [4, 8, 10]


def test_run_experiment_retention_bounds_disk_and_resumes(tmp_path):
    # End-to-end: keep_checkpoints=2 with per-round saves must leave at
    # most k+1 rounds on disk (k newest + the protected best-accuracy
    # round), and a resume from the retained set must continue cleanly
    # and keep honoring retention.
    from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                               RunConfig, ShardConfig)
    from fedtpu.orchestration.checkpoint import complete_steps
    from fedtpu.orchestration.loop import run_experiment

    def cfg(rounds):
        return ExperimentConfig(
            data=DataConfig(csv_path=None, synthetic_rows=256),
            shard=ShardConfig(num_clients=4),
            fed=FedConfig(rounds=rounds),
            run=RunConfig(checkpoint_dir=str(tmp_path), checkpoint_every=1,
                          keep_checkpoints=2),
        )

    res = run_experiment(cfg(6), verbose=False)
    assert res.rounds_run == 6
    steps = complete_steps(str(tmp_path))
    assert len(steps) <= 3 and steps[-1] == 6
    best_round = int(np.argmax(res.global_metrics["accuracy"])) + 1
    assert best_round in steps

    res2 = run_experiment(cfg(10), verbose=False, resume=True)
    assert res2.rounds_run == 10
    # The pre-resume history is carried over intact through retention.
    np.testing.assert_allclose(res2.global_metrics["accuracy"][:6],
                               res.global_metrics["accuracy"])
    steps2 = complete_steps(str(tmp_path))
    assert len(steps2) <= 3 and steps2[-1] == 10
    best2 = int(np.argmax(res2.global_metrics["accuracy"])) + 1
    assert best2 in steps2


def test_fresh_run_refuses_dir_with_existing_rounds(tmp_path):
    # A fresh (non-resume) periodic-checkpointing run into a directory
    # already holding rounds would let a later resume restore the stale
    # higher round over the new work, and retention would GC the fresh
    # rounds (review r4) — refuse up front.
    import pytest

    from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                               RunConfig, ShardConfig)
    from fedtpu.orchestration.loop import run_experiment

    def cfg():
        return ExperimentConfig(
            data=DataConfig(csv_path=None, synthetic_rows=128),
            shard=ShardConfig(num_clients=4),
            fed=FedConfig(rounds=2),
            run=RunConfig(checkpoint_dir=str(tmp_path), checkpoint_every=1),
        )

    run_experiment(cfg(), verbose=False)
    with pytest.raises(ValueError, match="already holds"):
        run_experiment(cfg(), verbose=False)
    # resume=True remains the sanctioned way in.
    res = run_experiment(cfg(), verbose=False, resume=True)
    assert res.rounds_run == 2
