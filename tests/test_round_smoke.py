"""Smoke test: one compiled federated round on 8 virtual devices."""

import jax
import numpy as np

from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.parallel import make_mesh, client_sharding
from fedtpu.parallel.round import (build_round_fn, init_federated_state,
                                   global_params, build_eval_fn)


def test_round_runs_on_8_device_mesh():
    assert len(jax.devices()) == 8
    x, y = synthetic_income_like(512, 14, 2)
    batch_np = pack_clients(x, y, ShardConfig(num_clients=8))

    mesh = make_mesh(num_clients=8)
    init_fn, apply_fn = build_model(ModelConfig(input_dim=14))
    tx = build_optimizer(OptimConfig())
    state = init_federated_state(jax.random.key(0), mesh, 8, init_fn, tx)

    shard = client_sharding(mesh)
    batch = {
        "x": jax.device_put(batch_np.x, shard),
        "y": jax.device_put(batch_np.y, shard),
        "mask": jax.device_put(batch_np.mask, shard),
    }
    round_step = build_round_fn(mesh, apply_fn, tx, num_classes=2)

    state, metrics = round_step(state, batch)
    assert metrics["loss"].shape == (8,)
    assert float(metrics["client_mean"]["accuracy"]) >= 0.0

    # After averaging, every client slot must hold the identical global model.
    p = np.asarray(state["params"]["layers"][0]["w"])
    for c in range(1, 8):
        np.testing.assert_allclose(p[c], p[0], rtol=0, atol=0)

    # A few more rounds should drive accuracy up on separable synthetic data.
    for _ in range(20):
        state, metrics = round_step(state, batch)
    assert float(metrics["client_mean"]["accuracy"]) > 0.8

    ev = build_eval_fn(apply_fn, 2)
    m = ev(global_params(state), batch["x"][0], batch["y"][0])
    assert 0.0 <= float(m["accuracy"]) <= 1.0


def test_empty_hidden_sizes_is_logistic_regression():
    """hidden_sizes=() degenerates the MLP family to a single Linear —
    multinomial logistic regression — and the whole stack (init, round,
    averaging, metrics) handles it: the smallest model family a reference
    user might bring."""
    from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                               RunConfig)
    from fedtpu.orchestration.loop import run_experiment

    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=()))
    params = init_fn(jax.random.key(0))
    assert len(params["layers"]) == 1           # one Linear: logits head
    assert params["layers"][0]["w"].shape == (6, 2)

    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256,
                        synthetic_features=6),
        shard=ShardConfig(num_clients=8, shuffle=False),
        model=ModelConfig(input_dim=6, hidden_sizes=()),
        # Early stop disabled: a linear model saturating the separable
        # synthetic data within atol=1e-4 would otherwise stop the run and
        # fail the rounds_run assertion spuriously.
        fed=FedConfig(rounds=20, termination_patience=10**9),
        run=RunConfig(rounds_per_step=5),
    )
    result = run_experiment(cfg, verbose=False)
    assert result.rounds_run == 20
    assert np.isfinite(result.global_metrics["accuracy"][-1])
    assert result.global_metrics["accuracy"][-1] > 0.6   # separable synth
