"""Smoke test: one compiled federated round on 8 virtual devices."""

import jax
import numpy as np

from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.parallel import make_mesh, client_sharding
from fedtpu.parallel.round import (build_round_fn, init_federated_state,
                                   global_params, build_eval_fn)


def test_round_runs_on_8_device_mesh():
    assert len(jax.devices()) == 8
    x, y = synthetic_income_like(512, 14, 2)
    batch_np = pack_clients(x, y, ShardConfig(num_clients=8))

    mesh = make_mesh(num_clients=8)
    init_fn, apply_fn = build_model(ModelConfig(input_dim=14))
    tx = build_optimizer(OptimConfig())
    state = init_federated_state(jax.random.key(0), mesh, 8, init_fn, tx)

    shard = client_sharding(mesh)
    batch = {
        "x": jax.device_put(batch_np.x, shard),
        "y": jax.device_put(batch_np.y, shard),
        "mask": jax.device_put(batch_np.mask, shard),
    }
    round_step = build_round_fn(mesh, apply_fn, tx, num_classes=2)

    state, metrics = round_step(state, batch)
    assert metrics["loss"].shape == (8,)
    assert float(metrics["client_mean"]["accuracy"]) >= 0.0

    # After averaging, every client slot must hold the identical global model.
    p = np.asarray(state["params"]["layers"][0]["w"])
    for c in range(1, 8):
        np.testing.assert_allclose(p[c], p[0], rtol=0, atol=0)

    # A few more rounds should drive accuracy up on separable synthetic data.
    for _ in range(20):
        state, metrics = round_step(state, batch)
    assert float(metrics["client_mean"]["accuracy"]) > 0.8

    ev = build_eval_fn(apply_fn, 2)
    m = ev(global_params(state), batch["x"][0], batch["y"][0])
    assert 0.0 <= float(m["accuracy"]) <= 1.0
