"""Pallas kernel parity against the pure-XLA implementations (interpret mode
on the CPU mesh; the same kernels compile natively on TPU)."""

import numpy as np
import jax
import jax.numpy as jnp

from fedtpu.models.mlp import mlp_init, mlp_apply
from fedtpu.ops.pallas_kernels import fused_mlp_forward, weighted_average_clients


def test_fused_mlp_matches_xla_apply():
    params = mlp_init(jax.random.key(0), 14, (50, 200), 2)
    x = jax.random.normal(jax.random.key(1), (64, 14), jnp.float32)
    ref = mlp_apply(params, x)
    out = fused_mlp_forward(params, x, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_fused_mlp_gridded_rows():
    # 1024 rows forces multiple row tiles through the grid path.
    params = mlp_init(jax.random.key(2), 6, (8,), 3)
    x = jax.random.normal(jax.random.key(3), (1024, 6), jnp.float32)
    out = fused_mlp_forward(params, x, interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(mlp_apply(params, x)), atol=1e-4)


def test_fused_mlp_unpadded_rows():
    # 100 % 8 != 0: remainder rows must be computed, not dropped.
    params = mlp_init(jax.random.key(4), 6, (8,), 3)
    x = jax.random.normal(jax.random.key(5), (100, 6), jnp.float32)
    out = fused_mlp_forward(params, x, interpret=True)
    assert out.shape == (100, 3)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(mlp_apply(params, x)), atol=1e-4)


def test_experiment_with_pallas_heldout_eval_matches_xla():
    from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                               ModelConfig, RunConfig, ShardConfig)
    from fedtpu.orchestration.loop import run_experiment

    base = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256),
        shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=3),
        run=RunConfig(eval_test_every=1),
    )
    r_xla = run_experiment(base, verbose=False)
    r_pl = run_experiment(
        base.replace(model=ModelConfig(use_pallas=True)), verbose=False)
    np.testing.assert_allclose(r_pl.global_metrics["accuracy"],
                               r_xla.global_metrics["accuracy"], atol=1e-6)
    # The held-out eval ran through the Pallas kernel: same test metrics.
    np.testing.assert_allclose(r_pl.test_metrics["accuracy"],
                               r_xla.test_metrics["accuracy"], atol=1e-6)


def test_weighted_average_kernel_matches_numpy():
    rng = np.random.default_rng(0)
    stacked = rng.normal(size=(8, 96)).astype(np.float32)
    w = np.array([12, 12, 12, 12, 12, 12, 12, 19], np.float32)
    expected = (stacked * (w / w.sum())[:, None]).sum(axis=0)
    out = weighted_average_clients(jnp.asarray(stacked), jnp.asarray(w),
                                   interpret=True)
    np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5)


def test_fused_eval_confusion_matches_xla_chain():
    # The batched fused eval->confusion kernel (measured SLOWER than the
    # XLA chain on the v5e — see RESULTS.md; kept as a library op) must
    # match vmap(argmax -> confusion_matrix) exactly in interpret mode.
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
    from fedtpu.data.sharding import pack_clients
    from fedtpu.data.tabular import synthetic_income_like
    from fedtpu.models import build_model
    from fedtpu.ops import build_optimizer
    from fedtpu.ops.metrics import confusion_matrix
    from fedtpu.ops.pallas_kernels import fused_eval_confusion
    from fedtpu.parallel import make_mesh
    from fedtpu.parallel.round import init_federated_state

    x, y = synthetic_income_like(64 * 4, 6, 2)
    packed = pack_clients(x, y, ShardConfig(num_clients=4, shuffle=False))
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(16,)))
    tx = build_optimizer(OptimConfig())
    mesh = make_mesh(num_clients=4)
    state = init_federated_state(jax.random.key(3), mesh, 4, init_fn, tx,
                                 same_init=False)
    xd, yd, md = (jnp.asarray(packed.x), jnp.asarray(packed.y),
                  jnp.asarray(packed.mask))
    conf_pal = fused_eval_confusion(state["params"], xd, yd, md, 2)
    conf_xla = jax.vmap(lambda p, xx, yy, mm: confusion_matrix(
        yy, jnp.argmax(apply_fn(p, xx), -1), mm, 2))(
            state["params"], xd, yd, md)
    np.testing.assert_array_equal(np.asarray(conf_pal),
                                  np.asarray(conf_xla))


def test_fused_eval_confusion_rejects_wide_class_counts():
    import jax.numpy as jnp
    import pytest

    from fedtpu.ops.pallas_kernels import fused_eval_confusion

    params = {"layers": [{"w": jnp.zeros((2, 4, 9)),
                          "b": jnp.zeros((2, 9))}]}
    with pytest.raises(ValueError, match="> 8"):
        fused_eval_confusion(params, jnp.zeros((2, 8, 4)),
                             jnp.zeros((2, 8), jnp.int32),
                             jnp.ones((2, 8)), 9)
