"""Native C++ CSV loader (fedtpu.native) parity with the pandas path: both
must produce identical matrices, column typing, and LabelEncoder classes on
the shipped income CSV and on synthetic edge-case CSVs (quoting, CRLF,
missing trailing newline, empty cells)."""

import dataclasses

import numpy as np
import pytest

from fedtpu import native
from fedtpu.config import DataConfig, default_income_csv
from fedtpu.data.tabular import _load_encoded, load_tabular_dataset

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no C++ toolchain")


def _both(path):
    cols_n, mat_n, cls_n = _load_encoded(path, use_native=True)
    cols_p, mat_p, cls_p = _load_encoded(path, use_native=False)
    return (cols_n, mat_n, cls_n), (cols_p, mat_p, cls_p)


def test_income_csv_native_matches_pandas():
    path = default_income_csv()
    if path is None:
        pytest.skip("income CSV not present")
    (cols_n, mat_n, cls_n), (cols_p, mat_p, cls_p) = _both(path)
    assert cols_n == cols_p
    np.testing.assert_array_equal(mat_n, mat_p)
    assert set(cls_n) == set(cls_p)
    for k in cls_n:
        np.testing.assert_array_equal(np.asarray(cls_n[k], dtype=object),
                                      np.asarray(cls_p[k], dtype=object))


def test_quoting_crlf_and_missing_trailing_newline(tmp_path):
    p = tmp_path / "edge.csv"
    p.write_bytes(b'a,b,c\r\n1,"x,y",3.5\r\n2,"say ""hi""",\r\n3,z,7')
    cols, mat, cls = _load_encoded(str(p), use_native=True)
    assert cols == ["a", "b", "c"]
    # b is categorical with sorted-unique codes; c has an empty cell -> NaN.
    np.testing.assert_array_equal(mat[:, 0], [1.0, 2.0, 3.0])
    order = sorted(['x,y', 'say "hi"', 'z'])
    np.testing.assert_array_equal(mat[:, 1],
                                  [order.index('x,y'),
                                   order.index('say "hi"'),
                                   order.index('z')])
    assert mat[0, 2] == 3.5 and np.isnan(mat[1, 2]) and mat[2, 2] == 7.0
    assert list(cls["b"]) == order


def test_blank_lines_skipped_like_pandas(tmp_path):
    p = tmp_path / "blank.csv"
    p.write_text("a,b\n1,x\n\n2,y\n\n")
    (cols_n, mat_n, _), (cols_p, mat_p, _) = _both(str(p))
    assert cols_n == cols_p
    np.testing.assert_array_equal(mat_n, mat_p)
    assert mat_n.shape == (2, 2)


def test_hex_literals_stay_categorical_like_pandas(tmp_path):
    p = tmp_path / "hex.csv"
    p.write_text("a,b\n0x10,1\n0x2A,2\n")
    (cols_n, mat_n, cls_n), (cols_p, mat_p, cls_p) = _both(str(p))
    np.testing.assert_array_equal(mat_n, mat_p)
    np.testing.assert_array_equal(np.asarray(cls_n["a"], dtype=object),
                                  np.asarray(cls_p["a"], dtype=object))


def test_embedded_newline_in_quoted_field_classes_survive(tmp_path):
    p = tmp_path / "nl.csv"
    p.write_bytes(b'a,b\n1,"x\ny"\n2,z\n')
    cols, mat, cls = _load_encoded(str(p), use_native=True)
    assert list(cls["b"]) == sorted(["x\ny", "z"])
    np.testing.assert_array_equal(
        mat[:, 1], [sorted(["x\ny", "z"]).index("x\ny"),
                    sorted(["x\ny", "z"]).index("z")])


def test_ragged_row_is_an_error(tmp_path):
    p = tmp_path / "bad.csv"
    p.write_text("a,b\n1,2\n3\n")
    with pytest.raises(ValueError, match="ragged"):
        _load_encoded(str(p), use_native=True)


def test_end_to_end_dataset_identical_with_either_loader():
    path = default_income_csv()
    if path is None:
        pytest.skip("income CSV not present")
    ds_n = load_tabular_dataset(DataConfig(csv_path=path))
    ds_p = load_tabular_dataset(
        dataclasses.replace(DataConfig(csv_path=path), native_loader=False))
    np.testing.assert_array_equal(ds_n.x_train, ds_p.x_train)
    np.testing.assert_array_equal(ds_n.y_train, ds_p.y_train)
    np.testing.assert_array_equal(ds_n.x_test, ds_p.x_test)
    np.testing.assert_array_equal(ds_n.label_classes, ds_p.label_classes)
