"""Regression tests for the real findings the FTP011/FTP012/FTP013 pass
surfaced (PR 17 satellite: every fixed finding keeps a test).

- netproxy `_threads`: appended from the accept-loop thread while
  `stop()` iterated it from the main thread, unlocked (FTP011).
- MetricsRegistry: lock-free `+=` counters incremented from
  CompileExecutor's worker thread lost updates (FTP011-class).
- reshard signal handler: took `self._sig_lock` inside the handler —
  a self-deadlock against the main-thread frame it interrupts (FTP012).
- protocol.send_msg: compact separators without sort_keys — frame bytes
  were insertion-order-dependent on a deterministic-counter path
  (FTP013).
"""

import json
import signal
import socket
import threading

from fedtpu.analysis.engine import lint_paths


def _rule_clean(path: str, code: str) -> None:
    res = lint_paths([path], select=[code])
    assert not res.findings, [f"{f.path}:{f.line}: {f.message}"
                              for f in res.findings]


# ------------------------------------------------------- netproxy threads
def test_netproxy_thread_list_is_lock_guarded():
    """The accept loop and stop() now exchange `_threads` under `_lock`;
    the interprocedural rule that caught the race must stay clean."""
    _rule_clean("fedtpu/serving/netproxy.py", "FTP011")


def test_netproxy_stop_joins_threads_registered_concurrently():
    from fedtpu.resilience.netfaults import NetFaultPlan
    from fedtpu.serving.netproxy import NetFaultProxy

    plan = NetFaultPlan.load({"faults": []}, num_gateways=1)
    proxy = NetFaultProxy(plan=plan, gateway_index=0, backend_port=0,
                          port_file="")
    done = threading.Event()

    def fake_conn():
        done.wait(5.0)

    # Simulate the accept loop registering per-connection threads from
    # its own thread while the main thread stops the proxy.
    def register():
        for _ in range(16):
            t = threading.Thread(target=fake_conn, daemon=True)
            t.start()
            with proxy._lock:
                proxy._threads.append(t)

    reg = threading.Thread(target=register, daemon=True)
    reg.start()
    reg.join(5.0)
    done.set()
    proxy.stop()                          # iterates a locked snapshot
    assert len(proxy._threads) == 16
    assert all(not t.is_alive() for t in proxy._threads)


# --------------------------------------------------------- metrics locking
def test_counter_increments_from_many_threads_do_not_lose_updates():
    from fedtpu.telemetry.metrics import MetricsRegistry

    reg = MetricsRegistry()
    n_threads, per_thread = 8, 5000

    def work():
        c = reg.counter("background_compiles")
        for _ in range(per_thread):
            c.inc()
            reg.gauge("last").set(1.0)
            reg.histogram("stale").observe(1.0)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    snap = reg.snapshot()
    assert snap["counters"]["background_compiles"] == n_threads * per_thread
    assert snap["histograms"]["stale"]["count"] == n_threads * per_thread


def test_snapshot_and_reset_are_atomic_under_concurrent_updates():
    from fedtpu.telemetry.metrics import MetricsRegistry

    reg = MetricsRegistry()
    stop = threading.Event()

    def churn():
        while not stop.is_set():
            reg.counter("ticks").inc()
            reg.histogram("h").observe(2.0)

    t = threading.Thread(target=churn, daemon=True)
    t.start()
    try:
        for _ in range(200):
            snap = reg.snapshot()
            h = snap["histograms"].get("h")
            if h is not None:
                # A torn histogram would break count == sum/2 here.
                assert h["sum"] == 2.0 * h["count"]
        reg.reset()
        assert set(reg.snapshot()["counters"]) <= {"ticks"}
    finally:
        stop.set()
        t.join(5.0)


def test_standalone_instruments_default_their_own_lock():
    from fedtpu.telemetry.metrics import Counter, Gauge, Histogram

    c, g, h = Counter(), Gauge(), Histogram()
    c.inc(2.0)
    g.set(3.0)
    h.observe(1.0)
    assert c.value == 2.0 and g.value == 3.0 and h.count == 1


# -------------------------------------------------- reshard signal handler
def test_reshard_signal_handler_is_lock_free():
    """The handler is a plain flag store now — FTP012 must stay clean
    and the controller must not grow the lock back."""
    _rule_clean("fedtpu/resilience/reshard.py", "FTP012")
    from fedtpu.resilience.reshard import ReshardController
    ctl = ReshardController(process_count=2, process_index=0)
    assert not hasattr(ctl, "_sig_lock")


def test_reshard_handler_fires_while_main_thread_polls():
    """The exact interleaving the old lock deadlocked on: the signal
    arrives while the main thread is mid-poll. Lock-free, it just
    stores the flag."""
    from fedtpu.resilience.reshard import ReshardController

    ctl = ReshardController(process_count=1, process_index=0)
    ctl.install_signal_handlers()
    try:
        signal.raise_signal(signal.SIGUSR1)   # delivered on this thread
        assert ctl.signal_pending
        req = ctl._poll_signal(3)
        assert req is not None and req.mode == "shrink"
        assert not ctl.signal_pending
        # First notice wins: a second signal of the other mode while one
        # is pending must not overwrite it.
        signal.raise_signal(signal.SIGUSR1)
        signal.raise_signal(signal.SIGUSR2)
        assert ctl.signal_pending
        req = ctl._poll_signal(4)
        assert req is not None and req.mode == "shrink"
        ctl.clear_signal()
    finally:
        signal.signal(signal.SIGUSR1, signal.SIG_DFL)
        signal.signal(signal.SIGUSR2, signal.SIG_DFL)


# ----------------------------------------------------- protocol canonical
def test_send_msg_bytes_are_canonical_across_insertion_order():
    """Frame bytes feed the netlog's deterministic byte counters — the
    same payload must serialize identically however it was built."""
    _rule_clean("fedtpu/serving/protocol.py", "FTP013")
    from fedtpu.serving.protocol import send_msg

    def frame(obj) -> bytes:
        a, b = socket.socketpair()
        try:
            send_msg(a, obj)
            return b.recv(1 << 16)
        finally:
            a.close()
            b.close()

    one = frame({"kind": "update", "seq": 3, "client": 7})
    two = frame({"client": 7, "kind": "update", "seq": 3})
    assert one == two
    assert one.endswith(b"\n")
    decoded = json.loads(one)
    assert decoded == {"kind": "update", "seq": 3, "client": 7}
    assert one == (b'{"client":7,"kind":"update","seq":3}\n')
