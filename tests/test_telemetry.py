"""Telemetry subsystem — fedtpu.telemetry (tracer, metrics, manifest,
report) plus the observability satellites: bench JSON-last emission, the
resume engine-mismatch guard, the async/personalize rejection, sweep
winner-weight retention, reference-parity byte identity with telemetry
on, and the bare-print lint over the package.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           RunConfig, ShardConfig, TelemetryConfig)
from fedtpu.telemetry import (EVENT_SCHEMA_VERSION, MetricsRegistry,
                              NullTracer, Tracer, make_tracer)
from fedtpu.telemetry.report import aggregate, load_events, render_report


def _cfg(rounds=4, tmp=None, **run_kw):
    run_kw.setdefault("log_every", 1000)
    if tmp is not None:
        run_kw["telemetry"] = TelemetryConfig(events_path=str(tmp))
    return ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=512),
        shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=rounds, termination_patience=1000),
        run=RunConfig(**run_kw))


# ---------------------------------------------------------------- schema
def test_event_schema_roundtrip(tmp_path):
    """Emit -> read -> aggregate: every schema field survives the sink and
    the aggregation matches hand-computed numbers."""
    path = str(tmp_path / "ev.jsonl")
    tr = Tracer(path, run_id="deadbeef")
    durs = [0.25, 0.5, 1.0, 2.0]
    for i, d in enumerate(durs):
        tr.event("round", round=i + 1, dur_s=d, staleness_mean=float(i))
    tr.event("span", phase="eval", dur_s=0.125, note="x")
    reg = MetricsRegistry()
    reg.counter("rounds").inc(4)
    reg.gauge("g").set(7.5)
    reg.histogram("staleness", bins=(0, 1, 2)).observe_many([0, 1, 1, 5])
    tr.counters(reg.snapshot())
    tr.close()

    # Append garbage: a malformed line and a truncated (crash-cut) line.
    with open(path, "a") as f:
        f.write("not json\n")
        f.write('{"v": 1, "kind": "span", "pha')

    events, bad = load_events(path)
    assert bad == 2
    assert len(events) == 6
    for e in events:
        assert e["v"] == EVENT_SCHEMA_VERSION
        assert e["run_id"] == "deadbeef"
        assert set(e) == {"v", "run_id", "kind", "phase", "round",
                          "t_start", "dur_s", "payload"}
        # t_start defaults to emission time minus dur_s: the window END
        # (t_start + dur_s) always lands at/after the tracer epoch.
        assert e["t_start"] + e["dur_s"] >= 0.0

    agg = aggregate(events, malformed=bad)
    assert agg["malformed_lines"] == 2
    assert agg["run_ids"] == ["deadbeef"]
    assert agg["rounds"]["count"] == 4
    assert agg["rounds"]["last_round"] == 4
    assert np.isclose(agg["rounds"]["total_s"], sum(durs))
    cad = agg["rounds"]["cadence"]
    assert np.isclose(cad["p50_s"], np.percentile(durs, 50))
    assert np.isclose(cad["p90_s"], np.percentile(durs, 90))
    assert np.isclose(cad["max_s"], 2.0)
    assert agg["phases"]["eval"]["count"] == 1
    assert np.isclose(agg["phases"]["eval"]["total_s"], 0.125)
    assert agg["counters"]["rounds"] == 4
    assert agg["gauges"]["g"] == 7.5
    st = agg["staleness"]
    assert st["count"] == 4 and st["max"] == 5
    # le-style cumulative buckets over bins (0, 1, 2): 1, 3, 3.
    assert st["bucket_counts"] == [1, 3, 3]
    assert np.isclose(st["round_mean_of_means"], np.mean([0, 1, 2, 3]))


def test_null_tracer_is_total_noop(tmp_path):
    tr = make_tracer(None)
    assert isinstance(tr, NullTracer) and not tr.enabled
    with tr.span("anything", round=3) as sp:
        pass
    assert sp.end() == 0.0
    tr.event("round", dur_s=1.0)
    tr.counters({"counters": {}})
    tr.close()                                   # nothing written anywhere
    assert make_tracer(str(tmp_path / "e.jsonl")).enabled


def test_newer_schema_version_warns_not_crashes(tmp_path):
    path = str(tmp_path / "future.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"v": EVENT_SCHEMA_VERSION + 1, "run_id": "r",
                            "kind": "span", "phase": "warp", "round": None,
                            "t_start": 0.0, "dur_s": 1.0,
                            "payload": {"field_from_the_future": 1}}) + "\n")
    rendered, prom = render_report(path)
    assert "schema newer than" in rendered
    assert "warp" in rendered
    assert prom.endswith("\n")


# ----------------------------------------------------------- integration
def test_run_emits_events_and_report_reconstructs(tmp_path):
    """Acceptance: a run with telemetry on emits manifest + per-round
    span/counter events, and the report reconstructs the per-phase
    breakdown and cadence percentiles from the log ALONE."""
    ev = tmp_path / "events.jsonl"
    from fedtpu.orchestration.loop import run_experiment
    res = run_experiment(_cfg(rounds=4, tmp=ev, eval_test_every=2),
                         verbose=False)
    assert res.rounds_run == 4

    events, bad = load_events(str(ev))
    assert bad == 0
    agg = aggregate(events)
    man = agg["manifest"]
    assert man["program"] == "run" and man["engine"] == "sync1d"
    assert man["config_hash"] and man["mesh_shape"] == {"clients": 8}
    assert man["device_count"] == 8
    for phase in ("build", "compile", "chunk", "eval", "stop_check"):
        assert agg["phases"][phase]["count"] >= 1, phase
    assert agg["rounds"]["count"] == 4
    assert agg["rounds"]["cadence"]["p50_s"] > 0
    assert agg["counters"]["rounds"] == 4
    assert agg["counters"]["held_out_evals"] == 2
    assert agg["gauges"]["exchange_bytes_per_round_est"] > 0
    kinds = {e["kind"] for e in events}
    assert {"manifest", "span", "round", "counters", "run_end"} <= kinds

    # The report CLI renders all three formats from the same log.
    from fedtpu.cli import main
    prom_file = tmp_path / "metrics.prom"
    assert main(["report", str(ev), "--format", "json",
                 "--prometheus", str(prom_file)]) == 0
    prom = prom_file.read_text()
    assert "fedtpu_rounds_total 4" in prom
    assert 'fedtpu_round_duration_seconds{quantile="0.5"}' in prom


def test_async_run_records_staleness_histogram(tmp_path):
    ev = tmp_path / "events.jsonl"
    from fedtpu.orchestration.loop import run_experiment
    cfg = _cfg(rounds=6, tmp=ev)
    cfg = dataclasses.replace(cfg, fed=FedConfig(
        rounds=6, weighting="uniform", async_mode=True,
        async_arrival_rate=0.4, termination_patience=1000))
    run_experiment(cfg, verbose=False)
    agg = aggregate(load_events(str(ev))[0])
    assert agg["manifest"]["engine"] == "async"
    assert agg["counters"]["async_ticks"] == 6
    st = agg["staleness"]
    assert st["count"] == 6 * 8                  # ticks x client slots
    assert st["bucket_counts"][-1] == st["count"]
    assert sum(1 for e in load_events(str(ev))[0]
               if e["kind"] == "async_tick") == 6


def test_checkpoint_counters_roundtrip(tmp_path):
    from fedtpu.orchestration.loop import run_experiment
    from fedtpu.telemetry import default_registry
    ev = tmp_path / "events.jsonl"
    cfg = _cfg(rounds=3, tmp=ev, checkpoint_dir=str(tmp_path / "ck"),
               checkpoint_every=3)
    run_experiment(cfg, verbose=False)
    run_experiment(dataclasses.replace(
        cfg, fed=dataclasses.replace(cfg.fed, rounds=6)),
        verbose=False, resume=True)
    reg = default_registry().snapshot()
    assert reg["counters"]["checkpoint_restores"] >= 1
    assert reg["counters"]["checkpoint_saves"] >= 1
    assert reg["counters"]["checkpoint_bytes_written"] > 0
    assert any(e["kind"] == "resume"
               for e in load_events(str(ev))[0])


# ------------------------------------------------------------- satellites
def test_bench_json_is_last_stdout_line(tmp_path, capsys):
    """BENCH regression: the harness reads the LAST stdout line; detail
    lines must precede the (complete) JSON blob, and the blob is also
    written to a file."""
    from bench import emit_result
    result = {"metric": "m", "value": 1.25, "nested": {"a": [1, 2]}}
    out = tmp_path / "r.json"
    emit_result(result, ["[bench] detail one", "[bench] detail two"],
                out_path=str(out))
    cap = capsys.readouterr()
    lines = [ln for ln in cap.out.splitlines() if ln.strip()]
    assert json.loads(lines[-1]) == result       # last stdout line parses
    assert "[bench]" not in cap.out              # details are stderr-only
    assert "[bench] detail one" in cap.err
    assert json.loads(out.read_text()) == result


def test_bench_parser_has_out_and_events_flags(capsys):
    import bench
    with pytest.raises(SystemExit) as e:
        bench.main(["--help"])
    assert e.value.code == 0
    help_text = capsys.readouterr().out
    assert "--out" in help_text and "--events" in help_text


def test_resume_engine_mismatch_with_equal_client_counts(tmp_path):
    """Satellite regression: same client count on both sides used to slip
    past the count comparison and die inside orbax with an opaque
    structure error; the engine kind in the checkpoint meta must be
    checked FIRST and raise a clear ValueError."""
    from fedtpu.orchestration.loop import run_experiment
    sync_cfg = _cfg(rounds=3, checkpoint_dir=str(tmp_path / "sync"),
                    checkpoint_every=3)
    run_experiment(sync_cfg, verbose=False)
    async_same_count = dataclasses.replace(
        sync_cfg, fed=FedConfig(rounds=6, weighting="uniform",
                                async_mode=True, termination_patience=1000))
    with pytest.raises(ValueError, match="engine mismatch"):
        run_experiment(async_same_count, verbose=False, resume=True)

    # And the reverse direction: async-written, sync-resumed, equal counts.
    async_cfg = dataclasses.replace(
        _cfg(rounds=3, checkpoint_dir=str(tmp_path / "async"),
             checkpoint_every=3),
        fed=FedConfig(rounds=3, weighting="uniform", async_mode=True,
                      termination_patience=1000))
    run_experiment(async_cfg, verbose=False)
    sync_same_count = dataclasses.replace(
        async_cfg, fed=FedConfig(rounds=6, termination_patience=1000),
        run=dataclasses.replace(async_cfg.run,
                                checkpoint_dir=str(tmp_path / "async")))
    with pytest.raises(ValueError, match="engine mismatch"):
        run_experiment(sync_same_count, verbose=False, resume=True)


def test_async_mode_rejects_personalize_steps():
    """Satellite regression: async + personalize_steps used to run and
    silently fine-tune from stale per-slot locals instead of the final
    global; it must be rejected at build time."""
    from fedtpu.orchestration.loop import build_experiment
    cfg = dataclasses.replace(_cfg(rounds=2), fed=FedConfig(
        rounds=2, weighting="uniform", async_mode=True,
        personalize_steps=3, termination_patience=1000))
    with pytest.raises(ValueError, match="personalize_steps"):
        build_experiment(cfg)


def test_drop_nonwinning_weights_frees_losers():
    """Satellite regression: with keep_weights=False the sweep retained
    every launch's materialized winner candidate for the whole sweep;
    once the winner is known the rest must be dropped."""
    from fedtpu.sweep.grid import _drop_nonwinning_weights
    results = {
        ((8,), 0.01): {"win": {"w": np.ones(4)}},
        ((8,), 0.05): {"win": {"w": np.zeros(4)}},
        ((4, 4), 0.01): {"win": None},
    }
    dropped = _drop_nonwinning_weights(results, ((8,), 0.05))
    assert dropped == 1
    assert results[((8,), 0.01)]["win"] is None
    assert results[((4, 4), 0.01)]["win"] is None
    assert results[((8,), 0.05)]["win"] is not None


def test_sweep_emits_launch_spans(tmp_path):
    from fedtpu.data import load_dataset
    from fedtpu.sweep.grid import run_grid_search
    ev = tmp_path / "sweep.jsonl"
    cfg = dataclasses.replace(_cfg(rounds=2, tmp=ev), fed=FedConfig(
        rounds=2, weighting="uniform", termination_patience=1000))
    ds = load_dataset(cfg.data)
    res = run_grid_search(cfg, dataset=ds, hidden_grid=((8,), (4, 4)),
                          lr_grid=(0.01, 0.05), local_steps=10,
                          verbose=False)
    assert "params" in res
    events, bad = load_events(str(ev))
    assert bad == 0
    agg = aggregate(events)
    assert agg["manifest"]["program"] == "sweep"
    assert agg["phases"]["launch"]["count"] >= 1
    assert agg["counters"]["sweep_configs"] == 4
    assert any(e["kind"] == "sweep_end" for e in events)


# ------------------------------------------------------------------ parity
def test_reference_parity_lines_unchanged_with_telemetry_on(tmp_path,
                                                            capsys):
    """The reference-parity stdout (Round/CLIENT/early-stop lines) must be
    byte-identical whether telemetry is off or writing to a sink."""
    from fedtpu.orchestration.loop import run_experiment

    def parity_lines():
        out = capsys.readouterr().out
        return [ln for ln in out.splitlines()
                if ln.startswith(("Round ", "  CLIENT ", "Early stopping",
                                  "Training stopped"))]

    base = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256),
        shard=ShardConfig(num_clients=8),
        model=dataclasses.replace(_cfg().model, hidden_sizes=(4,)),
        fed=FedConfig(rounds=6, tolerance=1.0, termination_patience=2),
        run=RunConfig(log_every=1, log_per_client=True))
    run_experiment(base, verbose=False)          # burn compiles off-capture
    capsys.readouterr()

    run_experiment(base, verbose=True)
    plain = parity_lines()
    with_tel = dataclasses.replace(base, run=dataclasses.replace(
        base.run, telemetry=TelemetryConfig(
            events_path=str(tmp_path / "ev.jsonl"))))
    run_experiment(with_tel, verbose=True)
    traced = parity_lines()

    assert plain, "parity filter matched nothing — stdout shape changed"
    assert any(ln.startswith("Early stopping") for ln in plain)
    assert plain == traced
    # And the sink really was written during the second run.
    assert os.path.getsize(tmp_path / "ev.jsonl") > 0


# -------------------------------------------------------------------- lint
def test_no_bare_prints_outside_allowlist():
    """Every user-facing line goes through the telemetry logger (leveled,
    mirrored to the sink) — a new bare print() in fedtpu/ fails here.

    The walk + allowlist that used to live inline here is now rule FTP005
    (fedtpu.analysis.rules_generic.PRINT_ALLOWLIST — one place), so this
    test is a thin ``fedtpu lint --select FTP005`` invocation."""
    from fedtpu.cli import main as cli_main

    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "fedtpu")
    assert cli_main(["lint", root, "--select", "FTP005"]) == 0, (
        "bare print() outside the FTP005 allowlist (use fedtpu.telemetry's "
        "TelemetryLogger instead); run `fedtpu lint --select FTP005` "
        "for locations")
