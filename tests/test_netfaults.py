"""Wire-level network chaos: NetFaultPlan, the fault proxy, the line
cap, and the exactly-once contract under torn/replayed frames.

Layers under test (docs/resilience.md "Wire faults"):

- ``fedtpu.resilience.netfaults`` — seeded schedule materialization,
  canonical digest, validation (backend-free, milliseconds);
- ``fedtpu.serving.protocol`` — the streaming 8 MB line cap that keeps
  per-connection memory bounded while the connection survives;
- ``fedtpu.serving.netproxy`` — deterministic byte relay: accounting,
  decision log, and the ack-boundary fault semantics driven end-to-end
  through a REAL engine + a real retrying ``GatewayClient``;
- ``fedtpu.resilience.chaos`` — the scenario registry as the single
  source of truth for every scenario grouping and the CLI help;
- ``fedtpu.resilience.net_sim`` — the pinned campaign vs the committed
  golden (the tier-1 gate for the whole exactly-once chain).

The three live ``mp_net_*`` chaos rows (2-process gang + proxies +
subprocess loadgen, minutes each) are full-tier only (`slow`).
"""

import json
import os
import socket
import threading
import time

import pytest

from fedtpu.config import ServingConfig
from fedtpu.resilience.netfaults import (DEFAULT_FRAME_HORIZON, NET_KINDS,
                                         NetFaultPlan)
from fedtpu.serving import protocol
from fedtpu.serving.client import GatewayClient
from fedtpu.serving.netproxy import NetFaultProxy
from fedtpu.telemetry.metrics import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PLAN = {
    "seed": 3,
    "faults": [
        {"kind": "net_partition", "gateway": 0, "frame": 2, "frames": 3},
        {"kind": "net_torn_frame", "gateway": 1, "frame": 4,
         "boundary": "post_ack", "cut_bytes": 32},
        {"kind": "net_reset", "gateway": 0, "frame": 2, "phase": "accept"},
        {"kind": "net_dup_frame", "gateway": 1, "frame": 9},
        {"kind": "net_slow_link", "gateway": 0, "probability": 0.5,
         "window": [10, 17], "chunk_bytes": 256},
    ],
}


def _small_cfg(**kw):
    base = dict(cohort=8, buffer_size=2, tick_interval_s=0.0,
                data_rows=64, model_hidden=(8,), seed=0)
    base.update(kw)
    return ServingConfig(**base)


def _engine():
    from fedtpu.serving.engine import ServingEngine
    return ServingEngine(_small_cfg(), registry=MetricsRegistry())


# ------------------------------------------------------------- the plan

def test_plan_spec_forms_are_identical(tmp_path):
    """Dict, inline JSON, and file path specs materialize to the same
    schedule and the same digest — the digest is a pure function of the
    campaign content."""
    as_dict = NetFaultPlan.load(PLAN, num_gateways=2)
    as_json = NetFaultPlan.load(json.dumps(PLAN), num_gateways=2)
    path = tmp_path / "plan.json"
    path.write_text(json.dumps(PLAN))
    as_file = NetFaultPlan.load(str(path), num_gateways=2)
    assert as_dict.faults == as_json.faults == as_file.faults
    assert as_dict.digest == as_json.digest == as_file.digest
    # Every kind survived materialization; schedule order is canonical.
    assert {f.kind for f in as_dict.faults} == set(NET_KINDS)
    keys = [(f.gateway, f.frame, f.kind) for f in as_dict.faults]
    assert keys == sorted(keys)


def test_probabilistic_expansion_is_seed_deterministic():
    a = NetFaultPlan.load(PLAN, num_gateways=2)
    b = NetFaultPlan.load(PLAN, num_gateways=2)
    assert a.faults == b.faults and a.digest == b.digest
    moved = NetFaultPlan.load(dict(PLAN, seed=4), num_gateways=2)
    assert moved.digest != a.digest
    slow = [f for f in a.for_gateway(0) if f.kind == "net_slow_link"]
    assert slow, "p=0.5 over an 8-frame window fired nowhere (seed bug?)"
    assert all(10 <= f.frame <= 17 for f in slow)


def test_plan_validation_rejects_bad_entries(tmp_path):
    def load_one(entry, n=2):
        return NetFaultPlan.load({"faults": [entry]}, num_gateways=n)

    for entry in (
        {"kind": "net_unplug", "frame": 1},              # unknown kind
        {"kind": "net_partition", "gateway": 2, "frame": 1},  # bad gateway
        {"kind": "net_partition"},                       # no frame/prob
        {"kind": "net_partition", "frame": 0},           # 1-based ordinals
        {"kind": "net_dup_frame", "frame": 1, "frames": 2},  # not windowed
        {"kind": "net_torn_frame", "frame": 1, "cut_bytes": 0},
        {"kind": "net_torn_frame", "frame": 1, "boundary": "mid_ack"},
        {"kind": "net_slow_link", "frame": 1, "chunk_bytes": 0},
        {"kind": "net_slow_link", "frame": 1, "delay_s": -0.1},
        {"kind": "net_reset", "frame": 1, "phase": "connect"},
        {"kind": "net_partition", "probability": 1.5},
    ):
        with pytest.raises(ValueError):
            load_one(entry)
    not_an_object = tmp_path / "plan.json"
    not_an_object.write_text("[]")
    with pytest.raises(ValueError):
        NetFaultPlan.load(str(not_an_object))


def test_at_frame_and_at_accept_clocks_are_separate():
    """``net_reset``/``accept`` counts CONNECTIONS, everything else
    counts frames — the two ordinals must never cross-match."""
    plan = NetFaultPlan.load(PLAN, num_gateways=2)
    # frame 2 on gateway 0 carries a partition AND an accept-reset; the
    # frame clock must see only the partition (window covers 2..4).
    for k in (2, 3, 4):
        assert plan.at_frame(0, k).kind == "net_partition"
    assert plan.at_frame(0, 5) is None or plan.at_frame(0, 5).kind != \
        "net_partition"
    assert plan.at_accept(0, 2).phase == "accept"
    assert plan.at_accept(0, 3) is None
    assert plan.at_accept(1, 2) is None   # wrong gateway
    assert plan.at_frame(1, 4).boundary == "post_ack"
    assert plan.at_frame(1, 1) is None
    assert DEFAULT_FRAME_HORIZON >= 17    # PLAN's window fits the default


# ---------------------------------------------------- the registry pins

def test_scenario_registry_is_single_source_of_truth():
    from fedtpu.resilience import chaos
    names = [n for n, _, _ in chaos.SCENARIO_REGISTRY]
    assert len(names) == len(set(names))
    assert chaos.SCENARIOS == tuple(names)
    assert chaos.MP_SCENARIOS == tuple(
        n for n, fams, _ in chaos.SCENARIO_REGISTRY if "mp" in fams)
    assert chaos.RESHARD_SCENARIOS == tuple(
        n for n, fams, _ in chaos.SCENARIO_REGISTRY if "reshard" in fams)
    assert chaos.GATEWAY_SCENARIOS == ("mp_gateway_kill",
                                       "mp_store_shard_kill")
    assert chaos.NET_SCENARIOS == ("mp_net_partition", "mp_slow_gateway",
                                   "mp_torn_frame")
    assert chaos.AUTOSCALE_SCENARIO in names
    assert chaos.POISON_SCENARIO in names
    # Every net row has a pinned plan that loads for a 2-gateway fleet.
    for name in chaos.NET_SCENARIOS:
        plan = NetFaultPlan.load(chaos._NET_PLANS[name], num_gateways=2)
        assert plan.faults
    help_text = chaos.scenarios_help()
    for n in names:
        assert n in help_text, f"{n} missing from --scenarios help"


def test_cli_scenarios_help_is_registry_driven():
    from fedtpu.cli import build_parser
    from fedtpu.resilience.chaos import scenarios_help
    parser = build_parser()
    sub = next(a for a in parser._actions
               if getattr(a, "choices", None) and "chaos" in a.choices)
    chaos_p = sub.choices["chaos"]
    act = next(a for a in chaos_p._actions
               if "--scenarios" in a.option_strings)
    assert act.help == scenarios_help()


# ------------------------------------------------------- the line cap

def test_line_cap_streams_bounded_and_connection_survives():
    """An over-cap line trickled in many small TCP segments is refused
    AT the cap (one ``None``), never buffered whole, and the NEXT frame
    on the same connection still parses — the per-error-frame contract
    with bounded memory."""
    a, b = socket.socketpair()
    try:
        chunk = b"x" * 65536
        target = protocol.MAX_LINE_BYTES + 2 * len(chunk)

        def writer():
            sent = 0
            while sent < target:
                a.sendall(chunk)
                sent += len(chunk)
            a.sendall(b"\n")
            a.sendall(b'{"op":"hello","v":1}\n')

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        buf = protocol.LineBuffer()
        got, peak = [], 0
        for _ in range(4096):
            got.extend(protocol.recv_lines(b, buf))
            peak = max(peak, len(buf))
            if len(got) >= 2:
                break
        t.join(timeout=10)
        assert got[0] is None and buf.dropped == 1
        assert protocol.parse_msg(got[1]) == {"op": "hello", "v": 1}
        # Bounded: the buffer never held more than the cap plus one
        # recv's worth of tail, despite an over-cap line in flight.
        assert peak <= protocol.MAX_LINE_BYTES + 2 * len(chunk)
    finally:
        a.close()
        b.close()


def test_plain_bytearray_keeps_legacy_connection_error():
    a, b = socket.socketpair()
    try:
        t = threading.Thread(
            target=lambda: a.sendall(b"y" * (protocol.MAX_LINE_BYTES + 2)),
            daemon=True)
        t.start()
        buf = bytearray()
        with pytest.raises(ConnectionError):
            for _ in range(4096):
                list(protocol.recv_lines(b, buf))
        t.join(timeout=10)
    finally:
        a.close()
        b.close()


def test_stamped_refuses_to_restamp_a_retry():
    c = GatewayClient(port=1)
    frame = c.stamped({"op": "updates", "events": []})
    assert frame["seq"] == 1 and frame["nonce"] == c.nonce
    with pytest.raises(ValueError):
        c.stamped(frame)                  # a retry must resend, not forge
    with pytest.raises(ValueError):
        c.stamped({"op": "updates", "nonce": "other"})


# ----------------------------------------------- the proxy, end to end

def _mini_server(engine, stop):
    """A real-protocol accept loop over ``_handle`` — what run_server
    does minus the selectors/jit machinery (run_server's ``once`` mode
    would shut down when the proxy's backend connection drops, which is
    exactly what fault-driven reconnects do)."""
    from fedtpu.serving.server import _handle
    lsock = socket.socket()  # fedtpu: noqa[FTP009] settimeout(0.2) two lines down bounds every accept
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(8)
    lsock.settimeout(0.2)
    lock = threading.Lock()               # engine is single-threaded

    def serve_conn(csock):
        csock.settimeout(0.2)
        buf = protocol.LineBuffer()
        try:
            while not stop.is_set():
                try:
                    lines = list(protocol.recv_lines(csock, buf))
                except socket.timeout:
                    continue
                except (ConnectionError, OSError):
                    return
                for line in lines:
                    msg = protocol.parse_msg(line) if line else None
                    with lock:
                        resp = (_handle(engine, msg) if msg is not None
                                else protocol.error_msg("malformed"))
                    protocol.send_msg(csock, resp)
        finally:
            csock.close()

    def accept_loop():
        while not stop.is_set():
            try:
                csock, _ = lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=serve_conn, args=(csock,),
                             daemon=True).start()
        lsock.close()

    threading.Thread(target=accept_loop, daemon=True).start()
    return lsock.getsockname()[1]


def _proxied_client(tmp_path, plan, backend_port):
    base = str(tmp_path / "port")
    proxy = NetFaultProxy(NetFaultPlan.load(plan, num_gateways=1), 0,
                          backend_port,
                          protocol.net_proxy_port_file(base)).start()
    # The real port file exists too — the client must PREFER the proxy.
    (tmp_path / "port").write_text(str(backend_port))
    client = GatewayClient(port_file=base, retries=8, backoff_s=0.01,
                           timeout=5.0, seed=0)
    return proxy, client


def test_torn_ack_boundary_retry_is_exactly_once(tmp_path):
    """THE satellite bar: a connection reset between frame send and ack
    recv (net_torn_frame @ post_ack) is retryable-with-dedup. The retry
    resends the SAME stamped seq, the session table answers the original
    verdict, and the engine incorporates exactly once."""
    stop = threading.Event()
    eng = _engine()
    port = _mini_server(eng, stop)
    plan = {"seed": 0, "faults": [
        # frame 1 = hello, frame 2 = the updates frame whose ack dies.
        {"kind": "net_torn_frame", "gateway": 0, "frame": 2,
         "boundary": "post_ack", "cut_bytes": 32}]}
    proxy, client = _proxied_client(tmp_path, plan, port)
    try:
        events = [[1, 0.1, 0.0], [2, 0.2, 0.0]]
        counts = client.send_events(events)
        assert sum(counts.values()) == len(events)   # ORIGINAL verdicts
        assert client.stats["retried"] >= 1
        assert client._seq == 1                      # stamped exactly once
        assert eng.duplicate_drops == len(events)
        eng.drain()
        assert eng.incorporated == len(events)       # never twice
        stats = proxy.finish()
        assert stats["fired"] == {"net_torn_frame": 1}
        assert stats["connections"] >= 2             # the forced reconnect
        rec = proxy.records[0]
        assert rec["boundary"] == "post_ack" and rec["at_frame"] == 2
    finally:
        stop.set()
        proxy.stop()
        client.close()


def test_dup_frame_is_absorbed_with_original_verdicts(tmp_path):
    """A replayed frame (net_dup_frame) reaches the server twice; the
    duplicate is answered from the session cache (counted, swallowed by
    the wire) and the client-visible counts are the original ones."""
    stop = threading.Event()
    eng = _engine()
    port = _mini_server(eng, stop)
    plan = {"seed": 0, "faults": [
        {"kind": "net_dup_frame", "gateway": 0, "frame": 2}]}
    proxy, client = _proxied_client(tmp_path, plan, port)
    try:
        events = [[1, 0.1, 0.0], [2, 0.2, 0.0], [3, 0.3, 0.0]]
        counts = client.send_events(events)
        assert sum(counts.values()) == len(events)
        assert client.stats["retried"] == 0          # client never noticed
        # The replay happens AFTER the client's ack came back (that is
        # the point: the client never waits on it), so give the proxy a
        # moment to finish the duplicate round-trip.
        deadline = time.monotonic() + 5.0
        while eng.duplicate_drops < len(events) and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.duplicate_drops == len(events)    # server counted it
        eng.drain()
        assert eng.incorporated == len(events)
    finally:
        stop.set()
        proxy.stop()
        client.close()


def test_proxy_accounting_and_decision_log(tmp_path):
    """Byte/frame accounting against a clean plan (nothing fires), plus
    the decision-log artifact shape: header, records, summary."""
    stop = threading.Event()
    eng = _engine()
    port = _mini_server(eng, stop)
    plan = {"seed": 0, "faults": [
        {"kind": "net_reset", "gateway": 0, "frame": 2, "phase": "accept"}]}
    proxy, client = _proxied_client(tmp_path, plan, port)
    try:
        client.send_events([[1, 0.1, 0.0]])
        client.close()                    # conn 2 would be RST; avoid it
        stats = proxy.finish()
        assert stats["frames"] == stats["relayed_frames"] == 2  # hello+batch
        assert stats["frame_bytes"] == stats["bytes_in"] > 0
        assert stats["digest"] == NetFaultPlan.load(
            plan, num_gateways=1).digest
        log = open(f"{tmp_path}/port.net" + "log").read().splitlines()
        head = json.loads(log[0])
        assert head["gateway"] == 0 and head["digest"] == stats["digest"]
        tail = json.loads(log[-1])
        assert tail["summary"]["frames"] == 2
        assert tail["summary"]["fired"] == {}
        # finish() is idempotent — a second call must not re-emit.
        assert proxy.finish() == stats
    finally:
        stop.set()
        proxy.stop()


# --------------------------------------------------- the tier-1 golden

def test_net_sim_matches_committed_golden():
    """The pinned wire campaign replayed through the real engine/session
    machinery must match tests/goldens/net_sim.jsonl bitwise — the gate
    over the whole exactly-once chain."""
    from fedtpu.resilience.net_sim import compare_decisions, simulate
    sim = simulate()
    cmp = compare_decisions(
        sim["lines"],
        os.path.join(REPO, "tests", "goldens", "net_sim.jsonl"))
    assert cmp["ok"], cmp["reason"]
    s = sim["summary"]
    assert set(s["fired"]) == set(NET_KINDS)   # the campaign covers all
    assert s["lost_acked"] == 0
    assert s["duplicate_drops"] > 0
    assert s["incorporated"] == s["arrivals"]


# ------------------------------------------------- the live chaos rows

@pytest.mark.slow
@pytest.mark.parametrize("name", ("mp_net_partition", "mp_slow_gateway",
                                  "mp_torn_frame"))
def test_net_chaos_row(name, tmp_path):
    from fedtpu.resilience.chaos import run_scenario
    row = run_scenario(name, str(tmp_path), {}, 0, 0,
                       platform="cpu", timeout=570)
    assert row["ok"], row
