"""Post-training per-client personalization (fedtpu.training.personalize):
local fine-tuning from the final global model, no further averaging — the
classic FedAvg+fine-tune evaluation the reference has no analogue of."""

import numpy as np
import jax
import pytest

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           ModelConfig, OptimConfig, RunConfig, ShardConfig)
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.parallel import make_mesh, client_sharding
from fedtpu.parallel.round import build_round_fn, init_federated_state
from fedtpu.training.personalize import build_personalize_fn


def test_personalize_trains_each_client_separately():
    x, y = synthetic_income_like(256, 6, 2)
    packed = pack_clients(x, y, ShardConfig(num_clients=8, shuffle=False))
    mesh = make_mesh(num_clients=8)
    shard = client_sharding(mesh)
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig())
    state = init_federated_state(jax.random.key(0), mesh, 8, init_fn, tx,
                                 same_init=True)
    step = build_round_fn(mesh, apply_fn, tx, 2)
    for _ in range(3):
        state, _ = step(state, batch)

    fn = build_personalize_fn(apply_fn, tx, 2, steps=5)
    personal, metrics = fn(state["params"], batch)
    # Post-averaging slots were identical; after personalization on
    # different shards they must differ.
    p = np.asarray(jax.tree.leaves(personal)[0])
    assert np.abs(p[0] - p[1]).max() > 0
    assert set(metrics["per_client"]) == {"accuracy", "precision",
                                          "recall", "f1"}
    assert metrics["per_client"]["accuracy"].shape == (8,)
    assert 0.0 <= float(metrics["client_mean"]["accuracy"]) <= 1.0


def test_personalize_rejects_zero_steps():
    _, apply_fn = build_model(ModelConfig(input_dim=6, hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig())
    with pytest.raises(ValueError, match="steps"):
        build_personalize_fn(apply_fn, tx, 2, steps=0)


def test_personalization_lifts_noniid_client_mean_via_loop():
    # Dirichlet label-skewed shards: a single global model fits every skewed
    # local distribution poorly; local fine-tuning must lift (or at least
    # not hurt) the client-mean train accuracy. Also pins the loop wiring
    # (summary field, final_params stay global).
    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=512,
                        synthetic_features=6),
        shard=ShardConfig(num_clients=8, strategy="dirichlet",
                          dirichlet_alpha=0.3, shuffle=True),
        model=ModelConfig(input_dim=6, hidden_sizes=(8,)),
        optim=OptimConfig(),
        fed=FedConfig(rounds=10, personalize_steps=10),
        run=RunConfig(rounds_per_step=5),
    )
    from fedtpu.orchestration.loop import run_experiment
    result = run_experiment(cfg, verbose=False)
    assert result.personalized_metrics
    global_acc = result.global_metrics["accuracy"][-1]
    personal_acc = result.personalized_metrics["client_mean"]["accuracy"]
    assert personal_acc >= global_acc - 0.02
    assert result.summary()["personalized_client_mean"]["accuracy"] == \
        pytest.approx(personal_acc)


def test_personalization_off_by_default():
    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=128,
                        synthetic_features=6),
        shard=ShardConfig(num_clients=4),
        model=ModelConfig(input_dim=6, hidden_sizes=(8,)),
        fed=FedConfig(rounds=2),
        run=RunConfig(),
    )
    from fedtpu.orchestration.loop import run_experiment
    result = run_experiment(cfg, verbose=False)
    assert result.personalized_metrics == {}
    assert "personalized_client_mean" not in result.summary()
