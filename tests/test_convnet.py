"""ConvNet model family (BASELINE.json config 5 analogue, scaled down for the
single-core CPU mesh) + bf16 compute path."""

import numpy as np
import jax
import jax.numpy as jnp

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           ModelConfig, ShardConfig)
from fedtpu.models import build_model
from fedtpu.orchestration.loop import run_experiment


def _model_cfg(**kw):
    return ModelConfig(kind="convnet", image_shape=(8, 8, 3),
                       conv_channels=(8, 16), hidden_sizes=(32,),
                       num_classes=10, **kw)


def test_convnet_fedavg_end_to_end():
    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=128,
                        synthetic_features=8 * 8 * 3, synthetic_classes=10),
        shard=ShardConfig(num_clients=8),
        model=_model_cfg(),
        fed=FedConfig(rounds=2),
    )
    res = run_experiment(cfg, verbose=False)
    assert res.rounds_run == 2
    assert 0.0 <= res.global_metrics["accuracy"][-1] <= 1.0
    # Global convnet params came back with conv kernels intact.
    assert res.final_params["convs"][0]["w"].shape == (3, 3, 3, 8)


def test_convnet_accepts_nhwc_and_flat_inputs():
    init_fn, apply_fn = build_model(_model_cfg())
    params = init_fn(jax.random.key(0))
    imgs = jnp.ones((4, 8, 8, 3), jnp.float32)
    flat = imgs.reshape(4, -1)
    np.testing.assert_allclose(np.asarray(apply_fn(params, imgs)),
                               np.asarray(apply_fn(params, flat)),
                               atol=1e-6)


def test_bf16_compute_path():
    init_fn, apply_fn = build_model(_model_cfg(compute_dtype="bfloat16"))
    params = init_fn(jax.random.key(0))
    out = apply_fn(params, jnp.ones((4, 8, 8, 3), jnp.float32))
    # Params and logits stay f32 (mixed-precision recipe: bf16 matmuls only).
    assert out.dtype == jnp.float32
    assert params["head"]["w"].dtype == jnp.float32
    assert bool(jnp.isfinite(out).all())
