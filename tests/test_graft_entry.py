"""Driver entry-point regression tests.

Round-1 postmortem: ``MULTICHIP_r01.json`` failed rc=1 because the dryrun let
stray ops (``jax.random.key``, numpy→device converts) dispatch to the default
TPU backend, which in the driver environment was live-but-broken (libtpu
version mismatch). The dryrun must be hermetic: CPU-only, regardless of
XLA_FLAGS, and regardless of what the default backend is.

These run in subprocesses because backend initialization is process-global.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code: str, env_overrides: dict) -> subprocess.CompletedProcess:
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env.update(env_overrides)
    return subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=600)


def test_dryrun_hermetic_no_flags_cpu_only():
    """Without XLA_FLAGS, the dryrun must self-provision 8 CPU devices and
    never initialize any non-CPU backend."""
    proc = _run(
        "from __graft_entry__ import dryrun_multichip\n"
        "dryrun_multichip(8)\n"
        "import jax\n"
        "assert jax.default_backend() == 'cpu', jax.default_backend()\n"
        # Private-API check is best-effort: it is the only way to see that
        # no non-CPU backend was ever *initialized*, but must not turn a
        # JAX-internals rename into a false regression signal.
        "try:\n"
        "    import jax._src.xla_bridge as xb\n"
        "    backends = list(xb._backends.keys())\n"
        "except (ImportError, AttributeError):\n"
        "    backends = ['cpu']\n"
        "assert backends == ['cpu'], backends\n",
        {})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dryrun_multichip(8): ok" in proc.stdout


def test_dryrun_with_driver_flags():
    """Driver-style invocation (XLA_FLAGS force-host-device-count) passes."""
    proc = _run(
        "from __graft_entry__ import dryrun_multichip\n"
        "dryrun_multichip(8)\n",
        {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "2-D dp x tp mesh (4, 2) ok" in proc.stdout


def test_dryrun_after_backend_init_falls_back():
    """If backends are already initialized (default backend possibly
    non-CPU, e.g. the axon TPU on this box) but the CPU device-count flag is
    set, the dryrun completes via explicit CPU devices + default_device pin."""
    proc = _run(
        "import jax; jax.devices()\n"
        "from __graft_entry__ import dryrun_multichip\n"
        "dryrun_multichip(8)\n"
        "import jax\n"
        "assert any(d.platform == 'cpu' for d in jax.devices('cpu'))\n",
        {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dryrun_multichip(8): ok" in proc.stdout


def test_dryrun_after_backend_init_without_flag_raises_cleanly():
    """The round-1 failure shape: backends pre-initialized, NO CPU
    device-count flag, default backend cannot (or must not) serve the mesh.
    The dryrun must fail with the actionable RuntimeError from _devices_for —
    never by dispatching ops to a possibly-broken accelerator backend.
    (On this box the default backend is 1 axon TPU device < 8, so the raise
    path is exercised for real.)"""
    proc = _run(
        "import jax; jax.devices()\n"
        "from __graft_entry__ import dryrun_multichip\n"
        "try:\n"
        "    dryrun_multichip(8)\n"
        "except RuntimeError as e:\n"
        "    assert 'xla_force_host_platform_device_count' in str(e), e\n"
        "    print('clean-raise-ok')\n"
        "else:\n"
        "    print('ran-ok')\n",
        {})
    assert proc.returncode == 0, proc.stderr[-2000:]
    # Either outcome is acceptable (a healthy >=8-device default backend
    # would legitimately run), but a crash is not.
    assert ("clean-raise-ok" in proc.stdout) or ("ran-ok" in proc.stdout)


def test_entry_compiles():
    """entry() returns (fn, args) that jit-compile on the CPU backend."""
    proc = _run(
        "from __graft_entry__ import entry\n"
        "import jax\n"
        "fn, args = entry()\n"
        "out = jax.jit(fn)(*args)\n"
        "assert out.shape == (32, 2), out.shape\n",
        {"JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
