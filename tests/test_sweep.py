"""Federated grid search (hyperparameters_tuning.py analogue): the vmapped
learning-rate axis must agree with the sequential path."""

import numpy as np

from fedtpu.config import DataConfig, ExperimentConfig, ShardConfig
from fedtpu.data.tabular import load_tabular_dataset
from fedtpu.sweep.grid import run_grid_search


def _cfg():
    return ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256),
        shard=ShardConfig(num_clients=8),
    )


def test_vmap_and_sequential_paths_agree():
    cfg = _cfg()
    ds = load_tabular_dataset(cfg.data)
    hidden = ((8,), (4, 4))
    lrs = (0.01, 0.05)
    kw = dict(dataset=ds, hidden_grid=hidden, lr_grid=lrs, local_steps=20,
              verbose=False)
    res_v = run_grid_search(cfg, vmap_lr=True, **kw)
    res_s = run_grid_search(cfg, vmap_lr=False, **kw)

    assert len(res_v["table"]) == len(res_s["table"]) == 4
    tv = {(r["hidden_layer_sizes"], r["learning_rate"]): r["accuracy"]
          for r in res_v["table"]}
    ts = {(r["hidden_layer_sizes"], r["learning_rate"]): r["accuracy"]
          for r in res_s["table"]}
    for k in tv:
        np.testing.assert_allclose(tv[k], ts[k], atol=1e-5)
    assert res_v["params"] == res_s["params"]


def test_best_config_is_tracked():
    cfg = _cfg()
    res = run_grid_search(cfg, hidden_grid=((8,),), lr_grid=(0.01, 0.2),
                          local_steps=30, verbose=False)
    assert res["accuracy"] == max(r["accuracy"] for r in res["table"])
    assert set(res["params"]) == {"hidden_layer_sizes", "learning_rate"}
    assert res["weight_shapes"]  # averaged global weights were captured
