"""Federated grid search (hyperparameters_tuning.py analogue): the vmapped
learning-rate axis must agree with the sequential path."""

import numpy as np

from fedtpu.config import DataConfig, ExperimentConfig, ShardConfig
from fedtpu.data.tabular import load_tabular_dataset
from fedtpu.sweep.grid import run_grid_search


def _cfg():
    return ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256),
        shard=ShardConfig(num_clients=8),
    )


def test_vmap_and_sequential_paths_agree():
    cfg = _cfg()
    ds = load_tabular_dataset(cfg.data)
    hidden = ((8,), (4, 4))
    lrs = (0.01, 0.05)
    kw = dict(dataset=ds, hidden_grid=hidden, lr_grid=lrs, local_steps=20,
              verbose=False)
    res_v = run_grid_search(cfg, vmap_lr=True, **kw)
    res_s = run_grid_search(cfg, vmap_lr=False, **kw)

    assert len(res_v["table"]) == len(res_s["table"]) == 4
    tv = {(r["hidden_layer_sizes"], r["learning_rate"]): r["accuracy"]
          for r in res_v["table"]}
    ts = {(r["hidden_layer_sizes"], r["learning_rate"]): r["accuracy"]
          for r in res_s["table"]}
    for k in tv:
        np.testing.assert_allclose(tv[k], ts[k], atol=1e-5)
    assert res_v["params"] == res_s["params"]


def test_best_config_is_tracked():
    cfg = _cfg()
    res = run_grid_search(cfg, hidden_grid=((8,),), lr_grid=(0.01, 0.2),
                          local_steps=30, verbose=False)
    assert res["accuracy"] == max(r["accuracy"] for r in res["table"])
    assert set(res["params"]) == {"hidden_layer_sizes", "learning_rate"}
    assert res["weight_shapes"]  # averaged global weights were captured


def test_best_weights_round_trip(tmp_path):
    # VERDICT r1 missing item: the reference PRINTS the winning weight
    # matrices (hyperparameters_tuning.py:130-132); fedtpu must persist
    # them as a real artifact that round-trips and actually predicts.
    import jax
    from fedtpu.models.mlp import mlp_apply
    from fedtpu.sweep.grid import load_best_weights, save_best_weights

    cfg = _cfg()
    ds = load_tabular_dataset(cfg.data)
    res = run_grid_search(cfg, dataset=ds, hidden_grid=((8,), (4, 4)),
                          lr_grid=(0.01, 0.05), local_steps=20,
                          keep_weights=True, verbose=False)
    assert res["weights"] is not None
    path = str(tmp_path / "best.npz")
    save_best_weights(path, res)

    loaded = load_best_weights(path)
    assert loaded["params"]["learning_rate"] == (
        res["params"]["learning_rate"])
    assert tuple(loaded["params"]["hidden_layer_sizes"]) == (
        res["params"]["hidden_layer_sizes"])
    assert loaded["accuracy"] == res["accuracy"]
    jax.tree.map(np.testing.assert_array_equal,
                 loaded["weights"], res["weights"])
    # The restored pytree must plug straight into the model.
    logits = mlp_apply(loaded["weights"], ds.x_train[:16])
    assert logits.shape == (16, ds.num_classes)


def test_weights_dropped_without_flag(tmp_path):
    import pytest
    from fedtpu.sweep.grid import save_best_weights

    cfg = _cfg()
    res = run_grid_search(cfg, hidden_grid=((8,),), lr_grid=(0.01,),
                          local_steps=5, verbose=False)
    assert "weights" not in res           # default: shapes only, as before
    assert res["weight_shapes"]
    with pytest.raises(ValueError, match="keep_weights"):
        save_best_weights(str(tmp_path / "x.npz"), res)


def test_cli_sweep_saves_weights(tmp_path):
    from fedtpu.cli import main as cli_main
    from fedtpu.sweep.grid import load_best_weights

    out = tmp_path / "winner.npz"
    # --hidden-sizes / --learning-rate narrow the sweep to ONE config (the
    # flags must not be silently ignored — review r2): this runs a single
    # tiny architecture, not the full 10x9 reference grid.
    rc = cli_main(["sweep", "--csv", "", "--num-clients", "2",
                   "--hidden-sizes", "8", "--learning-rate", "0.01",
                   "--local-steps", "5",
                   "--save-weights", str(out), "--quiet", "--json"])
    assert rc == 0 or rc is None
    loaded = load_best_weights(str(out))
    assert tuple(loaded["params"]["hidden_layer_sizes"]) == (8,)
    assert loaded["params"]["learning_rate"] == 0.01
    assert len(loaded["weights"]["layers"]) == 2   # one hidden + head


def test_run_warm_starts_from_sweep_winner(tmp_path):
    # Closes the reference's dangling artifact loop: the sweep persists
    # the winner (hyperparameters_tuning.py only prints it), and a run can
    # START from it. On this easy synthetic set the winner is near-perfect,
    # so round 1 of the warm-started run must already sit far above a
    # fresh-init round 1.
    import dataclasses
    from fedtpu.config import FedConfig, ModelConfig, RunConfig
    from fedtpu.orchestration.loop import run_experiment
    from fedtpu.sweep.grid import save_best_weights

    cfg = _cfg()
    ds = load_tabular_dataset(cfg.data)
    best = run_grid_search(cfg, dataset=ds, hidden_grid=((8,),),
                           lr_grid=(0.05,), local_steps=60,
                           keep_weights=True, verbose=False)
    path = str(tmp_path / "winner.npz")
    save_best_weights(path, best)
    assert best["accuracy"] > 0.9

    run_cfg = dataclasses.replace(
        cfg,
        model=ModelConfig(input_dim=ds.input_dim, hidden_sizes=(8,)),
        fed=FedConfig(rounds=1, tolerance=0.0),
        run=RunConfig(rounds_per_step=1))
    fresh = run_experiment(run_cfg, dataset=ds, verbose=False)
    warm = run_experiment(
        dataclasses.replace(run_cfg, fed=dataclasses.replace(
            run_cfg.fed, init_weights_npz=path)),
        dataset=ds, verbose=False)
    assert warm.global_metrics["accuracy"][0] > 0.85
    assert (warm.global_metrics["accuracy"][0]
            > fresh.global_metrics["accuracy"][0] + 0.2)


def test_init_weights_architecture_mismatch_fails_fast(tmp_path):
    import dataclasses
    import pytest
    from fedtpu.config import FedConfig, ModelConfig, RunConfig
    from fedtpu.orchestration.loop import build_experiment
    from fedtpu.sweep.grid import save_best_weights

    cfg = _cfg()
    ds = load_tabular_dataset(cfg.data)
    best = run_grid_search(cfg, dataset=ds, hidden_grid=((8,),),
                           lr_grid=(0.05,), local_steps=5,
                           keep_weights=True, verbose=False)
    path = str(tmp_path / "winner.npz")
    save_best_weights(path, best)

    bad = dataclasses.replace(
        cfg,
        model=ModelConfig(input_dim=ds.input_dim, hidden_sizes=(16, 16)),
        fed=FedConfig(rounds=1, init_weights_npz=path),
        run=RunConfig())
    with pytest.raises(ValueError, match="architecture mismatch"):
        build_experiment(bad, dataset=ds)


def test_resume_takes_precedence_over_init_weights(tmp_path):
    # A checkpointed run restarted with BOTH --resume and --init-weights
    # must continue from the checkpoint, not restart from the artifact:
    # warm start seeds a NEW experiment; resume restores a live one.
    import dataclasses
    from fedtpu.config import FedConfig, ModelConfig, RunConfig
    from fedtpu.orchestration.loop import run_experiment
    from fedtpu.sweep.grid import save_best_weights

    cfg = _cfg()
    ds = load_tabular_dataset(cfg.data)
    best = run_grid_search(cfg, dataset=ds, hidden_grid=((8,),),
                           lr_grid=(0.05,), local_steps=5,
                           keep_weights=True, verbose=False)
    path = str(tmp_path / "winner.npz")
    save_best_weights(path, best)

    ck = str(tmp_path / "ck")
    run_cfg = dataclasses.replace(
        cfg,
        model=ModelConfig(input_dim=ds.input_dim, hidden_sizes=(8,)),
        fed=FedConfig(rounds=3, tolerance=0.0),
        run=RunConfig(rounds_per_step=1, checkpoint_dir=ck,
                      checkpoint_every=1))
    first = run_experiment(run_cfg, dataset=ds, verbose=False)
    assert first.rounds_run == 3

    both = dataclasses.replace(
        run_cfg, fed=dataclasses.replace(run_cfg.fed, rounds=5,
                                         init_weights_npz=path))
    resumed = run_experiment(both, dataset=ds, verbose=False, resume=True)
    # Continued 4..5 from the checkpoint (history restored + 2 new rounds),
    # not a fresh 5-round warm-started run.
    assert resumed.rounds_run == 5
    assert len(resumed.global_metrics["accuracy"]) == 5
    np.testing.assert_allclose(resumed.global_metrics["accuracy"][:3],
                               first.global_metrics["accuracy"], atol=1e-6)


# ------------------------------------------------- plateau-stop semantics

def test_plateau_stop_freezes_exactly_at_the_plateau_point():
    """Mechanism pin: with a huge tol every post-first step is 'no
    improvement', so sklearn's bookkeeping (counter resets on improvement,
    stop once it EXCEEDS n_iter_no_change) trains exactly
    n_iter_no_change + 2 steps and then coasts — the result must equal a
    fixed-step run of that length bit-for-bit."""
    import jax
    import jax.numpy as jnp
    import optax
    from fedtpu.models.mlp import mlp_init
    from fedtpu.parallel.mesh import client_sharding, make_mesh
    from fedtpu.sweep.grid import _build_sweep_fn
    from fedtpu.data.sharding import pack_clients

    cfg = _cfg()
    ds = load_tabular_dataset(cfg.data)
    mesh = make_mesh(num_clients=8)
    shard = client_sharding(mesh)
    packed = pack_clients(ds.x_train, ds.y_train, cfg.shard)
    x, y, mask = (jax.device_put(v, shard)
                  for v in (packed.x, packed.y, packed.mask))

    def inputs():
        base = mlp_init(jax.random.key(42), ds.input_dim, (8,),
                        ds.num_classes)
        params = jax.tree.map(
            lambda p: jnp.broadcast_to(p, (8, 1) + p.shape), base)
        opt_state = jax.vmap(jax.vmap(
            lambda p: optax.scale_by_adam(eps_root=0.0).init(p)))(params)
        put = lambda t: jax.tree.map(
            lambda p: jax.device_put(p, shard), t)
        return put(params), put(opt_state)

    lrs = jnp.asarray([0.01], jnp.float32)
    # n_iter_no_change=2, tol=1e9: step 1 improves from inf (counter 0);
    # steps 2-4 each fail the tol bar (counter 1,2,3); 3 > 2 stops after
    # step 4.
    plateau_fn = _build_sweep_fn(mesh, ds.num_classes, local_steps=20,
                                 optim_cfg=cfg.optim, plateau_stop=True,
                                 tol=1e9, n_iter_no_change=2)
    p0, s0 = inputs()
    avg_p, _, _, mean_steps = plateau_fn(p0, s0, lrs, x, y, mask)
    assert float(np.asarray(mean_steps)[0]) == 4.0

    fixed_fn = _build_sweep_fn(mesh, ds.num_classes, local_steps=4,
                               optim_cfg=cfg.optim)
    p1, s1 = inputs()
    avg_p_fixed, _, _, fixed_steps = fixed_fn(p1, s1, lrs, x, y, mask)
    assert float(np.asarray(fixed_steps)[0]) == 4.0
    for a, b in zip(jax.tree.leaves(avg_p), jax.tree.leaves(avg_p_fixed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_plateau_stop_fires_before_the_cap_like_sklearn():
    """The reference's grid runs under MLPClassifier(max_iter=400), where
    400 is a CAP: sklearn's adam stops at the loss plateau. Demonstrate
    the cap-vs-count distinction on real sklearn, then check fedtpu's
    plateau trainer also stops early while the fixed trainer runs all
    400 steps (VERDICT r2 missing #1)."""
    from sklearn.neural_network import MLPClassifier

    cfg = _cfg()
    ds = load_tabular_dataset(cfg.data)
    clf = MLPClassifier(hidden_layer_sizes=(8,), max_iter=400,
                        random_state=42)
    clf.fit(ds.x_train, ds.y_train)
    assert clf.n_iter_ < 400  # max_iter is a cap, not a step count

    res = run_grid_search(cfg, dataset=ds, hidden_grid=((8,),),
                          lr_grid=(0.004,), local_steps=400,
                          plateau_stop=True, verbose=False)
    row = res["table"][0]
    assert row["mean_local_steps"] < 400
    res_fixed = run_grid_search(cfg, dataset=ds, hidden_grid=((8,),),
                                lr_grid=(0.004,), local_steps=400,
                                verbose=False)
    assert res_fixed["table"][0]["mean_local_steps"] == 400


def test_bucket_pad_matches_unpadded_exactly():
    """Zero-padding to the depth bucket is EXACT for a ReLU MLP (module
    docstring): padded activations stay zero through forward, ReLU'(0)=0
    kills their gradients, Adam leaves zero weights zero. The whole
    table, the winner, and the winner's (sliced) weights must match the
    unpadded run; compile count must drop to one per depth class."""
    cfg = _cfg()
    ds = load_tabular_dataset(cfg.data)
    hidden = ((8,), (16,), (4, 4), (16, 8), (8, 16))   # 2 depth classes
    lrs = (0.01, 0.05)
    kw = dict(dataset=ds, hidden_grid=hidden, lr_grid=lrs, local_steps=20,
              keep_weights=True, verbose=False)
    res_b = run_grid_search(cfg, bucket_pad=True, **kw)
    res_u = run_grid_search(cfg, bucket_pad=False, **kw)

    tb = {(r["hidden_layer_sizes"], r["learning_rate"]): r
          for r in res_b["table"]}
    tu = {(r["hidden_layer_sizes"], r["learning_rate"]): r
          for r in res_u["table"]}
    assert set(tb) == set(tu) and len(tb) == 10
    for k in tb:
        for m in ("accuracy", "precision", "recall", "f1"):
            np.testing.assert_allclose(tb[k][m], tu[k][m], atol=1e-6)
    assert res_b["params"] == res_u["params"]
    # Winner weights come back at TRUE dims and match the unpadded run.
    for lb, lu in zip(res_b["weights"]["layers"],
                      res_u["weights"]["layers"]):
        assert lb["w"].shape == lu["w"].shape
        np.testing.assert_allclose(lb["w"], lu["w"], atol=1e-6)
    # 5 architectures, 2 depth classes: bucketing compiles 2 programs.
    if res_b["compile_count"] is not None:
        assert res_b["compile_count"] == 2
        assert res_u["compile_count"] == 5


def test_bucket_pad_plateau_matches_unpadded():
    # The plateau detector watches a loss that includes the L2 term —
    # zero pads add exactly zero to it, so stop points cannot move.
    cfg = _cfg()
    ds = load_tabular_dataset(cfg.data)
    kw = dict(dataset=ds, hidden_grid=((4, 4), (8, 16)), lr_grid=(0.05,),
              local_steps=60, plateau_stop=True, verbose=False)
    res_b = run_grid_search(cfg, bucket_pad=True, **kw)
    res_u = run_grid_search(cfg, bucket_pad=False, **kw)
    for rb, ru in zip(res_b["table"], res_u["table"]):
        np.testing.assert_allclose(rb["mean_local_steps"],
                                   ru["mean_local_steps"], atol=0)
        np.testing.assert_allclose(rb["accuracy"], ru["accuracy"],
                                   atol=1e-6)


def test_arch_vmap_parity_with_per_arch_launches():
    """Round-5 launch cut (VERDICT r4 #2): stacking a depth class's
    architectures into the vmapped axis must match one launch per
    architecture — same table, same winner, same weights. Tolerances sit
    at float-drift scale (the two launch plans are differently-shaped
    XLA programs, which MAY tile reductions differently even though the
    vmapped slots are elementwise-independent; observed bit-identical on
    the CPU CI and the v5e, but bitness is not a contract)."""
    cfg = _cfg()
    ds = load_tabular_dataset(cfg.data)
    hidden = ((8,), (6,), (4, 4), (6, 4))      # two depth classes, 2 archs each
    lrs = (0.01, 0.05, 0.1)
    kw = dict(dataset=ds, hidden_grid=hidden, lr_grid=lrs, local_steps=20,
              keep_weights=True, verbose=False)
    res_a = run_grid_search(cfg, vmap_arch=True, **kw)
    res_p = run_grid_search(cfg, vmap_arch=False, **kw)

    assert res_a["launch_count"] == 2          # one per depth class
    assert res_p["launch_count"] == 4          # one per architecture
    assert len(res_a["table"]) == len(res_p["table"]) == 12
    for ra, rp in zip(res_a["table"], res_p["table"]):
        assert ra["hidden_layer_sizes"] == rp["hidden_layer_sizes"]
        assert ra["learning_rate"] == rp["learning_rate"]
        np.testing.assert_allclose(ra["accuracy"], rp["accuracy"],
                                   atol=1e-6)
        np.testing.assert_allclose(ra["f1"], rp["f1"], atol=1e-6)
    assert res_a["params"] == res_p["params"]
    for a, b in zip((l[k] for l in res_a["weights"]["layers"]
                     for k in ("w", "b")),
                    (l[k] for l in res_p["weights"]["layers"]
                     for k in ("w", "b"))):
        np.testing.assert_allclose(a, b, atol=1e-5)


def test_tie_set_is_reported_and_stable():
    """Round-5 winner stability (VERDICT r4 #3): the strict-> argmax stays
    the labeled parity answer, and the tie set is the stable result —
    identical across launch plans even where the argmax could drift."""
    cfg = _cfg()
    ds = load_tabular_dataset(cfg.data)
    # Separable synthetic data + enough steps => several configs hit 1.0.
    kw = dict(dataset=ds, hidden_grid=((8,), (6,), (4, 4)),
              lr_grid=(0.05, 0.1), local_steps=150, verbose=False)
    res_a = run_grid_search(cfg, vmap_arch=True, **kw)
    res_p = run_grid_search(cfg, vmap_arch=False, **kw)

    # The winner is a member of its own tie set, and every tie-set row is
    # flagged in the table.
    for res in (res_a, res_p):
        keys = {(t["hidden_layer_sizes"], t["learning_rate"])
                for t in res["tie_set"]}
        assert (res["params"]["hidden_layer_sizes"],
                res["params"]["learning_rate"]) in keys
        flagged = {(r["hidden_layer_sizes"], r["learning_rate"])
                   for r in res["table"] if r["in_tie_set"]}
        assert flagged == keys
        assert res["tie_tolerance"] == 1e-6
    # Stability across launch plans: the SET matches even if the argmax
    # member could differ under drift.
    assert ({(t["hidden_layer_sizes"], t["learning_rate"])
             for t in res_a["tie_set"]}
            == {(t["hidden_layer_sizes"], t["learning_rate"])
                for t in res_p["tie_set"]})
    # On this separable task the tie is real (the instability VERDICT r4
    # documented): more than one config at the top.
    assert len(res_a["tie_set"]) > 1
