"""Federated grid search (hyperparameters_tuning.py analogue): the vmapped
learning-rate axis must agree with the sequential path."""

import numpy as np

from fedtpu.config import DataConfig, ExperimentConfig, ShardConfig
from fedtpu.data.tabular import load_tabular_dataset
from fedtpu.sweep.grid import run_grid_search


def _cfg():
    return ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256),
        shard=ShardConfig(num_clients=8),
    )


def test_vmap_and_sequential_paths_agree():
    cfg = _cfg()
    ds = load_tabular_dataset(cfg.data)
    hidden = ((8,), (4, 4))
    lrs = (0.01, 0.05)
    kw = dict(dataset=ds, hidden_grid=hidden, lr_grid=lrs, local_steps=20,
              verbose=False)
    res_v = run_grid_search(cfg, vmap_lr=True, **kw)
    res_s = run_grid_search(cfg, vmap_lr=False, **kw)

    assert len(res_v["table"]) == len(res_s["table"]) == 4
    tv = {(r["hidden_layer_sizes"], r["learning_rate"]): r["accuracy"]
          for r in res_v["table"]}
    ts = {(r["hidden_layer_sizes"], r["learning_rate"]): r["accuracy"]
          for r in res_s["table"]}
    for k in tv:
        np.testing.assert_allclose(tv[k], ts[k], atol=1e-5)
    assert res_v["params"] == res_s["params"]


def test_best_config_is_tracked():
    cfg = _cfg()
    res = run_grid_search(cfg, hidden_grid=((8,),), lr_grid=(0.01, 0.2),
                          local_steps=30, verbose=False)
    assert res["accuracy"] == max(r["accuracy"] for r in res["table"])
    assert set(res["params"]) == {"hidden_layer_sizes", "learning_rate"}
    assert res["weight_shapes"]  # averaged global weights were captured


def test_best_weights_round_trip(tmp_path):
    # VERDICT r1 missing item: the reference PRINTS the winning weight
    # matrices (hyperparameters_tuning.py:130-132); fedtpu must persist
    # them as a real artifact that round-trips and actually predicts.
    import jax
    from fedtpu.models.mlp import mlp_apply
    from fedtpu.sweep.grid import load_best_weights, save_best_weights

    cfg = _cfg()
    ds = load_tabular_dataset(cfg.data)
    res = run_grid_search(cfg, dataset=ds, hidden_grid=((8,), (4, 4)),
                          lr_grid=(0.01, 0.05), local_steps=20,
                          keep_weights=True, verbose=False)
    assert res["weights"] is not None
    path = str(tmp_path / "best.npz")
    save_best_weights(path, res)

    loaded = load_best_weights(path)
    assert loaded["params"]["learning_rate"] == (
        res["params"]["learning_rate"])
    assert tuple(loaded["params"]["hidden_layer_sizes"]) == (
        res["params"]["hidden_layer_sizes"])
    assert loaded["accuracy"] == res["accuracy"]
    jax.tree.map(np.testing.assert_array_equal,
                 loaded["weights"], res["weights"])
    # The restored pytree must plug straight into the model.
    logits = mlp_apply(loaded["weights"], ds.x_train[:16])
    assert logits.shape == (16, ds.num_classes)


def test_weights_dropped_without_flag(tmp_path):
    import pytest
    from fedtpu.sweep.grid import save_best_weights

    cfg = _cfg()
    res = run_grid_search(cfg, hidden_grid=((8,),), lr_grid=(0.01,),
                          local_steps=5, verbose=False)
    assert "weights" not in res           # default: shapes only, as before
    assert res["weight_shapes"]
    with pytest.raises(ValueError, match="keep_weights"):
        save_best_weights(str(tmp_path / "x.npz"), res)


def test_cli_sweep_saves_weights(tmp_path):
    from fedtpu.cli import main as cli_main
    from fedtpu.sweep.grid import load_best_weights

    out = tmp_path / "winner.npz"
    # --hidden-sizes / --learning-rate narrow the sweep to ONE config (the
    # flags must not be silently ignored — review r2): this runs a single
    # tiny architecture, not the full 10x9 reference grid.
    rc = cli_main(["sweep", "--csv", "", "--num-clients", "2",
                   "--hidden-sizes", "8", "--learning-rate", "0.01",
                   "--local-steps", "5",
                   "--save-weights", str(out), "--quiet", "--json"])
    assert rc == 0 or rc is None
    loaded = load_best_weights(str(out))
    assert tuple(loaded["params"]["hidden_layer_sizes"]) == (8,)
    assert loaded["params"]["learning_rate"] == 0.01
    assert len(loaded["weights"]["layers"]) == 2   # one hidden + head


def test_run_warm_starts_from_sweep_winner(tmp_path):
    # Closes the reference's dangling artifact loop: the sweep persists
    # the winner (hyperparameters_tuning.py only prints it), and a run can
    # START from it. On this easy synthetic set the winner is near-perfect,
    # so round 1 of the warm-started run must already sit far above a
    # fresh-init round 1.
    import dataclasses
    from fedtpu.config import FedConfig, ModelConfig, RunConfig
    from fedtpu.orchestration.loop import run_experiment
    from fedtpu.sweep.grid import save_best_weights

    cfg = _cfg()
    ds = load_tabular_dataset(cfg.data)
    best = run_grid_search(cfg, dataset=ds, hidden_grid=((8,),),
                           lr_grid=(0.05,), local_steps=60,
                           keep_weights=True, verbose=False)
    path = str(tmp_path / "winner.npz")
    save_best_weights(path, best)
    assert best["accuracy"] > 0.9

    run_cfg = dataclasses.replace(
        cfg,
        model=ModelConfig(input_dim=ds.input_dim, hidden_sizes=(8,)),
        fed=FedConfig(rounds=1, tolerance=0.0),
        run=RunConfig(rounds_per_step=1))
    fresh = run_experiment(run_cfg, dataset=ds, verbose=False)
    warm = run_experiment(
        dataclasses.replace(run_cfg, fed=dataclasses.replace(
            run_cfg.fed, init_weights_npz=path)),
        dataset=ds, verbose=False)
    assert warm.global_metrics["accuracy"][0] > 0.85
    assert (warm.global_metrics["accuracy"][0]
            > fresh.global_metrics["accuracy"][0] + 0.2)


def test_init_weights_architecture_mismatch_fails_fast(tmp_path):
    import dataclasses
    import pytest
    from fedtpu.config import FedConfig, ModelConfig, RunConfig
    from fedtpu.orchestration.loop import build_experiment
    from fedtpu.sweep.grid import save_best_weights

    cfg = _cfg()
    ds = load_tabular_dataset(cfg.data)
    best = run_grid_search(cfg, dataset=ds, hidden_grid=((8,),),
                           lr_grid=(0.05,), local_steps=5,
                           keep_weights=True, verbose=False)
    path = str(tmp_path / "winner.npz")
    save_best_weights(path, best)

    bad = dataclasses.replace(
        cfg,
        model=ModelConfig(input_dim=ds.input_dim, hidden_sizes=(16, 16)),
        fed=FedConfig(rounds=1, init_weights_npz=path),
        run=RunConfig())
    with pytest.raises(ValueError, match="architecture mismatch"):
        build_experiment(bad, dataset=ds)


def test_resume_takes_precedence_over_init_weights(tmp_path):
    # A checkpointed run restarted with BOTH --resume and --init-weights
    # must continue from the checkpoint, not restart from the artifact:
    # warm start seeds a NEW experiment; resume restores a live one.
    import dataclasses
    from fedtpu.config import FedConfig, ModelConfig, RunConfig
    from fedtpu.orchestration.loop import run_experiment
    from fedtpu.sweep.grid import save_best_weights

    cfg = _cfg()
    ds = load_tabular_dataset(cfg.data)
    best = run_grid_search(cfg, dataset=ds, hidden_grid=((8,),),
                           lr_grid=(0.05,), local_steps=5,
                           keep_weights=True, verbose=False)
    path = str(tmp_path / "winner.npz")
    save_best_weights(path, best)

    ck = str(tmp_path / "ck")
    run_cfg = dataclasses.replace(
        cfg,
        model=ModelConfig(input_dim=ds.input_dim, hidden_sizes=(8,)),
        fed=FedConfig(rounds=3, tolerance=0.0),
        run=RunConfig(rounds_per_step=1, checkpoint_dir=ck,
                      checkpoint_every=1))
    first = run_experiment(run_cfg, dataset=ds, verbose=False)
    assert first.rounds_run == 3

    both = dataclasses.replace(
        run_cfg, fed=dataclasses.replace(run_cfg.fed, rounds=5,
                                         init_weights_npz=path))
    resumed = run_experiment(both, dataset=ds, verbose=False, resume=True)
    # Continued 4..5 from the checkpoint (history restored + 2 new rounds),
    # not a fresh 5-round warm-started run.
    assert resumed.rounds_run == 5
    assert len(resumed.global_metrics["accuracy"]) == 5
    np.testing.assert_allclose(resumed.global_metrics["accuracy"][:3],
                               first.global_metrics["accuracy"], atol=1e-6)
