"""Poisoning defense at serving scale (fedtpu.robust; docs/robustness.md).

Four contracts:

* **Screen precision** — honest-but-heterogeneous clients (dirichlet
  label skew) must produce ZERO screened updates at the default
  thresholds; a threshold sweep shows where the norm test starts to
  bite, so the default's headroom is a measured number, not a vibe.
* **Screen recall** — an amplified sign-flipped update is screened once
  the rolling median is warm, and a screened arrival changes nothing
  (the global step equals the attacker-absent step bitwise).
* **Quarantine determinism** — strike/quarantine decisions are pure
  functions of the virtual-time tick stream: bitwise identical across a
  mid-campaign checkpoint/restore, and durably flagged in the client
  store's versioned reputation field.
* **The golden gate** — the defense sim's decision JSONL is bitwise
  deterministic and matches the COMMITTED golden
  (tests/goldens/defense_sim.jsonl), with divergence reported by first
  differing line (autoscale-gate idiom).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from fedtpu.config import (ModelConfig, OptimConfig, ServingConfig,
                           ShardConfig)
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.robust.defense_sim import (compare_decisions, simulate,
                                       write_decisions)
from fedtpu.serving.traces import (TRACE_SCHEMA_VERSION,
                                   TRACE_SCHEMA_VERSION_POISON,
                                   load_trace_arrays, poisoned_user_ids,
                                   read_trace, synthesize_trace,
                                   write_trace)
from fedtpu.telemetry.metrics import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "goldens", "defense_sim.jsonl")

C = 8


def _screen_fixtures(strategy="dirichlet"):
    """A driven async setup over label-skewed honest shards."""
    import jax

    from fedtpu.parallel import async_fed, client_sharding, make_mesh
    x, y = synthetic_income_like(256, 6, 2, seed=0)
    packed = pack_clients(x, y, ShardConfig(num_clients=C, shuffle=False,
                                            strategy=strategy,
                                            dirichlet_alpha=0.3))
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(16, 8)))
    tx = build_optimizer(OptimConfig())
    mesh = make_mesh(num_clients=C)
    batch = {k: jax.device_put(v, client_sharding(mesh)) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    return mesh, init_fn, apply_fn, tx, batch


def _drive(mesh, init_fn, apply_fn, tx, batch, *, ticks, weights,
           norm_mult=4.0, cos_min=-0.2, warmup=8, window=16):
    """Run ``ticks`` driven screen ticks with per-tick arrival weight
    rows from ``weights`` (callable tick -> (C,) array). Returns the
    total screened count and the final state."""
    import jax

    from fedtpu.parallel import async_fed
    state = async_fed.init_async_state(jax.random.key(0), mesh, C,
                                       init_fn, tx, same_init=True,
                                       screen_window=window)
    step = async_fed.build_async_round_fn(
        mesh, apply_fn, tx, 2, driven=True, screen=True,
        screen_norm_mult=norm_mult, screen_cos_min=cos_min,
        screen_warmup=warmup, screen_window=window)
    screened = 0
    for k in range(ticks):
        arr = np.asarray(weights(k), np.float32)[None, :]
        state, m = step(state, batch, arr)
        screened += int(np.asarray(m["screened"]).sum())
    return screened, state


# ------------------------------------------------------- screen precision

def test_label_skew_honest_clients_zero_false_positives():
    """Satellite pin: dirichlet label-skewed HONEST clients are exactly
    the hard case for a norm/direction screen (heterogeneous data means
    heterogeneous update norms and directions) — at the default
    thresholds none of them may be screened."""
    fx = _screen_fixtures("dirichlet")
    screened, _ = _drive(*fx, ticks=24, weights=lambda k: np.ones(C))
    assert screened == 0


def test_threshold_sweep_locates_the_norm_test_bite_point():
    """Sweep screen_norm_mult downward over the same honest label-skew
    traffic: the default never fires, a paranoid multiplier eventually
    does, and the false-positive count is monotone as thresholds
    tighten — the sweep that justifies the 4.0 default."""
    fx = _screen_fixtures("dirichlet")
    counts = {}
    for mult in (4.0, 2.0, 1.05, 0.7):
        counts[mult], _ = _drive(*fx, ticks=24, norm_mult=mult,
                                 weights=lambda k: np.ones(C))
    assert counts[4.0] == 0
    assert counts[0.7] > 0, counts
    ordered = [counts[m] for m in (4.0, 2.0, 1.05, 0.7)]
    assert ordered == sorted(ordered), counts


# --------------------------------------------------------- screen recall

def test_sign_flipped_update_is_screened_once_warm():
    """An attacker submitting a 10x sign-flipped update (arrival weight
    -10) is screened every post-warmup tick; honest peers are not."""
    fx = _screen_fixtures("contiguous")

    def weights(k):
        w = np.ones(C)
        w[3] = -10.0
        return w

    import jax

    from fedtpu.parallel import async_fed
    mesh, init_fn, apply_fn, tx, batch = fx
    state = async_fed.init_async_state(jax.random.key(0), mesh, C,
                                       init_fn, tx, same_init=True,
                                       screen_window=16)
    step = async_fed.build_async_round_fn(
        mesh, apply_fn, tx, 2, driven=True, screen=True,
        screen_warmup=4, screen_window=16)
    hits = []
    for k in range(10):
        arr = np.asarray(weights(k), np.float32)[None, :]
        state, m = step(state, batch, arr)
        scr = np.asarray(m["screened"])
        # Honest clients never screened.
        assert scr[[i for i in range(C) if i != 3]].sum() == 0
        hits.append(float(scr[3]))
    # Warmup passes within the first few ticks; from then on the
    # attacker is caught every tick.
    assert sum(hits) >= 5, hits
    assert hits[-1] == 1.0 and hits[-2] == 1.0


# ----------------------------------------------- quarantine determinism

def _poison_rows(arrivals=260, users=30, seed=5, frac=0.2, scale=10.0):
    header, t, user, lat = synthesize_trace(users, arrivals, 20.0,
                                            seed=seed, poison_frac=frac,
                                            poison_scale=scale)
    atk = {int(u) for u in poisoned_user_ids(users, seed, frac)}
    rows = [([int(user[i]), float(t[i]), float(lat[i]), None, scale]
             if int(user[i]) in atk else
             [int(user[i]), float(t[i]), float(lat[i])])
            for i in range(len(t))]
    return rows, sorted(atk)


def _defense_cfg(**kw):
    base = dict(cohort=8, buffer_size=2, tick_interval_s=0.5,
                data_rows=64, model_hidden=(8,), seed=0, screen=True,
                quarantine_strikes=3)
    base.update(kw)
    return ServingConfig(**base)


def test_quarantine_bitwise_across_checkpoint_restore(tmp_path):
    """Mid-campaign kill+resume must not move a single defense decision:
    run the same poisoned replay straight through and split across a
    checkpoint/restore, and compare the decision log, strike table,
    quarantine set, and tick history bitwise."""
    from fedtpu.serving.engine import ServingEngine
    rows, attackers = _poison_rows()
    half = len(rows) // 2

    a = ServingEngine(_defense_cfg(), registry=MetricsRegistry())
    a.offer_many(rows)
    a.drain()
    assert a.quarantined, "campaign never quarantined anyone"
    assert set(a.quarantined) <= set(attackers)

    b1 = ServingEngine(_defense_cfg(), registry=MetricsRegistry())
    b1.offer_many(rows[:half])
    ckdir = str(tmp_path / "ck")
    b1.checkpoint(ckdir)
    b2 = ServingEngine(_defense_cfg(), registry=MetricsRegistry())
    b2.restore(ckdir)
    assert b2.strikes == b1.strikes
    assert b2.quarantined == b1.quarantined
    b2.offer_many(rows[half:])
    b2.drain()

    assert b2.quarantined == a.quarantined
    assert b2.strikes == a.strikes
    assert b2.screened_total == a.screened_total
    assert b2.history_lines() == a.history_lines()
    # The post-restore decision tail continues the uninterrupted log.
    assert b2.defense_log == a.defense_log[len(b1.defense_log):]


def test_quarantine_refused_at_offer_and_flagged_in_store():
    """A quarantined user's later offers are refused without spending an
    admission token, and the store's versioned reputation field carries
    the flag durably (quarantined_ids round-trips it)."""
    from fedtpu.serving.admission import SCREENED
    from fedtpu.serving.engine import ServingEngine
    rows, attackers = _poison_rows()
    eng = ServingEngine(_defense_cfg(), registry=MetricsRegistry())
    eng.attach_store(total_users=30)
    eng.offer_many(rows)
    eng.drain()
    assert eng.quarantined
    flagged = sorted(int(u) for u in eng.store.quarantined_ids())
    assert flagged == sorted(eng.quarantined)
    victim = next(iter(eng.quarantined))
    before = dict(eng.admission.counts)
    assert eng.offer(99.0, victim, 0.0) == SCREENED
    after = dict(eng.admission.counts)
    assert after[SCREENED] == before[SCREENED] + 1


def test_cohort_sampler_refuses_quarantined_ids():
    from fedtpu.cohort.scheduler import CohortSampler
    s = CohortSampler(total_clients=10, cohort_size=4, seed=0)
    s.refuse([1, 2])
    for r in range(6):
        cohort = s.sample(r)
        assert not ({1, 2} & set(int(c) for c in cohort.ravel()))
    with pytest.raises(ValueError, match="population exhausted"):
        s.refuse(range(9))


# ------------------------------------------------------------ trace v2

def test_poison_free_synthesis_is_byte_identical_v1(tmp_path):
    h1, t, u, lat = synthesize_trace(100, 60, seed=3)
    h2, t2, u2, l2 = synthesize_trace(100, 60, seed=3, poison_frac=0.0)
    assert h1.v == TRACE_SCHEMA_VERSION and h1.to_json() == h2.to_json()
    p1, p2 = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    write_trace(p1, h1, t, u, lat)
    write_trace(p2, h2, t2, u2, l2)
    with open(p1, "rb") as fa, open(p2, "rb") as fb:
        assert fa.read() == fb.read()


def test_poisoned_trace_v2_roundtrip(tmp_path):
    h, t, u, lat = synthesize_trace(100, 80, seed=3, poison_frac=0.2,
                                    poison_scale=8.0)
    assert h.v == TRACE_SCHEMA_VERSION_POISON
    assert h.params["poison_frac"] == 0.2
    atk = {int(x) for x in poisoned_user_ids(100, 3, 0.2)}
    assert len(atk) == 20
    path = str(tmp_path / "p.jsonl")
    write_trace(path, h, t, u, lat)
    _, events = read_trace(path)
    for ev in events:
        assert ev.poison == (8.0 if ev.user in atk else 0.0)
    # The 4-tuple array loader (cohort trace sampling, autoscale sim)
    # stays backward compatible with v2 files.
    h3, t3, u3, l3 = load_trace_arrays(path)
    np.testing.assert_array_equal(u3, u)


def test_trace_reader_rejects_future_schema(tmp_path):
    path = tmp_path / "v3.jsonl"
    path.write_text('{"kind": "trace_header", "v": 3, "users": 1, '
                    '"arrivals": 0}\n')
    with pytest.raises(ValueError, match="unsupported trace schema"):
        read_trace(str(path))


def test_poisoned_user_ids_is_deterministic_and_validated():
    a = poisoned_user_ids(1000, 7, 0.1)
    b = poisoned_user_ids(1000, 7, 0.1)
    np.testing.assert_array_equal(a, b)
    assert len(a) == 100 and len(set(a.tolist())) == 100
    assert poisoned_user_ids(1000, 7, 0.0).size == 0
    with pytest.raises(ValueError, match="poison_frac"):
        poisoned_user_ids(10, 0, 1.5)


# ------------------------------------------------------ the golden gate

def test_defense_sim_is_bitwise_deterministic():
    a = simulate()
    b = simulate()
    assert a["lines"] == b["lines"]
    assert a["summary"]["quarantined"] == b["summary"]["quarantined"]


def test_defense_sim_matches_committed_golden():
    """The tier-1 gate: the pinned simulation's decision log must match
    the committed golden bitwise, and the pinned campaign must be fully
    contained — every attacker quarantined, no honest user touched."""
    out = simulate()
    cmp = compare_decisions(out["lines"], GOLDEN)
    assert cmp["ok"], cmp["reason"]
    s = out["summary"]
    assert s["quarantined"] == s["attackers"]
    assert s["quarantined_honest"] == []
    assert s["eval_accuracy"] >= 0.9


def test_defense_sim_compare_reports_first_divergence(tmp_path):
    path = str(tmp_path / "g.jsonl")
    write_decisions(path, ["a", "b", "c"])
    assert compare_decisions(["a", "b", "c"], path)["ok"]
    div = compare_decisions(["a", "X", "c"], path)
    assert not div["ok"] and "first divergence at line 2" in div["reason"]
    short = compare_decisions(["a"], path)
    assert not short["ok"] and "count 1 != golden 3" in short["reason"]


@pytest.mark.slow
def test_check_defense_sim_folds_golden_into_exit_code(tmp_path):
    """`fedtpu check --defense-sim` folds the pinned golden into the
    one-shot health verdict; a divergent golden fails it. Subprocess:
    check pins the platform at import time."""
    out = subprocess.run(
        [sys.executable, "-m", "fedtpu.cli", "check", "--json",
         "--defense-sim", GOLDEN],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    rep = json.loads(out.stdout)
    assert rep["defense_sim"]["ok"] is True
    assert rep["defense_sim"]["quarantined_honest"] == []
    bad = str(tmp_path / "bad.jsonl")
    write_decisions(bad, ["{}"])
    out = subprocess.run(
        [sys.executable, "-m", "fedtpu.cli", "check", "--json",
         "--defense-sim", bad],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode != 0
    rep = json.loads(out.stdout)
    assert rep["defense_sim"]["ok"] is False


@pytest.mark.slow
def test_chaos_mp_poison_campaign_row(tmp_path):
    """The acceptance drill: 2-gateway fleet, three passes (defended /
    defenses-off / clean), exact attacker-set containment, accuracy
    within tolerance of clean, zero gang restarts, and a demonstrably
    degraded undefended run."""
    from fedtpu.resilience.chaos import run_scenario
    row = run_scenario("mp_poison_campaign", str(tmp_path), {}, 0, 0,
                       "cpu", 540)
    assert row["ok"], json.dumps(row, indent=2)
    assert row["quarantined"] == row["attackers"]
    assert row["quarantined_honest"] == []
    assert row["gang_restarts"] == 0
    assert (row["accuracy_defended"]
            >= row["accuracy_clean"] - 0.01)
    assert (row["accuracy_undefended"]
            <= row["accuracy_clean"] - 0.05)
