"""fedtpu.compilation: serialized-executable cache, fingerprints, overlap.

The contract under test is the one docs/performance.md sells: a
deserialized executable IS the fresh-compiled program (bitwise, not
approximately), cache keys move with anything that changes the program
(arch, client count, dtype, chunk width) and with nothing that doesn't,
and the background-compile overlap path produces the identical history
to the eager loop. Everything runs on the conftest-pinned 8-device CPU
mesh with tiny synthetic configs.
"""

from __future__ import annotations

import contextlib
import dataclasses

import numpy as np
import pytest

import jax

from fedtpu.compilation import (CompileExecutor, ProgramCache,
                                program_config_slice, program_fingerprint,
                                warmup_preset)
from fedtpu.config import get_preset


def tiny_cfg(hidden=(8,), rounds=4, rows=256, rps=1, **run_kw):
    cfg = get_preset("income-8")
    return dataclasses.replace(
        cfg,
        data=dataclasses.replace(cfg.data, csv_path=None, dataset_name=None,
                                 synthetic_rows=rows),
        model=dataclasses.replace(cfg.model, hidden_sizes=tuple(hidden)),
        fed=dataclasses.replace(cfg.fed, rounds=rounds),
        run=dataclasses.replace(cfg.run, rounds_per_step=rps,
                                log_every=0, **run_kw),
    )


@contextlib.contextmanager
def persistent_cache(tmpdir):
    """Scope the process-global persistent-cache config to one test."""
    from fedtpu.compilation import configure_persistent_cache
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        configure_persistent_cache(str(tmpdir))
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          prev_min)


def bitwise_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))


# ------------------------------------------------------- serialize roundtrip
def test_serialize_deserialize_execute_bitwise_equal(tmp_path):
    """store -> (fresh ProgramCache) load -> execute must be bitwise equal
    to the fresh-compiled round program: the cache returns the program,
    not a reproduction of it."""
    from fedtpu.orchestration.loop import build_experiment
    from fedtpu.utils.trees import clone

    exp = build_experiment(tiny_cfg())
    step = exp.make_step(1)
    key = program_fingerprint("round", mesh=exp.mesh,
                              args=(exp.state, exp.batch))

    cache = ProgramCache(str(tmp_path))
    entry = cache.get_or_compile(key, step, exp.state, exp.batch)
    assert not entry.warm and cache.misses == 1

    warm = ProgramCache(str(tmp_path)).load(key)
    assert warm is not None and warm.warm

    out_cold = entry.compiled(clone(exp.state), exp.batch)
    out_warm = warm.compiled(clone(exp.state), exp.batch)
    jax.block_until_ready((out_cold, out_warm))
    assert bitwise_equal(out_cold, out_warm)

    # And the cache's own second lookup is a hit, not a recompile.
    again = cache.get_or_compile(key, step, exp.state, exp.batch)
    assert again.warm and cache.hits >= 1


def test_load_rejects_corrupted_payload(tmp_path):
    from fedtpu.orchestration.loop import build_experiment

    exp = build_experiment(tiny_cfg())
    key = program_fingerprint("round", mesh=exp.mesh,
                              args=(exp.state, exp.batch))
    cache = ProgramCache(str(tmp_path))
    cache.get_or_compile(key, exp.make_step(1), exp.state, exp.batch)
    bin_path, _ = cache._paths(key)
    with open(bin_path, "r+b") as fh:
        fh.seek(10)
        fh.write(b"\x00\x01\x02\x03")
    # Integrity guard: a flipped payload degrades to a miss, never a crash.
    assert ProgramCache(str(tmp_path)).load(key) is None


# ----------------------------------------------------------- key sensitivity
def test_fingerprint_moves_with_the_program():
    """Changed hidden sizes / client count / dtype must miss; the identical
    config must hit. The fingerprint needs no backend: abstract shapes via
    ShapeDtypeStruct."""
    base_cfg = program_config_slice(tiny_cfg(hidden=(8,)))
    args = (jax.ShapeDtypeStruct((4, 16), np.float32),)

    def fp(config=base_cfg, a=args, extra=None):
        return program_fingerprint("round", config=config, args=a,
                                   extra=extra)

    assert fp() == fp()                                     # deterministic
    assert fp(config=program_config_slice(tiny_cfg(hidden=(16,)))) != fp()
    wide_cfg = tiny_cfg()
    wide_cfg = dataclasses.replace(
        wide_cfg, shard=dataclasses.replace(wide_cfg.shard, num_clients=4))
    assert fp(config=program_config_slice(wide_cfg)) != fp()
    assert fp(a=(jax.ShapeDtypeStruct((4, 16), np.float16),)) != fp()
    assert fp(a=(jax.ShapeDtypeStruct((8, 16), np.float32),)) != fp()
    assert fp(extra={"rounds_per_step": 4}) != fp()
    # Telemetry knobs are excluded from the slice: pointing logs elsewhere
    # must NOT invalidate the cache.
    relogged = tiny_cfg()
    relogged = dataclasses.replace(
        relogged, run=dataclasses.replace(relogged.run, log_every=7))
    assert program_config_slice(relogged) == base_cfg


def test_fingerprint_is_stable_across_concrete_and_abstract_args():
    """warmup (concrete arrays) and the overlap loop (ShapeDtypeStructs)
    must derive the SAME key for the same program."""
    x = jax.numpy.zeros((4, 16), jax.numpy.float32)
    sds = jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    assert (program_fingerprint("round", args=(x,))
            == program_fingerprint("round", args=(sds,)))


def test_fingerprint_separates_same_extent_slices_of_one_mesh():
    """Two equal-sized slices of one parent mesh — the MPMD client slice
    vs the server slice — compile against DIFFERENT device sets and must
    never share a cache entry: the mesh signature carries the device
    assignment, not just the axis extents. Identical slices still hit."""
    from jax.sharding import Mesh
    devs = np.asarray(jax.devices())
    assert devs.size >= 8                 # conftest's 8-device CPU pin
    lo = Mesh(devs[:4], ("clients",))
    hi = Mesh(devs[4:8], ("clients",))
    again = Mesh(devs[:4], ("clients",))
    assert (program_fingerprint("round", mesh=lo)
            == program_fingerprint("round", mesh=again))
    assert (program_fingerprint("round", mesh=lo)
            != program_fingerprint("round", mesh=hi))


# ------------------------------------------------------------- the executor
def test_executor_dedupes_blocks_and_reraises():
    calls = {"n": 0}

    def build():
        calls["n"] += 1
        return "compiled"

    def boom():
        raise RuntimeError("lowering failed")

    with CompileExecutor() as ex:
        f1 = ex.submit("k1", build)
        f2 = ex.submit("k1", build)          # dedupe: same future
        assert f1 is f2
        assert ex.get("k1") == "compiled"
        assert calls["n"] == 1
        ex.submit("k2", boom)
        with pytest.raises(RuntimeError, match="lowering failed"):
            ex.get("k2", timeout=30)
        assert ex.succeeded() == ["k1"]


# ---------------------------------------------------------- overlap parity
@pytest.mark.slow
def test_overlap_loop_bitwise_identical_to_eager(tmp_path):
    """overlap_compile trains R=1 warmup rounds while the R-wide program
    compiles; final params and recorded history must be bitwise identical
    to the eager path, and the wide program must land in the cache."""
    from fedtpu.orchestration.loop import run_experiment

    eager = run_experiment(tiny_cfg(rounds=6, rps=3), verbose=False)
    overlapped = run_experiment(
        tiny_cfg(rounds=6, rps=3, overlap_compile=True,
                 compilation_cache=str(tmp_path)),
        verbose=False)
    assert eager.rounds_run == overlapped.rounds_run == 6
    assert bitwise_equal(eager.final_params, overlapped.final_params)
    assert eager.global_metrics["accuracy"] == \
        overlapped.global_metrics["accuracy"]
    cached = ProgramCache(str(tmp_path / "programs")).entries()
    assert cached, "overlap run did not persist the wide program"


# ----------------------------------------------- warm start / zero recompile
@pytest.mark.slow
def test_second_build_through_program_cache_zero_backend_compiles(tmp_path):
    """A SECOND in-process build of the same round program through the
    ProgramCache must report zero backend_compile events under the armed
    RecompileSentinel: the warm path deserializes the executable, it never
    re-enters XLA. (The raw jax persistent cache can't make this promise —
    0.4.x emits backend_compile_duration even on its disk hits.)"""
    from fedtpu.analysis.guards import RecompileSentinel
    from fedtpu.orchestration.loop import build_experiment
    from fedtpu.utils.trees import clone

    cfg = tiny_cfg(hidden=(9,))              # shape unique to this test
    exp = build_experiment(cfg)
    key = program_fingerprint("round", config=program_config_slice(cfg),
                              mesh=exp.mesh, args=(exp.state, exp.batch))
    cold = ProgramCache(str(tmp_path)).get_or_compile(
        key, exp.make_step(1), exp.state, exp.batch)   # pays the compile
    assert not cold.warm
    jax.block_until_ready(clone(exp.state))   # pre-pay clone's own compile

    sentinel = RecompileSentinel(label="warm_cache_smoke")
    with sentinel.armed():
        warm = ProgramCache(str(tmp_path)).get_or_compile(
            key, exp.make_step(1), exp.state, exp.batch)
        _, m = warm.compiled(clone(exp.state), exp.batch)
        jax.block_until_ready(m)
    assert warm.warm
    assert sentinel.available
    assert sentinel.count == 0, (
        f"{sentinel.count} backend compiles despite a warm program cache")


@pytest.mark.slow
def test_warmup_preset_then_check_start_warm(tmp_path):
    """fedtpu warmup twice over the same dir: the second pass must be all
    hits; run_check --warmup-cache over that dir stays retrace-free."""
    from fedtpu.analysis.check import run_check

    with persistent_cache(tmp_path):
        cold = warmup_preset(preset="income-8", cache_dir=str(tmp_path),
                             synthetic_rows=256)
        assert cold["misses"] == len(cold["programs"]) > 0
        warm = warmup_preset(preset="income-8", cache_dir=str(tmp_path),
                             synthetic_rows=256)
        assert warm["hits"] == len(warm["programs"])
        assert all(p["warm"] for p in warm["programs"])

        report = run_check(rounds=2, synthetic_rows=256,
                           warmup_cache=str(tmp_path))
        assert report["ok"] and report["warmup_cache"] == str(tmp_path)
