"""Asynchronous (FedBuff-style) engine — fedtpu.parallel.async_fed.

The load-bearing pin is the degenerate-case contract: arrival_rate=1 +
staleness_power=0 + server_lr=1 must reproduce the SYNCHRONOUS uniform
delta path exactly — same local training, same mean, same global. The
async machinery (anchors, pull ticks, discounting) then only has to be
right about what it ADDS, which the staleness and discount pins cover.
"""

import jax
import numpy as np
import pytest

from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.ops.server_opt import identity_server_optimizer
from fedtpu.parallel import async_fed, client_sharding, make_mesh
from fedtpu.parallel.round import (build_round_fn, global_params,
                                   init_federated_state)

C = 8


def _fixtures(hidden=(16, 8)):
    x, y = synthetic_income_like(256, 6, 2, seed=0)
    packed = pack_clients(x, y, ShardConfig(num_clients=C, shuffle=False))
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=hidden))
    tx = build_optimizer(OptimConfig())
    mesh = make_mesh(num_clients=C)
    batch = {k: jax.device_put(v, client_sharding(mesh)) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    return mesh, init_fn, apply_fn, tx, batch


def test_rate1_no_discount_equals_synchronous_delta_path():
    mesh, init_fn, apply_fn, tx, batch = _fixtures()
    # Async, everyone arrives every tick, no discounting.
    # same_init=False on BOTH sides: the starting global is the uniform
    # mean of per-client inits, exactly the sync delta path's shared start.
    a_state = async_fed.init_async_state(jax.random.key(0), mesh, C,
                                         init_fn, tx, same_init=False)
    a_step = async_fed.build_async_round_fn(
        mesh, apply_fn, tx, 2, arrival_rate=1.0, staleness_power=0.0,
        server_lr=1.0, ticks_per_step=7)
    a_state, a_metrics = a_step(a_state, batch)
    assert np.all(np.asarray(a_metrics["staleness"]) == 0.0)

    # Synchronous uniform delta path from the same init.
    server = identity_server_optimizer()
    s_state = init_federated_state(jax.random.key(0), mesh, C, init_fn, tx,
                                   same_init=False, server_opt=server)
    s_step = build_round_fn(mesh, apply_fn, tx, 2, weighting="uniform",
                            server_opt=server, rounds_per_step=7)
    s_state, _ = s_step(s_state, batch)

    a_g = jax.tree.map(np.asarray, async_fed.async_global_params(a_state))
    s_g = jax.tree.map(np.asarray, global_params(s_state))
    for a, b in zip(jax.tree.leaves(a_g), jax.tree.leaves(s_g)):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_staleness_bookkeeping_under_sampling():
    mesh, init_fn, apply_fn, tx, batch = _fixtures()
    state = async_fed.init_async_state(jax.random.key(0), mesh, C,
                                       init_fn, tx)
    step = async_fed.build_async_round_fn(
        mesh, apply_fn, tx, 2, arrival_rate=0.4, arrival_seed=1,
        ticks_per_step=10)
    state, metrics = step(state, batch)
    stale = np.asarray(metrics["staleness"])          # (10, C)
    assert stale.shape == (10, C)
    assert (stale >= 0).all()
    # Sparse arrivals must produce genuinely stale updates somewhere.
    assert stale.max() >= 2, stale
    # Every pull tick is in the past (<= total ticks run).
    pulls = np.asarray(state["pull_tick"])
    assert (pulls <= 10).all() and (pulls >= 0).all()
    # At least one client arrived (pulled after tick 0).
    assert pulls.max() > 0


def test_staleness_discount_changes_the_global():
    mesh, init_fn, apply_fn, tx, batch = _fixtures()
    outs = {}
    for p in (0.0, 0.5):
        state = async_fed.init_async_state(jax.random.key(0), mesh, C,
                                           init_fn, tx)
        step = async_fed.build_async_round_fn(
            mesh, apply_fn, tx, 2, arrival_rate=0.4, arrival_seed=1,
            staleness_power=p, ticks_per_step=10)
        state, _ = step(state, batch)
        outs[p] = jax.tree.map(np.asarray,
                               async_fed.async_global_params(state))
    moved = max(float(np.abs(a - b).max())
                for a, b in zip(jax.tree.leaves(outs[0.0]),
                                jax.tree.leaves(outs[0.5])))
    assert moved > 1e-6   # discounting is live exactly when staleness > 0


def test_async_training_converges():
    mesh, init_fn, apply_fn, tx, batch = _fixtures()
    state = async_fed.init_async_state(jax.random.key(0), mesh, C,
                                       init_fn, tx)
    step = async_fed.build_async_round_fn(
        mesh, apply_fn, tx, 2, arrival_rate=0.5, ticks_per_step=20)
    acc = 0.0
    for _ in range(5):                                 # 100 ticks
        state, metrics = step(state, batch)
        acc = float(np.asarray(metrics["client_mean"]["accuracy"])[-1])
    assert acc > 0.9, acc


def test_guards():
    mesh, init_fn, apply_fn, tx, _ = _fixtures()
    with pytest.raises(ValueError, match="arrival_rate"):
        async_fed.build_async_round_fn(mesh, apply_fn, tx, 2,
                                       arrival_rate=0.0)
    with pytest.raises(ValueError, match="staleness_power"):
        async_fed.build_async_round_fn(mesh, apply_fn, tx, 2,
                                       staleness_power=-1.0)
    with pytest.raises(ValueError, match="server_lr"):
        async_fed.build_async_round_fn(mesh, apply_fn, tx, 2, server_lr=0.0)
