"""Asynchronous (FedBuff-style) engine — fedtpu.parallel.async_fed.

The load-bearing pin is the degenerate-case contract: arrival_rate=1 +
staleness_power=0 + server_lr=1 must reproduce the SYNCHRONOUS uniform
delta path exactly — same local training, same mean, same global. The
async machinery (anchors, pull ticks, discounting) then only has to be
right about what it ADDS, which the staleness and discount pins cover.
"""

import jax
import numpy as np
import pytest

from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.ops.server_opt import identity_server_optimizer
from fedtpu.parallel import async_fed, client_sharding, make_mesh
from fedtpu.parallel.round import (build_round_fn, global_params,
                                   init_federated_state)

C = 8


def _fixtures(hidden=(16, 8)):
    x, y = synthetic_income_like(256, 6, 2, seed=0)
    packed = pack_clients(x, y, ShardConfig(num_clients=C, shuffle=False))
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=hidden))
    tx = build_optimizer(OptimConfig())
    mesh = make_mesh(num_clients=C)
    batch = {k: jax.device_put(v, client_sharding(mesh)) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    return mesh, init_fn, apply_fn, tx, batch


def test_rate1_no_discount_equals_synchronous_delta_path():
    mesh, init_fn, apply_fn, tx, batch = _fixtures()
    # Async, everyone arrives every tick, no discounting.
    # same_init=False on BOTH sides: the starting global is the uniform
    # mean of per-client inits, exactly the sync delta path's shared start.
    a_state = async_fed.init_async_state(jax.random.key(0), mesh, C,
                                         init_fn, tx, same_init=False)
    a_step = async_fed.build_async_round_fn(
        mesh, apply_fn, tx, 2, arrival_rate=1.0, staleness_power=0.0,
        server_lr=1.0, ticks_per_step=7)
    a_state, a_metrics = a_step(a_state, batch)
    assert np.all(np.asarray(a_metrics["staleness"]) == 0.0)

    # Synchronous uniform delta path from the same init.
    server = identity_server_optimizer()
    s_state = init_federated_state(jax.random.key(0), mesh, C, init_fn, tx,
                                   same_init=False, server_opt=server)
    s_step = build_round_fn(mesh, apply_fn, tx, 2, weighting="uniform",
                            server_opt=server, rounds_per_step=7)
    s_state, _ = s_step(s_state, batch)

    a_g = jax.tree.map(np.asarray, async_fed.async_global_params(a_state))
    s_g = jax.tree.map(np.asarray, global_params(s_state))
    for a, b in zip(jax.tree.leaves(a_g), jax.tree.leaves(s_g)):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_staleness_bookkeeping_under_sampling():
    mesh, init_fn, apply_fn, tx, batch = _fixtures()
    state = async_fed.init_async_state(jax.random.key(0), mesh, C,
                                       init_fn, tx)
    step = async_fed.build_async_round_fn(
        mesh, apply_fn, tx, 2, arrival_rate=0.4, arrival_seed=1,
        ticks_per_step=10)
    state, metrics = step(state, batch)
    stale = np.asarray(metrics["staleness"])          # (10, C)
    assert stale.shape == (10, C)
    assert (stale >= 0).all()
    # Sparse arrivals must produce genuinely stale updates somewhere.
    assert stale.max() >= 2, stale
    # Every pull tick is in the past (<= total ticks run).
    pulls = np.asarray(state["pull_tick"])
    assert (pulls <= 10).all() and (pulls >= 0).all()
    # At least one client arrived (pulled after tick 0).
    assert pulls.max() > 0


def test_staleness_discount_changes_the_global():
    mesh, init_fn, apply_fn, tx, batch = _fixtures()
    outs = {}
    for p in (0.0, 0.5):
        state = async_fed.init_async_state(jax.random.key(0), mesh, C,
                                           init_fn, tx)
        step = async_fed.build_async_round_fn(
            mesh, apply_fn, tx, 2, arrival_rate=0.4, arrival_seed=1,
            staleness_power=p, ticks_per_step=10)
        state, _ = step(state, batch)
        outs[p] = jax.tree.map(np.asarray,
                               async_fed.async_global_params(state))
    moved = max(float(np.abs(a - b).max())
                for a, b in zip(jax.tree.leaves(outs[0.0]),
                                jax.tree.leaves(outs[0.5])))
    assert moved > 1e-6   # discounting is live exactly when staleness > 0


def test_async_training_converges():
    mesh, init_fn, apply_fn, tx, batch = _fixtures()
    state = async_fed.init_async_state(jax.random.key(0), mesh, C,
                                       init_fn, tx)
    step = async_fed.build_async_round_fn(
        mesh, apply_fn, tx, 2, arrival_rate=0.5, ticks_per_step=20)
    acc = 0.0
    for _ in range(5):                                 # 100 ticks
        state, metrics = step(state, batch)
        acc = float(np.asarray(metrics["client_mean"]["accuracy"])[-1])
    assert acc > 0.9, acc


def test_guards():
    mesh, init_fn, apply_fn, tx, _ = _fixtures()
    with pytest.raises(ValueError, match="arrival_rate"):
        async_fed.build_async_round_fn(mesh, apply_fn, tx, 2,
                                       arrival_rate=0.0)
    with pytest.raises(ValueError, match="staleness_power"):
        async_fed.build_async_round_fn(mesh, apply_fn, tx, 2,
                                       staleness_power=-1.0)
    with pytest.raises(ValueError, match="server_lr"):
        async_fed.build_async_round_fn(mesh, apply_fn, tx, 2, server_lr=0.0)


# ---------------------------------------------------------------- product
# Round-5 productization (VERDICT r4 next #1): the async engine as a
# first-class run_experiment / CLI / checkpoint citizen.

import dataclasses

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           RunConfig)
from fedtpu.orchestration.loop import build_experiment, run_experiment


def _async_cfg(rounds=10, **fed_kw):
    return ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=512),
        shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=rounds, weighting="uniform", async_mode=True,
                      async_arrival_rate=fed_kw.pop("arrival", 0.4),
                      termination_patience=fed_kw.pop("patience", 1000),
                      **fed_kw),
        run=RunConfig(log_every=1000),
    )


def test_run_experiment_async_end_to_end():
    cfg = dataclasses.replace(_async_cfg(rounds=30),
                              run=RunConfig(eval_test_every=10,
                                            log_every=1000))
    res = run_experiment(cfg, verbose=False)
    assert res.rounds_run == 30
    for k in ("accuracy", "precision", "recall", "f1"):
        assert len(res.global_metrics[k]) == 30
        assert len(res.test_metrics[k]) == 3
    # Staleness is recorded per tick, one (C,) vector each, and genuinely
    # nonzero under sparse arrivals.
    assert len(res.staleness) == 30
    assert res.staleness[0].shape == (8,)
    assert max(s.max() for s in res.staleness) >= 2
    s = res.summary()
    assert s["mean_staleness"] > 0 and s["max_staleness"] >= 2
    # The async run actually trains.
    assert res.global_metrics["accuracy"][-1] > 0.9


def test_async_early_stop_on_tick_metrics():
    # lr=0 + same_init freezes the global: tick metrics plateau from tick
    # 1, so patience 3 stops at tick 4 exactly like the sync loop.
    from fedtpu.config import OptimConfig
    cfg = dataclasses.replace(_async_cfg(rounds=50, patience=3,
                                         same_init=True),
                              optim=OptimConfig(learning_rate=0.0))
    res = run_experiment(cfg, verbose=False)
    assert res.stopped_early
    assert res.rounds_run == 4


def test_async_checkpoint_resume_bitwise(tmp_path):
    """save -> restore -> tick == uninterrupted ticking: the arrival draws
    are deterministic in (seed, tick), and anchors/pull_tick round-trip
    through the checkpoint."""
    def cfg(rounds, d):
        return dataclasses.replace(
            _async_cfg(rounds=rounds),
            run=RunConfig(checkpoint_dir=str(d), checkpoint_every=3,
                          log_every=1000))
    r_full = run_experiment(cfg(6, tmp_path / "a"), verbose=False)
    run_experiment(cfg(3, tmp_path / "b"), verbose=False)
    r_res = run_experiment(cfg(6, tmp_path / "b"), verbose=False,
                           resume=True)
    assert len(r_res.global_metrics["accuracy"]) == 6
    for a, b in zip(jax.tree.leaves(r_full.final_params),
                    jax.tree.leaves(r_res.final_params)):
        np.testing.assert_array_equal(a, b)


def test_async_chunked_ticks_bitwise():
    """ticks_per_step (RunConfig.rounds_per_step) scans ticks in-graph;
    the trajectory must be bit-identical to tick-at-a-time."""
    r1 = run_experiment(_async_cfg(rounds=6), verbose=False)
    r3 = run_experiment(
        dataclasses.replace(_async_cfg(rounds=6),
                            run=RunConfig(rounds_per_step=3,
                                          log_every=1000)),
        verbose=False)
    for a, b in zip(jax.tree.leaves(r1.final_params),
                    jax.tree.leaves(r3.final_params)):
        np.testing.assert_array_equal(a, b)


def test_async_elastic_resume_carries_the_freshest_anchor(tmp_path):
    """Async elastic resume (round 5): a restart IS every client
    re-pulling the freshest anchor. Pin: with lr=0 the global can never
    move, so the elastic leg's final global must equal the first leg's
    EXACTLY — any mean-over-slots collapse (the sync rule) would mix
    distinct local models and break this."""
    from fedtpu.config import OptimConfig
    def cfg(rounds, clients):
        return dataclasses.replace(
            _async_cfg(rounds=rounds),
            shard=ShardConfig(num_clients=clients),
            optim=OptimConfig(learning_rate=0.0),
            run=RunConfig(checkpoint_dir=str(tmp_path), checkpoint_every=4,
                          log_every=1000))
    first = run_experiment(cfg(4, 8), verbose=False)
    grown = run_experiment(cfg(8, 4), verbose=False, resume=True)
    assert grown.rounds_run == 8
    assert len(grown.global_metrics["accuracy"]) == 8   # history carried
    for a, b in zip(jax.tree.leaves(first.final_params),
                    jax.tree.leaves(grown.final_params)):
        np.testing.assert_array_equal(a, b)
    # Staleness restarted at the resume tick: everyone re-pulled, so no
    # age can exceed the 4 post-resume ticks.
    assert max(s.max() for s in grown.staleness) <= 4


def test_async_elastic_resume_drops_pending_buffer_loudly(tmp_path, capsys):
    def cfg(rounds, clients):
        base = _async_cfg(rounds=rounds, arrival=1.0)
        return dataclasses.replace(
            base,
            shard=ShardConfig(num_clients=clients),
            fed=dataclasses.replace(base.fed, async_buffer_size=10 ** 6),
            run=RunConfig(checkpoint_dir=str(tmp_path), checkpoint_every=4,
                          log_every=1000))
    run_experiment(cfg(4, 8), verbose=False)        # 32 updates pending
    run_experiment(cfg(8, 4), verbose=True, resume=True)
    out = capsys.readouterr().out
    assert "Async elastic resume at tick 4: 8 -> 4 clients" in out
    assert "32 pending buffered updates dropped" in out


@pytest.mark.parametrize("fed_kw,match", [
    (dict(weighting="data_size"), "uniform"),
    (dict(participation_rate=0.5), "arrival"),
    (dict(server_opt="fedadam"), "server update"),
    (dict(dp_clip_norm=1.0), "DP"),
    (dict(robust_aggregation="median"), "robust"),
    (dict(compress="int8"), "compress"),
    (dict(scaffold=True), "SCAFFOLD"),
    (dict(aggregation="ring"), "psum"),
])
def test_async_incompatible_knobs_rejected(fed_kw, match):
    fed_kw.setdefault("weighting", "uniform")
    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256),
        fed=FedConfig(async_mode=True, **fed_kw))
    with pytest.raises(ValueError, match=match):
        build_experiment(cfg)


def test_async_model_parallel_rejected():
    cfg = dataclasses.replace(
        _async_cfg(), run=RunConfig(model_parallel=2))
    with pytest.raises(ValueError, match="1-D engine"):
        build_experiment(cfg)


def test_cli_async_flags_map_to_config():
    from fedtpu.cli import build_parser, _apply_overrides
    from fedtpu.config import get_preset
    args = build_parser().parse_args(
        ["run", "--async", "--arrival-rate", "0.25", "--arrival-seed", "7",
         "--staleness-power", "0", "--server-lr", "0.5",
         "--weighting", "uniform"])
    cfg = _apply_overrides(get_preset(args.preset), args)
    assert cfg.fed.async_mode
    assert cfg.fed.async_arrival_rate == 0.25
    assert cfg.fed.async_arrival_seed == 7
    assert cfg.fed.async_staleness_power == 0.0
    assert cfg.fed.server_lr == 0.5
    assert cfg.fed.weighting == "uniform"
    # Default run (no --async) must not flip the mode.
    args = build_parser().parse_args(["run"])
    assert not _apply_overrides(get_preset(args.preset), args).fed.async_mode


def test_single_device_mesh_cb_gt_1():
    """All clients on ONE device (the real-TPU one-chip shape, cb=8).
    Found on first chip contact: device_put of an already-placed array is
    a no-op there, so params/anchors initialized from the same tree
    aliased the same buffers and the donating tick crashed with 'donate
    the same buffer twice'."""
    mesh, init_fn, apply_fn, tx, _ = _fixtures()
    mesh1 = make_mesh(1, C)                     # 1 device, 8 client slots
    assert mesh1.devices.size == 1
    x, y = synthetic_income_like(256, 6, 2, seed=0)
    packed = pack_clients(x, y, ShardConfig(num_clients=C, shuffle=False))
    batch = {k: jax.device_put(v, client_sharding(mesh1)) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    state = async_fed.init_async_state(jax.random.key(0), mesh1, C,
                                       init_fn, tx)
    step = async_fed.build_async_round_fn(mesh1, apply_fn, tx, 2,
                                          arrival_rate=0.5,
                                          ticks_per_step=5)
    for _ in range(2):                          # second call donates too
        state, metrics = step(state, batch)
    acc = np.asarray(metrics["client_mean"]["accuracy"])
    assert np.isfinite(acc).all()


def test_async_checkpoint_resumed_under_sync_config_not_collapsed(tmp_path):
    """Review r5: an async-written checkpoint resumed under a SYNC config
    with a different client count must not silently mean-collapse the
    per-client local models (the guard must look at the checkpoint, not
    only the live template)."""
    cfg = dataclasses.replace(
        _async_cfg(rounds=3),
        run=RunConfig(checkpoint_dir=str(tmp_path), checkpoint_every=3,
                      log_every=1000))
    run_experiment(cfg, verbose=False)
    sync_grown = dataclasses.replace(
        cfg, shard=ShardConfig(num_clients=4),
        fed=dataclasses.replace(cfg.fed, async_mode=False, rounds=6))
    with pytest.raises(ValueError, match="engine mismatch"):
        run_experiment(sync_grown, verbose=False, resume=True)


def test_cli_async_knobs_without_async_rejected():
    from fedtpu.cli import build_parser, _apply_overrides
    from fedtpu.config import get_preset
    args = build_parser().parse_args(["run", "--arrival-rate", "0.25"])
    with pytest.raises(SystemExit, match="require --async"):
        _apply_overrides(get_preset(args.preset), args)


# ------------------------------------------------------------ FedBuff buffer
def test_buffer_size_one_is_bitwise_the_per_tick_apply():
    """M<=1 degenerate contract: the buffered program with an always-
    resetting buffer computes the identical float sequence as the default
    per-arrival-tick apply."""
    mesh, init_fn, apply_fn, tx, batch = _fixtures()
    outs = {}
    for m in (0, 1):
        state = async_fed.init_async_state(jax.random.key(0), mesh, C,
                                           init_fn, tx, buffer_size=m)
        step = async_fed.build_async_round_fn(
            mesh, apply_fn, tx, 2, arrival_rate=0.4, arrival_seed=1,
            buffer_size=m, ticks_per_step=10)
        state, _ = step(state, batch)
        outs[m] = jax.tree.map(np.asarray,
                               async_fed.async_global_params(state))
    for a, b in zip(jax.tree.leaves(outs[0]), jax.tree.leaves(outs[1])):
        np.testing.assert_array_equal(a, b)


def test_buffered_apply_waits_for_m_updates():
    """True FedBuff semantics: with arrival_rate=1 and C=8 clients, every
    tick contributes 8 updates, so M=16 applies exactly every 2nd tick —
    the global is UNCHANGED after tick 1 and moves after tick 2."""
    mesh, init_fn, apply_fn, tx, batch = _fixtures()

    def global_after(ticks):
        state = async_fed.init_async_state(jax.random.key(0), mesh, C,
                                           init_fn, tx, buffer_size=16)
        step = async_fed.build_async_round_fn(
            mesh, apply_fn, tx, 2, arrival_rate=1.0, staleness_power=0.0,
            buffer_size=16, ticks_per_step=1)
        counts = []
        for _ in range(ticks):
            state, _ = step(state, batch)
            counts.append(float(np.asarray(state["buf_count"])))
        return (jax.tree.map(np.asarray,
                             async_fed.async_global_params(state)), counts)

    g0 = jax.tree.map(
        np.asarray,
        async_fed.async_global_params(async_fed.init_async_state(
            jax.random.key(0), mesh, C, init_fn, tx, buffer_size=16)))
    g1, c1 = global_after(1)
    g2, c2 = global_after(2)
    # Tick 1: 8 < 16 buffered — global untouched, buffer half full.
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_array_equal(a, b)
    assert c1 == [8.0]
    # Tick 2: 16 >= 16 — apply fires, buffer resets.
    moved = max(float(np.abs(a - b).max())
                for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)))
    assert moved > 1e-6
    assert c2 == [8.0, 0.0]
    # And the M=16 trajectory over 2 ticks equals ONE synchronous apply
    # of all 16 accumulated (2-tick) updates — which differs from the
    # M=0 per-tick trajectory (two sequential applies).
    state0 = async_fed.init_async_state(jax.random.key(0), mesh, C,
                                        init_fn, tx)
    step0 = async_fed.build_async_round_fn(
        mesh, apply_fn, tx, 2, arrival_rate=1.0, staleness_power=0.0,
        ticks_per_step=2)
    state0, _ = step0(state0, batch)
    g_seq = jax.tree.map(np.asarray, async_fed.async_global_params(state0))
    assert max(float(np.abs(a - b).max())
               for a, b in zip(jax.tree.leaves(g2),
                               jax.tree.leaves(g_seq))) > 1e-6


def test_buffered_state_checkpoints_and_resumes_bitwise(tmp_path):
    """The server buffer is run state: save mid-buffer -> restore -> tick
    must be bitwise identical to uninterrupted ticking (a dropped buffer
    would silently lose the pending contributions)."""
    def cfg(rounds, d):
        base = _async_cfg(rounds=rounds, arrival=0.3)
        return dataclasses.replace(
            base,
            fed=dataclasses.replace(base.fed, async_buffer_size=6),
            run=RunConfig(checkpoint_dir=str(d), checkpoint_every=3,
                          log_every=1000))
    r_full = run_experiment(cfg(9, tmp_path / "a"), verbose=False)
    run_experiment(cfg(3, tmp_path / "b"), verbose=False)
    r_res = run_experiment(cfg(9, tmp_path / "b"), verbose=False,
                           resume=True)
    for a, b in zip(jax.tree.leaves(r_full.final_params),
                    jax.tree.leaves(r_res.final_params)):
        np.testing.assert_array_equal(a, b)


def test_async_buffer_starvation_warns_loudly(tmp_path, capsys):
    """K-buffer starvation guard (VERDICT item 7): --buffer-size large
    relative to total arrivals means the buffer NEVER fills, so the
    global silently never advances. The run must stay sound (all rounds
    recorded) but end with a loud CLI warning + an ``async_starvation``
    event carrying the pending count."""
    import json

    from fedtpu.config import TelemetryConfig
    ev = str(tmp_path / "ev.jsonl")
    base = _async_cfg(rounds=4, arrival=1.0)
    cfg = dataclasses.replace(
        base,
        fed=dataclasses.replace(base.fed, async_buffer_size=10 ** 6),
        run=RunConfig(log_every=1000,
                      telemetry=TelemetryConfig(events_path=ev)))
    res = run_experiment(cfg, verbose=True)
    out = capsys.readouterr().out
    assert "ASYNC K-BUFFER STARVATION" in out
    assert "32 buffered update(s)" in out        # 4 ticks x 8 clients
    assert res.rounds_run == 4
    assert len(res.global_metrics["accuracy"]) == 4   # metrics still sound
    sv = [json.loads(l) for l in open(ev)
          if json.loads(l)["kind"] == "async_starvation"]
    assert sv and sv[0]["payload"]["pending"] == 32
    assert sv[0]["payload"]["buffer_size"] == 10 ** 6

    # Control: a buffer that drains every tick (M == arrivals per tick)
    # must not warn — the guard is about NEVER-applied contributions.
    cfg2 = dataclasses.replace(
        base, fed=dataclasses.replace(base.fed, async_buffer_size=8),
        run=RunConfig(log_every=1000))
    run_experiment(cfg2, verbose=True)
    assert "STARVATION" not in capsys.readouterr().out


def test_buffered_step_requires_buffered_state():
    mesh, init_fn, apply_fn, tx, batch = _fixtures()
    state = async_fed.init_async_state(jax.random.key(0), mesh, C,
                                       init_fn, tx)          # no buffer keys
    step = async_fed.build_async_round_fn(mesh, apply_fn, tx, 2,
                                          buffer_size=4)
    with pytest.raises(ValueError, match="buffer_size"):
        step(state, batch)


def test_sync_checkpoint_under_async_config_rejected(tmp_path):
    """Reverse engine mismatch: a sync-written checkpoint elastically
    resumed under --async has no pull/anchor history to restore."""
    from fedtpu.config import OptimConfig
    sync_cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=512),
        shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=3, termination_patience=1000),
        run=RunConfig(checkpoint_dir=str(tmp_path), checkpoint_every=3,
                      log_every=1000))
    run_experiment(sync_cfg, verbose=False)
    async_grown = dataclasses.replace(
        sync_cfg, shard=ShardConfig(num_clients=4),
        fed=FedConfig(rounds=6, weighting="uniform", async_mode=True,
                      termination_patience=1000))
    with pytest.raises(ValueError, match="engine mismatch"):
        run_experiment(async_grown, verbose=False, resume=True)
