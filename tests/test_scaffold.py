"""SCAFFOLD control-variate drift correction (fedtpu.parallel.round,
Karimireddy et al. 2020, option-I variates).

The pins, in order of how much they constrain the implementation:

1. EXACT: at local_steps=1 with plain SGD the aggregated global trajectory
   equals FedAvg's — per-client corrections (c - c_i) cancel in the client
   mean because c == mean(c_i). Any sign/placement error breaks this.
2. EXACT: with identical shards + same_init the corrections are
   identically zero and scaffold == the plain delta path, any optimizer.
3. INVARIANT: server_cv == mean(client_cv) after every round (the paper's
   c = mean(c_i) under full participation, from the zero init).
4. BENEFIT (falsifiable): single-class clients + many local steps —
   maximal heterogeneity — where FedAvg stalls at a drift-biased point;
   scaffold settles 1.40x closer to global stationarity (measured by
   |grad F| at the final global). Deterministic seeds; no flake surface.
"""

import dataclasses

import jax
import numpy as np
import pytest

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           ModelConfig, OptimConfig, RunConfig, ShardConfig)
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.ops.server_opt import identity_server_optimizer
from fedtpu.orchestration.loop import build_experiment, run_experiment
from fedtpu.parallel import make_mesh, client_sharding
from fedtpu.parallel.round import (build_round_fn, global_params,
                                   init_federated_state)


def _setup(scaffold, optim=None, hidden=(16, 8), num_clients=8, seed=1,
           label_sort=True, rows=512, features=8, identical_shards=False):
    x, y = synthetic_income_like(rows, features, 2, seed=seed)
    if label_sort:
        order = np.argsort(y, kind="stable")
        x, y = x[order], y[order]
    if identical_shards:
        n = rows // num_clients
        x = np.tile(x[:n], (num_clients, 1)).reshape(num_clients, n, features)
        y = np.tile(y[:n], num_clients).reshape(num_clients, n)
        batch_np = {"x": x, "y": y,
                    "mask": np.ones((num_clients, n), np.float32)}
    else:
        packed = pack_clients(x, y, ShardConfig(num_clients=num_clients,
                                                shuffle=False))
        batch_np = {"x": packed.x, "y": packed.y, "mask": packed.mask}
    init_fn, apply_fn = build_model(ModelConfig(input_dim=features,
                                                hidden_sizes=hidden))
    tx = build_optimizer(optim or OptimConfig(name="sgd", learning_rate=0.05,
                                              momentum=0.0))
    mesh = make_mesh(num_clients=num_clients)
    server = identity_server_optimizer()
    state = init_federated_state(jax.random.key(0), mesh, num_clients,
                                 init_fn, tx, same_init=True,
                                 server_opt=server, scaffold=scaffold)
    batch = {k: jax.device_put(v, client_sharding(mesh))
             for k, v in batch_np.items()}
    return mesh, apply_fn, tx, server, state, batch


def _global(mesh, apply_fn, tx, server, state, batch, scaffold, **kw):
    step = build_round_fn(mesh, apply_fn, tx, 2, weighting="uniform",
                          server_opt=server, scaffold=scaffold, **kw)
    state, _ = step(state, batch)
    return state


@pytest.mark.parametrize("rounds", [10])
def test_e1_sgd_global_trajectory_equals_fedavg(rounds):
    """Pin 1: E=1 + SGD -> corrections cancel in the client mean; the
    GLOBAL model is bit-near FedAvg's even though per-client locals differ."""
    outs = {}
    for scaf in (False, True):
        args = _setup(scaf)
        state = _global(*args, scaffold=scaf, local_steps=1,
                        rounds_per_step=rounds)
        outs[scaf] = jax.tree.map(np.asarray, global_params(state))
    for a, b in zip(jax.tree.leaves(outs[False]), jax.tree.leaves(outs[True])):
        np.testing.assert_allclose(a, b, atol=5e-7)


def test_identical_shards_corrections_vanish_any_optimizer():
    """Pin 2: identical shards + same_init -> c_i == c always, corrections
    exactly zero -> scaffold == plain delta path under Adam too."""
    outs = {}
    for scaf in (False, True):
        args = _setup(scaf, optim=OptimConfig(), identical_shards=True,
                      label_sort=False)
        state = _global(*args, scaffold=scaf, local_steps=4,
                        rounds_per_step=5)
        outs[scaf] = jax.tree.map(np.asarray, global_params(state))
    for a, b in zip(jax.tree.leaves(outs[False]), jax.tree.leaves(outs[True])):
        np.testing.assert_allclose(a, b, atol=1e-7)


def test_server_cv_is_mean_of_client_cv():
    """Pin 3: the paper's invariant c == mean_i(c_i), inductive from the
    zero init under full participation."""
    args = _setup(True)
    state = _global(*args, scaffold=True, local_steps=4, rounds_per_step=7)
    mean_ccv = jax.tree.map(lambda c: np.asarray(c).mean(axis=0),
                            state["client_cv"])
    for a, b in zip(jax.tree.leaves(mean_ccv),
                    jax.tree.leaves(jax.tree.map(np.asarray,
                                                 state["server_cv"]))):
        np.testing.assert_allclose(a, b, atol=1e-6)
    # And the variates are alive, not zeros (they carry real gradients).
    assert max(float(np.abs(np.asarray(l)).max())
               for l in jax.tree.leaves(state["client_cv"])) > 1e-4


def test_scaffold_lowers_the_drift_floor():
    """Pin 4 (falsifiable benefit): single-class clients (4-class task,
    label-sorted over 8 clients) + E=32 local steps is maximal
    heterogeneity; plain FedAvg stalls where the drift bias balances the
    descent — a point with |grad F| bounded away from stationarity — while
    SCAFFOLD's corrected dynamics settle measurably closer to a stationary
    point of the GLOBAL objective. Measured (identical on CPU and v5e,
    stable from round 50 through 300): |grad F| 3.50e-1 vs 2.49e-1 —
    a 1.40x lower floor. Assert 1.15x so only a real regression trips.

    (Accuracy is the wrong observable here: a binary linear model's argmax
    is scale-invariant, and symmetric label-skew drift mostly inflates
    scale — the runs that 'showed' accuracy gains in development were a
    protocol bug, evaluating on a differently-seeded synthetic task.)"""
    rng = np.random.default_rng(2)
    centers = rng.normal(0.0, 0.8, size=(4, 8))
    y = np.arange(512) % 4
    rng.shuffle(y)
    x = (centers[y] + rng.normal(0.0, 1.0, size=(512, 8))).astype(np.float32)
    order = np.argsort(y, kind="stable")
    packed = pack_clients(x[order], y[order].astype(np.int32),
                          ShardConfig(num_clients=8, shuffle=False))
    init_fn, apply_fn = build_model(ModelConfig(input_dim=8, hidden_sizes=(),
                                                num_classes=4))
    from fedtpu.ops.losses import masked_cross_entropy
    import jax.numpy as jnp
    gfn = jax.jit(jax.grad(lambda p: masked_cross_entropy(
        apply_fn(p, packed.x.reshape(-1, 8)), packed.y.reshape(-1),
        packed.mask.reshape(-1))))
    floors = {}
    for scaf in (False, True):
        tx = build_optimizer(OptimConfig(name="sgd", learning_rate=0.05,
                                         momentum=0.0))
        mesh = make_mesh(num_clients=8)
        server = identity_server_optimizer()
        state = init_federated_state(jax.random.key(0), mesh, 8, init_fn, tx,
                                     same_init=True, server_opt=server,
                                     scaffold=scaf)
        batch = {k: jax.device_put(v, client_sharding(mesh)) for k, v in
                 {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
        step = build_round_fn(mesh, apply_fn, tx, 4, weighting="uniform",
                              server_opt=server, scaffold=scaf,
                              local_steps=32, rounds_per_step=100)
        state, _ = step(state, batch)
        g = global_params(state)
        floors[scaf] = float(jnp.sqrt(sum(
            jnp.sum(jnp.square(l)) for l in jax.tree.leaves(gfn(g)))))
    assert floors[True] * 1.15 < floors[False], floors


def test_incompatible_combos_raise():
    mesh, apply_fn, tx, server, _, _ = _setup(True)
    base = dict(weighting="uniform", server_opt=server, scaffold=True)
    with pytest.raises(ValueError, match="uniform"):
        build_round_fn(mesh, apply_fn, tx, 2, server_opt=server,
                       scaffold=True, weighting="data_size")
    with pytest.raises(ValueError, match="DP"):
        build_round_fn(mesh, apply_fn, tx, 2, dp_clip_norm=1.0, **base)
    with pytest.raises(ValueError, match="compress|robust"):
        build_round_fn(mesh, apply_fn, tx, 2, compress="int8", **base)
    with pytest.raises(ValueError, match="compress|robust"):
        build_round_fn(mesh, apply_fn, tx, 2,
                       robust_aggregation="median", **base)
    with pytest.raises(ValueError, match="byzantine|incoherent"):
        build_round_fn(mesh, apply_fn, tx, 2, byzantine_clients=1, **base)
    with pytest.raises(ValueError, match="psum"):
        build_round_fn(mesh, apply_fn, tx, 2, aggregation="ring", **base)


def test_state_roundfn_mismatch_raises():
    mesh, apply_fn, tx, server, state_scaf, batch = _setup(True)
    plain = build_round_fn(mesh, apply_fn, tx, 2, weighting="uniform",
                           server_opt=server)
    with pytest.raises(ValueError, match="scaffold"):
        plain(state_scaf, batch)
    _, _, _, _, state_plain, _ = _setup(False)
    scaf = build_round_fn(mesh, apply_fn, tx, 2, weighting="uniform",
                          server_opt=server, scaffold=True)
    with pytest.raises(ValueError, match="scaffold"):
        scaf(state_plain, batch)
    with pytest.raises(ValueError, match="delta path"):
        init_federated_state(jax.random.key(0), mesh, 8,
                             build_model(ModelConfig(input_dim=8))[0], tx,
                             scaffold=True)


def _cfg(**fed_kw):
    return ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256,
                        synthetic_features=6),
        shard=ShardConfig(num_clients=8, shuffle=False),
        model=ModelConfig(input_dim=6, hidden_sizes=(16, 8)),
        fed=FedConfig(rounds=4, weighting="uniform", scaffold=True,
                      local_steps=2, **fed_kw),
        run=RunConfig(rounds_per_step=2),
    )


def test_run_experiment_scaffold_end_to_end(tmp_path):
    """Full orchestration: scaffold trains, checkpoints carry the variates,
    and a resumed run restores them (not zeros)."""
    ck = str(tmp_path / "ck")
    cfg = dataclasses.replace(
        _cfg(), run=RunConfig(rounds_per_step=2, checkpoint_dir=ck,
                              checkpoint_every=2))
    res = run_experiment(cfg, verbose=False)
    assert res.rounds_run == 4 and not res.diverged
    assert 0.0 <= res.global_metrics["accuracy"][-1] <= 1.0

    # Resume restores the saved variates into the live state.
    exp = build_experiment(cfg)
    assert "client_cv" in exp.state and "server_cv" in exp.state
    from fedtpu.orchestration.checkpoint import load_checkpoint
    state, _, step = load_checkpoint(ck, state_like=exp.state)
    assert step == 4
    assert max(float(np.abs(np.asarray(l)).max())
               for l in jax.tree.leaves(state["client_cv"])) > 1e-6

    cfg6 = dataclasses.replace(
        cfg, fed=dataclasses.replace(cfg.fed, rounds=6))
    res6 = run_experiment(cfg6, verbose=False, resume=True)
    # rounds_run counts THROUGH training end incl. the 4 restored rounds.
    assert res6.rounds_run == 6
    assert len(res6.global_metrics["accuracy"]) == 6


def test_model_parallel_scaffold_rejected():
    cfg = dataclasses.replace(_cfg(), run=RunConfig(model_parallel=2))
    with pytest.raises(ValueError, match="1-D engine"):
        build_experiment(cfg)


def test_scaffold_bf16_params_supported():
    """Review r4: f32-hardcoded variates under bf16 params used to die in
    XLA with an opaque scan-carry dtype mismatch. Variates now live in the
    param dtype; one corrected round must execute and keep the invariant
    (at bf16 tolerance)."""
    import jax.numpy as jnp

    x, y = synthetic_income_like(256, 6, 2, seed=0)
    packed = pack_clients(x, y, ShardConfig(num_clients=8, shuffle=False))
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(16, 8),
                                                param_dtype="bfloat16"))
    tx = build_optimizer(OptimConfig(name="sgd", learning_rate=0.05,
                                     momentum=0.0))
    mesh = make_mesh(num_clients=8)
    server = identity_server_optimizer()
    state = init_federated_state(jax.random.key(0), mesh, 8, init_fn, tx,
                                 same_init=True, server_opt=server,
                                 scaffold=True)
    assert all(l.dtype == jnp.bfloat16
               for l in jax.tree.leaves(state["client_cv"]))
    batch = {k: jax.device_put(v, client_sharding(mesh)) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    step = build_round_fn(mesh, apply_fn, tx, 2, weighting="uniform",
                          server_opt=server, scaffold=True, local_steps=2,
                          rounds_per_step=3)
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["client_mean"]["accuracy"][-1]))
    mean_ccv = jax.tree.map(
        lambda c: np.asarray(c, np.float32).mean(axis=0), state["client_cv"])
    for a, b in zip(jax.tree.leaves(mean_ccv),
                    jax.tree.leaves(jax.tree.map(
                        lambda s: np.asarray(s, np.float32),
                        state["server_cv"]))):
        np.testing.assert_allclose(a, b, atol=2e-2)


def test_sampled_scaffold_invariant_and_stale_variates():
    """Client sampling (paper's partial-participation rule): absentees
    keep their stale variates and contribute zero to the server-variate
    mean, so c == mean_i(c_i) keeps holding; after one sampled round some
    clients' variates must be refreshed and some still zero."""
    args = _setup(True)
    mesh, apply_fn, tx, server, state, batch = args
    step = build_round_fn(mesh, apply_fn, tx, 2, weighting="uniform",
                          server_opt=server, scaffold=True,
                          participation_rate=0.5, participation_seed=7,
                          local_steps=2)
    state, _ = step(state, batch)
    norms1 = np.array([
        float(np.sqrt(sum(np.sum(np.square(np.asarray(l)[c]))
                          for l in jax.tree.leaves(state["client_cv"]))))
        for c in range(8)])
    assert (norms1 > 1e-8).any(), "no client refreshed its variate"
    assert (norms1 < 1e-12).any(), "no absentee kept the stale (zero) variate"
    # Invariant across several more sampled rounds.
    for _ in range(4):
        state, _ = step(state, batch)
    mean_ccv = jax.tree.map(lambda c: np.asarray(c).mean(axis=0),
                            state["client_cv"])
    for a, b in zip(jax.tree.leaves(mean_ccv),
                    jax.tree.leaves(jax.tree.map(np.asarray,
                                                 state["server_cv"]))):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_sampled_path_with_all_participants_matches_full():
    """participation_rate=0.999 (the sampled code path) with a seed where
    every draw lands below it must reproduce the full-participation
    scaffold exactly — the where-select and |S|/N-mean reduce to the
    dense rule when S == all."""
    outs = {}
    for rate in (1.0, 0.999):
        args = _setup(True)
        mesh, apply_fn, tx, server, state, batch = args
        kw = {} if rate == 1.0 else dict(participation_rate=rate,
                                         participation_seed=0)
        step = build_round_fn(mesh, apply_fn, tx, 2, weighting="uniform",
                              server_opt=server, scaffold=True,
                              local_steps=2, rounds_per_step=5, **kw)
        state, _ = step(state, batch)
        outs[rate] = jax.tree.map(np.asarray, state["params"])
    for a, b in zip(jax.tree.leaves(outs[1.0]), jax.tree.leaves(outs[0.999])):
        np.testing.assert_allclose(a, b, atol=1e-7)
