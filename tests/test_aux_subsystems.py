"""Auxiliary subsystems (SURVEY.md §5 gaps the reference left open): JSONL
metrics logging, profiler wiring, CIFAR-10 loader, multi-host helpers."""

import dataclasses
import json
import os

import jax
import numpy as np

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           RunConfig, ShardConfig)
from fedtpu.data.cifar10 import load_cifar10, synthetic_cifar_like
from fedtpu.data.sharding import pack_clients
from fedtpu.orchestration.loop import run_experiment
from fedtpu.parallel import make_mesh
from fedtpu.parallel import multihost


def test_metrics_jsonl_written(tmp_path):
    path = str(tmp_path / "m.jsonl")
    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=128),
        shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=3),
        run=RunConfig(metrics_jsonl=path),
    )
    res = run_experiment(cfg, verbose=False)
    lines = [json.loads(l) for l in open(path)]
    assert [l["round"] for l in lines] == [1, 2, 3]
    np.testing.assert_allclose(
        [l["client_mean"]["accuracy"] for l in lines],
        res.global_metrics["accuracy"], atol=1e-9)


def test_profiler_trace_produced(tmp_path):
    pdir = str(tmp_path / "prof")
    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=128),
        shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=2),
        run=RunConfig(profile_dir=pdir),
    )
    run_experiment(cfg, verbose=False)
    # A trace directory with at least one event file must exist.
    found = [f for _, _, fs in os.walk(pdir) for f in fs]
    assert found, "no profiler output written"


def test_nonfinite_guard_halts_diverged_run(tmp_path):
    from fedtpu.config import OptimConfig
    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=128),
        shard=ShardConfig(num_clients=8),
        # An absurd learning rate reliably drives the loss to NaN.
        optim=OptimConfig(learning_rate=1e18),
        fed=FedConfig(rounds=50),
        run=RunConfig(checkpoint_dir=str(tmp_path / "ck")),
    )
    res = run_experiment(cfg, verbose=False)
    assert res.diverged and res.stopped_early
    assert res.summary()["diverged"] is True
    assert res.rounds_run < 50
    from fedtpu.orchestration.checkpoint import latest_step
    # The poisoned state is quarantined under diverged/ — resume must NOT
    # see it as the latest periodic checkpoint.
    assert latest_step(str(tmp_path / "ck")) is None
    assert latest_step(str(tmp_path / "ck" / "diverged")) == res.rounds_run


def test_resume_after_divergence_restores_last_good_checkpoint(tmp_path):
    """A diverged run must leave resume pointing at the last GOOD periodic
    checkpoint — never the quarantined NaN state."""
    from fedtpu.config import OptimConfig
    ck = str(tmp_path / "ck")
    good = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=128),
        shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=2),
        run=RunConfig(checkpoint_dir=ck, checkpoint_every=1),
    )
    run_experiment(good, verbose=False)        # rounds 1-2 checkpointed, finite

    bad = dataclasses.replace(
        good, optim=OptimConfig(learning_rate=1e18),
        fed=FedConfig(rounds=10))
    res = run_experiment(bad, verbose=False, resume=True)
    assert res.diverged

    from fedtpu.orchestration.checkpoint import latest_step, load_checkpoint
    from fedtpu.orchestration.loop import build_experiment
    # The guard's contract: whatever the latest periodic checkpoint is, its
    # params are FINITE (a non-finite state may only ever be quarantined).
    # With lr=1e18 the first bad update leaves huge-but-finite params (so
    # its round may legitimately checkpoint); NaN states may not.
    exp = build_experiment(good)
    state, _, step = load_checkpoint(ck, state_like=exp.state)
    assert step >= 2
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree.leaves(state["params"]))
    # The poisoned state is quarantined separately, NaN and all.
    assert latest_step(os.path.join(ck, "diverged")) is not None
    bad_state, _, _ = load_checkpoint(os.path.join(ck, "diverged"),
                                      state_like=exp.state)
    assert not all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree.leaves(bad_state["params"]))


def test_divergence_in_chunked_run_labels_chunk_end(tmp_path):
    """With rounds_per_step>1 the quarantined state is the chunk-end state
    and must be labeled as such (not the in-chunk detection round)."""
    from fedtpu.config import OptimConfig
    ck = str(tmp_path / "ck")
    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=128),
        shard=ShardConfig(num_clients=8),
        optim=OptimConfig(learning_rate=1e18),
        fed=FedConfig(rounds=20),
        run=RunConfig(checkpoint_dir=ck, rounds_per_step=5),
    )
    res = run_experiment(cfg, verbose=False)
    assert res.diverged
    from fedtpu.orchestration.checkpoint import latest_step
    step = latest_step(os.path.join(ck, "diverged"))
    assert step is not None and step % 5 == 0  # chunk-end label


def test_cifar10_synthetic_fallback_shapes():
    ds = load_cifar10(root="/nonexistent", synthetic_rows=100)
    assert ds.x_train.shape == (80, 32 * 32 * 3)
    assert ds.x_test.shape == (20, 32 * 32 * 3)
    assert ds.num_classes == 10


def _fabricate_cifar_batches(d, per_batch=8):
    """A minimal, REAL-format cifar-10-batches-py: 5 train pickles + one
    test pickle, bytes keys, uint8 (N, 3072) row-major RGB planes + label
    lists — exactly what the torchvision/keras-distributed tarball
    unpacks to and what _load_batch parses."""
    import pickle

    rng = np.random.default_rng(7)
    d.mkdir(parents=True, exist_ok=True)
    planted = {}
    for name in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]:
        data = rng.integers(0, 256, size=(per_batch, 3072), dtype=np.uint8)
        labels = [int(v) for v in rng.integers(0, 10, per_batch)]
        with open(d / name, "wb") as f:
            pickle.dump({b"data": data, b"labels": labels,
                         b"batch_label": b"fabricated"}, f)
        planted[name] = (data, labels)
    return planted


def test_cifar10_real_batches_branch(tmp_path):
    """VERDICT r4 weak #6: the REAL-data branch of the config-5 loader,
    exercised against a fabricated on-disk batch set — load, NHWC
    transpose, /255 normalization, train concat, test split, flatten."""
    from fedtpu.data.cifar10 import find_cifar10_dir

    d = tmp_path / "cifar-10-batches-py"
    planted = _fabricate_cifar_batches(d, per_batch=8)
    assert find_cifar10_dir(str(d)) == str(d)

    ds = load_cifar10(root=str(d), flatten=False)
    assert ds.x_train.shape == (40, 32, 32, 3)      # 5 batches x 8
    assert ds.x_test.shape == (8, 32, 32, 3)
    assert ds.num_classes == 10
    # Normalization + CHW->HWC transpose pinned against the raw bytes:
    # row r of b"data" is 1024 R + 1024 G + 1024 B values, each plane
    # row-major 32x32 — so pixel (h, w, c) = raw[r, c*1024 + h*32 + w]/255.
    raw, labels = planted["data_batch_1"]
    for (r, h, w, c) in ((0, 0, 0, 0), (3, 5, 17, 1), (7, 31, 31, 2)):
        np.testing.assert_allclose(ds.x_train[r, h, w, c],
                                   raw[r, c * 1024 + h * 32 + w] / 255.0,
                                   rtol=1e-6)
    np.testing.assert_array_equal(ds.y_train[:8], np.asarray(labels))
    assert ds.x_train.min() >= 0.0 and ds.x_train.max() <= 1.0
    # The flattened view (what pack_clients consumes) is the same data.
    ds_flat = load_cifar10(root=str(d), flatten=True)
    np.testing.assert_array_equal(ds_flat.x_train,
                                  ds.x_train.reshape(40, -1))
    # And it shards through the standard packing path.
    packed = pack_clients(ds_flat.x_train, ds_flat.y_train,
                          ShardConfig(num_clients=8, shuffle=False))
    assert packed.x.shape[0] == 8 and packed.x.shape[2] == 3072
    assert int(packed.mask.sum()) == 40     # every real row exactly once


def test_cifar10_real_branch_via_load_dataset(tmp_path):
    """The dataset_name='cifar10' config path takes the real branch when
    the batches exist at a candidate location (chdir into tmp)."""
    import os as _os

    from fedtpu.config import DataConfig
    from fedtpu.data import load_dataset

    _fabricate_cifar_batches(tmp_path / "cifar-10-batches-py")
    cwd = _os.getcwd()
    _os.chdir(tmp_path)
    try:
        ds = load_dataset(DataConfig(dataset_name="cifar10"))
    finally:
        _os.chdir(cwd)
    assert ds.x_train.shape == (40, 3072)           # real branch, not synthetic
    assert ds.num_classes == 10


def test_synthetic_cifar_deterministic():
    a, ya = synthetic_cifar_like(32)
    b, yb = synthetic_cifar_like(32)
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(ya, yb)


def test_multihost_single_process_paths():
    # Single-process: initialize() is a no-op, the local slice is everything,
    # and distribute_client_batch matches plain device_put.
    multihost.initialize()
    x = np.random.default_rng(0).normal(size=(64, 4)).astype(np.float32)
    y = (np.arange(64) % 2).astype(np.int32)
    packed = pack_clients(x, y, ShardConfig(num_clients=8, shuffle=False))
    mesh = make_mesh(num_clients=8)
    assert multihost.local_client_slice(8, mesh) == slice(0, 8)
    batch = multihost.distribute_client_batch(packed, mesh)
    np.testing.assert_allclose(np.asarray(batch["x"]), packed.x)
    assert len(batch["x"].sharding.device_set) == 8  # client-axis sharded


def test_local_client_slice_multiprocess_simulated(monkeypatch):
    """Simulate a 4-process pod (2 devices each) with fake device objects:
    each process must own exactly its contiguous block of the client axis,
    and the blocks must partition it."""
    import types

    class FakeDevice:
        def __init__(self, pid):
            self.process_index = pid

    # 8 devices, process layout [0,0,1,1,2,2,3,3] — the standard pod order.
    devices = np.array([FakeDevice(i // 2) for i in range(8)])
    mesh = types.SimpleNamespace(devices=devices)

    slices = []
    for pid in range(4):
        monkeypatch.setattr(multihost.jax, "process_index", lambda p=pid: p)
        slices.append(multihost.local_client_slice(32, mesh))
    # 32 clients / 8 devices = 4 per device; 2 devices per process = 8 rows.
    assert slices == [slice(0, 8), slice(8, 16), slice(16, 24), slice(24, 32)]
    # A process owning no devices of this mesh gets the empty slice.
    monkeypatch.setattr(multihost.jax, "process_index", lambda: 9)
    assert multihost.local_client_slice(32, mesh) == slice(0, 0)


def test_looks_multihost_env_detection(monkeypatch):
    # Clear EVERY hint the detector consults — on a real pod worker some
    # (TPU_WORKER_HOSTNAMES, COORDINATOR_ADDRESS) are legitimately set and
    # would make the baseline assert fail spuriously.
    for var in (*multihost._MULTIHOST_ENV_HINTS,
                "SLURM_NTASKS", "OMPI_COMM_WORLD_SIZE"):
        monkeypatch.delenv(var, raising=False)
    assert not multihost._looks_multihost()
    monkeypatch.setenv("SLURM_NTASKS", "4")
    assert multihost._looks_multihost()
    monkeypatch.setenv("SLURM_NTASKS", "1")
    assert not multihost._looks_multihost()
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
    assert multihost._looks_multihost()


def test_lazy_top_level_api_resolves():
    """Every name in fedtpu._LAZY resolves to a callable via PEP 562 —
    a renamed/moved symbol breaks `fedtpu.<name>` for users even though
    direct module imports still pass."""
    import fedtpu
    for name in fedtpu._LAZY:
        assert callable(getattr(fedtpu, name)), name
    assert set(fedtpu._LAZY) <= set(dir(fedtpu))
