"""MPMD round pipelining (RunConfig.mpmd): the round decomposed into a
DAG of AOT sub-programs — client step, aggregate+apply, metrics — with
async dispatch and the monolithic loop as bitwise-parity oracle.

Semantics contract (fedtpu/orchestration/mpmd.py + loop.py):

* the DAG's recorded metric history and final params are BITWISE equal
  to the monolithic run (the sub-programs are built from the same
  primitives in the same op order, and the metrics program compiles on
  the client mesh so its cross-client sums partition identically);
* mpmd rides the pipelined pending machinery: early-stop decisions lag
  one in-flight chunk but the recorded history and the stop round match
  the synchronous run exactly;
* a SIGTERM drain mid-pipeline processes the in-flight chunk first, so
  the checkpoint lands on a consistent chunk boundary and resume
  reproduces the uninterrupted history;
* faults that edit the live batch mask (client dropout) stay bitwise
  because the metrics sub-program reads the mask per call, never a
  build-time snapshot;
* configs whose round math cannot split at the client/aggregate
  boundary are rejected loudly at startup.
"""

import dataclasses
import json

import jax
import numpy as np
import pytest

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           ModelConfig, RunConfig, ShardConfig)
from fedtpu.orchestration.loop import run_experiment
from fedtpu.resilience.supervisor import Preempted


def _cfg(**run_kw):
    return ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256,
                        synthetic_features=6),
        shard=ShardConfig(num_clients=4, shuffle=False),
        model=ModelConfig(input_dim=6, hidden_sizes=(8,)),
        fed=FedConfig(rounds=12, tolerance=0.0),
        run=RunConfig(rounds_per_step=3, **run_kw),
    )


def _assert_bitwise(a, b):
    assert set(a.global_metrics) == set(b.global_metrics)
    for k in a.global_metrics:
        np.testing.assert_array_equal(a.global_metrics[k],
                                      b.global_metrics[k], err_msg=k)
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)),
        a.final_params, b.final_params)


def test_mpmd_matches_monolithic_bitwise():
    """Chain path (rounds_per_step=3): history AND final params."""
    mono = run_experiment(_cfg(), verbose=False)
    mp = run_experiment(_cfg(mpmd=True), verbose=False)
    assert mp.rounds_run == mono.rounds_run == 12
    _assert_bitwise(mono, mp)


def test_mpmd_width1_matches_monolithic_bitwise():
    """Two-program DAG (client -> aggregate, no scan): the degenerate
    width where cross-program buffer handoff replaces the scan carry."""
    def cfg(mpmd):
        base = _cfg(mpmd=mpmd)
        return dataclasses.replace(
            base, fed=dataclasses.replace(base.fed, rounds=4),
            run=dataclasses.replace(base.run, rounds_per_step=1))
    mono = run_experiment(cfg(False), verbose=False)
    mp = run_experiment(cfg(True), verbose=False)
    assert mp.rounds_run == mono.rounds_run == 4
    _assert_bitwise(mono, mp)


def test_mpmd_early_stop_round_agreement():
    # tolerance=1 makes every round "no significant change": both
    # engines must stop at round patience+1 with identical recorded
    # histories (the in-flight overshoot chunk's metrics are dropped,
    # exactly like pipelined_stop).
    def cfg(mpmd):
        base = _cfg(mpmd=mpmd)
        return dataclasses.replace(
            base, fed=dataclasses.replace(base.fed, rounds=30,
                                          tolerance=1.0,
                                          termination_patience=4))
    mono = run_experiment(cfg(False), verbose=False)
    mp = run_experiment(cfg(True), verbose=False)
    assert mono.stopped_early and mp.stopped_early
    assert mp.rounds_run == mono.rounds_run
    for k in mono.global_metrics:
        np.testing.assert_array_equal(mono.global_metrics[k],
                                      mp.global_metrics[k])


def test_mpmd_sigterm_drain_lands_on_chunk_boundary_and_resumes(tmp_path):
    """SIGTERM mid-pipeline: the drain processes the in-flight chunk
    before checkpointing, so the saved round is a consistent boundary
    (history rounds == state round), and resume completes the run with
    the uninterrupted monolithic history bitwise."""
    baseline = run_experiment(_cfg(), verbose=False)
    ck = str(tmp_path / "ck")
    plan = json.dumps({"seed": 0, "faults": [
        {"kind": "process_kill", "round": 5, "signal": "SIGTERM"}]})
    cfg = _cfg(mpmd=True, fault_plan=plan, checkpoint_dir=ck,
               checkpoint_every=3)
    with pytest.raises(Preempted) as exc:
        run_experiment(cfg, verbose=False)
    from fedtpu.orchestration.checkpoint import latest_step
    drained = latest_step(ck)
    # The fault fires inside the second chunk (round 5 of 12 at width
    # 3); the drain must flush the pipeline to the round it reports.
    assert drained == exc.value.round == 5
    res = run_experiment(cfg, verbose=False, resume=True)
    assert res.rounds_run == 12 and not res.diverged
    _assert_bitwise(baseline, res)


def test_mpmd_dropout_fault_stays_bitwise_with_oracle():
    """client_dropout edits the live batch mask in place for one round;
    the metrics sub-program must see the SAME mask the oracle's
    in-graph masked_client_mean sees (a build-time nonempty snapshot
    would diverge here)."""
    plan = json.dumps({"seed": 0, "faults": [
        {"kind": "client_dropout", "round": 4, "clients": [1]}]})
    mono = run_experiment(_cfg(fault_plan=plan), verbose=False)
    mp = run_experiment(_cfg(mpmd=True, fault_plan=plan), verbose=False)
    assert mp.rounds_run == mono.rounds_run == 12
    _assert_bitwise(mono, mp)


@pytest.mark.parametrize("run_kw,match", [
    ({"pipelined_stop": True}, "subsumes"),
    ({"overlap_compile": True}, "overlap_compile"),
    ({"on_divergence": "rollback", "checkpoint_dir": "d",
      "checkpoint_every": 2}, "rollback"),
    ({"model_parallel": 2}, "model_parallel"),
])
def test_mpmd_invalid_run_configs_rejected(run_kw, match):
    with pytest.raises(ValueError, match=match):
        run_experiment(_cfg(mpmd=True, **run_kw), verbose=False)


@pytest.mark.parametrize("fed_kw,match", [
    ({"async_mode": True, "weighting": "uniform"}, "async_mode"),
    ({"server_opt": "fedadam"}, "server_opt"),
    ({"scaffold": True}, "scaffold"),
    ({"participation_rate": 0.5}, "participation_rate"),
])
def test_mpmd_invalid_fed_configs_rejected(fed_kw, match):
    base = _cfg(mpmd=True)
    cfg = dataclasses.replace(base,
                              fed=dataclasses.replace(base.fed, **fed_kw))
    with pytest.raises(ValueError, match=match):
        run_experiment(cfg, verbose=False)


def test_mpmd_parity_check_probe():
    """The `fedtpu check --mpmd` fold's probe: ok=True with no
    mismatches on the standard preset shrunk to synthetic data."""
    from fedtpu.orchestration.mpmd import parity_check
    rep = parity_check("income-8", rounds=4, synthetic_rows=256)
    assert rep["ok"]
    assert rep["metric_mismatches"] == []
    assert rep["param_leaf_mismatches"] == 0
    assert rep["rounds_run"] == [4, 4]


def test_mpmd_trace_chains_and_chrome_export(tmp_path):
    """Each chunk's pass through the DAG is one trace-id chain in the
    PR 16 timeline — client_step -> aggregate -> metrics in causal
    order — and the Chrome/Perfetto export renders the stage slices."""
    from fedtpu.config import TelemetryConfig
    from fedtpu.telemetry.timeline import (chrome_trace, load_timeline,
                                           trace_chains)
    ev = str(tmp_path / "events.jsonl")
    cfg = _cfg(mpmd=True, telemetry=TelemetryConfig(events_path=ev))
    run_experiment(cfg, verbose=False)
    sources = load_timeline([ev])
    chains = [c for c in trace_chains(sources)
              if str(c["chain"]).startswith("mpmd-")]
    assert len(chains) == 4                    # 12 rounds at width 3
    for c in chains:
        assert [s["stage"] for s in c["stages"]] == [
            "client_step", "aggregate", "metrics"]
        assert all(s["op"] == "mpmd" for s in c["stages"])
    names = {e.get("name") for e in chrome_trace(sources)["traceEvents"]}
    assert {"trace:client_step", "trace:aggregate",
            "trace:metrics"} <= names


def test_mpmd_manifest_records_dag(tmp_path):
    """The run manifest names the engine and the DAG's sub-programs, and
    keeps the audited-program caveat honest (the runtime audit summary
    gates the monolithic ORACLE; the per-sub-program contracts live in
    the committed mpmd_* goldens)."""
    from fedtpu.config import TelemetryConfig
    from fedtpu.telemetry.report import aggregate, load_events
    ev = str(tmp_path / "events.jsonl")
    cfg = _cfg(mpmd=True, telemetry=TelemetryConfig(events_path=ev))
    run_experiment(cfg, verbose=False)
    agg = aggregate(load_events(ev)[0])
    man = agg["manifest"]
    assert man["engine"] == "mpmd"
    assert man["mpmd"]["width"] == 3
    assert man["mpmd"]["sub_programs"] == sorted(
        ["mpmd_client", "mpmd_aggregate", "mpmd_chain", "mpmd_metrics"])
    assert agg["static_analysis"]["audited_program"] == "monolithic_oracle"
    assert agg["static_analysis"]["engine"] == "mpmd_chain"
