"""Ring all-reduce (fedtpu.parallel.ring): both explicit ICI ring schedules
must match psum, standalone and as the round program's aggregation backend.
The ring is the TPU-native answer to the reference's rank-0
gather/average/bcast funnel (FL_CustomMLP...:101-120)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from fedtpu.parallel.ring import (ring_all_reduce_sum,
                                  ring_all_reduce_sum_rsag)
from fedtpu.parallel.ring_pallas import pallas_ring_all_reduce_sum
from tests.test_fedavg import _setup


def _run_reduce(fn, shape, seed=0):
    mesh = jax.make_mesh((8,), ("clients",))
    x = jax.random.normal(jax.random.key(seed), (8,) + shape, jnp.float32)

    def body(xb):
        return fn(xb[0], "clients", 8)[None]

    out = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P("clients"),  # fedtpu: noqa[FTP006] one-shot test launch
                                out_specs=P("clients")))(x)
    return np.asarray(out), np.asarray(x.sum(axis=0))


@pytest.mark.parametrize("fn", [ring_all_reduce_sum, ring_all_reduce_sum_rsag])
@pytest.mark.parametrize("shape", [(4,), (5, 3), (7, 2, 3)])
def test_ring_matches_global_sum(fn, shape):
    # (7,2,3) exercises the rsag zero-pad path: 42 elements % 8 != 0.
    out, expected = _run_reduce(fn, shape)
    for d in range(8):
        np.testing.assert_allclose(out[d], expected, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("shape", [(4,), (8, 128), (3, 7, 5)])
def test_pallas_rdma_ring_matches_global_sum(shape):
    """The RDMA-kernel ring (fedtpu.parallel.ring_pallas) — every hop a real
    pltpu.make_async_remote_copy — must produce the same global sum as the
    plain sum (interpret mode on the virtual CPU mesh, which requires
    check_vma=False; Mosaic on real multi-chip)."""
    mesh = jax.make_mesh((8,), ("clients",))
    x = jax.random.normal(jax.random.key(0), (8,) + shape, jnp.float32)

    def body(xb):
        return pallas_ring_all_reduce_sum(xb[0], "clients", 8)[None]

    out = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P("clients"),  # fedtpu: noqa[FTP006] one-shot test launch
                                out_specs=P("clients"),
                                check_vma=False))(x)
    out, expected = np.asarray(out), np.asarray(x.sum(axis=0))
    for d in range(8):
        np.testing.assert_allclose(out[d], expected, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("n", [2, 3, 4, 5, 8, 16])
def test_pallas_ring_capacity_credits_balance(n):
    """Flow-control arithmetic: every credit the right neighbor sends is
    either consumed by a pre-send wait or drained at kernel end, so the
    regular semaphores finish at exactly zero for any ring size."""
    from fedtpu.parallel.ring_pallas import _residual_credits
    received = [sum(1 for s in range(n - 1) if s % 2 == p) for p in (0, 1)]
    consumed = [sum(1 for s in range(2, n - 1) if (s + 1) % 2 == p)
                for p in (0, 1)]
    residual = _residual_credits(n)
    for p in (0, 1):
        assert residual[p] >= 0
        assert consumed[p] + residual[p] == received[p]


@pytest.mark.parametrize("rounds_per_step", [1, 3])
@pytest.mark.parametrize("aggregation", ["ring", "ring-rsag"])
def test_round_with_ring_aggregation_matches_psum(aggregation,
                                                  rounds_per_step):
    from fedtpu.parallel import make_mesh
    from fedtpu.parallel.round import build_round_fn
    state, batch, _, packed = _setup()
    mesh = make_mesh(num_clients=8)
    from fedtpu.config import ModelConfig, OptimConfig
    from fedtpu.models import build_model
    from fedtpu.ops import build_optimizer
    _, apply_fn = build_model(ModelConfig(input_dim=6, hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig())

    step_psum = build_round_fn(mesh, apply_fn, tx, 2, aggregation="psum",
                               rounds_per_step=rounds_per_step)
    step_ring = build_round_fn(mesh, apply_fn, tx, 2, aggregation=aggregation,
                               rounds_per_step=rounds_per_step)
    from fedtpu.utils.trees import clone
    # round_step donates its input state; clone to step the same start twice.
    s1, m1 = step_psum(clone(state), batch)
    s2, m2 = step_ring(state, batch)
    # Ring sums in neighbor order — same value up to float reassociation.
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-5, atol=1e-6),
        s1["params"], s2["params"])
    np.testing.assert_allclose(np.asarray(m1["client_mean"]["accuracy"]),
                               np.asarray(m2["client_mean"]["accuracy"]),
                               atol=1e-6)
