"""In-graph metric parity against sklearn (the reference's metric source:
FL_CustomMLP...:85-90 — accuracy + weighted precision/recall/F1,
zero_division=0)."""

import numpy as np
import pytest
from sklearn.metrics import (accuracy_score, precision_score, recall_score,
                             f1_score)

from fedtpu.ops.metrics import confusion_matrix, metrics_from_confusion


def _sklearn_reference(y, p):
    return {
        "accuracy": accuracy_score(y, p),
        "precision": precision_score(y, p, average="weighted",
                                     zero_division=0),
        "recall": recall_score(y, p, average="weighted", zero_division=0),
        "f1": f1_score(y, p, average="weighted", zero_division=0),
    }


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
@pytest.mark.parametrize("num_classes", [2, 5])
def test_metrics_match_sklearn(seed, num_classes):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, num_classes, size=500).astype(np.int32)
    p = rng.integers(0, num_classes, size=500).astype(np.int32)
    mask = np.ones(500, np.float32)
    ours = metrics_from_confusion(confusion_matrix(y, p, mask, num_classes))
    ref = _sklearn_reference(y, p)
    for k, v in ref.items():
        np.testing.assert_allclose(float(ours[k]), v, atol=1e-6, err_msg=k)


def test_zero_division_semantics():
    # Class 2 never predicted and class 3 never true: both per-class terms
    # must be 0, not NaN (zero_division=0).
    y = np.array([0, 0, 1, 1, 2, 2], np.int32)
    p = np.array([0, 1, 1, 0, 3, 3], np.int32)
    mask = np.ones(6, np.float32)
    ours = metrics_from_confusion(confusion_matrix(y, p, mask, 4))
    ref = _sklearn_reference(y, p)
    for k, v in ref.items():
        assert np.isfinite(float(ours[k]))
        np.testing.assert_allclose(float(ours[k]), v, atol=1e-6, err_msg=k)


def test_mask_excludes_padding():
    y = np.array([0, 1, 0, 1], np.int32)
    p = np.array([0, 1, 1, 0], np.int32)  # last two rows are "padding"
    mask = np.array([1, 1, 0, 0], np.float32)
    ours = metrics_from_confusion(confusion_matrix(y, p, mask, 2))
    assert float(ours["accuracy"]) == 1.0


def test_summed_confusions_equal_concatenated_predictions():
    # Pooled-metric semantics #2 (FL_SkLearn...:132-134): metrics over
    # concatenated predictions == metrics of the SUM of confusion matrices.
    rng = np.random.default_rng(9)
    confs, ys, ps = [], [], []
    for _ in range(4):
        y = rng.integers(0, 3, size=100).astype(np.int32)
        p = rng.integers(0, 3, size=100).astype(np.int32)
        confs.append(np.asarray(confusion_matrix(
            y, p, np.ones(100, np.float32), 3)))
        ys.append(y)
        ps.append(p)
    pooled = metrics_from_confusion(np.sum(confs, axis=0))
    ref = _sklearn_reference(np.concatenate(ys), np.concatenate(ps))
    for k, v in ref.items():
        np.testing.assert_allclose(float(pooled[k]), v, atol=1e-6, err_msg=k)
