"""Optimizer parity against the torch driver's exact configuration:
Adam(lr=0.004) + StepLR(step_size=30, gamma=0.5), one scheduler step per
update (FL_CustomMLP...:44-46,73)."""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import torch

from fedtpu.config import OptimConfig
from fedtpu.ops.optim import build_optimizer


def test_adam_steplr_matches_torch_trajectory():
    # Quadratic bowl: loss = 0.5 * ||p - t||^2, grad = p - t. 70 steps crosses
    # the StepLR boundary at step 30 (lr 0.004 -> 0.002) and at 60 (-> 0.001).
    rng = np.random.default_rng(0)
    p0 = rng.normal(size=(16,)).astype(np.float32)
    target = rng.normal(size=(16,)).astype(np.float32)

    # --- torch reference
    p_t = torch.nn.Parameter(torch.tensor(p0.copy()))
    opt = torch.optim.Adam([p_t], lr=0.004)
    sched = torch.optim.lr_scheduler.StepLR(opt, step_size=30, gamma=0.5)
    t_target = torch.tensor(target)
    torch_traj = []
    for _ in range(70):
        opt.zero_grad()
        loss = 0.5 * ((p_t - t_target) ** 2).sum()
        loss.backward()
        opt.step()
        sched.step()
        torch_traj.append(p_t.detach().numpy().copy())

    # --- fedtpu
    tx = build_optimizer(OptimConfig())
    p_j = jnp.asarray(p0)
    state = tx.init(p_j)

    @jax.jit
    def step(p, s):
        grads = p - jnp.asarray(target)
        updates, s = tx.update(grads, s, p)
        return optax.apply_updates(p, updates), s

    for i in range(70):
        p_j, state = step(p_j, state)
        np.testing.assert_allclose(np.asarray(p_j), torch_traj[i], atol=2e-5,
                                   err_msg=f"diverged at step {i}")


def test_schedule_staircase_boundaries():
    tx = build_optimizer(OptimConfig(learning_rate=0.004,
                                     steplr_step_size=30, steplr_gamma=0.5))
    sched = optax.exponential_decay(0.004, 30, 0.5, staircase=True)
    np.testing.assert_allclose(float(sched(0)), 0.004, rtol=1e-6)
    np.testing.assert_allclose(float(sched(29)), 0.004, rtol=1e-6)
    np.testing.assert_allclose(float(sched(30)), 0.002, rtol=1e-6)
    np.testing.assert_allclose(float(sched(60)), 0.001, rtol=1e-6)
