"""Optimizer parity against the torch driver's exact configuration:
Adam(lr=0.004) + StepLR(step_size=30, gamma=0.5), one scheduler step per
update (FL_CustomMLP...:44-46,73)."""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import torch

from fedtpu.config import OptimConfig
from fedtpu.ops.optim import build_optimizer


def test_adam_steplr_matches_torch_trajectory():
    # Quadratic bowl: loss = 0.5 * ||p - t||^2, grad = p - t. 70 steps crosses
    # the StepLR boundary at step 30 (lr 0.004 -> 0.002) and at 60 (-> 0.001).
    rng = np.random.default_rng(0)
    p0 = rng.normal(size=(16,)).astype(np.float32)
    target = rng.normal(size=(16,)).astype(np.float32)

    # --- torch reference
    p_t = torch.nn.Parameter(torch.tensor(p0.copy()))
    opt = torch.optim.Adam([p_t], lr=0.004)
    sched = torch.optim.lr_scheduler.StepLR(opt, step_size=30, gamma=0.5)
    t_target = torch.tensor(target)
    torch_traj = []
    for _ in range(70):
        opt.zero_grad()
        loss = 0.5 * ((p_t - t_target) ** 2).sum()
        loss.backward()
        opt.step()
        sched.step()
        torch_traj.append(p_t.detach().numpy().copy())

    # --- fedtpu
    tx = build_optimizer(OptimConfig())
    p_j = jnp.asarray(p0)
    state = tx.init(p_j)

    @jax.jit
    def step(p, s):
        grads = p - jnp.asarray(target)
        updates, s = tx.update(grads, s, p)
        return optax.apply_updates(p, updates), s

    for i in range(70):
        p_j, state = step(p_j, state)
        np.testing.assert_allclose(np.asarray(p_j), torch_traj[i], atol=2e-5,
                                   err_msg=f"diverged at step {i}")


def test_schedule_staircase_boundaries():
    tx = build_optimizer(OptimConfig(learning_rate=0.004,
                                     steplr_step_size=30, steplr_gamma=0.5))
    sched = optax.exponential_decay(0.004, 30, 0.5, staircase=True)
    np.testing.assert_allclose(float(sched(0)), 0.004, rtol=1e-6)
    np.testing.assert_allclose(float(sched(29)), 0.004, rtol=1e-6)
    np.testing.assert_allclose(float(sched(30)), 0.002, rtol=1e-6)
    np.testing.assert_allclose(float(sched(60)), 0.001, rtol=1e-6)


def test_onehot_ce_equals_gather_ce():
    # The r2 perf fix replaced take_along_axis with a one-hot contraction
    # (fedtpu/ops/losses.py) claiming exactness — pin value AND gradient
    # equality against the gather formulation, padded rows included.
    import jax
    import jax.numpy as jnp
    from fedtpu.ops.losses import masked_cross_entropy

    def gather_ce(logits, labels, mask):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(
            logp, labels[:, None].astype(jnp.int32), axis=-1)[:, 0]
        return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)

    rng = np.random.default_rng(0)
    for k in (2, 10):
        logits = jnp.asarray(rng.standard_normal((64, k)) * 5, jnp.float32)
        labels = jnp.asarray(rng.integers(0, k, 64), jnp.int32)
        mask = jnp.asarray((rng.random(64) < 0.8), jnp.float32)
        a, ga = jax.value_and_grad(masked_cross_entropy)(logits, labels, mask)
        b, gb = jax.value_and_grad(gather_ce)(logits, labels, mask)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(ga), np.asarray(gb))


def test_bf16_compute_trajectory_tracks_f32():
    # VERDICT r1 item 3: bf16 compute_dtype needs trajectory-parity
    # evidence, not just an accuracy spot check. bf16 matmuls round each
    # product to 8 mantissa bits, so exact equality is impossible — pin
    # that the ACCURACY TRAJECTORY tracks f32 closely and reaches the same
    # plateau on a real few-round federated run. Early stopping is disabled
    # (tolerance=0) so both runs always produce full-length histories —
    # otherwise bf16 rounding could tip the stop comparator and shape the
    # comparison out of existence.
    import dataclasses
    from fedtpu.config import (DataConfig, ExperimentConfig, ModelConfig,
                               ShardConfig, RunConfig, FedConfig)
    from fedtpu.orchestration.loop import run_experiment

    base = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=512,
                        synthetic_features=8),
        shard=ShardConfig(num_clients=4, shuffle=False),
        model=ModelConfig(input_dim=8, hidden_sizes=(16,)),
        fed=FedConfig(rounds=30, tolerance=0.0),
        run=RunConfig(rounds_per_step=10),
    )
    res_f32 = run_experiment(base, verbose=False)
    bf16 = dataclasses.replace(
        base, model=dataclasses.replace(base.model,
                                        compute_dtype="bfloat16"))
    res_bf16 = run_experiment(bf16, verbose=False)

    acc32 = np.asarray(res_f32.global_metrics["accuracy"])
    acc16 = np.asarray(res_bf16.global_metrics["accuracy"])
    assert acc32.shape == acc16.shape == (30,)
    # Same plateau at the end (within 2 points), close all along (within 5).
    assert abs(acc32[-1] - acc16[-1]) < 0.02, (acc32[-1], acc16[-1])
    assert np.max(np.abs(acc32 - acc16)) < 0.05
