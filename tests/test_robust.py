"""Byzantine-robust aggregation (median / trimmed mean) + the matching
model-poisoning fault injection. The attack/defense pair the reference has
no analogue of: its only aggregation is the mean, which a single malicious
rank can move arbitrarily far."""

import numpy as np
import jax
import pytest

from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.parallel import make_mesh, client_sharding
from fedtpu.parallel.round import build_round_fn, init_federated_state


def _setup(num_clients=8, rows=200, lr=0.004, **round_kw):
    x, y = synthetic_income_like(rows, 6, 2)
    packed = pack_clients(x, y, ShardConfig(num_clients=num_clients,
                                            shuffle=False))
    mesh = make_mesh(num_clients=num_clients)
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig(learning_rate=lr))
    state = init_federated_state(jax.random.key(1), mesh, num_clients,
                                 init_fn, tx, same_init=True)
    shard = client_sharding(mesh)
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    round_step = build_round_fn(mesh, apply_fn, tx, 2, **round_kw)
    return state, batch, round_step


def _leaf0(state):
    return np.asarray(jax.tree.leaves(state["params"])[0])


def test_median_matches_numpy_oracle():
    # lr=0 freezes training, but same_init makes all slots equal — use
    # different inits so the median has something to select.
    state, batch, step = _setup(lr=0.0, robust_aggregation="median",
                            weighting="uniform")
    mesh = make_mesh(num_clients=8)
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig(learning_rate=0.0))
    state = init_federated_state(jax.random.key(3), mesh, 8, init_fn, tx,
                                 same_init=False)
    before = _leaf0(state)                       # (8, in, out), all distinct
    new_state, _ = step(state, batch)
    after = _leaf0(new_state)
    expected = np.median(before, axis=0)
    for c in range(8):
        np.testing.assert_allclose(after[c], expected, atol=1e-6)


def test_trimmed_mean_matches_numpy_oracle():
    state0, batch, step = _setup(lr=0.0, robust_aggregation="trimmed_mean",
                                 trim_ratio=0.25, weighting="uniform")   # trims 2 of 8 per end
    mesh = make_mesh(num_clients=8)
    init_fn, _ = build_model(ModelConfig(input_dim=6, hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig(learning_rate=0.0))
    state = init_federated_state(jax.random.key(3), mesh, 8, init_fn, tx,
                                 same_init=False)
    before = _leaf0(state)
    new_state, _ = step(state, batch)
    after = _leaf0(new_state)
    srt = np.sort(before, axis=0)
    expected = srt[2:6].mean(axis=0)
    np.testing.assert_allclose(after[0], expected, atol=1e-6)


def test_median_resists_byzantine_minority_mean_does_not():
    # 2 of 8 clients submit 10x sign-flipped updates. The median's global
    # must stay within the honest range; the mean's must leave it.
    kw = dict(byzantine_clients=2, weighting="uniform")
    m_state, batch, m_step = _setup(robust_aggregation="median", **kw)
    a_state, _, a_step = _setup(robust_aggregation="none", **kw)
    h_state, _, h_step = _setup(robust_aggregation="none",
                                weighting="uniform")  # no attack: honest ref

    start = _leaf0(m_state)[0]
    m_state, _ = m_step(m_state, batch)
    a_state, _ = a_step(a_state, batch)
    h_state, _ = h_step(h_state, batch)

    honest_move = np.abs(_leaf0(h_state)[0] - start).max()
    median_move = np.abs(_leaf0(m_state)[0] - start).max()
    mean_move = np.abs(_leaf0(a_state)[0] - start).max()
    # Poisoned mean: 2/8 clients at -10x shift the mean by ~(1-2*11/8)=~-1.75x
    # the honest step; the median ignores the 2 outliers entirely.
    assert mean_move > 1.5 * honest_move
    assert median_move <= 1.5 * honest_move


def test_byzantine_injection_converges_under_median():
    state, batch, step = _setup(robust_aggregation="median",
                                byzantine_clients=2, weighting="uniform")
    for _ in range(20):
        state, metrics = step(state, batch)
    acc = float(metrics["client_mean"]["accuracy"])
    assert np.isfinite(acc) and acc > 0.5


def test_byzantine_composes_with_dp_clipping():
    # Clipping bounds the poisoned update's norm: with clip c, lr 1, the
    # global step is at most c even with every client malicious.
    from fedtpu.ops.server_opt import identity_server_optimizer
    clip = 1e-3
    mesh = make_mesh(num_clients=8)
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig())
    server = identity_server_optimizer()
    state = init_federated_state(jax.random.key(1), mesh, 8, init_fn, tx,
                                 same_init=True, server_opt=server)
    x, y = synthetic_income_like(200, 6, 2)
    packed = pack_clients(x, y, ShardConfig(num_clients=8, shuffle=False))
    shard = client_sharding(mesh)
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    step = build_round_fn(mesh, apply_fn, tx, 2, server_opt=server,
                          dp_clip_norm=clip, byzantine_clients=8,
                          weighting="uniform")
    g0 = jax.tree.map(lambda p: np.asarray(p)[0], state["params"])
    state, _ = step(state, batch)
    g1 = jax.tree.map(lambda p: np.asarray(p)[0], state["params"])
    moved = np.sqrt(sum(np.sum((a - b) ** 2) for a, b in
                        zip(jax.tree.leaves(g1), jax.tree.leaves(g0))))
    assert moved <= clip * (1 + 1e-5)


def test_robust_rejects_bad_combos():
    with pytest.raises(ValueError, match="unknown robust_aggregation"):
        _setup(robust_aggregation="rfa_typo")
    # Coordinate-wise rules are mask-aware now: median + sampling builds.
    # Whole-update rules still need every client's vector present.
    _setup(robust_aggregation="median", weighting="uniform",
           participation_rate=0.5)
    with pytest.raises(ValueError, match="full participation"):
        _setup(robust_aggregation="krum", krum_f=2, weighting="uniform",
               participation_rate=0.5)
    with pytest.raises(ValueError, match="cohort robust path"):
        _setup(robust_aggregation="geometric_median", weighting="uniform",
               participation_rate=0.5)
    with pytest.raises(ValueError, match="unweighted"):
        _setup(robust_aggregation="median")   # default data_size weighting
    with pytest.raises(ValueError, match="plain psum"):
        _setup(robust_aggregation="median", weighting="uniform",
               aggregation="ring")
    with pytest.raises(ValueError, match="plain psum"):
        _setup(robust_aggregation="median", weighting="uniform",
               dp_clip_norm=1.0)
    with pytest.raises(ValueError, match="trim_ratio"):
        _setup(robust_aggregation="trimmed_mean", weighting="uniform",
               trim_ratio=0.5)
    with pytest.raises(ValueError, match="removes all"):
        # 0.49 of 8 clients rounds to 4 per end -> nothing left.
        state, batch, step = _setup(robust_aggregation="trimmed_mean",
                                    weighting="uniform", trim_ratio=0.49)
        step(state, batch)


def test_krum_matches_numpy_oracle():
    # lr=0, distinct inits: krum must pick exactly the client numpy says.
    state, batch, step = _setup(lr=0.0, robust_aggregation="krum",
                                krum_f=2, weighting="uniform")
    mesh = make_mesh(num_clients=8)
    init_fn, _ = build_model(ModelConfig(input_dim=6, hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig(learning_rate=0.0))
    state = init_federated_state(jax.random.key(3), mesh, 8, init_fn, tx,
                                 same_init=False)
    flat = np.concatenate(
        [np.asarray(l).reshape(8, -1)
         for l in jax.tree.leaves(state["params"])], axis=1)
    d2 = ((flat[:, None, :] - flat[None, :, :]) ** 2).sum(-1)
    np.fill_diagonal(d2, np.inf)
    scores = np.sort(d2, axis=1)[:, :8 - 2 - 2].sum(axis=1)
    winner = int(np.argmin(scores))
    expected = _leaf0(state)[winner]

    new_state, _ = step(state, batch)
    after = _leaf0(new_state)
    for c in range(8):
        np.testing.assert_allclose(after[c], expected, atol=1e-6)


def test_krum_resists_byzantine_minority():
    # 2 of 8 poisoned, krum_f=2: the winner must be an honest client, so
    # the global stays within the honest movement range.
    kw = dict(byzantine_clients=2, weighting="uniform")
    k_state, batch, k_step = _setup(robust_aggregation="krum", krum_f=2,
                                    **kw)
    h_state, _, h_step = _setup(robust_aggregation="none",
                                weighting="uniform")
    start = _leaf0(k_state)[0]
    k_state, _ = k_step(k_state, batch)
    h_state, _ = h_step(h_state, batch)
    honest_move = np.abs(_leaf0(h_state)[0] - start).max()
    krum_move = np.abs(_leaf0(k_state)[0] - start).max()
    # A poisoned winner would move ~10x the honest step.
    assert krum_move <= 3 * honest_move


def test_krum_rejects_byzantine_majority_config():
    # Blanchard precondition n >= 2f + 3: krum_f=3 with 8 clients is
    # well-defined arithmetic but the resilience guarantee is void.
    state, batch, step = _setup(robust_aggregation="krum", krum_f=3,
                                weighting="uniform")
    with pytest.raises(ValueError, match="2 \\* krum_f \\+ 3"):
        step(state, batch)


def test_krum_centering_survives_large_common_offset():
    # Distances are shift-invariant; the implementation centers on the
    # client mean before the gram matrix so a large shared model magnitude
    # cannot noise-rank the f32 scores. Same oracle winner with a huge
    # common offset added to every client.
    state, batch, step = _setup(lr=0.0, robust_aggregation="krum",
                                krum_f=2, weighting="uniform")
    mesh = make_mesh(num_clients=8)
    init_fn, _ = build_model(ModelConfig(input_dim=6, hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig(learning_rate=0.0))
    state = init_federated_state(jax.random.key(3), mesh, 8, init_fn, tx,
                                 same_init=False)
    # Add a large identical offset to every client's params (f64 oracle
    # first, from the un-shifted values).
    flat = np.concatenate(
        [np.asarray(l).reshape(8, -1)
         for l in jax.tree.leaves(state["params"])], axis=1).astype(np.float64)
    d2 = ((flat[:, None, :] - flat[None, :, :]) ** 2).sum(-1)
    np.fill_diagonal(d2, np.inf)
    scores = np.sort(d2, axis=1)[:, :8 - 2 - 2].sum(axis=1)
    winner = int(np.argmin(scores))
    expected = _leaf0(state)[winner] + 1e4

    state["params"] = jax.tree.map(lambda p: p + 1e4, state["params"])
    new_state, _ = step(state, batch)
    after = _leaf0(new_state)
    np.testing.assert_allclose(after[0], expected, rtol=1e-6)


def test_geometric_median_matches_numpy_weiszfeld():
    state, batch, step = _setup(lr=0.0,
                                robust_aggregation="geometric_median",
                                weighting="uniform")
    mesh = make_mesh(num_clients=8)
    init_fn, _ = build_model(ModelConfig(input_dim=6, hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig(learning_rate=0.0))
    state = init_federated_state(jax.random.key(3), mesh, 8, init_fn, tx,
                                 same_init=False)
    flat = np.concatenate(
        [np.asarray(l).reshape(8, -1)
         for l in jax.tree.leaves(state["params"])], axis=1)
    u = flat.mean(axis=0)
    from fedtpu.parallel.round import WEISZFELD_ITERS
    for _ in range(WEISZFELD_ITERS):          # same smoothed Weiszfeld
        d = np.sqrt(((flat - u) ** 2).sum(axis=1))
        w = 1.0 / np.maximum(d, 1e-8)
        u = (w[:, None] * flat).sum(axis=0) / w.sum()
    leaf0_size = _leaf0(state)[0].size
    expected = u[:leaf0_size].reshape(_leaf0(state)[0].shape)

    new_state, _ = step(state, batch)
    after = _leaf0(new_state)
    for c in range(8):
        np.testing.assert_allclose(after[c], expected, atol=1e-5)


def test_geometric_median_resists_byzantine_minority():
    kw = dict(byzantine_clients=2, weighting="uniform")
    g_state, batch, g_step = _setup(
        robust_aggregation="geometric_median", **kw)
    h_state, _, h_step = _setup(robust_aggregation="none",
                                weighting="uniform")
    start = _leaf0(g_state)[0]
    g_state, _ = g_step(g_state, batch)
    h_state, _ = h_step(h_state, batch)
    honest_move = np.abs(_leaf0(h_state)[0] - start).max()
    gm_move = np.abs(_leaf0(g_state)[0] - start).max()
    assert gm_move <= 3 * honest_move


def test_trimmed_mean_robustness_needs_enough_trim():
    """Trimmed mean survives k attackers ONLY when trim_ratio * C >= k
    (every poisoned value must fall in the trimmed tail). At C=8 with a
    2-client sign-flip attack: trim_ratio=0.25 (trims 2 per end) converges;
    the default 0.1 (trims 1) keeps one 10x-poisoned update in the mean,
    which drags every round's step backward — accuracy collapses. The
    requirement is documented, not hidden."""
    kw = dict(byzantine_clients=2, weighting="uniform")
    enough_state, batch, enough_step = _setup(
        robust_aggregation="trimmed_mean", trim_ratio=0.25, **kw)  # trims 2
    thin_state, _, thin_step = _setup(
        robust_aggregation="trimmed_mean", trim_ratio=0.1, **kw)   # trims 1

    for _ in range(30):
        enough_state, em = enough_step(enough_state, batch)
        thin_state, tm = thin_step(thin_state, batch)

    acc_enough = float(em["client_mean"]["accuracy"])
    acc_thin = float(tm["client_mean"]["accuracy"])
    assert acc_enough > 0.7      # trim 2 >= 2 attackers: converges
    assert acc_thin < 0.55       # trim 1 < 2 attackers: the attack wins


def test_weiszfeld_iteration_budget_converges():
    """VERDICT r3 weak #5: nothing pinned that the fixed WEISZFELD_ITERS
    budget suffices. Pin two properties of the exact smoothed-Weiszfeld
    recurrence the round program scans (same eps, same update), at a
    small and a model-scale joint-update dimension, under a 25%
    outlier cluster: (a) the sum-of-distances objective is monotone
    non-increasing every iteration (the Weiszfeld guarantee — a
    violation means the implementation regressed), and (b) the iterate
    is numerically stationary by the LAST budgeted iteration (relative
    step < 1e-7), i.e. the budget is sufficient, not merely traditional."""
    from fedtpu.parallel.round import WEISZFELD_ITERS

    rng = np.random.default_rng(0)
    for dim in (64, 120_000):
        flat = rng.normal(size=(8, dim))
        flat[:2] += 50.0 / np.sqrt(dim)   # 2/8 Byzantine-style outliers
        u = flat.mean(axis=0)

        def objective(u):
            return float(np.sqrt(((flat - u) ** 2).sum(axis=1)).sum())

        objs = [objective(u)]
        rel_steps = []
        for _ in range(WEISZFELD_ITERS):
            d = np.sqrt(((flat - u) ** 2).sum(axis=1))
            w = 1.0 / np.maximum(d, 1e-8)
            u_new = (w[:, None] * flat).sum(axis=0) / w.sum()
            rel_steps.append(np.linalg.norm(u_new - u)
                             / max(np.linalg.norm(u_new), 1e-12))
            u = u_new
            objs.append(objective(u))
        assert all(b <= a * (1 + 1e-12) for a, b in zip(objs, objs[1:])), \
            f"objective increased at dim={dim}: {objs}"
        assert rel_steps[-1] < 1e-7, \
            (f"iterate not stationary after {WEISZFELD_ITERS} iterations "
             f"at dim={dim}: relative step {rel_steps[-1]:.2e}")
