"""Resilience subsystem (fedtpu.resilience): FaultPlan determinism and
validation, in-loop injection semantics, divergence rollback (recovery,
budget, exclusion), SIGTERM drain -> Preempted, heartbeat, the
supervisor's exit-code contract (scripted children), and the report's
resilience section. Process-killing end-to-end variants live in
tests/test_chaos_supervised.py."""

import dataclasses
import json
import os
import time

import jax.numpy as jnp
import numpy as np
import pytest

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           OptimConfig, RunConfig, ShardConfig)
from fedtpu.orchestration.loop import run_experiment
from fedtpu.resilience.faults import (FaultInjector, FaultPlan,
                                      corrupt_checkpoint)
from fedtpu.resilience.supervisor import (EXIT_PREEMPTED, Preempted,
                                          read_heartbeat, supervise,
                                          write_heartbeat)

ROUNDS = 6
NAN_PLAN = json.dumps(
    {"seed": 0, "faults": [{"kind": "nan_update", "round": 3,
                            "clients": [1]}]})


def _cfg(rounds=ROUNDS, **run_kw):
    return ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=128),
        shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=rounds),
        run=RunConfig(**run_kw),
    )


@pytest.fixture(scope="module")
def baseline():
    """One uninterrupted reference run shared by the exact-recovery
    assertions below."""
    return run_experiment(_cfg(), verbose=False)


# ------------------------------------------------------------- FaultPlan
def test_plan_spec_forms_are_identical(tmp_path):
    raw = {"seed": 3, "faults": [
        {"kind": "client_dropout", "round": 2, "clients": [1, 3]},
        {"kind": "straggler", "round": 1, "clients": [0], "delay_s": 0.5}]}
    p = tmp_path / "plan.json"
    p.write_text(json.dumps(raw))
    from_dict = FaultPlan.load(raw, num_clients=8, rounds=10)
    from_inline = FaultPlan.load(json.dumps(raw), num_clients=8, rounds=10)
    from_file = FaultPlan.load(str(p), num_clients=8, rounds=10)
    assert from_dict == from_inline == from_file
    assert len(from_dict.digest) == 16
    # Materialized plans come back sorted by round.
    assert [f.round for f in from_dict.faults] == [1, 2]


def test_plan_probabilistic_is_a_pure_function_of_the_seed():
    spec = {"seed": 7, "faults": [
        {"kind": "straggler", "probability": 0.3, "rounds": [1, 50],
         "clients": [2], "delay_s": 0.01}]}
    a = FaultPlan.load(spec, num_clients=8, rounds=50)
    b = FaultPlan.load(spec, num_clients=8, rounds=50)
    assert a == b and a.faults           # same seed: identical schedule
    c = FaultPlan.load({**spec, "seed": 8}, num_clients=8, rounds=50)
    assert c.digest != a.digest          # seed is part of the schedule


@pytest.mark.parametrize("entry,match", [
    ({"kind": "meteor", "round": 1}, "unknown kind"),
    ({"kind": "straggler", "clients": [0]}, "'round' or 'probability'"),
    ({"kind": "nan_update", "round": 1, "clients": [99]}, "outside"),
    ({"kind": "client_dropout", "round": 1}, "needs 'clients'"),
    ({"kind": "process_kill", "round": 1, "signal": "SIGSTOP"}, "signal"),
    ({"kind": "straggler", "round": 1, "clients": [0]}, "delay_s"),
    ({"kind": "nan_update", "round": 99, "clients": [0]}, "outside"),
    ({"kind": "nan_update", "probability": 1.5, "clients": [0]},
     "probability"),
])
def test_plan_validation_rejects(entry, match):
    with pytest.raises(ValueError, match=match):
        FaultPlan.load({"faults": [entry]}, num_clients=8, rounds=10)


# ---------------------------------------------------------- FaultInjector
def _injector(spec, restart_count=0):
    plan = FaultPlan.load(spec, num_clients=8, rounds=20)
    return FaultInjector(plan, restart_count=restart_count)


def test_chunk_limit_isolates_fault_rounds():
    inj = _injector({"faults": [{"kind": "straggler", "round": 4,
                                 "clients": [0], "delay_s": 0.001}]})
    # 0-based round 3 carries the fault: a chunk from round 0 must stop
    # short of it, a chunk AT it must be width 1, past it is unlimited.
    assert inj.chunk_limit(0, 8) == 3
    assert inj.chunk_limit(3, 8) == 1
    assert inj.chunk_limit(4, 8) == 8
    inj.pre_round(3, {}, {})             # consumes the fault (sleeps 1ms)
    assert inj.chunk_limit(0, 8) == 8    # nothing armed anymore


def test_once_kinds_disarm_on_restart():
    spec = {"faults": [
        {"kind": "process_kill", "round": 5},
        {"kind": "ckpt_corrupt", "round": 6},
        {"kind": "client_dropout", "round": 7, "clients": [2]}]}
    assert _injector(spec, restart_count=0).armed_count == 3
    # A supervised restart replays the kill window: only the dropout
    # survives (re-arming the kill would loop kill->restart forever).
    assert _injector(spec, restart_count=1).armed_count == 1


def test_dropout_zeroes_then_restores_the_original_mask():
    inj = _injector({"faults": [{"kind": "client_dropout", "round": 1,
                                 "clients": [2, 5]}]})
    mask = jnp.ones((8, 16))
    batch = {"mask": mask}
    inj.pre_round(0, {}, batch)
    got = np.asarray(batch["mask"])
    assert got[2].sum() == 0 and got[5].sum() == 0 and got[0].sum() == 16
    inj.post_round(0, batch)
    assert batch["mask"] is mask         # the ORIGINAL array object


def test_exclude_drops_offenders_future_faults():
    inj = _injector({"faults": [
        {"kind": "nan_update", "round": 3, "clients": [1]},
        {"kind": "nan_update", "round": 5, "clients": [1]},
        {"kind": "straggler", "round": 6, "clients": [0],
         "delay_s": 0.01}]})
    inj.exclude([1])
    # Client 1 left the federation: its NaN faults are gone, client 0's
    # straggler stays.
    assert inj.armed_count == 1


# ---------------------------------------------- run_experiment integration
def test_nan_fault_halts_by_default(tmp_path):
    cfg = _cfg(fault_plan=NAN_PLAN,
               checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2)
    res = run_experiment(cfg, verbose=False)
    assert res.diverged and res.rounds_run == 3


def test_nan_rollback_recovers_bitwise(tmp_path, baseline):
    cfg = _cfg(fault_plan=NAN_PLAN, on_divergence="rollback",
               checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2)
    res = run_experiment(cfg, verbose=False)
    assert not res.diverged and res.rounds_run == ROUNDS
    # The replay is round-keyed: recovery is exact, not approximate.
    for k in baseline.global_metrics:
        np.testing.assert_array_equal(res.global_metrics[k],
                                      baseline.global_metrics[k])


def test_rollback_budget_exhausted_halts(tmp_path):
    plan = json.dumps({"seed": 0, "faults": [
        {"kind": "nan_update", "round": 3, "clients": [1]},
        {"kind": "nan_update", "round": 4, "clients": [2]}]})
    cfg = _cfg(fault_plan=plan, on_divergence="rollback",
               rollback_retries=1,
               checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2)
    res = run_experiment(cfg, verbose=False)
    # Retry 1 replays round 3 cleanly; round 4's fresh NaN exceeds the
    # run budget -> the ordinary halt path.
    assert res.diverged and res.rounds_run == 4


def test_rollback_exclude_removes_offender(tmp_path):
    ev = str(tmp_path / "ev.jsonl")
    from fedtpu.config import TelemetryConfig
    cfg = _cfg(fault_plan=NAN_PLAN, on_divergence="rollback",
               rollback_exclude=True,
               checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
               telemetry=TelemetryConfig(events_path=ev))
    res = run_experiment(cfg, verbose=False)
    assert not res.diverged and res.rounds_run == ROUNDS
    from fedtpu.telemetry.report import aggregate, load_events
    agg = aggregate(*load_events(ev))
    assert agg["resilience"]["exclusions"][0]["clients"] == [1]
    assert len(agg["resilience"]["rollbacks"]) == 1
    assert agg["counters"]["clients_excluded"] == 1


def test_preempt_drains_checkpoint_and_resume_matches(tmp_path, baseline):
    ck = str(tmp_path / "ck")
    plan = json.dumps({"seed": 0, "faults": [
        {"kind": "process_kill", "round": 3, "signal": "SIGTERM"}]})
    cfg = _cfg(fault_plan=plan, checkpoint_dir=ck, checkpoint_every=2)
    with pytest.raises(Preempted) as exc:
        run_experiment(cfg, verbose=False)
    assert exc.value.round == 3
    from fedtpu.orchestration.checkpoint import latest_step
    assert latest_step(ck) == 3          # the drain's checkpoint
    # Resume finishes the job with exactly the uninterrupted history
    # (the drained fault was consumed; resume starts past its round).
    res = run_experiment(cfg, verbose=False, resume=True)
    assert res.rounds_run == ROUNDS and not res.diverged
    for k in baseline.global_metrics:
        np.testing.assert_array_equal(res.global_metrics[k],
                                      baseline.global_metrics[k])


def test_run_writes_heartbeat(tmp_path):
    hb = str(tmp_path / "hb.json")
    res = run_experiment(_cfg(rounds=2, heartbeat_file=hb), verbose=False)
    beat = read_heartbeat(hb)
    assert res.rounds_run == 2
    assert beat["status"] == "done" and beat["round"] == 2
    assert beat["restarts"] == 0 and beat["pid"] == os.getpid()


@pytest.mark.parametrize("run_kw,match", [
    ({"on_divergence": "retry"}, "on_divergence"),
    ({"on_divergence": "rollback"}, "checkpoint"),
    ({"on_divergence": "rollback", "checkpoint_dir": "d",
      "checkpoint_every": 2, "pipelined_stop": True}, "pipelined"),
    ({"rollback_exclude": True}, "rollback_exclude"),
])
def test_invalid_resilience_configs_rejected(run_kw, match):
    with pytest.raises(ValueError, match=match):
        run_experiment(_cfg(**run_kw), verbose=False)


def test_corrupt_checkpoint_fallback_restores_previous_round(tmp_path):
    ck = str(tmp_path / "ck")
    cfg = _cfg(rounds=4, checkpoint_dir=ck, checkpoint_every=1)
    run_experiment(cfg, verbose=False)
    from fedtpu.orchestration.checkpoint import (complete_steps,
                                                 load_checkpoint_fallback)
    from fedtpu.orchestration.loop import build_experiment
    assert corrupt_checkpoint(ck) == 4
    assert complete_steps(ck)[-1] == 4   # still LOOKS committed
    exp = build_experiment(cfg)
    with pytest.warns(RuntimeWarning, match="round 4 failed to restore"):
        _, _, step = load_checkpoint_fallback(ck, state_like=exp.state)
    assert step == 3                     # newest round that actually loads


# -------------------------------------------------------------- heartbeat
def test_heartbeat_roundtrip_and_garbage(tmp_path):
    hb = str(tmp_path / "hb.json")
    assert read_heartbeat(hb) is None                    # missing
    write_heartbeat(hb, status="running", round=7)
    beat = read_heartbeat(hb)
    assert beat["status"] == "running" and beat["round"] == 7
    assert beat["pid"] == os.getpid() and beat["time"] <= time.time()
    with open(hb, "w") as fh:
        fh.write('{"torn')
    assert read_heartbeat(hb) is None                    # mid-crash junk
    assert not [f for f in os.listdir(tmp_path)
                if ".tmp." in f]                         # atomic: no litter


# ----------------------------------------------- supervisor (scripted kids)
# Children are tiny `python -c` scripts via the test-only _cmd_prefix:
# each run appends its FEDTPU_RESTARTS to a log file and exits per script,
# so every assertion below reads the actual launch sequence.
def _script(body):
    return ("import os, sys\n"
            "log = sys.argv[1]\n"
            "n = sum(1 for _ in open(log)) if os.path.exists(log) else 0\n"
            "open(log, 'a').write(os.environ['FEDTPU_RESTARTS'] + '\\n')\n"
            + body)


def _supervise(tmp_path, body, **kw):
    log = tmp_path / "launches.txt"
    kw.setdefault("backoff_base", 0.01)
    kw.setdefault("backoff_max", 0.05)
    rc = supervise([str(log)],
                   events=str(tmp_path / "ev.jsonl"), verbose=False,
                   _cmd_prefix=[__import__("sys").executable, "-c",
                                _script(body)], **kw)
    launches = (log.read_text().splitlines()
                if log.exists() else [])
    return rc, launches


def test_supervisor_restarts_crash_then_succeeds(tmp_path):
    rc, launches = _supervise(tmp_path,
                              "sys.exit(0 if n >= 1 else 9)",
                              max_restarts=2)
    assert rc == 0
    assert launches == ["0", "1"]        # FEDTPU_RESTARTS per launch


def test_supervisor_never_restarts_divergence(tmp_path):
    rc, launches = _supervise(tmp_path, "sys.exit(3)", max_restarts=5)
    assert rc == 3 and launches == ["0"]


def test_supervisor_budget_exhausted_returns_last_rc(tmp_path):
    rc, launches = _supervise(tmp_path, "sys.exit(9)", max_restarts=1)
    assert rc == 9 and launches == ["0", "1"]


def test_supervisor_preemption_restarts_without_backoff(tmp_path):
    t0 = time.time()
    rc, launches = _supervise(
        tmp_path, f"sys.exit(0 if n >= 1 else {EXIT_PREEMPTED})",
        max_restarts=2, backoff_base=30.0)
    # A 30 s crash backoff would blow this bound; preemption skips it.
    assert rc == 0 and launches == ["0", "1"]
    assert time.time() - t0 < 20


def test_supervisor_hang_detection_kills_stale_child(tmp_path):
    hb = str(tmp_path / "hb.json")
    write_heartbeat(hb, status="running", round=1)
    rc, launches = _supervise(
        tmp_path, "import time\ntime.sleep(60)",
        max_restarts=0, hang_timeout=1.0, heartbeat=hb)
    assert rc != 0 and launches == ["0"]   # killed, budget 0 -> give up
    ev = [json.loads(l) for l in open(tmp_path / "ev.jsonl")]
    exits = [e for e in ev if e["kind"] == "child_exit"]
    assert exits and exits[-1]["payload"]["hung"] is True


# ------------------------------------------------------------------ report
def test_report_aggregates_resilience_timeline(tmp_path):
    ev = str(tmp_path / "ev.jsonl")
    from fedtpu.telemetry import make_tracer
    tracer = make_tracer(ev)
    tracer.event("manifest", config_hash="c", restarts=1,
                 fault_plan="abcd1234")
    tracer.event("fault", round=4, fault="process_kill", fault_round=4,
                 signal="SIGKILL", process_index=0)
    tracer.event("resume", round=2)
    tracer.event("rollback", round=5, restored_round=4, attempt=1,
                 reason="loss/metrics at round 5")
    tracer.event("exclusion", round=4, clients=[2])
    tracer.event("preempted", round=6)
    tracer.event("restart", restarts=1, rc=-9, hung=False, backoff_s=1.0,
                 resume=True)
    tracer.event("child_exit", rc=-9, restarts=0, hung=False)
    tracer.event("supervisor_exit", rc=0, reason="done", restarts=1)
    tracer.close()
    from fedtpu.telemetry.report import aggregate, load_events, render_text
    agg = aggregate(*load_events(ev))
    res = agg["resilience"]
    assert res["faults"][0]["fault"] == "process_kill"
    assert res["rollbacks"][0]["restored_round"] == 4
    assert res["exclusions"][0]["clients"] == [2]
    assert res["restarts"] == 1 and res["child_exit_codes"] == [-9]
    assert res["preempted_rounds"] == [6] and res["resume_rounds"] == [2]
    assert res["supervisor_exit"]["reason"] == "done"
    assert agg["manifest"]["restarts"] == 1
    assert agg["manifest"]["fault_plan"] == "abcd1234"
    text = render_text(agg)
    assert "fault process_kill @ round 4" in text
    assert "rollback @ round 5 -> restored round 4" in text
    assert "supervisor restarts: 1" in text
