"""Resilience subsystem (fedtpu.resilience): FaultPlan determinism and
validation, in-loop injection semantics, divergence rollback (recovery,
budget, exclusion), SIGTERM drain -> Preempted, heartbeat, the
supervisor's exit-code contract (scripted children), and the report's
resilience section. Process-killing end-to-end variants live in
tests/test_chaos_supervised.py."""

import dataclasses
import json
import os
import sys
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           OptimConfig, RunConfig, ShardConfig)
from fedtpu.orchestration.loop import run_experiment
from fedtpu.resilience.faults import (FaultInjector, FaultPlan,
                                      corrupt_checkpoint)
from fedtpu.resilience.distributed import (NO_CHECKPOINT, CollectiveWatchdog,
                                           agree_resume_step,
                                           heartbeat_path_for,
                                           publish_local_step)
from fedtpu.resilience.supervisor import (EXIT_PREEMPTED, Preempted,
                                          read_heartbeat, supervise,
                                          supervise_gang, write_heartbeat)

ROUNDS = 6
NAN_PLAN = json.dumps(
    {"seed": 0, "faults": [{"kind": "nan_update", "round": 3,
                            "clients": [1]}]})


def _cfg(rounds=ROUNDS, **run_kw):
    return ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=128),
        shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=rounds),
        run=RunConfig(**run_kw),
    )


@pytest.fixture(scope="module")
def baseline():
    """One uninterrupted reference run shared by the exact-recovery
    assertions below."""
    return run_experiment(_cfg(), verbose=False)


# ------------------------------------------------------------- FaultPlan
def test_plan_spec_forms_are_identical(tmp_path):
    raw = {"seed": 3, "faults": [
        {"kind": "client_dropout", "round": 2, "clients": [1, 3]},
        {"kind": "straggler", "round": 1, "clients": [0], "delay_s": 0.5}]}
    p = tmp_path / "plan.json"
    p.write_text(json.dumps(raw))
    from_dict = FaultPlan.load(raw, num_clients=8, rounds=10)
    from_inline = FaultPlan.load(json.dumps(raw), num_clients=8, rounds=10)
    from_file = FaultPlan.load(str(p), num_clients=8, rounds=10)
    assert from_dict == from_inline == from_file
    assert len(from_dict.digest) == 16
    # Materialized plans come back sorted by round.
    assert [f.round for f in from_dict.faults] == [1, 2]


def test_plan_probabilistic_is_a_pure_function_of_the_seed():
    spec = {"seed": 7, "faults": [
        {"kind": "straggler", "probability": 0.3, "rounds": [1, 50],
         "clients": [2], "delay_s": 0.01}]}
    a = FaultPlan.load(spec, num_clients=8, rounds=50)
    b = FaultPlan.load(spec, num_clients=8, rounds=50)
    assert a == b and a.faults           # same seed: identical schedule
    c = FaultPlan.load({**spec, "seed": 8}, num_clients=8, rounds=50)
    assert c.digest != a.digest          # seed is part of the schedule


@pytest.mark.parametrize("entry,match", [
    ({"kind": "meteor", "round": 1}, "unknown kind"),
    ({"kind": "straggler", "clients": [0]}, "'round' or 'probability'"),
    ({"kind": "nan_update", "round": 1, "clients": [99]}, "outside"),
    ({"kind": "client_dropout", "round": 1}, "needs 'clients'"),
    ({"kind": "process_kill", "round": 1, "signal": "SIGSTOP"}, "signal"),
    ({"kind": "straggler", "round": 1, "clients": [0]}, "delay_s"),
    ({"kind": "nan_update", "round": 99, "clients": [0]}, "outside"),
    ({"kind": "nan_update", "probability": 1.5, "clients": [0]},
     "probability"),
])
def test_plan_validation_rejects(entry, match):
    with pytest.raises(ValueError, match=match):
        FaultPlan.load({"faults": [entry]}, num_clients=8, rounds=10)


# ---------------------------------------------------------- FaultInjector
def _injector(spec, restart_count=0):
    plan = FaultPlan.load(spec, num_clients=8, rounds=20)
    return FaultInjector(plan, restart_count=restart_count)


def test_chunk_limit_isolates_fault_rounds():
    inj = _injector({"faults": [{"kind": "straggler", "round": 4,
                                 "clients": [0], "delay_s": 0.001}]})
    # 0-based round 3 carries the fault: a chunk from round 0 must stop
    # short of it, a chunk AT it must be width 1, past it is unlimited.
    assert inj.chunk_limit(0, 8) == 3
    assert inj.chunk_limit(3, 8) == 1
    assert inj.chunk_limit(4, 8) == 8
    inj.pre_round(3, {}, {})             # consumes the fault (sleeps 1ms)
    assert inj.chunk_limit(0, 8) == 8    # nothing armed anymore


def test_once_kinds_disarm_on_restart():
    spec = {"faults": [
        {"kind": "process_kill", "round": 5},
        {"kind": "ckpt_corrupt", "round": 6},
        {"kind": "client_dropout", "round": 7, "clients": [2]}]}
    assert _injector(spec, restart_count=0).armed_count == 3
    # A supervised restart replays the kill window: only the dropout
    # survives (re-arming the kill would loop kill->restart forever).
    assert _injector(spec, restart_count=1).armed_count == 1


def test_dropout_zeroes_then_restores_the_original_mask():
    inj = _injector({"faults": [{"kind": "client_dropout", "round": 1,
                                 "clients": [2, 5]}]})
    mask = jnp.ones((8, 16))
    batch = {"mask": mask}
    inj.pre_round(0, {}, batch)
    got = np.asarray(batch["mask"])
    assert got[2].sum() == 0 and got[5].sum() == 0 and got[0].sum() == 16
    inj.post_round(0, batch)
    assert batch["mask"] is mask         # the ORIGINAL array object


def test_exclude_drops_offenders_future_faults():
    inj = _injector({"faults": [
        {"kind": "nan_update", "round": 3, "clients": [1]},
        {"kind": "nan_update", "round": 5, "clients": [1]},
        {"kind": "straggler", "round": 6, "clients": [0],
         "delay_s": 0.01}]})
    inj.exclude([1])
    # Client 1 left the federation: its NaN faults are gone, client 0's
    # straggler stays.
    assert inj.armed_count == 1


# ---------------------------------------------- run_experiment integration
def test_nan_fault_halts_by_default(tmp_path):
    cfg = _cfg(fault_plan=NAN_PLAN,
               checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2)
    res = run_experiment(cfg, verbose=False)
    assert res.diverged and res.rounds_run == 3


def test_nan_rollback_recovers_bitwise(tmp_path, baseline):
    cfg = _cfg(fault_plan=NAN_PLAN, on_divergence="rollback",
               checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2)
    res = run_experiment(cfg, verbose=False)
    assert not res.diverged and res.rounds_run == ROUNDS
    # The replay is round-keyed: recovery is exact, not approximate.
    for k in baseline.global_metrics:
        np.testing.assert_array_equal(res.global_metrics[k],
                                      baseline.global_metrics[k])


def test_rollback_budget_exhausted_halts(tmp_path):
    plan = json.dumps({"seed": 0, "faults": [
        {"kind": "nan_update", "round": 3, "clients": [1]},
        {"kind": "nan_update", "round": 4, "clients": [2]}]})
    cfg = _cfg(fault_plan=plan, on_divergence="rollback",
               rollback_retries=1,
               checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2)
    res = run_experiment(cfg, verbose=False)
    # Retry 1 replays round 3 cleanly; round 4's fresh NaN exceeds the
    # run budget -> the ordinary halt path.
    assert res.diverged and res.rounds_run == 4


def test_rollback_exclude_removes_offender(tmp_path):
    ev = str(tmp_path / "ev.jsonl")
    from fedtpu.config import TelemetryConfig
    cfg = _cfg(fault_plan=NAN_PLAN, on_divergence="rollback",
               rollback_exclude=True,
               checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
               telemetry=TelemetryConfig(events_path=ev))
    res = run_experiment(cfg, verbose=False)
    assert not res.diverged and res.rounds_run == ROUNDS
    from fedtpu.telemetry.report import aggregate, load_events
    agg = aggregate(*load_events(ev))
    assert agg["resilience"]["exclusions"][0]["clients"] == [1]
    assert len(agg["resilience"]["rollbacks"]) == 1
    assert agg["counters"]["clients_excluded"] == 1


def test_preempt_drains_checkpoint_and_resume_matches(tmp_path, baseline):
    ck = str(tmp_path / "ck")
    plan = json.dumps({"seed": 0, "faults": [
        {"kind": "process_kill", "round": 3, "signal": "SIGTERM"}]})
    cfg = _cfg(fault_plan=plan, checkpoint_dir=ck, checkpoint_every=2)
    with pytest.raises(Preempted) as exc:
        run_experiment(cfg, verbose=False)
    assert exc.value.round == 3
    from fedtpu.orchestration.checkpoint import latest_step
    assert latest_step(ck) == 3          # the drain's checkpoint
    # Resume finishes the job with exactly the uninterrupted history
    # (the drained fault was consumed; resume starts past its round).
    res = run_experiment(cfg, verbose=False, resume=True)
    assert res.rounds_run == ROUNDS and not res.diverged
    for k in baseline.global_metrics:
        np.testing.assert_array_equal(res.global_metrics[k],
                                      baseline.global_metrics[k])


def test_run_writes_heartbeat(tmp_path):
    hb = str(tmp_path / "hb.json")
    res = run_experiment(_cfg(rounds=2, heartbeat_file=hb), verbose=False)
    beat = read_heartbeat(hb)
    assert res.rounds_run == 2
    assert beat["status"] == "done" and beat["round"] == 2
    assert beat["restarts"] == 0 and beat["pid"] == os.getpid()


@pytest.mark.parametrize("run_kw,match", [
    ({"on_divergence": "retry"}, "on_divergence"),
    ({"on_divergence": "rollback"}, "checkpoint"),
    ({"on_divergence": "rollback", "checkpoint_dir": "d",
      "checkpoint_every": 2, "pipelined_stop": True}, "pipelined"),
    ({"rollback_exclude": True}, "rollback_exclude"),
])
def test_invalid_resilience_configs_rejected(run_kw, match):
    with pytest.raises(ValueError, match=match):
        run_experiment(_cfg(**run_kw), verbose=False)


def test_corrupt_checkpoint_fallback_restores_previous_round(tmp_path):
    ck = str(tmp_path / "ck")
    cfg = _cfg(rounds=4, checkpoint_dir=ck, checkpoint_every=1)
    run_experiment(cfg, verbose=False)
    from fedtpu.orchestration.checkpoint import (complete_steps,
                                                 load_checkpoint_fallback)
    from fedtpu.orchestration.loop import build_experiment
    assert corrupt_checkpoint(ck) == 4
    assert complete_steps(ck)[-1] == 4   # still LOOKS committed
    exp = build_experiment(cfg)
    with pytest.warns(RuntimeWarning, match="round 4 failed to restore"):
        _, _, step = load_checkpoint_fallback(ck, state_like=exp.state)
    assert step == 3                     # newest round that actually loads


# -------------------------------------------------------------- heartbeat
def test_heartbeat_roundtrip_and_garbage(tmp_path):
    hb = str(tmp_path / "hb.json")
    assert read_heartbeat(hb) is None                    # missing
    write_heartbeat(hb, status="running", round=7)
    beat = read_heartbeat(hb)
    assert beat["status"] == "running" and beat["round"] == 7
    assert beat["pid"] == os.getpid() and beat["time"] <= time.time()
    with open(hb, "w") as fh:
        fh.write('{"torn')
    assert read_heartbeat(hb) is None                    # mid-crash junk
    assert not [f for f in os.listdir(tmp_path)
                if ".tmp." in f]                         # atomic: no litter


# ----------------------------------------------- supervisor (scripted kids)
# Children are tiny `python -c` scripts via the test-only _cmd_prefix:
# each run appends its FEDTPU_RESTARTS to a log file and exits per script,
# so every assertion below reads the actual launch sequence.
def _script(body):
    return ("import os, sys\n"
            "log = sys.argv[1]\n"
            "n = sum(1 for _ in open(log)) if os.path.exists(log) else 0\n"
            "open(log, 'a').write(os.environ['FEDTPU_RESTARTS'] + '\\n')\n"
            + body)


def _supervise(tmp_path, body, **kw):
    log = tmp_path / "launches.txt"
    kw.setdefault("backoff_base", 0.01)
    kw.setdefault("backoff_max", 0.05)
    rc = supervise([str(log)],
                   events=str(tmp_path / "ev.jsonl"), verbose=False,
                   _cmd_prefix=[__import__("sys").executable, "-c",
                                _script(body)], **kw)
    launches = (log.read_text().splitlines()
                if log.exists() else [])
    return rc, launches


def test_supervisor_restarts_crash_then_succeeds(tmp_path):
    rc, launches = _supervise(tmp_path,
                              "sys.exit(0 if n >= 1 else 9)",
                              max_restarts=2)
    assert rc == 0
    assert launches == ["0", "1"]        # FEDTPU_RESTARTS per launch


def test_supervisor_never_restarts_divergence(tmp_path):
    rc, launches = _supervise(tmp_path, "sys.exit(3)", max_restarts=5)
    assert rc == 3 and launches == ["0"]


def test_supervisor_budget_exhausted_returns_last_rc(tmp_path):
    rc, launches = _supervise(tmp_path, "sys.exit(9)", max_restarts=1)
    assert rc == 9 and launches == ["0", "1"]


def test_supervisor_preemption_restarts_without_backoff(tmp_path):
    t0 = time.time()
    rc, launches = _supervise(
        tmp_path, f"sys.exit(0 if n >= 1 else {EXIT_PREEMPTED})",
        max_restarts=2, backoff_base=30.0)
    # A 30 s crash backoff would blow this bound; preemption skips it.
    assert rc == 0 and launches == ["0", "1"]
    assert time.time() - t0 < 20


def test_supervisor_hang_detection_kills_stale_child(tmp_path):
    hb = str(tmp_path / "hb.json")
    write_heartbeat(hb, status="running", round=1)
    rc, launches = _supervise(
        tmp_path, "import time\ntime.sleep(60)",
        max_restarts=0, hang_timeout=1.0, heartbeat=hb)
    assert rc != 0 and launches == ["0"]   # killed, budget 0 -> give up
    ev = [json.loads(l) for l in open(tmp_path / "ev.jsonl")]
    exits = [e for e in ev if e["kind"] == "child_exit"]
    assert exits and exits[-1]["payload"]["hung"] is True


# ------------------------------------------------------ collective watchdog
def test_watchdog_fires_on_stuck_guard(tmp_path):
    ev = str(tmp_path / "ev.jsonl")
    hb = str(tmp_path / "hb.json")
    fired = []
    wd = CollectiveWatchdog(0.2, events_path=ev, process_index=1,
                            heartbeat=hb, restart_count=2, poll=0.05,
                            _abort=fired.append).start()
    with wd.guard("chunk_fetch", 4):
        deadline = time.time() + 10
        while not wd.fired and time.time() < deadline:
            time.sleep(0.02)         # "hung" main thread, but interruptible
    wd.stop()
    assert fired == [EXIT_PREEMPTED] and wd.fired
    e = json.loads(open(ev).read().splitlines()[-1])
    assert e["kind"] == "collective_hang" and e["round"] == 4
    assert e["payload"]["phase"] == "chunk_fetch"
    assert e["payload"]["process"] == 1 and e["payload"]["restarts"] == 2
    # waited is strictly > timeout at fire time, but the event rounds it
    # to 3 decimals — which can land exactly ON the timeout.
    assert e["payload"]["waited_s"] >= 0.2
    assert read_heartbeat(hb)["status"] == "collective_hang"


def test_watchdog_tolerates_fast_guards_and_idle():
    fired = []
    wd = CollectiveWatchdog(0.5, poll=0.02, _abort=fired.append).start()
    for rnd in range(5):             # many short fetches, each < timeout
        with wd.guard("chunk_fetch", rnd):
            time.sleep(0.03)
    time.sleep(0.6)                  # disarmed idle never counts as hung
    wd.stop()
    assert not fired and not wd.fired


def test_watchdog_rejects_nonpositive_timeout():
    with pytest.raises(ValueError, match="collective_timeout"):
        CollectiveWatchdog(0.0)


# ----------------------------------------------------- checkpoint agreement
def test_agreement_restores_minimum_common_step(tmp_path):
    ck = str(tmp_path / "ck")
    publish_local_step(ck, 1, 6, restart_count=1)
    publish_local_step(ck, 2, 4, restart_count=1)
    assert agree_resume_step(ck, 0, 3, 8, restart_count=1, timeout=5) == 4
    # The protocol dir is invisible to checkpoint scanning.
    from fedtpu.orchestration.checkpoint import latest_step
    assert latest_step(ck) is None


def test_agreement_no_checkpoint_means_consensual_fresh_start(tmp_path):
    ck = str(tmp_path / "ck")
    publish_local_step(ck, 1, None)
    assert agree_resume_step(ck, 0, 2, 7, timeout=5) == NO_CHECKPOINT


def test_agreement_ignores_stale_generation_and_times_out(tmp_path):
    ck = str(tmp_path / "ck")
    publish_local_step(ck, 1, 6, restart_count=0)    # previous launch
    with pytest.raises(TimeoutError, match=r"process\(es\) \[1\]"):
        agree_resume_step(ck, 0, 2, 6, restart_count=1, timeout=0.3,
                          poll=0.02)


def test_agreement_waits_for_late_peer(tmp_path):
    ck = str(tmp_path / "ck")
    t = threading.Timer(0.3, publish_local_step, args=(ck, 1, 2, 0))
    t.start()
    try:
        assert agree_resume_step(ck, 0, 2, 5, timeout=10, poll=0.02) == 2
    finally:
        t.cancel()


def test_agreement_ignores_same_count_leftovers_from_previous_launch(
        tmp_path):
    # The split-brain hole the launch nonce closes: a previous MANUAL
    # launch (no gang parent) ran at generation 0 and left its protocol
    # file behind; this launch is ALSO generation 0, so the restart
    # count alone cannot tell the stale record from a fresh one. The
    # launch tag must — reading the leftover step would restore a
    # different round than the peer that arrives after the overwrite.
    ck = str(tmp_path / "ck")
    publish_local_step(ck, 1, 4, restart_count=0, launch_id="prev")
    with pytest.raises(TimeoutError, match="launch"):
        agree_resume_step(ck, 0, 2, 10, restart_count=0, timeout=0.3,
                          poll=0.02, launch_id="cur")


def test_agreement_process0_clears_previous_launch_records(tmp_path):
    ck = str(tmp_path / "ck")
    # Leftover from a previously LARGER gang: no current process index
    # would ever overwrite p5.json, so only cleanup removes it.
    stale = publish_local_step(ck, 5, 9, restart_count=2, launch_id="old")
    t = threading.Timer(0.2, publish_local_step, args=(ck, 1, 3, 0),
                        kwargs={"launch_id": "new"})
    t.start()
    try:
        assert agree_resume_step(ck, 0, 2, 7, timeout=10, poll=0.02,
                                 launch_id="new") == 3
    finally:
        t.cancel()
    assert not os.path.exists(stale)
    # Current-launch records survive the cleanup.
    assert agree_resume_step(ck, 0, 2, 7, timeout=5, poll=0.02,
                             launch_id="new") == 3


def test_heartbeat_path_per_process():
    assert heartbeat_path_for("/x/hb.json", 0) == "/x/hb.json"
    assert heartbeat_path_for("/x/hb.json", 3) == "/x/hb.json.p3"


# ------------------------------------------------- collective_hang faults
def test_plan_collective_hang_payload_and_once_semantics():
    spec = {"faults": [{"kind": "collective_hang", "round": 2,
                        "process_index": 1, "delay_s": 0.5}]}
    plan = FaultPlan.load(spec, num_clients=8, rounds=10)
    assert plan.faults[0].payload() == {
        "fault": "collective_hang", "fault_round": 2,
        "process_index": 1, "delay_s": 0.5}
    # Once-only, like process_kill: re-arming on a restarted run would
    # wedge -> restart -> wedge forever.
    assert FaultInjector(plan, restart_count=1).armed_count == 0


def test_collective_hang_wedges_only_the_matching_process():
    spec = {"faults": [{"kind": "collective_hang", "round": 1,
                        "process_index": 1, "delay_s": 30.0}]}
    plan = FaultPlan.load(spec, num_clients=8, rounds=10)
    t0 = time.time()
    FaultInjector(plan, process_index=0).pre_round(0, {}, {})
    assert time.time() - t0 < 5      # not this process: no sleep
    bcast = {"faults": [{"kind": "collective_hang", "round": 1,
                         "process_index": -1, "delay_s": 0.2}]}
    plan = FaultPlan.load(bcast, num_clients=8, rounds=10)
    t0 = time.time()
    FaultInjector(plan, process_index=5).pre_round(0, {}, {})
    assert time.time() - t0 >= 0.2   # -1 broadcasts to every process


# --------------------------------------------- gang supervisor (scripted)
# Same scripted-children trick as the single-process supervisor tests
# above, but each child logs "<FEDTPU_RESTARTS> <FEDTPU_COORDINATOR>
# <FEDTPU_LAUNCH_ID>" to its own per-process file so the assertions can
# read the whole launch matrix (who ran, in which generation, against
# which coordinator, under which launch identity).
def _gang_script(body):
    return ("import os, sys, time\n"
            "log = sys.argv[1]\n"
            "pid = os.environ.get('FEDTPU_PROCESS_ID', '')\n"
            "gen = os.environ['FEDTPU_RESTARTS']\n"
            "coord = os.environ.get('FEDTPU_COORDINATOR', '')\n"
            "launch = os.environ.get('FEDTPU_LAUNCH_ID', '')\n"
            "open(log + '.p' + (pid or '0'), 'a').write("
            "gen + ' ' + coord + ' ' + launch + '\\n')\n"
            + body)


def _gang(tmp_path, body, num_processes=2, **kw):
    log = tmp_path / "gang"
    kw.setdefault("backoff_base", 0.01)
    kw.setdefault("backoff_max", 0.05)
    kw.setdefault("grace", 3.0)
    rc = supervise_gang([str(log)], num_processes=num_processes,
                        events=str(tmp_path / "gev.jsonl"), verbose=False,
                        _cmd_prefix=[sys.executable, "-c",
                                     _gang_script(body)], **kw)
    launches = {}
    for i in range(max(num_processes, 1)):
        p = tmp_path / f"gang.p{i}"
        launches[i] = p.read_text().splitlines() if p.exists() else []
    events = [json.loads(l) for l in open(tmp_path / "gev.jsonl")]
    return rc, launches, events


def test_gang_restart_is_all_or_nothing_with_fresh_port(tmp_path):
    rc, launches, events = _gang(
        tmp_path,
        "if pid == '1' and gen == '0':\n"
        "    sys.exit(9)\n"
        "time.sleep(0.5)\nsys.exit(0)",
        max_restarts=2)
    assert rc == 0
    # BOTH processes relaunched in generation 1 — the healthy worker was
    # torn down with its crashed peer, not left blocked in a collective.
    assert [l.split()[0] for l in launches[0]] == ["0", "1"]
    assert [l.split()[0] for l in launches[1]] == ["0", "1"]
    # Fresh coordinator port per launch, identical across the gang.
    ports = [l.split()[1] for l in launches[0]]
    assert ports[0] != ports[1]
    assert [l.split()[1] for l in launches[1]] == ports
    # Fresh launch id per relaunch, identical across the gang: the
    # checkpoint-agreement generation that makes a previous launch's
    # leftover .agreement files unreadable.
    lids = [l.split()[2] for l in launches[0]]
    assert lids[0] and lids[1] and lids[0] != lids[1]
    assert [l.split()[2] for l in launches[1]] == lids
    g = [e for e in events if e["kind"] == "gang_restart"]
    assert len(g) == 1 and g[0]["payload"]["proc"] == 1
    assert g[0]["payload"]["coordinator_died"] is False


def test_gang_never_restarts_divergence(tmp_path):
    rc, launches, events = _gang(tmp_path, "sys.exit(3)", max_restarts=5)
    assert rc == 3
    assert launches[0] == launches[1] and len(launches[0]) == 1
    assert not [e for e in events if e["kind"] == "gang_restart"]


def test_gang_coordinator_death_is_flagged_and_survived(tmp_path):
    rc, launches, events = _gang(
        tmp_path,
        "if pid == '0' and gen == '0':\n"
        "    sys.exit(9)\n"
        "time.sleep(0.5)\nsys.exit(0)",
        max_restarts=2)
    assert rc == 0 and len(launches[0]) == 2
    g = [e for e in events if e["kind"] == "gang_restart"]
    assert g and g[0]["payload"]["coordinator_died"] is True


def test_gang_member_finishing_first_is_not_a_failure(tmp_path):
    rc, launches, events = _gang(
        tmp_path,
        "if pid == '0':\n"
        "    sys.exit(0)\n"
        "time.sleep(0.4)\nsys.exit(0)",
        max_restarts=2)
    assert rc == 0
    assert len(launches[0]) == 1 and len(launches[1]) == 1
    assert not [e for e in events if e["kind"] == "gang_restart"]


def test_gang_preemption_restarts_without_backoff(tmp_path):
    t0 = time.time()
    rc, launches, _ = _gang(
        tmp_path,
        f"if pid == '1' and gen == '0':\n"
        f"    sys.exit({EXIT_PREEMPTED})\n"
        "time.sleep(0.3)\nsys.exit(0)",
        max_restarts=2, backoff_base=30.0)
    # A 30 s crash backoff would blow this bound; preemption skips it.
    assert rc == 0 and len(launches[1]) == 2
    assert time.time() - t0 < 20


def test_gang_hang_detection_kills_stale_member(tmp_path):
    rc, launches, events = _gang(
        tmp_path, "time.sleep(60)",
        max_restarts=0, hang_timeout=1.0,
        heartbeat=str(tmp_path / "hb.json"))
    assert rc != 0 and len(launches[0]) == 1
    exits = [e for e in events if e["kind"] == "child_exit"]
    assert exits and exits[-1]["payload"]["hung"] is True


def test_gang_hang_restart_skips_backoff_like_preemption(tmp_path):
    # A heartbeat-detected hang SIGKILLs the member (rc -9), but the
    # failure mode is the one the collective watchdog reports as 75:
    # the last periodic checkpoint is intact, so the relaunch must not
    # pay crash backoff.
    t0 = time.time()
    rc, launches, events = _gang(
        tmp_path,
        "if gen == '0':\n"
        "    time.sleep(60)\n"
        "time.sleep(0.3)\nsys.exit(0)",
        max_restarts=1, hang_timeout=1.0, backoff_base=30.0,
        heartbeat=str(tmp_path / "hb.json"))
    # A 30 s crash backoff would blow this bound; a hang skips it.
    assert rc == 0 and len(launches[0]) == 2
    assert time.time() - t0 < 20
    g = [e for e in events if e["kind"] == "gang_restart"]
    assert len(g) == 1 and g[0]["payload"]["hung"] is True
    assert g[0]["payload"]["backoff_s"] == 0.0


def test_gang_of_one_delegates_to_the_single_supervisor(tmp_path):
    rc, launches, events = _gang(tmp_path, "sys.exit(0)", num_processes=1)
    # No coordinator/launch env set: both trailing fields are empty.
    assert rc == 0 and launches[0] == ["0  "]
    assert not [e for e in events if e["kind"] == "gang_start"]


# ------------------------------------------------------------------ report
def test_report_aggregates_resilience_timeline(tmp_path):
    ev = str(tmp_path / "ev.jsonl")
    from fedtpu.telemetry import make_tracer
    tracer = make_tracer(ev)
    tracer.event("manifest", config_hash="c", restarts=1,
                 fault_plan="abcd1234")
    tracer.event("fault", round=4, fault="process_kill", fault_round=4,
                 signal="SIGKILL", process_index=0)
    tracer.event("resume", round=2)
    tracer.event("rollback", round=5, restored_round=4, attempt=1,
                 reason="loss/metrics at round 5")
    tracer.event("exclusion", round=4, clients=[2])
    tracer.event("preempted", round=6)
    tracer.event("restart", restarts=1, rc=-9, hung=False, backoff_s=1.0,
                 resume=True)
    tracer.event("child_exit", rc=-9, restarts=0, hung=False)
    tracer.event("gang_restart", restarts=1, rc=75, proc=1, hung=True,
                 backoff_s=0.0, resume=True, coordinator_died=False)
    tracer.event("supervisor_exit", rc=0, reason="done", restarts=1)
    tracer.close()
    # collective_hang uses the watchdog's direct wire format (the tracer
    # claims the top-level "phase"/"round" slots for itself).
    with open(ev, "a") as fh:
        fh.write(json.dumps({
            "v": 1, "kind": "collective_hang", "round": 6, "dur_s": 12.5,
            "payload": {"process": 1, "phase": "chunk_fetch",
                        "timeout_s": 12.0, "waited_s": 12.5,
                        "restarts": 0, "pid": 1}}) + "\n")
    from fedtpu.telemetry.report import aggregate, load_events, render_text
    agg = aggregate(*load_events(ev))
    res = agg["resilience"]
    assert res["faults"][0]["fault"] == "process_kill"
    assert res["rollbacks"][0]["restored_round"] == 4
    assert res["exclusions"][0]["clients"] == [2]
    assert res["restarts"] == 1 and res["child_exit_codes"] == [-9]
    assert res["preempted_rounds"] == [6] and res["resume_rounds"] == [2]
    assert res["gang_restarts"] == 1
    assert res["collective_hangs"][0]["round"] == 6
    assert res["collective_hangs"][0]["phase"] == "chunk_fetch"
    assert res["supervisor_exit"]["reason"] == "done"
    assert agg["manifest"]["restarts"] == 1
    assert agg["manifest"]["fault_plan"] == "abcd1234"
    text = render_text(agg)
    assert "fault process_kill @ round 4" in text
    assert "rollback @ round 5 -> restored round 4" in text
    assert "supervisor restarts: 1" in text
    assert "COLLECTIVE HANG @ round 6" in text
    assert "gang restarts: 1" in text
