"""End-to-end orchestration loop: history shapes, early stopping semantics
(FL_CustomMLP...:181-192), held-out eval."""

import numpy as np

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig, RunConfig,
                           ShardConfig)
from fedtpu.orchestration.loop import run_experiment


def _cfg(**fed_kw):
    return ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=512),
        shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=fed_kw.pop("rounds", 10), **fed_kw),
        run=RunConfig(eval_test_every=1),
    )


def test_run_experiment_history_shapes():
    res = run_experiment(_cfg(rounds=5), verbose=False)
    assert res.rounds_run == 5
    for k in ("accuracy", "precision", "recall", "f1"):
        assert len(res.global_metrics[k]) == 5
        assert len(res.pooled_metrics[k]) == 5
        assert len(res.test_metrics[k]) == 5
        assert res.per_client_metrics[k][0].shape == (8,)
    assert len(res.sec_per_round) == 5
    assert res.final_params["layers"][0]["w"].ndim == 2  # global, no client axis


def test_training_improves_metrics():
    res = run_experiment(_cfg(rounds=25), verbose=False)
    acc = res.global_metrics["accuracy"]
    assert acc[-1] > acc[0]
    assert acc[-1] > 0.8  # separable synthetic data


def test_early_stopping_with_huge_tolerance():
    # atol=1.0 makes every round "unchanged": patience must fire exactly.
    res = run_experiment(_cfg(rounds=50, termination_patience=3,
                              tolerance=1.0), verbose=False)
    assert res.stopped_early
    # Round 1 sets prev; rounds 2,3,4 count down 3->0 => stop at round 4.
    assert res.rounds_run == 4


def test_no_early_stop_when_metrics_move():
    res = run_experiment(_cfg(rounds=8, termination_patience=10,
                              tolerance=1e-12), verbose=False)
    assert not res.stopped_early
    assert res.rounds_run == 8


def test_run_experiment_is_deterministic():
    """Same config, two runs, identical metric histories (client-mean,
    pooled, per-client, test, personalized) and final params — the
    reproducibility guarantee the reference undermines with unseeded
    per-rank shuffles (SURVEY.md §2a _split_data)."""
    import jax
    from fedtpu.config import ModelConfig

    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256,
                        synthetic_features=6),
        shard=ShardConfig(num_clients=8, shuffle=True, shard_seed=5),
        model=ModelConfig(input_dim=6, hidden_sizes=(8,)),
        fed=FedConfig(rounds=6, participation_rate=0.7,
                      personalize_steps=3),
        run=RunConfig(rounds_per_step=3, eval_test_every=3),
    )
    a = run_experiment(cfg, verbose=False)
    b = run_experiment(cfg, verbose=False)
    for k in a.global_metrics:
        np.testing.assert_array_equal(a.global_metrics[k],
                                      b.global_metrics[k])
        np.testing.assert_array_equal(a.pooled_metrics[k],
                                      b.pooled_metrics[k])
        np.testing.assert_array_equal(a.per_client_metrics[k],
                                      b.per_client_metrics[k])
        np.testing.assert_array_equal(a.test_metrics[k], b.test_metrics[k])
        np.testing.assert_array_equal(
            a.personalized_metrics["per_client"][k],
            b.personalized_metrics["per_client"][k])
    jax.tree.map(np.testing.assert_array_equal, a.final_params,
                 b.final_params)
    assert (a.personalized_metrics["client_mean"]
            == b.personalized_metrics["client_mean"])
