"""End-to-end orchestration loop: history shapes, early stopping semantics
(FL_CustomMLP...:181-192), held-out eval."""

import numpy as np

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig, RunConfig,
                           ShardConfig)
from fedtpu.orchestration.loop import run_experiment


def _cfg(**fed_kw):
    return ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=512),
        shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=fed_kw.pop("rounds", 10), **fed_kw),
        run=RunConfig(eval_test_every=1),
    )


def test_run_experiment_history_shapes():
    res = run_experiment(_cfg(rounds=5), verbose=False)
    assert res.rounds_run == 5
    for k in ("accuracy", "precision", "recall", "f1"):
        assert len(res.global_metrics[k]) == 5
        assert len(res.pooled_metrics[k]) == 5
        assert len(res.test_metrics[k]) == 5
        assert res.per_client_metrics[k][0].shape == (8,)
    assert len(res.sec_per_round) == 5
    assert res.final_params["layers"][0]["w"].ndim == 2  # global, no client axis


def test_training_improves_metrics():
    res = run_experiment(_cfg(rounds=25), verbose=False)
    acc = res.global_metrics["accuracy"]
    assert acc[-1] > acc[0]
    assert acc[-1] > 0.8  # separable synthetic data


def test_early_stopping_with_huge_tolerance():
    # atol=1.0 makes every round "unchanged": patience must fire exactly.
    res = run_experiment(_cfg(rounds=50, termination_patience=3,
                              tolerance=1.0), verbose=False)
    assert res.stopped_early
    # Round 1 sets prev; rounds 2,3,4 count down 3->0 => stop at round 4.
    assert res.rounds_run == 4


def test_no_early_stop_when_metrics_move():
    res = run_experiment(_cfg(rounds=8, termination_patience=10,
                              tolerance=1e-12), verbose=False)
    assert not res.stopped_early
    assert res.rounds_run == 8
