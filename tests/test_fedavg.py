"""FedAvg correctness against a numpy oracle implementing the reference's
weighted average verbatim (FL_CustomMLP...:108-116), plus the
optimizer-state-not-averaged invariant (SURVEY.md §7 'hard parts')."""

import numpy as np
import jax

from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.parallel import make_mesh, client_sharding
from fedtpu.parallel.round import build_round_fn, init_federated_state


def _setup(num_clients=8, rows=200, lr=0.004, weighting="data_size",
           same_init=False):
    x, y = synthetic_income_like(rows, 6, 2)
    packed = pack_clients(x, y, ShardConfig(num_clients=num_clients,
                                            shuffle=False))
    mesh = make_mesh(num_clients=num_clients)
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig(learning_rate=lr))
    state = init_federated_state(jax.random.key(1), mesh, num_clients,
                                 init_fn, tx, same_init=same_init)
    shard = client_sharding(mesh)
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    round_step = build_round_fn(mesh, apply_fn, tx, 2, weighting=weighting)
    return state, batch, round_step, packed


def _oracle_weighted_average(per_client_weights, sizes):
    """Verbatim numpy transcription of FL_CustomMLP...:110-115 semantics."""
    total = sum(sizes)
    return sum(w * (n / total) for w, n in zip(per_client_weights, sizes))


def test_weighted_average_matches_numpy_oracle():
    # lr=0 turns the train step into identity, isolating the averaging.
    state, batch, round_step, packed = _setup(lr=0.0)
    before = np.asarray(state["params"]["layers"][0]["w"])  # (C, in, out)
    new_state, _ = round_step(state, batch)
    after = np.asarray(new_state["params"]["layers"][0]["w"])
    expected = _oracle_weighted_average(list(before),
                                        list(packed.counts.astype(float)))
    for c in range(8):
        np.testing.assert_allclose(after[c], expected, atol=1e-6)


def test_uniform_average_matches_plain_mean():
    state, batch, round_step, _ = _setup(lr=0.0, weighting="uniform")
    before = np.asarray(state["params"]["layers"][1]["b"])
    new_state, _ = round_step(state, batch)
    after = np.asarray(new_state["params"]["layers"][1]["b"])
    np.testing.assert_allclose(after[0], before.mean(axis=0), atol=1e-6)


def test_unequal_shards_weight_by_true_counts():
    # 103 rows over 8 clients: counts [12]*7+[19]; padding must not leak into
    # the weights (weight == mask sum == len(X_local), FL_CustomMLP...:104).
    state, batch, round_step, packed = _setup(rows=103, lr=0.0)
    assert packed.counts.tolist() == [12] * 7 + [19]
    before = np.asarray(state["params"]["layers"][0]["w"])
    new_state, _ = round_step(state, batch)
    after = np.asarray(new_state["params"]["layers"][0]["w"])
    expected = _oracle_weighted_average(list(before),
                                        [12.0] * 7 + [19.0])
    np.testing.assert_allclose(after[0], expected, atol=1e-6)


def test_optimizer_state_is_not_averaged():
    # The reference averages parameters ONLY (:101-120); Adam moments stay
    # per-client. With different shards, clients' moments must diverge.
    state, batch, round_step, _ = _setup(lr=0.004)
    new_state, _ = round_step(state, batch)
    mu = np.asarray(jax.tree.leaves(new_state["opt_state"])[1])  # some moment
    assert mu.shape[0] == 8
    assert not np.allclose(mu[0], mu[1])


def test_identical_data_same_init_equals_single_client():
    # N clients with identical shards and identical init must follow exactly
    # the single-client trajectory (averaging identical params is identity).
    x, y = synthetic_income_like(128, 6, 2)
    mesh = make_mesh(num_clients=8)
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig())

    # 8 clients, every one holding the SAME 128 rows.
    xc = np.broadcast_to(x, (8, *x.shape)).copy()
    yc = np.broadcast_to(y, (8, *y.shape)).copy()
    mask = np.ones((8, len(y)), np.float32)
    shard = client_sharding(mesh)
    batch = {"x": jax.device_put(xc, shard), "y": jax.device_put(yc, shard),
             "mask": jax.device_put(mask, shard)}
    state = init_federated_state(jax.random.key(5), mesh, 8, init_fn, tx,
                                 same_init=True)
    round_step = build_round_fn(mesh, apply_fn, tx, 2)
    for _ in range(3):
        state, metrics = round_step(state, batch)

    # Single-client run (mesh of 1 device slice).
    mesh1 = make_mesh(num_devices=1, num_clients=1)
    state1 = init_federated_state(jax.random.key(5), mesh1, 1, init_fn, tx,
                                  same_init=True)
    shard1 = client_sharding(mesh1)
    batch1 = {"x": jax.device_put(xc[:1], shard1),
              "y": jax.device_put(yc[:1], shard1),
              "mask": jax.device_put(mask[:1], shard1)}
    round1 = build_round_fn(mesh1, apply_fn, tx, 2)
    for _ in range(3):
        state1, metrics1 = round1(state1, batch1)

    np.testing.assert_allclose(
        np.asarray(state["params"]["layers"][0]["w"])[0],
        np.asarray(state1["params"]["layers"][0]["w"])[0],
        atol=1e-5)
    np.testing.assert_allclose(float(metrics["client_mean"]["accuracy"]),
                               float(metrics1["client_mean"]["accuracy"]),
                               atol=1e-6)
