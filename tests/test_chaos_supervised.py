"""Supervised variant of tests/test_chaos_resume.py: the child is
killed by a deterministic in-loop fault (fedtpu.resilience.faults)
instead of by the test, and ``fedtpu supervise`` — not the test — does
the restart. Asserts the full contract end to end:

  * SIGKILL mid-run: supervisor restarts with --resume; the final
    per-round metric history is bitwise identical to an uninterrupted
    run of the same job.
  * SIGTERM mid-run: the loop drains a checkpoint, exits 75
    (EX_TEMPFAIL); the supervisor restarts WITHOUT backoff; same
    bitwise bar.
  * Restart counts are read back from the events sink and the (last)
    child's run manifest — the reporting path is part of the contract.

Everything runs through ``fedtpu chaos``'s scenario machinery (one
subprocess per child, parent stays jax-free), so this module is also
the pytest gate for the chaos matrix rows the ISSUE names. Each child
is a full CLI training run: this module is excluded from the quick
tier in conftest.py, like test_chaos_resume.py.
"""

import json
import os
import subprocess
import sys

import pytest

from fedtpu.resilience.chaos import (SCENARIOS, _child_env, _history,
                                     _run_args, run_chaos, run_scenario)
from fedtpu.telemetry.report import aggregate, load_events

ROUNDS = 8          # fault fires at round 5 (rounds // 2 + 1)
NUM_CLIENTS = 4


@pytest.fixture(scope="module")
def chaos_env(tmp_path_factory):
    """One uninterrupted baseline shared by both kill scenarios."""
    wd = str(tmp_path_factory.mktemp("chaos"))
    out = subprocess.run(
        [sys.executable, "-m", "fedtpu.cli",
         *_run_args(wd, "baseline", ROUNDS, NUM_CLIENTS, "cpu")],
        env=_child_env(), capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, (out.stderr or "")[-2000:]
    baseline = _history(os.path.join(wd, "baseline.metrics.jsonl"))
    assert sorted(baseline) == list(range(1, ROUNDS + 1))
    return wd, baseline


def _kill_scenario(chaos_env, name):
    wd, baseline = chaos_env
    row = run_scenario(name, wd, baseline, ROUNDS, NUM_CLIENTS,
                       platform="cpu", timeout=600)
    # The scenario's own verdict: survived, bitwise history match, the
    # fault actually fired, and at least one supervised restart.
    assert row["ok"], row
    assert row["rc"] == 0 and row["restarts"] >= 1

    # Independent of the verdict logic: recompute the bitwise match and
    # read the restart count from the manifest, not just the counters.
    hist = _history(os.path.join(wd, f"{name}.metrics.jsonl"))
    assert hist == baseline              # exact final state, all rounds

    events, bad = load_events(os.path.join(wd, f"{name}.events.jsonl"))
    agg = aggregate(events, malformed=bad)
    # Manifests are last-one-wins: the surviving (restarted) child wrote
    # the last manifest, and it knows how many launches preceded it.
    assert agg["manifest"]["restarts"] == 1
    assert agg["manifest"]["fault_plan"]         # plan digest recorded
    assert agg["resilience"]["restarts"] == 1
    fault = agg["resilience"]["faults"][0]
    assert fault["fault"] == "process_kill"
    return agg


def test_supervised_sigkill_recovers_to_exact_state(chaos_env):
    agg = _kill_scenario(chaos_env, "sigkill")
    # SIGKILL is abrupt: no drain, so the child exit code is -9 and the
    # restart resumed from the last periodic checkpoint.
    assert -9 in agg["resilience"]["child_exit_codes"]
    assert agg["resilience"]["resume_rounds"]


def test_supervised_sigterm_preemption_drains_and_resumes(chaos_env):
    agg = _kill_scenario(chaos_env, "preempt")
    # SIGTERM is graceful: the loop drained a checkpoint at the fault
    # round and exited 75; the supervisor restarted without backoff.
    assert 75 in agg["resilience"]["child_exit_codes"]
    assert agg["resilience"]["preempted_rounds"] == [ROUNDS // 2 + 1]
    restarts = [e for e in load_events(
        os.path.join(chaos_env[0], "preempt.events.jsonl"))[0]
        if e["kind"] == "restart"]
    assert restarts and restarts[0]["payload"]["backoff_s"] == 0


@pytest.mark.slow
def test_full_chaos_matrix_is_green(tmp_path):
    """The ISSUE's headline acceptance: every scenario — single-process
    AND the mp_* gang rows — in one go (identical to
    ``fedtpu chaos --rounds 8``)."""
    report = run_chaos(rounds=ROUNDS, num_clients=NUM_CLIENTS,
                       workdir=str(tmp_path), keep_artifacts=True,
                       verbose=False)
    assert report["ok"], json.dumps(report, indent=2)
    assert [r["scenario"] for r in report["scenarios"]] == list(SCENARIOS)
