"""sklearn warm-start limitation demo (FL_SkLearn_MLPClassifier_Limitation.py):
fit() re-initializes, so averaging has no effect — and the fedtpu path does
not share the limitation."""

import numpy as np

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           ModelConfig, ShardConfig)
from fedtpu.data.tabular import load_tabular_dataset
from fedtpu.parity.sklearn_warmstart import run_parity_demo, run_sklearn_rounds


def _cfg():
    return ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=300),
        shard=ShardConfig(num_clients=2),
        model=ModelConfig(hidden_sizes=(16,)),
        fed=FedConfig(rounds=3, weighting="uniform"),
    )


def test_limitation_demonstrated():
    cfg = _cfg()
    ds = load_tabular_dataset(cfg.data)
    out = run_sklearn_rounds(ds, cfg, max_iter=25, verbose=False)
    # Deterministic re-init (random_state=42): every round's post-fit weights
    # are identical although different global weights were applied — the
    # averaging is demonstrably discarded (FL_SkLearn...:95-101).
    assert out["limitation_demonstrated"]
    fps = out["fit_fingerprints"]
    assert len(fps) == 3
    np.testing.assert_allclose(fps, fps[0], rtol=1e-6)


def test_full_demo_contrasts_both_paths():
    out = run_parity_demo(_cfg(), sklearn_max_iter=25, verbose=False)
    assert out["limitation_demonstrated"]
    assert out["fedtpu_uses_global_weights"]
    assert len(out["fedtpu"]["pooled_metrics"]["accuracy"]) == 3


def test_final_global_weight_stats_reported():
    # The reference's closing report (FL_SkLearn...:146-150): per-layer
    # shape/mean/std of the final global weights — both paths must emit it.
    cfg = _cfg()
    out = run_parity_demo(cfg, sklearn_max_iter=25, verbose=False)
    hidden = tuple(cfg.model.hidden_sizes)
    n_layers = len(hidden) + 1
    for side in ("sklearn", "fedtpu"):
        stats = out[side]["global_weight_stats"]
        # coefs then intercepts, one of each per layer.
        assert len(stats) == 2 * n_layers
        for st in stats:
            assert set(st) == {"shape", "mean", "std"}
            assert np.isfinite(st["mean"]) and np.isfinite(st["std"])
    # The weight matrices' shapes must describe the actual architecture.
    first = out["sklearn"]["global_weight_stats"][0]
    assert first["shape"][1] == hidden[0]
