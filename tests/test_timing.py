"""Fetch-forced timing utilities (fedtpu.utils.timing).

Round-1 postmortem: every recorded perf number was a dispatch-rate artifact
because jax.block_until_ready does not synchronize on the tunneled axon
transport. These utilities are the repo-wide fix; the floor check is the
guard that makes the artifact class impossible to record again.
"""

import numpy as np
import pytest

from fedtpu.utils.timing import (Timer, assert_above_flops_floor,
                                 force_fetch, measured_peak_flops)


def test_force_fetch_returns_scalar_from_tree():
    import jax.numpy as jnp
    tree = {"a": jnp.arange(6.0).reshape(2, 3), "b": jnp.float32(4.0)}
    # Leaves are ordered by key: 'a' then 'b' — last leaf is b.
    assert force_fetch(tree) == 4.0


def test_force_fetch_depends_on_computation():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return {"out": (x * 2).sum(keepdims=True)}

    assert force_fetch(f(jnp.ones(5))) == 10.0


def test_force_fetch_refuses_host_only_trees():
    # A fetch that proves nothing must fail loudly, not look like success —
    # otherwise a refactor that converts metrics to numpy earlier would
    # silently reintroduce the dispatch-rate artifact.
    with pytest.raises(TypeError, match="no device-backed"):
        force_fetch({})
    with pytest.raises(TypeError, match="no device-backed"):
        force_fetch({"static": "notanarray", "np": np.ones(3)})


def test_flops_floor_passes_above_and_raises_below():
    peak = 1e12
    flops = 1e9                         # floor = 1e9 / 2e12 = 5e-4 s
    floor = assert_above_flops_floor(1e-3, flops, peak, label="ok")
    assert floor == pytest.approx(5e-4)
    with pytest.raises(RuntimeError, match="timing methodology broken"):
        # 100x faster than physics allows — the round-1 artifact shape.
        assert_above_flops_floor(5e-6, flops, peak, label="artifact")


def test_measured_peak_flops_is_positive_and_sane():
    # Tiny shapes so the CPU test environment finishes fast; we only check
    # the plumbing (slope math, fetch forcing), not absolute accuracy.
    peak = measured_peak_flops(dtype="float32", n=64, chains=(2, 8))
    assert peak > 0
    # A 64^3 matmul is 5.2e5 FLOP; any real machine does it in under a
    # second and no machine exceeds 1 EFLOP/s.
    assert 5.2e5 < peak < 1e18


def test_timer_laps():
    t = Timer().start()
    a = t.lap()
    b = t.lap()
    assert a >= 0 and b >= 0
    assert t.total == pytest.approx(a + b)
    assert t.mean() == pytest.approx((a + b) / 2)
