"""Adaptive DP clipping (Andrew et al. 2021) — fedtpu.parallel.round.

Pins: the one-round oracle (clip update recomputed from independently
derived client update norms), the long-run equilibrium (the clip settles
inside the update-norm distribution, bracketing the target quantile), the
split-noise calibration identity, and the orchestration plumbing
(summary, checkpoint carry, guards)."""

import dataclasses

import jax
import numpy as np
import pytest

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           ModelConfig, OptimConfig, RunConfig, ShardConfig)
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.ops.server_opt import clip_by_global_norm, identity_server_optimizer
from fedtpu.orchestration.loop import build_experiment, run_experiment
from fedtpu.parallel import make_mesh, client_sharding
from fedtpu.parallel.round import (build_round_fn,
                                   effective_delta_noise_multiplier,
                                   init_federated_state)
from fedtpu.training.client import make_local_train_step


def _setup(clip0=1.0, num_clients=8):
    x, y = synthetic_income_like(256, 6, 2, seed=0)
    packed = pack_clients(x, y, ShardConfig(num_clients=num_clients,
                                            shuffle=False))
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(16, 8)))
    tx = build_optimizer(OptimConfig())
    mesh = make_mesh(num_clients=num_clients)
    server = identity_server_optimizer()
    state = init_federated_state(jax.random.key(0), mesh, num_clients,
                                 init_fn, tx, same_init=True,
                                 server_opt=server,
                                 adaptive_clip_init=clip0)
    batch = {k: jax.device_put(v, client_sharding(mesh))
             for k, v in {"x": packed.x, "y": packed.y,
                          "mask": packed.mask}.items()}
    return mesh, apply_fn, tx, server, state, batch


def test_one_round_clip_update_matches_oracle():
    """clip_1 == clip_0 * exp(-lr * (b - quantile)) with b recomputed from
    norms derived by running the local step independently. The initial
    clip is placed at the measured norm median so the indicator genuinely
    splits the cohort (b == 0.5, neither saturated extreme)."""
    quant, lr_c = 0.5, 0.3
    mesh, apply_fn, tx, server, state, batch = _setup(clip0=1.0)
    # Oracle: per-client deltas from one local step on the same start.
    local = make_local_train_step(apply_fn, tx)
    trained, _, _ = jax.vmap(local)(state["params"], state["opt_state"],
                                    batch["x"], batch["y"], batch["mask"])
    delta = jax.tree.map(lambda t, s: np.asarray(t) - np.asarray(s),
                         trained, state["params"])
    _, norms = clip_by_global_norm(
        jax.tree.map(jax.numpy.asarray, delta), 1.0)
    srt = np.sort(np.asarray(norms))
    clip0 = float((srt[3] + srt[4]) / 2)     # midpoint: exactly 4 of 8 below
    b = float((np.asarray(norms) <= clip0).mean())
    assert b == 0.5, (b, srt)
    expected = clip0 * np.exp(-lr_c * (b - quant))
    # Same key -> identical federation, now with the chosen initial clip.
    mesh, apply_fn, tx, server, state, batch = _setup(clip0=clip0)

    step = build_round_fn(mesh, apply_fn, tx, 2, weighting="uniform",
                          server_opt=server, dp_clip_norm=clip0,
                          dp_adaptive_clip=True, dp_target_quantile=quant,
                          dp_clip_lr=lr_c)
    state, _ = step(state, batch)
    np.testing.assert_allclose(float(np.asarray(state["dp_clip"])),
                               expected, rtol=1e-5)


@pytest.mark.parametrize("quant", [0.3, 0.7])
def test_clip_settles_inside_the_norm_distribution(quant):
    """Long-run equilibrium: from a far-too-large initial clip, the
    geometric tracker descends into the client-norm distribution and
    oscillates around the target quantile — the realized under-clip
    fraction over the tail brackets it."""
    mesh, apply_fn, tx, server, state, batch = _setup(clip0=10.0)
    step = build_round_fn(mesh, apply_fn, tx, 2, weighting="uniform",
                          server_opt=server, dp_clip_norm=10.0,
                          dp_adaptive_clip=True, dp_target_quantile=quant,
                          dp_clip_lr=0.5)
    clips = []
    for _ in range(60):
        state, _ = step(state, batch)
        clips.append(float(np.asarray(state["dp_clip"])))
    # Tail behavior: per-round log-steps decode b_t exactly
    # (b_t = quant - ln(c_t/c_{t-1}) / lr); their tail mean is the realized
    # under-clip fraction the tracker saw.
    logs = np.diff(np.log(np.asarray([10.0] + clips)))
    b_tail = quant - logs[-20:] / 0.5
    assert 0.0 <= b_tail.mean() <= 1.0
    assert abs(b_tail.mean() - quant) < 0.35, (quant, b_tail.mean())
    # And it genuinely left the too-large init region.
    assert clips[-1] < 1.0


def test_effective_delta_noise_multiplier_identity():
    """z^-2 == z_delta^-2 + (2*z_count)^-2 (Andrew et al.), and the guard
    for the impossible split."""
    z, zb = 1.1, 2.0
    zd = effective_delta_noise_multiplier(z, zb)
    assert zd > z                        # deltas pay MORE noise than z alone
    np.testing.assert_allclose(zd ** -2 + (2 * zb) ** -2, z ** -2, rtol=1e-12)
    with pytest.raises(ValueError, match="exceed"):
        effective_delta_noise_multiplier(1.0, 0.5)


def test_guards():
    mesh, apply_fn, tx, server, state, batch = _setup()
    with pytest.raises(ValueError, match="initial clip"):
        build_round_fn(mesh, apply_fn, tx, 2, weighting="uniform",
                       server_opt=server, dp_adaptive_clip=True)
    with pytest.raises(ValueError, match="meaningless"):
        build_round_fn(mesh, apply_fn, tx, 2, weighting="uniform",
                       server_opt=server, dp_clip_norm=1.0,
                       dp_adaptive_clip=True,
                       dp_count_noise_multiplier=2.0)
    with pytest.raises(ValueError, match="dp_adaptive_clip"):
        build_round_fn(mesh, apply_fn, tx, 2, weighting="uniform",
                       server_opt=server, dp_clip_norm=1.0,
                       dp_count_noise_multiplier=2.0)
    # State/round_fn mismatch, both directions.
    plain = build_round_fn(mesh, apply_fn, tx, 2, weighting="uniform",
                           server_opt=server, dp_clip_norm=1.0)
    with pytest.raises(ValueError, match="freeze"):
        plain(state, batch)
    init_fn, _ = build_model(ModelConfig(input_dim=6, hidden_sizes=(16, 8)))
    state_plain = init_federated_state(jax.random.key(0), mesh, 8, init_fn,
                                       tx, same_init=True, server_opt=server)
    adaptive = build_round_fn(mesh, apply_fn, tx, 2, weighting="uniform",
                              server_opt=server, dp_clip_norm=1.0,
                              dp_adaptive_clip=True)
    with pytest.raises(ValueError, match="adaptive_clip_init"):
        adaptive(state_plain, batch)


def _cfg(ck=None, **fed_kw):
    fed = dict(rounds=4, weighting="uniform", dp_clip_norm=0.1,
               dp_adaptive_clip=True, dp_clip_lr=0.4)
    fed.update(fed_kw)
    return ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256,
                        synthetic_features=6),
        shard=ShardConfig(num_clients=8, shuffle=False),
        model=ModelConfig(input_dim=6, hidden_sizes=(16, 8)),
        fed=FedConfig(**fed),
        run=RunConfig(rounds_per_step=2,
                      **({"checkpoint_dir": ck, "checkpoint_every": 2}
                         if ck else {})),
    )


def test_run_experiment_adaptive_dp_end_to_end(tmp_path):
    """Noise + adaptive clip through the orchestration loop: the summary
    reports the accountant's epsilon (charged at the CONFIGURED z) and the
    final clip; checkpoints carry the clip; resume restores it."""
    ck = str(tmp_path / "ck")
    cfg = _cfg(ck=ck, dp_noise_multiplier=1.0,
               dp_count_noise_multiplier=2.0)
    res = run_experiment(cfg, verbose=False)
    summary = res.summary()
    assert summary["final_dp_clip"] is not None
    assert summary["final_dp_clip"] != pytest.approx(0.1)   # it moved
    assert np.isfinite(summary["dp"]["epsilon"])
    assert summary["dp"]["noise_multiplier"] == 1.0         # configured z

    from fedtpu.orchestration.checkpoint import load_checkpoint
    exp = build_experiment(cfg)
    state, _, step_no = load_checkpoint(ck, state_like=exp.state)
    assert step_no == 4
    np.testing.assert_allclose(float(np.asarray(state["dp_clip"])),
                               summary["final_dp_clip"], rtol=1e-6)

    cfg6 = dataclasses.replace(cfg, fed=dataclasses.replace(cfg.fed,
                                                            rounds=6))
    res6 = run_experiment(cfg6, verbose=False, resume=True)
    assert res6.rounds_run == 6
    assert res6.final_dp_clip is not None


def test_model_parallel_adaptive_clip_rejected():
    cfg = dataclasses.replace(_cfg(), run=RunConfig(model_parallel=2))
    with pytest.raises(ValueError, match="1-D engine"):
        build_experiment(cfg)


def test_data_size_weighting_uses_count_fraction():
    """Review r4 regression: under weighting='data_size' the clipped
    fraction must still be a client-COUNT fraction (a weight denominator
    would pin b near 0 and grow the clip without bound). Same one-round
    closed form as the uniform oracle."""
    quant, lr_c = 0.5, 0.3
    mesh, apply_fn, tx, server, state, batch = _setup(clip0=1.0)
    local = make_local_train_step(apply_fn, tx)
    trained, _, _ = jax.vmap(local)(state["params"], state["opt_state"],
                                    batch["x"], batch["y"], batch["mask"])
    delta = jax.tree.map(lambda t, s: np.asarray(t) - np.asarray(s),
                         trained, state["params"])
    _, norms = clip_by_global_norm(
        jax.tree.map(jax.numpy.asarray, delta), 1.0)
    srt = np.sort(np.asarray(norms))
    clip0 = float((srt[3] + srt[4]) / 2)
    expected = clip0 * np.exp(-lr_c * (0.5 - quant))   # == clip0 here
    mesh, apply_fn, tx, server, state, batch = _setup(clip0=clip0)
    step = build_round_fn(mesh, apply_fn, tx, 2, weighting="data_size",
                          server_opt=server, dp_clip_norm=clip0,
                          dp_adaptive_clip=True, dp_target_quantile=quant,
                          dp_clip_lr=lr_c)
    state, _ = step(state, batch)
    np.testing.assert_allclose(float(np.asarray(state["dp_clip"])),
                               expected, rtol=1e-5)


def test_zero_participant_round_holds_clip_when_noise_free():
    """Advisor r4 regression: in plain quantile-tracking mode
    (dp_count_noise_multiplier == 0) a round that samples zero
    participants observed nothing, so the clip must NOT drift toward the
    b = 0.5 prior. (With count noise on, the DP release happens
    regardless and the update must consume it as drawn.)"""
    mesh, apply_fn, tx, server, state, batch = _setup(clip0=1.0)
    step = build_round_fn(mesh, apply_fn, tx, 2, weighting="uniform",
                          participation_rate=1e-9,
                          server_opt=server, dp_clip_norm=1.0,
                          dp_adaptive_clip=True, dp_target_quantile=0.9,
                          dp_clip_lr=0.3)
    state, _ = step(state, batch)
    np.testing.assert_allclose(float(np.asarray(state["dp_clip"])), 1.0,
                               rtol=0, atol=0)
    # Same config with count noise on: the release consumes the draw, so
    # the clip moves even with no participants.
    mesh, apply_fn, tx, server, state, batch = _setup(clip0=1.0)
    step_noisy = build_round_fn(mesh, apply_fn, tx, 2, weighting="uniform",
                                participation_rate=1e-9,
                                server_opt=server, dp_clip_norm=1.0,
                                dp_noise_multiplier=0.1,
                                dp_count_noise_multiplier=0.2,
                                dp_adaptive_clip=True,
                                dp_target_quantile=0.9, dp_clip_lr=0.3)
    state, _ = step_noisy(state, batch)
    assert float(np.asarray(state["dp_clip"])) != 1.0
