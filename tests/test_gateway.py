"""fedtpu.serving.gateway + the retrying GatewayClient (ISSUE 12).

Pins the fault-tolerant multi-host ingestion contracts:
- the modular ownership rule and the redirect error frame shape;
- redirect-atomic batches: ANY foreign event refuses the whole frame,
  the session seq is NOT committed, nothing is admitted;
- idempotent sessions: a retried update frame (the lost-ack window) is
  deduplicated against the engine's incorporation counters and answered
  with the ORIGINAL counts — the exactly-once acceptance bar;
- the write-ahead log replays acked-but-uncheckpointed updates into a
  fresh engine bitwise, and the client's post-replay retries still
  dedup;
- the flush/adopt shard-failover handoff round-trips the dead shard's
  rows bitwise, fences on generation, and replays its spooled queue;
- a real 2-gateway in-process fleet serves a partitioned loadgen path
  end to end (redirect following included);
- probe_fleet reports per-member liveness without raising.

The chaos rows themselves (supervised gang + SIGKILL) are `slow`-marked
subprocess tests delegating to fedtpu.resilience.chaos.
"""

import os
import threading

import numpy as np
import pytest

from fedtpu.config import ServingConfig
from fedtpu.serving import protocol
from fedtpu.serving.client import GatewayClient
from fedtpu.serving.gateway import (_Gateway, _gateway_handle, owner_of,
                                    probe_fleet, redirect_msg, run_gateway)
from fedtpu.telemetry.metrics import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _small_cfg(**kw):
    base = dict(cohort=8, buffer_size=2, tick_interval_s=0.5,
                data_rows=64, model_hidden=(8,), seed=0)
    base.update(kw)
    return ServingConfig(**base)


def _engine(**kw):
    from fedtpu.serving.engine import ServingEngine
    return ServingEngine(_small_cfg(tick_interval_s=0.0, **kw),
                         registry=MetricsRegistry())


# ------------------------------------------------------------------ routing

def test_owner_of_and_redirect_msg():
    assert owner_of(5, 2) == 1 and owner_of(4, 2) == 0
    assert owner_of(7, 1) == 0
    assert owner_of(3, 0) == 0          # degenerate fleet clamps to 1
    msg = redirect_msg(5, 1, 2, "/tmp/base")
    assert msg["op"] == "error"
    assert msg["redirect"]["gateway"] == 1
    assert msg["redirect"]["num_gateways"] == 2
    assert (msg["redirect"]["port_file"]
            == protocol.gateway_port_file("/tmp/base", 1))
    # Without a port-file base the redirect still names the owner.
    assert "port_file" not in redirect_msg(5, 1, 2, None)["redirect"]


def test_gateway_ownership_tracks_adoption():
    gw = _Gateway(0, 2, None, "gen", None)
    assert gw.owns_user(0) and gw.owns_user(4) and not gw.owns_user(1)
    gw.owned.add(1)                     # the post-adopt state
    assert gw.owns_user(1) and gw.owns_user(3)


def test_client_partition_matches_gateway_owner():
    c = GatewayClient(port=1, num_gateways=3)
    assert all(c.owner_of(u) == owner_of(u, 3) for u in range(12))
    # The idempotency stamp: one nonce per CLIENT, monotonic seq.
    a, b = c.stamped({"op": "updates"}), c.stamped({"op": "updates"})
    assert a["nonce"] == b["nonce"] == c.nonce
    assert b["seq"] == a["seq"] + 1


# ------------------------------------------------- idempotent sessions + WAL

def test_retried_frame_incorporated_exactly_once():
    """THE dedup acceptance bar: a retried updates frame (simulated
    dropped ack) is absorbed by the session cache — answered with the
    ORIGINAL counts, flagged duplicate, counted as serve_duplicate_drop
    — and the engine's admission/incorporation counters do not move."""
    from fedtpu.serving.server import _handle

    eng = _engine()
    frame = {"op": "updates", "events": [[1, 0.1, 0.0], [2, 0.2, 0.0]],
             "nonce": "n1", "seq": 1}
    first = _handle(eng, frame)
    assert first["op"] == "acks" and "duplicate" not in first
    counts_after_first = dict(eng.admission.counts)
    second = _handle(eng, dict(frame))   # the lost-ack retry
    assert second["op"] == "acks" and second["duplicate"] is True
    assert second["counts"] == first["counts"]
    assert dict(eng.admission.counts) == counts_after_first
    assert eng.duplicate_drops == 2      # both retried events dropped
    snap = eng.registry.snapshot()["counters"]
    assert snap["serve_duplicate_drop"] == 2
    eng.drain()
    assert eng.incorporated == 2         # exactly once, never four


def test_wal_replays_acked_updates_into_fresh_engine(tmp_path):
    """SIGKILL between processing and checkpoint: every acked frame is
    in the WAL, so a fresh engine replaying it reaches the same
    incorporated state as an uninterrupted run — and the client's retry
    of the lost-ack frame still dedups after the replay."""
    from fedtpu.serving.server import _handle

    wal = str(tmp_path / "wal.jsonl")
    ev1, ev2 = [[1, 0.1, 0.0], [2, 0.2, 0.0]], [[3, 0.3, 0.0]]
    a = _engine()
    a.wal_path = wal
    _handle(a, {"op": "updates", "events": ev1, "nonce": "n", "seq": 1})
    r2 = _handle(a, {"op": "updates", "events": ev2, "nonce": "n",
                     "seq": 2})
    # Engine a dies here (no checkpoint); only the WAL survives.
    b = _engine()
    b.wal_path = wal
    assert b.replay_wal() == 3
    r2b = _handle(b, {"op": "updates", "events": ev2, "nonce": "n",
                      "seq": 2})
    assert r2b["duplicate"] is True and r2b["counts"] == r2["counts"]
    b.drain()
    c = _engine()
    c.offer_many([tuple(r) for r in ev1 + ev2])
    c.drain()
    assert b.incorporated == c.incorporated == 3
    assert b.history_lines() == c.history_lines()


# ------------------------------------------------------ the gateway handler

def test_gateway_handle_redirects_and_keeps_batches_atomic():
    eng = _engine()
    gw = _Gateway(0, 2, "/tmp/pf", "gen0", None)
    w = _gateway_handle(gw, eng, {"op": "hello",
                                  "v": protocol.PROTOCOL_VERSION})
    assert w["op"] == "welcome" and w["gateway"] == 0
    assert w["num_gateways"] == 2 and w["owned"] == [0]
    assert w["generation"] == "gen0"
    # Owned update passes through to the base handler.
    assert _gateway_handle(gw, eng, {"op": "update", "user": 2,
                                     "t": 0.1})["op"] == "ack"
    # Foreign single update: redirect naming the owner + its port file.
    r = _gateway_handle(gw, eng, {"op": "update", "user": 3, "t": 0.1})
    assert r["op"] == "error" and r["redirect"]["gateway"] == 1
    assert (r["redirect"]["port_file"]
            == protocol.gateway_port_file("/tmp/pf", 1))
    # Redirect-atomic batch: ONE foreign event refuses the whole frame,
    # nothing is admitted, and the seq is NOT committed — the
    # re-partitioned resend under the same stamp is new work.
    counts0 = dict(eng.admission.counts)
    rb = _gateway_handle(gw, eng, {"op": "updates",
                                   "events": [[0, 0.2, 0.0],
                                              [1, 0.2, 0.0]],
                                   "nonce": "x", "seq": 1})
    assert rb["op"] == "error" and rb["redirect"]["owners"] == {"1": 1}
    assert dict(eng.admission.counts) == counts0
    ok = _gateway_handle(gw, eng, {"op": "updates",
                                   "events": [[0, 0.2, 0.0]],
                                   "nonce": "x", "seq": 1})
    assert ok["op"] == "acks" and "duplicate" not in ok
    assert gw.redirects == 2
    snap = eng.registry.snapshot()["counters"]
    assert snap["gateway_redirects"] == 2


def test_flush_adopt_handoff_roundtrip_is_bitwise(tmp_path):
    """The store-shard failover: g1 flushes (writeback + spool +
    digest-stamped, generation-fenced checkpoint), dies; g0 adopts —
    rows land bitwise, the id range moves, the spooled queue replays,
    and a stale-generation export is refused."""
    e0, e1 = _engine(), _engine()
    s0 = e0.attach_store(40, shard_index=0, num_shards=2)
    s1 = e1.attach_store(40, shard_index=1, num_shards=2)
    s0.generation = s1.generation = "genA"
    gw0 = _Gateway(0, 2, None, "genA", str(tmp_path / "g0"))
    gw1 = _Gateway(1, 2, None, "genA", str(tmp_path / "g1"))
    for u in (1, 3, 5):
        assert _gateway_handle(gw1, e1, {"op": "update", "user": u,
                                         "t": 0.1})["op"] == "ack"
    e1.drain()                           # bind + incorporate the slots
    # One admitted-but-unincorporated update left pending to spool.
    _gateway_handle(gw1, e1, {"op": "update", "user": 7, "t": 9.9})
    fl = _gateway_handle(gw1, e1, {"op": "flush",
                                   "path": str(tmp_path / "spool.jsonl")})
    assert fl["op"] == "flushed" and fl["generation"] == "genA"
    assert fl["spooled"] == 1 and fl["slots"] >= 1

    bad = _gateway_handle(gw0, e0, {"op": "adopt", "shard": 1,
                                    "checkpoint_dir": str(tmp_path / "g1"),
                                    "generation": "genB"})
    assert bad["op"] == "error" and "generation" in bad["reason"]

    ad = _gateway_handle(gw0, e0, {"op": "adopt", "shard": 1,
                                   "checkpoint_dir": str(tmp_path / "g1"),
                                   "spool": fl["spool"],
                                   "generation": "genA"})
    assert ad["op"] == "adopted" and ad["owned"] == [0, 1]
    assert ad["rows"] >= 1 and ad["replayed"] == 1
    assert gw0.owns_user(1) and gw0.owns_user(3)
    ids = np.array([1, 3, 5], np.int64)
    assert s0.owns(ids).all()
    for want, have in zip(s1.read(ids), s0.read(ids)):
        np.testing.assert_array_equal(want, have)
    # The replayed pending update incorporates on the survivor's clock.
    assert any(p.user == 7 for p in e0.pending)
    snap = e0.registry.snapshot()["counters"]
    assert snap["gateway_adoptions"] == 1


# ------------------------------------------------------------- socket fleet

def test_two_gateway_fleet_inprocess(tmp_path):
    """Full wire path: two run_gateway threads (once=True) behind one
    port-file base, driven by the partitioning GatewayClient — including
    a deliberately misrouted frame whose redirect the client follows."""
    pf = str(tmp_path / "port")
    threads = [
        threading.Thread(target=run_gateway, kwargs=dict(
            cfg=_small_cfg(), gateway_index=g, num_gateways=2,
            port_file=pf, once=True,
            history_path=str(tmp_path / "hist.jsonl"), verbose=False))
        for g in (0, 1)]
    for th in threads:
        th.start()
    try:
        with GatewayClient(port_file=pf, num_gateways=2, seed=0) as c:
            w = c.hello(0)
            assert w["gateway"] == 0 and w["num_gateways"] == 2
            events = [[k % 10, 0.05 * k, 0.0] for k in range(40)]
            counts = c.send_events(events)
            assert sum(counts.values()) == 40
            # Misroute on purpose: user 1 sent to gateway 0 redirects,
            # the client follows to the owner and gets a real ack.
            resp = c.request(c.stamped({"op": "update", "user": 1,
                                        "t": 5.0}), gateway=0)
            assert resp["op"] == "ack"
            assert c.stats["redirected"] >= 1
            drains = c.request_each({"op": "drain"})
            assert all(r is not None and r["op"] == "drained"
                       for r in drains.values())
            incorporated = sum(r["incorporated"]
                               for r in drains.values())
            assert incorporated == 41    # 40 batched + 1 redirected
    finally:
        for th in threads:
            th.join(timeout=60)
    assert not any(th.is_alive() for th in threads)
    for g in (0, 1):
        assert os.path.exists(f"{tmp_path / 'hist.jsonl'}.g{g}")


def test_probe_fleet_reports_liveness(tmp_path):
    pf = str(tmp_path / "port")
    th = threading.Thread(target=run_gateway, kwargs=dict(
        cfg=_small_cfg(), gateway_index=0, num_gateways=1, port_file=pf,
        once=True, verbose=False))
    th.start()
    try:
        rows = probe_fleet(pf, 1, timeout=30)
    finally:
        th.join(timeout=60)
    assert rows[0]["ok"] and rows[0]["gateway_reported"] == 0
    assert rows[0]["backlog"] == 0
    # A fleet that never came up: rows report errors, nothing raises.
    dead = probe_fleet(str(tmp_path / "nope"), 2, timeout=0.2)
    assert len(dead) == 2
    assert not any(r["ok"] for r in dead)
    assert all("error" in r for r in dead)


# -------------------------------------------------- chaos rows (full tier)

@pytest.mark.slow
def test_chaos_mp_gateway_kill_row(tmp_path):
    """SIGKILL one gateway of a supervised fleet under driven load: zero
    lost acked updates, duplicates absorbed, SLO burn inside budget."""
    from fedtpu.resilience.chaos import run_scenario
    row = run_scenario("mp_gateway_kill", str(tmp_path), {}, 0, 0,
                       platform="cpu", timeout=570)
    assert row["ok"], row
    assert row["gang_restarts"] >= 1
    assert row["duplicate_drops"] >= 1
    assert row["lost_acked"] == 0


@pytest.mark.slow
def test_chaos_mp_store_shard_kill_row(tmp_path):
    """Shard death mid-round: the survivor absorbs ownership via
    flush/adopt and the degraded fleet's history is bitwise
    reproducible."""
    from fedtpu.resilience.chaos import run_scenario
    row = run_scenario("mp_store_shard_kill", str(tmp_path), {}, 0, 0,
                       platform="cpu", timeout=570)
    assert row["ok"], row
    assert row["history_match"] is True
