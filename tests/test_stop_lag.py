"""Stop-lag parity (VERDICT r4 'missing' #1): the reference reads its stop
signal one loop-top late (FL_CustomMLPCLassifierImplementation_Multiple_
Rounds.py:132 reads the signal set at :195) — does that train an extra
round fedtpu's immediate stop misses? EXECUTED answer: no. The doomed
iteration r+1 breaks before its Barrier/train_one_epoch, so detection at
round r leaves exactly r trained AND r averaged rounds, which is the round
fedtpu already stops at. The lag's only observable residue is the second
message ("Training stopped early at round N.") printed from the doomed
iteration — reproduced by fedtpu's loop for log-faithful A/B.

These tests pin that claim by EXECUTING the reference's own
``train_and_evaluate`` (imported read-only from /root/reference under a
fake single-rank comm — no MPI needed) against fedtpu's loop on an
identical plateau, rather than trusting a reading of the code: a
--stop-lag-parity flag was deliberately NOT added, because the behavior it
would emulate (one extra trained round) is not what the reference does.
"""

import importlib.util
import os
import sys
import types

import numpy as np
import pytest

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           OptimConfig, RunConfig, ShardConfig)
from fedtpu.orchestration.loop import run_experiment

REF = ("/root/reference/"
       "FL_CustomMLPCLassifierImplementation_Multiple_Rounds.py")

# One plateau, both drivers: constant metrics from round 1. Round 1 seeds
# prev_metric; rounds 2..4 count patience 3 down to 0 -> detection at
# round 4 (1-indexed).
PATIENCE = 3
DETECTION_ROUND = 4


class _FakeComm:
    """Single-rank stand-in for MPI.COMM_WORLD: every collective is the
    identity, so the reference's control flow runs unchanged."""

    def Get_rank(self):
        return 0

    def Get_size(self):
        return 1

    def bcast(self, obj, root=0):
        return obj

    def gather(self, obj, root=0):
        return [obj]

    def Barrier(self):
        pass

    def Abort(self):
        raise RuntimeError("comm.Abort")


def _load_reference_module():
    fake = types.ModuleType("mpi4py")
    fake.MPI = types.SimpleNamespace(COMM_WORLD=_FakeComm())
    saved = sys.modules.get("mpi4py")
    sys.modules["mpi4py"] = fake
    try:
        spec = importlib.util.spec_from_file_location("_ref_multiround", REF)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    finally:
        if saved is None:
            del sys.modules["mpi4py"]
        else:
            sys.modules["mpi4py"] = saved
    return mod


@pytest.mark.skipif(not os.path.exists(REF),
                    reason="reference checkout not present")
def test_reference_trains_exactly_the_detection_round_count(capsys):
    """Execute the reference's train_and_evaluate on a canned plateau and
    count its side effects: detection at round r must leave r trainings
    and r averagings — NOT r+1 — and print both stop messages."""
    ref = _load_reference_module()
    comm = _FakeComm()
    rng = np.random.RandomState(0)
    fl = ref.FederatedMLPLearning(rng.randn(64, 5).astype("float32"),
                                  rng.randint(0, 2, 64), rank=0, size=1)
    calls = {"train": 0, "avg": 0}
    fl.train_one_epoch = lambda: calls.__setitem__("train",
                                                   calls["train"] + 1)
    fl.evaluate_local = lambda: {"accuracy": 0.5, "precision": 0.5,
                                 "recall": 0.5, "f1": 0.5}
    fl.federated_averaging = lambda c: calls.__setitem__("avg",
                                                         calls["avg"] + 1)
    history = fl.train_and_evaluate(comm, rounds=20,
                                    termination_patience=PATIENCE,
                                    tolerance=1e-4)
    out = capsys.readouterr().out
    assert calls["train"] == DETECTION_ROUND
    # The post-detection averaging at :198 still runs in the detection
    # round itself (after the signal is set) — but never again.
    assert calls["avg"] == DETECTION_ROUND
    assert len(history["accuracy"]) == DETECTION_ROUND
    assert "Early stopping triggered" in out
    # The doomed iteration's message carries its 0-indexed loop variable,
    # which equals the 1-indexed detection round.
    assert f"Training stopped early at round {DETECTION_ROUND}." in out


def _plateau_cfg(rounds):
    # learning_rate=0 freezes every client model and same_init makes the
    # round-1 averaging the identity, so metrics are bit-identical from
    # round 1 on — the fedtpu analogue of the canned constant metrics.
    return ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256),
        shard=ShardConfig(num_clients=8),
        optim=OptimConfig(learning_rate=0.0),
        fed=FedConfig(rounds=rounds, termination_patience=PATIENCE,
                      tolerance=1e-4, same_init=True),
        run=RunConfig(),
    )


def test_fedtpu_stops_at_the_reference_trained_round_count(capsys):
    res = run_experiment(_plateau_cfg(rounds=20), verbose=True)
    out = capsys.readouterr().out
    assert res.stopped_early
    assert res.rounds_run == DETECTION_ROUND
    for k in ("accuracy", "precision", "recall", "f1"):
        assert len(res.global_metrics[k]) == DETECTION_ROUND
    assert "Early stopping triggered" in out
    assert f"Training stopped early at round {DETECTION_ROUND}." in out


def test_no_doomed_iteration_message_when_detection_hits_the_last_round():
    """Reference parity at the boundary: detection on the FINAL round means
    the loop never re-enters, so the second message must not print."""
    import io
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        res = run_experiment(_plateau_cfg(rounds=DETECTION_ROUND),
                             verbose=True)
    out = buf.getvalue()
    assert res.stopped_early
    assert res.rounds_run == DETECTION_ROUND
    assert "Early stopping triggered" in out
    assert "Training stopped early" not in out
