"""CLI layer (the reference has none — SURVEY.md §1 L6)."""

import json

from fedtpu.cli import main


def test_presets_listing(capsys):
    assert main(["presets"]) == 0
    out = capsys.readouterr().out
    for name in ("income-2", "income-8", "sklearn-parity", "income-32-noniid",
                 "cifar10-32"):
        assert name in out


def test_run_with_overrides_json(capsys):
    rc = main(["run", "--preset", "income-8", "--csv", "", "--rounds", "3",
               "--num-clients", "4", "--quiet", "--json"])
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    summary = json.loads(line)
    assert summary["rounds_run"] == 3
    assert "accuracy" in summary["final_global_metrics"]
