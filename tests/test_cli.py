"""CLI layer (the reference has none — SURVEY.md §1 L6)."""

import json

from fedtpu.cli import main


def test_presets_listing(capsys):
    assert main(["presets"]) == 0
    out = capsys.readouterr().out
    for name in ("income-2", "income-8", "sklearn-parity", "income-32-noniid",
                 "cifar10-32"):
        assert name in out


def test_run_with_overrides_json(capsys):
    rc = main(["run", "--preset", "income-8", "--csv", "", "--rounds", "3",
               "--num-clients", "4", "--quiet", "--json"])
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    summary = json.loads(line)
    assert summary["rounds_run"] == 3
    assert "accuracy" in summary["final_global_metrics"]


def test_sweep_table_jsonl(tmp_path, monkeypatch):
    # Shrink the grid (2 archs x 9 lrs) — the full 10x9 takes minutes on CPU;
    # the full-size grid is exercised by the recorded TPU run (RESULTS.md).
    from fedtpu.sweep import grid
    monkeypatch.setattr(grid, "HIDDEN_GRID", ((8,), (8, 8)))
    path = str(tmp_path / "table.jsonl")
    rc = main(["sweep", "--csv", "", "--num-clients", "2",
               "--table-jsonl", path, "--quiet"])
    assert rc == 0
    rows = [json.loads(l) for l in open(path)]
    assert len(rows) == 2 * 9
    assert {"hidden_layer_sizes", "learning_rate", "accuracy",
            "f1"} <= set(rows[0])


def test_sweep_bad_table_path_fails_fast(monkeypatch):
    import pytest
    from fedtpu.sweep import grid

    def boom(*a, **k):                    # the sweep must never start
        raise AssertionError("sweep ran despite bad table path")

    monkeypatch.setattr(grid, "run_grid_search", boom)
    with pytest.raises(FileNotFoundError):
        main(["sweep", "--csv", "", "--num-clients", "2",
              "--table-jsonl", "/nonexistent-dir/t.jsonl", "--quiet"])


def test_sweep_honors_local_steps(tmp_path, monkeypatch):
    from fedtpu.sweep import grid
    seen = {}
    real = grid.run_grid_search

    def spy(cfg, **kw):
        seen.update(kw)
        kw.setdefault("hidden_grid", ((8,),))
        kw.setdefault("lr_grid", (0.004,))
        return real(cfg, **kw)

    monkeypatch.setattr(grid, "run_grid_search", spy)
    main(["sweep", "--csv", "", "--num-clients", "2", "--local-steps", "7",
          "--quiet"])
    assert seen.get("local_steps") == 7


def test_run_new_aggregation_flags_reach_config(monkeypatch):
    """--server-opt / --dp-* / --compress / --robust-* / --byzantine-clients
    must land in FedConfig (a dropped override silently runs the wrong
    experiment)."""
    import fedtpu.cli as cli
    captured = {}

    def spy(cfg, verbose=True, resume=False):
        captured["fed"] = cfg.fed

        class R:
            def summary(self):
                return {}
        return R()

    import fedtpu.orchestration.loop as loop
    monkeypatch.setattr(loop, "run_experiment", spy)
    rc = cli.main(["run", "--csv", "", "--rounds", "1",
                   "--server-opt", "fedyogi", "--server-lr", "0.05",
                   "--server-momentum", "0.8",
                   "--dp-clip-norm", "2.0", "--dp-noise-multiplier", "0.2",
                   "--weighting", "uniform", "--quiet"])
    assert rc == 0
    fed = captured["fed"]
    assert fed.server_opt == "fedyogi"
    assert fed.server_lr == 0.05
    assert fed.server_momentum == 0.8
    assert fed.dp_clip_norm == 2.0
    assert fed.dp_noise_multiplier == 0.2

    rc = cli.main(["run", "--csv", "", "--rounds", "1",
                   "--compress", "int8", "--quiet"])
    assert rc == 0
    assert captured["fed"].compress == "int8"

    rc = cli.main(["run", "--csv", "", "--rounds", "1",
                   "--weighting", "uniform",
                   "--robust-aggregation", "krum", "--krum-f", "1",
                   "--byzantine-clients", "1", "--quiet"])
    assert rc == 0
    fed = captured["fed"]
    assert fed.robust_aggregation == "krum"
    assert fed.krum_f == 1
    assert fed.byzantine_clients == 1

    rc = cli.main(["run", "--csv", "", "--rounds", "1",
                   "--weighting", "uniform",
                   "--robust-aggregation", "trimmed_mean",
                   "--trim-ratio", "0.2", "--quiet"])
    assert rc == 0
    assert captured["fed"].trim_ratio == 0.2


def test_run_compile_flags_reach_run_config(monkeypatch, tmp_path):
    """--compilation-cache / --overlap-compile must land in RunConfig —
    that is how run_experiment, the sweep, and library callers get the
    persistent-cache / background-compile behavior."""
    import fedtpu.cli as cli
    import fedtpu.orchestration.loop as loop
    captured = {}

    def spy(cfg, verbose=True, resume=False):
        captured["run"] = cfg.run

        class R:
            def summary(self):
                return {}
        return R()

    monkeypatch.setattr(loop, "run_experiment", spy)
    import os

    import jax
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    cache_dir = str(tmp_path / "cc")
    try:
        rc = cli.main(["run", "--csv", "", "--rounds", "1",
                       "--compilation-cache", cache_dir,
                       "--overlap-compile", "--quiet"])
    finally:
        # main() applies the cache config process-globally; scope it here.
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          prev_min)
    assert rc == 0
    assert captured["run"].compilation_cache == os.path.abspath(cache_dir)
    assert captured["run"].overlap_compile is True
    # Defaults stay off: no flag, no cache, no overlap.
    rc = cli.main(["run", "--csv", "", "--rounds", "1", "--quiet"])
    assert rc == 0
    assert captured["run"].compilation_cache is None
    assert captured["run"].overlap_compile is False


def test_run_compress_end_to_end_via_cli(capsys):
    rc = main(["run", "--csv", "", "--rounds", "2", "--num-clients", "4",
               "--compress", "int8", "--quiet", "--json"])
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(line)["rounds_run"] == 2


def test_compilation_cache_flag_populates_cache(tmp_path):
    # --compilation-cache must be applied BEFORE any compile, so repeat CLI
    # invocations serve their XLA executables from disk. Subprocesses: the
    # cache config is process-global.
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cache = tmp_path / "xlacache"
    cmd = [sys.executable, "-m", "fedtpu.cli", "run", "--csv", "",
           "--num-clients", "2", "--hidden-sizes", "8", "--rounds", "1",
           "--compilation-cache", str(cache), "--quiet", "--json"]
    # Threshold 0: cache even the tiny CPU test program deterministically
    # (the CLI respects the env var and must not clobber it).
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="0")
    r = subprocess.run(cmd, cwd=repo, env=env, capture_output=True,
                       text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    assert cache.is_dir() and len(list(cache.iterdir())) > 0


def test_every_documented_flag_exists_in_the_parser():
    """Docs-accuracy guard: every `--flag` README/docs/API.md/PARITY.md
    mention must exist in the real CLI parser (doc rot on the flag surface
    fails loudly here)."""
    import os
    import re

    from fedtpu.cli import build_parser

    parser = build_parser()
    known = set()
    # Top-level + every subparser's option strings.
    subactions = [a for a in parser._actions
                  if a.__class__.__name__ == "_SubParsersAction"]
    for sp in [parser] + [p for a in subactions
                          for p in a.choices.values()]:
        for act in sp._actions:
            known.update(act.option_strings)

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    documented = set()
    for rel in ("README.md", "docs/API.md", "docs/ARCHITECTURE.md",
                "docs/observability.md", "docs/analysis.md",
                "docs/performance.md", "docs/resilience.md",
                "docs/serving.md", "docs/scaling.md", "docs/autoscale.md",
                "docs/robustness.md",
                "PARITY.md",
                "benchmarks/RESULTS.md"):
        text = open(os.path.join(root, rel)).read()
        # Underscores ARE captured so `--dp_clip_norm`-style typos show up
        # as unknown flags instead of silently failing to match.
        documented.update(re.findall(
            r"(?<![\w/-])(--[a-z][a-z0-9_-]+)(?![a-z0-9_-])", text))
    # Flags documented for OTHER executables, not fedtpu.cli.
    other_tools = {"--reps",                       # benchmarks/*.py
                   "--out",                        # bench.py result file
                   "--eval-every",                 # accuracy_parity.py
                   "--min-speedup",                # benchmarks/compile_bench.py
                   "--socket-events",              # benchmarks/serving_bench.py
                   "--skip-socket",                # benchmarks/serving_bench.py
                   "--trace",                      # benchmarks/async_bench.py
                   "--scale", "--total-clients",   # benchmarks/scaling.py
                   "--store",                      # benchmarks/scaling.py
                   "--write",     # python -m fedtpu.telemetry.timeline_sim
                   "--xla_force_host_platform_device_count",  # XLA flag
                   "--hostfile", "--np"}           # mpirun (reference docs)
    missing = documented - known - other_tools
    assert not missing, f"docs mention unknown CLI flags: {sorted(missing)}"
    # And the guard itself must be live: the docs do document real flags.
    assert len(documented & known) > 20
