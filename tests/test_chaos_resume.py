"""Kill-and-resume chaos test (VERDICT r2 item 5): SIGKILL a REAL
checkpointed CLI run mid-training in a subprocess, resume it with
``--resume``, and require the resumed run to reach the exact same final
state and metric history as an uninterrupted run of the same command.

This is the crash path the checkpoint subsystem exists for — the
reference loses everything on any failure (FL_CustomMLP...:203-205 is a
bare driver with no persistence; SURVEY.md §5). The in-process resume
machinery is covered by tests/test_checkpoint.py; here the process
actually dies (SIGKILL — no atexit, no finally blocks), relying on
orbax's atomic commit so the latest on-disk checkpoint is always a
complete one.
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from fedtpu.orchestration.checkpoint import latest_step, load_checkpoint

ROUNDS = 200          # cap; the run early-stops deterministically first
CKPT_EVERY = 2
HIDDEN = "32"
# SIGKILL once this checkpoint exists. The earliest one maximizes the
# remaining window (the ~8 later orbax saves, ~100-300 ms each on this
# box, dominate it) so the child can't slip to a clean exit between the
# poll and the signal.
KILL_AT_STEP = 2


def _cmd(ckpt_dir, keep=None):
    cmd = [sys.executable, "-m", "fedtpu.cli", "run",
           "--csv", "", "--platform", "cpu",
           "--rounds", str(ROUNDS), "--hidden-sizes", HIDDEN,
           "--checkpoint-dir", ckpt_dir,
           "--checkpoint-every", str(CKPT_EVERY),
           "--quiet", "--json"]
    if keep is not None:
        cmd += ["--keep-checkpoints", str(keep)]
    return cmd


def _env():
    # Hermetic CPU subprocess (the CLI's --platform cpu does the real pin;
    # stripping the flags mirrors tests/test_multihost_e2e.py).
    return {k: v for k, v in os.environ.items()
            if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}


def _run_to_completion(ckpt_dir, extra=()):
    out = subprocess.run(_cmd(ckpt_dir) + list(extra), env=_env(),
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-4000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_sigkill_mid_training_then_resume_matches_uninterrupted(tmp_path):
    ck_a = str(tmp_path / "uninterrupted")
    ck_b = str(tmp_path / "killed")

    summary_a = _run_to_completion(ck_a)
    assert summary_a["rounds_run"] < ROUNDS  # early stop fired: real run

    # Same command, but SIGKILL the process as soon as checkpoint
    # KILL_AT_STEP exists (well before the early-stop round). The kill is
    # inherently a wall-clock race against the child finishing; up to 3
    # attempts absorb a lost race on a descheduled box instead of flaking.
    for attempt in range(3):
        if os.path.isdir(ck_b):
            shutil.rmtree(ck_b)
        proc = subprocess.Popen(_cmd(ck_b, keep=2), env=_env(),
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        try:
            deadline = time.time() + 240
            while time.time() < deadline:
                step = latest_step(ck_b)
                if step is not None and step >= KILL_AT_STEP:
                    break
                if proc.poll() is not None:
                    break                  # finished early: lost the race
                time.sleep(0.02)
            else:
                pytest.fail("no checkpoint appeared before the deadline")
            proc.send_signal(signal.SIGKILL)
        finally:
            # Failure paths reach here with the child still alive — kill
            # before wait() or the test blocks on the full (or wedged) run.
            if proc.poll() is None:
                proc.kill()
            proc.wait()
        if proc.returncode != 0:
            break                          # killed mid-run: window won
    assert proc.returncode != 0, \
        "child completed before SIGKILL on 3 attempts — widen the window"
    killed_at = latest_step(ck_b)
    assert killed_at is not None
    assert killed_at < summary_a["rounds_run"]  # it really died mid-run

    # Resume the killed run; it must finish the job. The killed run and
    # its resume both run under retention (--keep-checkpoints 2): a
    # SIGKILL between a save and its GC, or mid-GC, must never leave a
    # state resume can't use (VERDICT r3 #7).
    summary_b = _run_to_completion(
        ck_b, extra=("--resume", "--keep-checkpoints", "2"))

    # The headline assertion: metric history and final state of
    # (killed + resumed) are EXACTLY the uninterrupted run's.
    assert summary_b["rounds_run"] == summary_a["rounds_run"]
    assert summary_b["stopped_early"] == summary_a["stopped_early"]
    assert summary_b["final_global_metrics"] == \
        summary_a["final_global_metrics"]

    step_a, step_b = latest_step(ck_a), latest_step(ck_b)
    assert step_a == step_b
    # Retention bounded the killed+resumed run's disk: at most the 2
    # newest rounds plus the protected best-accuracy round remain.
    from fedtpu.orchestration.checkpoint import complete_steps
    assert len(complete_steps(ck_b)) <= 3
    # Mirror the CLI's effective config (income-8 preset, --csv "" ->
    # synthetic data, --hidden-sizes 32) to build a state template.
    import dataclasses

    from fedtpu.config import get_preset
    from fedtpu.orchestration.loop import build_experiment
    base = get_preset("income-8")
    exp = build_experiment(base.replace(
        data=dataclasses.replace(base.data, csv_path=None,
                                 dataset_name=None),
        model=dataclasses.replace(base.model, hidden_sizes=(32,))))
    state_a, hist_a, _ = load_checkpoint(ck_a, state_like=exp.state)
    state_b, hist_b, _ = load_checkpoint(ck_b, state_like=exp.state)
    for k in hist_a:
        np.testing.assert_array_equal(hist_a[k], hist_b[k])
    import jax
    for a, b in zip(jax.tree.leaves(state_a), jax.tree.leaves(state_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
