"""Causal fleet tracing (docs/observability.md): schema-v2 identity and
v1 back-compat, the timeline merger + deterministic renderer, trace_id
determinism across retries, the crash flight recorder (ring bounds,
tracer flush, the supervisor's exit-75 flush), the merged-report
identity keying over colliding run_ids, and the defense/network/MFU
Prometheus export.

The committed golden ``tests/goldens/timeline_sim.jsonl`` is gated here
(and by ``fedtpu check --timeline-sim``): the pinned two-gateway
campaign replayed through the REAL serving engines must render
bitwise-identically — one retried update must read as a single trace_id
whose chain shows client_stamp -> wal -> admit -> buffer_insert ->
incorporate and then the retry's client_stamp -> dedup_drop.
"""

import json
import os
import sys

from fedtpu.serving import protocol
from fedtpu.telemetry.report import aggregate, render_prometheus, render_text
from fedtpu.telemetry.timeline import (STAGES, chrome_trace,
                                       default_artifacts,
                                       deterministic_lines, load_timeline,
                                       trace_chains)
from fedtpu.telemetry.trace import (FLIGHT_RECORDER_CAPACITY, FlightRecorder,
                                    NullTracer, Tracer, crash_artifact_path)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ trace_id

def test_trace_id_deterministic_across_retry():
    """A retry resends the SAME (nonce, seq) stamp, so it must map to the
    same trace_id — that equality is what folds the retry into the
    original update's causal chain."""
    tid = protocol.trace_id("client-nonce-7", 3)
    assert tid == protocol.trace_id("client-nonce-7", 3)      # the retry
    assert len(tid) == 16 and int(tid, 16) >= 0               # hex, stable width
    assert tid != protocol.trace_id("client-nonce-7", 4)      # next frame
    assert tid != protocol.trace_id("client-nonce-8", 3)      # other client
    # numeric-string seq normalizes like the int (wire JSON roundtrip)
    assert tid == protocol.trace_id("client-nonce-7", "3")


# -------------------------------------------------- v1 -> v2 back-compat

def _v1_line(kind, rnd=None, payload=None):
    return {"v": 1, "run_id": "oldrun", "kind": kind, "phase": None,
            "round": rnd, "t_start": 0.5, "dur_s": 0.1,
            "payload": payload or {}}


def test_v1_events_read_with_identity_defaults(tmp_path):
    p = tmp_path / "ev.jsonl"
    with open(p, "w") as fh:
        for r in range(3):
            fh.write(json.dumps(_v1_line("round", rnd=r)) + "\n")
    src, = load_timeline([str(p)])
    assert src["type"] == "events" and src["label"] == "run"
    agg = aggregate(src["records"], src["malformed"])
    assert agg["identities"] == [
        {"run_id": "oldrun", "role": "run", "process_index": 0}]
    assert agg["rounds"]["count"] == 3
    # single-source report: no fleet "sources:" line in the text view
    assert "sources:" not in render_text(agg)


# -------------------------------------- merged report / colliding run_ids

def _v2_line(kind, role, pidx, rnd=None, payload=None, rid="sharedrun"):
    return {"v": 2, "run_id": rid, "kind": kind, "round": rnd,
            "t_start": 1.0, "dur_s": 0.01, "process_index": pidx,
            "pid": 1234, "launch_id": "L0", "role": role,
            "payload": payload or {}}


def _fleet_events():
    """Two gateways restored from one lineage: run_id COLLIDES, only the
    v2 (role, process_index) identity tells them apart."""
    ev = []
    for r in range(2):
        ev.append(_v2_line("round", "run", 0, rnd=r))
    for g in (0, 1):
        ev.append(_v2_line("serve_tick", f"gateway-{g}", g, rnd=1,
                           payload={"version": 1}))
        ev.append(_v2_line("serve_screened", f"gateway-{g}", g, rnd=1,
                           payload={"n_screened": 2 + g}))
        ev.append(_v2_line("net_fault", f"gateway-{g}", g,
                           payload={"gateway": g, "fault": "drop_frame"}))
    ev.append(_v2_line("serve_quarantine", "gateway-0", 0, rnd=2,
                       payload={"user": 5, "strikes": 3}))
    return ev


def test_merged_report_keys_colliding_run_ids():
    agg = aggregate(_fleet_events())
    assert agg["run_ids"] == ["sharedrun"]          # the collision
    idents = [(i["role"], i["process_index"]) for i in agg["identities"]]
    assert idents == [("gateway-0", 0), ("gateway-1", 1), ("run", 0)]
    txt = render_text(agg)
    assert "sources: gateway-0/p0, gateway-1/p1, run/p0" in txt


def test_prometheus_exports_defense_and_network():
    prom = render_prometheus(aggregate(_fleet_events()))
    assert "fedtpu_screened_updates_total 5" in prom          # 2 + 3
    assert "fedtpu_quarantined_users 1" in prom
    assert 'fedtpu_net_faults_fired_total{gateway="0"} 1' in prom
    assert 'fedtpu_net_faults_fired_total{gateway="1"} 1' in prom


# -------------------------------------------------- flight recorder ring

def test_flight_recorder_ring_bounds(tmp_path):
    fr = FlightRecorder()
    for i in range(3 * FLIGHT_RECORDER_CAPACITY):
        fr.record(f"line-{i}")
    assert len(fr) == FLIGHT_RECORDER_CAPACITY        # bounded
    lines = fr.lines()
    assert lines[0] == f"line-{2 * FLIGHT_RECORDER_CAPACITY}"  # oldest kept
    assert lines[-1] == f"line-{3 * FLIGHT_RECORDER_CAPACITY - 1}"
    out = tmp_path / "crash.jsonl"
    assert fr.flush(str(out)) == FLIGHT_RECORDER_CAPACITY
    assert out.read_text().splitlines() == lines
    # flush never raises from a crash path — bad target returns 0
    assert fr.flush(str(tmp_path / "no" / "such" / "dir" / "x")) == 0
    assert FlightRecorder().flush(str(tmp_path / "empty.jsonl")) == 0
    assert not (tmp_path / "empty.jsonl").exists()    # empty ring: no file


def test_tracer_flush_crash_writes_artifact(tmp_path):
    ev = tmp_path / "events.jsonl"
    tr = Tracer(str(ev), role="serve")
    try:
        tr.event("serve_tick", round=1, version=2)
        path = tr.flush_crash(reason="handler:boom")
    finally:
        tr.close()
    assert path == crash_artifact_path(str(ev), "serve")
    assert path.endswith("events.crash.serve.jsonl")
    recs = [json.loads(l) for l in open(path)]
    assert recs[0]["kind"] == "serve_tick" and recs[0]["role"] == "serve"
    assert recs[-1]["kind"] == "crash_flush"
    assert recs[-1]["payload"]["reason"] == "handler:boom"
    assert all(r["v"] == 2 for r in recs)
    assert NullTracer().flush_crash(reason="x") is None   # telemetry off


def test_supervisor_flushes_flight_recorder_on_exit_75(tmp_path):
    """A child that keeps exiting 75 (preempted) with the restart budget
    at zero takes the supervisor's budget_exhausted exit path — which
    must leave the post-mortem events.crash.supervisor.jsonl behind."""
    from fedtpu.resilience.supervisor import supervise
    ev = tmp_path / "ev.jsonl"
    rc = supervise(["unused-arg"], max_restarts=0, backoff_base=0.01,
                   backoff_max=0.02, events=str(ev), verbose=False,
                   _cmd_prefix=[sys.executable, "-c", "import sys; sys.exit(75)"])
    assert rc == 75
    crash = tmp_path / "events.crash.supervisor.jsonl"
    assert crash.exists() and crash.stat().st_size > 0
    recs = [json.loads(l) for l in open(crash)]
    assert recs[-1]["kind"] == "crash_flush"
    assert recs[-1]["payload"]["reason"] == "budget_exhausted:rc=75"
    assert any(r["kind"] == "child_exit" for r in recs)
    assert all(r.get("role") == "supervisor" for r in recs)


# ------------------------------------------------------- timeline merger

def _trace_line(role, pidx, stage, tid, rnd, **extra):
    line = _v2_line("trace", role, pidx, rnd=rnd,
                    payload={"trace_id": tid, **extra})
    line["phase"] = stage       # the causal stage rides the phase field
    return line


def test_timeline_merges_and_orders_chains(tmp_path):
    tid = protocol.trace_id("nonce", 0)
    gw = tmp_path / "ev.jsonl.g0"
    with open(gw, "w") as fh:
        # Written out of causal order on purpose: the chain must sort by
        # (tick, stage rank), not file position.
        fh.write(json.dumps(_trace_line("gateway-0", 0, "incorporate",
                                        tid, 2)) + "\n")
        fh.write(json.dumps(_trace_line("gateway-0", 0, "client_stamp",
                                        tid, 1, user=4, seq=0)) + "\n")
        fh.write(json.dumps(_trace_line("gateway-0", 0, "wal",
                                        tid, 1)) + "\n")
    net = tmp_path / "ev.jsonl.g0.netlog"
    with open(net, "w") as fh:
        fh.write(json.dumps({"gateway": 0, "seed": 7, "digest": "d"}) + "\n")
        fh.write(json.dumps({"summary": {"frames": 1}}) + "\n")
    dec = tmp_path / "decisions.jsonl"
    with open(dec, "w") as fh:
        fh.write(json.dumps({"v": 1, "version": 3, "t": 0.5,
                             "decisions": [{"kind": "scale_up"}]}) + "\n")

    sources = load_timeline([str(dec), str(net), str(gw)])
    assert [s["label"] for s in sources] == ["autoscale", "gateway-0",
                                             "proxy-0"]
    assert [s["type"] for s in sources] == ["decisions", "events", "netlog"]

    chains = trace_chains(sources)
    assert len(chains) == 1 and chains[0]["chain"] == tid
    assert [s["stage"] for s in chains[0]["stages"]] == [
        "client_stamp", "wal", "incorporate"]
    assert all(s["stage"] in STAGES for s in chains[0]["stages"])

    lines = deterministic_lines(sources)
    rows = [json.loads(l) for l in lines]
    headers = [r for r in rows if "source" in r]
    assert [(h["source"], h["records"]) for h in headers] == [
        ("autoscale", 1), ("gateway-0", 3), ("proxy-0", 2)]
    # goldenability: no wall-clock or process accidents survive
    for r in rows:
        for banned in ("t_start", "dur_s", "pid", "run_id", "launch_id"):
            assert banned not in r
        assert str(tmp_path) not in json.dumps(r)     # no paths leak
    assert lines == deterministic_lines(load_timeline(
        [str(gw), str(dec), str(net)]))               # argv-order stable

    trace = chrome_trace(sources)
    names = {e["name"] for e in trace["traceEvents"] if e["ph"] == "M"}
    assert names == {"process_name"}
    phs = {e["ph"] for e in trace["traceEvents"]}
    assert "s" in phs and "f" in phs                  # flow arrows stitched

    expanded = default_artifacts(str(tmp_path / "ev.jsonl"))
    assert str(gw) in expanded and str(net) in expanded


# ------------------------------------------------- the tier-1 golden gate

def test_timeline_sim_matches_committed_golden():
    """The pinned two-gateway campaign replayed through the REAL serving
    engines (client stamps, gateway WAL, session dedup, K-buffer,
    incorporation) must render bitwise-identically to the committed
    golden — the gate over the whole causal-tracing chain."""
    from fedtpu.telemetry.timeline_sim import compare_decisions, simulate
    sim = simulate()
    cmp = compare_decisions(
        sim["lines"],
        os.path.join(REPO, "tests", "goldens", "timeline_sim.jsonl"))
    assert cmp["ok"], cmp["reason"]
    s = sim["summary"]
    assert s["retry_duplicate"]
    # The retried frame's single trace_id reads as one causal chain:
    # the original pass ends in incorporate, the retry in dedup_drop.
    stages = s["retry_stages"]
    for stage in ("client_stamp", "wal", "admit", "buffer_insert",
                  "incorporate", "dedup_drop"):
        assert stage in stages, (stage, stages)
    assert stages.index("incorporate") < stages.index("dedup_drop")
    assert sum(s["incorporated"]) == s["arrivals"]    # exactly-once
    assert sum(s["duplicate_drops"]) >= 1
