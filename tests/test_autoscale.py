"""fedtpu.autoscale — the SLO-driven control plane (ISSUE 11 tier-1
suite).

Pins the contracts the autoscale subsystem documents:
- the virtual-time simulator is bitwise-deterministic and its decision
  sequence matches the COMMITTED golden through the CLI gate (the
  acceptance criterion — `fedtpu autoscale --simulate --golden`);
- the default threshold policy honors hysteresis (N consecutive hot
  snapshots before acting) and cooldown (a refractory window after
  every action), and a preemption NOTICE bypasses both with the
  pre_drain ordered BEFORE the shrink;
- the SignalBus fold: version stamping, SLO-burn math off the
  cumulative le-bucket histogram, and preferring a stats payload's own
  exported burn over recomputation;
- the serving engine's machine-readable signals block, runtime
  configure, and the pre-drain durability spool;
- `fedtpu report` over multiple sinks: combined + per-source view,
  the autoscale section, and heartbeat status rows.

The full control-plane drill (serve + gang + live controller under a
real preemption notice) is the slow-marked chaos row at the bottom.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from fedtpu.autoscale.controller import (compare_decisions, simulate,
                                         write_decisions)
from fedtpu.autoscale.policy import (HOLD, PRE_DRAIN, SHRINK, Decision,
                                     ThresholdHysteresisPolicy, get_policy,
                                     register_policy)
from fedtpu.autoscale.signals import (SignalBus, Snapshot,
                                      read_gang_members, slo_burn_from_hist)
from fedtpu.cli import main as cli_main
from fedtpu.config import AutoscaleConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "goldens", "autoscale_sim.jsonl")


def _snap(version=0, t=0.0, **kw):
    return Snapshot(version=version, t=t, **kw)


# ------------------------------------------------------------- simulator

def test_simulate_decision_sequence_is_bitwise_deterministic():
    """Two fresh simulations of the seeded trace produce byte-identical
    decision JSONL — the property the committed golden rests on — and
    the run exercises the interesting paths: the backlog drains fully
    (not truncated by the safety valve) and the mid-burst preemption
    notice spools real pending work."""
    a, b = simulate(), simulate()
    assert a["lines"] == b["lines"]
    assert len(a["lines"]) >= 10
    s = a["summary"]
    assert s["control_ticks"] == len(a["lines"])
    assert not s["truncated"]
    assert s["spooled"] > 0                  # the notice hit a real backlog
    assert s["decisions"].get("pre_drain") == 1
    assert s["incorporated"] == s["admitted"]
    assert s["backlog_end"] == 0


def test_autoscale_cli_matches_committed_golden(capsys):
    """The tier-1 gate: the CLI simulation replays bitwise against the
    committed golden and says so (audit-gate idiom)."""
    rc = cli_main(["autoscale", "--simulate", "--golden", GOLDEN])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert f"golden: matches {GOLDEN}" in out


def test_autoscale_cli_fails_on_divergent_golden(tmp_path, capsys):
    """A tampered golden must FAIL the gate with the first divergence
    named — silent pass on mismatch would make the contract decorative."""
    lines = simulate()["lines"]
    rec = json.loads(lines[0])
    rec["t"] += 1.0
    bad = [json.dumps(rec, sort_keys=True, separators=(",", ":"))]
    bad += lines[1:]
    path = str(tmp_path / "bad.jsonl")
    write_decisions(path, bad)
    rc = cli_main(["autoscale", "--simulate", "--golden", path])
    out = capsys.readouterr().out
    assert rc == 1
    assert "first divergence at line 1" in out


def test_golden_is_clean_decision_contract():
    """The committed artifact itself: every line parses, is in canonical
    form (sorted keys, no whitespace — byte comparison IS the check),
    carries schema v1, and the sequence contains exactly one pre_drain
    ordered immediately before a shrink."""
    with open(GOLDEN, encoding="utf-8") as fh:
        raw = [ln.rstrip("\n") for ln in fh if ln.strip()]
    assert len(raw) >= 10
    kinds_per_line = []
    for i, line in enumerate(raw):
        rec = json.loads(line)
        assert line == json.dumps(rec, sort_keys=True,
                                  separators=(",", ":"))
        assert rec["v"] == 1
        assert rec["version"] == i          # gapless snapshot versions
        kinds_per_line.append([d["kind"] for d in rec["decisions"]])
    pre = [ks for ks in kinds_per_line if PRE_DRAIN in ks]
    assert len(pre) == 1
    assert pre[0].index(PRE_DRAIN) < pre[0].index(SHRINK)


def test_compare_decisions_reports_count_and_divergence(tmp_path):
    path = str(tmp_path / "g.jsonl")
    write_decisions(path, ["a", "b", "c"])
    assert compare_decisions(["a", "b", "c"], path)["ok"]
    short = compare_decisions(["a", "b"], path)
    assert not short["ok"] and "count 2 != golden 3" in short["reason"]
    div = compare_decisions(["a", "X", "c"], path)
    assert not div["ok"] and "line 2" in div["reason"]
    gone = compare_decisions(["a"], str(tmp_path / "missing.jsonl"))
    assert not gone["ok"] and "unreadable" in gone["reason"]


# ---------------------------------------------------------------- policy

def _hot(version, t):
    return _snap(version, t, backlog=10_000)     # >> backlog_high


def _cold(version, t):
    return _snap(version, t, backlog=0)


def test_threshold_policy_requires_consecutive_hot_ticks():
    """hysteresis_ticks=3: two hot snapshots hold; the third scales up
    with the full action triple; one cold snapshot in between resets
    the streak."""
    cfg = AutoscaleConfig(hysteresis_ticks=3, cooldown_ticks=0)
    pol = ThresholdHysteresisPolicy(cfg)
    st = pol.initial_state()
    d1, st = pol.decide(_hot(0, 0.5), st)
    d2, st = pol.decide(_hot(1, 1.0), st)
    assert [d.kind for d in d1] == [HOLD] and [d.kind for d in d2] == [HOLD]
    # A cold tick resets the hot streak — two more hots still hold.
    _, st = pol.decide(_cold(2, 1.5), st)
    d4, st = pol.decide(_hot(3, 2.0), st)
    d5, st = pol.decide(_hot(4, 2.5), st)
    assert [d.kind for d in d4] == [HOLD] and [d.kind for d in d5] == [HOLD]
    d6, st = pol.decide(_hot(5, 3.0), st)
    assert [d.kind for d in d6] == ["grow", "set_tick_cadence",
                                    "set_cohort_size"]
    assert d6[1].value == cfg.tick_fast_s
    assert d6[2].value == float(cfg.cohort_high)


def test_threshold_policy_cooldown_is_refractory():
    """Every action opens cooldown_ticks of forced holds: a still-hot
    system cannot re-trigger until the actuated change has had a chance
    to land."""
    cfg = AutoscaleConfig(hysteresis_ticks=1, cooldown_ticks=2)
    pol = ThresholdHysteresisPolicy(cfg)
    st = pol.initial_state()
    d, st = pol.decide(_hot(0, 0.5), st)
    assert d[0].kind == "grow"
    for v in (1, 2):
        d, st = pol.decide(_hot(v, 0.5 + 0.5 * v), st)
        assert [x.kind for x in d] == [HOLD]
    d, st = pol.decide(_hot(3, 2.0), st)
    assert d[0].kind == "grow"               # cooldown elapsed, acts again


def test_preemption_notice_bypasses_hysteresis():
    """A notice on the very first snapshot — zero hot history, backlog
    quiet — still acts immediately: pre_drain(victim) strictly before
    shrink, then the cooldown applies so the next tick holds."""
    cfg = AutoscaleConfig(hysteresis_ticks=5, cooldown_ticks=3)
    pol = ThresholdHysteresisPolicy(cfg)
    d, st = pol.decide(_snap(0, 0.5, notice=1), pol.initial_state())
    assert [x.kind for x in d] == [PRE_DRAIN, SHRINK]
    assert d[0].victim == 1
    d2, st = pol.decide(_hot(1, 1.0), st)
    assert [x.kind for x in d2] == [HOLD]


def test_policy_registry_rejects_duplicates_and_unknown_names():
    assert isinstance(get_policy("threshold", AutoscaleConfig()),
                      ThresholdHysteresisPolicy)
    with pytest.raises(ValueError, match="already registered"):
        register_policy("threshold", ThresholdHysteresisPolicy)
    with pytest.raises(ValueError, match="unknown policy"):
        get_policy("nope", AutoscaleConfig())


def test_decision_shape_is_closed():
    with pytest.raises(ValueError, match="unknown decision kind"):
        Decision("explode")
    # Fixed serialized shape: no optional keys for the bitwise golden.
    assert set(Decision(HOLD).to_json()) == {"kind", "n", "value", "victim"}


# --------------------------------------------------------------- signals

def test_slo_burn_from_hist_math():
    # 4 observations against bins (..., 1.0, ...): 1 above the 1.0
    # bound. Violating share 0.25 over budget 0.1 => burn 2.5.
    hist = {"count": 4, "bins": [0.5, 1.0, 5.0],
            "bucket_counts": [1, 3, 4]}
    assert slo_burn_from_hist(hist, 1.0, 0.1) == pytest.approx(2.5)
    # Objective beyond the last bound: everything passes.
    assert slo_burn_from_hist(hist, 10.0, 0.1) == 0.0
    # Missing / empty histograms are quiet zeros, not crashes.
    assert slo_burn_from_hist(None, 1.0, 0.1) == 0.0
    assert slo_burn_from_hist({"count": 0}, 1.0, 0.1) == 0.0
    with pytest.raises(ValueError, match="error_budget"):
        slo_burn_from_hist(hist, 1.0, 0.0)


def test_signal_bus_folds_stats_and_prefers_exported_burn():
    bus = SignalBus(objective_s=1.0, error_budget=0.1)
    hist = {"count": 4, "bins": [1.0], "bucket_counts": [3]}
    # The stats payload's own slo_burn (the serving engine's export)
    # wins over histogram recomputation — live and sim read one number.
    s1 = bus.fold(1.0, stats={"backlog": 7, "slo_burn": 0.125},
                  latency_hist=hist)
    assert s1.version == 0 and s1.backlog == 7 and s1.slo_burn == 0.125
    # No exported burn: fall back to the histogram fold.
    s2 = bus.fold(2.0, stats={"backlog": 1},
                  members=[(0, "serving"), (1, "parked")], notice=1,
                  latency_hist=hist)
    assert s2.version == 1                      # auto-increments
    assert s2.slo_burn == pytest.approx(2.5)
    assert s2.members == ((0, "serving"), (1, "parked"))
    assert s2.notice == 1
    # Snapshots serialize with the full fixed shape.
    assert s2.to_json()["v"] == 1
    with pytest.raises(ValueError):
        SignalBus(objective_s=0.0)


def test_read_gang_members_statuses(tmp_path):
    from fedtpu.resilience.distributed import heartbeat_path_for
    from fedtpu.resilience.supervisor import write_heartbeat

    base = str(tmp_path / "hb")
    write_heartbeat(heartbeat_path_for(base, 0), status="serving")
    write_heartbeat(heartbeat_path_for(base, 1), status="parked")
    now = time.time()
    members = read_gang_members(base, 4, now=now)
    assert members == ((0, "serving"), (1, "parked"), (2, "missing"),
                       (3, "missing"))
    # An old beat downgrades to stale — except parked, which is the
    # supervisor's deliberate steady state, not a liveness failure.
    members = read_gang_members(base, 2, now=now + 1000.0)
    assert members == ((0, "stale"), (1, "parked"))


def test_admission_window_rates_slide_and_evict():
    from fedtpu.serving.admission import (ACCEPT, REJECT_BACKPRESSURE,
                                          AdmissionController,
                                          AdmissionPolicy)
    ctl = AdmissionController(AdmissionPolicy(max_pending=1, window_s=2.0))
    assert ctl.decide(0.0, 0, 0) == ACCEPT
    assert ctl.decide(0.5, 0, 5) == REJECT_BACKPRESSURE
    win = ctl.window_rates(1.0)
    assert win["decisions"] == 2
    assert win["rates"][ACCEPT] == 0.5
    assert win["rates"][REJECT_BACKPRESSURE] == 0.5
    # The accept at t=0 slides out of the 2 s window; cumulative counts
    # are untouched (one bookkeeping path, two views).
    win = ctl.window_rates(2.5)
    assert win["decisions"] == 1
    assert win["rates"][REJECT_BACKPRESSURE] == 1.0
    assert ctl.counts[ACCEPT] == 1
    # Empty window: all-zero shares, no division crash.
    assert ctl.window_rates(100.0)["rates"][ACCEPT] == 0.0


# ---------------------------------------------------- engine integration

def test_engine_signals_configure_and_pre_drain(tmp_path):
    """The serving side of the control loop, against a real engine:
    signals() exposes the machine-readable block off the engine's own
    bookkeeping, configure() retargets cadence/cohort mid-run, and
    pre_drain() spools the pending queue WITHOUT consuming it."""
    from fedtpu.config import ServingConfig
    from fedtpu.serving.engine import ServingEngine
    from fedtpu.telemetry.metrics import MetricsRegistry

    eng = ServingEngine(ServingConfig(cohort=8, buffer_size=2,
                                      tick_interval_s=100.0, data_rows=64,
                                      model_hidden=(8,), seed=0),
                        registry=MetricsRegistry())
    for u in range(3):
        eng.offer(0.1 * (u + 1), u, 0.0)
    sig = eng.signals()
    assert sig["backlog"] == 3 and sig["admitted"] == 3
    assert sig["window_decisions"] == 3
    assert sig["rates"]["accept"] == 1.0
    assert sig["slo_burn"] == 0.0            # nothing incorporated yet
    assert eng.summary()["signals"]["backlog"] == 3   # same block, no fork
    applied = eng.configure(tick_interval_s=0.25, flush_every=64)
    assert applied == {"tick_interval_s": 0.25, "flush_every": 64}
    assert eng.signals()["tick_interval_s"] == 0.25
    spool = str(tmp_path / "spool.jsonl")
    n, path = eng.pre_drain(spool)
    assert (n, path) == (3, spool)
    assert len(eng.pending) == 3             # durability copy, not a drain
    with open(spool, encoding="utf-8") as fh:
        rows = [json.loads(ln) for ln in fh]
    assert [r["user"] for r in rows] == [0, 1, 2]
    # No spool_dir configured and no explicit path -> a loud error.
    with pytest.raises(ValueError, match="spool_dir"):
        eng.pre_drain()


# ---------------------------------------------------------------- report

def test_report_merges_sources_with_autoscale_and_heartbeats(tmp_path):
    """`fedtpu report a.jsonl b.jsonl --heartbeat hb --num-processes 2`:
    one combined aggregation (the autoscale section from the controller
    sink) plus the per-source view and live heartbeat rows."""
    from fedtpu.resilience.distributed import heartbeat_path_for
    from fedtpu.resilience.supervisor import write_heartbeat
    from fedtpu.telemetry import make_tracer
    from fedtpu.telemetry.report import render_report

    ctl_log = str(tmp_path / "ctl.jsonl")
    tracer = make_tracer(ctl_log)
    summary = simulate(tracer=tracer)["summary"]
    tracer.close()
    other_log = str(tmp_path / "serve.jsonl")
    with open(other_log, "w", encoding="utf-8") as fh:
        fh.write(json.dumps({"v": 1, "run_id": "x", "kind": "serve_start",
                             "phase": None, "round": None, "t_start": 0.0,
                             "dur_s": 0.0, "payload": {"port": 1}}) + "\n")
    hb = str(tmp_path / "hb")
    write_heartbeat(heartbeat_path_for(hb, 0), status="serving")
    write_heartbeat(heartbeat_path_for(hb, 1), status="parked")

    text, prom = render_report([ctl_log, other_log], heartbeat=hb,
                               process_count=2)
    assert f"control ticks: {summary['control_ticks']}" in text
    assert "pre_drain" in text
    assert "per-source view" in text
    assert ctl_log in text and other_log in text
    assert "heartbeat p0: serving" in text
    assert "heartbeat p1: parked" in text
    # Single-path str still works (the long-standing call shape).
    text_one, _ = render_report(ctl_log)
    assert "per-source view" not in text_one
    assert "autoscale" in text_one


# ------------------------------------------------------- chaos drill (slow)

@pytest.mark.slow
def test_chaos_autoscale_absorbs_preemption_without_restart(tmp_path):
    """The acceptance drill (`mp_autoscale_preempt`): serve under driven
    load + a 2-process gang; a preemption notice is absorbed by the
    CONTROLLER's pre-drain spool + live SIGUSR1 shrink — zero gang
    restarts, no lost admitted updates after the final drain, SLO burn
    within the pinned budget."""
    from fedtpu.resilience.chaos import (AUTOSCALE_BURN_BUDGET,
                                         AUTOSCALE_SCENARIO, run_scenario)
    from fedtpu.telemetry.report import aggregate, load_events

    wd = str(tmp_path)
    row = run_scenario(AUTOSCALE_SCENARIO, wd, {}, rounds=6, num_clients=4,
                       platform="cpu", timeout=600)
    assert row["ok"], row
    assert row["gang_restarts"] == 0
    assert row["reshards"] >= 1 and row["reshard_failures"] == 0
    assert row["spooled"] > 0
    assert row["lost_updates"] == 0 and row["backlog"] == 0
    assert row["acted"].get("pre_drain", 0) >= 1
    assert row["acted"].get("shrink", 0) >= 1
    assert row["slo_burn"] is not None
    assert row["slo_burn"] <= AUTOSCALE_BURN_BUDGET
    # The controller's decisions came back out of its events sink.
    events, bad = load_events(
        os.path.join(wd, f"{AUTOSCALE_SCENARIO}.ctl.events.jsonl"))
    agg = aggregate(events, malformed=bad)["autoscale"]
    assert agg["acted"].get("pre_drain", 0) >= 1
    assert agg["pre_drains"] and agg["pre_drains"][0]["spooled"] > 0


@pytest.mark.slow
def test_check_autoscale_sim_folds_golden_into_exit_code(tmp_path):
    """`fedtpu check --autoscale-sim` (satellite 6): the pinned golden
    folds into the one-shot health verdict; a divergent golden fails it
    in an otherwise healthy environment. Subprocess: check pins the
    platform at import time."""
    out = subprocess.run(
        [sys.executable, "-m", "fedtpu.cli", "check", "--json",
         "--autoscale-sim", GOLDEN],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    rep = json.loads(out.stdout)
    assert rep["autoscale_sim"]["ok"] is True
    bad = str(tmp_path / "bad.jsonl")
    write_decisions(bad, ["{}"])
    out = subprocess.run(
        [sys.executable, "-m", "fedtpu.cli", "check", "--json",
         "--autoscale-sim", bad],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode != 0
    rep = json.loads(out.stdout)
    assert rep["autoscale_sim"]["ok"] is False
