"""local_steps (classic FedAvg E local epochs) and prox_mu (FedProx):
defaults reproduce the reference exactly; the extensions obey their defining
identities."""

import jax
import numpy as np

from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.parallel import make_mesh, client_sharding
from fedtpu.parallel.round import build_round_fn, init_federated_state


def _single_client(local_steps=1, prox_mu=0.0, rounds=1):
    x, y = synthetic_income_like(64, 6, 2)
    packed = pack_clients(x, y, ShardConfig(num_clients=1, shuffle=False))
    mesh = make_mesh(num_devices=1, num_clients=1)
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig())
    state = init_federated_state(jax.random.key(5), mesh, 1, init_fn, tx)
    shard = client_sharding(mesh)
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    step = build_round_fn(mesh, apply_fn, tx, 2, local_steps=local_steps,
                          prox_mu=prox_mu)
    for _ in range(rounds):
        state, m = step(state, batch)
    return state, m


def test_local_steps_equals_rounds_for_single_client():
    """With one client, averaging is the identity, so E local steps in one
    round must equal E rounds of one step — bit-comparable trajectories
    (the LR schedule advances per optimizer update in both, like the
    reference's StepLR at :73)."""
    s3, _ = _single_client(local_steps=3, rounds=1)
    s1, _ = _single_client(local_steps=1, rounds=3)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-6, atol=1e-7),
        s3["params"], s1["params"])


def test_prox_zero_is_plain_fedavg():
    sp, _ = _single_client(local_steps=4, prox_mu=0.0)
    s0, _ = _single_client(local_steps=4)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=0, atol=0),
        sp["params"], s0["params"])


def test_prox_bounds_client_drift():
    """Larger mu must pull the post-round params closer to the round-start
    anchor (FedProx's defining property)."""
    x, y = synthetic_income_like(64, 6, 2)
    packed = pack_clients(x, y, ShardConfig(num_clients=1, shuffle=False))
    mesh = make_mesh(num_devices=1, num_clients=1)
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig())
    shard = client_sharding(mesh)
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}

    def drift(mu):
        state = init_federated_state(jax.random.key(5), mesh, 1, init_fn, tx)
        before = jax.tree.map(np.asarray, state["params"])
        step = build_round_fn(mesh, apply_fn, tx, 2, local_steps=8,
                              prox_mu=mu)
        state, _ = step(state, batch)
        after = jax.tree.map(np.asarray, state["params"])
        return sum(float(np.sum((a - b) ** 2)) for a, b in
                   zip(jax.tree.leaves(after), jax.tree.leaves(before)))

    d0, d_small, d_big = drift(0.0), drift(1.0), drift(100.0)
    assert d_big < d_small < d0


def test_engines_agree_with_local_steps_and_prox():
    from tests.test_tp import _engines
    # _engines builds both engines identically; push E>1 + prox through both.
    (s1, b1, step1), (s2, b2, step2) = _engines()
    from fedtpu.config import ModelConfig as MC, OptimConfig as OC
    from fedtpu.models import build_model as bm
    from fedtpu.parallel import tp
    # Rebuild steps with the extension knobs on the SAME states/batches.
    init_fn, apply_fn = bm(MC(input_dim=6, hidden_sizes=(16, 8)))
    tx = build_optimizer(OC())
    mesh1 = make_mesh(num_clients=8)
    mesh2 = tp.make_mesh_2d(2, 8)
    step1 = build_round_fn(mesh1, apply_fn, tx, 2, local_steps=3, prox_mu=0.5)
    step2 = tp.build_round_fn_2d(mesh2, apply_fn, tx, 2, local_steps=3,
                                 prox_mu=0.5)
    s1, m1 = step1(s1, b1)
    s2, m2 = step2(s2, b2)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=2e-5, atol=1e-5),
        s1["params"], s2["params"])
    np.testing.assert_allclose(float(m1["client_mean"]["accuracy"]),
                               float(m2["client_mean"]["accuracy"]),
                               atol=1e-6)
