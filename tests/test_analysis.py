"""fedtpu.analysis: rule engine fixtures, reporters, guards, self-lint.

Layout mirrors the subsystem: per-rule fixture snippets (positive +
negative + suppressed) against ``lint_source``, reporter goldens, CLI
exit-code contracts, and the runtime half (recompile sentinel /
transfer guard / ``fedtpu check``'s driver).
"""

import json
import os
import textwrap

import pytest

from fedtpu.analysis.engine import RULES, lint_paths, lint_source
from fedtpu.analysis.reporters import render_json, render_text

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def codes(src, path="fixture.py", **kw):
    return [f.rule for f in lint_source(textwrap.dedent(src), path, **kw).findings]


# ------------------------------------------------------------ rule fixtures
# Each rule: a seeded violation it must catch, a near-miss negative it
# must not flag, and the noqa'd variant it must suppress.

FIXTURES = {
    "FTP001": {
        "positive": """
            import jax
            @jax.jit
            def step(state, batch):
                return float(state["loss"])
            """,
        "negative": """
            import jax
            @jax.jit
            def step(state, batch):
                n = int(4)          # constant, not traced
                return state
            def host_process(metrics):
                return float(metrics["loss"])   # host path: never traced
            """,
        "suppressed": """
            import jax
            @jax.jit
            def step(state, batch):
                return float(state["loss"])  # fedtpu: noqa[FTP001] fixture
            """,
    },
    "FTP002": {
        "positive": """
            import jax
            def sample(seed):
                k = jax.random.key(seed)
                a = jax.random.normal(k, (3,))
                b = jax.random.uniform(k, (3,))
                return a + b
            """,
        "negative": """
            import jax
            def sample(seed, n):
                k = jax.random.key(seed)
                k1, k2 = jax.random.split(k)
                a = jax.random.normal(k1, (3,))
                b = jax.random.uniform(k2, (3,))
                for i in range(n):
                    b = b + jax.random.normal(jax.random.fold_in(k2, i))
                return a + b
            """,
        "suppressed": """
            import jax
            def sample(seed):
                k = jax.random.key(seed)
                a = jax.random.normal(k, (3,))
                b = jax.random.uniform(k, (3,))  # fedtpu: noqa[FTP002] fixture
                return a + b
            """,
    },
    "FTP003": {
        "positive": """
            import jax
            from functools import partial
            @partial(jax.jit, donate_argnums=(0,))
            def step(state, batch):
                return state
            def run(state, batch):
                new = step(state, batch)
                stale = state["params"]     # use-after-donate
                return new, stale
            """,
        "negative": """
            import jax
            from functools import partial
            @partial(jax.jit, donate_argnums=(0,))
            def step(state, batch):
                return state, 1.0
            def run(state, batch):
                state, m = step(state, batch)   # rebound in the same stmt
                return state, m
            """,
        "suppressed": """
            import jax
            from functools import partial
            @partial(jax.jit, donate_argnums=(0,))
            def step(state, batch):
                return state
            def run(state, batch):
                new = step(state, batch)
                stale = state["params"]  # fedtpu: noqa[FTP003] fixture
                return new, stale
            """,
    },
    "FTP004": {
        "positive": """
            import jax
            @jax.jit
            def step(x):
                if x > 0:
                    return x
                return -x
            """,
        "negative": """
            import jax
            def build(flag):
                @jax.jit
                def step(state, batch):
                    if flag and "buf" not in state:   # static: closure + `not in`
                        return state
                    if batch["x"].ndim > 2:           # static: shape metadata
                        return state
                    return state
                return step
            """,
        "suppressed": """
            import jax
            @jax.jit
            def step(x):
                if x > 0:  # fedtpu: noqa[FTP004] fixture
                    return x
                return -x
            """,
    },
    "FTP006": {
        "positive": """
            import jax
            def sweep(fns, xs):
                out = []
                for fn, x in zip(fns, xs):
                    out.append(jax.jit(fn)(x))   # wrapper rebuilt per iter
                return out
            """,
        "negative": """
            import jax
            def make(k: int):
                @jax.jit
                def f(x):
                    return x * k
                return f
            def sweep(step, xs):
                # hoisted wrapper + AOT idiom: .lower on a bound callable
                compiled = step.lower(xs[0]).compile()
                return [compiled(x) for x in xs]
            """,
        "suppressed": """
            import jax
            def once(fn, x):
                return jax.jit(fn)(x)  # fedtpu: noqa[FTP006] fixture
            """,
    },
    "FTP005": {
        "positive": """
            def f():
                print("hi")
            """,
        "negative": """
            import sys
            def f(log):
                log.info("hi")
                sys.stdout.write("raw\\n")   # not a bare print call
            """,
        "suppressed": """
            def f():
                print("hi")  # fedtpu: noqa[FTP005] fixture
            """,
    },
    "FTP007": {
        "positive": """
            import sys
            def worker(rc):
                sys.exit(rc)
            """,
        "negative": """
            import sys
            def worker(rc):
                raise RuntimeError(f"worker failed rc={rc}")
            def parse(argv):
                return sys.argv[1:]          # sys use, not an exit
            """,
        "suppressed": """
            import os
            def die():
                os._exit(7)  # fedtpu: noqa[FTP007] fixture
            """,
    },
    "FTP008": {
        "positive": """
            import jax
            def agg(x):
                return jax.lax.psum(x, "clients")
            """,
        "negative": """
            import jax
            CLIENTS_AXIS = "clients"
            def agg(x):
                return jax.lax.psum(x, "clients")
            def agg2(x, axis):
                return jax.lax.psum(x, axis)   # Name-valued axis: skipped
            """,
        "suppressed": """
            import jax
            def agg(x):
                return jax.lax.psum(x, "clients")  # fedtpu: noqa[FTP008] fixture
            """,
    },
    "FTP009": {
        "positive": """
            import socket
            def connect(host, port):
                s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                s.connect((host, port))
                return socket.create_connection((host, port))
            """,
        "negative": """
            import socket
            def connect(host, port):
                return socket.create_connection((host, port), timeout=5.0)
            """,
        "suppressed": """
            import socket
            def listener():
                s = socket.socket()  # fedtpu: noqa[FTP009] fixture
                return s
            """,
    },
    "FTP010": {
        "positive": """
            import jax, time
            step = jax.jit(lambda s, b: s)
            def bench(state, batch):
                t0 = time.perf_counter()
                state = step(state, batch)
                t1 = time.perf_counter()   # delta times the enqueue only
                return t1 - t0
            """,
        "negative": """
            import jax, time
            step = jax.jit(lambda s, b: s)
            def bench(state, batch):
                t0 = time.perf_counter()
                state = jax.block_until_ready(step(state, batch))
                t1 = time.perf_counter()   # synced: times real compute
                return t1 - t0
            def stamp(log):
                t0 = time.time()
                log.info("no device work between the reads")
                t1 = time.time()
                return t1 - t0
            """,
        "suppressed": """
            import jax, time
            step = jax.jit(lambda s, b: s)
            def bench(state, batch):
                t0 = time.perf_counter()
                state = step(state, batch)
                t1 = time.perf_counter()  # fedtpu: noqa[FTP010] fixture
                return t1 - t0
            """,
    },
    "FTP011": {
        "positive": """
            import threading
            class Pump:
                def __init__(self):
                    self.rows = []
                def start(self):
                    t = threading.Thread(target=self._worker)
                    t.start()
                    self.rows.append("started")   # races with the worker
                def _worker(self):
                    self.rows.append("tick")
            """,
        "negative": """
            import threading
            class Pump:
                def __init__(self):
                    self.rows = []
                    self._lock = threading.Lock()
                def start(self):
                    t = threading.Thread(target=self._worker)
                    t.start()
                    with self._lock:
                        self.rows.append("started")
                def _worker(self):
                    with self._lock:
                        self.rows.append("tick")
            """,
        "suppressed": """
            import threading
            class Pump:
                def __init__(self):
                    self.rows = []
                def start(self):
                    t = threading.Thread(target=self._worker)
                    t.start()
                    self.rows.append("started")  # fedtpu: noqa[FTP011] fixture
                def _worker(self):
                    self.rows.append("tick")
            """,
    },
    "FTP012": {
        "positive": """
            import signal
            import threading
            class Ctl:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.mode = None
                def install(self):
                    signal.signal(signal.SIGUSR1, self._on_sig)
                def _on_sig(self, signum, frame):
                    with self._lock:
                        self.mode = "shrink"
            """,
        "negative": """
            import signal
            class Ctl:
                def __init__(self):
                    self.mode = None
                def install(self):
                    signal.signal(signal.SIGUSR1, self._on_sig)
                def _on_sig(self, signum, frame):
                    if self.mode is None:
                        self.mode = "shrink"    # flag store: reentrant-safe
            """,
        "suppressed": """
            import signal
            import threading
            class Ctl:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.mode = None
                def install(self):
                    signal.signal(signal.SIGUSR1, self._on_sig)
                def _on_sig(self, signum, frame):
                    with self._lock:  # fedtpu: noqa[FTP012] fixture
                        self.mode = "shrink"
            """,
    },
    "FTP013": {
        "positive": """
            import json
            import time
            def emit(fh, row):
                row = dict(row)
                row["stamp"] = time.time()
                fh.write(json.dumps(row, sort_keys=True) + "\\n")
            """,
        "negative": """
            import json
            import time
            def emit(fh, members, spent):
                row = {"members": sorted(members), "spent_s": spent}
                fh.write(json.dumps(row, sort_keys=True) + "\\n")
            """,
        "suppressed": """
            import json
            import time
            def emit(fh, row):
                row = dict(row)
                row["stamp"] = time.time()
                fh.write(json.dumps(row, sort_keys=True) + "\\n")  # fedtpu: noqa[FTP013] fixture
            """,
    },
    "FTP101": {
        "positive": """
            def f(xs=[]):
                return xs
            """,
        "negative": """
            def f(xs=None, y=()):
                return xs or []
            """,
        "suppressed": """
            def f(xs=[]):  # fedtpu: noqa[FTP101] fixture
                return xs
            """,
    },
    "FTP102": {
        "positive": """
            def f(g):
                try:
                    g()
                except Exception:
                    pass
            """,
        "negative": """
            def f(g, log):
                try:
                    g()
                except ValueError:
                    pass
                except Exception as e:
                    log.warn(e)
            """,
        "suppressed": """
            def f(g):
                try:
                    g()
                except Exception:  # fedtpu: noqa[FTP102] fixture
                    pass
            """,
    },
}


@pytest.mark.parametrize("code", sorted(FIXTURES))
def test_rule_fixture_positive(code):
    assert code in codes(FIXTURES[code]["positive"]), (
        f"{code} missed its seeded violation")


@pytest.mark.parametrize("code", sorted(FIXTURES))
def test_rule_fixture_negative(code):
    assert code not in codes(FIXTURES[code]["negative"]), (
        f"{code} false-positived on its negative fixture")


@pytest.mark.parametrize("code", sorted(FIXTURES))
def test_rule_fixture_suppressed(code):
    result = lint_source(textwrap.dedent(FIXTURES[code]["suppressed"]),
                         "fixture.py")
    assert code not in [f.rule for f in result.findings]
    assert code in [f.rule for f in result.suppressed], (
        f"{code} suppression was not recorded")


def test_rule_fixtures_catch_seeded_violations():
    """Aggregate guard (quick tier): every registered FTP rule has a
    fixture that its checker actually fires on."""
    for code in RULES:
        assert code in FIXTURES, f"rule {code} has no fixture"
        assert code in codes(FIXTURES[code]["positive"])


# --------------------------------------------------------- engine semantics
def test_ftp002_tuple_unpack_reuse():
    """Keys bound by tuple-unpacking a split are tracked individually:
    reusing one element is the same bug as reusing a scalar key."""
    src = """
        import jax
        def f(k):
            k1, k2 = jax.random.split(k)
            a = jax.random.normal(k1, (3,))
            b = jax.random.uniform(k1, (3,))
            return a + b
    """
    assert codes(src) == ["FTP002"]
    clean = """
        import jax
        def f(k):
            k1, k2 = jax.random.split(k)
            return jax.random.normal(k1) + jax.random.uniform(k2)
    """
    assert codes(clean) == []


def test_ftp002_indexed_split_reuse():
    """Constant-indexed elements of a split result (`ks[0]`) are key
    identities; a dynamic index (`ks[i]`) is opaque and never flagged,
    and rebinding the array resets every derived identity."""
    src = """
        import jax
        def f(k):
            ks = jax.random.split(k, 3)
            a = jax.random.normal(ks[0])
            b = jax.random.uniform(ks[0])
            return a + b
    """
    assert codes(src) == ["FTP002"]
    clean = """
        import jax
        def f(k, i):
            ks = jax.random.split(k, 3)
            a = jax.random.normal(ks[0]) + jax.random.uniform(ks[1])
            b = jax.random.normal(ks[i]) + jax.random.uniform(ks[i])
            ks = jax.random.split(ks[2], 3)
            return a + b + jax.random.normal(ks[0])
    """
    assert codes(clean) == []


def test_select_and_ignore_filters():
    src = FIXTURES["FTP005"]["positive"]
    assert codes(src, select=["FTP005"]) == ["FTP005"]
    assert codes(src, select=["FTP101"]) == []
    assert codes(src, ignore=["FTP005"]) == []
    with pytest.raises(ValueError, match="FTP999"):
        codes(src, select=["FTP999"])


def test_syntax_error_is_a_finding_not_a_crash():
    result = lint_source("def broken(:\n", "bad.py")
    assert not result.clean
    assert result.parse_errors[0].rule == "FTP000"


def test_noqa_is_per_line_and_per_code():
    src = textwrap.dedent("""
        def f():
            print("a")  # fedtpu: noqa[FTP101] wrong code on purpose
            print("b")
        """)
    result = lint_source(src, "fixture.py")
    # Wrong code suppresses nothing; both prints surface.
    assert [f.rule for f in result.findings] == ["FTP005", "FTP005"]
    assert result.suppressed == []


def test_lint_paths_walks_and_dedupes(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "a.py").write_text("def f():\n    print('x')\n")
    (pkg / "__pycache__").mkdir()
    (pkg / "__pycache__" / "junk.py").write_text("print('never seen')\n")
    # Passing the dir AND the file must not double-count.
    result = lint_paths([str(pkg), str(pkg / "a.py")])
    assert result.files_checked == 1
    assert [f.rule for f in result.findings] == ["FTP005"]


# --------------------------------------------------------------- reporters
# ----------------------------------------- interprocedural rules (FTP011-013)
def test_ftp011_event_barrier_negative():
    """The scheduler's prefetch/writeback archetype: a cross-thread
    write/read pair ordered by an Event wait/set handoff is NOT a race."""
    src = """
        import threading
        from concurrent.futures import ThreadPoolExecutor
        class Sched:
            def __init__(self):
                self._pool = ThreadPoolExecutor(max_workers=1)
                self._wb_done = threading.Event()
                self._state = None
            def _prepare(self, wb_done):
                wb_done.wait(5.0)
                return self._state          # read AFTER writeback commits
            def run_chunk(self):
                self._wb_done = threading.Event()
                self._pool.submit(self._prepare, self._wb_done)
                self._state = {"round": 1}  # writeback...
                self._wb_done.set()         # ...then release the reader
        """
    assert "FTP011" not in codes(src)


def test_ftp011_unlocked_cross_thread_write_fires_interprocedurally():
    """The write happens two calls deep from the thread entry — only an
    interprocedural flow sees it."""
    src = """
        import threading
        class Relay:
            def __init__(self):
                self.count = 0
            def start(self):
                t = threading.Thread(target=self._loop)
                t.start()
            def _loop(self):
                self._tick()
            def _tick(self):
                self.count += 1
            def stats(self):
                return self.count
        """
    assert "FTP011" in codes(src)


def test_ftp011_prestart_writes_are_happens_before():
    """Writes in the starting function BEFORE .start() cannot race with
    the thread they configure (the netproxy port/_lsock pattern)."""
    src = """
        import threading
        class Relay:
            def __init__(self):
                self.port = 0
            def start(self):
                self.port = 4242            # before start(): ordered
                t = threading.Thread(target=self._loop)
                t.start()
            def _loop(self):
                use(self.port)
        """
    assert "FTP011" not in codes(src)


def test_ftp012_factory_returned_handler_resolves():
    """reshard archetype: the handler is a closure returned by a factory
    — registration by `signal.signal(sig, self._make(m))` still scans
    the closure body."""
    src = """
        import signal
        import threading
        class Ctl:
            def __init__(self):
                self._lock = threading.Lock()
                self.mode = None
            def install(self):
                signal.signal(signal.SIGUSR1, self._make("shrink"))
            def _make(self, mode):
                def _handler(signum, frame):
                    with self._lock:
                        self.mode = mode
                return _handler
        """
    assert "FTP012" in codes(src)


def test_ftp012_handler_reached_io_two_calls_deep():
    src = """
        import signal
        def install(report):
            def _handler(signum, frame):
                _note(report)
            signal.signal(signal.SIGTERM, _handler)
        def _note(report):
            print("caught")      # I/O + allocation off the safe list
        """
    assert "FTP012" in codes(src)


def test_ftp013_set_iteration_without_sort_keys_fires():
    src = """
        import json
        def emit(fh, ids):
            members = set(ids)
            fh.write(json.dumps({"members": list(members)}) + "\\n")
        """
    assert "FTP013" in codes(src)


def test_ftp013_compact_separators_without_sort_keys_fires():
    """Compact separators declare canonical intent (the golden-writer
    signature); omitting sort_keys there leaks dict insertion order."""
    src = """
        import json
        def send(sock, obj):
            sock.sendall(json.dumps(obj, separators=(",", ":")).encode())
        """
    assert "FTP013" in codes(src)


def test_ftp013_wall_clock_allowed_inside_timing_module():
    src = """
        import json
        import time
        def emit(fh):
            row = {"t": time.perf_counter()}
            fh.write(json.dumps(row, sort_keys=True) + "\\n")
        """
    assert "FTP013" not in codes(src, path="fedtpu/utils/timing.py")
    assert "FTP013" in codes(src, path="fedtpu/other.py")


def test_text_reporter_golden():
    result = lint_source('def f():\n    print("hi")\n', "pkg/mod.py")
    assert render_text(result) == (
        "pkg/mod.py:2:5: FTP005 bare print(); use the telemetry logger "
        "(fedtpu/telemetry/log.py) or a Tracer event\n"
        "1 finding, 0 suppressed, 1 file checked"
    )


def test_text_reporter_clean_golden():
    result = lint_source("x = 1\n", "pkg/mod.py")
    assert render_text(result) == "0 findings, 0 suppressed, 1 file checked"


def test_json_reporter_schema():
    result = lint_source('def f():\n    print("hi")\n', "pkg/mod.py")
    payload = json.loads(render_json(result))
    assert payload["schema_version"] == 1
    assert payload["clean"] is False
    assert payload["files_checked"] == 1
    (finding,) = payload["findings"]
    assert finding["rule"] == "FTP005"
    assert finding["path"] == "pkg/mod.py"
    assert finding["line"] == 2
    # Machine-readable rule catalog rides along.
    assert set(payload["rules"]) == set(RULES)


def test_sarif_reporter_round_trip():
    """`--format sarif` (satellite): valid SARIF 2.1.0 shape, every
    registered rule in the driver catalog, findings and suppressions
    round-trip with 1-based columns and source-relative URIs."""
    from fedtpu.analysis.reporters import render_sarif

    src = ('def f():\n    print("hi")\n'
           'def g():\n    print("ho")  # fedtpu: noqa[FTP005] fixture\n')
    result = lint_source(src, "pkg/mod.py")
    sarif = json.loads(render_sarif(result))
    assert sarif["version"] == "2.1.0"
    (run,) = sarif["runs"]
    assert run["tool"]["driver"]["name"] == "fedtpu-lint"
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} == set(RULES)
    live = [r for r in run["results"] if "suppressions" not in r]
    supp = [r for r in run["results"] if "suppressions" in r]
    assert len(live) == 1 and len(supp) == 1
    loc = live[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "pkg/mod.py"
    assert loc["region"]["startLine"] == 2
    assert loc["region"]["startColumn"] == 5       # 1-based for SARIF
    assert live[0]["ruleId"] == supp[0]["ruleId"] == "FTP005"
    assert supp[0]["suppressions"][0]["kind"] == "inSource"
    # Round-trip: the SARIF results reconstruct the engine's findings.
    got = {(r["ruleId"],
            r["locations"][0]["physicalLocation"]["artifactLocation"]["uri"],
            r["locations"][0]["physicalLocation"]["region"]["startLine"])
           for r in live}
    want = {(f.rule, f.path, f.line) for f in result.findings}
    assert got == want


def test_cli_lint_format_sarif(tmp_path, capsys):
    from fedtpu.cli import main as cli_main

    bad = tmp_path / "bad.py"
    bad.write_text("def f():\n    print('x')\n")
    assert cli_main(["lint", str(bad), "--format", "sarif"]) == 1
    sarif = json.loads(capsys.readouterr().out)
    assert sarif["runs"][0]["results"][0]["ruleId"] == "FTP005"


# --------------------------------------------------------------------- CLI
def test_cli_lint_exit_codes(tmp_path, capsys):
    from fedtpu.cli import main as cli_main

    bad = tmp_path / "bad.py"
    bad.write_text("def f():\n    print('x')\n")
    assert cli_main(["lint", str(bad)]) == 1
    out = capsys.readouterr().out
    assert f"{bad}:2:5: FTP005" in out

    assert cli_main(["lint", str(bad), "--ignore", "FTP005"]) == 0
    assert cli_main(["lint", str(bad), "--select", "FTP101"]) == 0
    capsys.readouterr()   # drain the text outputs before the JSON one

    assert cli_main(["lint", str(bad), "--format", "json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["findings"][0]["rule"] == "FTP005"

    with pytest.raises(SystemExit, match="FTP999"):
        cli_main(["lint", str(bad), "--select", "FTP999"])


def test_self_lint_fedtpu_is_clean():
    """Acceptance: `fedtpu lint fedtpu/` exits 0 — every finding in the
    package is fixed or justified with an inline noqa."""
    from fedtpu.cli import main as cli_main

    assert cli_main(["lint", os.path.join(REPO, "fedtpu")]) == 0


# ------------------------------------------------------------------ guards
def test_recompile_sentinel_counts_compiles_and_cached_calls_are_free():
    import jax
    import jax.numpy as jnp

    from fedtpu.analysis.guards import RecompileSentinel, RetraceError

    sentinel = RecompileSentinel(label="t")
    assert sentinel.available

    f = jax.jit(lambda x: x * 3)
    f(jnp.ones(4)).block_until_ready()      # warmup, uncounted

    with sentinel.armed():
        f(jnp.ones(4)).block_until_ready()  # cache hit
    assert sentinel.count == 0

    with sentinel.armed():
        f(jnp.ones(8)).block_until_ready()  # new shape: real retrace
    assert sentinel.count >= 1

    # fail=True raises at exit of the armed block — the tests' mode.
    strict = RecompileSentinel(label="t2", fail=True)
    with pytest.raises(RetraceError, match="unexpected recompile"):
        with strict.armed():
            f(jnp.ones(16)).block_until_ready()
    strict.disarm()  # idempotent; already disarmed by the context exit


def test_sentinel_counts_into_registry():
    import jax
    import jax.numpy as jnp

    from fedtpu.analysis.guards import RecompileSentinel
    from fedtpu.telemetry import MetricsRegistry

    reg = MetricsRegistry()
    sentinel = RecompileSentinel(label="t3", registry=reg)
    g = jax.jit(lambda x: x + 7)
    with sentinel.armed():
        g(jnp.ones(5)).block_until_ready()
    assert reg.counter("unexpected_retraces").value >= 1


def test_guards_transfer_disallow_blocks_host_pulls():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fedtpu.analysis.guards import guards

    y = jax.jit(lambda x: x * 2)(jnp.ones(3))  # fedtpu: noqa[FTP006] one-shot warmup compile for the guard test
    y.block_until_ready()
    # "disallow" blocks implicit host->device promotion (the class of
    # accidental transfer the round loop must never perform mid-window;
    # d2h of committed arrays counts as explicit in jax's taxonomy and
    # stays allowed — the metrics fetch at chunk boundaries is deliberate).
    with pytest.raises(Exception, match="[Dd]isallow"):
        with guards(transfer="disallow"):
            jnp.add(y, np.ones(3)).block_until_ready()
    # And the guard is scoped: the same op works after the block.
    assert np.asarray(jnp.add(y, np.ones(3)))[0] == 3.0


def test_guards_debug_nans_is_scoped():
    import jax

    from fedtpu.analysis.guards import guards

    before = jax.config.jax_debug_nans
    with guards(transfer="allow", nans=True):
        assert jax.config.jax_debug_nans is True
    assert jax.config.jax_debug_nans == before


@pytest.mark.slow
def test_run_check_round_step_is_retrace_free():
    """`fedtpu check`: the real income-8 round step must be cache-stable
    after warmup (this exact driver caught the round-counter placement
    retrace fixed in parallel/round.py / tp.py / async_fed.py)."""
    from fedtpu.analysis.check import run_check

    report = run_check(rounds=2, synthetic_rows=256)
    assert report["sentinel_available"]
    assert report["recompiles"] == 0
    assert report["ok"] is True
