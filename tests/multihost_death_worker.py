"""Worker for the process-death failure-propagation test (the reference's
``comm.Abort`` analogue, FL_CustomMLP...:203-205).

Both processes run one good federated round over the 2-process mesh; then
process 1 dies abruptly (``os._exit`` — no shutdown handshake, the SIGKILL
shape). Process 0 keeps stepping: its next cross-process collective blocks,
the coordination service notices the missed heartbeats within the (shortened)
``heartbeat_timeout_seconds``, and the JAX runtime TERMINATES the survivor
with a fatal "distributed service detected fatal errors" diagnostic. The
parent test asserts exactly that: survivors die fast and loudly — they never
hang and never keep computing a partial federation.
"""

import os
import sys
import time

HEARTBEAT_S = 10


def main():
    pid, nprocs, port, outdir = (int(sys.argv[1]), int(sys.argv[2]),
                                 sys.argv[3], sys.argv[4])
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from fedtpu.parallel import multihost

    multihost.initialize(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=nprocs, process_id=pid,
                         heartbeat_timeout_seconds=HEARTBEAT_S)

    import numpy as np
    from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
    from fedtpu.data.sharding import pack_clients
    from fedtpu.data.tabular import synthetic_income_like
    from fedtpu.models import build_model
    from fedtpu.ops import build_optimizer
    from fedtpu.parallel.mesh import make_mesh
    from fedtpu.parallel.round import build_round_fn, init_federated_state

    x, y = synthetic_income_like(200, 6, 2)
    packed = pack_clients(x, y, ShardConfig(num_clients=8, shuffle=False))
    mesh = make_mesh(num_clients=8)
    batch = multihost.distribute_client_batch(packed, mesh)
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig())
    state = init_federated_state(jax.random.key(1), mesh, 8, init_fn, tx,
                                 same_init=True)
    step = build_round_fn(mesh, apply_fn, tx, 2)

    state, m = step(state, batch)
    acc = float(np.asarray(m["client_mean"]["accuracy"]))
    with open(os.path.join(outdir, f"death_round1_{pid}.txt"), "w") as f:
        f.write(repr(acc))
    print(f"worker {pid}: round 1 ok acc={acc:.4f}", flush=True)  # fedtpu: noqa[FTP005] stdout IS the worker->parent IPC protocol

    if pid == 1:
        print(f"worker {pid}: dying abruptly now", flush=True)  # fedtpu: noqa[FTP005] stdout IS the worker->parent IPC protocol
        os._exit(77)  # fedtpu: noqa[FTP007] simulating an abrupt worker death is this script's whole job

    # Survivor: keep stepping AND fetching. The fetch is the part that can
    # hang — it must instead end in the runtime terminating this process.
    t0 = time.time()
    for i in range(1000):
        state, m = step(state, batch)
        _ = float(np.asarray(m["client_mean"]["accuracy"]))
        # Timestamped progress so the parent can verify the survivor was
        # genuinely blocked (no post-death rounds complete), not looping.
        with open(os.path.join(outdir, "survivor_progress.txt"), "a") as f:
            f.write(f"{i} {time.time() - t0:.1f}\n")
    # Unreachable if propagation works: the runtime must have killed us.
    with open(os.path.join(outdir, "survivor_never_died.txt"), "w") as f:
        f.write(f"{time.time() - t0:.1f}")
    sys.exit(3)  # fedtpu: noqa[FTP007] worker script exit code is the parent test's assertion signal


if __name__ == "__main__":
    main()
