"""Unit tests for fedtpu.orchestration.privacy.PrivacyLedger — the DP
RDP bookkeeping extracted from run_experiment (VERDICT r3 #8). The
end-to-end resume-composition behavior is pinned through run_experiment
in test_dp_accountant (test_resume_composes_heterogeneous_rdp,
test_noise_off_resume_segment_voids_the_guarantee); these tests pin the
ledger in isolation, including the advisor-r3 zero-order-overlap
projection."""

import math

import numpy as np

from fedtpu.config import FedConfig
from fedtpu.ops.dp_accountant import (DEFAULT_ORDERS, epsilon_from_rdp,
                                      rdp_vector)
from fedtpu.orchestration.privacy import PrivacyLedger


def _fed(**kw) -> FedConfig:
    base = dict(dp_clip_norm=1.0, dp_noise_multiplier=1.1,
                participation_rate=1.0)
    base.update(kw)
    return FedConfig(**base)


def test_fresh_run_accumulates_per_step():
    led = PrivacyLedger(_fed())
    per_step = np.asarray(rdp_vector(1.0, 1.1))
    np.testing.assert_allclose(led.rdp_at(7), per_step * 7)
    assert not led.base_assumed and not led.composed
    assert not led.void_at(7)


def test_noise_off_fresh_run_is_zero_curve():
    led = PrivacyLedger(_fed(dp_noise_multiplier=0.0, dp_clip_norm=0.0))
    assert np.all(led.rdp_at(100) == 0)
    meta = led.checkpoint_meta(100)
    # Persisted UNCONDITIONALLY (zero curve while DP is off) so a later
    # DP segment composes on top instead of guessing.
    assert np.all(np.asarray(meta["dp_rdp"]) == 0)
    assert not meta["dp_guarantee_void"]


def test_checkpoint_meta_roundtrips_exactly():
    led = PrivacyLedger(_fed())
    meta = led.checkpoint_meta(5)
    led2 = PrivacyLedger(_fed(dp_noise_multiplier=2.0), start_round=5,
                         restored_meta=meta)
    # Segment 2 charges its own sigma per round ON TOP of the restored
    # curve — exact heterogeneous composition.
    expect = (np.asarray(rdp_vector(1.0, 1.1)) * 5
              + np.asarray(rdp_vector(1.0, 2.0)) * 3)
    np.testing.assert_allclose(led2.rdp_at(8), expect)
    assert led2.composed and not led2.base_assumed


def test_same_length_curve_without_orders_is_trusted():
    saved = np.asarray(rdp_vector(1.0, 1.1)) * 4
    led = PrivacyLedger(_fed(), start_round=4,
                        restored_meta={"dp_rdp": saved})
    np.testing.assert_allclose(led.base, saved)
    assert not led.base_assumed


def test_partial_order_overlap_projects_monotone_upper_bound():
    # Old grid = today's grid minus its first order: surviving orders
    # project exactly; the missing smallest order gets the NEXT saved
    # order's value (Renyi divergence is non-decreasing in the order, so
    # that's a safe upper bound — never an under-report).
    old_orders = np.asarray(DEFAULT_ORDERS[1:])
    old_curve = np.linspace(0.1, 1.0, len(old_orders))
    led = PrivacyLedger(_fed(), start_round=3,
                        restored_meta={"dp_rdp": old_curve,
                                       "dp_rdp_orders": old_orders})
    assert led.base[0] == old_curve[0]
    np.testing.assert_allclose(led.base[1:], old_curve)
    assert not led.base_assumed
    assert math.isfinite(
        epsilon_from_rdp(list(led.rdp_at(3)), 1e-5)["epsilon"])


def test_orders_above_saved_max_drop_out_as_inf():
    # A saved grid covering only small orders: today's larger orders
    # cannot be bounded from it and get +inf — they drop out of the
    # epsilon minimization, which can only loosen epsilon.
    old_orders = np.asarray([2, 3, 4])
    old_curve = np.asarray([0.1, 0.2, 0.3])
    led = PrivacyLedger(_fed(), start_round=3,
                        restored_meta={"dp_rdp": old_curve,
                                       "dp_rdp_orders": old_orders})
    np.testing.assert_allclose(led.base[:3], old_curve)
    assert np.all(np.isinf(led.base[3:]))
    assert not led.base_assumed
    assert math.isfinite(
        epsilon_from_rdp(list(led.rdp_at(3)), 1e-5)["epsilon"])


def test_zero_order_overlap_projects_finite_not_inf():
    # Advisor r3: a disjoint order grid used to project to an all-inf
    # curve — epsilon=inf with no flag, indistinguishable from a
    # genuinely infinite spend. Monotonicity bounds every one of today's
    # orders by the smallest saved value at a LARGER order, so the
    # projection stays finite with no assumption at all.
    foreign_orders = np.asarray([1000, 2000, 3000])
    foreign_curve = np.asarray([0.5, 0.7, 0.9])
    led = PrivacyLedger(_fed(), start_round=6,
                        restored_meta={"dp_rdp": foreign_curve,
                                       "dp_rdp_orders": foreign_orders})
    np.testing.assert_allclose(led.base, np.full(led.base.shape, 0.5))
    assert not led.base_assumed
    assert math.isfinite(
        epsilon_from_rdp(list(led.rdp_at(6)), 1e-5)["epsilon"])


def test_noise_off_resume_never_zeroes_restored_spend():
    # Review r4 (laundering): resuming with noise OFF from a foreign-grid
    # curve with positive spend must preserve the spend — base stays
    # positive, void_at fires once unnoised rounds train, and the
    # persisted meta keeps both.
    led = PrivacyLedger(_fed(dp_noise_multiplier=0.0, dp_clip_norm=0.0),
                        start_round=6,
                        restored_meta={"dp_rdp": [0.5, 0.7, 0.9],
                                       "dp_rdp_orders": [1000, 2000, 3000]})
    assert np.any(led.base > 0)
    assert led.composed
    assert led.void_at(10)
    meta = led.checkpoint_meta(10)
    assert np.any(np.asarray(meta["dp_rdp"]) > 0)
    assert meta["dp_guarantee_void"]


def test_mismatched_curve_and_orders_lengths_degrades_not_crashes():
    # Cross-version or partially-written meta: len(dp_rdp) !=
    # len(dp_rdp_orders). No per-order attribution is trustworthy —
    # resume must degrade to the unattributable path, not IndexError.
    led = PrivacyLedger(_fed(), start_round=4,
                        restored_meta={"dp_rdp": np.asarray([0.1, 0.2, 0.3]),
                                       "dp_rdp_orders": np.asarray([2, 3])})
    np.testing.assert_allclose(led.base,
                               np.asarray(rdp_vector(1.0, 1.1)) * 4)
    assert led.base_assumed


def test_unattributable_spend_with_noise_off_is_inf_and_flagged():
    # Unidentifiable grid (no orders array, length mismatch) with noise
    # off: no rate to assume and nothing to project — the spend is
    # carried as +inf (over-report, the safe direction), flagged so the
    # report distinguishes it from a genuinely infinite spend.
    led = PrivacyLedger(_fed(dp_noise_multiplier=0.0, dp_clip_norm=0.0),
                        start_round=4,
                        restored_meta={"dp_rdp": np.asarray([0.1, 0.2])})
    assert np.all(np.isinf(led.base))
    assert led.base_assumed
    assert led.void_at(5)


def test_zero_order_overlap_with_zero_spend_stays_exact():
    # An all-zero curve is zero spend on ANY grid — no assumption needed
    # even when no order matches.
    led = PrivacyLedger(_fed(), start_round=6,
                        restored_meta={"dp_rdp": np.zeros(3),
                                       "dp_rdp_orders": [1000, 2000, 3000]})
    assert np.all(led.base == 0)
    assert not led.base_assumed


def test_unidentifiable_grid_assumes_current_rate():
    # Curve present, no orders array, length != today's grid: the spend
    # exists but cannot be attributed per order.
    led = PrivacyLedger(_fed(), start_round=4,
                        restored_meta={"dp_rdp": np.asarray([0.1, 0.2])})
    np.testing.assert_allclose(led.base,
                               np.asarray(rdp_vector(1.0, 1.1)) * 4)
    assert led.base_assumed


def test_pre_r3_checkpoint_without_curve():
    # Under a DP config the pre-resume rounds are charged at the current
    # rate, flagged; without DP a missing curve is simply zero.
    led = PrivacyLedger(_fed(), start_round=9, restored_meta={})
    np.testing.assert_allclose(led.base,
                               np.asarray(rdp_vector(1.0, 1.1)) * 9)
    assert led.base_assumed
    led_off = PrivacyLedger(_fed(dp_noise_multiplier=0.0, dp_clip_norm=0.0),
                            start_round=9, restored_meta={})
    assert np.all(led_off.base == 0) and not led_off.base_assumed


def test_guarantee_void_when_training_unnoised_after_noised():
    noised = PrivacyLedger(_fed())
    meta = noised.checkpoint_meta(5)
    cont = PrivacyLedger(_fed(dp_noise_multiplier=0.0, dp_clip_norm=0.0),
                         start_round=5, restored_meta=meta)
    # At the resume point itself nothing unnoised has trained yet.
    assert not cont.void_at(5)
    assert cont.void_at(6)
    # And the flag is sticky through a further checkpoint/resume cycle,
    # even under a noised continuation.
    meta2 = cont.checkpoint_meta(7)
    led3 = PrivacyLedger(_fed(), start_round=7, restored_meta=meta2)
    assert led3.void_at(7) and led3.void_at(20)


def test_assumed_flag_is_sticky_across_resumes():
    led = PrivacyLedger(_fed(), start_round=4,
                        restored_meta={"dp_rdp": np.asarray([0.1, 0.2])})
    assert led.base_assumed
    meta = led.checkpoint_meta(8)
    led2 = PrivacyLedger(_fed(), start_round=8, restored_meta=meta)
    assert led2.base_assumed


def test_sampling_rate_enters_per_step():
    full = PrivacyLedger(_fed(participation_rate=1.0))
    sub = PrivacyLedger(_fed(participation_rate=0.25))
    # Subsampling amplifies privacy: the subsampled curve is strictly
    # below full participation at every order.
    assert np.all(sub.per_step < full.per_step)
