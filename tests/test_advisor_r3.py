"""Regression tests for the round-2 advisor findings:

1. (medium) In pipelined_stop mode the chunk-end state-finiteness gate
   must ALSO run at periodic-checkpoint boundaries (the pipeline is
   already synced there), so a poisoned state can never persist as the
   latest good checkpoint that resume would restore.
2. (low) The deferred loop-exit state gate must label its quarantine
   checkpoint with the round the SAVED state corresponds to (`rnd`,
   which after a pipelined early stop includes the dropped in-flight
   overshoot chunk) — not `rounds_run`.
3. (low) measured_peak_flops must warn loudly when the slope is
   non-positive and it falls back to the fixed-cost-contaminated
   whole-chain estimate, instead of silently underestimating peak.
"""

import dataclasses
import time

import numpy as np
import pytest

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           ModelConfig, RunConfig, ShardConfig)
from fedtpu.orchestration import loop as loop_mod
from fedtpu.orchestration.checkpoint import latest_step, load_checkpoint
from fedtpu.orchestration.loop import build_experiment, run_experiment


def _cfg(**run_kw):
    return ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256,
                        synthetic_features=6),
        shard=ShardConfig(num_clients=4, shuffle=False),
        model=ModelConfig(input_dim=6, hidden_sizes=(8,)),
        fed=FedConfig(rounds=12, tolerance=0.0),
        run=RunConfig(rounds_per_step=3, **run_kw),
    )


def test_pipelined_periodic_ckpt_gated_on_state_finiteness(
        tmp_path, monkeypatch):
    # Force the state gate to report "poisoned" while metrics stay finite —
    # the exact scenario (overflowed Adam moments, finite metrics) the gate
    # documents. Before the fix, pipelined mode skipped the gate at
    # checkpoint boundaries and the periodic save persisted the poisoned
    # state as the latest checkpoint resume would restore.
    monkeypatch.setattr(loop_mod, "_tree_finite", lambda t: False)
    ck = str(tmp_path / "ck")
    cfg = _cfg(pipelined_stop=True, checkpoint_dir=ck, checkpoint_every=3)
    res = run_experiment(cfg, verbose=False)
    assert res.diverged and res.stopped_early
    # No periodic save may have happened: the first checkpoint boundary
    # (round 3) must hit the gate BEFORE save_checkpoint.
    assert latest_step(ck) is None
    assert latest_step(str(tmp_path / "ck" / "diverged")) == 3


def test_deferred_gate_quarantine_label_matches_saved_state(
        tmp_path, monkeypatch):
    # Pipelined early stop: the final state carries the dropped in-flight
    # overshoot chunk (state round > rounds_run). The deferred gate's
    # quarantine label must equal the SAVED state's round.
    monkeypatch.setattr(loop_mod, "_tree_finite", lambda t: False)
    ck = str(tmp_path / "ck")
    base = _cfg(pipelined_stop=True, checkpoint_dir=ck)
    cfg = dataclasses.replace(
        base, fed=dataclasses.replace(base.fed, rounds=30, tolerance=1.0,
                                      termination_patience=2))
    res = run_experiment(cfg, verbose=False)
    assert res.stopped_early and res.diverged
    label = latest_step(str(tmp_path / "ck" / "diverged"))
    assert label is not None
    # The contract under test: label == the round stored IN the saved state.
    exp = build_experiment(cfg)
    state, _, step = load_checkpoint(str(tmp_path / "ck" / "diverged"),
                                     state_like=exp.state)
    assert step == label == int(np.asarray(state["round"]))
    # And the overshoot is real: the saved state trained past the recorded
    # history (one in-flight chunk), so rounds_run alone would mislabel it.
    assert label > res.rounds_run


def test_sync_early_stop_exit_gate_catches_poisoned_state(
        tmp_path, monkeypatch):
    # Synchronous mode's one unchecked path: an early-stop break whose
    # final chunk poisoned the state while its pre-update metrics stayed
    # finite. The deferred exit gate must now cover it (review r3) —
    # before, the run returned diverged=False with NaN final params.
    monkeypatch.setattr(loop_mod, "_tree_finite", lambda t: False)
    ck = str(tmp_path / "ck")
    base = _cfg(checkpoint_dir=ck)
    cfg = dataclasses.replace(
        base, fed=dataclasses.replace(base.fed, rounds=30, tolerance=1.0,
                                      termination_patience=1))
    res = run_experiment(cfg, verbose=False)
    assert res.stopped_early and res.diverged
    label = latest_step(str(tmp_path / "ck" / "diverged"))
    exp = build_experiment(cfg)
    state, _, step = load_checkpoint(str(tmp_path / "ck" / "diverged"),
                                     state_like=exp.state)
    assert step == label == int(np.asarray(state["round"]))


def test_peak_flops_negative_slope_warns(monkeypatch):
    from fedtpu.utils.timing import measured_peak_flops

    # A clock that advances a fixed amount per call makes every timed
    # window identical -> slope exactly 0 -> the fallback path.
    tick = {"t": 0.0}

    def fake_counter():
        tick["t"] += 0.5
        return tick["t"]

    monkeypatch.setattr(time, "perf_counter", fake_counter)
    with pytest.warns(RuntimeWarning, match="non-positive slope"):
        peak = measured_peak_flops(dtype="float32", n=16, chains=(2, 4))
    assert peak > 0


def test_peak_flops_escalation_recovers_before_fallback(monkeypatch):
    """VERDICT r3 weak #7: one noisy attempt must not degrade to the
    contaminated whole-chain fallback — chain lengths escalate and a
    recovered slope returns the clean estimate, warning-free."""
    import warnings

    from fedtpu.utils.timing import measured_peak_flops

    # 12 timed perf_counter calls per attempt (2 chains x 3 windows x
    # start/stop). Attempt 0: every window identical -> slope 0. Attempt 1
    # (chains doubled to (4, 8)): second chain's windows take 1.0 s vs
    # 0.5 s -> slope recovers.
    calls = {"n": 0, "t": 0.0}

    def fake_counter():
        attempt, j = calls["n"] // 12, calls["n"] % 12
        calls["n"] += 1
        calls["t"] += 1.0 if (attempt >= 1 and j >= 6) else 0.5
        return calls["t"]

    monkeypatch.setattr(time, "perf_counter", fake_counter)
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        peak = measured_peak_flops(dtype="float32", n=16, chains=(2, 4))
    # Recovered on attempt 1 with ks=(4, 8): dt = 1.0 - 0.5 = 0.5 s.
    assert peak == pytest.approx(2.0 * 16**3 * (8 - 4) / 0.5)
