"""Jaxpr-level program auditor (fedtpu.analysis.program / .collectives).

Three layers, mirroring the auditor's own stack:

  * schedule extraction on hand-built shard_map programs — psum byte
    accounting, scan trip multiplication, and the AUD001 negative
    fixture (a lax.cond whose branches disagree on collectives);
  * donation proof on tiny jitted steps — the realized-alias positive,
    the AUD002 negative fixture (a donated buffer with no output to
    alias), and the ``alias_expected`` exemption for donate-to-free
    stream buffers;
  * the four real engines via the preset probes — trace-only (no
    compile), asserting the structural invariants the goldens pin:
    sync/cohort schedule parity, the async pull broadcast, and the
    GSPMD tp engine's empty explicit schedule.

The full compile-backed contract (digests, HLO census, donation tables)
lives in tests/goldens/audit_*.json, gated by test_audit_gate.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import fedtpu.parallel  # noqa: F401  (installs the jax.shard_map shim)
from fedtpu.analysis.collectives import (comm_bytes, extract_schedule,
                                         schedule_digest)
from fedtpu.analysis.program import (_PROBES, _synthetic_cfg,
                                     donation_proof, engine_audit_spec)
from fedtpu.parallel.mesh import make_mesh

P = jax.sharding.PartitionSpec
CLIENTS = "clients"


def _mesh():
    return make_mesh(num_clients=len(jax.devices()))


def _shard_mapped(body, mesh):
    return jax.shard_map(body, mesh=mesh, in_specs=P(CLIENTS),
                         out_specs=P(CLIENTS))


# ------------------------------------------------------- schedule extraction


def test_extract_schedule_counts_psum_bytes():
    mesh = _mesh()

    def body(x):
        return jax.lax.psum(x, CLIENTS) * x

    x = jnp.ones((len(jax.devices()), 4), jnp.float32)
    sched = extract_schedule(jax.make_jaxpr(_shard_mapped(body, mesh))(x))
    assert [op.op for op in sched.ops] == ["psum"]
    assert sched.ops[0].axes == (CLIENTS,)
    # per-shard operand: (1, 4) f32 = 16 bytes, one trip
    assert comm_bytes(sched.ops) == 16
    assert not sched.findings and not sched.has_dynamic


def test_scan_multiplies_collective_trips():
    mesh = _mesh()
    steps = 5

    def body(x):
        def inner(c, _):
            return c + jax.lax.psum(c, CLIENTS), None
        out, _ = jax.lax.scan(inner, x, None, length=steps)
        return out

    x = jnp.ones((len(jax.devices()), 4), jnp.float32)
    sched = extract_schedule(jax.make_jaxpr(_shard_mapped(body, mesh))(x))
    assert [op.trips for op in sched.ops] == [steps]
    assert comm_bytes(sched.ops) == 16 * steps


def test_branch_divergent_schedule_flags_aud001():
    """The AUD001 negative fixture: one cond branch psums, the other
    doesn't — the round's collective schedule depends on a runtime
    predicate, so SPMD ranks can disagree and deadlock."""
    mesh = _mesh()

    def body(x):
        return jax.lax.cond(x.sum() > 0,
                            lambda v: jax.lax.psum(v, CLIENTS),
                            lambda v: v * 2.0, x)

    x = jnp.ones((len(jax.devices()), 4), jnp.float32)
    sched = extract_schedule(jax.make_jaxpr(_shard_mapped(body, mesh))(x))
    codes = [f.code for f in sched.findings]
    assert codes == ["AUD001"]
    assert "branch" in sched.findings[0].message


def test_branch_identical_schedule_is_clean():
    mesh = _mesh()

    def body(x):
        return jax.lax.cond(x.sum() > 0,
                            lambda v: jax.lax.psum(v, CLIENTS) + 1.0,
                            lambda v: jax.lax.psum(v, CLIENTS) - 1.0, x)

    x = jnp.ones((len(jax.devices()), 4), jnp.float32)
    sched = extract_schedule(jax.make_jaxpr(_shard_mapped(body, mesh))(x))
    assert not sched.findings
    assert [op.op for op in sched.ops] == ["psum"]


# ------------------------------------------------------------ donation proof


def _compiled_text(step, *args):
    return step.lower(*args).compile().as_text()


def test_donation_proof_proves_realized_alias():
    step = jax.jit(lambda s: s * 2.0, donate_argnums=(0,))
    s = jnp.ones((1024,), jnp.float32)
    proof = donation_proof(_compiled_text(step, s), (s,), (0,))
    assert proof["ok"], proof
    assert [row["aliased"] for row in proof["table"]] == [True]


def test_donation_proof_flags_unaliased_aud002():
    """The AUD002 negative fixture: `b` is donated but the program emits
    no output of its shape, so the donation can never be realized."""
    step = jax.jit(lambda s, b: s + b.sum(), donate_argnums=(0, 1))
    s = jnp.ones((1024,), jnp.float32)
    b = jnp.ones((2048,), jnp.float32)
    proof = donation_proof(_compiled_text(step, s, b), (s, b), (0, 1))
    assert not proof["ok"]
    codes = [f.code for f in proof["findings"]]
    assert codes == ["AUD002"]
    by_alias = {row["shape"][0]: row["aliased"] for row in proof["table"]}
    assert by_alias == {1024: True, 2048: False}


def test_alias_expected_exempts_consumed_stream_buffers():
    """Same program, but arg 1 declared donate-to-free (the cohort-xs
    idiom): the row stays unaliased in the table, with no finding."""
    step = jax.jit(lambda s, b: s + b.sum(), donate_argnums=(0, 1))
    s = jnp.ones((1024,), jnp.float32)
    b = jnp.ones((2048,), jnp.float32)
    proof = donation_proof(_compiled_text(step, s, b), (s, b), (0, 1),
                           alias_expected=(0,))
    assert proof["ok"], proof
    assert [row["aliased"] for row in proof["table"]] == [True, False]


def test_sub_floor_unaliased_leaf_is_table_only():
    proof_rows = donation_proof(
        "HloModule m, entry_computation_layout={()->()}",  # no alias header
        (jnp.ones((4,), jnp.float32),), (0,))
    # 16 bytes < the 1 KiB defect floor: recorded, not flagged.
    assert proof_rows["table"][0]["aliased"] is False
    assert proof_rows["ok"], proof_rows


# ----------------------------------------------------------- engine schedules


def _trace_engine(name, preset="income-2"):
    cfg = _synthetic_cfg(preset, 256)
    step, args, spec, mesh, _ = _PROBES[name](cfg)
    return extract_schedule(jax.make_jaxpr(step)(*args)), spec, mesh


def test_sync_engine_schedule_is_pure_psum():
    sched, spec, _ = _trace_engine("sync")
    assert sched.ops, "sync engine traced to an empty schedule"
    assert {op.op for op in sched.ops} == {"psum"}
    assert all(op.axes == (CLIENTS,) for op in sched.ops)
    assert not sched.findings
    assert comm_bytes(sched.ops) > 0
    assert spec["engine"] == "sync"


def test_cohort_schedule_matches_sync_parity():
    """The cohort scheduler's design claim: a cohort step runs the SAME
    per-round collective program as the sync engine — byte for byte."""
    sync_sched, _, _ = _trace_engine("sync")
    cohort_sched, spec, _ = _trace_engine("cohort")
    assert schedule_digest(cohort_sched.ops) == schedule_digest(sync_sched.ops)
    assert comm_bytes(cohort_sched.ops) == comm_bytes(sync_sched.ops)
    assert spec["alias_expected"] == (0,)


def test_async_engine_gathers_pulls():
    sched, spec, _ = _trace_engine("async")
    kinds = {op.op for op in sched.ops}
    assert "psum" in kinds and "all_gather" in kinds
    assert not sched.findings
    assert spec["engine"] == "async"


def test_tp_engine_has_no_explicit_collectives():
    """GSPMD engine: sharding constraints only — the collective schedule
    materializes post-partitioning, so the jaxpr walk must come back
    empty and the contract leans on the compiled-HLO census instead."""
    if len(jax.devices()) < 2 or len(jax.devices()) % 2:
        pytest.skip("tp probe needs an even device count >= 2")
    sched, spec, mesh = _trace_engine("tp")
    assert sched.ops == []
    assert not sched.findings
    assert set(spec["collective_axes"]) == {"clients", "model"}
    assert dict(mesh.shape)["model"] == 2


def test_engine_audit_spec_selects_like_build_experiment():
    import dataclasses as dc
    cfg = _synthetic_cfg("income-2", 256)
    assert engine_audit_spec(cfg)["engine"] == "sync"
    assert engine_audit_spec(dc.replace(
        cfg, fed=dc.replace(cfg.fed, async_mode=True)))["engine"] == "async"
    assert engine_audit_spec(dc.replace(
        cfg, run=dc.replace(cfg.run, model_parallel=2)))["engine"] == "tp"
    assert engine_audit_spec(dc.replace(
        cfg, fed=dc.replace(cfg.fed, cohort_size=2)))["engine"] == "cohort"


def test_manifest_audit_summary_shape():
    """The run-manifest stamp: trace-only (no donation proof), carrying
    exactly the keys orchestration/loop.py ships."""
    from fedtpu.analysis.program import audit_step_summary
    cfg = _synthetic_cfg("income-2", 256)
    step, args, _, _, _ = _PROBES["sync"](cfg)
    stamp = audit_step_summary(step, args)
    assert set(stamp) == {"schedule_digest", "collectives",
                          "comm_bytes_per_round", "donation_ok", "findings"}
    assert stamp["donation_ok"] is None  # no compile without donate_argnums
    assert stamp["collectives"] > 0 and stamp["findings"] == 0
    assert np.array(stamp["comm_bytes_per_round"]) > 0
