"""The MPMD per-sub-program audit gate: `fedtpu audit <preset>
--engines mpmd_client,mpmd_aggregate,mpmd_chain,mpmd_metrics`.

The MPMD DAG (fedtpu/orchestration/mpmd.py) splits the round into four
AOT sub-programs, and each one's collective schedule is gated
INDEPENDENTLY here — a psum leaking into the client step or the metrics
program (both contractually collective-free), a dropped donation, or a
perturbed chain schedule shows up as a golden diff.  These goldens are
SEPARATE files from audit_<preset>.json on purpose: the default engine
set (sync/async/tp/cohort) is pinned by tests/test_audit_gate.py and
must not grow.

Generated under the hermetic suite env (CPU backend, 8 virtual devices
— tests/conftest.py) via:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m fedtpu.cli audit <preset> --synthetic-rows 256 \
        --engines mpmd_client,mpmd_aggregate,mpmd_chain,mpmd_metrics \
        --write-golden tests/goldens/audit_mpmd_<preset>.json

Regenerate the same way after an INTENDED schedule change and review
the diff like any other golden.
"""

import json
import os

import pytest

from fedtpu.cli import main as cli_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDENS = os.path.join(REPO, "tests", "goldens")
# income-4 pins the post-reshard topology alongside its parent, same as
# the monolithic gate.
PRESETS = ("income-4", "income-8")
ENGINES = "mpmd_client,mpmd_aggregate,mpmd_chain,mpmd_metrics"


def _golden_path(preset):
    return os.path.join(GOLDENS, f"audit_mpmd_{preset}.json")


@pytest.mark.parametrize("preset", PRESETS)
def test_mpmd_audit_matches_committed_golden(preset, capsys):
    rc = cli_main(["audit", preset, "--synthetic-rows", "256",
                   "--engines", ENGINES,
                   "--golden", _golden_path(preset)])
    out = capsys.readouterr().out
    assert rc == 0, f"fedtpu audit diverged from its golden:\n{out}"
    assert f"golden: matches {_golden_path(preset)}" in out


def test_mpmd_goldens_are_clean_contracts():
    """The committed contracts themselves, plus the DAG's structural
    invariants: the client step and the metrics program are
    collective-free in the jaxpr; the aggregate and the chain own the
    clients-axis reductions; the chain's per-round schedule is the
    aggregate's (one reduction set per scanned round)."""
    for preset in PRESETS:
        with open(_golden_path(preset), encoding="utf-8") as fh:
            golden = json.load(fh)
        assert golden["ok"] and golden["findings"] == [], preset
        eng = golden["engines"]
        assert set(eng) == {"mpmd_client", "mpmd_aggregate",
                            "mpmd_chain", "mpmd_metrics"}
        for name, contract in eng.items():
            assert "skipped" not in contract, (preset, name)
        # The whole point of the decomposition: the client step
        # dispatches without waiting on any cross-device phase.
        assert eng["mpmd_client"]["schedule"] == []
        assert eng["mpmd_metrics"]["schedule"] == []
        assert eng["mpmd_aggregate"]["comm_bytes_per_round"] > 0
        assert eng["mpmd_chain"]["comm_bytes_per_round"] > 0
        # One reduction set per scanned round: same ops, same per-trip
        # bytes, more trips.
        def op_set(contract):
            return {(s["op"], tuple(s["axes"]), tuple(map(tuple,
                                                          s["shapes"])))
                    for s in contract["schedule"]}
        assert op_set(eng["mpmd_aggregate"]) == op_set(eng["mpmd_chain"]), \
            preset
