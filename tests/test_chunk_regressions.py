"""Regressions for chunked-loop review findings: no checkpoint/eval after a
mid-chunk early stop; test_metrics round-alignment under chunking; chunked
participation trajectory equivalence."""

import numpy as np

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig, RunConfig,
                           ShardConfig)
from fedtpu.orchestration.checkpoint import latest_step
from fedtpu.orchestration.loop import run_experiment


def _data():
    return DataConfig(csv_path=None, synthetic_rows=256)


def test_no_checkpoint_after_midchunk_early_stop(tmp_path):
    ckdir = str(tmp_path / "ck")
    cfg = ExperimentConfig(
        data=_data(), shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=50, termination_patience=3, tolerance=1.0),
        run=RunConfig(rounds_per_step=8, checkpoint_dir=ckdir,
                      checkpoint_every=5, eval_test_every=2),
    )
    res = run_experiment(cfg, verbose=False)
    assert res.stopped_early and res.rounds_run == 4
    # Stop fired inside the first chunk: no checkpoint of overshoot state,
    # no post-stop held-out eval.
    assert latest_step(ckdir) is None
    assert len(res.test_metrics["accuracy"]) == 0


def test_chunked_test_metrics_alignment():
    base = ExperimentConfig(
        data=_data(), shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=6),
    )
    r1 = run_experiment(base.replace(run=RunConfig(eval_test_every=2)),
                        verbose=False)
    r3 = run_experiment(base.replace(run=RunConfig(eval_test_every=2,
                                                   rounds_per_step=3)),
                        verbose=False)
    # Unchunked evals at rounds 2, 4, 6; chunked must keep the same length
    # (due rounds within one chunk share the chunk-end params).
    assert len(r1.test_metrics["accuracy"]) == 3
    assert len(r3.test_metrics["accuracy"]) == 3


def test_chunked_participation_matches_unchunked():
    base = ExperimentConfig(
        data=_data(), shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=6, participation_rate=0.5,
                      participation_seed=11),
    )
    r1 = run_experiment(base, verbose=False)
    r2 = run_experiment(base.replace(run=RunConfig(rounds_per_step=3)),
                        verbose=False)
    # Sampling keys depend only on (seed, round, client): identical subsets,
    # identical trajectories regardless of chunking.
    np.testing.assert_allclose(r2.global_metrics["accuracy"],
                               r1.global_metrics["accuracy"], atol=1e-6)
