"""Server-optimizer (FedOpt) + DP delta-aggregation tests.

The reference's only aggregation is parameter averaging
(FL_CustomMLP...:108-119); fedtpu generalizes it to a server optimizer over
client deltas (fedtpu.ops.server_opt). The key invariant pinned here:
``fedavgm(momentum=0, lr=1)`` on the delta path is EXACTLY parameter
averaging, so the extension is a strict superset of the reference rule.
"""

import numpy as np
import jax
import jax.numpy as jnp

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           ModelConfig, OptimConfig, RunConfig, ShardConfig)
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.ops.server_opt import (clip_by_global_norm, make_server_optimizer)
from fedtpu.parallel import make_mesh, client_sharding
from fedtpu.parallel.round import build_round_fn, init_federated_state


def _setup(server=None, num_clients=8, rows=200, lr=0.004,
           weighting="data_size", **round_kw):
    x, y = synthetic_income_like(rows, 6, 2)
    packed = pack_clients(x, y, ShardConfig(num_clients=num_clients,
                                            shuffle=False))
    mesh = make_mesh(num_clients=num_clients)
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig(learning_rate=lr))
    state = init_federated_state(jax.random.key(1), mesh, num_clients,
                                 init_fn, tx, same_init=True,
                                 server_opt=server)
    shard = client_sharding(mesh)
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    round_step = build_round_fn(mesh, apply_fn, tx, 2, weighting=weighting,
                                server_opt=server, **round_kw)
    return state, batch, round_step


def _params0(state):
    return jax.tree.map(lambda p: np.asarray(p)[0], state["params"])


# ---------------------------------------------------------------- unit level

def test_update_rules_match_numpy_oracle():
    delta = {"w": jnp.array([1.0, -2.0]), "b": jnp.array([0.5])}
    d = {k: np.asarray(v) for k, v in delta.items()}
    lr, mom, b1, b2, tau = 0.5, 0.9, 0.9, 0.99, 1e-3

    # fedavgm, two steps with the same delta.
    opt = make_server_optimizer("fedavgm", learning_rate=lr, momentum=mom)
    s = opt.init(delta)
    step1, s = opt.update(delta, s)
    step2, s = opt.update(delta, s)
    for k in d:
        np.testing.assert_allclose(step1[k], lr * d[k], rtol=1e-6)
        np.testing.assert_allclose(step2[k], lr * (mom * d[k] + d[k]),
                                   rtol=1e-6)

    # fedadam.
    opt = make_server_optimizer("fedadam", learning_rate=lr, b1=b1, b2=b2,
                                tau=tau)
    s = opt.init(delta)
    step1, s = opt.update(delta, s)
    for k in d:
        m = (1 - b1) * d[k]
        v = (1 - b2) * d[k] ** 2
        np.testing.assert_allclose(step1[k], lr * m / (np.sqrt(v) + tau),
                                   rtol=1e-5)

    # fedadagrad accumulates the raw square.
    opt = make_server_optimizer("fedadagrad", learning_rate=lr, b1=b1,
                                tau=tau)
    s = opt.init(delta)
    _, s = opt.update(delta, s)
    _, s = opt.update(delta, s)
    for k in d:
        np.testing.assert_allclose(s["v"][k], 2 * d[k] ** 2, rtol=1e-6)

    # fedyogi second moment: v - (1-b2) d^2 sign(v - d^2), from v=0.
    opt = make_server_optimizer("fedyogi", learning_rate=lr, b1=b1, b2=b2,
                                tau=tau)
    s = opt.init(delta)
    _, s = opt.update(delta, s)
    for k in d:
        np.testing.assert_allclose(s["v"][k],
                                   -(1 - b2) * d[k] ** 2 * np.sign(-d[k] ** 2),
                                   rtol=1e-6)


def test_clip_by_global_norm_is_per_client_joint():
    delta = {"w": jnp.array([[3.0, 4.0], [0.3, 0.4]]),  # norms 5, then joint
             "b": jnp.array([[0.0], [0.0]])}
    clipped, norms = clip_by_global_norm(delta, 1.0)
    np.testing.assert_allclose(norms, [5.0, 0.5], rtol=1e-6)
    # client 0 scaled by 1/5 (joint norm across BOTH leaves), client 1 intact.
    np.testing.assert_allclose(clipped["w"][0], [0.6, 0.8], rtol=1e-6)
    np.testing.assert_allclose(clipped["w"][1], [0.3, 0.4], rtol=1e-6)


def test_unknown_server_opt_rejected():
    import pytest
    with pytest.raises(ValueError, match="unknown server optimizer"):
        make_server_optimizer("sgd")


# ----------------------------------------------------------- round-fn level

def test_fedavgm_identity_point_is_exactly_fedavg():
    # momentum=0, lr=1 on the delta path == parameter averaging: pinned
    # against the vanilla engine path, 3 rounds, same init and data.
    vanilla_state, batch, vanilla_step = _setup(server=None)
    ident = make_server_optimizer("fedavgm", learning_rate=1.0, momentum=0.0)
    delta_state, _, delta_step = _setup(server=ident)

    for _ in range(3):
        vanilla_state, _ = vanilla_step(vanilla_state, batch)
        delta_state, _ = delta_step(delta_state, batch)

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                atol=1e-5),
        vanilla_state["params"], delta_state["params"])


def test_fedadam_trains_and_carries_server_state():
    server = make_server_optimizer("fedadam", learning_rate=0.03)
    state, batch, step = _setup(server=server)
    accs = []
    for _ in range(10):
        state, metrics = step(state, batch)
        accs.append(float(metrics["client_mean"]["accuracy"]))
    assert "server_opt_state" in state
    m_leaves = jax.tree.leaves(state["server_opt_state"]["m"])
    assert all(np.all(np.isfinite(np.asarray(l))) for l in m_leaves)
    assert any(np.abs(np.asarray(l)).max() > 0 for l in m_leaves)
    assert accs[-1] > 0.5  # learned something on separable synthetic data
    # All client slots hold the identical server model.
    p = np.asarray(jax.tree.leaves(state["params"])[0])
    np.testing.assert_allclose(p, np.broadcast_to(p[:1], p.shape), atol=0)


def test_server_path_inside_multi_round_scan():
    server = make_server_optimizer("fedyogi", learning_rate=0.02)
    state, batch, step = _setup(server=server, rounds_per_step=4)
    state, metrics = step(state, batch)
    assert metrics["client_mean"]["accuracy"].shape == (4,)
    assert int(state["round"]) == 4
    assert "server_opt_state" in state


def test_missing_server_state_is_a_clear_error():
    import pytest
    server = make_server_optimizer("fedadam")
    state, batch, _ = _setup(server=None)          # state WITHOUT server init
    _, _, step = _setup(server=server)
    with pytest.raises(ValueError, match="server_opt_state"):
        step(state, batch)


def test_stale_server_state_is_a_clear_error():
    # The opposite mismatch: a state built WITH server_opt stepped by a
    # round_fn built WITHOUT it must raise, not silently drop the server
    # momentum and fall back to parameter averaging (ADVICE r1).
    import pytest
    server = make_server_optimizer("fedadam")
    state, batch, _ = _setup(server=server)        # state WITH server init
    _, _, step = _setup(server=None)
    with pytest.raises(ValueError, match="silently dropped"):
        step(state, batch)


def test_stale_server_state_is_a_clear_error_2d():
    import pytest
    from fedtpu.parallel import tp
    server = make_server_optimizer("fedadam")
    mesh = tp.make_mesh_2d(2, num_clients=4)
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig())
    state = tp.init_federated_state_2d(jax.random.key(0), mesh, 4, init_fn,
                                       tx, server_opt=server)
    x, y = synthetic_income_like(64, 6, 2)
    packed = pack_clients(x, y, ShardConfig(num_clients=4, shuffle=False))
    shard = tp.batch_sharding_2d(mesh)
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    step = tp.build_round_fn_2d(mesh, apply_fn, tx, 2)   # no server_opt
    with pytest.raises(ValueError, match="silently dropped"):
        step(state, batch)


def test_dp_noise_rejects_data_size_weighting_both_engines():
    # DP noise std is calibrated to a client-agnostic sensitivity bound;
    # data_size weighting would silently deflate the privacy level
    # (ADVICE r1, severity medium) — both engines must fail fast.
    import pytest
    from fedtpu.parallel import tp
    ident = make_server_optimizer("fedavgm", learning_rate=1.0, momentum=0.0)
    with pytest.raises(ValueError, match="uniform"):
        _setup(server=ident, dp_clip_norm=1.0, dp_noise_multiplier=0.5,
               weighting="data_size")
    mesh = tp.make_mesh_2d(2, num_clients=4)
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig())
    with pytest.raises(ValueError, match="uniform"):
        tp.build_round_fn_2d(mesh, apply_fn, tx, 2, weighting="data_size",
                             dp_clip_norm=1.0, dp_noise_multiplier=0.5)


def test_delta_path_rejects_ring_aggregation():
    import pytest
    with pytest.raises(ValueError, match="psum"):
        _setup(server=make_server_optimizer("fedadam"), aggregation="ring")


# ------------------------------------------------------------------ DP level

def test_dp_huge_clip_no_noise_is_plain_fedavg():
    vanilla_state, batch, vanilla_step = _setup(server=None)
    ident = make_server_optimizer("fedavgm", learning_rate=1.0, momentum=0.0)
    dp_state, _, dp_step = _setup(server=ident, dp_clip_norm=1e9)
    for _ in range(2):
        vanilla_state, _ = vanilla_step(vanilla_state, batch)
        dp_state, _ = dp_step(dp_state, batch)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                atol=1e-5),
        vanilla_state["params"], dp_state["params"])


def test_dp_clip_bounds_global_step():
    # With lr=1, no momentum, no noise: ||g1 - g0|| <= clip (each client's
    # delta is clipped to `clip`, and a convex combination can't exceed it).
    clip = 1e-3
    ident = make_server_optimizer("fedavgm", learning_rate=1.0, momentum=0.0)
    state, batch, step = _setup(server=ident, dp_clip_norm=clip)
    g0 = _params0(state)
    state, _ = step(state, batch)
    g1 = _params0(state)
    moved = np.sqrt(sum(np.sum((a - b) ** 2) for a, b in
                        zip(jax.tree.leaves(g1), jax.tree.leaves(g0))))
    assert moved <= clip * (1 + 1e-5)


def test_dp_noise_is_seed_deterministic():
    ident = make_server_optimizer("fedavgm", learning_rate=1.0, momentum=0.0)
    runs = {}
    for seed in (0, 0, 7):
        state, batch, step = _setup(server=ident, weighting="uniform",
                                    dp_clip_norm=0.1,
                                    dp_noise_multiplier=0.5, dp_seed=seed)
        state, _ = step(state, batch)
        runs.setdefault(seed, []).append(_params0(state))
    a, b = runs[0]
    jax.tree.map(lambda x, y: np.testing.assert_array_equal(x, y), a, b)
    c = runs[7][0]
    diffs = [np.abs(x - y).max() for x, y in
             zip(jax.tree.leaves(a), jax.tree.leaves(c))]
    assert max(diffs) > 0  # different seed, different noise


def test_zero_participant_round_leaves_server_untouched():
    # Plain FedOpt (no DP) under sampling: participation_rate ~ 0 makes
    # every round empty — the server model AND its momentum must not move.
    server = make_server_optimizer("fedavgm", learning_rate=1.0,
                                   momentum=0.9)
    state, batch, step = _setup(server=server, participation_rate=1e-9)
    g0 = _params0(state)
    m0 = jax.tree.map(np.asarray, jax.device_get(state["server_opt_state"]))
    state, _ = step(state, batch)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 g0, _params0(state))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b), m0,
                 jax.tree.map(np.asarray,
                              jax.device_get(state["server_opt_state"])))


def test_dp_with_sampling_uses_fixed_denominator():
    # DP + sampling: sigma rides the PUBLIC q*C denominator, so even an
    # empty round releases noise (the mechanism, not a bug) — params move.
    ident = make_server_optimizer("fedavgm", learning_rate=1.0, momentum=0.0)
    state, batch, step = _setup(server=ident, weighting="uniform",
                                dp_clip_norm=0.5, dp_noise_multiplier=1.0,
                                participation_rate=1e-9)
    g0 = _params0(state)
    state, _ = step(state, batch)
    g1 = _params0(state)
    diffs = [np.abs(a - b).max() for a, b in
             zip(jax.tree.leaves(g1), jax.tree.leaves(g0))]
    assert max(diffs) > 0


def test_dp_with_sampling_rejects_data_size_weighting():
    import pytest
    ident = make_server_optimizer("fedavgm", learning_rate=1.0, momentum=0.0)
    with pytest.raises(ValueError, match="uniform"):
        _setup(server=ident, weighting="data_size", dp_clip_norm=0.5,
               participation_rate=0.5)


def test_2d_engine_fedavgm_identity_matches_vanilla_2d():
    # The 1-D invariant holds on the 2-D tensor-parallel engine too:
    # fedavgm(momentum=0, lr=1) == parameter averaging.
    from fedtpu.parallel import tp
    x, y = synthetic_income_like(256, 6, 2)
    packed = pack_clients(x, y, ShardConfig(num_clients=8, shuffle=False))
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(8, 8)))
    tx = build_optimizer(OptimConfig())
    mesh = tp.make_mesh_2d(2, 8)
    batch = {k: jax.device_put(v, tp.batch_sharding_2d(mesh)) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}

    ident = make_server_optimizer("fedavgm", learning_rate=1.0, momentum=0.0)
    v_state = tp.init_federated_state_2d(jax.random.key(1), mesh, 8,
                                         init_fn, tx, same_init=True)
    d_state = tp.init_federated_state_2d(jax.random.key(1), mesh, 8,
                                         init_fn, tx, same_init=True,
                                         server_opt=ident)
    v_step = tp.build_round_fn_2d(mesh, apply_fn, tx, 2)
    d_step = tp.build_round_fn_2d(mesh, apply_fn, tx, 2, server_opt=ident)
    for _ in range(3):
        v_state, _ = v_step(v_state, batch)
        d_state, _ = d_step(d_state, batch)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                atol=1e-5),
        v_state["params"], d_state["params"])


def test_2d_engine_fedadam_matches_1d_engine():
    # Same FedAdam round on both engines, same init/data: identical
    # trajectories up to collective reassociation.
    from fedtpu.parallel import tp
    from fedtpu.parallel import make_mesh, client_sharding
    x, y = synthetic_income_like(256, 6, 2)
    packed = pack_clients(x, y, ShardConfig(num_clients=8, shuffle=False))
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(8, 8)))
    tx = build_optimizer(OptimConfig())
    server = make_server_optimizer("fedadam", learning_rate=0.02)
    key = jax.random.key(1)

    mesh1 = make_mesh(num_clients=8)
    s1 = init_federated_state(key, mesh1, 8, init_fn, tx, same_init=True,
                              server_opt=server)
    b1 = {k: jax.device_put(v, client_sharding(mesh1)) for k, v in
          {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    step1 = build_round_fn(mesh1, apply_fn, tx, 2, server_opt=server)

    mesh2 = tp.make_mesh_2d(2, 8)
    s2 = tp.init_federated_state_2d(key, mesh2, 8, init_fn, tx,
                                    same_init=True, server_opt=server)
    b2 = {k: jax.device_put(v, tp.batch_sharding_2d(mesh2)) for k, v in
          {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    step2 = tp.build_round_fn_2d(mesh2, apply_fn, tx, 2, server_opt=server)

    for _ in range(3):
        s1, m1 = step1(s1, b1)
        s2, m2 = step2(s2, b2)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                atol=2e-5),
        s1["params"], s2["params"])
    np.testing.assert_allclose(float(m1["client_mean"]["accuracy"]),
                               float(m2["client_mean"]["accuracy"]),
                               atol=1e-6)


def test_2d_engine_runs_dp_via_loop():
    from fedtpu.orchestration.loop import run_experiment
    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256,
                        synthetic_features=6),
        shard=ShardConfig(num_clients=4, shuffle=False),
        model=ModelConfig(input_dim=6, hidden_sizes=(8,)),
        fed=FedConfig(rounds=4, server_opt="fedyogi", server_lr=0.02,
                      dp_clip_norm=1.0, dp_noise_multiplier=0.05,
                      weighting="uniform"),
        run=RunConfig(model_parallel=2, rounds_per_step=2),
    )
    result = run_experiment(cfg, verbose=False)
    assert result.rounds_run == 4
    assert all(np.isfinite(v) for v in result.global_metrics["accuracy"])


def test_dp_noise_requires_clip():
    import pytest
    with pytest.raises(ValueError, match="dp_clip_norm"):
        _setup(server=None, dp_noise_multiplier=1.0)


# ------------------------------------------------------------ loop-level e2e

def test_run_experiment_with_fedadam_and_dp():
    from fedtpu.orchestration.loop import run_experiment
    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256,
                        synthetic_features=6),
        shard=ShardConfig(num_clients=8, shuffle=False),
        model=ModelConfig(input_dim=6, hidden_sizes=(8,)),
        optim=OptimConfig(),
        fed=FedConfig(rounds=6, server_opt="fedadam", server_lr=0.02,
                      dp_clip_norm=1.0, dp_noise_multiplier=0.01,
                      weighting="uniform"),
        run=RunConfig(rounds_per_step=3),
    )
    result = run_experiment(cfg, verbose=False)
    assert result.rounds_run == 6
    assert all(np.isfinite(v) for v in result.global_metrics["accuracy"])


def test_2d_engine_builds_server_opt_state():
    from fedtpu.orchestration.loop import build_experiment
    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=128,
                        synthetic_features=6),
        shard=ShardConfig(num_clients=4),
        model=ModelConfig(input_dim=6, hidden_sizes=(8,)),
        fed=FedConfig(server_opt="fedadam"),
        run=RunConfig(model_parallel=2),
    )
    exp = build_experiment(cfg)
    assert "server_opt_state" in exp.state
    # Server second moments are clients-free and model-sharded like the
    # hidden params they mirror.
    m0 = exp.state["server_opt_state"]["v"]["layers"][0]["w"]
    assert m0.ndim == 2   # (in, hidden) — no client axis


def test_noise_only_dp_fails_fast_on_both_engines():
    import pytest
    from fedtpu.orchestration.loop import build_experiment
    for mp in (1, 2):
        cfg = ExperimentConfig(
            data=DataConfig(csv_path=None, synthetic_rows=128,
                            synthetic_features=6),
            shard=ShardConfig(num_clients=4),
            model=ModelConfig(input_dim=6, hidden_sizes=(8,)),
            fed=FedConfig(dp_noise_multiplier=1.0),
            run=RunConfig(model_parallel=mp),
        )
        with pytest.raises(ValueError, match="dp_clip_norm"):
            build_experiment(cfg)
