"""Data pipeline + sharding semantics (reference: FL_CustomMLP...:48-61,
216-246; SURVEY.md §1 L1)."""

import os

import numpy as np
import pytest

from fedtpu.config import DataConfig, ShardConfig, default_income_csv
from fedtpu.data.sharding import shard_indices, pack_clients
from fedtpu.data.tabular import load_tabular_dataset, synthetic_income_like

REF_CSV = default_income_csv()


def test_synthetic_dataset_shapes():
    ds = load_tabular_dataset(DataConfig(csv_path=None, synthetic_rows=1000))
    assert ds.x_train.shape == (800, 14)
    assert ds.x_test.shape == (200, 14)
    assert ds.num_classes == 2
    assert ds.x_train.dtype == np.float32
    assert ds.y_train.dtype == np.int32


@pytest.mark.skipif(REF_CSV is None, reason="income CSV not available")
def test_income_csv_pipeline_matches_reference_semantics():
    ds = load_tabular_dataset(DataConfig(csv_path=REF_CSV))
    # 10,000 rows, 14 features, 80/20 split (FL_CustomMLP...:239).
    assert ds.x_train.shape == (8000, 14)
    assert ds.x_test.shape == (2000, 14)
    assert ds.num_classes == 2
    # Scaler-leakage parity: full-data standardization means the TRAIN+TEST
    # pool has mean ~0 / std ~1 per feature (FL_CustomMLP...:235-236).
    allx = np.concatenate([ds.x_train, ds.x_test])
    np.testing.assert_allclose(allx.mean(axis=0), 0.0, atol=1e-4)
    np.testing.assert_allclose(allx.std(axis=0), 1.0, atol=1e-3)
    # Balanced labels: exactly 5000/5000 overall.
    y_all = np.concatenate([ds.y_train, ds.y_test])
    assert (y_all == 0).sum() == 5000 and (y_all == 1).sum() == 5000


@pytest.mark.skipif(REF_CSV is None, reason="income CSV not available")
def test_split_bit_parity_with_sklearn():
    from sklearn.model_selection import train_test_split

    ds = load_tabular_dataset(DataConfig(csv_path=REF_CSV))
    # Rebuild the split directly with sklearn on the same preprocessed X.
    allx = np.zeros((10000,))  # only need index parity; use labels
    y = np.concatenate([ds.y_train, ds.y_test])  # not ordered — use shapes
    assert len(ds.y_train) == 8000
    # The same call with the same seed must reproduce our split sizes.
    a, b = train_test_split(np.arange(10000), test_size=0.2, random_state=42)
    assert len(a) == len(ds.y_train) and len(b) == len(ds.y_test)


def test_clean_pipeline_no_leakage():
    ds = load_tabular_dataset(DataConfig(csv_path=None, synthetic_rows=1000,
                                         scaler_leakage_parity=False))
    # Train-only statistics: train is standardized, test is merely transformed.
    np.testing.assert_allclose(ds.x_train.mean(axis=0), 0.0, atol=1e-4)
    np.testing.assert_allclose(ds.x_train.std(axis=0), 1.0, atol=1e-3)


def test_contiguous_shards_partition_with_remainder():
    y = np.arange(103) % 2
    idx = shard_indices(y, ShardConfig(num_clients=8, shuffle=False))
    sizes = [len(i) for i in idx]
    assert sizes == [12] * 7 + [19]  # chunk=max(1,103//8)=12, last takes rest
    # A true partition: disjoint union of all indices.
    allidx = np.concatenate(idx)
    assert len(np.unique(allidx)) == 103


def test_shared_seed_shuffle_is_a_partition():
    y = np.arange(1000) % 2
    idx = shard_indices(y, ShardConfig(num_clients=8, shuffle=True))
    allidx = np.concatenate(idx)
    assert len(np.unique(allidx)) == 1000  # no overlap


def test_unseeded_bug_parity_shards_overlap():
    # The reference's per-rank unseeded shuffle (FL_CustomMLP...:53) makes
    # shards overlap with near-certainty; assert we reproduce that.
    y = np.arange(1000) % 2
    np.random.seed(123)  # seed the global RNG only for test determinism
    idx = shard_indices(y, ShardConfig(num_clients=8, shuffle=True,
                                       unseeded_per_client_bug=True))
    allidx = np.concatenate(idx)
    assert len(np.unique(allidx)) < 1000  # overlap == not a partition


def test_dirichlet_shards_partition_and_skew():
    x, y = synthetic_income_like(2000, 4, 10)
    cfg = ShardConfig(num_clients=8, strategy="dirichlet",
                      dirichlet_alpha=0.1, shard_seed=3)
    idx = shard_indices(y, cfg)
    allidx = np.concatenate(idx)
    assert len(np.unique(allidx)) == 2000  # partition
    # Heavy skew: some client must be far from the uniform label histogram.
    label_fracs = []
    for i in idx:
        if len(i) == 0:
            continue
        counts = np.bincount(y[i], minlength=10) / len(i)
        label_fracs.append(counts.max())
    assert max(label_fracs) > 0.25  # uniform would be ~0.1


def test_label_sort_shards_are_single_label():
    y = np.repeat([0, 1], 500)
    idx = shard_indices(y, ShardConfig(num_clients=2, strategy="label_sort"))
    assert set(y[idx[0]]) == {0} and set(y[idx[1]]) == {1}


def test_pack_clients_masks_and_counts():
    x = np.arange(103 * 3, dtype=np.float32).reshape(103, 3)
    y = (np.arange(103) % 2).astype(np.int32)
    packed = pack_clients(x, y, ShardConfig(num_clients=8, shuffle=False))
    assert packed.x.shape == (8, 24, 3)  # 19 padded to multiple of 8
    assert packed.counts.tolist() == [12] * 7 + [19]
    np.testing.assert_allclose(packed.mask.sum(axis=1), packed.counts)
    # Padding rows are zero and masked out.
    assert packed.x[0, 12:].sum() == 0.0
    assert packed.mask[0, 12:].sum() == 0.0
    # Real rows survive the packing intact (shuffle=False => order parity).
    np.testing.assert_allclose(packed.x[0, :12], x[:12])
