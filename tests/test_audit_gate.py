"""The tier-1 program-audit gate: `fedtpu audit <preset> --golden ...`.

Mirrors test_lint_gate.py one layer down: where the lint gate keeps the
AST clean, this gate pins the compiled truth — the collective schedule
(op/axis/bytes/trips per engine), the donation tables, and the
post-SPMD HLO collective census — of every engine on the canonical
income presets against committed goldens.  Any PR that adds a psum,
drops a donation, or perturbs the GSPMD partitioning shows up as a
golden diff here, in the ordinary `-m 'not slow'` flow.

The goldens were generated under this suite's hermetic env (CPU
backend, 8 virtual devices — tests/conftest.py) via:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m fedtpu.cli audit <preset> --synthetic-rows 256 \
        --write-golden tests/goldens/audit_<preset>.json

Regenerate the same way after an INTENDED schedule/donation change and
review the diff like any other golden.
"""

import json
import os

import pytest

from fedtpu.cli import main as cli_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDENS = os.path.join(REPO, "tests", "goldens")
# income-4 is income-8's shrink target: goldening it alongside its parent
# pins the post-reshard schedule too (see tests/test_reshard.py's
# shrink-rebuilt-step digest check against this same golden).
PRESETS = ("income-2", "income-4", "income-8")


def _golden_path(preset):
    return os.path.join(GOLDENS, f"audit_{preset}.json")


@pytest.mark.parametrize("preset", PRESETS)
def test_audit_matches_committed_golden(preset, capsys):
    rc = cli_main(["audit", preset, "--synthetic-rows", "256",
                   "--golden", _golden_path(preset)])
    out = capsys.readouterr().out
    assert rc == 0, f"fedtpu audit diverged from its golden:\n{out}"
    assert f"golden: matches {_golden_path(preset)}" in out


def test_goldens_are_clean_contracts():
    """The committed contracts themselves: no findings, every engine
    present (none silently skipped), and non-trivial schedules — guards
    against regenerating a golden from a degraded environment."""
    for preset in PRESETS:
        with open(_golden_path(preset), encoding="utf-8") as fh:
            golden = json.load(fh)
        assert golden["ok"] and golden["findings"] == [], preset
        assert set(golden["engines"]) == {"sync", "async", "tp", "cohort"}
        for name, contract in golden["engines"].items():
            assert "skipped" not in contract, (preset, name)
        assert golden["engines"]["sync"]["comm_bytes_per_round"] > 0
        # GSPMD engine: schedule lives in the HLO census, not the jaxpr.
        assert golden["engines"]["tp"]["schedule"] == []
        assert golden["engines"]["tp"]["hlo_collectives"]
        # Cohort/sync parity is a design invariant, pinned here too.
        assert (golden["engines"]["cohort"]["schedule_digest"]
                == golden["engines"]["sync"]["schedule_digest"])
