"""RDP privacy accountant (fedtpu.ops.dp_accountant): pinned against a
published value, the q=1 closed form, and monotonicity; plus the run
summary wiring (VERDICT r2 weak #6 — a DP knob must output epsilon)."""

import dataclasses
import math

import numpy as np
import pytest

from fedtpu.ops.dp_accountant import (closed_form_gaussian_epsilon,
                                      privacy_spent, rdp_sampled_gaussian)


def test_abadi_et_al_canonical_value():
    # The canonical moments-accountant example (Abadi et al. 2016, §5;
    # reproduced in TF-Privacy's tutorials): q=0.01, sigma=4, T=10000,
    # delta=1e-5 -> epsilon ~= 1.26.
    out = privacy_spent(q=0.01, noise_multiplier=4.0, steps=10000,
                        delta=1e-5)
    assert abs(out["epsilon"] - 1.26) < 0.03
    assert out["order"] == 20


def test_full_participation_matches_closed_form():
    # q=1 is the plain Gaussian mechanism; minimizing the RDP-to-DP
    # conversion over REAL orders has a closed form. Integer orders may
    # only be slightly LOOSER (never tighter).
    for sigma, steps in ((2.0, 100), (1.0, 10), (5.0, 1000)):
        exact = closed_form_gaussian_epsilon(sigma, steps, 1e-5)
        got = privacy_spent(q=1.0, noise_multiplier=sigma, steps=steps,
                            delta=1e-5)["epsilon"]
        assert exact <= got <= exact * 1.05


def test_monotonicity():
    base = dict(q=0.1, noise_multiplier=1.0, steps=100, delta=1e-5)
    eps = privacy_spent(**base)["epsilon"]
    assert privacy_spent(**{**base, "steps": 1000})["epsilon"] > eps
    assert privacy_spent(**{**base, "noise_multiplier": 2.0})["epsilon"] < eps
    assert privacy_spent(**{**base, "q": 0.5})["epsilon"] > eps
    assert privacy_spent(**{**base, "delta": 1e-8})["epsilon"] > eps


def test_edge_cases():
    assert privacy_spent(0.1, 1.0, 0, 1e-5)["epsilon"] == 0.0
    assert privacy_spent(0.0, 1.0, 100, 1e-5)["epsilon"] == 0.0
    assert math.isinf(privacy_spent(0.1, 0.0, 100, 1e-5)["epsilon"])
    # Subsampling amplifies: q<1 must be strictly cheaper than q=1.
    full = rdp_sampled_gaussian(1.0, 1.0, 8)
    sub = rdp_sampled_gaussian(0.1, 1.0, 8)
    assert 0 < sub < full
    with pytest.raises(ValueError):
        rdp_sampled_gaussian(1.5, 1.0, 8)
    with pytest.raises(ValueError):
        privacy_spent(0.1, 1.0, 10, delta=0.0)


def test_run_summary_reports_epsilon():
    from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                               ShardConfig)
    from fedtpu.orchestration.loop import run_experiment

    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=128),
        shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=3, weighting="uniform", dp_clip_norm=1.0,
                      dp_noise_multiplier=1.0),
    )
    res = run_experiment(cfg, verbose=False)
    dp = res.summary()["dp"]
    assert dp["rounds"] == res.rounds_run == 3
    assert dp["sampling_rate"] == 1.0 and dp["noise_multiplier"] == 1.0
    expect = privacy_spent(1.0, 1.0, 3, cfg.fed.dp_delta)["epsilon"]
    np.testing.assert_allclose(dp["epsilon"], expect)
    assert 0 < dp["epsilon"] < 20

    # Pipelined early stop: the released params carry the in-flight
    # overshoot chunk's extra noised rounds — the accountant must count
    # the state's trained rounds, never the shorter recorded history
    # (under-reporting epsilon is the unsafe direction).
    from fedtpu.config import RunConfig
    over = dataclasses.replace(
        cfg,
        fed=dataclasses.replace(cfg.fed, rounds=30, tolerance=1.0,
                                termination_patience=2,
                                dp_noise_multiplier=1.0),
        run=RunConfig(rounds_per_step=3, pipelined_stop=True))
    res_o = run_experiment(over, verbose=False)
    assert res_o.stopped_early
    assert res_o.rounds_trained > res_o.rounds_run
    dp_o = res_o.privacy_spent()
    assert dp_o["rounds"] == res_o.rounds_trained
    assert (dp_o["epsilon"]
            > privacy_spent(1.0, 1.0, res_o.rounds_run, 1e-5)["epsilon"])

    # Clip-only runs (no noise) must NOT claim an epsilon.
    # (composition across resume: test_resume_composes_heterogeneous_rdp)
    clip_only = dataclasses.replace(
        cfg, fed=dataclasses.replace(cfg.fed, dp_noise_multiplier=0.0))
    res2 = run_experiment(clip_only, verbose=False)
    assert "dp" not in res2.summary()


def test_rdp_vector_roundtrip_matches_privacy_spent():
    from fedtpu.ops.dp_accountant import epsilon_from_rdp, rdp_vector

    v = rdp_vector(0.3, 1.5)
    direct = privacy_spent(0.3, 1.5, 40, 1e-5)
    via_curve = epsilon_from_rdp([r * 40 for r in v], 1e-5)
    assert via_curve == direct


def test_resume_composes_heterogeneous_rdp(tmp_path):
    """Resuming a DP checkpoint with a DIFFERENT noise multiplier must
    charge the pre-resume rounds at the rate they were actually noised
    with (restored RDP curve), never at the new config's rate — the
    under-reporting hole review r3 found."""
    from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                               RunConfig, ShardConfig)
    from fedtpu.ops.dp_accountant import epsilon_from_rdp, rdp_vector
    from fedtpu.orchestration.loop import run_experiment

    ck = str(tmp_path / "ck")
    base = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=128),
        shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=4, weighting="uniform", dp_clip_norm=1.0,
                      dp_noise_multiplier=0.2),
        run=RunConfig(checkpoint_dir=ck, checkpoint_every=1),
    )
    first = run_experiment(base, verbose=False)
    assert first.rounds_run == 4

    # Resume for 4 more rounds at 10x the noise.
    resumed_cfg = dataclasses.replace(
        base, fed=dataclasses.replace(base.fed, rounds=8,
                                      dp_noise_multiplier=2.0))
    res = run_experiment(resumed_cfg, verbose=False, resume=True)
    assert res.rounds_run == 8
    dp = res.privacy_spent()
    assert "resume_rdp" not in dp  # the curve was recorded, not assumed

    v_low = np.asarray(rdp_vector(1.0, 0.2))   # rounds 1-4, sigma=0.2
    v_high = np.asarray(rdp_vector(1.0, 2.0))  # rounds 5-8, sigma=2.0
    exact = epsilon_from_rdp(list(4 * v_low + 4 * v_high), 1e-5)["epsilon"]
    np.testing.assert_allclose(dp["epsilon"], exact, rtol=1e-12)
    # The naive (all-8-rounds-at-current-sigma) epsilon is far SMALLER —
    # exactly the under-report the composition prevents.
    naive = privacy_spent(1.0, 2.0, 8, 1e-5)["epsilon"]
    assert dp["epsilon"] > 3 * naive


def test_noise_off_resume_segment_voids_the_guarantee(tmp_path):
    """Rounds trained with noise OFF after noised rounds are NOT
    post-processing — they re-access the private data, so the released
    model has no finite (epsilon, delta). The accountant must report
    epsilon=inf with a reason, never the earlier segments' finite spend,
    and the void must survive later resumes (flags persist in the
    checkpoint meta — review r3)."""
    from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                               RunConfig, ShardConfig)
    from fedtpu.orchestration.loop import run_experiment

    ck = str(tmp_path / "ck")
    base = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=128),
        shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=3, weighting="uniform", dp_clip_norm=1.0,
                      dp_noise_multiplier=1.0),
        run=RunConfig(checkpoint_dir=ck, checkpoint_every=1),
    )
    a = run_experiment(base, verbose=False)
    assert math.isfinite(a.privacy_spent()["epsilon"])
    assert not a.dp_guarantee_void

    # Segment B: clip stays (same state structure), noise OFF — trains 2
    # more rounds on the private data without noise.
    b_cfg = dataclasses.replace(
        base, fed=dataclasses.replace(base.fed, rounds=5,
                                      dp_noise_multiplier=0.0))
    b = run_experiment(b_cfg, verbose=False, resume=True)
    dp_b = b.privacy_spent()
    assert b.dp_guarantee_void
    assert math.isinf(dp_b["epsilon"])
    assert "guarantee_void" in dp_b

    # Segment C: noise back on — the void is sticky (persisted), no
    # later segment can launder the epsilon back to finite.
    c_cfg = dataclasses.replace(
        base, fed=dataclasses.replace(base.fed, rounds=7,
                                      dp_noise_multiplier=1.0))
    c = run_experiment(c_cfg, verbose=False, resume=True)
    dp_c = c.privacy_spent()
    assert c.dp_guarantee_void and math.isinf(dp_c["epsilon"])

    # Control: a fresh DP run that merely COMPLETES (no unnoised rounds)
    # stays finite, and a noiseless-from-scratch run still claims nothing.
    assert "dp" in a.summary()
    plain = dataclasses.replace(
        base,
        fed=dataclasses.replace(base.fed, dp_noise_multiplier=0.0),
        run=RunConfig())
    assert "dp" not in run_experiment(plain, verbose=False).summary()
