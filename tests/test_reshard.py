"""Elastic live resharding (docs/resilience.md "Elastic resharding").

Three layers, mirroring the subsystem split:

- the wire-free redistribution planner (fedtpu.parallel.reshard): row
  maps, local-row assembly, the no-wire invariant, and bitwise carry;
- the reshard protocol controller (fedtpu.resilience.reshard): plan/
  signal polling, ack barriers and their ReshardFailed degradation, the
  grow spool's generation discipline, and the run-done release;
- the integrated single-process plan path through run_experiment
  (shrink then grow in one run, no restart), plus the audit-gate tie-in:
  a shrink-REBUILT round step must compile to exactly the collective
  schedule pinned in the income-4 golden (tests/test_audit_gate.py).

The 2-process gang path (agreement records, park/grow-back, the
mid-reshard death fallback) is exercised end-to-end by
``fedtpu chaos --scenarios mp_shrink,mp_grow,mp_shrink_dead``.
"""

import dataclasses
import json
import os

import jax
import numpy as np
import pytest

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           RunConfig, ShardConfig, TelemetryConfig)
from fedtpu.orchestration.loop import build_experiment, run_experiment
from fedtpu.parallel.mesh import (client_sharding, make_mesh,
                                  replicated_sharding, submesh)
from fedtpu.parallel.reshard import (grow_row_map, host_rows, is_client_leaf,
                                     reshard_state, shrink_row_map)
from fedtpu.resilience.faults import FaultPlan
from fedtpu.resilience.reshard import ReshardController, ReshardFailed

# ---------------------------------------------------------------- planner


def test_row_maps():
    assert shrink_row_map(2, 4) == [2, 3, 4, 5]
    assert shrink_row_map(0, 3) == [0, 1, 2]
    # Survivors' rows return to their pre-shrink global positions; the
    # rest are join rows (-1).
    assert grow_row_map(4, 8) == [0, 1, 2, 3, -1, -1, -1, -1]
    assert grow_row_map(4, 8, block_start=2) == [-1, -1, 0, 1, 2, 3, -1, -1]


def _mesh_state(num_clients=8):
    mesh = make_mesh(None, num_clients)
    c = jax.device_put(
        np.arange(num_clients * 3, dtype=np.float32).reshape(num_clients, 3),
        client_sharding(mesh))
    r = jax.device_put(np.float32(7.0), replicated_sharding(mesh))
    return mesh, {"params": {"w": c}, "round": r}


def test_is_client_leaf():
    _, state = _mesh_state()
    assert is_client_leaf(state["params"]["w"])
    assert not is_client_leaf(state["round"])
    assert not is_client_leaf(np.zeros(3))      # host leaf: no sharding


def test_host_rows_roundtrip():
    _, state = _mesh_state()
    w = state["params"]["w"]
    got = host_rows(w, slice(2, 6))
    np.testing.assert_array_equal(got, np.asarray(w)[2:6])
    np.testing.assert_array_equal(host_rows(w, slice(0, 8)), np.asarray(w))


def test_host_rows_raises_on_non_addressable_rows():
    """The no-wire invariant: a row held only by another process is a hard
    planning error. Single-process arrays are always fully addressable, so
    the missing-shard topology is stubbed."""

    class _Shard:
        index = (slice(0, 2),)
        data = np.zeros((2, 3), dtype=np.float32)

    class _Leaf:
        shape = (4, 3)
        dtype = np.float32
        addressable_shards = [_Shard()]

    with pytest.raises(ValueError, match="not addressable"):
        host_rows(_Leaf(), slice(0, 4))


def test_reshard_state_shrink_is_bitwise():
    mesh, state = _mesh_state(8)
    dst = submesh(mesh, num_clients=4)
    new, steps = reshard_state(state, dst_mesh=dst, dst_clients=4,
                               row_map=shrink_row_map(2, 4))
    np.testing.assert_array_equal(np.asarray(new["params"]["w"]),
                                  np.asarray(state["params"]["w"])[2:6])
    assert float(new["round"]) == 7.0
    kinds = {s.path: s.kind for s in steps}
    assert set(kinds.values()) == {"client", "replicated"}
    client = [s for s in steps if s.kind == "client"]
    assert client[0].rows == 4 and client[0].join_rows == 0
    assert client[0].nbytes == 4 * 3 * 4


def test_reshard_state_grow_fills_join_rows():
    mesh, state = _mesh_state(4)
    dst = submesh(mesh, num_clients=4)  # same extent; the MAP drives rows
    fills = {}

    def join(path, jidx, row_shape, dtype):
        fills[path] = list(jidx)
        return np.full((len(jidx),) + row_shape, 42.0, dtype=dtype)

    new, steps = reshard_state(
        state, dst_mesh=make_mesh(None, 8), dst_clients=8,
        row_map=grow_row_map(4, 8, block_start=2), join_rows=join,
        replicated_values={"['round']": np.float32(9.0)})
    out = np.asarray(new["params"]["w"])
    np.testing.assert_array_equal(out[2:6], np.asarray(state["params"]["w"]))
    assert (out[[0, 1, 6, 7]] == 42.0).all()
    assert fills["['params']['w']"] == [0, 1, 6, 7]
    # Replicated override wins over the live host value.
    assert float(new["round"]) == 9.0
    client = [s for s in steps if s.kind == "client"][0]
    assert client.rows == 8 and client.join_rows == 4


def test_reshard_state_rejects_bad_row_map():
    mesh, state = _mesh_state(4)
    with pytest.raises(ValueError, match="row_map"):
        reshard_state(state, dst_mesh=mesh, dst_clients=4, row_map=[0, 1])


def test_reshard_state_remote_rows_fills_non_addressable(tmp_path):
    """ISSUE 12: the genuinely cross-host row path. Rows the executing
    process cannot address — here, row-map entries past the 2-client
    source extent, the absorb-from-a-dead-peer case — are filled by the
    remote_rows callback (a dead shard's exported arrays) instead of
    raising, and carried rows stay bitwise."""
    mesh, state = _mesh_state(2)
    asked = {}

    def remote(path, missing, row_shape, dtype):
        asked[path] = list(missing)
        base = np.asarray(missing, np.int64).reshape(-1, *([1] *
                                                           len(row_shape)))
        return (100.0 + base).astype(dtype) * np.ones(row_shape, dtype)

    new, steps = reshard_state(
        state, dst_mesh=make_mesh(None, 4), dst_clients=4,
        row_map=[0, 1, 2, 3], remote_rows=remote)
    out = np.asarray(new["params"]["w"])
    np.testing.assert_array_equal(out[:2],
                                  np.asarray(state["params"]["w"]))
    np.testing.assert_array_equal(out[2], np.full(3, 102.0, np.float32))
    np.testing.assert_array_equal(out[3], np.full(3, 103.0, np.float32))
    assert asked["['params']['w']"] == [2, 3]
    client = [s for s in steps if s.kind == "client"][0]
    assert client.rows == 4 and client.join_rows == 0
    # A wrong-shape fill is a hard error, not silent corruption.
    with pytest.raises(ValueError, match="remote_rows returned shape"):
        reshard_state(state, dst_mesh=make_mesh(None, 4), dst_clients=4,
                      row_map=[0, 1, 2, 3],
                      remote_rows=lambda p, m, s, d: np.zeros((1, 1), d))


# ------------------------------------------------------------- controller


def _ctl(tmp_path, idx=0, count=2, launch="L0", **kw):
    return ReshardController(process_index=idx, process_count=count,
                             launch_id=launch, restart_count=0,
                             checkpoint_dir=str(tmp_path), **kw)


def test_ack_roundtrip_and_timeout_degrades(tmp_path):
    ctl = _ctl(tmp_path, ack_timeout=0.4)
    ctl.publish_ack(0, "a", 3)
    ctl.await_acks(0, "a", (0,))                      # own ack: immediate
    with pytest.raises(ReshardFailed):
        ctl.await_acks(0, "a", (0, 1))                # peer never acks
    # Phase tags do not alias: the phase-a ack satisfies no phase-b wait.
    with pytest.raises(ReshardFailed):
        ctl.await_acks(0, "b", (0,))


def test_spool_roundtrip_and_generation_fence(tmp_path):
    ctl = _ctl(tmp_path)
    join = {"['params']['w']": np.arange(6, dtype=np.float32).reshape(2, 3)}
    repl = {"['round']": np.float32(5.0)}
    ctl.write_spool(1, join, repl, {"history": {"accuracy": [0.5]}})
    j, r, control = ctl.read_spool(1)
    np.testing.assert_array_equal(j["['params']['w']"],
                                  join["['params']['w']"])
    assert float(r["['round']"]) == 5.0
    assert control["history"] == {"accuracy": [0.5]}
    # Another launch generation must refuse this spool outright.
    stale = _ctl(tmp_path, launch="L1")
    with pytest.raises(ReshardFailed, match="another generation"):
        stale.read_spool(1)


def test_poll_plan_fires_once_and_not_after_restart(tmp_path):
    spec = {"seed": 0, "faults": [{"kind": "preempt_notice", "round": 3,
                                   "target_clients": 4,
                                   "process_index": 1}]}
    plan = FaultPlan.load(spec, num_clients=8, rounds=8)
    ctl = ReshardController(plan=plan, process_index=0, process_count=2,
                            launch_id="L0", restart_count=0,
                            checkpoint_dir=str(tmp_path))
    assert ctl.poll(0) is None and ctl.poll(1) is None
    req = ctl.poll(2)                  # 1-based round 3 = 0-based loop-top 2
    assert (req.mode, req.victim, req.target_clients) == ("shrink", 1, 4)
    assert ctl.poll(2) is None         # once-only
    # A gang restart must not replay the notice that just failed.
    ctl2 = ReshardController(plan=plan, process_index=0, process_count=2,
                             launch_id="L0", restart_count=1,
                             checkpoint_dir=str(tmp_path))
    assert all(ctl2.poll(r) is None for r in range(8))


def test_signal_agreement_converges(tmp_path):
    """Two processes see the notice at different loop-tops; both fire at
    max(published) + 1 with the same victim."""
    a, b = _ctl(tmp_path, idx=0), _ctl(tmp_path, idx=1)
    a.request_signal("shrink")
    assert a.poll(5) is None           # publishes round 5, waits for peer
    b.request_signal("shrink")
    assert b.poll(6) is None           # publishes round 6
    assert a.poll(6) is None           # agreed round is 7, not yet reached
    ra, rb = a.poll(7), b.poll(7)
    assert ra is not None and rb is not None
    assert (ra.mode, ra.victim, ra.round) == (rb.mode, rb.victim, rb.round)
    assert ra.round == 7 and ra.mode == "shrink" and ra.victim == 1


def test_committed_bookkeeping_and_finish(tmp_path):
    ctl = _ctl(tmp_path, idx=0, count=2)
    ctl.committed("shrink", 1)
    assert ctl.active == (0,) and ctl.parked_victim == 1 and ctl.seq == 1
    ctl.finish()
    done = os.path.join(str(tmp_path), ".reshard", "run_done")
    with open(done) as fh:
        assert json.load(fh)["launch"] == "L0"
    ctl.committed("grow", 1)
    assert ctl.active == (0, 1) and ctl.parked_victim is None
    # Nobody parked: finish is a no-op (marker already consumed/removed).
    os.remove(done)
    ctl.finish()
    assert not os.path.exists(done)


def test_finish_is_leader_only(tmp_path):
    ctl = _ctl(tmp_path, idx=1, count=3)
    ctl.committed("shrink", 2)         # active (0, 1): leader is 0, not us
    ctl.finish()
    assert not os.path.exists(
        os.path.join(str(tmp_path), ".reshard", "run_done"))


# ------------------------------------------- integrated single-process plan


def _cfg(rounds=6, fault_plan=None, events=None):
    return ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=512),
        shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=rounds, termination_patience=10,
                      tolerance=1e-12),
        run=RunConfig(eval_test_every=0, fault_plan=fault_plan,
                      telemetry=TelemetryConfig(events_path=events)),
    )


def test_single_process_shrink_grow_no_restart(tmp_path):
    """One run: 8 clients -> shrink to 4 at round 3 -> grow back to 8 at
    round 5 -> finish all 6 rounds. The pre-shrink prefix is bitwise the
    no-fault baseline's; the shrink round visibly changes the cohort."""
    ev = str(tmp_path / "ev.jsonl")
    plan = json.dumps({"seed": 0, "faults": [
        {"kind": "preempt_notice", "round": 3, "target_clients": 4},
        {"kind": "preempt_cancel", "round": 5},
    ]})
    res = run_experiment(_cfg(fault_plan=plan, events=ev), verbose=False)
    base = run_experiment(_cfg(), verbose=False)
    assert res.rounds_run == 6 and not res.diverged
    acc, bacc = res.global_metrics["accuracy"], base.global_metrics["accuracy"]
    assert acc[:2] == bacc[:2]                    # bitwise pre-shrink prefix
    assert acc[2] != bacc[2]                      # 4-client rounds differ
    with open(ev) as fh:
        events = [json.loads(ln) for ln in fh if ln.strip()]
    done = [e for e in events if e["kind"] == "reshard_done"]
    assert [e["payload"]["mode"] for e in done] == ["shrink", "grow"]
    assert done[0]["payload"]["target"] == 4
    assert done[1]["payload"]["target"] == 8
    assert all(s["join_rows"] == 0 for s in done[0]["payload"]["steps"])
    assert any(s["join_rows"] > 0 for s in done[1]["payload"]["steps"])


def test_shrink_rebuilt_step_matches_income4_audit_golden():
    """The audit-gate tie-in (tests/test_audit_gate.py): the round step a
    live shrink REBUILDS (income-8 topology minus half its mesh, data
    repacked through the partition view) must compile to exactly the
    collective schedule pinned in the committed income-4 golden — a
    reshard can never silently change the schedule contract."""
    from fedtpu.analysis.program import audit_step_summary
    from fedtpu.data import load_dataset
    from fedtpu.parallel.round import AUDIT_SPEC

    cfg8 = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256),
        shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=5))
    ds = load_dataset(cfg8.data)
    exp8 = build_experiment(cfg8, ds)
    dst = submesh(exp8.mesh, num_clients=4)
    cfg4 = dataclasses.replace(
        cfg8, shard=dataclasses.replace(cfg8.shard, num_clients=4,
                                        partition_clients=8,
                                        partition_offset=0))
    exp4 = build_experiment(cfg4, ds, mesh=dst)
    summary = audit_step_summary(
        exp4.make_step(1), (exp4.state, exp4.batch),
        donate_argnums=AUDIT_SPEC["donate_argnums"])
    golden_path = os.path.join(os.path.dirname(__file__), "goldens",
                               "audit_income-4.json")
    with open(golden_path, encoding="utf-8") as fh:
        golden = json.load(fh)["engines"]["sync"]
    assert summary["schedule_digest"] == golden["schedule_digest"]
    assert summary["comm_bytes_per_round"] == golden["comm_bytes_per_round"]
    assert summary["findings"] == 0
