"""Compositional chaos fuzzing: campaign artifacts, the invariant-oracle
library, the deterministic two-gateway executor, ddmin shrinking, and
the committed-corpus bitwise replay gate.

Layers under test (docs/resilience.md "Chaos fuzzing"):

- ``fedtpu.resilience.fuzz`` — digest-stamped Campaign artifacts, the
  seeded sampler, the in-process gang executor (virtual frame/round
  clocks, never wall time), ddmin, and ``run_corpus`` (the
  ``fedtpu check --fuzz-corpus`` tier-1 gate over tests/corpus/);
- ``fedtpu.resilience.oracles`` — one positive + one negative fixture
  per oracle, and the composite judges pinned against the chaos rows'
  historical boolean bars (mp_gateway_kill, mp_torn_frame);
- ``fedtpu.resilience.faults`` — the ``torn`` ckpt_corrupt mode and the
  fallback walk past a torn round;
- ``fedtpu.serving.engine`` — seeded WAL short-writes: the damaged tail
  tears cleanly on replay and the client retry dedups exactly once;
- ``fedtpu.resilience.supervisor`` — restart backoff as a pure function
  of (exit, hung, crash_streak), no wall-clock jitter.

The multi-campaign sweep and ddmin-from-noise runs are full-tier only
(`slow`); the quick tier keeps the corpus gate, the stale-WAL-tail
violation demo, and one executor run per satellite.
"""

import copy
import inspect
import json
import os

import pytest

from fedtpu.resilience import oracles
from fedtpu.resilience.fuzz import (Campaign, run_campaign, run_corpus,
                                    sample_campaign, shrink_campaign,
                                    write_corpus_entry)

CORPUS_DIR = os.path.join(os.path.dirname(__file__), "corpus")

# The committed reproducer of the stale-WAL-tail rollback bug the fuzzer
# found (fedtpu.resilience.fuzz module docstring): newest checkpoint
# torn on disk + a later crash force the fallback walk to an older
# round; replaying the WAL tail onto it would dedup away the client's
# resends of the rolled-back frames.
STALE_TAIL = {
    "name": "stale_tail", "seed": 11, "rounds": 8,
    "faults": [
        {"kind": "ckpt_corrupt", "mode": "torn", "round": 6, "gateway": 0},
        {"kind": "process_kill", "round": 7, "gateway": 0},
    ],
}


# ---------------------------------------------------------------------------
# campaign artifact: canonical form, digest, load


def test_campaign_digest_roundtrip():
    c = sample_campaign(7, 3)
    again = Campaign.load(c.to_json())
    assert again.digest == c.digest
    assert again.canonical() == c.canonical()
    # entry order is canonicalized away: a manifest with reordered
    # entries is the SAME campaign
    flipped = Campaign(name=c.name, seed=c.seed, rounds=c.rounds,
                       poison_fraction=c.poison_fraction,
                       faults=list(reversed(c.faults)),
                       net_faults=list(reversed(c.net_faults)),
                       notices=list(reversed(c.notices)))
    assert flipped.digest == c.digest


def test_campaign_digest_mismatch_fails_loudly():
    c = sample_campaign(7, 3)
    manifest = c.manifest()
    manifest["faults"].append({"kind": "straggler", "round": 2,
                               "gateway": 0, "delay_s": 1.0})
    with pytest.raises(ValueError, match="digest mismatch"):
        Campaign.from_dict(manifest)


def test_sampler_is_deterministic_and_covers_the_fault_space():
    a = [sample_campaign(5, i) for i in range(20)]
    b = [sample_campaign(5, i) for i in range(20)]
    assert [c.digest for c in a] == [c.digest for c in b]
    # different seeds move the draw
    assert sample_campaign(6, 0).digest != sample_campaign(5, 0).digest
    kinds = set()
    for c in a:
        kinds |= {e["kind"] for e in c.faults}
        kinds |= {e["kind"] for e in c.net_faults}
        kinds |= {e["kind"] for e in c.notices}
    # 20 draws must visit both fault families (full coverage is the
    # sweep's job, not one seed's)
    assert any(k.startswith("net_") for k in kinds)
    assert any(not k.startswith("net_") for k in kinds)


# ---------------------------------------------------------------------------
# oracle library: one positive + one negative fixture per oracle


def test_exactly_once_oracle():
    assert oracles.exactly_once(10, 10).ok
    v = oracles.exactly_once(10, 12)
    assert not v.ok and v.observed == 12 and v.expected == 10
    assert not oracles.exactly_once(None, 10).ok


def test_no_lost_acked_oracle():
    assert oracles.no_lost_acked(0).ok
    assert not oracles.no_lost_acked(3).ok     # acked update vanished
    assert not oracles.no_lost_acked(-2).ok    # double incorporation
    assert not oracles.no_lost_acked(None).ok


def test_history_bitwise_oracle_full_mode():
    base = {1: "a", 2: "b", 3: "c"}
    assert oracles.history_bitwise(dict(base), base).ok
    v = oracles.history_bitwise({1: "a", 2: "X", 3: "c"}, base)
    assert not v.ok and v.observed["first_divergence"] == 2
    assert not oracles.history_bitwise({1: "a", 2: "b"}, base).ok


def test_history_bitwise_oracle_prefix_divergent_mode():
    base = {1: "a", 2: "b", 3: "c"}
    hist = {1: "a", 2: "X", 3: "c"}
    ok = oracles.history_bitwise(hist, base, mode="prefix_divergent",
                                 fault_round=2)
    assert ok.ok
    # identical history means the fault silently didn't apply
    assert not oracles.history_bitwise(dict(base), base,
                                       mode="prefix_divergent",
                                       fault_round=2).ok
    # divergence BEFORE the fault round breaks the prefix bar
    assert not oracles.history_bitwise({1: "Z", 2: "X", 3: "c"}, base,
                                       mode="prefix_divergent",
                                       fault_round=2).ok
    with pytest.raises(ValueError):
        oracles.history_bitwise(hist, base, mode="prefix_divergent")


def test_exit_contract_oracle():
    assert oracles.exit_contract([[137, 75, 0], [0]]).ok
    assert oracles.exit_contract([[76], [0]]).ok
    assert not oracles.exit_contract([[3, 0]]).ok      # diverged
    assert not oracles.exit_contract([[0, 137]]).ok    # died at the end
    assert not oracles.exit_contract([[42, 0]]).ok     # unknown transient
    assert not oracles.exit_contract([[]]).ok          # no exit recorded


def test_monotone_rounds_oracle():
    assert oracles.monotone_rounds([1, 2, 2, 5]).ok
    v = oracles.monotone_rounds([1, 4, 3], member=1)
    assert not v.ok and v.observed["regression_at"] == 2
    assert v.observed["member"] == 1


def test_slo_burn_and_backlog_oracles():
    assert oracles.slo_burn_bounded(1.5, 2.5).ok
    assert not oracles.slo_burn_bounded(3.0, 2.5).ok
    assert not oracles.slo_burn_bounded(None, 2.5).ok  # signal went dark
    assert oracles.backlog_drained(0).ok
    assert not oracles.backlog_drained(7).ok
    assert not oracles.backlog_drained(None).ok


def test_quarantine_containment_oracle():
    assert oracles.quarantine_containment([3, 5], [3, 5]).ok
    assert not oracles.quarantine_containment([3], [3, 5]).ok  # missed
    assert not oracles.quarantine_containment([3, 9], [3]).ok  # honest hit
    # subset mode: undershooting is fine, honest casualties are not
    assert oracles.quarantine_containment([3], [3, 5], mode="subset").ok
    assert not oracles.quarantine_containment([9], [3, 5],
                                              mode="subset").ok


def test_defense_effective_oracle():
    assert oracles.defense_effective(0.80, 0.60, 0.82, 0.05, 0.10).ok
    # defense leaked accuracy
    assert not oracles.defense_effective(0.70, 0.60, 0.82, 0.05, 0.10).ok
    # attack was toothless — the row proves nothing
    assert not oracles.defense_effective(0.80, 0.80, 0.82, 0.05, 0.10).ok
    assert not oracles.defense_effective(None, 0.6, 0.8, 0.05, 0.10).ok


# ---------------------------------------------------------------------------
# composite judges vs the chaos rows' historical boolean bars
# (satellite: refactored rows' verdicts must be unchanged)


def _legacy_gateway_kill_ok(f):
    return (f["survived"] and f["retried"] >= 1
            and f["gang_restarts"] >= 1 and f["duplicate_drops"] >= 1
            and f["lost_acked"] == 0
            and f["client_admitted"] == f["fleet_admitted"]
            and f["backlog"] == 0 and f["slo_burn"] is not None
            and f["slo_burn"] <= 2.5)


def _legacy_net_row_ok(f):
    return (f["survived"] and f["netlog_match"] and f["retried"] >= 1
            and f["duplicate_drops"] >= 1 and f["lost_acked"] == 0
            and f["client_admitted"] == f["fleet_admitted"]
            and f["backlog"] == 0 and f["gang_restarts"] == 0
            and f["slo_burn"] is not None and f["slo_burn"] <= 2.5)


GATEWAY_KILL_PASS = dict(survived=True, retried=2, gang_restarts=1,
                         duplicate_drops=14, lost_acked=0,
                         client_admitted=192, fleet_admitted=192,
                         backlog=0, slo_burn=1.2)
TORN_FRAME_PASS = dict(survived=True, netlog_match=True, retried=1,
                       duplicate_drops=14, lost_acked=0,
                       client_admitted=192, fleet_admitted=192,
                       backlog=0, gang_restarts=0, slo_burn=0.8)


def test_judge_gateway_kill_matches_legacy_mp_gateway_kill_bar():
    mutations = [{}, {"survived": False}, {"retried": 0},
                 {"gang_restarts": 0}, {"duplicate_drops": 0},
                 {"lost_acked": 3}, {"fleet_admitted": 190},
                 {"backlog": 2}, {"slo_burn": None}, {"slo_burn": 9.0}]
    for mut in mutations:
        f = {**GATEWAY_KILL_PASS, **mut}
        vs = oracles.judge_gateway_kill(**f, burn_budget=2.5)
        assert oracles.summarize(vs)["ok"] == _legacy_gateway_kill_ok(f), \
            f"verdict changed for mutation {mut}"


def test_judge_net_row_matches_legacy_mp_torn_frame_bar():
    mutations = [{}, {"survived": False}, {"netlog_match": False},
                 {"retried": 0}, {"duplicate_drops": 0},
                 {"lost_acked": 1}, {"client_admitted": 191},
                 {"backlog": 1}, {"gang_restarts": 1},
                 {"slo_burn": None}, {"slo_burn": 3.1}]
    for mut in mutations:
        f = {**TORN_FRAME_PASS, **mut}
        vs = oracles.judge_net_row(**f, burn_budget=2.5)
        assert oracles.summarize(vs)["ok"] == _legacy_net_row_ok(f), \
            f"verdict changed for mutation {mut}"


def test_verdicts_render_canonically():
    vs = oracles.judge_net_row(**TORN_FRAME_PASS, burn_budget=2.5)
    for v in vs:
        d = v.as_dict()
        # bitwise artifact requirement: canonical JSON twice is bytes-equal
        assert (json.dumps(d, sort_keys=True)
                == json.dumps(copy.deepcopy(d), sort_keys=True))
        assert set(d) == {"oracle", "ok", "observed", "expected", "detail"}


# ---------------------------------------------------------------------------
# supervisor restart backoff: pure function, no wall-clock jitter
# (satellite: regression pin)


def test_restart_backoff_is_a_pure_function_of_exit_and_streak():
    from fedtpu.resilience.supervisor import (EXIT_PREEMPTED,
                                              restart_backoff)
    seq = [restart_backoff(1, False, k, backoff_base=0.5, backoff_max=30.0)
           for k in range(8)]
    assert seq == [0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 30.0, 30.0]
    # repeated evaluation is bitwise-identical — no jitter source at all
    assert seq == [restart_backoff(1, False, k, backoff_base=0.5,
                                   backoff_max=30.0) for k in range(8)]
    # preemption and watchdog hangs restart immediately, whatever the streak
    assert restart_backoff(EXIT_PREEMPTED, False, 5, 0.5, 30.0) == 0.0
    assert restart_backoff(1, True, 5, 0.5, 30.0) == 0.0


def test_both_supervisors_route_delay_through_restart_backoff():
    # the pin that keeps the pure function wired in: neither supervise
    # loop may grow its own inline backoff (or a jitter term) again
    from fedtpu.resilience import supervisor
    for fn in (supervisor.supervise, supervisor.supervise_gang):
        src = inspect.getsource(fn)
        assert "restart_backoff(" in src, fn.__name__
        assert "random" not in src, fn.__name__


# ---------------------------------------------------------------------------
# torn checkpoints + WAL short writes (satellites) — real engine, no gang


@pytest.fixture(scope="module")
def tiny_engine_factory():
    from fedtpu.config import ServingConfig
    from fedtpu.serving.engine import ServingEngine
    from fedtpu.telemetry.metrics import MetricsRegistry

    def make():
        cfg = ServingConfig(cohort=8, buffer_size=2, tick_interval_s=0.5,
                            data_rows=64, model_hidden=(8,), seed=0)
        return ServingEngine(cfg, registry=MetricsRegistry())

    return make


def _feed(eng, nonce, seq, n=6, t0=0.0):
    from fedtpu.serving.server import _handle
    rows = [[u, t0 + 0.3 * u, 0.1] for u in range(n)]
    return _handle(eng, {"op": "updates", "events": rows,
                         "nonce": nonce, "seq": seq})


def test_torn_ckpt_corrupt_mode_and_fallback_walk(tiny_engine_factory,
                                                  tmp_path):
    from fedtpu.orchestration.checkpoint import (complete_steps,
                                                 load_checkpoint_fallback)
    from fedtpu.resilience.faults import corrupt_checkpoint
    ck = str(tmp_path / "ck")
    eng = tiny_engine_factory()
    _feed(eng, "n0", 1, t0=0.0)
    eng.checkpoint(ck)
    good_step = eng.tick_count
    _feed(eng, "n0", 2, t0=10.0)
    eng.checkpoint(ck)
    steps = complete_steps(ck)
    assert len(steps) == 2
    # torn mode: seeded truncation, header left byte-intact — the round
    # still LOOKS committed, only a restore attempt can tell
    hit = corrupt_checkpoint(ck, mode="torn", seed=3)
    assert hit == steps[-1]
    assert complete_steps(ck) == steps
    with pytest.warns(RuntimeWarning, match="failed to restore"):
        _, _, landed = load_checkpoint_fallback(ck)
    assert landed == good_step
    # the torn mode is seeded: same seed, same surviving byte count
    assert (corrupt_checkpoint(ck, step=hit, mode="torn", seed=3)
            == hit)
    # and the oracle sees the same thing the walk does
    with pytest.warns(RuntimeWarning):
        assert oracles.checkpoint_restorable(ck).ok
    with pytest.raises(ValueError):
        corrupt_checkpoint(ck, mode="lightning")


def test_wal_short_write_tears_cleanly_and_retry_dedups(
        tiny_engine_factory, tmp_path):
    from fedtpu.serving.server import _handle
    wal = str(tmp_path / "wal.jsonl")
    eng = tiny_engine_factory()
    eng.wal_path = wal
    first = _feed(eng, "n0", 1, t0=0.0)
    assert first["op"] == "acks" and not first.get("duplicate")
    # disk fills mid-append of seq 2: a short write must surface as an
    # OSError AFTER flushing the damaged prefix (that is what a real
    # ENOSPC leaves behind)
    eng.wal_shortwrite = lambda nonce, seq, line: 25
    with pytest.raises(OSError):
        _feed(eng, "n0", 2, t0=10.0)
    eng.wal_shortwrite = None
    raw = open(wal, encoding="utf-8").read()
    assert len(raw.splitlines()[-1]) == 25          # the torn tail
    # crash + recover: replay tears cleanly at the damaged line
    eng2 = tiny_engine_factory()
    eng2.wal_path = wal
    replayed = eng2.replay_wal()
    assert replayed == 6                            # seq 1's rows only
    incorporated_before = eng2.signals()["incorporated"]
    # client retries seq 1 (acked pre-crash): dedups, counts replayed
    dup = _feed(eng2, "n0", 1, t0=0.0)
    assert dup.get("duplicate") is True
    assert dup["counts"] == first["counts"]
    assert eng2.duplicate_drops >= 1
    # the torn seq 2 was NEVER acked, so its retry is fresh work —
    # incorporated exactly once
    retry = _feed(eng2, "n0", 2, t0=10.0)
    assert not retry.get("duplicate")
    again = _feed(eng2, "n0", 2, t0=10.0)
    assert again.get("duplicate") is True
    _handle(eng2, {"op": "drain"})
    sig = eng2.signals()
    assert sig["incorporated"] > incorporated_before
    assert sig["backlog"] == 0


# ---------------------------------------------------------------------------
# the stale-WAL-tail violation the fuzzer found (fixed this PR)


def test_stale_wal_tail_replay_loses_acked_updates_without_the_guard():
    c = Campaign.from_dict(STALE_TAIL)
    bad = run_campaign(c, replay_stale_wal_tail=True)
    assert not bad["ok"]
    # two independent oracles catch it: the fleet admitted less than the
    # client was told, and acked rows are gone from the incorporated sum
    assert "exactly_once" in bad["failed"]
    assert "no_lost_acked" in bad["failed"]
    assert bad["summary"]["lost_acked"] > 0


# ---------------------------------------------------------------------------
# the committed corpus: bitwise replay gate (tier-1 acceptance)


def test_corpus_campaigns_replay_bitwise_and_pass_all_oracles():
    report = run_corpus(CORPUS_DIR)
    assert report["campaigns"] >= 2
    for row in report["rows"]:
        assert row["ok"], (row["name"], row["reason"])
        assert row["replay_bitwise"], row["name"]
        assert row["golden_ok"], row["name"]
    assert report["ok"]


def test_corpus_gate_rejects_a_tampered_manifest(tmp_path):
    src = sorted(p for p in os.listdir(CORPUS_DIR)
                 if p.endswith(".json"))[0]
    with open(os.path.join(CORPUS_DIR, src), encoding="utf-8") as fh:
        manifest = json.load(fh)
    manifest["faults"] = (manifest.get("faults") or []) + [
        {"kind": "straggler", "round": 2, "gateway": 0, "delay_s": 9.9}]
    tampered = tmp_path / src
    tampered.write_text(json.dumps(manifest))
    report = run_corpus(str(tmp_path))
    assert not report["ok"]
    assert "digest mismatch" in report["rows"][0]["reason"]


def test_corpus_gate_fails_on_an_empty_directory(tmp_path):
    report = run_corpus(str(tmp_path))
    assert not report["ok"]
    assert "no campaigns" in report["reason"]


# ---------------------------------------------------------------------------
# report: the fuzz section


def test_report_aggregates_and_renders_the_fuzz_section():
    from fedtpu.telemetry.report import aggregate, render_text
    events = [
        {"v": 1, "kind": "fuzz_campaign",
         "payload": {"name": "c0000_000", "digest": "aa", "ok": True,
                     "failed": [], "entries": 3,
                     "fired": {"process_kill": 1, "net_reset": 2}}},
        {"v": 1, "kind": "fuzz_campaign",
         "payload": {"name": "c0000_001", "digest": "bb", "ok": False,
                     "failed": ["no_lost_acked"], "entries": 5,
                     "fired": {"ckpt_corrupt": 1}, "shrunk_entries": 2,
                     "reproducer": "tests/corpus/c0000_001_min.json"}},
        {"v": 1, "kind": "fuzz_run",
         "payload": {"ok": True, "campaigns": 2, "passed": 1,
                     "failed": ["c0000_001"], "seed": 0}},
    ]
    agg = aggregate(events)
    fz = agg["fuzz"]
    assert fz["campaigns"] == 2 and fz["passed"] == 1
    assert fz["failed_oracles"] == {"no_lost_acked": 1}
    assert fz["fired"] == {"ckpt_corrupt": 1, "net_reset": 2,
                           "process_kill": 1}
    text = render_text(agg)
    assert "fuzz (compositional chaos campaigns)" in text
    assert "VIOLATION c0000_001" in text
    assert "2-entry reproducer" in text


# ---------------------------------------------------------------------------
# full-tier: sweeps and ddmin from noise


@pytest.mark.slow
def test_fuzz_sweep_every_campaign_passes_or_shrinks(tmp_path):
    from fedtpu.resilience.fuzz import run_fuzz
    events = str(tmp_path / "events.jsonl")
    report = run_fuzz(budget=4, seed=3, events=events)
    assert report["ok"]
    assert report["campaigns"] == 4
    with open(events, encoding="utf-8") as fh:
        lines = [json.loads(ln) for ln in fh]
    assert sum(1 for e in lines if e["kind"] == "fuzz_campaign") == 4
    assert lines[-1]["kind"] == "fuzz_run"


@pytest.mark.slow
def test_ddmin_shrinks_noise_down_to_the_essential_pair(tmp_path):
    noisy = Campaign(
        name="noisy", seed=11, rounds=8,
        faults=STALE_TAIL["faults"] + [
            {"kind": "straggler", "round": 3, "gateway": 1,
             "delay_s": 1.0},
            {"kind": "client_dropout", "round": 2, "frac": 0.25}],
        net_faults=[{"kind": "net_torn_frame", "gateway": 1, "frame": 4,
                     "boundary": "post_ack", "cut_bytes": 48},
                    {"kind": "net_dup_frame", "gateway": 0, "frame": 9}],
        notices=[{"kind": "preempt_notice", "round": 4, "gateway": 1}])

    def unguarded_fails(c):
        return not run_campaign(c, replay_stale_wal_tail=True)["ok"]

    assert unguarded_fails(noisy)
    mini = shrink_campaign(noisy, predicate=unguarded_fails)
    mc = mini["campaign"]
    assert mini["removed"] == 5
    assert mc.faults == STALE_TAIL["faults"]
    assert mc.net_faults == [] and mc.notices == []
    # and the minimized reproducer round-trips through the corpus layout
    res = run_campaign(mc)
    paths = write_corpus_entry(mc, res["artifact"], str(tmp_path))
    gate = run_corpus(str(tmp_path))
    assert gate["ok"], gate["rows"]
    assert os.path.exists(paths["golden"])


@pytest.mark.slow
def test_campaign_replay_is_bitwise_across_runs():
    c = sample_campaign(3, 6)   # ckpt_corrupt+preempt+short-write combo
    a = run_campaign(c)
    b = run_campaign(c)
    assert a["lines"] == b["lines"]
    assert a["artifact"] == b["artifact"]
